"""Test harness config: force a deterministic 8-device CPU mesh.

Real-TPU runs are exercised by bench.py and the driver's compile checks;
unit tests validate bit-exactness and sharding semantics on a virtual CPU
mesh (fast, deterministic, no TPU contention), per the multi-chip testing
strategy in the task brief.  Set KASPA_TPU_TEST_REAL_DEVICE=1 to run the
suite on whatever device JAX picks (e.g. the tunneled TPU).

NOTE: the axon sitecustomize hook registers the TPU plugin at interpreter
startup (before this conftest runs), so env-var-based platform selection is
too late — we must override via jax.config before any backend initializes.
"""

import os

if not os.environ.get("KASPA_TPU_TEST_REAL_DEVICE"):
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
    import jax

    jax.config.update("jax_platforms", "cpu")
    assert jax.devices()[0].platform == "cpu", "CPU platform override failed"

from kaspa_tpu.utils import jax_setup

jax_setup.setup()
