"""Peer-facing resilience: handshake deadlines, misbehavior scoring to a
ban, injected wire faults, reconnect backoff, and the IBD progress
deadline.

The shape under test: an adversarial or broken peer costs bounded
resources (one reader thread until a deadline, 40 points per malformed
frame until a ban) and a flapping address is redialed on an exponential,
jittered schedule instead of a tight loop.
"""

from __future__ import annotations

import socket
import struct
import time
from types import SimpleNamespace

import pytest

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.params import simnet_params
from kaspa_tpu.p2p import wire
from kaspa_tpu.p2p.address_manager import (
    RECONNECT_BACKOFF_BASE,
    RECONNECT_BACKOFF_MAX,
    AddressManager,
    ConnectionManager,
    NetAddress,
)
from kaspa_tpu.p2p.node import MSG_VERSION, Node
from kaspa_tpu.p2p.transport import P2PServer, connect_outbound
from kaspa_tpu.resilience.faults import FAULTS


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


def _wait(predicate, timeout: float = 10.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def _recv_eof(sock: socket.socket, timeout: float) -> bool:
    """True if the remote closes the connection within ``timeout``."""
    sock.settimeout(timeout)
    try:
        while True:
            if sock.recv(4096) == b"":
                return True
    except (socket.timeout, ConnectionError, OSError):
        return False


def test_half_open_socket_reaped_by_handshake_deadline(monkeypatch):
    """A peer that connects and never speaks (SYN flood residue, wedged
    middlebox) is dropped at the handshake deadline instead of pinning a
    reader thread forever."""
    monkeypatch.setenv("KASPA_TPU_P2P_HANDSHAKE_TIMEOUT", "0.5")
    node = Node(Consensus(simnet_params(bps=2)), "victim")
    server = P2PServer(node, port=0)
    server.start()
    try:
        host, port = server.address.rsplit(":", 1)
        raw = socket.create_connection((host, int(port)), timeout=5.0)
        try:
            assert _wait(lambda: len(node.peers) == 1, 5.0), "accept never registered"
            # send nothing: the handshake deadline must reap the peer
            assert _recv_eof(raw, 5.0), "half-open socket was not closed"
            assert _wait(lambda: len(node.peers) == 0, 5.0)
        finally:
            raw.close()
    finally:
        server.stop()


def _handshake_version_frame(node: Node) -> bytes:
    return wire.encode_frame(
        MSG_VERSION,
        {
            "protocol_version": node.protocol_version,
            "network": node.consensus.params.name,
            "listen_port": 0,
            "id": 0xDEAD,
        },
    )


def _malformed_body_frame() -> bytes:
    """Valid header (magic/type/len intact — the stream stays synced), body
    that cannot decode: an addresses payload whose count varint promises
    far more bytes than arrive."""
    type_id = wire._TYPE_IDS["addresses"]
    body = b"\xff" * 5
    return wire.MAGIC + bytes([type_id]) + struct.pack("<I", len(body)) + body


def test_corrupt_frames_score_then_ban_then_refused(monkeypatch):
    """Three body-corrupt frames: 40 points each, the third crosses the ban
    threshold — the peer is dropped, the IP is banned, and a reconnect is
    refused at accept."""
    node = Node(Consensus(simnet_params(bps=2)), "victim")
    amgr = AddressManager()
    node.address_manager = amgr
    server = P2PServer(node, port=0, address_manager=amgr)
    server.start()
    try:
        host, port = server.address.rsplit(":", 1)
        raw = socket.create_connection((host, int(port)), timeout=5.0)
        try:
            raw.sendall(_handshake_version_frame(node))
            assert _wait(lambda: len(node.peers) == 1, 5.0)
            peer = node.peers[0]
            for _ in range(3):
                raw.sendall(_malformed_body_frame())
            assert _wait(lambda: peer.misbehavior_score >= 100, 5.0), peer.misbehavior_score
            assert _wait(lambda: amgr.is_banned("127.0.0.1"), 5.0)
            assert _wait(lambda: not peer.alive, 5.0)
        finally:
            raw.close()

        # the banned address is refused at accept (socket closed unserved)
        raw2 = socket.create_connection((host, int(port)), timeout=5.0)
        try:
            assert _recv_eof(raw2, 5.0), "banned peer was served"
            assert len(node.peers) == 0
        finally:
            raw2.close()
    finally:
        server.stop()


def test_injected_send_faults_drop_and_disconnect():
    """p2p.send cooperative faults: a dropped frame silently never leaves
    (connection stays up); an injected disconnect severs the peer."""
    params = simnet_params(bps=2)
    a = Node(Consensus(params), "a")
    b = Node(Consensus(params), "b")
    server = P2PServer(a, port=0)
    server.start()
    out_peer = None
    try:
        out_peer = connect_outbound(b, server.address)
        assert out_peer.wait_handshaken(10.0)
        assert _wait(lambda: a.peers and a.peers[0].handshaken, 10.0)

        FAULTS.configure({"p2p.send": {"mode": "drop", "hits": [1]}}, seed=1)
        out_peer.send("ping", 1)  # dropped on the floor
        out_peer.send("ping", 2)  # hit 2: passes
        assert out_peer.alive
        time.sleep(0.3)
        assert a.peers and a.peers[0].alive  # dropped frame != dropped peer

        FAULTS.configure({"p2p.send": {"mode": "disconnect", "hits": [1]}}, seed=1)
        out_peer.send("ping", 3)
        assert not out_peer.alive
        assert _wait(lambda: len(a.peers) == 0, 5.0)  # remote sees the close
    finally:
        server.stop()
        for peer in list(a.peers) + list(b.peers) + ([out_peer] if out_peer else []):
            peer.close()


def test_reconnect_backoff_grows_exponentially_with_jitter():
    amgr = AddressManager()
    cm = ConnectionManager(SimpleNamespace(peers=[]), amgr, tick_seconds=3600)
    now = [1000.0]
    cm._clock = lambda: now[0]
    addr = NetAddress("10.0.0.1", 16111)

    delays = []
    for _ in range(12):
        cm._note_dial(addr, ok=False)
        delays.append(cm._next_dial[addr] - now[0])
    for n, d in enumerate(delays):
        base = min(RECONNECT_BACKOFF_BASE * (2.0**n), RECONNECT_BACKOFF_MAX)
        assert 0.5 * base <= d <= 1.5 * base, (n, d, base)
    assert delays[-1] <= 1.5 * RECONNECT_BACKOFF_MAX  # cap holds
    assert delays[3] > delays[0]  # growth is visible through the jitter

    # the gate blocks until the delay elapses, then admits a redial
    assert not cm._may_dial(addr, now[0])
    now[0] += 1.5 * RECONNECT_BACKOFF_MAX + 1
    assert cm._may_dial(addr, now[0])

    # one success resets the ladder to the base delay
    cm._note_dial(addr, ok=True)
    assert addr not in cm._next_dial and addr not in cm._fail_counts
    cm._note_dial(addr, ok=False)
    first = cm._next_dial[addr] - now[0]
    assert first <= 1.5 * RECONNECT_BACKOFF_BASE


def test_tick_respects_backoff_gate():
    """A permanent peer that fails to dial is not redialed until its
    backoff window elapses — no tight reconnect loop."""
    amgr = AddressManager()
    cm = ConnectionManager(SimpleNamespace(peers=[]), amgr, tick_seconds=3600)
    now = [500.0]
    cm._clock = lambda: now[0]
    dials = []
    cm._dial = lambda addr: (dials.append(addr), False)[1]
    addr = NetAddress("10.0.0.2", 16111)
    cm._permanent[addr] = 0

    cm._tick()
    cm._tick()  # same instant: gated
    assert len(dials) == 1
    now[0] += 1.5 * RECONNECT_BACKOFF_BASE + 0.1  # past any jittered first delay
    cm._tick()
    assert len(dials) == 2
    assert cm._permanent[addr] == 2  # retry attempts tracked


def test_ibd_progress_deadline_drops_stalled_donor():
    """A donor that goes quiet mid-IBD past the deadline loses the sync
    slot, is scored, and is disconnected."""
    node = Node(Consensus(simnet_params(bps=2)), "joiner")
    closed = []

    class FakeDonor:
        misbehavior_score = 0
        peer_address = None

        def close(self):
            closed.append(1)

    donor = FakeDonor()
    node._ibd = {"peer": donor, "last_progress": 1000.0}
    with node.lock:
        node.prune_caches(now=1000.0 + 1)  # inside the deadline
    assert node._ibd and not closed
    with node.lock:
        node.prune_caches(now=1000.0 + 10_000)
    assert not node._ibd
    assert donor.misbehavior_score == 40 and closed == [1]
