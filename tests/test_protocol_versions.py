"""Protocol-version-tiered flow registration.

Mirrors the reference's per-version flow sets (flows/src/{v7,v8,v10}/mod.rs)
and handshake negotiation (flow_context.rs:822-852): v7 = base flows,
v8 = + block-body requests, v10 = + pruning-point SMT state; near Toccata
activation only v10 peers are accepted.
"""

from __future__ import annotations

import random

import pytest

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.params import simnet_params
from kaspa_tpu.consensus.processes.coinbase import MinerData
from kaspa_tpu.p2p.node import (
    MSG_REQUEST_PP_SMT,
    Node,
    ProtocolError,
    connect,
)
from kaspa_tpu.sim.simulator import Miner


def _mine(node: Node, n: int, t0: int = 10_000, miner=None) -> list:
    miner = miner or Miner(0, random.Random(5))
    out = []
    for i in range(n):
        t = node.consensus.build_block_template(
            MinerData(miner.spk, b""), [], timestamp=t0 + 600 * i
        )
        node.submit_block(t)
        out.append(t)
    return out


def test_v7_peer_negotiates_and_syncs():
    """A v7-capped peer handshakes down to v7 on both endpoints and still
    relay-syncs full blocks from a v10 node (the base flow subset)."""
    params = simnet_params(bps=2)
    a = Node(Consensus(params), "new-node")
    b = Node(Consensus(params), "old-node")
    b.protocol_version = 7
    pa, pb = connect(a, b)
    assert pa.protocol_version == 7 and pb.protocol_version == 7

    blocks = _mine(a, 8)
    assert b.consensus.sink() == a.consensus.sink()
    # and the old peer's blocks flow back
    _mine(b, 2, t0=60_000, miner=Miner(1, random.Random(9)))
    assert a.consensus.sink() == b.consensus.sink()


def test_tiered_message_refused_below_negotiated_version():
    """A flow introduced in a later tier than negotiated is a protocol
    violation — the reference never registers it for the old tier."""
    params = simnet_params(bps=2)
    a = Node(Consensus(params), "new-node")
    b = Node(Consensus(params), "old-node")
    b.protocol_version = 7
    pa, pb = connect(a, b)
    with pytest.raises(ProtocolError, match="requires protocol v10"):
        pb.send(MSG_REQUEST_PP_SMT, {"pp": b"\x00" * 32, "offset": 0})


def test_v10_required_near_toccata_activation():
    """One day before Toccata activation, handshakes from pre-v10 peers are
    refused (flow_context.rs:827-841)."""
    params = simnet_params(bps=2)
    params.toccata_activation = 0  # active => within the gate window
    a = Node(Consensus(params), "gatekeeper")
    b = Node(Consensus(params), "old-node")
    b.protocol_version = 9
    with pytest.raises(ProtocolError, match="v10 required"):
        connect(b, a)  # b's version arrives at a and is refused


def test_body_only_fetch_completes_header_only_blocks():
    """v8 flow: a node holding headers fetches just the bodies and the
    blocks complete through the normal pipeline
    (request_block_bodies.rs round trip)."""
    params = simnet_params(bps=2)
    a = Node(Consensus(params), "donor")
    blocks = _mine(a, 8)
    b = Node(Consensus(params), "header-first")
    for blk in blocks:
        b.consensus.validate_and_insert_header(blk.header)
        assert b.consensus.storage.statuses.get(blk.hash) == "header_only"
    pa, pb = connect(a, b)
    b.request_bodies(pb, [blk.hash for blk in blocks])
    assert b.consensus.sink() == a.consensus.sink()
    for blk in blocks:
        assert b.consensus.storage.block_transactions.has(blk.hash)

    # a v7 peer cannot be asked for bodies
    c = Node(Consensus(params), "v7")
    c.protocol_version = 7
    pa2, pc = connect(a, c)
    with pytest.raises(ProtocolError, match="needs v8"):
        a.request_bodies(pa2, [blocks[0].hash])


def test_headers_first_sync_end_to_end():
    """v8 headers-first catch-up: the syncer streams headers above its sink
    anchor, fetches only the bodies, and converges to the donor's state —
    the reference's body_only_ibd_permitted mode (v8/mod.rs)."""
    params = simnet_params(bps=2)
    donor = Node(Consensus(params), "donor")
    blocks = _mine(donor, 20)
    joiner = Node(Consensus(params), "joiner")
    pj, pd = connect(joiner, donor)
    joiner.headers_first_sync(pj)
    assert joiner.consensus.sink() == donor.consensus.sink()
    for blk in blocks:
        assert joiner.consensus.storage.block_transactions.has(blk.hash)
    # a v7 peer cannot drive it
    old = Node(Consensus(params), "old")
    old.protocol_version = 7
    po, _ = connect(old, donor)
    with pytest.raises(ProtocolError, match="needs v8"):
        old.headers_first_sync(po)


def test_headers_first_wire_roundtrip():
    """The headers chunk + reject frames survive the binary codec."""
    from kaspa_tpu.p2p import wire
    from kaspa_tpu.p2p.node import MSG_HEADERS, MSG_REJECT, MSG_REQUEST_HEADERS

    params = simnet_params(bps=2)
    n = Node(Consensus(params), "w")
    blocks = _mine(n, 3)
    payload = {
        "headers": [b.header for b in blocks],
        "done": False,
        "continuation": blocks[-1].hash,
    }
    frame = wire.encode_frame(MSG_HEADERS, payload)
    buf = memoryview(frame)
    pos = [0]

    def rd(k):
        b = bytes(buf[pos[0] : pos[0] + k])
        pos[0] += k
        return b

    name, dec = wire.read_message(rd)
    assert name == MSG_HEADERS and not dec["done"]
    assert [h.hash for h in dec["headers"]] == [b.header.hash for b in blocks]
    assert dec["continuation"] == blocks[-1].hash

    frame2 = wire.encode_frame(MSG_REJECT, "protocol violation: test")
    buf = memoryview(frame2)
    pos[0] = 0
    name2, dec2 = wire.read_message(rd)
    assert name2 == MSG_REJECT and dec2 == "protocol violation: test"

    frame3 = wire.encode_frame(MSG_REQUEST_HEADERS, blocks[0].hash)
    buf = memoryview(frame3)
    pos[0] = 0
    name3, dec3 = wire.read_message(rd)
    assert name3 == MSG_REQUEST_HEADERS and dec3 == blocks[0].hash
