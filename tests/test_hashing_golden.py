"""Golden-vector tests for tx/header/merkle hashing.

Expected values come from the reference test suites (cited per test) —
cross-implementation equivalence in the style of the reference's own
golden-DAG testing strategy (SURVEY.md §4).
"""

import pytest

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.consensus.model import (
    SUBNETWORK_ID_COINBASE,
    SUBNETWORK_ID_NATIVE,
    SUBNETWORK_ID_REGISTRY,
    ComputeCommit,
    ScriptPublicKey,
    Transaction,
    TransactionInput,
    TransactionOutpoint,
    TransactionOutput,
    subnetwork_from_byte,
)
from kaspa_tpu.crypto import blake3 as b3
from kaspa_tpu.crypto import hashing as h
from kaspa_tpu.crypto import merkle


def _tx(version, inputs, outputs, lock_time, subnetwork, gas, payload, mass=0):
    return Transaction(version, inputs, outputs, lock_time, subnetwork, gas, payload, storage_mass=mass)


def _inp(txid32, index, sig_script, sequence, sig_ops):
    return TransactionInput(TransactionOutpoint(txid32, index), sig_script, sequence, ComputeCommit.sigops(sig_ops))


# consensus/core/src/hashing/tx.rs tests (Tests #1-#14)
def test_transaction_hashing_golden():
    cases = []
    cases.append((
        _tx(0, [], [], 0, subnetwork_from_byte(0), 0, b""),
        "2c18d5e59ca8fc4c23d9560da3bf738a8f40935c11c162017fbf2c907b7e665c",
        "c9e29784564c269ce2faaffd3487cb4684383018ace11133de082dce4bb88b0b",
    ))
    inputs = [_inp(h.hash_from_u64_word(0), 2, bytes([1, 2]), 7, 5)]
    cases.append((
        _tx(0, inputs, [], 0, subnetwork_from_byte(0), 0, b""),
        "b2d65ae36e123eb73f253176d7234a57656b84d0d60b9fc746ab0d0f085c9cc7",
        "7d9f7cfdd77f236a41895ac5cdda2fa42f7122964ba995fdfacebce54efad7e8",
    ))
    outputs = [TransactionOutput(1564, ScriptPublicKey(7, bytes([1, 2, 3, 4, 5])))]
    cases.append((
        _tx(0, inputs, outputs, 0, subnetwork_from_byte(0), 0, b""),
        "67289b12146d1b5ef384332137399791a5cfe89506ff31688b0d95ae821d0a0c",
        "492279c0ed5018aa00b0b2d42c1c42350285f2e689236a81829edaf818e30fdb",
    ))
    cases.append((
        _tx(0, inputs, outputs, 54, subnetwork_from_byte(0), 3, b""),
        "7cd34b788d7d230970d4bfd955c34c5abc49e3bcdd5adb03a77bb71d05554401",
        "de319664ee9f4197e89be0d0e08b2b6cac110efc2cf107de1fbc6bd2ce29d545",
    ))
    inputs2 = [_inp(h.hex_to_hash("59b3d6dc6cdc660c389c3fdb5704c48c598d279cdf1bab54182db586a4c95dd5"), 2, bytes([1, 2]), 7, 5)]
    cases.append((
        _tx(0, inputs2, outputs, 54, subnetwork_from_byte(0), 3, b""),
        "c9dd78e818445f617a28348d6db752142e2fab440effa58140ad2773e638b628",
        "1be9978bcab9424f15adac6fca0a64c3f56344a7cd0ec92a225496e19a0d122c",
    ))
    cases.append((
        _tx(0, [], outputs, 54, SUBNETWORK_ID_COINBASE, 3, b""),
        "2578783ec93c3a02414a228e10b1b5af298623254775f972f97df08d4ec28c8f",
        "dffa96c75ef9d17520991fc6d88813531e230488e75b65f65ce958f2d54d2451",
    ))
    cases.append((
        _tx(0, inputs2, outputs, 54, SUBNETWORK_ID_REGISTRY, 3, b""),
        "3f6cea6d7ac8f6b2f86209fa748ea0ef5a1d5d380d43b79e77d52e770bb9a7b9",
        "9abf01c6c312dd984ff19c23bec85e8678e6ea34041fe3c5de52fd9344adac63",
    ))
    cases.append((
        _tx(0, inputs2, outputs, 54, SUBNETWORK_ID_REGISTRY, 3, bytes([1, 2, 3])),
        "4acda997dfb31c6518224c9ac00d0777fc7cbecdab461be3c0816b1cba19a056",
        "f0bb137ed71a91445ddf9224c76f755153a296eeb4fdc29b8393ddd81bf34ce6",
    ))
    cases.append((
        _tx(0, inputs2, outputs, 54, SUBNETWORK_ID_REGISTRY, 3, bytes([1, 2, 3]), mass=5),
        "4acda997dfb31c6518224c9ac00d0777fc7cbecdab461be3c0816b1cba19a056",
        "ced89bbf642cda42d29d9518d16e35cbbf85d10e1ab106b7dc2e0a821308ac91",
    ))
    cases.append((
        _tx(1, inputs2, outputs, 54, SUBNETWORK_ID_REGISTRY, 3, bytes([1, 2, 3])),
        "a08a500b21be3e692c080b14e399fcfa2cfa01b25c08f2f8e7414d1c116e8d18",
        "773f5582d847a1c48947eb4e6e6ac569f90f0f9d979b4c939b72ef008f025e02",
    ))
    # v1: id excludes mass commitments; hash commits to mass & compute_budget
    def v1_tx(budget, mass=0):
        i = TransactionInput(TransactionOutpoint(h.ZERO_HASH, 0), b"", 0, ComputeCommit.budget(budget))
        return _tx(1, [i], [], 0, SUBNETWORK_ID_NATIVE, 0, b"", mass=mass)

    cases.append((
        v1_tx(111),
        "5978e7aa1a9ba8fdf12dae6aa39aa198a91985e91192b291e207d4d6246349e6",
        "c41c18964aab2abe309a79de3dcf0353eee216e29ab83448cbec0c4c5792056c",
    ))
    cases.append((
        v1_tx(222),
        "5978e7aa1a9ba8fdf12dae6aa39aa198a91985e91192b291e207d4d6246349e6",
        "415dfbc5b38e5805e20831d43a49bc770f4f591b00964ac922d108f6a224c590",
    ))

    def v1_sigops_tx(sigops):
        i = TransactionInput(TransactionOutpoint(h.ZERO_HASH, 0), b"", 0, ComputeCommit.sigops(sigops))
        return _tx(1, [i], [], 0, SUBNETWORK_ID_NATIVE, 0, b"")

    cases.append((
        v1_sigops_tx(111),
        "5978e7aa1a9ba8fdf12dae6aa39aa198a91985e91192b291e207d4d6246349e6",
        "55724643b090b9a1c1b9b93b03ffac9cb1bd913a1cf0605a36509322af825864",
    ))
    cases.append((
        v1_sigops_tx(222),
        "5978e7aa1a9ba8fdf12dae6aa39aa198a91985e91192b291e207d4d6246349e6",
        "55724643b090b9a1c1b9b93b03ffac9cb1bd913a1cf0605a36509322af825864",
    ))

    for i, (tx, exp_id, exp_hash) in enumerate(cases):
        assert chash.tx_id(tx).hex() == exp_id, f"txid mismatch test {i + 1}"
        assert chash.tx_hash(tx).hex() == exp_hash, f"txhash mismatch test {i + 1}"


def test_zero_payload_digest():
    # constant from consensus/core/src/hashing/tx.rs (ZERO_PAYLOAD_DIGEST):
    # validates the pure-python keyed BLAKE3 against the blake3 crate
    assert b3.PAYLOAD_ZERO_DIGEST.hex() == "9c0ca2acb45e92ffe6ceb4ae29188b35c82d9676cdd3ce067fd6ccc30a9c4a38"


def test_blake3_multi_chunk_structure():
    # structural self-consistency across the chunk/tree boundary sizes
    for n in (0, 1, 63, 64, 65, 1023, 1024, 1025, 2048, 3000, 5000):
        d = b3.keyed_hash(b"TransactionRest", bytes(range(256)) * ((n // 256) + 1))
        assert len(d) == 32


def test_merkle_root_golden():
    # consensus/core/src/merkle.rs merkle_root_test (block 100k coinbase set)
    tx1 = _tx(
        0,
        [],
        [TransactionOutput(0x12A05F200, ScriptPublicKey(0, bytes.fromhex("a914da1745e9b549bd0bfa1a569971c77eba30cd5a4b87")))],
        0,
        SUBNETWORK_ID_COINBASE,
        0,
        bytes([9] + [0] * 18),
    )
    tx2 = _tx(
        0,
        [
            _inp(bytes.fromhex("165e38e8b3914595d9c641f3b8eec2f34611896b821a683b7a4edefe2c000000"), 0xFFFFFFFF, b"", 2**64 - 1, 0),
            _inp(bytes.fromhex("4bb07535dfd58e0b3cd64fd7155280872a0471bcf83095526ace0e38c6000000"), 0xFFFFFFFF, b"", 2**64 - 1, 0),
        ],
        [],
        0,
        SUBNETWORK_ID_NATIVE,
        0,
        b"",
    )
    tx3 = _tx(
        0,
        [
            _inp(
                bytes.fromhex("032e38e9c0a84c6046d687d10556dcacc41d275ec55fc00779ac88fdf357a187"),
                0,
                bytes.fromhex(
                    "493046022100c352d3dd993a981beba4a63ad15c209275ca9470abfcd57da93b58e4eb5dce82022100840792bc1f4560"
                    "62819f15d33ee7055cf7b5ee1af1ebcc6028d9cdb1c3af7748014104f46db5e9d61a9dc27b8d64ad23e7383a4e6ca164"
                    "593c2527c038c0857eb67ee8e825dca65046b82c9331586c82e0fd1f633f25f87c161bc6f8a630121df2b3d3"
                ),
                2**64 - 1,
                0,
            )
        ],
        [
            TransactionOutput(0x2123E300, ScriptPublicKey(0, bytes.fromhex("76a914c398efa9c392ba6013c5e04ee729755ef7f58b3288ac"))),
            TransactionOutput(0x108E20F00, ScriptPublicKey(0, bytes.fromhex("76a914948c765a6914d43f2a7ac177da2c2f6b52de3d7c88ac"))),
        ],
        0,
        SUBNETWORK_ID_NATIVE,
        0,
        b"",
    )
    tx4 = _tx(
        0,
        [
            _inp(
                bytes.fromhex("c33ebff2a709f13d9f9a7569ab16a32786af7d7e2de09265e41c61d078294ecf"),
                1,
                bytes.fromhex(
                    "4730440220032d30df5ee6f57fa46cddb5eb8d0d9fe8de6b342d27942ae90a3231e0ba333e02203deee8060fdc70230a"
                    "7f5b4ad7d7bc3e628cbe219a886b84269eaeb81e26b4fe014104ae31c31bf91278d99b8377a35bbce5b27d9fff154568"
                    "39e919453fc7b3f721f0ba403ff96c9deeb680e5fd341c0fc3a7b90da4631ee39560639db462e9cb850f"
                ),
                2**64 - 1,
                0,
            )
        ],
        [
            TransactionOutput(0xF4240, ScriptPublicKey(0, bytes.fromhex("76a914b0dcbf97eabf4404e31d952477ce822dadbe7e1088ac"))),
            TransactionOutput(0x11D260C0, ScriptPublicKey(0, bytes.fromhex("76a9146b1281eec25ab4e1e0793ff4e08ab1abb3409cd988ac"))),
        ],
        0,
        SUBNETWORK_ID_NATIVE,
        0,
        b"",
    )
    tx5 = _tx(
        0,
        [
            _inp(
                bytes.fromhex("0b6072b386d4a773235237f64c1126ac3b240c84b917a3909ba1c43ded5f51f4"),
                0,
                bytes.fromhex(
                    "493046022100bb1ad26df930a51cce110cf44f7a48c3c561fd977500b1ae5d6b6fd13d0b3f4a022100c5b42951acedff"
                    "14abba2736fd574bdb465f3e6f8da12e2c5303954aca7f78f3014104a7135bfe824c97ecc01ec7d7e336185c81e2aa2c"
                    "41ab175407c09484ce9694b4 4953fcb751206564a9c24dd094d42fdbfdd5aad3e063ce6af4cfaaea4ea14fbb".replace(" ", "")
                ),
                2**64 - 1,
                0,
            )
        ],
        [
            TransactionOutput(0xF4240, ScriptPublicKey(0, bytes.fromhex("76a91439aa3d569e06a1d7926dc4be1193c99bf2eb9ee088ac"))),
        ],
        0,
        SUBNETWORK_ID_NATIVE,
        0,
        b"",
    )
    txs = [tx1, tx2, tx3, tx4, tx5]
    assert merkle.calc_hash_merkle_root(txs).hex() == "46ecf45be3baca349dfe8a78deaf053b0aa6d538974da50fd6efb4d266bc8d21"

    tx1.storage_mass = 7
    assert merkle.calc_hash_merkle_root(txs).hex() == "754a0159dc4b3daa1695284d96c82aba272a1143e42e6004af2baa1e3ced2307"
    assert (
        merkle.calc_hash_merkle_root_pre_crescendo(txs).hex()
        == "46ecf45be3baca349dfe8a78deaf053b0aa6d538974da50fd6efb4d266bc8d21"
    )


def test_merkle_edges():
    assert merkle.calc_merkle_root([]) == h.ZERO_HASH
    leaf = h.hash_from_u64_word(7)
    assert merkle.calc_merkle_root([leaf]) == leaf


def test_header_hash_structure():
    from kaspa_tpu.consensus.model import Header

    hd = Header(
        version=1,
        parents_by_level=[[h.hash_from_u64_word(1)]],
        hash_merkle_root=h.ZERO_HASH,
        accepted_id_merkle_root=h.ZERO_HASH,
        utxo_commitment=h.ZERO_HASH,
        timestamp=234,
        bits=23,
        nonce=567,
        daa_score=0,
        blue_work=0,
        blue_score=0,
        pruning_point=h.ZERO_HASH,
    )
    assert hd.hash != h.ZERO_HASH and len(hd.hash) == 32
    # blue_work encoding: 0 -> empty var-bytes; 123456 -> 3-byte BE (header.rs test_hash_blue_work)
    hasher = h.BlockHash()
    chash._w_blue_work(hasher, 123456)
    hasher2 = h.BlockHash()
    hasher2.update(bytes([3, 0, 0, 0, 0, 0, 0, 0, 1, 226, 64]))
    assert hasher.digest() == hasher2.digest()
    hasher = h.BlockHash()
    chash._w_blue_work(hasher, 0)
    hasher2 = h.BlockHash()
    hasher2.update(bytes(8))
    assert hasher.digest() == hasher2.digest()
