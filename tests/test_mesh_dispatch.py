"""Mesh-sharded dispatch correctness (ops/mesh.py).

conftest forces 8 CPU host devices (XLA_FLAGS
--xla_force_host_platform_device_count=8), so every mesh size up to 8 is a
real sharded execution here, through the same shard_map entries production
uses.  The contract under test: mesh size is invisible in results — masks,
muhash digests, and BatchScriptChecker decisions are bit-identical to
single-device dispatch, for any batch size (divisible or not, empty,
single job).
"""

import hashlib
import random

import numpy as np
import pytest

from kaspa_tpu.observability.core import REGISTRY
from kaspa_tpu.ops import mesh


@pytest.fixture(autouse=True)
def _mesh_off_after():
    yield
    mesh.configure(1)


def test_configure_resolution():
    assert mesh.configure(1) == 1
    assert mesh.configure(0) == 1  # <= 1 disables
    assert mesh.configure(8) == 8
    assert mesh.configure("auto") == 8  # conftest forces 8 host devices
    assert mesh.configure(64) == 8  # clamps to visible devices
    assert mesh.configure("3") == 3
    state = REGISTRY.snapshot()["mesh"]
    assert state["size"] == 3 and state["configured"] == "3"


# --- muhash -----------------------------------------------------------------


def _muhash_vals(n: int, seed: int = 0):
    from kaspa_tpu.ops import muhash_ops as mo

    rng = random.Random(seed)
    return [rng.getrandbits(3072) % mo.F.modulus for _ in range(n)]


@pytest.mark.parametrize("n", [0, 1, 7, 64, 200])
def test_muhash_product_identical_across_mesh(n):
    from kaspa_tpu.ops import muhash_ops as mo

    vals = _muhash_vals(n, seed=n)
    oracle = 1
    for v in vals:
        oracle = oracle * v % mo.F.modulus
    mesh.configure(1)
    assert mo.batch_product_ints(vals) == oracle
    mesh.configure(8)
    assert mo.batch_product_ints(vals) == oracle
    # non-pow2 mesh: per-shard padding with the monoid identity
    mesh.configure(3)
    assert mo.batch_product_ints(vals) == oracle


# --- batched signature verification ----------------------------------------


def _schnorr_items(n: int, corrupt_every: int = 4):
    from kaspa_tpu.crypto import eclib

    items = []
    for i in range(n):
        sk = i + 1
        msg = hashlib.sha256(bytes([i, n])).digest()
        sig = eclib.schnorr_sign(msg, sk)
        if corrupt_every and i % corrupt_every == corrupt_every - 1:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])
        items.append((eclib.schnorr_pubkey(sk), msg, sig))
    return items


def test_schnorr_mask_identical_mesh1_vs_mesh8():
    from kaspa_tpu.crypto import secp

    # 7 items -> bucket 8, 1 lane/shard on the 8-mesh.  Deliberately the same
    # padded shape as the other schnorr tests here: one shard_map trace of
    # the verify ladder serves the whole file (each extra shape costs
    # minutes of trace time on CPU and would blow the tier-1 budget).
    items = _schnorr_items(7)
    mesh.configure(1)
    m1 = np.asarray(secp.schnorr_verify_batch(items))
    mesh.configure(8)
    m8 = np.asarray(secp.schnorr_verify_batch(items))
    assert m1.tolist() == m8.tolist()
    assert not m1.all() and m1.any()  # mixed validity actually exercised


def test_dispatch_verify_padding_edges():
    """Direct mesh-layer edges: empty batch, single job (7 pad lanes on an
    8-mesh), and a batch not divisible by the shard count."""
    from kaspa_tpu.crypto import secp

    mesh.configure(8)
    assert secp.schnorr_verify_batch([]).shape == (0,)
    single = np.asarray(secp.schnorr_verify_batch(_schnorr_items(1, corrupt_every=0)))
    assert single.tolist() == [True]
    bad_single = np.asarray(secp.schnorr_verify_batch(_schnorr_items(1, corrupt_every=1)))
    assert bad_single.tolist() == [False]


def test_mesh_metrics_surface():
    from kaspa_tpu.crypto import secp
    from kaspa_tpu.ops import muhash_ops as mo

    mesh.configure(8)
    secp.schnorr_verify_batch(_schnorr_items(3))
    mo.batch_product_ints(_muhash_vals(10, seed=99))
    snap = REGISTRY.snapshot()
    assert snap["counters"]["mesh_dispatches"]["schnorr"] >= 1
    assert snap["counters"]["mesh_dispatches"]["muhash"] >= 1
    occ = snap["histograms"]["mesh_shard_occupancy_pct"]
    assert occ["count"] >= 8  # one observation per shard per dispatch
    assert snap["histograms"]["mesh_padding_waste_pct"]["count"] >= 1
    assert snap["mesh"]["size"] == 8


def test_batch_checker_decisions_identical_mesh1_vs_mesh8():
    """The production path: BatchScriptChecker fast-path decisions must be
    bit-identical across mesh sizes (the acceptance criterion's unit-level
    form; the sim replay covers the full-block form)."""
    from kaspa_tpu.consensus import hashing as chash
    from kaspa_tpu.consensus.model import (
        SUBNETWORK_ID_NATIVE,
        ComputeCommit,
        Transaction,
        TransactionInput,
        TransactionOutpoint,
        TransactionOutput,
        UtxoEntry,
    )
    from kaspa_tpu.crypto import eclib
    from kaspa_tpu.txscript import standard
    from kaspa_tpu.txscript.batch import BatchScriptChecker
    from kaspa_tpu.txscript.caches import SigCache

    def p2pk_tx(seed, corrupt):
        rng = random.Random(seed)
        sk = rng.randrange(1, eclib.N)
        pub = eclib.schnorr_pubkey(sk)
        spk = standard.pay_to_pub_key(pub)
        entry = UtxoEntry(10_000, spk, 5, False)
        tx = Transaction(
            0,
            [TransactionInput(TransactionOutpoint(bytes([seed]) * 32, 0), b"", 0, ComputeCommit.sigops(1))],
            [TransactionOutput(9_000, spk)], 0, SUBNETWORK_ID_NATIVE, 0, b"",
        )
        reused = chash.SigHashReusedValues()
        msg = chash.calc_schnorr_signature_hash(tx, [entry], 0, chash.SIG_HASH_ALL, reused)
        sig = eclib.schnorr_sign(msg, sk, rng.randbytes(32))
        if corrupt:
            sig = sig[:9] + bytes([sig[9] ^ 1]) + sig[10:]
        tx.inputs[0].signature_script = standard.schnorr_signature_script(sig, chash.SIG_HASH_ALL)
        return tx, [entry]

    txs = [p2pk_tx(seed, corrupt=(seed % 3 == 0)) for seed in range(40, 47)]

    def run():
        checker = BatchScriptChecker(SigCache())  # fresh cache: no cross-run skips
        for token, (tx, entries) in enumerate(txs):
            checker.collect_tx(token, tx, entries)
        return {
            t: None if e is None else (getattr(e, "input_index", None), str(e))
            for t, e in checker.dispatch().items()
        }

    mesh.configure(1)
    r1 = run()
    mesh.configure(8)
    r8 = run()
    assert r1 == r8
    assert any(v is not None for v in r1.values()) and any(v is None for v in r1.values())
