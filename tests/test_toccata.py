"""Toccata surface tests: ZK precompiles, covenants, introspection opcodes,
runtime resource metering, fork gating.

Mirrors the reference's test layout: runtime_resource_meter.rs tests,
covenants.rs tests, zk_precompiles tests (incl. the succinct.* golden
fixtures for the claim-binding chain), opcode-level introspection tests.
"""

import hashlib
import os
import random

import pytest

import kaspa_tpu.crypto.bn254 as bn254
from kaspa_tpu.consensus.model import (
    Covenant,
    ScriptPublicKey,
    Transaction,
    TransactionInput,
    TransactionOutpoint,
    TransactionOutput,
    UtxoEntry,
)
from kaspa_tpu.crypto import eclib
from kaspa_tpu.crypto.blake3 import blake3, blake3_keyed
from kaspa_tpu.txscript import zk_precompiles as zk
from kaspa_tpu.txscript.covenants import CovenantsContext, CovenantsError, covenant_id
from kaspa_tpu.txscript.resource_meter import (
    MeterError,
    RuntimeScriptUnitMeter,
    RuntimeSigOpCounter,
)
from kaspa_tpu.txscript.vm import EngineFlags, TxScriptError, TxScriptEngine, serialize_i64

TOCCATA = EngineFlags(covenants_enabled=True)
R0_DATA = "/root/reference/crypto/txscript/src/zk_precompiles/tests/data"


# ----------------------------------------------------------------------
# resource meter (runtime_resource_meter.rs tests)
# ----------------------------------------------------------------------


def test_sigops_meter_enforces_sigop_limit():
    m = RuntimeSigOpCounter(2)
    m.consume_sig_ops()
    m.consume_sig_ops()
    assert m.used_sig_ops == 2
    with pytest.raises(MeterError, match="sig op limit"):
        m.consume_sig_ops()


def test_script_units_meter_charges_sigops():
    m = RuntimeScriptUnitMeter(100, 250)
    m.consume_sig_ops(2)
    assert m.used_sig_ops == 2
    assert m.used_script_units == 200
    with pytest.raises(MeterError, match="used 300, limit 250"):
        m.consume_sig_ops(1)
    assert m.used_sig_ops == 2 and m.used_script_units == 200


def test_script_units_meter_charges_pushed_bytes():
    m = RuntimeScriptUnitMeter(0, 20)
    m.charge_newly_pushed_bytes(7)
    m.charge_newly_pushed_bytes(0)
    m.charge_newly_pushed_bytes(9)
    assert m.used_script_units == 16
    with pytest.raises(MeterError):
        m.charge_newly_pushed_bytes(5)


def test_sigops_meter_ignores_script_unit_charges():
    m = RuntimeSigOpCounter(1)
    m.consume_script_units(50)
    m.charge_newly_pushed_bytes(50)
    assert m.used_script_units == 0 and m.used_sig_ops == 0


# ----------------------------------------------------------------------
# BN254 / Groth16
# ----------------------------------------------------------------------


def test_bn254_pairing_bilinearity():
    e1 = bn254.pairing(bn254.G2_GEN, bn254.G1_GEN)
    assert e1 != bn254.F12_ONE
    lhs = bn254.pairing(bn254.g2_mul(bn254.G2_GEN, 13), bn254.g1_mul(bn254.G1_GEN, 7))
    assert lhs == bn254.f12_pow(e1, 91)
    assert bn254.f12_pow(e1, bn254.R) == bn254.F12_ONE


def test_bn254_compressed_serde_roundtrip():
    for k in (1, 2, 12345, bn254.R - 1):
        p1 = bn254.g1_mul(bn254.G1_GEN, k)
        assert bn254.g1_deserialize_compressed(bn254.g1_serialize_compressed(p1)) == p1
        p2 = bn254.g2_mul(bn254.G2_GEN, k)
        assert bn254.g2_deserialize_compressed(bn254.g2_serialize_compressed(p2)) == p2
    assert bn254.g1_deserialize_compressed(bn254.g1_serialize_compressed(None)) is None
    # ark vector: G1 generator = 1 || zeros (flags 00: y=2 is "positive")
    assert bn254.g1_serialize_compressed(bn254.G1_GEN) == b"\x01" + b"\x00" * 31
    with pytest.raises(bn254.DeserializeError):
        bn254.g1_deserialize_compressed(b"\xff" * 32)  # non-canonical x


def _forged_groth16(n_inputs=2, seed=5):
    """Valid-by-construction Groth16 instance: pick all dlogs, solve for C
    so that e(A,B) = e(alpha,beta) e(L,gamma) e(C,delta)."""
    rng = random.Random(seed)
    R = bn254.R
    a_, b_, g_, d_ = [rng.randrange(1, R) for _ in range(4)]
    r_, s_ = rng.randrange(1, R), rng.randrange(1, R)
    ls = [rng.randrange(1, R) for _ in range(n_inputs + 1)]
    xs = [rng.randrange(1, R) for _ in range(n_inputs)]
    l_total = (ls[0] + sum(x * l for x, l in zip(xs, ls[1:]))) % R
    c_ = (r_ * s_ - a_ * b_ - l_total * g_) * pow(d_, -1, R) % R
    vk = (
        bn254.g1_serialize_compressed(bn254.g1_mul(bn254.G1_GEN, a_))
        + bn254.g2_serialize_compressed(bn254.g2_mul(bn254.G2_GEN, b_))
        + bn254.g2_serialize_compressed(bn254.g2_mul(bn254.G2_GEN, g_))
        + bn254.g2_serialize_compressed(bn254.g2_mul(bn254.G2_GEN, d_))
        + (n_inputs + 1).to_bytes(8, "little")
        + b"".join(bn254.g1_serialize_compressed(bn254.g1_mul(bn254.G1_GEN, l)) for l in ls)
    )
    proof = (
        bn254.g1_serialize_compressed(bn254.g1_mul(bn254.G1_GEN, r_))
        + bn254.g2_serialize_compressed(bn254.g2_mul(bn254.G2_GEN, s_))
        + bn254.g1_serialize_compressed(bn254.g1_mul(bn254.G1_GEN, c_))
    )
    return vk, proof, xs


def _groth_stack(vk, proof, xs):
    st = [bn254.fr_serialize(x) for x in reversed(xs)]
    st.append(serialize_i64(len(xs)))
    st.append(proof)
    st.append(vk)
    return st


def test_groth16_accepts_valid_proof_and_meters():
    vk, proof, xs = _forged_groth16()
    m = RuntimeScriptUnitMeter(0, 10**12)
    zk.groth16_verify(_groth_stack(vk, proof, xs), m)
    assert m.used_script_units == 3 * zk.GROTH16_GAMMA_ABC_G1_ELEMENT_SCRIPT_UNITS


def test_groth16_rejects_tampering():
    vk, proof, xs = _forged_groth16()
    bad_proof = bytes([proof[0] ^ 1]) + proof[1:]
    with pytest.raises(zk.ZkError, match="verification failed|invalid proof"):
        zk.groth16_verify(_groth_stack(vk, bad_proof, xs), RuntimeScriptUnitMeter(0, 10**12))
    with pytest.raises(zk.ZkError, match="verification failed"):
        zk.groth16_verify(
            _groth_stack(vk, proof, [xs[0], (xs[1] + 1) % bn254.R]), RuntimeScriptUnitMeter(0, 10**12)
        )


def test_groth16_arity_mismatch_rejected_before_charge():
    vk, proof, xs = _forged_groth16()
    m = RuntimeScriptUnitMeter(0, 0)  # zero budget: any charge would error
    with pytest.raises(zk.ZkError, match="arity mismatch"):
        zk.groth16_verify(_groth_stack(vk, proof, xs[:1]), m)
    assert m.used_script_units == 0


def test_groth16_over_budget_vk_rejected_via_meter():
    vk, proof, xs = _forged_groth16()
    with pytest.raises(MeterError):
        zk.groth16_verify(_groth_stack(vk, proof, xs), RuntimeScriptUnitMeter(0, 200_000))


def test_groth16_trailing_bytes_rejected():
    vk, proof, xs = _forged_groth16()
    with pytest.raises(zk.ZkError, match="trailing verifying key"):
        zk.groth16_verify(_groth_stack(vk + b"\xab", proof, xs), RuntimeScriptUnitMeter(0, 10**12))
    with pytest.raises(zk.ZkError, match="trailing proof"):
        zk.groth16_verify(_groth_stack(vk, proof + b"\xcd", xs), RuntimeScriptUnitMeter(0, 10**12))


def test_groth16_oversized_fr_rejected():
    vk, proof, xs = _forged_groth16()
    st = _groth_stack(vk, proof, xs)
    st[0] = b"\x00" * 64  # 64-byte public input push
    with pytest.raises(zk.ZkError, match="Invalid Fr length"):
        zk.groth16_verify(st, RuntimeScriptUnitMeter(0, 10**12))


def test_zk_tag_parsing_and_costs():
    assert zk.parse_tag(b"\x20") == zk.TAG_GROTH16
    assert zk.parse_tag(b"\x21") == zk.TAG_R0_SUCCINCT
    with pytest.raises(zk.ZkError, match="missing"):
        zk.parse_tag(b"")
    with pytest.raises(zk.ZkError, match="length 2"):
        zk.parse_tag(b"\x20\x20")
    with pytest.raises(zk.ZkError, match="Unknown"):
        zk.parse_tag(b"\x42")
    assert zk.compute_zk_cost(0x20) == 14_000_000
    assert zk.compute_zk_cost(0x21) == 25_000_000
    assert zk.compute_zk_cost(0x99) == zk.MAX_TAG_COST  # unknown -> max


# ----------------------------------------------------------------------
# RISC0 succinct: structural + golden claim binding
# ----------------------------------------------------------------------


@pytest.mark.skipif(not os.path.exists(R0_DATA), reason="reference fixtures not mounted")
def test_r0_claim_binding_matches_reference_fixtures():
    read = lambda n: bytes.fromhex(open(f"{R0_DATA}/succinct.{n}.hex").read().strip())
    zk.compute_assert_claim(read("claim"), read("image"), read("journal"))
    # any perturbation must break the binding
    with pytest.raises(zk.R0Error):
        zk.compute_assert_claim(read("claim"), read("journal"), read("image"))
    bad = bytes([read("image")[0] ^ 1]) + read("image")[1:]
    with pytest.raises(zk.R0Error):
        zk.compute_assert_claim(read("claim"), bad, read("journal"))


def test_r0_operand_parsing():
    with pytest.raises(zk.R0Error, match="digest length"):
        zk.parse_digest(b"\x00" * 31)
    with pytest.raises(zk.R0Error, match="seal length"):
        zk.parse_seal(b"\x00" * 5)
    assert zk.parse_seal(b"\x01\x00\x00\x00\x02\x00\x00\x00") == [1, 2]
    with pytest.raises(zk.R0Error, match="hashfn"):
        zk.parse_hashfn(b"\x07")
    with pytest.raises(zk.R0Error, match="merkle index"):
        zk.parse_merkle_index(b"\x00")
    assert len(zk.parse_digest_list(b"\x00" * 64)) == 2


def test_r0_merkle_proof_path_folding():
    h = lambda a, b: hashlib.sha256(a + b).digest()
    leaves = [hashlib.sha256(bytes([i])).digest() for i in range(4)]
    l2 = [h(leaves[0], leaves[1]), h(leaves[2], leaves[3])]
    root = h(l2[0], l2[1])
    proof = zk.MerkleProof(index=2, digests=[leaves[3], l2[0]])
    assert proof.root(leaves[2], h) == root
    assert zk.MerkleProof(index=1, digests=[leaves[0], l2[1]]).root(leaves[1], h) == root


@pytest.mark.skipif(not os.path.exists(R0_DATA), reason="reference fixtures not mounted")
def test_r0_succinct_fails_closed_on_seal():
    read = lambda n: bytes.fromhex(open(f"{R0_DATA}/succinct.{n}.hex").read().strip())
    # stack bottom..top: claim, control_index, control_digests, seal,
    # journal, image, control_id, hashfn
    st = [
        read("claim"),
        bytes.fromhex(open(f"{R0_DATA}/succinct.control_index.hex").read().strip() or "00000000"),
        b"",
        b"\x00" * 8,
        read("journal"),
        read("image"),
        read("control_id"),
        b"\x01",  # poseidon2
    ]
    with pytest.raises(zk.R0Error, match="seal verification unavailable"):
        zk.r0_succinct_verify(st, RuntimeScriptUnitMeter(0, 10**12))
    # unsupported hashfn short-circuits earlier
    st2 = [read("claim"), b"\x00" * 4, b"", b"", read("journal"), read("image"), read("control_id"), b"\x02"]
    with pytest.raises(zk.R0Error, match="unsupported hashfn"):
        zk.r0_succinct_verify(st2, RuntimeScriptUnitMeter(0, 10**12))


# ----------------------------------------------------------------------
# covenants (covenants.rs tests)
# ----------------------------------------------------------------------

SPK = ScriptPublicKey(0, b"")


def _cov_tx(input_cov_ids, outputs, correct_ids=True):
    """outputs: list of (value, authorizing_input, covenant_group)."""
    inputs = [
        TransactionInput(TransactionOutpoint(bytes([i]) * 32, 0), b"", 0, 0)
        for i in range(len(input_cov_ids))
    ]
    entries = [
        UtxoEntry(1000, SPK, 0, False, covenant_id=(None if c is None else bytes([c]) * 32))
        for c in input_cov_ids
    ]
    outs = [
        TransactionOutput(v, SPK, covenant=Covenant(auth, bytes([grp]) * 32))
        for (v, auth, grp) in outputs
    ]
    tx = Transaction(0, inputs, outs, 0, b"\x00" * 20, 0, b"")
    if correct_ids:
        groups = {}
        for i, (v, auth, grp) in enumerate(outputs):
            in_cov = input_cov_ids[auth] if auth < len(input_cov_ids) else None
            if in_cov != grp:
                groups.setdefault((auth, grp), []).append(i)
        for (auth, grp), idxs in groups.items():
            cid = covenant_id(tx.inputs[auth].previous_outpoint, ((j, tx.outputs[j]) for j in idxs))
            for j in idxs:
                tx.outputs[j] = TransactionOutput(
                    tx.outputs[j].value, SPK, covenant=Covenant(auth, cid)
                )
    return tx, entries


def test_covenants_genesis_outputs_do_not_populate_contexts():
    tx, entries = _cov_tx([None], [(100, 0, 1), (100, 0, 1)])
    ctx = CovenantsContext.from_tx(tx, entries)
    assert not ctx.input_ctxs and not ctx.shared_ctxs


def test_covenants_wrong_genesis_id_rejected():
    tx, entries = _cov_tx([None], [(100, 0, 1)], correct_ids=False)
    with pytest.raises(CovenantsError, match="wrong genesis covenant id"):
        CovenantsContext.from_tx(tx, entries)


def test_covenants_continuation_with_genesis():
    # input carries covenant 42; output 0 continues it, outputs 1-3 are genesis
    tx, entries = _cov_tx([42], [(100, 0, 42), (100, 0, 100), (100, 0, 200), (100, 0, 100)])
    ctx = CovenantsContext.from_tx(tx, entries)
    cov42 = bytes([42]) * 32
    assert ctx.input_ctxs[0].auth_outputs == [0]
    assert ctx.shared_ctxs[cov42].input_indices == [0]
    assert ctx.shared_ctxs[cov42].output_indices == [0]
    assert ctx.num_auth_outputs(0) == 1 and ctx.auth_output_index(0, 0) == 0
    assert ctx.num_covenant_inputs(cov42) == 1 and ctx.covenant_input_index(cov42, 0) == 0
    with pytest.raises(CovenantsError):
        ctx.auth_output_index(0, 1)


def test_covenants_auth_input_out_of_bounds():
    tx, entries = _cov_tx([None], [(100, 1, 1)], correct_ids=False)
    with pytest.raises(CovenantsError, match="out of bounds"):
        CovenantsContext.from_tx(tx, entries)


def test_covenants_input_without_outputs_keeps_shared_ctx():
    inputs = [TransactionInput(TransactionOutpoint(b"\x01" * 32, 0), b"", 0, 0)]
    entries = [UtxoEntry(1000, SPK, 0, False, covenant_id=bytes([42]) * 32)]
    tx = Transaction(0, inputs, [TransactionOutput(100, SPK), TransactionOutput(200, SPK)], 0, b"\x00" * 20, 0, b"")
    ctx = CovenantsContext.from_tx(tx, entries)
    cov42 = bytes([42]) * 32
    assert not ctx.input_ctxs
    assert ctx.shared_ctxs[cov42].input_indices == [0]
    assert ctx.shared_ctxs[cov42].output_indices == []


# ----------------------------------------------------------------------
# VM: Toccata opcodes
# ----------------------------------------------------------------------


def _engine(script=None, tx=None, entries=None, flags=TOCCATA, meter=None):
    e = TxScriptEngine(tx=tx, utxo_entries=entries, input_index=0, flags=flags, meter=meter)
    if script is not None:
        e.execute_standalone(script)
    return e


def _push(data: bytes) -> bytes:
    assert len(data) <= 75
    return bytes([len(data)]) + data


def _intro_tx():
    inputs = [
        TransactionInput(TransactionOutpoint(b"\xaa" * 32, 7), b"\x51\x52", 5, 1),
        TransactionInput(TransactionOutpoint(b"\xbb" * 32, 1), b"", 0, 1),
    ]
    outs = [
        TransactionOutput(1500, ScriptPublicKey(0, b"\xac")),
        TransactionOutput(2500, ScriptPublicKey(1, b"\x51\x51")),
    ]
    entries = [
        UtxoEntry(1000, ScriptPublicKey(0, b"\x51"), 77, True),
        UtxoEntry(3000, ScriptPublicKey(0, b"\x52\x53"), 99, False),
    ]
    tx = Transaction(1, inputs, outs, 1234, b"\x07" * 20, 42, b"payload-bytes")
    return tx, entries


def _run_ops(tx, entries, script, flags=TOCCATA):
    e = TxScriptEngine(tx=tx, utxo_entries=entries, input_index=0, flags=flags)
    e.execute_script(script, verify_only_push=False)
    return e.dstack


def test_introspection_kip10_ungated():
    tx, entries = _intro_tx()
    flags = EngineFlags()  # pre-Toccata
    assert _run_ops(tx, entries, bytes([0xB3]), flags) == [b"\x02"]  # input count
    assert _run_ops(tx, entries, bytes([0xB4]), flags) == [b"\x02"]  # output count
    assert _run_ops(tx, entries, bytes([0xB9]), flags) == [b""]  # input index 0
    assert _run_ops(tx, entries, bytes([0x51]) + bytes([0xBE]), flags) == [serialize_i64(3000)]
    assert _run_ops(tx, entries, bytes([0x51]) + bytes([0xC2]), flags) == [serialize_i64(2500)]
    # spk serialization: BE version + script
    assert _run_ops(tx, entries, b"\x00" + bytes([0xBF]), flags) == [b"\x00\x00\x51"]
    assert _run_ops(tx, entries, bytes([0x51]) + bytes([0xC3]), flags) == [b"\x00\x01\x51\x51"]


def test_introspection_gated_ops():
    tx, entries = _intro_tx()
    assert _run_ops(tx, entries, bytes([0xB2])) == [serialize_i64(1)]  # version
    assert _run_ops(tx, entries, bytes([0xB5])) == [serialize_i64(1234)]  # locktime
    assert _run_ops(tx, entries, bytes([0xB6])) == [b"\x07" * 20]  # subnet
    assert _run_ops(tx, entries, bytes([0xB7])) == [serialize_i64(42)]  # gas
    assert _run_ops(tx, entries, bytes([0xC4])) == [serialize_i64(13)]  # payload len
    assert _run_ops(tx, entries, b"\x00" + bytes([0xBA])) == [b"\xaa" * 32]
    assert _run_ops(tx, entries, b"\x00" + bytes([0xBB])) == [serialize_i64(7)]
    assert _run_ops(tx, entries, b"\x00" + bytes([0xBD])) == [(5).to_bytes(8, "little")]
    assert _run_ops(tx, entries, b"\x00" + bytes([0xC0])) == [serialize_i64(77)]
    assert _run_ops(tx, entries, b"\x00" + bytes([0xC1])) == [serialize_i64(1)]
    assert _run_ops(tx, entries, b"\x00" + bytes([0xC9])) == [serialize_i64(2)]
    # payload substring [0, 7)
    assert _run_ops(tx, entries, b"\x00" + bytes([0x57]) + bytes([0xB8])) == [b"payload"]
    # gated op without the flag -> reserved error
    with pytest.raises(TxScriptError, match="reserved|invalid"):
        _run_ops(tx, entries, bytes([0xB2]), EngineFlags())


def test_splice_bitwise_arith_ops():
    tx, entries = _intro_tx()
    run = lambda s: _run_ops(tx, entries, s)
    assert run(_push(b"ab") + _push(b"cd") + bytes([0x7E])) == [b"abcd"]  # cat
    assert run(_push(b"abcdef") + bytes([0x51]) + bytes([0x54]) + bytes([0x7F])) == [b"bcd"]
    assert run(_push(b"\x0f\xf0") + bytes([0x83])) == [b"\xf0\x0f"]  # invert
    assert run(_push(b"\x0f\x0f") + _push(b"\x33\x33") + bytes([0x84])) == [b"\x03\x03"]
    assert run(_push(b"\x0f\x0f") + _push(b"\x33\x33") + bytes([0x85])) == [b"\x3f\x3f"]
    assert run(_push(b"\x0f\x0f") + _push(b"\x33\x33") + bytes([0x86])) == [b"\x3c\x3c"]
    assert run(bytes([0x56]) + bytes([0x57]) + bytes([0x95])) == [serialize_i64(42)]  # mul
    assert run(_push(b"\x2a") + bytes([0x57]) + bytes([0x96])) == [serialize_i64(6)]  # div
    # trunc-toward-zero semantics: -7 / 2 == -3, -7 % 2 == -1
    assert run(_push(b"\x87") + bytes([0x52]) + bytes([0x96])) == [serialize_i64(-3)]
    assert run(_push(b"\x87") + bytes([0x52]) + bytes([0x97])) == [serialize_i64(-1)]
    with pytest.raises(TxScriptError, match="division by zero"):
        run(bytes([0x51]) + b"\x00" + bytes([0x96]))
    # bitwise length mismatch
    with pytest.raises(TxScriptError, match="equal length"):
        run(bytes([0x51]) + _push(b"\x01\x02") + bytes([0x84]))
    # pre-Toccata these are disabled at the execute level
    with pytest.raises(TxScriptError, match="disabled"):
        _run_ops(tx, entries, _push(b"a") + _push(b"b") + bytes([0x7E]), EngineFlags())


def test_num2bin_bin2num():
    tx, entries = _intro_tx()
    run = lambda s: _run_ops(tx, entries, s)
    assert run(_push(b"\x2a") + bytes([0x54]) + bytes([0xCD])) == [b"\x2a\x00\x00\x00"]
    assert run(_push(b"\x87") + bytes([0x54]) + bytes([0xCD])) == [b"\x07\x00\x00\x80"]  # -7
    with pytest.raises(TxScriptError, match="cannot encode"):
        run(_push(serialize_i64(2**20)) + bytes([0x51]) + bytes([0xCD]))
    with pytest.raises(TxScriptError, match="exceeds 8"):
        run(bytes([0x51]) + bytes([0x59]) + bytes([0xCD]))
    # bin2num: non-minimal input re-encodes minimally
    assert run(_push(b"\x2a\x00\x00\x00") + bytes([0xCE])) == [b"\x2a"]
    assert run(_push(b"\x07\x00\x00\x80") + bytes([0xCE])) == [serialize_i64(-7)]


def test_blake3_opcodes():
    tx, entries = _intro_tx()
    run = lambda s: _run_ops(tx, entries, s)
    assert run(_push(b"abc") + bytes([0xD9])) == [blake3(b"abc")]
    key = bytes(range(32))
    assert run(_push(b"abc") + _push(key) + bytes([0xDA])) == [blake3_keyed(key, b"abc")]
    with pytest.raises(TxScriptError, match="32 bytes"):
        run(_push(b"abc") + _push(b"short") + bytes([0xDA]))
    # blake2b keyed
    import hashlib as h

    assert run(_push(b"abc") + _push(b"k" * 8) + bytes([0xA7])) == [
        h.blake2b(b"abc", digest_size=32, key=b"k" * 8).digest()
    ]


def test_checksig_from_stack():
    tx, entries = _intro_tx()
    sk = 0x1234567890ABCDEF
    pub = eclib.schnorr_pubkey(sk)
    msg = hashlib.sha256(b"csfs").digest()
    sig = eclib.schnorr_sign(msg, sk, b"\x05" * 32)
    script = _push(sig[:64])[:0]  # placate linters
    ok = _run_ops(tx, entries, _push(sig) + _push(msg) + _push(pub) + bytes([0xD7]))
    assert ok == [b"\x01"]
    bad_sig = bytes([sig[0] ^ 1]) + sig[1:]
    assert _run_ops(tx, entries, _push(bad_sig) + _push(msg) + _push(pub) + bytes([0xD7])) == [b""]
    # ecdsa variant
    epub = eclib.ecdsa_pubkey(sk)
    esig = eclib.ecdsa_sign(msg, sk, 777)
    assert _run_ops(tx, entries, _push(esig) + _push(msg) + _push(epub) + bytes([0xD8])) == [b"\x01"]


def test_covenant_opcodes():
    tx, entries = _cov_tx([42], [(100, 0, 42), (100, 0, 100)])
    cov42 = bytes([42]) * 32
    run = lambda s: _run_ops(tx, entries, s)
    assert run(b"\x00" + bytes([0xCB])) == [b"\x01"]  # auth output count
    assert run(b"\x00" + b"\x00" + bytes([0xCC])) == [b""]  # auth output idx 0
    assert run(b"\x00" + bytes([0xCF])) == [cov42]  # input covenant id
    assert run(_push(cov42) + bytes([0xD0])) == [b"\x01"]  # cov input count
    assert run(_push(cov42) + b"\x00" + bytes([0xD1])) == [b""]  # cov input idx
    assert run(_push(cov42) + bytes([0xD2])) == [b"\x01"]  # cov output count
    assert run(_push(cov42) + b"\x00" + bytes([0xD3])) == [b""]
    assert run(b"\x00" + bytes([0xD5])) == [cov42]  # output covenant id
    assert run(b"\x00" + bytes([0xD6])) == [b""]  # authorizing input 0
    assert run(bytes([0x51]) + bytes([0xD6])) == [b""]  # genesis output: auth 0 too
    # unbound output -> zero hash / -1
    tx2, entries2 = _intro_tx()
    assert _run_ops(tx2, entries2, b"\x00" + bytes([0xD5])) == [b"\x00" * 32]
    assert _run_ops(tx2, entries2, b"\x00" + bytes([0xD6])) == [serialize_i64(-1)]


def test_zk_precompile_opcode_end_to_end():
    tx, entries = _intro_tx()
    vk, proof, xs = _forged_groth16(n_inputs=1, seed=9)
    e = TxScriptEngine(tx=tx, utxo_entries=entries, input_index=0, flags=TOCCATA,
                       meter=RuntimeScriptUnitMeter(0, 10**12))
    # stack built directly (operands exceed 75-byte push for vk)
    e.dstack = _groth_stack(vk, proof, xs)
    e.dstack.append(b"\x20")  # tag
    e._op_zk_precompile()
    assert e.dstack == [b"\x01"]
    assert e.meter.used_script_units == 14_000_000 + 2 * zk.GROTH16_GAMMA_ABC_G1_ELEMENT_SCRIPT_UNITS
    # gated off
    e2 = TxScriptEngine(tx=tx, utxo_entries=entries, input_index=0)
    e2.dstack = [b"\x20"]
    with pytest.raises(TxScriptError, match="reserved"):
        e2._op_zk_precompile()


def test_toccata_limits_relaxed():
    e = TxScriptEngine(flags=TOCCATA)
    assert e.max_scripts_size == 1_000_000
    assert e.max_element_size == 1_000_000
    assert e.max_ops == 1_000_000
    e2 = TxScriptEngine()
    assert e2.max_scripts_size == 10_000
    assert e2.max_element_size == 520
    assert e2.max_ops == 201


def test_fork_activation_params():
    from kaspa_tpu.consensus.params import NEVER_ACTIVATION, simnet_params

    p = simnet_params()
    assert p.toccata_activation == NEVER_ACTIVATION
    assert not p.toccata_active(10**18)
    p.toccata_activation = 100
    assert not p.toccata_active(99) and p.toccata_active(100)


def test_runtime_sigop_counter_enforced_pre_toccata():
    """lib.rs:545: pre-Toccata the engine meters executed sig ops against
    the input's committed sig-op count — more checksigs than committed must
    fail, enough must pass."""
    from kaspa_tpu.txscript.resource_meter import RuntimeSigOpCounter

    tx, entries = _intro_tx()
    sk = 424242
    pub = eclib.schnorr_pubkey(sk)
    msg = hashlib.sha256(b"m").digest()
    sig = eclib.schnorr_sign(msg, sk, b"\x02" * 32)
    # CSFS twice under a budget of 1 (use Toccata flags for the opcode, with
    # the sig-op regime meter to isolate the counting behavior)
    script = (_push(sig) + _push(msg) + _push(pub) + bytes([0xD7, 0x75])) * 2 + b"\x51"
    e = TxScriptEngine(tx=tx, utxo_entries=entries, input_index=0, flags=TOCCATA,
                       meter=RuntimeSigOpCounter(1))
    with pytest.raises(TxScriptError, match="sig op limit"):
        e.execute_script(script, verify_only_push=False)
    e2 = TxScriptEngine(tx=tx, utxo_entries=entries, input_index=0, flags=TOCCATA,
                        meter=RuntimeSigOpCounter(2))
    e2.execute_script(script, verify_only_push=False)  # exactly enough


def test_pushed_bytes_charged_under_script_unit_meter():
    """lib.rs:632: every byte an opcode pushes costs one script unit, so
    element-doubling (DUP CAT) exhausts the budget instead of ballooning."""
    tx, entries = _intro_tx()
    grow = _push(b"\x41" * 64) + bytes([0x76, 0x7E]) * 12  # 64B doubling 12x
    m = RuntimeScriptUnitMeter(0, 10_000)
    e = TxScriptEngine(tx=tx, utxo_entries=entries, input_index=0, flags=TOCCATA, meter=m)
    with pytest.raises(TxScriptError, match="exceeded committed script units"):
        e.execute_script(grow, verify_only_push=False)
    assert m.used_script_units <= 10_000
