"""Chaos-engineering layer: fault registry, device breaker, degraded lane,
crash-safe storage, and the VM fallback retry/drain discipline.

The invariants under test: fault selection is hit-indexed and seeded
(never wall clock), so two runs of one workload fire identical faults;
an injected fault may cost throughput (degraded lane, retry, journal
repair) but never changes an acceptance decision or loses committed
state.
"""

from __future__ import annotations

import random
import threading
import time

import pytest

from kaspa_tpu.crypto import eclib, secp
from kaspa_tpu.resilience import breaker as breaker_mod
from kaspa_tpu.resilience.faults import FAULTS, FaultInjected, FaultWedged, mangle_frame
from kaspa_tpu.storage.kv import _PythonEngine
from kaspa_tpu.txscript import batch as script_batch


@pytest.fixture(autouse=True)
def _clean_faults():
    """Every test starts and ends disarmed with a fresh device breaker."""
    FAULTS.clear()
    breaker_mod.device_breaker().reset()
    yield
    FAULTS.clear()
    breaker_mod.device_breaker().reset()


# --- fault registry -------------------------------------------------------


def test_hit_selection_hits_every_after_max():
    FAULTS.configure({"p.hits": {"mode": "error", "hits": [2, 4]}}, seed=1)
    fired = []
    for i in range(1, 6):
        try:
            FAULTS.fire("p.hits")
        except FaultInjected as e:
            fired.append((i, e.hit))
    assert fired == [(2, 2), (4, 4)]

    FAULTS.configure({"p.every": {"mode": "error", "every": 3, "max": 2}}, seed=1)
    fired = [i for i in range(1, 13) if _fires("p.every", i)]
    assert fired == [3, 6]  # every 3rd, capped at 2 firings

    FAULTS.configure({"p.after": {"mode": "error", "after": 4}}, seed=1)
    fired = [i for i in range(1, 8) if _fires("p.after", i)]
    assert fired == [4, 5, 6, 7]


def _fires(point: str, _i: int) -> bool:
    try:
        FAULTS.fire(point)
        return False
    except FaultInjected:
        return True


def test_unscheduled_points_and_disarmed_registry_are_free():
    assert FAULTS.fire("never.scheduled") is None  # disarmed
    FAULTS.configure({"other.point": {"mode": "error", "hits": [1]}}, seed=0)
    assert FAULTS.fire("never.scheduled") is None  # armed, not scheduled


def test_event_log_is_deterministic_and_sorted():
    schedule = {"b.point": {"mode": "error", "hits": [1, 3]}, "a.point": {"mode": "slow", "delay": 0, "hits": [2]}}

    def run():
        FAULTS.configure(schedule, seed=9)
        for _ in range(4):
            for p in ("b.point", "a.point"):
                try:
                    FAULTS.fire(p)
                except FaultInjected:
                    pass
        return FAULTS.events()

    first, second = run(), run()
    assert first == second
    assert first == [
        {"point": "a.point", "hit": 2, "mode": "slow"},
        {"point": "b.point", "hit": 1, "mode": "error"},
        {"point": "b.point", "hit": 3, "mode": "error"},
    ]


def test_wedge_sleeps_then_raises():
    FAULTS.configure({"w": {"mode": "wedge", "delay": 0.05, "hits": [1]}}, seed=0)
    t0 = time.monotonic()
    with pytest.raises(FaultWedged):
        FAULTS.fire("w")
    assert time.monotonic() - t0 >= 0.04


def test_cooperative_action_rng_is_seed_stable():
    def draws(seed):
        FAULTS.configure({"c": {"mode": "corrupt", "hits": [1]}}, seed=seed)
        act = FAULTS.fire("c")
        assert act is not None and act.mode == "corrupt"
        return [act.rng.randrange(1000) for _ in range(4)]

    assert draws(7) == draws(7)
    assert draws(7) != draws(8)


def test_mangle_frame_modes():
    FAULTS.configure({"c": {"mode": "corrupt", "after": 1}}, seed=3)
    frame = bytes(range(64))
    act = FAULTS.fire("c")
    mangled = mangle_frame(frame, act)
    assert len(mangled) == len(frame) and mangled != frame
    assert mangled[:8] == frame[:8]  # header region untouched: stream stays synced
    act2 = FAULTS.fire("c")
    act2.mode = "truncate"
    assert mangle_frame(frame, act2) == frame[:32]
    act3 = FAULTS.fire("c")
    act3.mode = "drop"
    assert mangle_frame(frame, act3) is None


# --- circuit breaker ------------------------------------------------------


def _fake_clock(start=100.0):
    now = [start]

    def clock():
        return now[0]

    return clock, now


def test_breaker_trips_probes_and_recovers():
    clock, now = _fake_clock()
    br = breaker_mod.CircuitBreaker("t", failure_threshold=2, backoff_base=1.0, clock=clock)
    assert br.allow() and br.allow()
    br.record_failure()
    br.record_failure()  # second consecutive failure: trip
    assert br.state == breaker_mod.OPEN and br.trips == 1
    assert not br.allow() and br.denied == 1  # inside the backoff window
    now[0] += 1.0
    assert br.allow()  # half-open probe
    assert br.state == breaker_mod.HALF_OPEN and br.probes == 1
    now[0] += 2.5
    br.record_success()
    assert br.state == breaker_mod.CLOSED and br.recoveries == 1
    assert br.recovery_latencies == [pytest.approx(3.5)]
    assert [t["to"] for t in br.transitions] == ["open", "half_open", "closed"]


def test_breaker_failed_probe_doubles_backoff():
    clock, now = _fake_clock()
    br = breaker_mod.CircuitBreaker("t", failure_threshold=1, backoff_base=1.0, backoff_max=3.0, clock=clock)
    assert br.allow()
    br.record_failure()  # trip: reopen after 1s
    now[0] += 0.5
    assert not br.allow()
    now[0] += 0.5
    assert br.allow()  # probe at +1s
    br.record_failure()  # failed probe: reopen after 2s
    now[0] += 1.9
    assert not br.allow()
    now[0] += 0.2
    assert br.allow()
    br.record_failure()  # second failed probe: 4s capped to backoff_max=3
    now[0] += 2.9
    assert not br.allow()
    now[0] += 0.2
    assert br.allow()
    br.record_success()
    assert br.state == breaker_mod.CLOSED


def test_breaker_half_open_admits_single_probe():
    clock, now = _fake_clock()
    br = breaker_mod.CircuitBreaker("t", failure_threshold=1, backoff_base=1.0, clock=clock)
    br.allow()
    br.record_failure()
    now[0] += 1.5
    assert br.allow()  # the probe
    assert not br.allow()  # concurrent dispatch while the probe is in flight
    br.record_success()
    assert br.allow()


# --- degraded dispatch lane ----------------------------------------------


def _schnorr_items(n=10, seed=5):
    rng = random.Random(seed)
    items = []
    for i in range(n):
        sk = rng.randrange(1, eclib.N)
        msg = rng.randbytes(32)
        pub = eclib.schnorr_pubkey(sk)
        sig = eclib.schnorr_sign(msg, sk, rng.randbytes(32))
        if i % 3 == 1:
            msg = rng.randbytes(32)  # wrong message: host-verify False
        elif i % 3 == 2 and i % 2 == 0:
            pub = b"\x00" * 32  # invalid pubkey: precheck False
        items.append((pub, msg, sig))
    return items


def test_degraded_lane_matches_oracle_decisions():
    """With every device dispatch erroring, the host degraded lane must
    return exactly the oracle's accept/reject mask — faults degrade
    throughput, never decisions."""
    items = _schnorr_items()
    expect = [eclib.schnorr_verify(p, m, s) for p, m, s in items]
    FAULTS.configure({"device.verify": {"mode": "error", "after": 1}}, seed=0)
    mask = secp.schnorr_verify_batch(items)
    assert list(mask) == expect
    assert any(expect) and not all(expect)
    br = breaker_mod.device_breaker()
    assert br.consecutive_failures >= 1 or br.state != breaker_mod.CLOSED


def test_breaker_trip_reroutes_then_recovers_on_device_health(monkeypatch):
    """Three faulted dispatches trip the device breaker; once the schedule
    is exhausted and the backoff elapses, the probe succeeds and dispatch
    returns to the device lane."""
    import numpy as np

    # stand-in kernel with the real fault point, so the test exercises the
    # breaker state machine without paying the XLA compile of the ladder
    def fake_kernel(px, py, rc, d1, d2, ok):
        FAULTS.fire("device.verify")
        return np.asarray(ok)

    fake_kernel.__name__ = "schnorr_verify"
    monkeypatch.setattr(secp, "schnorr_verify", fake_kernel)

    br = breaker_mod.device_breaker()
    items = _schnorr_items(4)
    oracle = [eclib.schnorr_verify(p, m, s) for p, m, s in items]
    FAULTS.configure({"device.verify": {"mode": "error", "hits": [1, 2, 3]}}, seed=0)
    for _ in range(3):
        assert list(secp.schnorr_verify_batch(items)) == oracle  # degraded lane
    assert br.state == breaker_mod.OPEN and br.trips == 1
    # inside the backoff window: denied, still host-served, still correct
    assert list(secp.schnorr_verify_batch(items)) == oracle
    assert br.denied >= 1
    time.sleep(br.backoff_base + 0.05)
    # successful probe: the (stand-in) device answers again
    mask = secp.schnorr_verify_batch(items)
    assert br.state == breaker_mod.CLOSED and br.recoveries == 1
    assert len(mask) == len(items)


# --- VM fallback lane: retry + drain --------------------------------------


def test_vm_fallback_retries_injected_fault_to_success():
    runs = []

    def work():
        runs.append(1)

    job = script_batch._FallbackJob(token=0, input_index=0, run=work)
    FAULTS.configure({"vm.fallback.exec": {"mode": "error", "hits": [1, 2]}}, seed=0)
    assert script_batch._run_fallback(job) is None
    assert len(runs) == 1  # two faulted attempts retried, third ran the job


def test_vm_fallback_real_failures_are_not_retried():
    runs = []

    def bad():
        runs.append(1)
        raise ValueError("script rejected")

    job = script_batch._FallbackJob(token=0, input_index=3, run=bad)
    err = script_batch._run_fallback(job)
    assert isinstance(err, ValueError) and len(runs) == 1


def test_drain_fallback_pool_waits_for_inflight_jobs():
    release = threading.Event()
    done = []

    def slow():
        release.wait(5.0)
        done.append(1)

    pool = script_batch._fallback_pool()
    futs = [
        script_batch._submit_tracked(pool, script_batch._FallbackJob(token=i, input_index=i, run=slow))
        for i in range(3)
    ]
    assert not script_batch.drain_fallback_pool(timeout=0.1)  # still in flight
    release.set()
    assert script_batch.drain_fallback_pool(timeout=5.0)
    assert len(done) == 3 and all(f.result() is None for f in futs)


# --- crash-safe storage ---------------------------------------------------


def test_torn_tail_is_repaired_and_later_writes_survive(tmp_path):
    """A torn frame at the log tail is truncated on replay, so frames
    appended by the NEXT session land on the valid prefix instead of being
    buried behind garbage (the orphaned-frame regression)."""
    path = str(tmp_path / "kv.log")
    eng = _PythonEngine(path)
    eng.put(b"a", b"1")
    eng.put(b"b", b"2")
    eng.close()

    with open(path, "ab") as f:
        f.write(b"KBAT\xff\xff")  # torn frame: header cut mid-length

    eng2 = _PythonEngine(path)  # replay truncates the torn tail
    assert eng2.get(b"a") == b"1" and eng2.get(b"b") == b"2"
    eng2.put(b"c", b"3")
    eng2.close()

    eng3 = _PythonEngine(path)
    assert [eng3.get(k) for k in (b"a", b"b", b"c")] == [b"1", b"2", b"3"]
    eng3.close()


def test_partial_flush_fault_reopens_to_pre_batch_state(tmp_path):
    """An injected mid-append crash (partial frame on disk) must reopen to
    the state before the torn batch — and the survivor keeps accepting
    writes."""
    path = str(tmp_path / "kv.log")
    eng = _PythonEngine(path)
    eng.put(b"k0", b"stable")
    FAULTS.configure({"storage.flush": {"mode": "partial", "hits": [1]}}, seed=4)
    with pytest.raises(FaultInjected):
        eng.put(b"k1", b"torn")
    FAULTS.clear()
    # the writer process "died" here: reopen from the on-disk image
    eng2 = _PythonEngine(path)
    assert eng2.get(b"k0") == b"stable"
    assert eng2.get(b"k1") is None  # torn batch fully rolled back
    eng2.put(b"k2", b"after")
    eng2.close()
    eng3 = _PythonEngine(path)
    assert eng3.get(b"k2") == b"after" and eng3.get(b"k1") is None
    eng3.close()


def test_batch_commit_fault_preserves_atomicity(tmp_path):
    """storage.commit erroring mid write-batch: nothing from the batch may
    be visible after reopen (the engine's all-or-nothing contract)."""
    from kaspa_tpu.storage.kv import KvStore

    path = str(tmp_path / "kv.db")
    db = KvStore(path, native=False)
    db.engine.put(b"base", b"v")
    FAULTS.configure({"storage.commit": {"mode": "error", "hits": [1]}}, seed=0)
    with pytest.raises(FaultInjected):
        with db.batch() as b:
            b.put(b"x", b"1")
            b.put(b"y", b"2")
    FAULTS.clear()
    db2 = KvStore(path, native=False)
    assert db2.engine.get(b"base") == b"v"
    db2.close()
