"""Verify fabric (kaspa_tpu/fabric/): wire format, verifyd service,
cross-host balancer, and the 2-D hybrid mesh spec/partition registry.

The contract under test: routing verify chunks over the fabric is
invisible in results — masks are bit-identical to direct batched
dispatch — while slice failures (send faults, corrupted frames, a
stopped server) fail over to the next slice or the bit-identical host
degraded lane without ever losing a ticket.

Shape discipline: every device call here lands in the same padded
bucket-8 shape the other verify tests use (each new bucket costs a
fresh XLA compile on CPU, minutes of tier-1 budget).  The degraded-lane
and stop-race tests never touch the device at all (host oracle lane).
"""

import hashlib

import numpy as np
import pytest

from kaspa_tpu.fabric import wire
from kaspa_tpu.fabric.balancer import FabricBalancer
from kaspa_tpu.fabric.service import VerifyService
from kaspa_tpu.observability.core import REGISTRY
from kaspa_tpu.ops import dispatch as coalesce
from kaspa_tpu.ops import mesh
from kaspa_tpu.p2p.proto.wire_format import ProtoWireError
from kaspa_tpu.resilience.faults import FAULTS


@pytest.fixture(autouse=True)
def _clean_after():
    yield
    FAULTS.clear()
    coalesce.configure(0)
    mesh.configure(1)


def _schnorr_items(n: int, corrupt_every: int = 4):
    from kaspa_tpu.crypto import eclib

    items = []
    for i in range(n):
        sk = i + 1
        msg = hashlib.sha256(bytes([i, n])).digest()
        sig = eclib.schnorr_sign(msg, sk)
        if corrupt_every and i % corrupt_every == corrupt_every - 1:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])
        items.append((eclib.schnorr_pubkey(sk), msg, sig))
    return items


# --- wire format -------------------------------------------------------------


def test_wire_hello_roundtrip():
    mtype, msg = wire.decode(wire.encode_hello(4, modes=wire.MODE_AGGREGATE))
    assert mtype == wire.HELLO
    assert msg == {"proto": wire.PROTO_VERSION, "slices": 4, "modes": wire.MODE_AGGREGATE}


def test_wire_hello_proto1_compat():
    # a proto-1 HELLO has no trailing modes varint; decode defaults modes=0
    mtype, msg = wire.decode(wire.encode_hello(2, proto=1)[: 1 + 1 + 1])
    assert mtype == wire.HELLO
    assert msg == {"proto": 1, "slices": 2, "modes": 0}


def test_wire_verify_req_roundtrip():
    items = [(b"\x02" * 32, b"\xaa" * 32, b"\x0f" * 64), (b"\x03" * 33, b"\xbb" * 32, b"\x10" * 65)]
    payload = wire.encode_verify_req(7, "ecdsa", 3, "trace-1", items)
    mtype, msg = wire.decode(payload)
    assert mtype == wire.VERIFY_REQ
    assert msg["req_id"] == 7 and msg["kind"] == "ecdsa" and msg["slice"] == 3
    assert msg["trace_id"] == "trace-1"
    assert msg["items"] == items
    # absent trace id decodes to None, not ""
    _, msg2 = wire.decode(wire.encode_verify_req(8, "schnorr", 0, None, []))
    assert msg2["trace_id"] is None and msg2["items"] == []


@pytest.mark.parametrize("lanes", [1, 7, 8, 9, 64])
def test_wire_mask_roundtrip_at_byte_edges(lanes):
    mask = np.array([i % 3 != 1 for i in range(lanes)], dtype=bool)
    _, msg = wire.decode(wire.encode_verify_resp(5, mask, 123, 456, 2))
    assert msg["ok"] is True
    assert msg["mask"].tolist() == mask.tolist()
    assert (msg["queue_ns"], msg["verify_ns"], msg["inflight"]) == (123, 456, 2)


def test_wire_error_and_status_roundtrip():
    _, err = wire.decode(wire.encode_error_resp(9, "kaboom"))
    assert err == {"req_id": 9, "ok": False, "error": "kaboom"}
    _, st = wire.decode(wire.encode_status_resp(11, [(1, 0), (0, 5)]))
    assert st == {"req_id": 11, "slices": [(1, 0), (0, 5)]}
    mtype, req = wire.decode(wire.encode_status_req(11))
    assert mtype == wire.STATUS_REQ and req == {"req_id": 11}


def test_wire_rejects_malformed():
    with pytest.raises(ProtoWireError):
        wire.decode(b"")
    with pytest.raises(ProtoWireError):
        wire.decode(bytes([0x7F]))  # unknown message type
    good = wire.encode_verify_req(1, "schnorr", 0, None, [(b"\x02" * 32, b"\xaa" * 32, b"\x0f" * 64)])
    with pytest.raises(ProtoWireError):
        wire.decode(good[: len(good) // 2])  # truncated mid-item
    # a decodable-but-lying mask length must not produce a short mask
    resp = bytearray(wire.encode_verify_resp(2, np.ones(8, dtype=bool), 0, 0, 0))
    resp[2] = 16  # claim 16 lanes, still 1 packed byte
    with pytest.raises(ProtoWireError):
        wire.decode(bytes(resp))


# --- service + balancer ------------------------------------------------------


def _serve(slices: int = 2):
    svc = VerifyService("127.0.0.1:0", slices=slices)
    host, port = svc.start()
    return svc, f"{host}:{port}"


def test_remote_verify_bit_identical():
    """One chunk over a real socket to an in-process verifyd: the mask
    must be byte-identical to direct batched dispatch, resolved remotely
    with zero lost tickets."""
    from kaspa_tpu.crypto import secp

    items = _schnorr_items(7)
    direct = np.asarray(secp.schnorr_verify_batch(items)).tolist()  # warms the kernel too

    svc, addr = _serve(slices=2)
    bal = FabricBalancer([addr], deadline_s=120.0)
    try:
        got = [bool(v) for v in bal.submit("schnorr", items).wait(120.0)]
        assert got == direct
        assert not all(got) and any(got)  # mixed validity actually exercised
        st = bal.stats()
        assert st["remote"] == 1 and st["degraded"] == 0 and st["lost"] == 0
        assert len(st["slices"]) == 2  # one routable lane per server slice
    finally:
        bal.close(timeout=5.0)
        svc.stop()
    snap = REGISTRY.snapshot()
    assert sum(snap["counters"].get("fabric_remote_jobs", {}).values()) >= 7
    assert sum(snap["counters"].get("fabric_service_requests", {}).values()) >= 1


def test_degraded_lane_when_no_slice_reachable():
    """Nothing listening on any address: every chunk lands on the host
    degraded lane (eclib oracle — no device), bit-identical, lost == 0."""
    items = _schnorr_items(7)
    bal = FabricBalancer(["127.0.0.1:1"], deadline_s=30.0)
    try:
        got = [bool(v) for v in bal.submit("schnorr", items).wait(30.0)]
        assert got == [i % 4 != 3 for i in range(7)]
        st = bal.stats()
        assert st["remote"] == 0 and st["degraded"] == 1 and st["lost"] == 0
    finally:
        bal.close(timeout=5.0)


def test_send_fault_fails_over_to_next_slice():
    """An injected fabric.send error on the first attempt: the chunk is
    re-routed (failover) and still resolves remotely, bit-identically."""
    from kaspa_tpu.crypto import secp

    items = _schnorr_items(7)
    direct = np.asarray(secp.schnorr_verify_batch(items)).tolist()

    svc, addr = _serve(slices=2)
    bal = FabricBalancer([addr], deadline_s=120.0)
    try:
        FAULTS.configure({"fabric.send": {"mode": "error", "hits": [1]}}, seed=0)
        got = [bool(v) for v in bal.submit("schnorr", items).wait(120.0)]
        assert got == direct
        st = bal.stats()
        assert st["failovers"] >= 1 and st["remote"] == 1 and st["lost"] == 0
    finally:
        FAULTS.clear()
        bal.close(timeout=5.0)
        svc.stop()


def test_truncated_frame_hangs_then_degrades():
    """A truncated request frame leaves the server reader blocked
    mid-frame: the request can never be answered, the balancer's deadline
    trips the slice as hung, and with no other slice the chunk resolves
    on the degraded lane — never lost, never wrong."""
    items = _schnorr_items(7)
    svc, addr = _serve(slices=1)
    bal = FabricBalancer([addr], deadline_s=2.0)
    try:
        FAULTS.configure({"fabric.send": {"mode": "truncate", "hits": [1]}}, seed=3)
        got = [bool(v) for v in bal.submit("schnorr", items).wait(30.0)]
        assert got == [i % 4 != 3 for i in range(7)]
        st = bal.stats()
        assert st["degraded"] == 1 and st["lost"] == 0
        assert sum(s["trips"] for s in st["slices"]) >= 1  # the hung verdict
    finally:
        FAULTS.clear()
        bal.close(timeout=5.0)
        svc.stop()


def test_server_stop_races_submit_without_losing_tickets():
    """stop() the service under a connected balancer, then submit: the
    dead link must route the chunk to the degraded lane, resolved exactly
    once (the fabric smoke's kill drill, at unit scale and device-free)."""
    items = _schnorr_items(7)
    svc, addr = _serve(slices=2)
    bal = FabricBalancer([addr], deadline_s=5.0)
    try:
        assert any(s.conn.alive for s in bal._slices)
        svc.stop()
        t = bal.submit("schnorr", items)
        got = [bool(v) for v in t.wait(30.0)]
        assert got == [i % 4 != 3 for i in range(7)]
        st = bal.stats()
        assert st["submitted"] == 1 and st["degraded"] == 1 and st["lost"] == 0
    finally:
        bal.close(timeout=5.0)


# --- 2-D hybrid mesh ---------------------------------------------------------


def test_mesh_2d_spec_parsing():
    # conftest forces 8 CPU host devices
    assert mesh.configure("2x4") == 8
    assert mesh.grid() == (2, 4)
    assert mesh.slice_count() == 2 and mesh.slice_width() == 4
    # grid clamping prefers keeping the slice count (the failover unit)
    assert mesh.configure("4x4") == 8
    assert mesh.grid() == (4, 2)
    # a single slice degenerates to the 1-D mesh
    assert mesh.configure("1x8") == 8
    assert mesh.grid() is None
    # plain integers never form a grid
    assert mesh.configure(8) == 8
    assert mesh.grid() is None and mesh.slice_count() == 1
    state = REGISTRY.snapshot()["mesh"]
    assert state["grid"] == "" and state["size"] == 8


def test_partition_rule_registry():
    from jax.sharding import PartitionSpec as P

    mesh.configure("2x4")
    assert mesh.partition_spec_for("px") == P(("slice", "shard"), None)
    assert mesh.partition_spec_for("valid_in") == P(("slice", "shard"))
    assert mesh.partition_spec_for("anything_else") == P()
    # 1-D projection collapses the composite batch axis onto "shard"
    assert mesh.partition_spec_for("px", flat=True) == P("shard", None)
    # registration is first-match-wins at the head of the registry
    before = list(mesh._partition_rules)
    try:
        mesh.register_partition_rule(r"px", ("shard",))
        assert mesh.partition_spec_for("px") == P("shard")
    finally:
        mesh._partition_rules[:] = before
    tree = {"layer": {"px": 1, "bias": 2}}
    specs = mesh.match_partition_rules(mesh.DEFAULT_PARTITION_RULES, tree)
    assert specs["layer"]["px"] == P(("slice", "shard"), None)
    assert specs["layer"]["bias"] == P()


def test_schnorr_mask_identical_1d_vs_2x4_grid():
    """The full 2-D grid (both mesh axes, no slice pinning) must be
    bit-identical to single-device dispatch — same bucket-8 shape as the
    1-D mesh tests, so the grid entry's local computation is served by
    the persistent compilation cache."""
    from kaspa_tpu.crypto import secp

    items = _schnorr_items(7)
    mesh.configure(1)
    m1 = np.asarray(secp.schnorr_verify_batch(items))
    mesh.configure("2x4")
    m2d = np.asarray(secp.schnorr_verify_batch(items))
    assert m1.tolist() == m2d.tolist()
    assert not m1.all() and m1.any()
    snap = REGISTRY.snapshot()
    assert snap["mesh"]["grid"] == "2x4"
