"""UPnP IGD port mapping against a fake local gateway.

Stands in for the reference's igd-backed mapping
(components/addressmanager configure_port_mapping +
port_mapping_extender.rs): SSDP discovery, device-description parsing,
GetExternalIPAddress, AddPortMapping with a lease, extender re-adds on the
half-lease tick, DeletePortMapping on stop.
"""

from __future__ import annotations

import http.server
import re
import socket
import threading

import pytest

from kaspa_tpu.p2p import upnp


DESCRIPTION_XML = """<?xml version="1.0"?>
<root xmlns="urn:schemas-upnp-org:device-1-0">
  <device>
    <deviceType>urn:schemas-upnp-org:device:InternetGatewayDevice:1</deviceType>
    <serviceList>
      <service>
        <serviceType>urn:schemas-upnp-org:service:Layer3Forwarding:1</serviceType>
        <controlURL>/l3f</controlURL>
      </service>
      <service>
        <serviceType>urn:schemas-upnp-org:service:WANIPConnection:1</serviceType>
        <controlURL>/ctl/WANIP</controlURL>
      </service>
    </serviceList>
  </device>
</root>"""


class _FakeIgd(http.server.BaseHTTPRequestHandler):
    mappings: list = []
    deletions: list = []

    def log_message(self, *a):  # quiet
        pass

    def do_GET(self):
        body = DESCRIPTION_XML.encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        body = self.rfile.read(int(self.headers.get("Content-Length", 0))).decode()
        action = self.headers.get("SOAPAction", "")
        if "GetExternalIPAddress" in action:
            payload = "<NewExternalIPAddress>203.0.113.7</NewExternalIPAddress>"
        elif "AddPortMapping" in action:
            ext = re.search(r"<NewExternalPort>(\d+)</NewExternalPort>", body).group(1)
            lease = re.search(r"<NewLeaseDuration>(\d+)</NewLeaseDuration>", body).group(1)
            desc = re.search(r"<NewPortMappingDescription>([^<]*)<", body).group(1)
            type(self).mappings.append((int(ext), int(lease), desc))
            payload = ""
        elif "DeletePortMapping" in action:
            ext = re.search(r"<NewExternalPort>(\d+)</NewExternalPort>", body).group(1)
            type(self).deletions.append(int(ext))
            payload = ""
        else:
            self.send_response(500)
            self.end_headers()
            return
        resp = f'<?xml version="1.0"?><s:Envelope><s:Body>{payload}</s:Body></s:Envelope>'.encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(resp)))
        self.end_headers()
        self.wfile.write(resp)


@pytest.fixture()
def fake_gateway():
    _FakeIgd.mappings = []
    _FakeIgd.deletions = []
    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _FakeIgd)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    http_port = httpd.server_address[1]

    # SSDP responder on a localhost UDP port (tests cannot multicast)
    ssdp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    ssdp.bind(("127.0.0.1", 0))
    ssdp_addr = ssdp.getsockname()
    stop = threading.Event()

    def respond():
        ssdp.settimeout(0.2)
        while not stop.is_set():
            try:
                data, peer = ssdp.recvfrom(2048)
            except socket.timeout:
                continue
            if b"M-SEARCH" in data:
                ssdp.sendto(
                    (
                        "HTTP/1.1 200 OK\r\n"
                        f"LOCATION: http://127.0.0.1:{http_port}/desc.xml\r\n"
                        "ST: urn:schemas-upnp-org:device:InternetGatewayDevice:1\r\n\r\n"
                    ).encode(),
                    peer,
                )

    threading.Thread(target=respond, daemon=True).start()
    yield ssdp_addr
    stop.set()
    httpd.shutdown()
    ssdp.close()


def test_discovery_mapping_and_extender(fake_gateway):
    gw = upnp.discover_gateway(timeout=2.0, ssdp_addr=fake_gateway)
    assert gw.service_type.endswith("WANIPConnection:1")
    assert gw.get_external_ip() == "203.0.113.7"

    gw.add_port_mapping(16111, "127.0.0.1", 16111)
    assert _FakeIgd.mappings == [(16111, upnp.UPNP_DEADLINE_SEC, upnp.UPNP_REGISTRATION_NAME)]

    # extender re-adds on its tick, delete runs on stop
    ext = upnp.PortMappingExtender(gw, 16111, "127.0.0.1", 16111, period_sec=0.2)
    ext.start()
    import time

    deadline = time.monotonic() + 5
    while ext.extend_count < 2 and time.monotonic() < deadline:
        time.sleep(0.05)
    ext.stop()
    assert ext.extend_count >= 2
    assert len(_FakeIgd.mappings) >= 3  # initial + at least two extensions
    assert _FakeIgd.deletions == [16111]


def test_configure_port_mapping_end_to_end(fake_gateway):
    external_ip, ext = upnp.configure_port_mapping(16111, timeout=2.0, ssdp_addr=fake_gateway)
    try:
        assert external_ip == "203.0.113.7"
        assert _FakeIgd.mappings and _FakeIgd.mappings[0][0] == 16111
    finally:
        ext.stop()
    assert _FakeIgd.deletions == [16111]


def test_no_gateway_fails_soft():
    # nothing answers on this closed localhost port: discovery raises the
    # typed error the daemon catches (fail-soft path)
    with pytest.raises(upnp.UpnpError, match="no internet gateway"):
        upnp.discover_gateway(timeout=0.3, ssdp_addr=("127.0.0.1", 1))
