"""Mainnet/testnet daemon bring-up, DB versioning, and mining-rule gating.

Reference: kaspad/src/daemon.rs:303-522 (network selection, DB version
stamping/upgrade refusal) and protocol/mining/src/rule_engine.rs
(sync-state-gated template serving).
"""

from __future__ import annotations

import random
import time

import pytest

from kaspa_tpu.node.daemon import DB_VERSION, Daemon, parse_args, rpc_call
from kaspa_tpu.consensus.params import simnet_params


def test_daemon_mainnet_bringup(tmp_path):
    """The daemon starts on real mainnet params: real genesis loads, is
    queryable by its published hash, templates are refused while unsynced,
    and a fabricated block is rejected by real-PoW validation."""
    from kaspa_tpu.consensus.networks import GENESIS_DATA

    args = parse_args(
        ["--appdir", str(tmp_path), "--rpclisten", "127.0.0.1:0", "--network", "mainnet", "--no-persist"]
    )
    d = Daemon(args)
    addr = d.start()
    try:
        info = rpc_call(addr, "getServerInfo")
        assert info["network_id"] == "kaspa-mainnet"
        assert args.address_prefix == "kaspa"
        genesis_hash = GENESIS_DATA["mainnet"]["hash"]
        blk = rpc_call(addr, "getBlock", {"hash": genesis_hash})
        assert blk["hash"] == genesis_hash
        assert blk["header"]["daa_score"] == GENESIS_DATA["mainnet"]["daa_score"]
        assert blk["verbose"]["is_chain_block"]

        # MiningRuleEngine: no peers + stale sink => no templates (mainnet
        # requires connectivity AND sync; rule_engine.rs should_mine)
        from kaspa_tpu.wallet.account import Account

        pay = Account.from_seed(b"\x04" * 32, prefix="kaspa").addresses()[0]
        with pytest.raises(RuntimeError, match="not synced"):
            rpc_call(addr, "getBlockTemplate", {"payAddress": pay})

        # a fabricated extension block fails real PoW validation
        from kaspa_tpu.consensus.model import Header
        from kaspa_tpu.consensus.model.block import Block
        from kaspa_tpu.consensus.consensus import RuleError

        g = bytes.fromhex(genesis_hash)
        fake = Header(
            version=1, parents_by_level=[[g]], hash_merkle_root=b"\x00" * 32,
            accepted_id_merkle_root=b"\x00" * 32, utxo_commitment=b"\x00" * 32,
            timestamp=GENESIS_DATA["mainnet"]["timestamp"] + 1000,
            bits=GENESIS_DATA["mainnet"]["bits"], nonce=7,
            daa_score=GENESIS_DATA["mainnet"]["daa_score"] + 1,
            blue_work=1, blue_score=1, pruning_point=g,
        )
        with pytest.raises(RuleError):
            d.consensus.validate_and_insert_block(Block(fake, []))
    finally:
        d.stop()


def test_db_version_stamp_and_refusal(tmp_path):
    args = parse_args(["--appdir", str(tmp_path), "--rpclisten", "127.0.0.1:0", "--bps", "2"])
    d = Daemon(args)
    d.start()
    assert d.db.engine.get(b"MTdb_version") == str(DB_VERSION).encode()
    d.stop()

    # tamper the stamp: the daemon must refuse, not misread the format
    from kaspa_tpu.storage.kv import KvStore

    db = KvStore(str(tmp_path / "consensus.db"))
    db.engine.put(b"MTdb_version", b"99")
    db.close()
    with pytest.raises(SystemExit, match="newer"):
        Daemon(parse_args(["--appdir", str(tmp_path), "--rpclisten", "127.0.0.1:0", "--bps", "2"]))


def test_rule_engine_predicates():
    from kaspa_tpu.mining import MiningRuleEngine

    params = simnet_params(bps=2)
    clock = [1_000_000_000_000]
    peers = [False]
    engine = MiningRuleEngine(
        lambda: None, params, lambda: peers[0], require_peers=True, now_ms=lambda: clock[0]
    )
    fresh = clock[0] - 1000
    stale = clock[0] - 2 * engine.synced_threshold_ms()

    assert not engine.should_mine(fresh)  # no peers
    peers[0] = True
    assert engine.should_mine(fresh)
    assert not engine.should_mine(stale)  # connected but behind

    # sync-rate rule: enough samples of a stalled network (low receive
    # rate, recent finality) flips the override and mining resumes
    for _ in range(6):
        engine.sync_rate_rule.check_rule(0, 20.0, finality_recent=True)
    assert engine.sync_rate_rule.use_sync_rate_rule
    assert engine.should_mine(stale)
    # ...but not when the finality point is old too (this node is behind)
    engine2 = MiningRuleEngine(
        lambda: None, params, lambda: True, require_peers=True, now_ms=lambda: clock[0]
    )
    for _ in range(6):
        engine2.sync_rate_rule.check_rule(0, 20.0, finality_recent=False)
    assert not engine2.sync_rate_rule.use_sync_rate_rule
    assert not engine2.should_mine(stale)


def test_templates_refused_during_ibd_served_after(tmp_path):
    """Two nodes: the syncer refuses templates during IBD and serves them
    once caught up (rule_engine.rs should_mine over sink recency)."""
    from kaspa_tpu.wallet.account import Account

    now_ms = int(time.time() * 1000)
    # genesis 2 hours in the past: a fresh node is NOT nearly synced
    params = simnet_params(bps=2, genesis_timestamp=now_ms - 2 * 3600 * 1000)
    pay = Account.from_seed(b"\x05" * 32, prefix="kaspasim").addresses()[0]

    args_a = parse_args(
        ["--appdir", str(tmp_path / "a"), "--rpclisten", "127.0.0.1:0",
         "--listen", "127.0.0.1:0", "--enable-unsynced-mining"]
    )
    a = Daemon(args_a, params=simnet_params(bps=2, genesis_timestamp=now_ms - 2 * 3600 * 1000))
    addr_a = a.start()
    args_b = parse_args(
        ["--appdir", str(tmp_path / "b"), "--rpclisten", "127.0.0.1:0", "--no-enable-unsynced-mining"]
    )
    b = Daemon(args_b, params=simnet_params(bps=2, genesis_timestamp=now_ms - 2 * 3600 * 1000))
    addr_b = b.start()
    try:
        # bootstrap miner (explicitly opted into unsynced mining) builds a
        # chain with wall-clock timestamps
        for _ in range(6):
            t = rpc_call(addr_a, "getBlockTemplate", {"payAddress": pay})
            rpc_call(addr_a, "submitBlockByTemplateHash", {"hash": t["block_hash"]})
            a.mining.template_cache.clear()
        sink_a = rpc_call(addr_a, "getBlockDagInfo")["sink"]

        # B, unsynced: refuses templates
        with pytest.raises(RuntimeError, match="not synced"):
            rpc_call(addr_b, "getBlockTemplate", {"payAddress": pay})

        # B catches up over the wire, then serves templates
        b.connect_peer(f"127.0.0.1:{a.p2p_server.address.rsplit(':', 1)[1]}")
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if rpc_call(addr_b, "getBlockDagInfo")["sink"] == sink_a:
                break
            time.sleep(0.3)
        assert rpc_call(addr_b, "getBlockDagInfo")["sink"] == sink_a
        t = rpc_call(addr_b, "getBlockTemplate", {"payAddress": pay})
        assert t["block_hash"]
    finally:
        a.stop()
        b.stop()


def test_ram_scale_flag(tmp_path):
    """--ram-scale multiplies every store cache budget (cache_policy_builder
    + kaspad --ram-scale)."""
    from kaspa_tpu.consensus.stores import CachePolicy

    args = parse_args(["--appdir", str(tmp_path), "--rpclisten", "127.0.0.1:0", "--ram-scale", "0.5"])
    d = Daemon(args)
    try:
        base = CachePolicy()
        assert d.cache_policy.headers == max(16, int(base.headers * 0.5))
        assert d.cache_policy.utxo_set == max(16, int(base.utxo_set * 0.5))
        # the budgets actually bound the attached stores
        assert d.consensus.storage.headers._access._budget == d.cache_policy.headers
    finally:
        d.stop()
