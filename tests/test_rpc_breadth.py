"""Coverage tests for the extended RpcCoreService surface.

Reference parity: rpc/core/src/api/rpc.rs (~45 RpcApi methods) — this file
exercises the batch added in round 2 (info/network/headers/fees/peers/
color/estimates) against a small mined DAG.
"""

from __future__ import annotations

import random

import pytest

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.params import simnet_params
from kaspa_tpu.p2p import Node
from kaspa_tpu.p2p.address_manager import AddressManager, NetAddress
from kaspa_tpu.rpc import RpcCoreService
from kaspa_tpu.rpc.service import RpcError
from kaspa_tpu.sim.simulator import Miner


@pytest.fixture()
def svc():
    params = simnet_params(bps=2)
    node = Node(Consensus(params), "rpc-test")
    amgr = AddressManager()
    service = RpcCoreService(
        node.consensus, node.mining, address_prefix="kaspasim",
        p2p_node=node, address_manager=amgr,
    )
    miner = Miner(0, random.Random(5))
    for _ in range(12):
        t = node.consensus.build_block_template(miner.miner_data, [])
        node.submit_block(t)
    return service, node


def test_info_network_counts(svc):
    service, node = svc
    assert service.ping() == {}
    assert service.get_current_network() == node.consensus.params.name
    info = service.get_info()
    assert info["is_synced"] and info["mempool_size"] == 0
    counts = service.get_block_count()
    assert counts["block_count"] == 12
    assert service.get_sync_status() is True
    sysinfo = service.get_system_info()
    assert sysinfo["cpu_physical_cores"] > 0


def test_headers_walk(svc):
    service, node = svc
    genesis = node.consensus.params.genesis.hash
    up = service.get_headers(genesis, limit=5, is_ascending=True)
    assert len(up) == 5
    assert up[0]["hash"] == genesis.hex()
    down = service.get_headers(node.consensus.sink(), limit=100, is_ascending=False)
    assert down[-1]["hash"] == genesis.hex()
    assert len(down) == 13  # 12 mined + genesis


def test_block_color_and_daa_estimates(svc):
    service, node = svc
    sink = node.consensus.sink()
    # every chain block is blue by definition
    assert service.get_current_block_color(sink) == {"blue": True}
    parent = node.consensus.storage.ghostdag.get_selected_parent(sink)
    assert service.get_current_block_color(parent) == {"blue": True}
    with pytest.raises(RpcError):
        service.get_current_block_color(b"\xaa" * 32)
    daa = node.consensus.get_virtual_daa_score()
    est = service.get_daa_score_timestamp_estimate([0, daa])
    assert len(est) == 2 and est[1] >= est[0]
    nhps = service.estimate_network_hashes_per_second(window_size=8)
    assert nhps > 0
    reward = service.get_block_reward_info()
    assert reward["subsidy"] > 0


def test_fee_estimate_shape(svc):
    service, _node = svc
    est = service.get_fee_estimate()
    assert est["priority_bucket"]["feerate"] >= 1.0
    rates = [est["priority_bucket"]["feerate"]] + [b["feerate"] for b in est["normal_buckets"]] + [
        b["feerate"] for b in est["low_buckets"]
    ]
    assert rates == sorted(rates, reverse=True)
    verbose = service.get_fee_estimate_experimental(verbose=True)
    assert verbose["verbose"]["mempool_ready_transactions_count"] == 0


def test_peer_and_ban_methods(svc):
    service, node = svc
    # in-process peers appear in connected info
    assert service.get_connections()["peers"] == len(node.peers)
    amgr = service.address_manager
    amgr.add_address(NetAddress("10.0.0.1", 16111))
    addrs = service.get_peer_addresses()
    assert "10.0.0.1:16111" in addrs["known_addresses"]
    service.ban("10.0.0.1")
    assert "10.0.0.1" in service.get_peer_addresses()["banned_addresses"]
    # banned ip's addresses are dropped from the known book
    assert "10.0.0.1:16111" not in service.get_peer_addresses()["known_addresses"]
    service.unban("10.0.0.1")
    assert service.get_peer_addresses()["banned_addresses"] == []
    with pytest.raises(RpcError):
        service.get_subnetwork("deadbeef")
    with pytest.raises(RpcError):
        service.resolve_finality_conflict(b"\x00" * 32)
    with pytest.raises(RpcError):
        service.get_seq_commit_lane_proof()


def test_address_manager_failure_pruning():
    amgr = AddressManager()
    a = NetAddress("10.1.1.1", 16111)
    amgr.add_address(a)
    for _ in range(3):
        amgr.mark_connection_failure(a)
    assert a in amgr.get_all_addresses()
    amgr.mark_connection_failure(a)  # exceeds MAX_CONNECTION_FAILED_COUNT
    assert a not in amgr.get_all_addresses()


def test_address_manager_ban_expiry():
    clock = [0]
    amgr = AddressManager(now_ms=lambda: clock[0])
    amgr.ban("9.9.9.9")
    assert amgr.is_banned("9.9.9.9")
    clock[0] = 24 * 60 * 60 * 1000 + 1
    assert not amgr.is_banned("9.9.9.9")


def test_consensus_api_facade(svc):
    """The formal ConsensusApi boundary (consensus/core/src/api/mod.rs):
    consumers read consensus through it, and errors are typed."""
    import pytest as _pytest

    from kaspa_tpu.consensus.api import ConsensusError

    service, node = svc
    api = service.api
    sink = api.get_sink()
    assert api.block_exists(sink) and api.is_chain_block(sink)
    assert api.get_block(sink).hash == sink
    assert api.get_block_status(sink) == "utxo_valid"
    assert api.get_sink_blue_score() == api.get_ghostdag_data(sink).blue_score
    assert api.get_virtual_daa_score() >= 1
    assert sink in api.get_tips()
    assert api.get_block_count() >= 1
    daa, ts = api.get_sink_daa_score_timestamp()
    assert daa >= 1 and ts > 0
    assert api.pruning_point() == node.consensus.params.genesis.hash
    chain = api.get_virtual_chain_from_block(node.consensus.params.genesis.hash)
    assert chain["added"][-1] == sink
    locator = api.create_virtual_selected_chain_block_locator()
    assert locator[0] == sink and locator[-1] == api.pruning_point()
    with _pytest.raises(ConsensusError):
        api.get_header(b"\x99" * 32)
    with _pytest.raises(ConsensusError):
        api.get_block_acceptance_data(b"\x99" * 32)


def test_utxo_return_address_resolves():
    """getUtxoReturnAddress resolves the first input's funding address from
    retained bodies (rpc.rs get_utxo_return_address; the reference uses its
    tx-index)."""
    from kaspa_tpu.consensus import hashing as chash
    from kaspa_tpu.consensus.model import Transaction, TransactionInput, TransactionOutput
    from kaspa_tpu.consensus.model.tx import ComputeCommit, SUBNETWORK_ID_NATIVE
    from kaspa_tpu.crypto import eclib
    from kaspa_tpu.txscript import standard

    params = simnet_params(bps=2)
    params.coinbase_maturity = 2
    node = Node(Consensus(params), "ra-test")
    service = RpcCoreService(node.consensus, node.mining, address_prefix="kaspasim")
    miner = Miner(0, random.Random(5))
    rng = random.Random(9)
    c = node.consensus
    for i in range(6):
        node.submit_block(c.build_block_template(miner.miner_data, [], timestamp=10_000 + 600 * i))
    # spend a mature coinbase back to the miner
    view = c.get_virtual_utxo_view()
    pov = c.get_virtual_daa_score()
    spend = None
    for op, entry in sorted(c.utxo_set.items(), key=lambda kv: (kv[0].transaction_id, kv[0].index)):
        if view.get(op) is None or entry.script_public_key != miner.spk:
            continue
        if entry.is_coinbase and entry.block_daa_score + params.coinbase_maturity > pov:
            continue
        tx = Transaction(
            0,
            [TransactionInput(op, b"", 0, ComputeCommit.sigops(1))],
            [TransactionOutput(entry.amount - 1000, miner.spk)],
            0, SUBNETWORK_ID_NATIVE, 0, b"",
        )
        reused = chash.SigHashReusedValues()
        msg = chash.calc_schnorr_signature_hash(tx, [entry], 0, chash.SIG_HASH_ALL, reused)
        tx.inputs[0].signature_script = standard.schnorr_signature_script(
            eclib.schnorr_sign(msg, miner.seckey, rng.randbytes(32)), chash.SIG_HASH_ALL
        )
        spend = tx
        break
    assert spend is not None
    blk = c.build_block_with_parents([c.sink()], miner.miner_data, [spend], timestamp=20_000)
    assert c.validate_and_insert_block(blk) == "utxo_valid"
    # the NEXT chain block accepts the tx
    nxt = c.build_block_with_parents([blk.hash], miner.miner_data, [], timestamp=21_000)
    assert c.validate_and_insert_block(nxt) == "utxo_valid"
    accepting_daa = c.get_virtual_daa_score()

    addr = service.get_utxo_return_address(spend.id(), accepting_daa)
    from kaspa_tpu.crypto.addresses import extract_script_pub_key_address

    assert addr == extract_script_pub_key_address(miner.spk, "kaspasim").to_string()
