"""PoW hashing golden tests.

Vectors extracted from consensus/pow/src/matrix.rs tests (heavy-hash
matrix-vector product, xoshiro-seeded full-rank matrix generation); the
keccak permutation is cross-checked against hashlib's SHAKE-256.
"""

import hashlib
import json
import os
import struct

from kaspa_tpu.crypto import powhash

VECTORS = json.load(open(os.path.join(os.path.dirname(__file__), "data_pow_vectors.json")))


def test_keccak_matches_hashlib_shake256():
    def shake256(data, outlen):
        state = [0] * 25
        rate = 136
        buf = bytearray(data)
        buf.append(0x1F)
        while len(buf) % rate:
            buf.append(0)
        buf[-1] ^= 0x80
        for off in range(0, len(buf), rate):
            for i in range(17):
                state[i] ^= struct.unpack("<Q", bytes(buf[off + 8 * i : off + 8 * i + 8]))[0]
            state = powhash.keccak_f1600(state)
        return struct.pack("<17Q", *state[:17])[:outlen]

    for msg in (b"", b"kaspa", bytes(range(200))):
        assert shake256(msg, 32) == hashlib.shake_256(msg).digest(32)


def test_heavy_hash_golden():
    mat = powhash.Matrix(VECTORS["heavy_matrix"])
    got = mat.heavy_hash(bytes(VECTORS["heavy_input"]))
    assert list(got) == VECTORS["heavy_expected"]


def test_matrix_generation_golden():
    gen = powhash.Matrix.generate(bytes(VECTORS["gen_input"]))
    assert gen.rows == VECTORS["gen_matrix"]


def test_pow_hash_structure():
    # single-permutation path: known-length input, deterministic
    h1 = powhash.pow_hash(b"\x01" * 32, 123456, 42)
    h2 = powhash.pow_hash(b"\x01" * 32, 123456, 42)
    h3 = powhash.pow_hash(b"\x01" * 32, 123456, 43)
    assert h1 == h2 and h1 != h3 and len(h1) == 32


def test_check_pow_mining_loop():
    """With target 2^255 each nonce passes w.p. 1/2: mining a nonce in a
    few tries validates the full check_pow path end to end."""
    from kaspa_tpu.consensus.model import Header

    hd = Header(
        version=1,
        parents_by_level=[[b"\x02" * 32]],
        hash_merkle_root=b"\x00" * 32,
        accepted_id_merkle_root=b"\x00" * 32,
        utxo_commitment=b"\x00" * 32,
        timestamp=1234,
        bits=0x207FFFFF,
        nonce=0,
        daa_score=1,
        blue_work=1,
        blue_score=1,
        pruning_point=b"\x00" * 32,
    )
    results = []
    for nonce in range(64):
        hd.nonce = nonce
        results.append(powhash.check_pow(hd))
    assert any(results), "no nonce passed a 2^255 target in 64 tries (p < 2^-64)"
    assert not all(results), "every nonce passed: target check is vacuous"
