"""Real-network genesis reproduction: the ultimate hashing parity check.

Our header hashing and tx/merkle stack must reproduce each network's real
genesis hash and merkle root from the raw constants (mirrored from
config/genesis.rs) — including the actual Kaspa mainnet genesis
58c2d419...8f2999 (launched 2021-11-22).
"""

from kaspa_tpu.consensus.networks import (
    GENESIS_DATA,
    _genesis_block,
    mainnet_params,
    simnet_network_params,
)
from kaspa_tpu.crypto import merkle


def test_genesis_hashes_reproduced_for_all_networks():
    for net, g in GENESIS_DATA.items():
        block = _genesis_block(net)
        assert block.header.hash.hex() == g["hash"], net
        assert merkle.calc_hash_merkle_root(block.transactions).hex() == g["hash_merkle_root"], net


def test_mainnet_params_construct():
    p = mainnet_params()
    assert p.bps == 10
    assert p.ghostdag_k == 124
    assert p.mergeset_size_limit == 248
    assert p.max_block_parents == 16
    assert p.genesis.hash.hex().startswith("58c2d419")


def test_simnet_consensus_boots_on_real_genesis():
    from kaspa_tpu.consensus.consensus import Consensus

    p = simnet_network_params()
    c = Consensus(p)
    assert c.sink() == p.genesis.hash
    assert c.get_virtual_daa_score() == GENESIS_DATA["simnet"]["daa_score"]
