"""KIP-21 SMT + sequencing-commitment tests.

Golden vectors come from the reference's own unit tests
(consensus/seq-commit/src/hashing.rs tests, crypto/smt/src/lib.rs tests);
tree/proof behavior mirrors crypto/smt/src/{tree,proof}.rs.
"""

import hashlib
import random

import pytest

from kaspa_tpu.consensus import seq_commit as sc
from kaspa_tpu.crypto.smt import (
    DEPTH,
    SEQ_COMMIT_ACTIVE,
    ZERO_HASH,
    SmtError,
    SmtProof,
    SparseMerkleTree,
    bit_at,
)


def h(b: int) -> bytes:
    return bytes([b]) + b"\x00" * 31


# ----------------------------------------------------------------------
# bit extraction + empty-hash table (lib.rs tests)
# ----------------------------------------------------------------------


def test_key_bit_extraction():
    assert not any(bit_at(b"\x00" * 32, d) for d in range(256))
    assert all(bit_at(b"\xff" * 32, d) for d in range(256))
    key = b"\x80" + b"\x00" * 31
    assert bit_at(key, 0) and not bit_at(key, 1) and not bit_at(key, 7)
    key = b"\x00" * 31 + b"\x01"
    assert not bit_at(key, 254) and bit_at(key, 255)
    key = b"\xa5" + b"\x00" * 31  # 10100101
    assert [bit_at(key, d) for d in range(8)] == [True, False, True, False, False, True, False, True]


def test_empty_hashes_table():
    t = SEQ_COMMIT_ACTIVE.empty_hashes
    assert t[0] == ZERO_HASH
    assert t[1] == SEQ_COMMIT_ACTIVE.hash_node(ZERO_HASH, ZERO_HASH)
    assert t[2] == SEQ_COMMIT_ACTIVE.hash_node(t[1], t[1])
    assert t[DEPTH] == SEQ_COMMIT_ACTIVE.empty_root() != ZERO_HASH
    assert len(set(t)) == DEPTH + 1  # all levels distinct


# ----------------------------------------------------------------------
# tree semantics (tree.rs)
# ----------------------------------------------------------------------


def test_empty_tree_root():
    assert SparseMerkleTree().root() == SEQ_COMMIT_ACTIVE.empty_root()


def test_single_leaf_collapses_to_root():
    t = SparseMerkleTree()
    key, leaf = hashlib.sha256(b"k").digest(), hashlib.sha256(b"v").digest()
    t.insert(key, leaf)
    assert t.root() == SEQ_COMMIT_ACTIVE.hash_collapsed(key, leaf)


def test_two_leaves_split_at_first_differing_bit():
    t = SparseMerkleTree()
    k_left = b"\x00" * 32  # bit 0 clear
    k_right = b"\x80" + b"\x00" * 31  # bit 0 set
    l1, l2 = h(1), h(2)
    t.insert(k_left, l1)
    t.insert(k_right, l2)
    H = SEQ_COMMIT_ACTIVE
    assert t.root() == H.hash_node(H.hash_collapsed(k_left, l1), H.hash_collapsed(k_right, l2))


def test_insert_update_delete_roundtrip():
    t = SparseMerkleTree()
    rng = random.Random(3)
    keys = [rng.randbytes(32) for _ in range(40)]
    for i, k in enumerate(keys):
        t.insert(k, h(i % 250 + 1))
    root_full = t.root()
    # update changes the root, reverting restores it
    t.insert(keys[7], h(200))
    assert t.root() != root_full
    t.insert(keys[7], h(8))
    assert t.root() == root_full
    # deletion down to one leaf collapses
    for k in keys[1:]:
        t.delete(k)
    assert t.root() == SEQ_COMMIT_ACTIVE.hash_collapsed(keys[0], h(1))
    t.delete(keys[0])
    assert t.root() == SEQ_COMMIT_ACTIVE.empty_root()


def test_root_is_insertion_order_independent():
    rng = random.Random(9)
    entries = [(rng.randbytes(32), rng.randbytes(32)) for _ in range(25)]
    t1, t2 = SparseMerkleTree(), SparseMerkleTree()
    for k, v in entries:
        t1.insert(k, v)
    for k, v in reversed(entries):
        t2.insert(k, v)
    assert t1.root() == t2.root()


# ----------------------------------------------------------------------
# proofs (proof.rs)
# ----------------------------------------------------------------------


def test_membership_proofs_verify_and_reject():
    t = SparseMerkleTree()
    rng = random.Random(5)
    entries = {rng.randbytes(32): rng.randbytes(32) for _ in range(30)}
    for k, v in entries.items():
        t.insert(k, v)
    root = t.root()
    for k, v in list(entries.items())[:10]:
        proof = t.prove(k)
        assert proof.verify(SEQ_COMMIT_ACTIVE, k, v, root)
        assert not proof.verify(SEQ_COMMIT_ACTIVE, k, h(99), root)  # wrong leaf
        assert not proof.verify(SEQ_COMMIT_ACTIVE, k, v, h(1))  # wrong root
    # proofs are compressed: far fewer than 256 siblings
    assert all(len(t.prove(k).siblings) < 16 for k in entries)


def test_non_membership_proofs():
    t = SparseMerkleTree()
    rng = random.Random(6)
    for _ in range(20):
        t.insert(rng.randbytes(32), rng.randbytes(32))
    root = t.root()
    absent = rng.randbytes(32)
    proof = t.prove(absent)
    assert proof.terminal[0] in ("empty", "collapsed_other")
    assert proof.verify(SEQ_COMMIT_ACTIVE, absent, None, root)
    # a non-membership proof cannot claim membership
    assert not proof.verify(SEQ_COMMIT_ACTIVE, absent, h(1), root)
    # empty tree: trivial non-membership
    empty = SparseMerkleTree()
    p0 = empty.prove(absent)
    assert p0.terminal == ("empty", 0)
    assert p0.verify(SEQ_COMMIT_ACTIVE, absent, None, empty.root())


def test_forged_foreign_terminal_rejected():
    t = SparseMerkleTree()
    key_in = b"\x00" * 32
    t.insert(key_in, h(1))
    t.insert(b"\xff" * 32, h(2))
    root = t.root()
    # try to prove non-membership of a key that IS present by presenting a
    # foreign collapsed terminal with a key outside the subtree
    proof = t.prove(b"\x01" + b"\x00" * 31)  # shares bit-0 subtree with key_in
    assert proof.terminal[0] == "collapsed_other"
    bad = SmtProof(proof.bitmap, proof.siblings, ("collapsed_other", proof.terminal[1], b"\xff" * 32, h(2)))
    assert not bad.verify(SEQ_COMMIT_ACTIVE, b"\x01" + b"\x00" * 31, None, root)


# ----------------------------------------------------------------------
# seq-commit hashing goldens (hashing.rs tests)
# ----------------------------------------------------------------------


def test_lane_key_golden():
    expected = bytes(
        [0x57, 0xC7, 0xE5, 0x2C, 0x76, 0x02, 0xB3, 0x66, 0xB3, 0xF6, 0x62, 0xAD, 0xDC, 0x36, 0x12, 0x96,
         0x77, 0xD4, 0x84, 0x4B, 0x84, 0x04, 0x68, 0xCC, 0xAA, 0x96, 0x31, 0x10, 0x6B, 0xEA, 0x88, 0x97]
    )
    assert sc.lane_key(b"\x42" * 20) == expected
    assert sc.lane_key(b"\x01" * 20) != sc.lane_key(b"\x02" * 20)


def test_coinbase_lane_key_constant_golden():
    expected = bytes(
        [0x8A, 0xA7, 0x80, 0x27, 0xDB, 0x66, 0xA1, 0x6C, 0xB6, 0x96, 0x92, 0xEE, 0x0A, 0xF5, 0xCB, 0x76,
         0x73, 0x8E, 0xF8, 0x0A, 0xD1, 0x4C, 0x9D, 0x13, 0x92, 0x0D, 0x7F, 0xA3, 0xCC, 0x40, 0xB9, 0xE4]
    )
    assert sc.COINBASE_LANE_KEY == expected


def test_activity_leaf_golden():
    expected = bytes(
        [0x4E, 0xF4, 0x3F, 0x31, 0x6E, 0xCF, 0x61, 0x6C, 0x69, 0x34, 0xB5, 0x66, 0xAE, 0x41, 0x05, 0x5E,
         0x97, 0x12, 0xF1, 0x08, 0x9B, 0x91, 0x4F, 0x33, 0x18, 0x6C, 0xDC, 0x9D, 0x55, 0x19, 0x11, 0x21]
    )
    assert sc.activity_leaf(h(1), 0, 0) == expected
    assert sc.activity_leaf(h(1), 0, 0) != sc.activity_leaf(h(1), 0, 1)


def test_activity_digest_single_leaf_is_identity():
    assert sc.activity_digest_lane([h(5)]) == h(5)
    assert sc.activity_digest_lane([]) == ZERO_HASH
    two = sc.activity_digest_lane([h(1), h(2)])
    assert two not in (h(1), h(2))


def test_blue_work_encoding_strips_leading_zeros():
    # blue_work 0 -> empty stripped bytes, len 0
    a = sc.miner_payload_leaf(h(1), 0, b"p")
    b = sc.miner_payload_leaf(h(1), 1, b"p")
    c = sc.miner_payload_leaf(h(1), 0x0100, b"p")
    assert len({a, b, c}) == 3


def test_seq_commit_chain_and_metadata_verify():
    lanes_root = h(1)
    pd = h(3)
    parent = h(4)
    shortcut = bytes([7]) * 32
    ar = sc.activity_root_hash(shortcut, lanes_root)
    sr = sc.seq_state_root(ar, pd)
    commit = sc.seq_commit(parent, sr)
    md = sc.SmtMetadata(lanes_root, pd, parent)
    sc.verify_smt_metadata(md, shortcut, commit, parent)  # ok
    with pytest.raises(sc.SmtVerifyError, match="parent_seq_commit"):
        sc.verify_smt_metadata(md, shortcut, ZERO_HASH, bytes([99]) * 32)
    with pytest.raises(sc.SmtVerifyError, match="seq_commit mismatch"):
        sc.verify_smt_metadata(md, shortcut, bytes([99]) * 32, parent)
    with pytest.raises(sc.SmtVerifyError, match="seq_commit mismatch"):
        sc.verify_smt_metadata(md, bytes([0xAB]) * 32, commit, parent)  # bad shortcut


def test_lane_state_advance_rollback_and_proofs():
    st = sc.LaneState()
    empty_root = st.lanes_root()
    lk1, lk2 = sc.lane_key(b"\x01" * 20), sc.lane_key(b"\x02" * 20)

    r1 = st.advance(h(10), {lk1: (h(100), 5)})
    r2 = st.advance(h(11), {lk2: (h(101), 6), lk1: (h(102), 6)})
    assert len({empty_root, r1, r2}) == 3

    # proofs against the live root
    p = st.prove_lane(lk1)
    assert p.verify(SEQ_COMMIT_ACTIVE, lk1, sc.smt_leaf_hash(h(102), 6), r2)
    absent = sc.lane_key(b"\x03" * 20)
    assert st.prove_lane(absent).verify(SEQ_COMMIT_ACTIVE, absent, None, r2)

    # reorg: roll back to the first chain block, then to genesis
    assert st.rollback(h(10)) == r1
    assert st.lane_tips[lk1] == (h(100), 5) and lk2 not in st.lane_tips
    assert st.rollback(None) == empty_root


def test_chainblock_seq_commit_opcode():
    from kaspa_tpu.txscript.vm import EngineFlags, TxScriptEngine, TxScriptError

    chain = [h(10), h(11), h(12)]
    commits = {b: sc.seq_commit(b, h(42)) for b in chain}
    acc = sc.SeqCommitAccessor(commits, chain, max_depth=1)
    e = TxScriptEngine(flags=EngineFlags(covenants_enabled=True), seq_commit_accessor=acc)
    e.dstack = [chain[2]]
    e._op_chainblock_seq_commit()
    assert e.dstack == [commits[chain[2]]]
    # too deep
    e.dstack = [chain[0]]
    with pytest.raises(TxScriptError, match="too deep"):
        e._op_chainblock_seq_commit()
    # not on the selected chain
    e.dstack = [h(99)]
    with pytest.raises(TxScriptError, match="pruned"):
        e._op_chainblock_seq_commit()
    commits_off = dict(commits); commits_off[h(77)] = h(1)
    acc2 = sc.SeqCommitAccessor(commits_off, chain, max_depth=5)
    e2 = TxScriptEngine(flags=EngineFlags(covenants_enabled=True), seq_commit_accessor=acc2)
    e2.dstack = [h(77)]
    with pytest.raises(TxScriptError, match="not on the selected chain"):
        e2._op_chainblock_seq_commit()
    # no accessor -> invalid opcode
    e3 = TxScriptEngine(flags=EngineFlags(covenants_enabled=True))
    e3.dstack = [chain[2]]
    with pytest.raises(TxScriptError, match="invalid opcode"):
        e3._op_chainblock_seq_commit()


def test_last_bit_sibling_keys_prove_at_leaf_depth():
    """Keys differing only in bit 255: depth-256 nodes are raw leaf hashes
    (proof.rs Leaf terminal), and membership proofs verify for both."""
    t = SparseMerkleTree()
    k0, k1 = b"\x00" * 32, b"\x00" * 31 + b"\x01"
    t.insert(k0, h(1))
    t.insert(k1, h(2))
    root = t.root()
    for k, leaf in ((k0, h(1)), (k1, h(2))):
        p = t.prove(k)
        assert p.terminal == ("leaf",)
        assert p.verify(SEQ_COMMIT_ACTIVE, k, leaf, root)
    assert not t.prove(k0).verify(SEQ_COMMIT_ACTIVE, k0, h(2), root)


def test_malformed_proofs_reject_instead_of_raising():
    t = SparseMerkleTree()
    t.insert(h(1), h(2))
    root = t.root()
    assert not SmtProof(b"", [], ("empty", 8)).verify(SEQ_COMMIT_ACTIVE, h(1), None, root)
    assert not SmtProof(b"\x00" * 32, [], ("collapsed",)).verify(SEQ_COMMIT_ACTIVE, h(1), h(2), root)
    assert not SmtProof(b"\x00" * 32, [], ("bogus", 1)).verify(SEQ_COMMIT_ACTIVE, h(1), h(2), root)
    assert not SmtProof(b"\x00" * 32, [], ("empty", 999)).verify(SEQ_COMMIT_ACTIVE, h(1), None, root)


def test_proof_encoding_is_canonical():
    """Flipping a bitmap bit beyond the terminal depth must invalidate the
    proof (no proof malleability)."""
    t = SparseMerkleTree()
    rng = random.Random(11)
    for _ in range(8):
        t.insert(rng.randbytes(32), rng.randbytes(32))
    k = next(iter(t._leaves))
    root = t.root()
    p = t.prove(k)
    assert p.verify(SEQ_COMMIT_ACTIVE, k, t.get(k), root)
    bm = bytearray(p.bitmap)
    bm[31] |= 0x01  # bit 255, far beyond any terminal depth here
    assert not SmtProof(bytes(bm), p.siblings, p.terminal).verify(SEQ_COMMIT_ACTIVE, k, t.get(k), root)


def test_lane_state_rollback_unknown_target_raises():
    st = sc.LaneState()
    st.advance(h(10), {sc.lane_key(b"\x01" * 20): (h(100), 5)})
    with pytest.raises(sc.SmtVerifyError, match="not in lane version history"):
        st.rollback(h(99))
    # state untouched by the failed rollback
    assert len(st.lane_tips) == 1


def test_proof_not_malleable_via_explicit_empty_sibling():
    """Setting a cleared bitmap bit and supplying the level's empty hash as
    an explicit sibling must NOT produce a second verifying encoding."""
    t = SparseMerkleTree()
    k = b"\x00" * 32
    t.insert(k, h(1))
    t.insert(b"\x00" * 31 + b"\x01", h(2))  # leaf-depth proof: bits 0..254 cleared
    root = t.root()
    p = t.prove(k)
    assert p.verify(SEQ_COMMIT_ACTIVE, k, t.get(k), root)
    depth = p.terminal_depth()
    cleared = [d for d in range(depth) if not (p.bitmap[d >> 3] & (0x80 >> (d & 7)))]
    assert cleared
    d = cleared[0]
    bm = bytearray(p.bitmap)
    bm[d >> 3] |= 0x80 >> (d & 7)
    insert_at = sum(1 for x in range(d) if p.bitmap[x >> 3] & (0x80 >> (x & 7)))
    sibs = list(p.siblings)
    sibs.insert(insert_at, SEQ_COMMIT_ACTIVE.empty_hashes[DEPTH - d - 1])
    forged = SmtProof(bytes(bm), sibs, p.terminal)
    assert not forged.verify(SEQ_COMMIT_ACTIVE, k, t.get(k), root)


def test_empty_terminal_depth_is_pinned():
    """('empty', d) under an empty parent sibling re-encodes as
    ('empty', d-1); only the shallowest encoding verifies."""
    t = SparseMerkleTree()
    t.insert(b"\x00" * 32, h(1))  # left half occupied, right half empty
    t.insert(b"\x40" + b"\x00" * 31, h(2))
    root = t.root()
    absent = b"\x80" + b"\xee" * 31  # right half: empty at depth 1
    p = t.prove(absent)
    assert p.terminal[0] == "empty"
    assert p.verify(SEQ_COMMIT_ACTIVE, absent, None, root)
    deeper = SmtProof(p.bitmap, p.siblings, ("empty", p.terminal[1] + 1))
    assert not deeper.verify(SEQ_COMMIT_ACTIVE, absent, None, root)
