"""Swarm drills: N live nodes over the real loopback wire (resilience/swarm.py).

One shared 3-node drill (module fixture) backs the partition/heal,
deep-reorg, late-join and relay-amplification assertions — the fleet is
the expensive part, the gates are all facts of a single run.  The
determinism test runs its own tiny 2-node drill twice and compares the
``deterministic`` report sections byte-for-byte.

Scenarios here deliberately omit the ``txs`` step: the schnorr-verify
kernel's first dispatch is a one-time JIT compile that would dominate
the fast lane; tx gossip is covered by the full default scenario under
``roundcheck --only swarm`` and the committed SWARM.json.
"""

from __future__ import annotations

import json

import pytest

from kaspa_tpu.resilience.swarm import (
    SwarmError,
    default_scenario,
    gates,
    parse_scenario,
    run_swarm,
)

_H = 4  # honest-side blocks while partitioned; attacker mines 2h+2

# attacker node0 splits off, mines the heavier chain, wins at heal; the
# post-heal relay round merges the losing tips into the winner's past so
# node2's late IBD (antipast flow serves the donor sink's PAST only)
# sees the whole DAG
_SCENARIO = [
    {"op": "mine", "nodes": [0, 1], "blocks": 8},
    {"op": "partition", "groups": [[0], [1]]},
    {"op": "mine", "nodes": [1], "blocks": _H},
    {"op": "mine", "nodes": [0], "blocks": 2 * _H + 2},
    {"op": "heal"},
    {"op": "converge"},
    {"op": "mine", "nodes": [0, 1], "blocks": 4},
    {"op": "converge"},
    {"op": "join", "node": 2},
    {"op": "converge"},
]
_TOTAL = 8 + _H + (2 * _H + 2) + 4


@pytest.fixture(scope="module")
def drill() -> dict:
    return run_swarm(3, seed=11, scenario=_SCENARIO)


def test_partition_heal_convergence(drill):
    assert all(gates(drill).values()), gates(drill)
    det = drill["deterministic"]
    assert det["blocks"] == _TOTAL
    # the partition severed exactly the cross-group ordered pairs
    part = next(e for e in det["events"] if e["op"] == "partition")
    assert part["severed"] == 2
    # every node ends bit-identical to the fault-free in-order replay
    fps = det["fingerprints"]
    assert len(fps) == 3
    assert all(fp == det["fault_free_fingerprints"] for fp in fps.values())


def test_deep_reorg_winner_propagates(drill):
    """The isolated attacker's heavier chain must win fleet-wide at heal:
    the first post-heal converged sink is the attacker's own tip."""
    events = drill["deterministic"]["events"]
    attacker_mine = next(
        e for e in events if e["op"] == "mine" and e["nodes"] == [0] and len(e["blocks"]) == 2 * _H + 2
    )
    heal_at = next(i for i, e in enumerate(events) if e["op"] == "heal")
    first_converge = next(e for e in events[heal_at:] if e["op"] == "converge")
    assert first_converge["sink"] == attacker_mine["blocks"][-1]


def test_late_join_ibd_at_depth(drill):
    """node2 joins after the whole drill's DAG exists and IBDs all of it."""
    events = drill["deterministic"]["events"]
    join = next(e for e in events if e["op"] == "join")
    assert join["node"] == 2 and join["depth"] == _TOTAL
    # the joiner was absent from the startup mesh...
    start = next(e for e in events if e["op"] == "start")
    assert start["joined"] == [0, 1]
    # ...and still ends with the same fingerprints as the donors
    fps = drill["deterministic"]["fingerprints"]
    assert fps["node2"] == fps["node0"] == fps["node1"]


def test_relay_amplification_within_budget(drill):
    """One INV burst must not amplify into O(peers) block transfers: the
    `_block_requested` in-flight ledger keeps fleet-wide MSG_BLOCK receipts
    under amp_budget x N x blocks."""
    relay = drill["fleet"]["relay"]
    assert relay["total_block_rx"] > 0  # the wire really carried blocks
    assert relay["amp_ok"], relay
    assert relay["amplification"] <= drill["config"]["amp_budget"]
    # the late joiner catches up over MSG_IBD_BLOCKS batches, which do not
    # count against the gossip budget — its MSG_BLOCK receipts stay zero
    assert relay["block_rx_by_node"]["node2"] == 0
    assert drill["fleet"]["lost_tickets"] == 0


def test_seeded_determinism_two_runs():
    """Same (n, seed, scenario) -> byte-identical `deterministic` section:
    event log, block hashes, fingerprints, fault-free comparison."""
    scenario = [
        {"op": "mine", "nodes": [0, 1], "blocks": 4},
        {"op": "partition", "groups": [[0], [1]]},
        {"op": "mine", "nodes": [0], "blocks": 2},
        {"op": "mine", "nodes": [1], "blocks": 3},
        {"op": "heal"},
        {"op": "converge"},
    ]
    a = run_swarm(2, seed=5, scenario=scenario)
    b = run_swarm(2, seed=5, scenario=scenario)
    assert json.dumps(a["deterministic"], sort_keys=True) == json.dumps(b["deterministic"], sort_keys=True)
    assert all(gates(a).values()) and all(gates(b).values())
    # a different seed shifts the miner identities -> different hashes
    c = run_swarm(2, seed=6, scenario=scenario)
    assert json.dumps(c["deterministic"], sort_keys=True) != json.dumps(a["deterministic"], sort_keys=True)


def test_scenario_parsing_and_validation():
    steps = parse_scenario('{"steps": [{"op": "mine", "nodes": [0], "blocks": 1}]}')
    assert steps == [{"op": "mine", "nodes": [0], "blocks": 1}]
    assert parse_scenario([{"op": "heal"}]) == [{"op": "heal"}]
    with pytest.raises(SwarmError):
        parse_scenario('[{"nodes": [0]}]')  # step without an op
    with pytest.raises(SwarmError):
        default_scenario(1)  # a fleet needs two nodes
    # the stock drill keeps the relay phase between heal and join
    ops = [s["op"] for s in default_scenario(5, blocks=24)]
    assert ops.index("heal") < ops.index("mine", ops.index("heal")) < ops.index("join")
