"""Two OS processes forming a network over the binary P2P wire.

The round-1 gap this closes: P2P existed only as in-process objects.  Here
two real daemon processes handshake over TCP (version/verack), the second
catches up via IBD, and subsequent blocks propagate by inv/request relay —
the integration shape of the reference's testing/integration daemon tests
over protocol/p2p's gRPC wire.
"""

import os
import subprocess
import sys
import time

import pytest

from kaspa_tpu.node.daemon import rpc_call

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_daemon(tmp_path, name, rpc_port, p2p_port, connect=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["KASPA_TPU_PLATFORM"] = "cpu"
    argv = [
        sys.executable, "-m", "kaspa_tpu.node",
        "--appdir", str(tmp_path / name),
        "--rpclisten", f"127.0.0.1:{rpc_port}",
        "--listen", f"127.0.0.1:{p2p_port}",
        "--bps", "2",
    ]
    if connect:
        argv += ["--connect", connect]
    proc = subprocess.Popen(argv, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    return proc


def _wait_rpc(addr, timeout=90.0):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            return rpc_call(addr, "getServerInfo")
        except Exception as e:  # noqa: BLE001
            last = e
            time.sleep(0.3)
    raise TimeoutError(f"rpc at {addr} not up: {last}")


def _free_ports(n):
    import socket

    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports



def test_two_process_network_converges(tmp_path):
    from kaspa_tpu.wallet.account import Account

    rpc_a, p2p_a, rpc_b, p2p_b = _free_ports(4)
    addr_a, addr_b = f"127.0.0.1:{rpc_a}", f"127.0.0.1:{rpc_b}"
    pay = Account.from_seed(b"\x02" * 32, prefix="kaspasim").addresses()[0]

    proc_a = proc_b = None
    try:
        proc_a = _spawn_daemon(tmp_path, "a", rpc_a, p2p_a)
        _wait_rpc(addr_a)
        # seed node A with a chain over its own RPC wire
        for _ in range(8):
            t = rpc_call(addr_a, "getBlockTemplate", {"payAddress": pay})
            rpc_call(addr_a, "submitBlockByTemplateHash", {"hash": t["block_hash"]})
        dag_a = rpc_call(addr_a, "getBlockDagInfo")
        assert dag_a["virtual_daa_score"] == 8

        # node B dials A and IBDs the chain
        proc_b = _spawn_daemon(tmp_path, "b", rpc_b, p2p_b, connect=f"127.0.0.1:{p2p_a}")
        _wait_rpc(addr_b)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            dag_b = rpc_call(addr_b, "getBlockDagInfo")
            if dag_b["sink"] == dag_a["sink"]:
                break
            time.sleep(0.5)
        assert dag_b["sink"] == dag_a["sink"], f"IBD did not converge: {dag_b} vs {dag_a}"

        # mine on B; the block must relay to A over the wire
        t = rpc_call(addr_b, "getBlockTemplate", {"payAddress": pay})
        rpc_call(addr_b, "submitBlockByTemplateHash", {"hash": t["block_hash"]})
        sink_b = rpc_call(addr_b, "getBlockDagInfo")["sink"]
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if rpc_call(addr_a, "getBlockDagInfo")["sink"] == sink_b:
                break
            time.sleep(0.3)
        assert rpc_call(addr_a, "getBlockDagInfo")["sink"] == sink_b, "relay A<-B failed"

        # and the reverse direction
        t = rpc_call(addr_a, "getBlockTemplate", {"payAddress": pay})
        rpc_call(addr_a, "submitBlockByTemplateHash", {"hash": t["block_hash"]})
        sink_a = rpc_call(addr_a, "getBlockDagInfo")["sink"]
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if rpc_call(addr_b, "getBlockDagInfo")["sink"] == sink_a:
                break
            time.sleep(0.3)
        assert rpc_call(addr_b, "getBlockDagInfo")["sink"] == sink_a, "relay B<-A failed"
    finally:
        for proc in (proc_a, proc_b):
            if proc is not None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()


def test_wire_codec_roundtrip():
    import random

    from kaspa_tpu.p2p import wire
    from kaspa_tpu.p2p.node import (
        MSG_BLOCK,
        MSG_INV_BLOCK,
        MSG_INV_TXS,
        MSG_VERSION,
    )
    from tests.test_serde import _rand_header, _rand_tx

    rng = random.Random(3)

    def roundtrip(msg_type, payload):
        frame = wire.encode_frame(msg_type, payload)
        pos = [0]

        def read_exactly(n):
            out = frame[pos[0] : pos[0] + n]
            assert len(out) == n
            pos[0] += n
            return out

        name, decoded = wire.read_message(read_exactly)
        assert name == msg_type
        assert pos[0] == len(frame)
        return decoded

    v = {"protocol_version": 7, "network": "kaspa-simnet", "listen_port": 16111, "id": 99}
    assert roundtrip(MSG_VERSION, v) == v
    h = rng.randbytes(32)
    assert roundtrip(MSG_INV_BLOCK, h) == h
    ids = [rng.randbytes(32) for _ in range(5)]
    assert roundtrip(MSG_INV_TXS, ids) == ids
    from kaspa_tpu.consensus.model.block import Block

    blk = Block(_rand_header(rng), [_rand_tx(rng) for _ in range(3)])
    out = roundtrip(MSG_BLOCK, blk)
    assert out.header == blk.header and out.transactions == blk.transactions

    # adversarial: bad magic / unknown type / oversized must raise WireError
    import pytest as _pytest

    with _pytest.raises(wire.WireError):
        wire.decode_frame(b"XX\x00\x00\x00\x00\x00")
    with _pytest.raises(wire.WireError):
        wire.decode_frame(wire.MAGIC + b"\xff\x00\x00\x00\x00")
    with _pytest.raises(wire.WireError):
        wire.decode_frame(wire.MAGIC + b"\x02\xff\xff\xff\xff")
