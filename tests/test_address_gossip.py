"""Three-node address bootstrap + locator sync (VERDICT r3 #7).

Reference: protocol/flows/src/v7/address.rs (RequestAddresses /
SendAddresses) + connectionmanager: node C explicitly connects only to B,
learns A's listen address through B's gossip, dials A via its connection
manager, and — after B goes away — still receives A's new branch, which it
can only do because of the gossip bootstrap.  Block transfer along the way
runs the exponential block-locator negotiation (sync/mod.rs), not a full
inventory exchange.
"""

import os
import subprocess
import sys
import time

from kaspa_tpu.node.daemon import rpc_call

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(tmp_path, name, rpc_port, p2p_port, connect=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["KASPA_TPU_PLATFORM"] = "cpu"
    argv = [
        sys.executable, "-m", "kaspa_tpu.node",
        "--appdir", str(tmp_path / name),
        "--rpclisten", f"127.0.0.1:{rpc_port}",
        "--listen", f"127.0.0.1:{p2p_port}",
        "--bps", "2",
    ]
    if connect:
        argv += ["--connect", connect]
    return subprocess.Popen(argv, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)


def _wait_rpc(addr, timeout=90.0):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            return rpc_call(addr, "getServerInfo")
        except Exception as e:  # noqa: BLE001
            last = e
            time.sleep(0.3)
    raise TimeoutError(f"rpc at {addr} not up: {last}")


def _wait(cond, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.4)
    raise AssertionError(f"timed out waiting for {what}")


def _mine(addr, pay, n):
    for _ in range(n):
        t = rpc_call(addr, "getBlockTemplate", {"payAddress": pay})
        rpc_call(addr, "submitBlockByTemplateHash", {"hash": t["block_hash"]})


def test_three_node_gossip_bootstrap(tmp_path):
    from kaspa_tpu.wallet.account import Account

    import socket

    socks, ports = [], []
    for _ in range(6):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    rpc_a, p2p_a, rpc_b, p2p_b, rpc_c, p2p_c = ports
    addr_a, addr_b, addr_c = (f"127.0.0.1:{p}" for p in (rpc_a, rpc_b, rpc_c))
    pay = Account.from_seed(b"\x03" * 32, prefix="kaspasim").addresses()[0]

    procs = {}
    try:
        procs["a"] = _spawn(tmp_path, "a", rpc_a, p2p_a)
        _wait_rpc(addr_a)
        _mine(addr_a, pay, 8)
        sink_a = rpc_call(addr_a, "getBlockDagInfo")["sink"]

        procs["b"] = _spawn(tmp_path, "b", rpc_b, p2p_b, connect=f"127.0.0.1:{p2p_a}")
        _wait_rpc(addr_b)
        _wait(lambda: rpc_call(addr_b, "getBlockDagInfo")["sink"] == sink_a, 90, "B<-A IBD")

        # C connects ONLY to B; gossip must teach it A's address
        procs["c"] = _spawn(tmp_path, "c", rpc_c, p2p_c, connect=f"127.0.0.1:{p2p_b}")
        _wait_rpc(addr_c)
        _wait(lambda: rpc_call(addr_c, "getBlockDagInfo")["sink"] == sink_a, 90, "C<-B locator sync")
        # A's listen address arrived via B's SendAddresses
        _wait(
            lambda: f"127.0.0.1:{p2p_a}" in rpc_call(addr_c, "getPeerAddresses")["known_addresses"],
            60,
            "C learning A's address via gossip",
        )
        # C's connection manager dials A from the gossiped address book
        _wait(
            lambda: any(
                p["address"] == f"127.0.0.1:{p2p_a}"
                for p in rpc_call(addr_c, "getConnectedPeerInfo")
            ),
            60,
            "C dialing A from the address book",
        )

        # partition: B leaves; A extends the chain; C must still follow via
        # its gossip-learned connection to A
        procs.pop("b").terminate()
        _mine(addr_a, pay, 4)
        sink_a2 = rpc_call(addr_a, "getBlockDagInfo")["sink"]
        assert sink_a2 != sink_a
        _wait(lambda: rpc_call(addr_c, "getBlockDagInfo")["sink"] == sink_a2, 90, "C following A's branch")
    finally:
        for proc in procs.values():
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


def test_dns_seeding(tmp_path):
    """--dnsseed resolves hostnames into the address book at startup
    (flow_context dnsseed bootstrap)."""
    from kaspa_tpu.node.daemon import Daemon, parse_args

    args = parse_args(
        ["--appdir", str(tmp_path), "--rpclisten", "127.0.0.1:0", "--no-persist",
         "--dnsseed", "localhost:16333", "--dnsseed", "no-such-host.invalid"]
    )
    d = Daemon(args)
    d.start()
    try:
        # seeding runs on a background thread so startup never blocks on DNS
        _wait(
            lambda: "127.0.0.1:16333" in [str(a) for a in d.address_manager.get_all_addresses()],
            10,
            "dns seed resolution",
        )
        known = [str(a) for a in d.address_manager.get_all_addresses()]
        assert not any("invalid" in a for a in known)  # failures skipped
    finally:
        d.stop()
