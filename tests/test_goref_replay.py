"""Golden-DAG cross-implementation replay (the json_test equivalence suite).

Replays DAG files produced by the golang kaspad (and re-validated by the
Rust reference's CI) through our full pipeline.  Every recomputed
consensus quantity — header hash, GHOSTDAG coloring/blue work, difficulty
bits, DAA score, past median time, merkle roots, muhash utxo commitments,
coinbase rewards, signature validity — must match the golden headers, or
the replay fails.  Reference: consensus_integration_tests.rs json_test.
"""

import os

import pytest

from kaspa_tpu.sim.goref import load_goref, replay_goref

pytestmark = pytest.mark.slow

DATA = "/root/reference/testing/integration/testdata/dags_for_json_tests"
TX_DAG = os.path.join(DATA, "goref-1060-tx-265-blocks", "blocks.json.gz")
NOTX_DAG = os.path.join(DATA, "goref-notx-5000-blocks", "blocks.json.gz")


@pytest.mark.skipif(not os.path.exists(TX_DAG), reason="reference testdata not mounted")
def test_goref_tx_dag_full_replay():
    """265 blocks with 1060 real transactions: full bit-for-bit validation."""
    consensus = replay_goref(TX_DAG)
    assert consensus.get_virtual_daa_score() == 265
    # every non-genesis block fully validated; the sink chain is UTXO-valid
    assert consensus.storage.statuses.get(consensus.sink()) == "utxo_valid"


@pytest.mark.skipif(not os.path.exists(NOTX_DAG), reason="reference testdata not mounted")
def test_goref_notx_dag_full_replay():
    """All 5000 header-stress blocks (~13s with the native chacha path)."""
    consensus = replay_goref(NOTX_DAG)
    assert consensus.get_virtual_daa_score() == 5000


PRUNING_DAG = os.path.join(DATA, "goref_custom_pruning_depth", "blocks.json.gz")


@pytest.mark.skipif(not os.path.exists(PRUNING_DAG), reason="reference testdata not mounted")
def test_goref_custom_pruning_depth_with_live_pruning():
    """700-block prefix of the custom-pruning-depth DAG (pruning_depth=450,
    finality=200): the pruning executor must advance the pruning point,
    delete history below it, keep the PP UTXO set commitment-exact — while
    the replay stays golden bit-for-bit.  (The full 5000-block file replays
    clean too but takes ~25 min of per-block CPU sig batches.)"""
    consensus = replay_goref(PRUNING_DAG, limit=700)
    assert consensus.get_virtual_daa_score() >= 680
    pp = consensus.pruning_processor
    g = consensus.params.genesis.hash
    # the pruning point moved and history was deleted
    assert pp.pruning_point != g
    assert len(pp.past_pruning_points) >= 2
    assert len(consensus.storage.headers) < 700
    assert not consensus.storage.block_transactions.has(g)
    # the maintained pruning-point UTXO set matches the header commitment
    assert pp.check_pruning_utxo_commitment()
    # virtual keeps working on top of the pruned DAG
    assert consensus.storage.statuses.get(consensus.sink()) == "utxo_valid"


@pytest.mark.skipif(not os.path.exists(TX_DAG), reason="reference testdata not mounted")
def test_goref_header_hash_roundtrip():
    """Loader asserts every header's recomputed hash equals the file's."""
    params, blocks = load_goref(TX_DAG)
    assert len(blocks) == 265 + 1
    # 224 non-coinbase spends in this capture (the "1060" in the dir name
    # counts the originating scenario's total txs, not per-file spends)
    assert sum(len(b.transactions) - 1 for b in blocks) == 224


@pytest.mark.skipif(
    not os.path.exists(PRUNING_DAG) or not os.environ.get("KASPA_TPU_FULL_REPLAY"),
    reason="full 5000-block pruning replay is ~25 min; set KASPA_TPU_FULL_REPLAY=1",
)
def test_goref_custom_pruning_depth_full_5000():
    """The complete custom-pruning-depth DAG: deep pruning execution and
    proof serving exercised over the whole file (the once-per-round deep
    tail run; the 700-block prefix covers the fast path)."""
    consensus = replay_goref(PRUNING_DAG)
    assert consensus.get_virtual_daa_score() >= 4900
    pp = consensus.pruning_processor
    assert pp.pruning_point != consensus.params.genesis.hash
    assert pp.check_pruning_utxo_commitment()
    assert consensus.storage.statuses.get(consensus.sink()) == "utxo_valid"
    # a pruned node must still serve an acceptable proof
    proof = consensus.pruning_proof_manager.build_proof()
    assert proof and proof[0]
