"""Sharded verification of a real goref block batch on the CPU mesh.

VERDICT r1 asked for multi-chip evidence beyond identical tiled lanes:
this replays a prefix of the golden tx DAG, captures the exact
(pubkey, sighash, sig) triples the consensus validator dispatched, then
re-runs them through the Schnorr kernel jitted over an 8-device mesh with
batch-dim sharding — the mask must match both the single-device dispatch
and the scalar eclib oracle, lane for lane.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kaspa_tpu.crypto import eclib, secp
from kaspa_tpu.ops.secp256k1 import points as pt
from kaspa_tpu.ops.secp256k1.verify import schnorr_verify_kernel
from kaspa_tpu.sim.goref import replay_goref

pytestmark = pytest.mark.slow

TX_DAG = (
    "/root/reference/testing/integration/testdata/dags_for_json_tests/"
    "goref-1060-tx-265-blocks/blocks.json.gz"
)


@pytest.mark.skipif(not os.path.exists(TX_DAG), reason="reference testdata not mounted")
def test_goref_block_batch_sharded_over_mesh(monkeypatch):
    captured = []
    real_batch = secp.schnorr_verify_batch

    def capturing_batch(items):
        items = list(items)
        captured.extend(items)
        return real_batch(items)

    # txscript.batch resolves secp.schnorr_verify_batch at call time on this
    # same module object, so one patch covers the validator's dispatch too
    monkeypatch.setattr(secp, "schnorr_verify_batch", capturing_batch)
    replay_goref(TX_DAG)  # txs appear late in this DAG: replay in full
    assert len(captured) >= 64, f"expected real sig jobs in the tx DAG, got {len(captured)}"

    triples = captured[:256]
    host_mask = np.asarray(real_batch(triples))
    oracle = np.array(
        [len(p) == 32 and len(s) == 64 and eclib.schnorr_verify(p, m, s) for p, m, s in triples]
    )
    assert (host_mask == oracle).all()

    devices = np.array(jax.devices()[:8])
    mesh = Mesh(devices, axis_names=("batch",))

    def sharded_verify(px, py, rc, s_scalars, e_scalars, valid_in):
        b = np.asarray(px).shape[0]
        assert b % 8 == 0  # secp buckets are powers of two >= 8
        from kaspa_tpu.ops.secp256k1.verify import _scalars_to_digits

        sdig = _scalars_to_digits(s_scalars, b)
        edig = _scalars_to_digits(e_scalars, b)
        lane = NamedSharding(mesh, P("batch", None))
        flat = NamedSharding(mesh, P("batch"))
        args = [
            jax.device_put(np.asarray(a), s)
            for a, s in zip(
                (px, py, rc, sdig, edig, np.asarray(valid_in)),
                (lane, lane, lane, lane, lane, flat),
            )
        ]
        fn = jax.jit(
            schnorr_verify_kernel.__wrapped__,
            in_shardings=(lane,) * 5 + (flat,),
            out_shardings=flat,
        )
        return np.asarray(fn(*args))

    monkeypatch.setattr(secp, "schnorr_verify", sharded_verify)
    sharded_mask = np.asarray(real_batch(triples))
    assert (sharded_mask == host_mask).all(), "mesh-sharded mask diverges from single-device dispatch"
    assert sharded_mask.all(), "golden DAG signatures must all verify"

    # and with adversarial lanes mixed in: corrupted copies of real triples
    bad = [(p, m, bytes([s[0] ^ 0xFF]) + s[1:]) for p, m, s in triples[:16]]
    mixed = triples[:48] + bad
    mixed_mask = np.asarray(real_batch(mixed))
    assert mixed_mask[:48].all() and not mixed_mask[48:].any()
