"""KIP-21/Toccata enforcement inside the consensus engine.

The reference verifies sequencing commitments during chain-block UTXO
verification (pipeline/virtual_processor/utxo_validation.rs:197-278) and
switches rulesets at the fork's DAA score (config/params.rs:724).  These
tests drive the same behavior end-to-end: activation divergence at the
exact score, lane evolution + inactivity expiry, reorg rollback of lane
state, restart-resume of the SMT, and the first-parent chain rule.
"""

import random

import pytest

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.consensus import seq_commit as sc
from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.model.tx import (
    ComputeCommit,
    SUBNETWORK_ID_NATIVE,
    Transaction,
    TransactionInput,
    TransactionOutput,
)
from kaspa_tpu.consensus.params import simnet_params
from kaspa_tpu.consensus.processes.coinbase import MinerData
from kaspa_tpu.crypto import eclib, merkle
from kaspa_tpu.txscript import standard

SEC = 7
PUB = eclib.schnorr_pubkey(SEC)
SPK = standard.pay_to_pub_key(PUB)
MD = MinerData(SPK, extra_data=b"toccata")


def _params(activation: int, **overrides):
    p = simnet_params(bps=2)
    p.toccata_activation = activation
    for k, v in overrides.items():
        setattr(p, k, v)
    return p


def _grow(c, tip, n, t0=10_000, txs=None):
    out = []
    for i in range(n):
        blk = c.build_block_with_parents([tip], MD, txs if i == 0 else [], timestamp=t0 + 600 * i)
        assert c.validate_and_insert_block(blk) == "utxo_valid"
        tip = blk.hash
        out.append(blk)
    return tip, out


def _signed_spend(consensus, rng, fee=100_000):
    view = consensus.get_virtual_utxo_view()
    pov = consensus.get_virtual_daa_score()
    maturity = consensus.params.coinbase_maturity
    for outpoint, entry in sorted(consensus.utxo_set.items(), key=lambda kv: (kv[0].transaction_id, kv[0].index)):
        if view.get(outpoint) is None or entry.script_public_key != SPK:
            continue
        if entry.is_coinbase and entry.block_daa_score + maturity > pov:
            continue
        tx = Transaction(
            0,
            [TransactionInput(outpoint, b"", 0, ComputeCommit.sigops(1))],
            [TransactionOutput(entry.amount - fee, SPK)],
            0,
            SUBNETWORK_ID_NATIVE,
            0,
            b"",
        )
        reused = chash.SigHashReusedValues()
        msg = chash.calc_schnorr_signature_hash(tx, [entry], 0, chash.SIG_HASH_ALL, reused)
        sig = eclib.schnorr_sign(msg, SEC, rng.randbytes(32))
        tx.inputs[0].signature_script = standard.schnorr_signature_script(sig, chash.SIG_HASH_ALL)
        return tx
    raise AssertionError("no mature utxo")


def test_activation_divergence_at_exact_daa_score():
    """Pre-fork blocks carry the KIP-15 root and version 1; from the exact
    activation score on, headers commit the KIP-21 seq-commit, version 2."""
    activation = 4
    c = Consensus(_params(activation))
    tip, blocks = _grow(c, c.params.genesis.hash, 8)

    for blk in blocks:
        h = blk.header
        gd = c.storage.ghostdag.get(blk.hash)
        sp_header = c.storage.headers.get(gd.selected_parent)
        kip15 = merkle.merkle_hash(
            sp_header.accepted_id_merkle_root,
            merkle.calc_merkle_root(c.acceptance_data[blk.hash]),
        )
        if h.daa_score < activation:
            assert h.version == 1  # constants.rs BLOCK_VERSION pre-fork
            assert h.accepted_id_merkle_root == kip15
        else:
            assert h.version == 2
            # the sequencing commitment chains differently from KIP-15
            assert h.accepted_id_merkle_root != kip15
            build = c.lane_tracker.builds[blk.hash]
            assert build.seq_commit == h.accepted_id_merkle_root
            # coinbase lane is touched by every chain block
            assert sc.COINBASE_LANE_KEY in build.updates


def test_kip15_root_rejected_after_activation():
    """A post-activation block carrying the (otherwise correct) KIP-15 root
    must be disqualified: the fork switches the commitment rule."""
    c = Consensus(_params(3))
    tip, _ = _grow(c, c.params.genesis.hash, 5)

    blk = c.build_block_with_parents([tip], MD, [], timestamp=99_000)
    gd = c.ghostdag_manager.ghostdag([tip])
    sp_header = c.storage.headers.get(gd.selected_parent)
    # recompute what the acceptance ids will be: single-parent chain block
    # accepts only the selected parent's coinbase
    sp_txs = c.storage.block_transactions.get(gd.selected_parent)
    kip15 = merkle.merkle_hash(
        sp_header.accepted_id_merkle_root, merkle.calc_merkle_root([sp_txs[0].id()])
    )
    assert blk.header.accepted_id_merkle_root != kip15
    blk.header.accepted_id_merkle_root = kip15
    blk.header.invalidate_cache()
    assert c.validate_and_insert_block(blk) == "disqualified"


def test_lane_touch_and_inactivity_expiry():
    """A native-lane touch activates the lane; staying idle for more than
    finality_depth blue scores expires it (SeqCommitBounds window)."""
    f = 4
    c = Consensus(_params(0, finality_depth=f, coinbase_maturity=2))
    rng = random.Random(5)
    tip, _ = _grow(c, c.params.genesis.hash, 4)

    tx = _signed_spend(c, rng)
    native_lk = sc.lane_key(bytes(SUBNETWORK_ID_NATIVE))
    blk = c.build_block_with_parents([tip], MD, [tx], timestamp=50_000)
    assert c.validate_and_insert_block(blk) == "utxo_valid"
    # the tx is accepted by the NEXT chain block (which merges blk)
    tip, _ = _grow(c, blk.hash, 1, t0=60_000)
    assert native_lk in c.lane_tracker.lane_tips
    count_with_lane = c.lane_tracker.builds[tip].active_lanes_count
    assert count_with_lane == 2  # coinbase + native

    # idle for > finality_depth chain blocks: the native lane expires
    tip, _ = _grow(c, tip, f + 2, t0=70_000)
    assert native_lk not in c.lane_tracker.lane_tips
    assert sc.COINBASE_LANE_KEY in c.lane_tracker.lane_tips
    assert c.lane_tracker.builds[tip].active_lanes_count == 1


def test_reorg_rolls_lane_state_back():
    """Lane state must follow the UTXO position across reorgs: after any
    virtual movement the materialized SMT root equals the recorded
    lanes_root of the position block."""
    c = Consensus(_params(0, coinbase_maturity=2))
    rng = random.Random(9)
    g = c.params.genesis.hash

    a_tip, _ = _grow(c, g, 4, t0=10_000)
    tx = _signed_spend(c, rng)
    blk = c.build_block_with_parents([a_tip], MD, [tx], timestamp=40_000)
    assert c.validate_and_insert_block(blk) == "utxo_valid"
    a_tip, _ = _grow(c, blk.hash, 1, t0=41_000)
    assert c.sink() == a_tip

    # longer competing chain from genesis (heavier -> reorg)
    b_tip = g
    for i in range(9):
        b = c.build_block_with_parents([b_tip], MD, [], timestamp=20_000 + 600 * i)
        c.validate_and_insert_block(b)
        b_tip = b.hash
    assert c.sink() == b_tip

    pos = c.utxo_position
    build = c.lane_tracker.builds.get(pos)
    assert build is not None and c.lane_tracker.tree.root() == build.lanes_root
    # the reorged-away chain's lane touch is gone from the materialized state
    assert sc.lane_key(bytes(SUBNETWORK_ID_NATIVE)) not in c.lane_tracker.lane_tips

    # reorg back: extend the original chain past the B chain
    a2, _ = _grow(c, a_tip, 7, t0=60_000)
    assert c.sink() == a2
    build = c.lane_tracker.builds[c.utxo_position]
    assert c.lane_tracker.tree.root() == build.lanes_root
    assert sc.lane_key(bytes(SUBNETWORK_ID_NATIVE)) in c.lane_tracker.lane_tips


def test_restart_resumes_lane_state(tmp_path):
    from kaspa_tpu.storage.kv import KvStore

    path = str(tmp_path / "db")
    params = _params(0, coinbase_maturity=2)
    db = KvStore(path)
    c = Consensus(params, db)
    rng = random.Random(11)
    tip, _ = _grow(c, params.genesis.hash, 4)
    tx = _signed_spend(c, rng)
    blk = c.build_block_with_parents([tip], MD, [tx], timestamp=50_000)
    assert c.validate_and_insert_block(blk) == "utxo_valid"
    tip, _ = _grow(c, blk.hash, 2, t0=60_000)
    root = c.lane_tracker.tree.root()
    tips = dict(c.lane_tracker.lane_tips)
    db.close()

    db2 = KvStore(path)
    c2 = Consensus(params, db2)
    assert c2.lane_tracker.tree.root() == root
    assert c2.lane_tracker.lane_tips == tips
    # and the reloaded node keeps building/validating chain blocks
    tip2, _ = _grow(c2, c2.sink(), 2, t0=90_000)
    assert c2.storage.statuses.get(tip2) == "utxo_valid"
    db2.close()


def test_seq_commit_opcode_end_to_end():
    """OpChainblockSeqCommit (0xd4) reads a chain block's sequencing
    commitment through the live consensus accessor: a covenant-style output
    gated on the commitment of an ancestor chain block is spendable."""
    c = Consensus(_params(0, coinbase_maturity=2))
    rng = random.Random(13)
    tip, blocks = _grow(c, c.params.genesis.hash, 4)

    target = blocks[1].hash  # early chain block
    expected = c.storage.headers.get(target).accepted_id_merkle_root
    # script: <target> OpChainblockSeqCommit <expected> OpEqual
    covenant_spk = standard.ScriptPublicKey(
        0, bytes([32]) + target + bytes([0xD4]) + bytes([32]) + expected + bytes([0x87])
    )

    # fund the covenant output
    fund = _signed_spend(c, rng)
    fund.outputs[0] = TransactionOutput(fund.outputs[0].value, covenant_spk)
    # re-commit the KIP-9 storage mass and re-sign after the output edit
    entry = c.get_virtual_utxo_view().get(fund.inputs[0].previous_outpoint)
    fund.storage_mass = c.transaction_validator.mass_calculator.calc_contextual_masses(fund, [entry])
    reused = chash.SigHashReusedValues()
    msg = chash.calc_schnorr_signature_hash(fund, [entry], 0, chash.SIG_HASH_ALL, reused)
    fund.inputs[0].signature_script = standard.schnorr_signature_script(
        eclib.schnorr_sign(msg, SEC, rng.randbytes(32)), chash.SIG_HASH_ALL
    )
    blk = c.build_block_with_parents([tip], MD, [fund], timestamp=50_000)
    assert c.validate_and_insert_block(blk) == "utxo_valid"
    tip, _ = _grow(c, blk.hash, 1, t0=60_000)

    # spend it: empty signature script, the spk script proves the commitment
    from kaspa_tpu.consensus.model.tx import TransactionOutpoint

    spend = Transaction(
        1,
        [TransactionInput(TransactionOutpoint(fund.id(), 0), b"", 0, ComputeCommit.budget(100))],
        [TransactionOutput(fund.outputs[0].value - 100_000, SPK)],
        0,
        SUBNETWORK_ID_NATIVE,
        0,
        b"",
    )
    blk2 = c.build_block_with_parents([tip], MD, [spend], timestamp=70_000)
    assert c.validate_and_insert_block(blk2) == "utxo_valid"
    tip, _ = _grow(c, blk2.hash, 1, t0=80_000)
    assert spend.id() in c.acceptance_data[tip]


def test_boundary_lane_retouch_nets_zero_count():
    """A lane expiring and re-activating in the same chain block must leave
    active_lanes_count unchanged (+1 new, +1 expired cancel)."""
    f = 3
    c = Consensus(_params(0, finality_depth=f, coinbase_maturity=2))
    rng = random.Random(21)
    tip, _ = _grow(c, c.params.genesis.hash, 4)
    native_lk = sc.lane_key(bytes(SUBNETWORK_ID_NATIVE))

    # touch the native lane
    tx = _signed_spend(c, rng)
    blk = c.build_block_with_parents([tip], MD, [tx], timestamp=50_000)
    assert c.validate_and_insert_block(blk) == "utxo_valid"
    tip, _ = _grow(c, blk.hash, 1, t0=60_000)
    touch_bs = c.lane_tracker.lane_tips[native_lk][1]
    assert c.lane_tracker.builds[tip].active_lanes_count == 2

    # idle until the lane sits exactly at the expiry boundary, then
    # re-touch it in the very block that would expire it
    while True:
        cur_bs = c.storage.ghostdag.get_blue_score(c.sink())
        if cur_bs + 1 - f > touch_bs:
            break
        tip, _ = _grow(c, tip, 1, t0=61_000 + cur_bs * 600)
    tx2 = _signed_spend(c, rng)
    blk2 = c.build_block_with_parents([tip], MD, [tx2], timestamp=90_000)
    assert c.validate_and_insert_block(blk2) == "utxo_valid"
    tip, _ = _grow(c, blk2.hash, 1, t0=95_000)
    assert native_lk in c.lane_tracker.lane_tips
    assert c.lane_tracker.builds[tip].active_lanes_count == 2


def test_first_parent_must_be_selected_parent():
    """Post-Toccata chain rule (utxo_validation.rs:219-238): a chain block
    whose first parent is not its selected parent is disqualified."""
    c = Consensus(_params(0))
    g = c.params.genesis.hash
    a, _ = _grow(c, g, 3, t0=10_000)
    side = c.build_block_with_parents([g], MD, [], timestamp=25_000)
    assert c.validate_and_insert_block(side) in ("utxo_valid", "utxo_pending")

    blk = c.build_block_with_parents([a, side.hash], MD, [], timestamp=40_000)
    gd = c.ghostdag_manager.ghostdag([a, side.hash])
    assert blk.header.parents_by_level[0][0] == gd.selected_parent
    # swap the direct-parent order; everything else stays intact
    blk.header.parents_by_level[0] = list(reversed(blk.header.parents_by_level[0]))
    blk.header.invalidate_cache()
    assert c.validate_and_insert_block(blk) == "disqualified"


# ---------------------------------------------------------------------------
# KIP-21 block lane limits (body_validation_in_isolation.rs:100-121,478-496)
# ---------------------------------------------------------------------------

from kaspa_tpu.consensus.consensus import RuleError
from kaspa_tpu.consensus.model.tx import TransactionOutpoint, subnetwork_from_byte


def _lane_tx(index: int, lane: bytes, gas: int) -> Transaction:
    """A minimal non-coinbase tx riding subnetwork `lane` with `gas`
    (mirrors the reference's toccata_lane_tx test helper)."""
    inp = TransactionInput(
        TransactionOutpoint(bytes([index]) * 32, 0), b"", (1 << 64) - 1, ComputeCommit.budget(0)
    )
    return Transaction(1, [inp], [TransactionOutput(1, SPK)], 0, lane, gas, b"")


def _block_with_lane_txs(c, tip, lane_txs, timestamp):
    """An otherwise-valid block whose body carries `lane_txs` appended after
    the coinbase; only hash_merkle_root is recommitted — the lane rules fire
    in body-in-isolation, before any UTXO-context validation."""
    blk = c.build_block_with_parents([tip], MD, [], timestamp=timestamp)
    blk.transactions = [blk.transactions[0]] + lane_txs
    blk.header.hash_merkle_root = merkle.calc_hash_merkle_root(blk.transactions)
    blk.header.invalidate_cache()
    blk.invalidate_cache() if hasattr(blk, "invalidate_cache") else None
    return blk


def test_lanes_per_block_limit_rejected():
    """A block occupying lanes_per_block+1 distinct lanes is rejected; one
    occupying exactly lanes_per_block passes body-in-isolation."""
    lpb = 3
    c = Consensus(_params(0, lanes_per_block=lpb))
    tip, _ = _grow(c, c.params.genesis.hash, 3)

    over = [_lane_tx(i, subnetwork_from_byte(3 + i), 0) for i in range(lpb + 1)]
    blk = _block_with_lane_txs(c, tip, over, 50_000)
    with pytest.raises(RuleError, match="lanes-per-block"):
        c.validate_and_insert_block(blk)

    # exactly LPB distinct lanes passes the body stage (the block is later
    # disqualified in UTXO context for its fabricated inputs — no RuleError)
    at = [_lane_tx(i, subnetwork_from_byte(3 + i), 0) for i in range(lpb)]
    blk2 = _block_with_lane_txs(c, tip, at, 51_000)
    assert c.validate_and_insert_block(blk2) in ("disqualified", "utxo_pending")


def test_gas_per_lane_limit_rejected():
    """Summed gas within one lane above gas_per_lane is rejected — by a
    single tx or accumulated across txs; the same gas spread across distinct
    lanes is fine."""
    cap = 1_000
    c = Consensus(_params(0, gas_per_lane=cap))
    tip, _ = _grow(c, c.params.genesis.hash, 3)

    one = [_lane_tx(1, subnetwork_from_byte(7), cap + 1)]
    with pytest.raises(RuleError, match="gas-per-lane"):
        c.validate_and_insert_block(_block_with_lane_txs(c, tip, one, 50_000))

    split = [_lane_tx(1, subnetwork_from_byte(7), cap // 2 + 1),
             _lane_tx(2, subnetwork_from_byte(7), cap // 2 + 1)]
    with pytest.raises(RuleError, match="gas-per-lane"):
        c.validate_and_insert_block(_block_with_lane_txs(c, tip, split, 51_000))

    spread = [_lane_tx(1, subnetwork_from_byte(7), cap),
              _lane_tx(2, subnetwork_from_byte(8), cap)]
    blk = _block_with_lane_txs(c, tip, spread, 52_000)
    assert c.validate_and_insert_block(blk) in ("disqualified", "utxo_pending")


def test_many_txs_single_lane_not_lane_limited():
    """lanes_per_block caps distinct lanes, not tx count: many zero-gas txs
    in one lane pass body-in-isolation."""
    c = Consensus(_params(0, lanes_per_block=2))
    tip, _ = _grow(c, c.params.genesis.hash, 3)
    txs = [_lane_tx(i, subnetwork_from_byte(9), 0) for i in range(1, 8)]
    blk = _block_with_lane_txs(c, tip, txs, 50_000)
    assert c.validate_and_insert_block(blk) in ("disqualified", "utxo_pending")
