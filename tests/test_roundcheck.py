"""Round-evidence tooling: roundcheck artifact + bench probe/dossier helpers."""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location("bench", os.path.join(REPO_ROOT, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_roundcheck_writes_round_evidence(tmp_path):
    out = tmp_path / "ROUNDCHECK.json"
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "tools", "roundcheck.py"),
            "--skip-tests",
            "--skip-bench",
            # the mesh lanes re-trace the verify ladder in fresh subprocesses
            # (minutes on CPU) — they get their own roundcheck run per round,
            # not a seat inside the tier-1 fast lane; same for the chaos
            # sustain run (three full replays of a hostile workload) and the
            # coalesced-dispatch throughput lane (bench child + dual replay)
            # and the obs lane (traced 24-block replay plus a tracing-off
            # overhead A/B whose 2% gate is noise under suite load)
            "--skip-mesh",
            "--skip-chaos",
            "--skip-dispatch",
            "--skip-obs",
            # and the fabric drill (a verifyd subprocess + three replays)
            "--skip-fabric",
            # and the ingest lane (an identity-check subprocess + a 24-block
            # tx-flood sustain replay)
            "--skip-ingest",
            # and the brownout ramp drill (another 24-block flood replay)
            "--skip-overload",
            # and the swarm drill (three live nodes over loopback sockets
            # running a full partition/heal/late-join scenario — minutes
            # of wall; it gets its own `roundcheck --only swarm` run)
            "--skip-swarm",
            # and the serving latency observatory (a 50k-virtual-subscriber
            # ramp + overhead A/B, minutes of wall and timing-sensitive —
            # it gets its own `roundcheck --only serving_load` run)
            "--skip-serving_load",
            # and the lint lane: the v2 gate runs the gated kernel-shape
            # audit (real eval_shape traces, ~50 s on CPU) — it gets its
            # own `roundcheck --only lint` acceptance run
            "--skip-lint",
            # and the aggregated-verify lane: its bench child traces BOTH
            # verify lanes from a cold process (minutes of XLA compile on
            # CPU, ~5x everything else in this run combined) — it gets its
            # own `roundcheck --only aggregate` acceptance run
            "--skip-aggregate",
            "--blocks",
            "8",
            "--out",
            str(out),
        ],
        cwd=REPO_ROOT,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout
    evidence = json.loads(out.read_text())
    assert evidence["ok"] is True
    sim = evidence["sections"]["sim"]
    assert sim["ok"] and sim["result"]["blocks"] == 8
    assert "created" in evidence


def test_roundcheck_only_selector(tmp_path):
    """--only SECTION runs exactly the named sections (skip flags ignored)
    and every section records its own wall_seconds in the artifact."""
    out = tmp_path / "RC.json"
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO_ROOT, "tools", "roundcheck.py"),
            "--only", "sim", "--skip-sim", "--blocks", "8", "--out", str(out),
        ],
        cwd=REPO_ROOT, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout
    evidence = json.loads(out.read_text())
    assert list(evidence["sections"]) == ["sim"]
    assert evidence["sections"]["sim"]["wall_seconds"] >= 0
    # unknown section names fail fast instead of silently running nothing
    bad = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "roundcheck.py"), "--only", "nope"],
        cwd=REPO_ROOT, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, timeout=60,
    )
    assert bad.returncode != 0 and "unknown --only" in bad.stdout


def test_bench_wedge_dossier_shape(tmp_path, monkeypatch):
    bench = _load_bench()
    monkeypatch.setenv("KASPA_TPU_BENCH_DOSSIER_DIR", str(tmp_path))
    probe_log = [{"t": bench._utc_stamp(), "event": "session_probe_start", "timeout_s": 1}]
    fallback = {"metric": bench.METRIC, "value": 123.4, "unit": bench.UNIT}
    path = bench._write_wedge_dossier(probe_log, fallback)
    assert os.path.dirname(path) == str(tmp_path)
    dossier = json.loads(open(path).read())
    assert dossier["reason"].startswith("device probe wedge")
    assert dossier["probe_log"] == probe_log
    assert dossier["cpu_fallback"]["value"] == 123.4
    # timestamped filename: bench_wedge_<UTC>.json
    assert os.path.basename(path).startswith("bench_wedge_20")


def test_bench_cached_wedge_fast_fail(tmp_path, monkeypatch):
    """A wedge dossier younger than the TTL short-circuits the probe +
    retry spiral; FORCE_PROBE bypasses; a stale dossier is ignored."""
    bench = _load_bench()
    monkeypatch.setenv("KASPA_TPU_BENCH_DOSSIER_DIR", str(tmp_path))
    monkeypatch.delenv("KASPA_TPU_BENCH_FORCE_PROBE", raising=False)

    log: list = []
    assert bench._cached_wedge(log) is None  # no dossier yet

    dossier = tmp_path / "bench_wedge_20260805T000000Z.json"
    dossier.write_text(json.dumps({"reason": "test", "cpu_fallback": {"value": 99.5}}))

    hit = bench._cached_wedge(log)
    assert hit is not None
    path, doc = hit
    assert path == str(dossier)
    assert doc["cpu_fallback"]["value"] == 99.5
    assert log and log[-1]["event"] == "cached_wedge_verdict"

    # the recurring daemon capture forces a fresh probe to notice recovery
    monkeypatch.setenv("KASPA_TPU_BENCH_FORCE_PROBE", "1")
    assert bench._cached_wedge([]) is None
    monkeypatch.delenv("KASPA_TPU_BENCH_FORCE_PROBE")

    # outside the TTL the verdict is stale and the probe runs fresh
    monkeypatch.setattr(bench, "WEDGE_TTL_S", -1.0)
    assert bench._cached_wedge([]) is None


def test_bench_spiral_exhaustion_writes_dossier(tmp_path, monkeypatch):
    bench = _load_bench()
    monkeypatch.setenv("KASPA_TPU_BENCH_DOSSIER_DIR", str(tmp_path))
    path = bench._write_wedge_dossier(
        [{"event": "attempt_spiral_exhausted"}], None,
        reason="attempt spiral exhausted (probe answered, workload never finished)",
    )
    doc = json.loads(open(path).read())
    assert doc["reason"].startswith("attempt spiral exhausted")
    # and the fresh dossier is immediately visible to the fast-fail cache
    monkeypatch.delenv("KASPA_TPU_BENCH_FORCE_PROBE", raising=False)
    assert bench._cached_wedge([]) is not None


def test_bench_probe_mode_emits_json_line():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["KASPA_TPU_BENCH_CHILD"] = "1"
    env["KASPA_TPU_BENCH_MODE"] = "probe"
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py")],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
        timeout=180,
    )
    line = [ln for ln in proc.stdout.strip().splitlines() if ln.startswith("{")][-1]
    obj = json.loads(line)
    assert obj["probe_ok"] is True and proc.returncode == 0
    assert obj["platform"] == "cpu"
