"""wRPC: WebSocket JSON-RPC transport round-trip + notification streaming.

Reference: rpc/wrpc/server — the same RpcCoreService served over a real
RFC 6455 WebSocket with id-matched calls, errors, and streamed
notifications on the same connection.
"""

from __future__ import annotations

import random

import pytest

from kaspa_tpu.node.daemon import Daemon, parse_args
from kaspa_tpu.rpc.wrpc import WrpcClient
from kaspa_tpu.sim.simulator import Miner


@pytest.fixture()
def daemon(tmp_path):
    args = parse_args(
        ["--appdir", str(tmp_path), "--rpclisten", "127.0.0.1:0",
         "--rpclisten-wrpc", "127.0.0.1:0", "--bps", "2"]
    )
    d = Daemon(args)
    d.start()
    yield d, d.wrpc_server.address
    d.stop()


def test_wrpc_calls_and_streaming(daemon):
    d, addr = daemon
    miner = Miner(0, random.Random(2))
    from kaspa_tpu.crypto.addresses import extract_script_pub_key_address

    pay = extract_script_pub_key_address(miner.spk, "kaspasim").to_string()

    client = WrpcClient(addr)
    try:
        info = client.call("getServerInfo")
        assert info["server_version"].startswith("kaspa-tpu")
        assert client.call("getBlockDagInfo")["block_count"] == 0

        # errors come back typed over the socket
        with pytest.raises(RuntimeError, match="unknown method"):
            client.call("noSuchMethod")

        # subscriptions stream on the same connection
        assert client.subscribe("block-added") == "ok"
        for _ in range(3):
            t = client.call("getBlockTemplate", {"payAddress": pay})
            res = client.call("submitBlockByTemplateHash", {"hash": t["block_hash"]})
            assert res["status"] in ("utxo_valid", "utxo_pending")
            d.mining.template_cache.clear()
        seen = 0
        for _ in range(6):
            event, data = client.next_notification(timeout=30)
            if event == "block-added":
                seen += 1
                assert data["hash"]
            if seen == 3:
                break
        assert seen == 3
        assert client.call("getBlockDagInfo")["block_count"] == 3
    finally:
        client.close()


def test_wrpc_frame_codec_roundtrip():
    from kaspa_tpu.rpc import wrpc

    for mask in (False, True):
        for payload in (b"", b"x", b"y" * 200, b"z" * 70000):
            frame = wrpc.encode_frame(wrpc.OP_TEXT, payload, mask=mask)
            pos = [0]

            def rd(n):
                out = frame[pos[0] : pos[0] + n]
                assert len(out) == n
                pos[0] += n
                return out

            op, decoded = wrpc.read_message(rd)
            assert op == wrpc.OP_TEXT and decoded == payload and pos[0] == len(frame)
    assert wrpc.accept_key("dGhlIHNhbXBsZSBub25jZQ==") == "s3pPLMBiTxaQ9kYGzzhZRbK+xOo="  # RFC 6455 §1.3

    # fragmented message assembly (FIN=0 TEXT + FIN=1 CONTINUATION)
    first = wrpc.encode_frame(wrpc.OP_TEXT, b"hello ", mask=True)
    first = bytes([first[0] & 0x7F]) + first[1:]  # clear FIN
    second = wrpc.encode_frame(0x0, b"world", mask=True)  # continuation
    frame = first + second
    pos = [0]

    def rd2(n):
        out = frame[pos[0] : pos[0] + n]
        pos[0] += n
        return out

    op, decoded = wrpc.read_message(rd2)
    assert op == wrpc.OP_TEXT and decoded == b"hello world"

    # declared-length bomb is refused, not buffered
    import struct as _struct

    bomb = bytes([0x81, 127]) + _struct.pack(">Q", 1 << 40)
    pos = [0]

    def rd3(n):
        out = bomb[pos[0] : pos[0] + n]
        pos[0] += n
        return out

    import pytest as _pytest

    with _pytest.raises(ValueError):
        wrpc.read_message(rd3)
