"""wRPC: WebSocket JSON-RPC transport round-trip + notification streaming.

Reference: rpc/wrpc/server — the same RpcCoreService served over a real
RFC 6455 WebSocket with id-matched calls, errors, and streamed
notifications on the same connection.
"""

from __future__ import annotations

import random

import pytest

from kaspa_tpu.node.daemon import Daemon, parse_args
from kaspa_tpu.rpc.wrpc import WrpcClient
from kaspa_tpu.sim.simulator import Miner


@pytest.fixture()
def daemon(tmp_path):
    args = parse_args(
        ["--appdir", str(tmp_path), "--rpclisten", "127.0.0.1:0",
         "--rpclisten-wrpc", "127.0.0.1:0", "--bps", "2"]
    )
    d = Daemon(args)
    d.start()
    yield d, d.wrpc_server.address
    d.stop()


def test_wrpc_calls_and_streaming(daemon):
    d, addr = daemon
    miner = Miner(0, random.Random(2))
    from kaspa_tpu.crypto.addresses import extract_script_pub_key_address

    pay = extract_script_pub_key_address(miner.spk, "kaspasim").to_string()

    client = WrpcClient(addr)
    try:
        info = client.call("getServerInfo")
        assert info["server_version"].startswith("kaspa-tpu")
        assert client.call("getBlockDagInfo")["block_count"] == 0

        # errors come back typed over the socket
        with pytest.raises(RuntimeError, match="unknown method"):
            client.call("noSuchMethod")

        # subscriptions stream on the same connection
        assert client.subscribe("block-added") == "ok"
        for _ in range(3):
            t = client.call("getBlockTemplate", {"payAddress": pay})
            res = client.call("submitBlockByTemplateHash", {"hash": t["block_hash"]})
            assert res["status"] in ("utxo_valid", "utxo_pending")
            d.mining.template_cache.clear()
        seen = 0
        for _ in range(6):
            event, data = client.next_notification(timeout=30)
            if event == "block-added":
                seen += 1
                assert data["hash"]
            if seen == 3:
                break
        assert seen == 3
        assert client.call("getBlockDagInfo")["block_count"] == 3
    finally:
        client.close()


def test_wrpc_frame_codec_roundtrip():
    from kaspa_tpu.rpc import wrpc

    for mask in (False, True):
        for payload in (b"", b"x", b"y" * 200, b"z" * 70000):
            frame = wrpc.encode_frame(wrpc.OP_TEXT, payload, mask=mask)
            pos = [0]

            def rd(n):
                out = frame[pos[0] : pos[0] + n]
                assert len(out) == n
                pos[0] += n
                return out

            op, decoded = wrpc.read_message(rd)
            assert op == wrpc.OP_TEXT and decoded == payload and pos[0] == len(frame)
    assert wrpc.accept_key("dGhlIHNhbXBsZSBub25jZQ==") == "s3pPLMBiTxaQ9kYGzzhZRbK+xOo="  # RFC 6455 §1.3

    # fragmented message assembly (FIN=0 TEXT + FIN=1 CONTINUATION)
    first = wrpc.encode_frame(wrpc.OP_TEXT, b"hello ", mask=True)
    first = bytes([first[0] & 0x7F]) + first[1:]  # clear FIN
    second = wrpc.encode_frame(0x0, b"world", mask=True)  # continuation
    frame = first + second
    pos = [0]

    def rd2(n):
        out = frame[pos[0] : pos[0] + n]
        pos[0] += n
        return out

    op, decoded = wrpc.read_message(rd2)
    assert op == wrpc.OP_TEXT and decoded == b"hello world"

    # declared-length bomb is refused, not buffered
    import struct as _struct

    bomb = bytes([0x81, 127]) + _struct.pack(">Q", 1 << 40)
    pos = [0]

    def rd3(n):
        out = bomb[pos[0] : pos[0] + n]
        pos[0] += n
        return out

    import pytest as _pytest

    with _pytest.raises(ValueError):
        wrpc.read_message(rd3)


# ---------------------------------------------------------------------------
# Borsh encoding (rpc/core/src/model Serializer layouts over borsh
# primitives; rpc/wrpc/server's second encoding)
# ---------------------------------------------------------------------------


def test_borsh_golden_vectors():
    """Byte-level goldens derived field-by-field from the reference's
    versioned Serializer impls (message.rs:276-286, :98-103)."""
    import io

    from kaspa_tpu.rpc import borsh_codec as bc

    # GetInfoResponse: u16 struct version | String p2p_id | u64 mempool |
    # String server_version | 4 bools
    w = io.BytesIO()
    bc.encode_get_info_response(w, {
        "p2p_id": "ab", "mempool_size": 3, "server_version": "x",
        "is_utxo_indexed": True, "is_synced": False,
        "has_notify_command": True, "has_message_id": True,
    })
    assert w.getvalue().hex() == (
        "0100"            # struct version 1 (u16 LE)
        "02000000" "6162"  # "ab" (u32 len + utf8)
        "0300000000000000"  # mempool_size u64
        "01000000" "78"    # "x"
        "01" "00" "01" "01"  # bools
    )
    assert bc.decode_get_info_response(io.BytesIO(w.getvalue()))["p2p_id"] == "ab"

    # SubmitBlockResponse: success + typed rejection
    w = io.BytesIO(); bc.encode_submit_block_response(w, None)
    assert w.getvalue().hex() == "010000"  # version 1 + enum tag 0 (Success)
    w = io.BytesIO(); bc.encode_submit_block_response(w, bc.REJECT_BLOCK_INVALID)
    assert w.getvalue().hex() == "01000101"  # tag 1 (Reject) + reason 1
    assert bc.decode_submit_block_response(io.BytesIO(w.getvalue())) == 1


def test_borsh_block_roundtrip():
    """SubmitBlockRequest survives encode/decode with identical block hash
    and transaction ids (the consensus-equality criterion)."""
    import io

    from kaspa_tpu.consensus.consensus import Consensus
    from kaspa_tpu.consensus.params import simnet_params
    from kaspa_tpu.consensus.processes.coinbase import MinerData
    from kaspa_tpu.rpc import borsh_codec as bc

    c = Consensus(simnet_params(bps=2))
    miner = Miner(0, random.Random(3))
    for i in range(3):
        t = c.build_block_template(MinerData(miner.spk, b"borsh"), [], timestamp=10_000 + 600 * i)
        c.validate_and_insert_block(t)
    w = io.BytesIO()
    bc.encode_submit_block_request(w, t, allow_non_daa_blocks=True)
    blk, allow = bc.decode_submit_block_request(io.BytesIO(w.getvalue()))
    assert allow is True
    assert blk.header.hash == t.header.hash  # every header field round-tripped
    assert [x.id() for x in blk.transactions] == [x.id() for x in t.transactions]


def test_borsh_over_websocket(daemon):
    """getInfo / submitBlock / notifyBlockAdded over the live WebSocket in
    Borsh encoding, sharing the socket with JSON frames."""
    import io

    from kaspa_tpu.rpc import borsh_codec as bc

    d, addr = daemon
    miner = Miner(0, random.Random(2))
    from kaspa_tpu.crypto.addresses import extract_script_pub_key_address

    pay = extract_script_pub_key_address(miner.spk, "kaspasim").to_string()
    client = WrpcClient(addr)
    try:
        # getInfo
        w = io.BytesIO(); bc.encode_get_info_request(w)
        body = client.call_borsh(bc.OP_GET_INFO, w.getvalue())
        info = bc.decode_get_info_response(io.BytesIO(body))
        assert info["is_synced"] is True and info["server_version"]

        # subscribe block-added (borsh event op)
        w = io.BytesIO(); bc.w_u32(w, bc.OP_BLOCK_ADDED_NOTIFICATION)
        client.call_borsh(bc.OP_SUBSCRIBE, w.getvalue())

        # submitBlock: fetch a template via JSON, submit via borsh
        t = client.call("getBlockTemplate", {"payAddress": pay})
        cached = d.mining.template_cache.get()
        assert cached is not None
        w = io.BytesIO(); bc.encode_submit_block_request(w, cached)
        body = client.call_borsh(bc.OP_SUBMIT_BLOCK, w.getvalue())
        assert bc.decode_submit_block_response(io.BytesIO(body)) is None  # Success

        # the block-added notification arrives borsh-encoded
        op, payload = client.borsh_notifications.get(timeout=30)
        assert op == bc.OP_BLOCK_ADDED_NOTIFICATION
        r = io.BytesIO(payload)
        bc.r_u16(r)  # notification struct version
        bc.r_u16(r)  # RpcBlock struct version
        bc.r_u16(r)  # RpcHeader struct version
        assert bc.r_hash(r) == cached.hash  # RpcHeader leads with the hash

        # a garbage frame produces a typed error, not a dropped socket
        with pytest.raises(RuntimeError):
            client.call_borsh(9999, b"")
        assert client.call("getBlockDagInfo")["block_count"] >= 1
    finally:
        client.close()


def test_borsh_fixture_goldens():
    """The committed fixtures in tests/fixtures/borsh pin the serving-tier
    wire byte-for-byte (regenerate with tools/gen_borsh_fixtures.py after
    an INTENTIONAL change; anything else here is a wire break)."""
    import io
    import json
    import os

    from kaspa_tpu.rpc import borsh_codec as bc
    from kaspa_tpu.rpc.borsh_vectors import sample_frames

    fixtures_dir = os.path.join(os.path.dirname(__file__), "fixtures", "borsh")
    with open(os.path.join(fixtures_dir, "manifest.json")) as f:
        manifest = json.load(f)
    frames = sample_frames()
    assert set(manifest) == set(frames)
    for name, (op, data) in frames.items():
        with open(os.path.join(fixtures_dir, f"{name}.bin"), "rb") as f:
            golden = f.read()
        assert data == golden, f"{name}: borsh wire bytes drifted from the committed fixture"
        assert manifest[name]["op"] == op
        assert manifest[name]["bytes"] == len(golden)

    # op numbers are wire ABI: pin them independently of the encoders
    assert (bc.OP_GET_UTXOS_BY_ADDRESSES, bc.OP_GET_BALANCE_BY_ADDRESS, bc.OP_GET_COIN_SUPPLY) == (145, 146, 147)
    assert bc.OP_UTXOS_CHANGED_NOTIFICATION == 64

    # the fixtures also decode: spot-check the Option<address> arms and the
    # versioned entry payload survive a round-trip
    _op, data = frames["get_utxos_by_addresses_response"]
    entries = bc.decode_get_utxos_by_addresses_response(io.BytesIO(data))
    assert len(entries) == 2
    (addr_a, _out_a, entry_a), (addr_b, _out_b, entry_b) = entries
    assert addr_a is not None and addr_b is None
    assert entry_a.is_coinbase is True and entry_a.covenant_id is None
    assert entry_b.covenant_id == b"\xee" * 32

    _op, frame = frames["utxos_changed_frame"]
    kind, _msg_id, op, r = bc.decode_frame(frame)
    assert kind == bc.KIND_NOTIFICATION and op == bc.OP_UTXOS_CHANGED_NOTIFICATION
    decoded = bc.decode_utxos_changed_notification(r)
    assert len(decoded["added"]) == 1 and decoded["removed"] == []
