"""Overload-control plane tests: hysteresis damping on the controller's
level state machine, the brownout action registry's engage/release
contract, and every shedding seam individually — ingest node-overloaded
rejection (ticket still resolves), fanout diff-conflation, template-
rebuild deferral, INV-relay damping, and the RPC retryAfterMs wire
encoding.  The controller is deterministic under an injected clock and
scripted signal values; no run ever depends on sampling-thread timing.
"""

from __future__ import annotations

import io
import json
import queue
import threading
import time

import pytest

from kaspa_tpu.ingest.tier import REJECTED, IngestTier
from kaspa_tpu.mempool.mempool import MempoolError
from kaspa_tpu.mempool.mining_manager import TemplateCache
from kaspa_tpu.notify.notifier import Notification
from kaspa_tpu.observability.shed import SHED
from kaspa_tpu.resilience.overload import (
    CRITICAL,
    ELEVATED,
    NOMINAL,
    SATURATED,
    BrownoutAction,
    BrownoutKnobs,
    OverloadController,
    PressureSignal,
    default_actions,
)
from kaspa_tpu.serving.broadcaster import Subscriber


class _Clock:
    """Deterministic monotonic clock: advances a fixed step per read."""

    def __init__(self, step: float = 1.0):
        self.now = 0.0
        self.step = step

    def __call__(self) -> float:
        self.now += self.step
        return self.now


def _scripted_controller(values, *, enter=(40, 120, 400), actions=(), **kw):
    """Controller over ONE signal that replays ``values`` (then holds the
    last value) — the level trace is a pure function of the schedule."""
    it = iter(values)
    state = {"last": 0.0}

    def read():
        try:
            state["last"] = next(it)
        except StopIteration:
            pass
        return state["last"]

    sig = PressureSignal("load", read, enter)
    return OverloadController([sig], actions, clock=_Clock(), **kw)


# --- hysteresis state machine ----------------------------------------------


def test_level_trace_is_deterministic():
    # enter (40, 120, 400), exit_ratio 0.7 -> exits (28, 84, 280);
    # rise_samples=2 escalates after two consecutive higher votes,
    # fall_samples=3 de-escalates after three holds below the level
    values = [0, 50, 50, 130, 130, 80, 80, 20, 20, 20, 20, 20]
    want = [0, 0, 1, 1, 2, 2, 2, 1, 1, 1, 0, 0]
    ctl = _scripted_controller(values)
    got = [ctl.sample() for _ in values]
    assert got == want
    st = ctl.stats()
    assert st["level"] == NOMINAL
    assert [t["to"] for t in st["transitions"]] == [
        "ELEVATED", "SATURATED", "ELEVATED", "NOMINAL",
    ]


def test_noisy_boundary_does_not_flap():
    # oscillation straddling the ELEVATED enter threshold (40) but above
    # its exit (28): the up-streak resets on every dip, so the controller
    # never escalates — and once forced up, the same band never drops it
    ctl = _scripted_controller([45, 35] * 10)
    assert [ctl.sample() for _ in range(20)] == [NOMINAL] * 20


def test_escalation_is_one_level_per_streak():
    # a CRITICAL-grade value must still walk NOMINAL -> ELEVATED ->
    # SATURATED -> CRITICAL one level per rise streak, never jumping
    ctl = _scripted_controller([10_000] * 8)
    trace = [ctl.sample() for _ in range(8)]
    assert trace == [0, 1, 1, 2, 2, 3, 3, 3]
    assert all(b - a <= 1 for a, b in zip(trace, trace[1:]))


def test_dwell_accounting_covers_every_level():
    ctl = _scripted_controller([10_000] * 6 + [0] * 12)
    for _ in range(18):
        ctl.sample()
    dwell = ctl.stats()["dwell_seconds"]
    assert ctl.level() == NOMINAL
    assert all(dwell[name] > 0 for name in ("ELEVATED", "SATURATED", "CRITICAL"))


def test_signal_read_failure_reads_as_no_pressure():
    def boom():
        raise RuntimeError("subsystem gone")

    ctl = OverloadController([PressureSignal("x", boom, (1, 2, 3))], clock=_Clock())
    assert [ctl.sample() for _ in range(3)] == [NOMINAL] * 3


# --- brownout action registry ----------------------------------------------


def test_actions_engage_refire_and_release():
    calls: list = []
    act = BrownoutAction(
        "rec", ELEVATED, lambda level: calls.append(("engage", level)),
        lambda: calls.append(("release", None)),
    )
    # up to SATURATED: engaged at ELEVATED, re-fired with the new level at
    # SATURATED (per-level tuning), released when dropping below ELEVATED
    ctl = _scripted_controller([130] * 4 + [0] * 6, actions=[act])
    for _ in range(10):
        ctl.sample()
    assert calls == [
        ("engage", ELEVATED), ("engage", SATURATED),
        ("engage", ELEVATED), ("release", None),
    ]


def test_broken_action_does_not_wedge_control():
    def boom(level):
        raise RuntimeError("seam gone")

    act = BrownoutAction("boom", ELEVATED, boom, lambda: None)
    ctl = _scripted_controller([50] * 4, actions=[act])
    assert [ctl.sample() for _ in range(4)] == [0, 1, 1, 1]


def test_shutdown_releases_engaged_actions():
    calls: list = []
    act = BrownoutAction(
        "rec", ELEVATED, lambda level: calls.append("engage"), lambda: calls.append("release")
    )
    ctl = _scripted_controller([50] * 3, actions=[act])
    for _ in range(3):
        ctl.sample()
    assert calls == ["engage"]
    ctl.shutdown()
    assert calls == ["engage", "release"]


def test_default_actions_drive_every_seam():
    """The standard registry against duck-typed seam stubs: every action
    individually observable, per-level knob values applied."""

    class Tier:
        def __init__(self):
            self.cap = "unset"
            self.overload = (False, 0)
            self.queue = self

        def set_capacity_limit(self, cap):
            self.cap = cap

        def set_overload(self, active, retry_after_ms=0):
            self.overload = (active, retry_after_ms)

    class Fanout:
        floor = "unset"

        def set_conflation(self, floor):
            self.floor = floor

    class Node:
        damped = False

        def set_relay_damping(self, active):
            self.damped = active

    class Mining:
        grace = 0.0

        def set_template_deferral(self, grace_s):
            self.grace = grace_s

    tier, fanout, node, mining = Tier(), Fanout(), Node(), Mining()
    actions = {
        a.name: a
        for a in default_actions(
            tier=tier, broadcaster=fanout, node=node, mining=mining, knobs=BrownoutKnobs()
        )
    }
    assert set(actions) == {
        "dispatch_yield", "ingest_caps", "ingest_shed",
        "fanout_conflation", "inv_damping", "template_deferral",
    }

    actions["ingest_caps"].engage(ELEVATED)
    assert tier.cap == 2048
    actions["ingest_caps"].engage(CRITICAL)
    assert tier.cap == 32
    actions["ingest_caps"].release()
    assert tier.cap is None

    actions["ingest_shed"].engage(SATURATED)
    assert tier.overload == (True, 500)
    actions["ingest_shed"].engage(CRITICAL)
    assert tier.overload == (True, 2000)
    actions["ingest_shed"].release()
    assert tier.overload == (False, 0)

    actions["fanout_conflation"].engage(SATURATED)
    assert fanout.floor == 16
    actions["fanout_conflation"].release()
    assert fanout.floor is None

    actions["inv_damping"].engage(SATURATED)
    assert node.damped is True
    actions["inv_damping"].release()
    assert node.damped is False

    actions["template_deferral"].engage(CRITICAL)
    assert mining.grace == pytest.approx(2.0)
    actions["template_deferral"].release()
    assert mining.grace == 0.0


def test_sharded_fanout_conflation_targets_pressured_shards_only():
    """A broadcaster exposing shard_depths() gets the per-shard variant:
    engagement conflates only partitions at/above the ELEVATED depth
    trip; release clears every shard."""

    class ShardedFanout:
        def __init__(self):
            self.depths = [10, 100, 63, 64]
            self.floors: dict = {}

        def shard_depths(self):
            return self.depths

        def set_conflation(self, floor, shard=None):
            if shard is None:
                self.floors = {i: floor for i in range(len(self.depths))}
            else:
                self.floors[shard] = floor

    fanout = ShardedFanout()
    actions = {
        a.name: a
        for a in default_actions(broadcaster=fanout, knobs=BrownoutKnobs())
    }
    # default fanout_depth trip is (64, 256, 768): shards 1 and 3 qualify
    actions["fanout_conflation"].engage(ELEVATED)
    assert fanout.floors == {0: None, 1: 64, 2: None, 3: 64}
    actions["fanout_conflation"].engage(SATURATED)
    assert fanout.floors == {0: None, 1: 16, 2: None, 3: 16}
    actions["fanout_conflation"].release()
    assert fanout.floors == {i: None for i in range(4)}

    # a custom threshold table flows through build_controller's seam
    fanout2 = ShardedFanout()
    acts2 = {
        a.name: a
        for a in default_actions(
            broadcaster=fanout2,
            knobs=BrownoutKnobs(),
            thresholds={"fanout_depth": (11, 256, 768)},
        )
    }
    acts2["fanout_conflation"].engage(ELEVATED)
    assert fanout2.floors == {0: None, 1: 64, 2: 64, 3: 64}


# --- shedding seams ---------------------------------------------------------


def test_ingest_overload_rejects_but_resolves_ticket():
    tier = IngestTier(mining=None)
    before = SHED.snapshot().get("ingest_shed", 0)
    tier.set_overload(True, retry_after_ms=700)
    ticket = tier.submit(object())
    assert ticket.wait(1.0)
    assert ticket.status == REJECTED
    assert isinstance(ticket.error, MempoolError)
    assert ticket.error.code == "node-overloaded"
    assert ticket.error.retry_after_ms == 700
    assert SHED.snapshot()["ingest_shed"] == before + 1
    # the lost==0 invariant survives the shed: submitted==resolved
    stats = tier.stats()
    assert stats["lost"] == 0 and stats["overload_active"] is True
    # releasing the brownout restores normal queueing
    tier.set_overload(False)
    t2 = tier.submit(object())
    assert t2.status is None  # queued, not rejected up-front
    assert tier.queue.depth() == 1


def test_subscriber_conflation_merges_for_slow_consumer():
    parked = threading.Event()

    class Sink:
        def put(self, item, timeout=None):
            parked.set()
            time.sleep(min(float(timeout or 0.25), 0.25))
            raise queue.Full

    before = SHED.snapshot().get("fanout_conflation", 0)
    sub = Subscriber("slow", lambda n: b"x", Sink(), maxlen=64)
    try:
        sub.conflate_floor = 1
        sub.offer(Notification("utxos-changed", {"added": [1], "removed": []}), time.perf_counter_ns())
        assert parked.wait(2.0)  # sender picked up event 1 and parked on the sink
        for i in (2, 3, 4, 5):
            sub.offer(
                Notification("utxos-changed", {"added": [i], "removed": [i * 10]}),
                time.perf_counter_ns(),
            )
        # events 2..5 conflated into ONE pending merged diff, in order
        assert sub.queue_depth() == 1
        assert sub.conflated == 3
        with sub._lock:
            merged = sub._dq[-1][0]
        assert merged.data["added"] == [2, 3, 4, 5]
        assert merged.data["removed"] == [20, 30, 40, 50]
        assert SHED.snapshot()["fanout_conflation"] == before + 3
    finally:
        sub.stop()


def test_template_deferral_serves_stale_within_grace():
    before = SHED.snapshot().get("template_deferral", 0)
    tc = TemplateCache(lifetime=1.0, debounce=0.0)
    tc.set("TEMPLATE")
    tc.mark_dirty()
    # normal behavior: dirty past debounce -> rebuild (miss)
    assert tc.get() is None
    # CRITICAL brownout: same staleness now serves, and the shed is counted
    tc.defer_grace = 5.0
    assert tc.get() == "TEMPLATE"
    assert SHED.snapshot()["template_deferral"] == before + 1
    # bounded staleness: past lifetime + grace the template is gone
    tc.created = time.monotonic() - 10.0
    assert tc.get() is None
    # block acceptance clears unconditionally, grace or not
    tc.set("T2")
    tc.clear()
    assert tc.get() is None


def test_relay_damping_suppresses_tx_inv_only():
    from kaspa_tpu.p2p.node import Node

    class Peer:
        def __init__(self):
            self.sent = []
            self.known_txs = set()
            self.known_blocks = set()

        def send(self, msg, payload):
            self.sent.append(msg)

    class Tx:
        def id(self):
            return b"t" * 32

    node = Node.__new__(Node)  # seam test: no consensus wiring needed
    node.peers = [Peer()]
    node.relay_damping = False
    before = SHED.snapshot().get("inv_damping", 0)
    node.broadcast_tx(Tx())
    assert node.peers[0].sent  # undamped: INV went out
    node.set_relay_damping(True)
    node.peers[0].sent.clear()
    node.broadcast_tx(Tx())
    assert node.peers[0].sent == []  # damped: suppressed, counted as shed
    assert SHED.snapshot()["inv_damping"] == before + 1
    node.set_relay_damping(False)


def test_rpc_wire_carries_overload_code_and_retry_hint():
    from kaspa_tpu.node.daemon import ConnectionPump

    class StubDaemon:
        def dispatch(self, method, params):
            raise MempoolError(
                "node overloaded, retry later", code="node-overloaded", retry_after_ms=750
            )

    pump = ConnectionPump(StubDaemon(), io.BytesIO(), "test-pump")
    try:
        raw = pump.handle_request(
            json.dumps({"id": 1, "method": "submitTransaction", "params": {}}).encode()
        )
        resp = json.loads(raw)
        assert resp["error_code"] == "node-overloaded"
        assert resp["retryAfterMs"] == 750
    finally:
        pump.stop.set()
        pump.outq.put(None)
