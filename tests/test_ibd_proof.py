"""Proof-based IBD over the flow layer (in-process transport).

A fresh node joining a network whose pruning point moved past genesis
cannot relay-sync (history below the donor's pruning point is gone); it
must negotiate, download proof + trusted data + PP UTXO chunks, bootstrap
a staging consensus, sync the remaining blocks into it, and atomically
swap.  Mirrors flows/src/ibd/flow.rs IbdType::DownloadHeadersProof.
"""

from __future__ import annotations

import random

import pytest

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.params import GenesisBlock, Params
from kaspa_tpu.p2p.node import Node, connect
from kaspa_tpu.sim.simulator import Miner


def _prune_params() -> Params:
    genesis = GenesisBlock(hash=b"\x01" + b"\x00" * 31, bits=0x207FFFFF, timestamp=0)
    return Params.from_bps(
        "simnet-ibdproof",
        2,
        genesis,
        skip_proof_of_work=True,
        coinbase_maturity=8,
        merge_depth=15,
        finality_depth=30,
        pruning_depth=60,
        pruning_proof_m=10,
        difficulty_window_size=15,
        min_difficulty_window_size=5,
        difficulty_sample_rate=2,
        past_median_time_window_size=10,
        past_median_time_sample_rate=2,
    )


@pytest.fixture(scope="module")
def donor_node():
    params = _prune_params()
    donor = Node(Consensus(params), "donor")
    miner = Miner(0, random.Random(31))
    for _ in range(160):
        t = donor.consensus.build_block_template(miner.miner_data, [])
        donor.submit_block(t)
    assert donor.consensus.pruning_processor.pruning_point != params.genesis.hash
    return params, donor


def test_fresh_node_proof_syncs(donor_node):
    params, donor = donor_node
    g = params.genesis.hash
    joiner = Node(Consensus(params), "joiner")
    original_consensus = joiner.consensus
    pj, pd = connect(joiner, donor)
    joiner.ibd_from(pj)
    # the staging consensus must have been swapped in
    assert joiner.consensus is not original_consensus
    assert joiner.consensus.sink() == donor.consensus.sink()
    assert joiner.consensus.pruning_processor.pruning_point == donor.consensus.pruning_processor.pruning_point
    assert dict(joiner.consensus.utxo_set) == dict(donor.consensus.utxo_set)
    assert joiner.consensus.pruning_processor.check_pruning_utxo_commitment()
    # the joiner never learned the pruned deep history
    assert not joiner.consensus.storage.block_transactions.has(
        donor.consensus.pruning_processor.past_pruning_points[0]
    ) or donor.consensus.pruning_processor.past_pruning_points[0] == g
    # and can mine on top + relay back to the donor
    miner = Miner(1, random.Random(5))
    t = joiner.consensus.build_block_template(miner.miner_data, [])
    joiner.submit_block(t)
    assert donor.consensus.sink() == joiner.consensus.sink()


def test_wire_codec_roundtrip_ibd_messages(donor_node):
    """The new IBD frames survive the binary wire codec bit-for-bit."""
    from kaspa_tpu.p2p import wire
    from kaspa_tpu.p2p.node import (
        MSG_IBD_CHAIN_INFO,
        MSG_PP_UTXO_CHUNK,
        MSG_PRUNING_PROOF,
        MSG_TRUSTED_DATA,
    )

    params, donor = donor_node
    cons = donor.consensus
    ppm = cons.pruning_proof_manager

    def roundtrip(msg, payload):
        frame = wire.encode_frame(msg, payload)
        type_id, plen = wire.decode_frame(frame[:7])
        name, decoded = wire.decode_payload(type_id, frame[7 : 7 + plen])
        assert name == msg
        return decoded

    info = {
        "sink": cons.sink(),
        "sink_blue_work": cons.storage.ghostdag.get_blue_work(cons.sink()),
        "pruning_point": cons.pruning_processor.pruning_point,
    }
    assert roundtrip(MSG_IBD_CHAIN_INFO, info) == info

    proof = ppm.build_proof()
    dec = roundtrip(MSG_PRUNING_PROOF, proof)
    assert [[h.hash for h in lvl] for lvl in dec] == [[h.hash for h in lvl] for lvl in proof]

    td = ppm.get_trusted_data()
    dt = roundtrip(MSG_TRUSTED_DATA, td)
    assert dt.pruning_point == td.pruning_point
    assert dt.past_pruning_points == td.past_pruning_points
    assert {h.hash for h in dt.headers} == {h.hash for h in td.headers}
    assert dt.ghostdag.keys() == td.ghostdag.keys()
    for h in td.ghostdag:
        assert dt.ghostdag[h].blue_work == td.ghostdag[h].blue_work
        assert dt.ghostdag[h].selected_parent == td.ghostdag[h].selected_parent
    assert dt.statuses == td.statuses
    assert dt.reach_mergesets == td.reach_mergesets
    assert dt.bodies.keys() == td.bodies.keys()
    assert dt.daa_excluded == td.daa_excluded
    assert dt.depth == td.depth
    assert dt.pruning_samples == td.pruning_samples
    assert dt.pp_windows == {k: list(v) for k, v in td.pp_windows.items()}

    items = sorted(
        cons.pruning_processor.pruning_utxo_set.items(),
        key=lambda kv: (kv[0].transaction_id, kv[0].index),
    )[:5]
    chunk = {"offset": 0, "pairs": items, "done": True}
    got = roundtrip(MSG_PP_UTXO_CHUNK, chunk)
    assert got["offset"] == 0 and got["done"] is True
    assert got["pairs"] == items


def test_synced_node_uses_plain_relay_catchup(donor_node):
    """A node already holding the donor's pruning point takes the relay
    path (no staging swap)."""
    params, donor = donor_node
    # clone the donor's state cheaply: proof-sync once, then fall behind
    behind = Node(Consensus(params), "behind")
    p1, _ = connect(behind, donor)
    behind.ibd_from(p1)
    assert behind.consensus.sink() == donor.consensus.sink()
    # donor mines a few more; `behind` is now simply behind (same pp epoch)
    miner = Miner(2, random.Random(6))
    target = behind.consensus
    for _ in range(5):
        t = donor.consensus.build_block_template(miner.miner_data, [])
        donor.consensus.validate_and_insert_block(t)
    p2, _ = connect(behind, donor)
    behind.ibd_from(p2)
    assert behind.consensus is target  # no swap happened
    assert behind.consensus.sink() == donor.consensus.sink()


def test_chunked_ibd_paginates(monkeypatch):
    """IBD streams bounded batches with continuation requests (flow.rs
    IBD_BATCH_SIZE): a 30-block sync at batch size 8 must take multiple
    chunks and still converge exactly."""
    import random

    from kaspa_tpu.consensus.consensus import Consensus
    from kaspa_tpu.consensus.params import simnet_params
    from kaspa_tpu.p2p import node as node_mod
    from kaspa_tpu.p2p.node import Node, connect
    from kaspa_tpu.sim.simulator import Miner

    monkeypatch.setattr(node_mod, "IBD_BATCH_SIZE", 8)
    params = simnet_params(bps=2)
    a = Node(Consensus(params), "chunk-a")
    b = Node(Consensus(params), "chunk-b")
    miner = Miner(0, random.Random(33))
    for _ in range(30):
        t = a.consensus.build_block_template(miner.miner_data, [])
        a.consensus.validate_and_insert_block(t)

    chunks = []
    orig = node_mod.Node._serve_antipast_chunk

    def counting(self, peer, low):
        chunks.append(low)
        return orig(self, peer, low)

    monkeypatch.setattr(node_mod.Node, "_serve_antipast_chunk", counting)

    pa, pb = connect(a, b)
    with b.lock:
        b.ibd_from(pb)
    assert b.consensus.sink() == a.consensus.sink()
    assert b.consensus.get_virtual_daa_score() == 30
    assert len(chunks) >= 3, f"expected multiple IBD chunks, got {len(chunks)}"
