"""Stratum bridge: protocol round-trip, share validation, vardiff, metrics.

Reference strategy: bridge/src/tests.rs + share_handler.rs — an in-process
stratum client drives subscribe/authorize/notify/submit against a
daemon-backed bridge over real TCP; share rejection paths (stale,
duplicate, low difficulty) and the vardiff adjustment loop are exercised
explicitly (vardiff with an injected clock for determinism).
"""

from __future__ import annotations

import json
import random
import socket

import pytest

from kaspa_tpu.bridge.stratum import (
    ShareHandler,
    StratumBridge,
    StratumServer,
    vardiff_compute_next_diff,
)
from kaspa_tpu.node.daemon import Daemon, parse_args
from kaspa_tpu.sim.simulator import Miner


class _StratumClient:
    def __init__(self, addr: str):
        host, port = addr.rsplit(":", 1)
        self.sock = socket.create_connection((host, int(port)), timeout=30)
        self.f = self.sock.makefile("rwb")
        self._id = 0
        self.notifications = []

    def call(self, method, params):
        self._id += 1
        self.f.write((json.dumps({"id": self._id, "method": method, "params": params}) + "\n").encode())
        self.f.flush()
        while True:
            msg = json.loads(self.f.readline())
            if msg.get("id") == self._id:
                return msg
            self.notifications.append(msg)

    def drain_notifications(self, until_method=None, limit=10):
        out = list(self.notifications)
        self.notifications.clear()
        while until_method and not any(m.get("method") == until_method for m in out) and limit:
            out.append(json.loads(self.f.readline()))
            limit -= 1
        return out

    def close(self):
        self.sock.close()


@pytest.fixture()
def rig(tmp_path):
    """Daemon + TCP stratum bridge, simnet (skip-PoW => every share solves)."""
    miner = Miner(0, random.Random(6))
    from kaspa_tpu.crypto.addresses import extract_script_pub_key_address

    pay = extract_script_pub_key_address(miner.spk, "kaspasim").to_string()
    args = parse_args(
        ["--appdir", str(tmp_path), "--rpclisten", "127.0.0.1:0",
         "--bps", "2", "--stratum", "127.0.0.1:0", "--stratum-pay-address", pay]
    )
    d = Daemon(args)
    d.start()
    yield d, d.stratum_server.address
    d.stop()


def test_stratum_mine_over_tcp(rig):
    d, addr = rig
    client = _StratumClient(addr)
    try:
        sub = client.call("mining.subscribe", ["kaspa-miner/1.0"])
        assert sub["error"] is None and sub["result"][1]
        auth = client.call("mining.authorize", ["worker1", "x"])
        assert auth["result"] is True
        notes = client.drain_notifications(until_method="mining.notify")
        methods = [m.get("method") for m in notes]
        assert "mining.set_difficulty" in methods and "mining.notify" in methods
        job = next(m for m in notes if m.get("method") == "mining.notify")["params"]
        job_id = job[0]

        # simnet skips PoW checks in consensus, but the bridge still runs
        # the real heavy-hash against the difficulty-1 share target
        # (DIFF1 = 2^255: ~half of random nonces qualify) — grind a few
        before = d.consensus.get_virtual_daa_score()
        good_nonce = None
        for nonce in range(1, 40):
            res = client.call("mining.submit", ["worker1", job_id, f"{nonce:016x}"])
            if res["error"] is None and res["result"] is True:
                good_nonce = nonce
                break
            assert res["error"][0] == 20  # only low-difficulty rejections
        assert good_nonce is not None, "no share qualified in 40 nonces"
        assert d.consensus.get_virtual_daa_score() == before + 1

        # duplicate share rejected
        dup = client.call("mining.submit", ["worker1", job_id, f"{good_nonce:016x}"])
        assert dup["error"] is not None and dup["error"][0] == 22

        # stale job rejected
        stale = client.call("mining.submit", ["worker1", "0000ffff", f"{2:016x}"])
        assert stale["error"] is not None and stale["error"][0] == 21

        # metrics exposition reflects the outcomes
        m = client.call("mining.get_metrics", [])["result"]
        assert "stratum_shares_accepted_total 1" in m
        assert "stratum_shares_duplicate_total 1" in m
        assert "stratum_shares_stale_total 1" in m
        assert "stratum_blocks_found_total 1" in m
        assert 'stratum_worker_difficulty{worker="worker1"}' in m
    finally:
        client.close()


def test_vardiff_adjusts_to_hashrate():
    """share_handler.rs vardiff: a too-fast worker gets a higher difficulty,
    a silent worker decays toward 1 — deterministic injected clock."""
    clock = [0.0]
    sh = ShareHandler(expected_shares_per_min=20.0, initial_difficulty=8.0, now=lambda: clock[0])

    # worker storms 60 shares in 36s => observed 100/min >> 20/min target
    for _ in range(60):
        sh.record_share("fast", "accepted")
    clock[0] = 36.0
    new = sh.maybe_adjust("fast")
    assert new is not None and new > 8.0
    assert sh.worker("fast").window_shares == 0  # window reset

    # worker with zero shares for 90s+ has its difficulty halved
    sh2 = ShareHandler(expected_shares_per_min=20.0, initial_difficulty=8.0, now=lambda: clock[0])
    sh2.worker("idle")
    clock[0] = 36.0 + 95.0
    sh2.worker("idle").window_start = 36.0
    new2 = sh2.maybe_adjust("idle")
    assert new2 is not None and new2 < 8.0

    # in-band rate leaves difficulty untouched
    sh3 = ShareHandler(expected_shares_per_min=20.0, initial_difficulty=8.0, now=lambda: clock[0])
    for _ in range(12):
        sh3.record_share("ok", "accepted")
    sh3.worker("ok").window_start = clock[0] - 36.0  # 12 shares/36s = 20/min
    assert sh3.maybe_adjust("ok") is None


def test_vardiff_compute_matches_reference_semantics():
    # below min elapsed / min shares: no adjustment
    assert vardiff_compute_next_diff(4.0, 2.0, 10.0, 20.0, True) is None
    # step clamps at 2x up and 0.5x down
    up = vardiff_compute_next_diff(4.0, 1000.0, 30.0, 20.0, False)
    assert up == pytest.approx(8.0)  # sqrt(ratio) clamped to 2.0
    down = vardiff_compute_next_diff(4.0, 3.0, 3600.0, 20.0, False)
    assert down == pytest.approx(2.0)  # clamped to 0.5x
    # pow2 clamp snaps toward powers of two, floor 1.0
    assert vardiff_compute_next_diff(4.0, 1000.0, 30.0, 20.0, True) == 8.0
    assert vardiff_compute_next_diff(1.0, 0.0, 95.0, 20.0, True) is None  # already at floor


def test_vardiff_hysteresis_no_clamp():
    """share_handler.rs:100-102: without pow2 clamping, adjustments smaller
    than 10% of the current difficulty are suppressed — a rate hovering just
    outside the dead band must not oscillate."""
    # ratio just under the lower band edge: sqrt(0.74) ≈ 0.86 → 14% change
    # clears the hysteresis and lowers difficulty
    adj = vardiff_compute_next_diff(100.0, 22.2, 60.0, 30.0, False)
    assert adj is not None and adj < 100.0
    # the guard fires when the diff-1.0 floor pulls the step back within
    # 10% of current: 1.05 * 0.5 floors to 1.0 → 4.8% change → held
    assert vardiff_compute_next_diff(1.05, 3.0, 3600.0, 20.0, False) is None
    # same slow-worker inputs at a larger current adjust normally
    assert vardiff_compute_next_diff(4.0, 3.0, 3600.0, 20.0, False) == pytest.approx(2.0)


def test_low_difficulty_share_rejected():
    """A share above the worker's target but below nothing is rejected 20."""
    from kaspa_tpu.consensus.params import simnet_params
    from kaspa_tpu.consensus.consensus import Consensus
    from kaspa_tpu.sim.simulator import Miner as M

    params = simnet_params(bps=2)
    c = Consensus(params)
    miner = M(0, random.Random(3))
    template = c.build_block_template(miner.miner_data, [])

    bridge = StratumBridge(
        lambda: template, lambda b: "utxo_valid", initial_difficulty=float(1 << 50)
    )
    # absurd difficulty => share target far below any real heavy-hash value,
    # but never below the (easy simnet) network target per the max() floor.
    # Force a hard network target to expose the share path:
    template.header.bits = 0x1D00FFFF  # bitcoin-ish hard target
    template.header.invalidate_cache()
    job_id, _pre, _ts = bridge.new_job()
    from kaspa_tpu.bridge.stratum import StratumError

    with pytest.raises(StratumError) as ei:
        bridge.submit("w", job_id, 12345)
    assert ei.value.code == 20
    assert bridge.state.shares_low_diff == 1
