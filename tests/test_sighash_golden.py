"""Sighash golden vectors (reference: consensus/core/src/hashing/sighash.rs tests).

Covers the full SigHashType matrix (ALL/NONE/SINGLE x ANYONECANPAY), payload/
gas/subnetwork coverage, v0 vs v1 compute-commit semantics, and the memoized
reused-values path.
"""

import copy
from dataclasses import replace

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.consensus.model import (
    SUBNETWORK_ID_NATIVE,
    ComputeCommit,
    ScriptPublicKey,
    Transaction,
    TransactionInput,
    TransactionOutpoint,
    TransactionOutput,
    UtxoEntry,
)

PREV_TX_ID = bytes.fromhex("880eb9819a31821d9d2399e2f35e2433b72637e393d71ecc9b8d0250f49153c3")
SPK1 = bytes.fromhex("208325613d2eeaf7176ac6c670b13c0043156c427438ed72d74b7800862ad884e8ac")
SPK2 = bytes.fromhex("20fcef4c106cf11135bbd70f02a726a92162d2fb8b22f0469126f800862ad884e8ac")

ALL = chash.SIG_HASH_ALL
NONE = chash.SIG_HASH_NONE
SINGLE = chash.SIG_HASH_SINGLE
ACP = chash.SIG_HASH_ANY_ONE_CAN_PAY


def _native_tx(version=0):
    def cc(i):
        if version == 0:
            return ComputeCommit.sigops(0)
        return ComputeCommit.budget([11, 22, 33][i])

    inputs = [TransactionInput(TransactionOutpoint(PREV_TX_ID, i), b"", i, cc(i)) for i in range(3)]
    outputs = [
        TransactionOutput(300, ScriptPublicKey(0, SPK2)),
        TransactionOutput(300, ScriptPublicKey(0, SPK1)),
    ]
    return Transaction(version, inputs, outputs, 1615462089000, SUBNETWORK_ID_NATIVE, 0, b"")


def _entries():
    return [
        UtxoEntry(100, ScriptPublicKey(0, SPK1), 0, False),
        UtxoEntry(200, ScriptPublicKey(0, SPK2), 0, False),
        UtxoEntry(300, ScriptPublicKey(0, SPK2), 0, False),
    ]


def _subnetwork_tx():
    tx = _native_tx()
    tx.subnetwork_id = bytes([1, 2, 3, 4, 5, 6, 7, 8, 9, 10] + [0] * 10)
    tx.gas = 250
    tx.payload = bytes([10, 11, 12, 13, 14, 15, 16, 17, 18, 19, 20])
    return tx


def _run(tx_factory, hash_type, input_index, action, expected):
    tx = tx_factory()
    entries = _entries()
    kind, arg = action
    if kind == "output":
        tx.outputs[arg].value = 100
    elif kind == "input":
        tx.inputs[arg].previous_outpoint = TransactionOutpoint(PREV_TX_ID, 2)
    elif kind == "budget":
        tx.inputs[arg].compute_commit = ComputeCommit.budget(1234)
    elif kind == "sigops":
        tx.inputs[arg].compute_commit = ComputeCommit.sigops(123)
    elif kind == "amount":
        entries[arg] = replace(entries[arg], amount=666)
    elif kind == "prev_spk":
        old = entries[arg].script_public_key
        entries[arg] = replace(entries[arg], script_public_key=ScriptPublicKey(old.version, old.script + bytes([1, 2, 3])))
    elif kind == "sequence":
        tx.inputs[arg].sequence = 12345
    elif kind == "payload":
        tx.payload = bytes([6, 6, 6, 4, 2, 0, 1, 3, 3, 7])
    elif kind == "gas":
        tx.gas = 1234
    elif kind == "subnetwork":
        tx.subnetwork_id = bytes([6, 6, 6, 4, 2, 0, 1, 3, 3, 7] + [0] * 10)
    reused = chash.SigHashReusedValues()
    got = chash.calc_schnorr_signature_hash(tx, entries, input_index, hash_type, reused)
    assert got.hex() == expected


NOOP = ("none", None)

VECTORS = [
    (_native_tx, ALL, 0, NOOP, "03b7ac6927b2b67100734c3cc313ff8c2e8b3ce3e746d46dd660b706a916b1f5"),
    (_native_tx, ALL, 0, ("input", 1), "a9f563d86c0ef19ec2e4f483901d202e90150580b6123c3d492e26e7965f488c"),
    (_native_tx, ALL, 0, ("budget", 1), "03b7ac6927b2b67100734c3cc313ff8c2e8b3ce3e746d46dd660b706a916b1f5"),
    (lambda: _native_tx(1), ALL, 0, ("sigops", 0), "5b2657524be672e019897646b56da3d192b453d78ae5e6e5c07f029a69f5f075"),
    (lambda: _native_tx(1), ALL, 0, ("sigops", 1), "5b2657524be672e019897646b56da3d192b453d78ae5e6e5c07f029a69f5f075"),
    (lambda: _native_tx(1), ALL, 0, ("budget", 0), "5b2657524be672e019897646b56da3d192b453d78ae5e6e5c07f029a69f5f075"),
    (lambda: _native_tx(1), ALL, 0, ("budget", 1), "5b2657524be672e019897646b56da3d192b453d78ae5e6e5c07f029a69f5f075"),
    (_native_tx, ALL, 0, ("output", 1), "aad2b61bd2405dfcf7294fc2be85f325694f02dda22d0af30381cb50d8295e0a"),
    (_native_tx, ALL, 0, ("sequence", 1), "0818bd0a3703638d4f01014c92cf866a8903cab36df2fa2506dc0d06b94295e8"),
    (_native_tx, ALL | ACP, 0, NOOP, "24821e466e53ff8e5fa93257cb17bb06131a48be4ef282e87f59d2bdc9afebc2"),
    (_native_tx, ALL | ACP, 0, ("input", 0), "d09cb639f335ee69ac71f2ad43fd9e59052d38a7d0638de4cf989346588a7c38"),
    (_native_tx, ALL | ACP, 0, ("input", 1), "24821e466e53ff8e5fa93257cb17bb06131a48be4ef282e87f59d2bdc9afebc2"),
    (_native_tx, ALL | ACP, 0, ("sequence", 1), "24821e466e53ff8e5fa93257cb17bb06131a48be4ef282e87f59d2bdc9afebc2"),
    (_native_tx, NONE, 0, NOOP, "38ce4bc93cf9116d2e377b33ff8449c665b7b5e2f2e65303c543b9afdaa4bbba"),
    (_native_tx, NONE, 0, ("output", 1), "38ce4bc93cf9116d2e377b33ff8449c665b7b5e2f2e65303c543b9afdaa4bbba"),
    (_native_tx, NONE, 0, ("sequence", 0), "d9efdd5edaa0d3fd0133ee3ab731d8c20e0a1b9f3c0581601ae2075db1109268"),
    (_native_tx, NONE, 0, ("sequence", 1), "38ce4bc93cf9116d2e377b33ff8449c665b7b5e2f2e65303c543b9afdaa4bbba"),
    (_native_tx, NONE | ACP, 0, NOOP, "06aa9f4239491e07bb2b6bda6b0657b921aeae51e193d2c5bf9e81439cfeafa0"),
    (_native_tx, NONE | ACP, 0, ("amount", 0), "f07f45f3634d3ea8c0f2cb676f56e20993edf9be07a83bf0dfdb3debcf1441bf"),
    (_native_tx, NONE | ACP, 0, ("prev_spk", 0), "20a525c54dc33b2a61201f05233c086dbe8e06e9515775181ed96550b4f2d714"),
    (_native_tx, SINGLE, 0, NOOP, "44a0b407ff7b239d447743dd503f7ad23db5b2ee4d25279bd3dffaf6b474e005"),
    (_native_tx, SINGLE, 0, ("output", 1), "44a0b407ff7b239d447743dd503f7ad23db5b2ee4d25279bd3dffaf6b474e005"),
    (_native_tx, SINGLE, 0, ("sequence", 0), "83796d22879718eee1165d4aace667bb6778075dab579c32c57be945f466a451"),
    (_native_tx, SINGLE, 0, ("sequence", 1), "44a0b407ff7b239d447743dd503f7ad23db5b2ee4d25279bd3dffaf6b474e005"),
    (_native_tx, SINGLE, 2, NOOP, "022ad967192f39d8d5895d243e025ec14cc7a79708c5e364894d4eff3cecb1b0"),
    (_native_tx, SINGLE, 2, ("output", 1), "022ad967192f39d8d5895d243e025ec14cc7a79708c5e364894d4eff3cecb1b0"),
    (_native_tx, SINGLE | ACP, 0, NOOP, "43b20aba775050cf9ba8d5e48fc7ed2dc6c071d23f30382aea58b7c59cfb8ed7"),
    (_native_tx, SINGLE | ACP, 2, NOOP, "846689131fb08b77f83af1d3901076732ef09d3f8fdff945be89aa4300562e5f"),
    (_native_tx, ALL, 0, ("payload", None), "72ea6c2871e0f44499f1c2b556f265d9424bfea67cca9cb343b4b040ead65525"),
    (_subnetwork_tx, ALL, 0, NOOP, "b2f421c933eb7e1a91f1d9e1efa3f120fe419326c0dbac487752189522550e0c"),
    (_subnetwork_tx, ALL, 0, ("payload", None), "12ab63b9aea3d58db339245a9b6e9cb6075b2253615ce0fb18104d28de4435a1"),
    (_subnetwork_tx, ALL, 0, ("gas", None), "2501edfc0068d591160c4bd98646c6e6892cdc051182a8be3ccd6d67f104fd17"),
    (_subnetwork_tx, ALL, 0, ("subnetwork", None), "a5d1230ede0dfcfd522e04123a7bcd721462fed1d3a87352031a4f6e3c4389b6"),
]


def test_sighash_golden_vectors():
    for i, (factory, ht, idx, action, expected) in enumerate(VECTORS):
        _run(factory, ht, idx, action, expected)


def test_ecdsa_sighash_is_domain_prefixed_sha256():
    import hashlib

    tx = _native_tx()
    entries = _entries()
    reused = chash.SigHashReusedValues()
    schnorr = chash.calc_schnorr_signature_hash(tx, entries, 0, ALL, reused)
    ecdsa = chash.calc_ecdsa_signature_hash(tx, entries, 0, ALL, chash.SigHashReusedValues())
    dom = hashlib.sha256(b"TransactionSigningHashECDSA").digest()
    assert ecdsa == hashlib.sha256(dom + schnorr).digest()


def test_reused_values_memoization():
    tx = _native_tx()
    entries = _entries()
    reused = chash.SigHashReusedValues()
    h0 = chash.calc_schnorr_signature_hash(tx, entries, 0, ALL, reused)
    assert reused.previous_outputs_hash is not None  # memoized after first input
    h0b = chash.calc_schnorr_signature_hash(tx, entries, 0, ALL, reused)
    assert h0 == h0b
