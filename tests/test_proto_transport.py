"""Two in-process nodes over the protobuf/gRPC wire: handshake + relay.

The same scenario runs over the custom frame codec and the protobuf codec;
the resulting app-level state (negotiated tier, sink, DAA score, block
availability) must be identical — the wire is a pluggable serialization,
never a behavior change.
"""

from __future__ import annotations

import random
import time

import pytest

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.params import simnet_params
from kaspa_tpu.consensus.processes.coinbase import MinerData
from kaspa_tpu.p2p.node import Node
from kaspa_tpu.p2p.transport import P2PServer, connect_outbound, get_codec
from kaspa_tpu.sim.simulator import Miner


def _mine(node: Node, n: int, t0: int = 10_000) -> list:
    miner = Miner(0, random.Random(5))
    out = []
    for i in range(n):
        with node.lock:
            t = node.consensus.build_block_template(
                MinerData(miner.spk, b""), [], timestamp=t0 + 600 * i
            )
            node.submit_block(t)
        out.append(t)
    return out


def _wait(predicate, timeout: float = 20.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def _run_scenario(codec_name: str) -> dict:
    """Handshake two socket-connected nodes, relay blocks, snapshot state."""
    params = simnet_params(bps=2)
    a = Node(Consensus(params), f"donor-{codec_name}")
    b = Node(Consensus(params), f"joiner-{codec_name}")
    server = P2PServer(a, port=0, codec=get_codec(codec_name))
    server.start()
    try:
        out_peer = connect_outbound(b, server.address, codec=get_codec(codec_name))
        assert _wait(lambda: a.peers and a.peers[0].handshaken), "inbound handshake"
        in_peer = a.peers[0]

        blocks = _mine(a, 6)
        want_sink = blocks[-1].hash

        def synced():
            with b.lock:
                return b.consensus.sink() == want_sink

        assert _wait(synced), f"block relay over {codec_name} wire did not converge"

        with b.lock:
            state = {
                "tier_out": out_peer.protocol_version,
                "tier_in": in_peer.protocol_version,
                "sink": b.consensus.sink(),
                "daa": b.consensus.get_virtual_daa_score(),
                "has_blocks": [b.consensus.reachability.has(blk.hash) for blk in blocks],
            }
        return state
    finally:
        server.stop()
        for peer in list(a.peers) + list(b.peers):
            peer.close()


@pytest.mark.parametrize("codec_name", ["custom", "proto"])
def test_handshake_and_block_relay(codec_name):
    state = _run_scenario(codec_name)
    assert state["tier_out"] == 10 and state["tier_in"] == 10
    assert all(state["has_blocks"])


def test_proto_wire_state_identical_to_custom_wire():
    """The acceptance bar: the proto transport produces bit-identical
    app-level state to the custom wire for the same scenario."""
    assert _run_scenario("custom") == _run_scenario("proto")


def test_codec_selector_rejects_unknown_wire():
    with pytest.raises(ValueError):
        get_codec("carrier-pigeon")


def test_daemon_flag_selects_proto_wire(tmp_path):
    """Two OS-process daemons both launched with --p2p-proto handshake and
    relay over the protobuf wire — the flag is runtime wire selection."""
    import os
    import subprocess
    import sys

    from kaspa_tpu.node.daemon import rpc_call
    from kaspa_tpu.wallet.account import Account

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def free_ports(n):
        import socket

        socks = [socket.socket() for _ in range(n)]
        for s in socks:
            s.bind(("127.0.0.1", 0))
        ports = [s.getsockname()[1] for s in socks]
        for s in socks:
            s.close()
        return ports

    def spawn(name, rpc_port, p2p_port, connect=None):
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        env["KASPA_TPU_PLATFORM"] = "cpu"
        argv = [
            sys.executable, "-m", "kaspa_tpu.node",
            "--appdir", str(tmp_path / name),
            "--rpclisten", f"127.0.0.1:{rpc_port}",
            "--listen", f"127.0.0.1:{p2p_port}",
            "--bps", "2",
            "--p2p-proto",
        ]
        if connect:
            argv += ["--connect", connect]
        return subprocess.Popen(argv, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)

    def wait_rpc(addr, timeout=90.0):
        deadline = time.monotonic() + timeout
        last = None
        while time.monotonic() < deadline:
            try:
                return rpc_call(addr, "getServerInfo")
            except Exception as e:  # noqa: BLE001
                last = e
                time.sleep(0.3)
        raise TimeoutError(f"rpc at {addr} not up: {last}")

    rpc_a, p2p_a, rpc_b = free_ports(3)
    addr_a, addr_b = f"127.0.0.1:{rpc_a}", f"127.0.0.1:{rpc_b}"
    pay = Account.from_seed(b"\x02" * 32, prefix="kaspasim").addresses()[0]
    proc_a = proc_b = None
    try:
        proc_a = spawn("a", rpc_a, p2p_a)
        wait_rpc(addr_a)
        for _ in range(4):
            t = rpc_call(addr_a, "getBlockTemplate", {"payAddress": pay})
            rpc_call(addr_a, "submitBlockByTemplateHash", {"hash": t["block_hash"]})
        sink_a = rpc_call(addr_a, "getBlockDagInfo")["sink"]

        proc_b = spawn("b", rpc_b, 0, connect=f"127.0.0.1:{p2p_a}")
        wait_rpc(addr_b)
        assert _wait(
            lambda: rpc_call(addr_b, "getBlockDagInfo")["sink"] == sink_a, timeout=120
        ), "IBD over --p2p-proto wire did not converge"

        # relay direction B -> A over the proto wire
        t = rpc_call(addr_b, "getBlockTemplate", {"payAddress": pay})
        rpc_call(addr_b, "submitBlockByTemplateHash", {"hash": t["block_hash"]})
        sink_b = rpc_call(addr_b, "getBlockDagInfo")["sink"]
        assert _wait(
            lambda: rpc_call(addr_a, "getBlockDagInfo")["sink"] == sink_b, timeout=60
        ), "relay over --p2p-proto wire failed"
    finally:
        for proc in (proc_a, proc_b):
            if proc is not None:
                proc.terminate()
                try:
                    proc.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    proc.kill()
