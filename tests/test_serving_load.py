"""The 50k-subscriber load harness's building blocks: fd-budget preflight,
the shared sender pool, the virtual-subscriber load generator (zipf scopes,
memory + datagram-wire sinks, exact-quantile lag recorder), and a scaled-
down end-to-end run of tools/serving_load.py (slow lane)."""

from __future__ import annotations

import json
import os
import queue
import random
import subprocess
import sys
import time
from time import perf_counter_ns

import pytest

from kaspa_tpu.notify.notifier import Notification
from kaspa_tpu.serving import SenderPool, Subscriber
from kaspa_tpu.serving.loadgen import AddressUniverse, LagRecorder, LoadGen
from kaspa_tpu.utils import fdbudget

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _wait_until(cond, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


# ---------------------------------------------------------------------------
# fd-budget preflight
# ---------------------------------------------------------------------------


def test_fd_budget_reports_limit_and_usage():
    b = fdbudget.budget()
    assert set(b) == {"limit", "in_use", "headroom", "available"}
    assert b["limit"] > 0
    assert b["in_use"] > 0  # this process certainly has stdio open
    assert b["available"] <= b["limit"] - b["in_use"]


def test_fd_preflight_passes_and_fails_with_remedy():
    ok = fdbudget.preflight(1, what="one socketpair end")
    assert ok["available"] >= 1
    need = 10**9
    with pytest.raises(fdbudget.FdBudgetError) as ei:
        fdbudget.preflight(need, what="an impossible wire cohort")
    msg = str(ei.value)
    assert "ulimit -n" in msg  # the remedy, spelled out
    assert "an impossible wire cohort" in msg
    assert str(need) in msg


# ---------------------------------------------------------------------------
# lag recorder + zipf universe
# ---------------------------------------------------------------------------


def test_lag_recorder_exact_percentiles_and_ring_overwrite():
    rec = LagRecorder(cap=1000)
    for v in range(1, 101):
        rec.record(float(v))
    p = rec.percentiles()
    assert p["count"] == 100
    assert p["max"] == 100.0
    assert p["p50"] == 51.0  # exact rank over the sorted samples
    assert p["p99"] == 100.0
    small = LagRecorder(cap=4)
    for v in range(10):
        small.record(float(v))
    assert small.count == 10
    assert len(small.samples) == 4  # ring: oldest overwritten past the cap
    assert small.percentiles()["max"] == 9.0
    small.reset()
    assert small.percentiles() == {"count": 0, "p50": 0.0, "p99": 0.0, "p999": 0.0}


def test_address_universe_zipf_skew_and_determinism():
    uni = AddressUniverse(2000, s=1.05, seed=3)
    a = uni.sample_hot(random.Random(11), 500)
    b = uni.sample_hot(random.Random(11), 500)
    assert a == b  # fixed seed -> identical draws
    assert all(0 <= i < 2000 for i in a)
    hot_mean = sum(a) / len(a)
    uniform_mean = sum(uni.sample_uniform(random.Random(11), 500)) / 500
    # popularity sampling concentrates far below the uniform mean rank
    assert hot_mean < uniform_mean * 0.5


# ---------------------------------------------------------------------------
# shared sender pool
# ---------------------------------------------------------------------------


def test_sender_pool_delivers_everything_in_order():
    pool = SenderPool(workers=2, batch=4)
    sinks = [queue.Queue() for _ in range(3)]
    subs = [
        Subscriber(f"pooled-{i}", lambda n: str(n.data["n"]).encode(), sinks[i], pool=pool)
        for i in range(3)
    ]
    total = 40
    try:
        assert all(s._thread is None for s in subs)  # no thread per consumer
        for i in range(total):
            for s in subs:
                s.offer(Notification("block-added", {"n": i}), perf_counter_ns())
        for i, s in enumerate(subs):
            got = [sinks[i].get(timeout=10) for _ in range(total)]
            assert got == [str(j).encode() for j in range(total)]
            assert _wait_until(lambda s=s: s.delivered == total)
        assert pool.pending() == 0
    finally:
        for s in subs:
            s.close()
        pool.close()


def test_sender_pool_offer_after_drain_rekicks():
    pool = SenderPool(workers=1, batch=8)
    sink: queue.Queue = queue.Queue()
    sub = Subscriber("rekick", lambda n: str(n.data["n"]).encode(), sink, pool=pool)
    try:
        for round_no in range(5):  # each round fully drains before the next
            sub.offer(Notification("block-added", {"n": round_no}), perf_counter_ns())
            assert sink.get(timeout=10) == str(round_no).encode()
            assert _wait_until(lambda: not sub._scheduled)
    finally:
        sub.close()
        pool.close()


# ---------------------------------------------------------------------------
# loadgen end to end (small population, memory + wire sinks)
# ---------------------------------------------------------------------------


def test_loadgen_small_population_with_wire_cohort():
    fdbudget.preflight(12, what="loadgen test wire cohort")
    lg = LoadGen(seed=3, addresses=400, sub_maxlen=256, pool_workers=2)
    try:
        lg.ramp_to(120, wire=6)
        assert len(lg.subscribers) == 120
        lg.ramp_to(150)  # second ramp grows, never shrinks
        assert len(lg.subscribers) == 150
        lg.drive(6, pace_hz=0.0, size=16, hot_frac=0.25)
        assert lg.drain(timeout=30.0)
        assert lg.dropped() == 0
        assert lg.disconnects == 0
        delivered = lg.delivered()
        assert delivered > 0
        p = lg.recorder.percentiles()
        assert p["count"] == delivered  # last-hop sample per delivery
        assert 0.0 < p["p50"] <= p["p99"] <= p["p999"] <= p["max"]
        assert lg.wire_reader is not None and lg.wire_reader.received > 0
        assert lg.fanout_busy_ns() > 0
        marker = lg.reset_window()  # window reset: recorder drops, counters snapshot
        assert lg.recorder.count == 0
        assert marker["delivered"] == delivered
    finally:
        lg.close()


def test_serving_preflight_accounts_shard_crews_and_sockets():
    b = fdbudget.serving_preflight(shards=4, pool_workers=2, wire_cohort=3)
    assert b["worker_slots"] == 8  # 4 crews x 2 workers
    assert b["socket_fds"] == 6    # socketpair per wire subscriber
    assert b["required"] == 14
    assert b["shards"] == 4
    # shards=0 still budgets one crew (the single-fanout shared pool)
    assert fdbudget.serving_preflight(shards=0, pool_workers=2, wire_cohort=0)["worker_slots"] == 2
    with pytest.raises(fdbudget.FdBudgetError) as ei:
        fdbudget.serving_preflight(shards=10**6, pool_workers=3, wire_cohort=0)
    assert "shard" in str(ei.value)


def test_loadgen_sharded_small_population():
    """shards > 1 swaps in the ShardedBroadcaster (per-shard pools, same
    drain seam) with zero call-site changes in the harness."""
    from kaspa_tpu.serving.shards import ShardedBroadcaster

    lg = LoadGen(seed=3, addresses=400, sub_maxlen=256, pool_workers=2, shards=3)
    try:
        assert isinstance(lg.broadcaster, ShardedBroadcaster)
        assert lg.pool is None  # crews are per shard
        lg.ramp_to(120, wire=4)
        # every subscriber carries its shard binding and its shard's pool
        for s in lg.subscribers:
            assert s.shard == lg.broadcaster.shard_of(s.name)
            assert s._pool is lg.broadcaster.sender_pool_for(s.name)
        lg.drive(6, pace_hz=0.0, size=16, hot_frac=0.25)
        assert lg.drain(timeout=30.0)
        assert lg.dropped() == 0
        assert lg.disconnects == 0
        assert lg.delivered() > 0
        assert lg.recorder.percentiles()["count"] == lg.delivered()
        assert lg.wire_reader is not None and lg.wire_reader.received > 0
        assert lg.fanout_busy_ns() > 0
        assert lg.broadcaster.pending() == 0
    finally:
        lg.close()


def test_loadgen_sharded_matches_single_fanout_deliveries():
    """Same seed, same drive: the sharded tier delivers exactly the same
    number of notifications as the single fanout (routing identity at the
    population level; byte identity is covered by serving/check.py)."""
    counts = []
    for shards in (0, 4):
        lg = LoadGen(seed=13, addresses=300, sub_maxlen=512, pool_workers=2, shards=shards)
        try:
            lg.ramp_to(80)
            lg.drive(5, pace_hz=0.0, size=12, hot_frac=0.25)
            assert lg.drain(timeout=30.0)
            counts.append(lg.delivered())
        finally:
            lg.close()
    assert counts[0] == counts[1] > 0


def test_loadgen_deterministic_scopes():
    a = LoadGen(seed=9, addresses=300)
    b = LoadGen(seed=9, addresses=300)
    try:
        a.ramp_to(40)
        b.ramp_to(40)
        scopes_a = [s.subscriptions.get("utxos-changed") for s in a.subscribers]
        scopes_b = [s.subscriptions.get("utxos-changed") for s in b.subscribers]
        assert scopes_a == scopes_b
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# the harness itself (scaled down; slow lane)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_serving_load_harness_small_run(tmp_path):
    out = tmp_path / "SERVING_LOAD.json"
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO_ROOT, "tools", "serving_load.py"),
            "--subscribers", "800", "--addresses", "800",
            "--overhead-population", "600", "--overhead-events", "40",
            "--events-per-stage", "6", "--saturation-events", "4",
            "--out", str(out),
        ],
        cwd=REPO_ROOT, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=600, env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    data = json.loads(out.read_text())
    assert summary["population"] == 800
    assert data["run_meta"]["fd_budget"]["limit"] > 0
    assert [s["population"] for s in data["stages"]][-1] == 800
    assert data["gates"]["population"]["ok"]
    assert data["gates"]["drained"]["ok"]
    assert data["gates"]["drop_rate_nominal"]["ok"]
    assert data["gates"]["p99_bounded"]["ok"], data["gates"]
    assert len(data["lag_vs_population"]) == len(data["stages"])
    assert data["saturation"]["deliveries_per_s"] > 0
    # the overhead A/B is timing-sensitive on a loaded host: require the
    # measurement to exist; the strict >=0.98x gate is enforced by the
    # roundcheck serving_load lane, which runs the harness standalone
    assert data["overhead"]["tracing_on_dps"] > 0
    assert data["overhead"]["tracing_off_dps"] > 0
    if proc.returncode != 0:
        failed = [k for k, g in data["gates"].items() if not g["ok"]]
        assert failed == ["overhead"], (failed, proc.stdout[-2000:])
