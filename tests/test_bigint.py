"""Bit-exactness of the int32-limb big-integer engine vs python ints.

Covers all three field contexts (secp256k1 p and n, muhash u3072) across
random values, boundary values, and chained lazy-limb expressions —
the TPU analog of the reference's uint tests (math/src/uint.rs) and
muhash u3072 fuzz target (crypto/muhash/fuzz/fuzz_targets/u3072.rs).
"""

import random

import jax.numpy as jnp
import pytest

from kaspa_tpu.ops import bigint as bi

CTXS = [bi.FP, bi.FN, bi.F3072]


def _vals(ctx, n=10, seed=0):
    rng = random.Random(seed)
    m = ctx.modulus
    edge = [0, 1, 2, m - 1, m - 2, ctx.c, ctx.c + 1, m // 2, (1 << ctx.bits) - 1 - ctx.c]
    return edge + [rng.randrange(m) for _ in range(n)]


@pytest.mark.parametrize("ctx", CTXS, ids=lambda c: c.name)
def test_mul_add_sub_canon(ctx):
    xs = _vals(ctx, seed=1)
    ys = list(reversed(xs))
    a = jnp.asarray(bi.ints_to_limbs(xs, ctx.W))
    b = jnp.asarray(bi.ints_to_limbs(ys, ctx.W))
    m = ctx.modulus
    assert bi.limbs_to_ints(bi.canon(ctx, bi.mul(ctx, a, b))) == [(x * y) % m for x, y in zip(xs, ys)]
    assert bi.limbs_to_ints(bi.canon(ctx, bi.add(ctx, a, b))) == [(x + y) % m for x, y in zip(xs, ys)]
    assert bi.limbs_to_ints(bi.canon(ctx, bi.sub(ctx, a, b))) == [(x - y) % m for x, y in zip(xs, ys)]
    assert bi.limbs_to_ints(bi.canon(ctx, bi.neg(ctx, a))) == [(-x) % m for x in xs]
    assert bi.limbs_to_ints(bi.canon(ctx, bi.mul_small(ctx, a, 21))) == [(21 * x) % m for x in xs]


@pytest.mark.parametrize("ctx", CTXS, ids=lambda c: c.name)
def test_chained_lazy_ops(ctx):
    xs = _vals(ctx, seed=2)
    ys = list(reversed(xs))
    a = jnp.asarray(bi.ints_to_limbs(xs, ctx.W))
    b = jnp.asarray(bi.ints_to_limbs(ys, ctx.W))
    t = bi.mul(ctx, bi.sub(ctx, a, b), bi.add(ctx, a, b))
    t = bi.sub(ctx, t, bi.mul(ctx, b, b))
    t = bi.add(ctx, t, bi.mul_small(ctx, a, -7))
    got = bi.limbs_to_ints(bi.canon(ctx, t))
    exp = [((x - y) * (x + y) - y * y - 7 * x) % ctx.modulus for x, y in zip(xs, ys)]
    assert got == exp


@pytest.mark.parametrize("ctx", [bi.FP, bi.FN], ids=lambda c: c.name)
def test_inverse(ctx):
    xs = [1, 2, 3, ctx.modulus - 1, 0xDEADBEEF123456789]
    a = jnp.asarray(bi.ints_to_limbs(xs, ctx.W))
    got = bi.limbs_to_ints(bi.canon(ctx, bi.inv(ctx, a)))
    assert got == [pow(x, -1, ctx.modulus) for x in xs]


@pytest.mark.parametrize("ctx", [bi.FP, bi.FN], ids=lambda c: c.name)
def test_batch_inverse_matches_fermat(ctx):
    """Batch-affine Montgomery inversion (one Fermat inversion + two
    scan passes of muls) must equal per-lane Fermat exactly; zero lanes
    pass through as 0 — including the lazy non-canonical zero (= m) —
    without poisoning the shared product chain."""
    m = ctx.modulus
    xs = [1, 2, 3, 0, m - 1, m, 0xDEADBEEF123456789, m // 2]
    a = jnp.asarray(bi.ints_to_limbs(xs, ctx.W))
    got = bi.limbs_to_ints(bi.canon(ctx, bi.inv_batch(ctx, a)))
    assert got == [pow(x, -1, m) if x % m else 0 for x in xs]
    # agrees lane-for-lane with the per-lane Fermat path on nonzero input
    nz = jnp.asarray(bi.ints_to_limbs([x for x in xs if x % m], ctx.W))
    assert bi.limbs_to_ints(bi.canon(ctx, bi.inv_batch(ctx, nz))) == bi.limbs_to_ints(
        bi.canon(ctx, bi.inv(ctx, nz))
    )


def test_batch_inverse_singleton():
    ctx = bi.FP
    a = jnp.asarray(bi.ints_to_limbs([7], ctx.W))
    assert bi.limbs_to_ints(bi.canon(ctx, bi.inv_batch(ctx, a))) == [pow(7, -1, ctx.modulus)]
    z = jnp.asarray(bi.ints_to_limbs([0], ctx.W))
    assert bi.limbs_to_ints(bi.canon(ctx, bi.inv_batch(ctx, z))) == [0]


def test_zero_and_eq():
    ctx = bi.FP
    a = jnp.asarray(bi.ints_to_limbs([0, ctx.modulus - 1, 5], ctx.W))
    b = jnp.asarray(bi.ints_to_limbs([ctx.modulus - 1, ctx.modulus - 1, 7], ctx.W))
    assert list(bi.is_zero(ctx, bi.sub(ctx, a, a))) == [True, True, True]
    assert list(bi.eq(ctx, a, b)) == [False, True, False]
    # p == 0 (mod p) via lazy representation of p itself
    p_limbs = jnp.asarray(bi.ints_to_limbs([ctx.modulus], ctx.W))
    assert list(bi.is_zero(ctx, p_limbs)) == [True]
