"""P2P relay/IBD + RPC service tests (daemon-integration style, in-process).

Reference strategy: testing/integration/src/{daemon_integration_tests,
rpc_tests}.rs — multiple full nodes wired in one process, driving mining,
relay, sync, and the RPC surface.
"""

import random

import pytest

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.params import simnet_params
from kaspa_tpu.crypto.addresses import Address, extract_script_pub_key_address
from kaspa_tpu.p2p import Node, connect
from kaspa_tpu.rpc import RpcCoreService
from kaspa_tpu.sim.simulator import Miner


@pytest.fixture()
def network():
    params = simnet_params(bps=2)
    a = Node(Consensus(params), "a")
    b = Node(Consensus(params), "b")
    c = Node(Consensus(params), "c")
    connect(a, b)
    connect(b, c)  # line topology: a <-> b <-> c
    rng = random.Random(23)
    miner = Miner(0, rng)
    return a, b, c, miner, rng


def _mine(node: Node, miner: Miner, n: int = 1):
    blocks = []
    for _ in range(n):
        t = node.consensus.build_block_template(miner.miner_data, [])
        node.submit_block(t)
        blocks.append(t)
    return blocks


def test_block_relay_propagates(network):
    a, b, c, miner, rng = network
    blocks = _mine(a, miner, 12)
    # every block must have reached c through b
    for blk in blocks:
        assert c.consensus.storage.block_transactions.has(blk.hash)
    assert a.consensus.sink() == b.consensus.sink() == c.consensus.sink()
    assert a.consensus.get_virtual_daa_score() == c.consensus.get_virtual_daa_score()


def test_tx_relay_and_mining_roundtrip(network):
    a, b, c, miner, rng = network
    _mine(a, miner, 14)  # mature some coinbases (simnet maturity = 8)
    # build a spend on node a and watch it reach node c's mempool
    from kaspa_tpu.consensus import hashing as chash
    from kaspa_tpu.consensus.model import Transaction, TransactionInput, TransactionOutput
    from kaspa_tpu.consensus.model.tx import SUBNETWORK_ID_NATIVE, ComputeCommit
    from kaspa_tpu.crypto import eclib
    from kaspa_tpu.txscript import standard

    view = a.consensus.get_virtual_utxo_view()
    pov = a.consensus.get_virtual_daa_score()
    chosen = None
    for op, e in a.consensus.utxo_set.items():
        if view.get(op) is not None and e.script_public_key == miner.spk and not (
            e.is_coinbase and e.block_daa_score + a.consensus.params.coinbase_maturity > pov
        ):
            chosen = (op, e)
            break
    assert chosen is not None
    op, e = chosen
    tx = Transaction(
        0,
        [TransactionInput(op, b"", 0, ComputeCommit.sigops(1))],
        [TransactionOutput(e.amount - 1000, miner.spk)],
        0,
        SUBNETWORK_ID_NATIVE,
        0,
        b"",
    )
    msg = chash.calc_schnorr_signature_hash(tx, [e], 0, chash.SIG_HASH_ALL, chash.SigHashReusedValues())
    sig = eclib.schnorr_sign(msg, miner.seckey, rng.randbytes(32))
    tx.inputs[0].signature_script = standard.schnorr_signature_script(sig, chash.SIG_HASH_ALL)

    a.submit_transaction(tx)
    assert b.mining.mempool.has(tx.id())
    assert c.mining.mempool.has(tx.id())

    # node c mines it; everyone converges and drops it from their mempool
    blk = c.consensus.build_block_template(miner.miner_data, [tx])
    c.submit_block(blk)
    assert a.consensus.storage.block_transactions.has(blk.hash)
    assert not a.mining.mempool.has(tx.id())
    assert not b.mining.mempool.has(tx.id())


def test_fresh_node_ibd(network):
    a, b, c, miner, rng = network
    _mine(a, miner, 10)
    fresh = Node(Consensus(a.consensus.params), "fresh")
    (pa, pf) = connect(a, fresh)
    fresh.ibd_from(fresh.peers[0])
    assert fresh.consensus.sink() == a.consensus.sink()
    assert fresh.consensus.get_virtual_daa_score() == a.consensus.get_virtual_daa_score()


def test_rpc_service_surface(network):
    a, b, c, miner, rng = network
    _mine(a, miner, 10)
    from kaspa_tpu.index import UtxoIndex

    rpc = RpcCoreService(a.consensus, a.mining, UtxoIndex(a.consensus), address_prefix="kaspasim")

    info = rpc.get_server_info()
    assert info.virtual_daa_score == a.consensus.get_virtual_daa_score()

    dag = rpc.get_block_dag_info()
    assert dag["block_count"] == 10
    assert dag["sink"] == a.consensus.sink().hex()

    blk = rpc.get_block(a.consensus.sink())
    assert blk["verbose"]["is_chain_block"]
    assert blk["header"]["blue_score"] == a.consensus.storage.ghostdag.get_blue_score(a.consensus.sink())

    # chain walk from genesis covers all chain blocks
    chain = rpc.get_virtual_chain_from_block(a.consensus.params.genesis.hash)
    assert dag["sink"] in chain["added_chain_blocks"][-1]

    # address-based queries through the utxoindex
    addr = extract_script_pub_key_address(miner.spk, "kaspasim").to_string()
    balance = rpc.get_balance_by_address(addr)
    assert balance > 0
    utxos = rpc.get_utxos_by_addresses([addr])
    assert sum(u["utxo_entry"]["amount"] for u in utxos) == balance
    assert rpc.get_coin_supply()["circulating_sompi"] >= balance

    # template + submit through RPC
    template = rpc.get_block_template(addr)
    assert rpc.submit_block(template) in ("utxo_valid", "utxo_pending")

    # metrics + notifications
    got = []
    lid = rpc.register_listener(got.append)
    rpc.start_notify(lid, "block-added")
    _mine(a, miner, 1)
    assert any(n.event_type == "block-added" for n in got)
    m = rpc.get_metrics()
    assert m["block_count"] == 12
