"""KIP-9 mass golden tests (vectors from consensus/core/src/mass/mod.rs tests)."""

import pytest

from kaspa_tpu.consensus.mass import (
    SOMPI_PER_KASPA,
    STORAGE_MASS_PARAMETER,
    MassCalculator,
    calc_storage_mass,
    transaction_estimated_serialized_size,
    utxo_plurality,
)
from kaspa_tpu.consensus.model import (
    ComputeCommit,
    ScriptPublicKey,
    Transaction,
    TransactionInput,
    TransactionOutpoint,
    TransactionOutput,
    UtxoEntry,
)
from kaspa_tpu.consensus.model.tx import SUBNETWORK_ID_NATIVE


def _tx_from_amounts(ins, outs):
    spk = ScriptPublicKey(0, b"")
    tx = Transaction(
        0,
        [TransactionInput(TransactionOutpoint(bytes([i]) * 32, 0), b"", 0, ComputeCommit.sigops(0)) for i in range(len(ins))],
        [TransactionOutput(v, spk) for v in outs],
        0,
        SUBNETWORK_ID_NATIVE,
        0,
        b"",
    )
    entries = [UtxoEntry(v, spk, 0, False) for v in ins]
    return tx, entries


def test_storage_mass_golden():
    """mass/mod.rs test_storage_mass vector-for-vector."""
    C = 10**12

    # 3:2 symmetric compound -> 0
    tx, entries = _tx_from_amounts([100, 200, 300], [300, 300])
    assert MassCalculator(0, 0, C).calc_contextual_masses(tx, entries) == 0

    # asymmetric outputs
    tx.outputs[0].value = 50
    tx.outputs[1].value = 550
    expected = C // 50 + C // 550 - 3 * (C // 200)
    assert MassCalculator(0, 0, C).calc_contextual_masses(tx, entries) == expected

    # more outs than ins at the C boundary
    base = 10_000 * SOMPI_PER_KASPA
    tx, entries = _tx_from_amounts([base, base, base * 2], [base] * 4)
    assert MassCalculator(0, 0, STORAGE_MASS_PARAMETER).calc_contextual_masses(tx, entries) == 4

    tx2, entries2 = _tx_from_amounts([base, base, base * 2], [10 * SOMPI_PER_KASPA, base, base, base])
    assert MassCalculator(0, 0, STORAGE_MASS_PARAMETER).calc_contextual_masses(tx2, entries2) == 1003

    # increase values over the limit -> 0
    tx3, entries3 = _tx_from_amounts([base, base, base * 2 + 4], [base + 1] * 4)
    assert MassCalculator(0, 0, STORAGE_MASS_PARAMETER).calc_contextual_masses(tx3, entries3) == 0

    # 2:2 relaxed formula
    tx, entries = _tx_from_amounts([100, 200], [50, 250])
    assert MassCalculator(0, 0, C).calc_contextual_masses(tx, entries) == 9_000_000_000
    tx.outputs[0].value = 100
    tx.outputs[1].value = 200
    assert MassCalculator(0, 0, C).calc_contextual_masses(tx, entries) == 0
    # 2:1
    tx.outputs.pop()
    tx.outputs[0].value = 50
    assert MassCalculator(0, 0, C).calc_contextual_masses(tx, entries) == 5_000_000_000


def test_utxo_plurality_boundaries():
    """mass/mod.rs verify_utxo_plurality_limits boundary asserts."""
    assert utxo_plurality(ScriptPublicKey(0, b""), False) == 1
    assert utxo_plurality(ScriptPublicKey(0, bytes(100 - 63)), False) == 1
    assert utxo_plurality(ScriptPublicKey(0, bytes(100 - 63 + 1)), False) == 2
    assert utxo_plurality(ScriptPublicKey(0, bytes(100 - 63)), True) == 2
    assert utxo_plurality(ScriptPublicKey(0, bytes(200 - 63 - 32)), True) == 2


def test_coinbase_mass_is_zero():
    from kaspa_tpu.consensus.model.tx import SUBNETWORK_ID_COINBASE

    cb = Transaction(0, [], [TransactionOutput(5, ScriptPublicKey(0, b"\x01"))], 0, SUBNETWORK_ID_COINBASE, 0, b"\x00" * 20)
    mc = MassCalculator()
    assert mc.calc_non_contextual_masses(cb).compute_mass == 0
    assert mc.calc_contextual_masses(cb, []) == 0


def test_compute_and_transient_mass_structure():
    tx, entries = _tx_from_amounts([1000], [500])
    tx.inputs[0] = TransactionInput(tx.inputs[0].previous_outpoint, b"\x00" * 65, 0, ComputeCommit.sigops(1))
    mc = MassCalculator(1, 10, STORAGE_MASS_PARAMETER)
    nc = mc.calc_non_contextual_masses(tx)
    size = transaction_estimated_serialized_size(tx)
    assert nc.transient_mass == size * 4
    assert nc.compute_mass == size * 1 + (2 + 0) * 10 + 1 * 1000  # size + spk bytes + 1 sigop


def test_wrong_mass_commitment_rejected_in_block():
    """A tx with an incorrect storage-mass commitment must disqualify its block."""
    import random

    from kaspa_tpu.consensus.consensus import Consensus
    from kaspa_tpu.sim.simulator import SimConfig, simulate

    res = simulate(SimConfig(bps=2, delay=0.5, num_miners=2, num_blocks=24, txs_per_block=2, seed=29))
    tx_block = next(b for b in res.blocks if len(b.transactions) > 1)
    assert any(t.storage_mass > 0 for t in tx_block.transactions[1:]), 'sim should commit nonzero storage mass'
    # replay with one commitment tampered: merkle must change (hash commits to
    # mass), so rebuild the merkle and expect chain disqualification
    from dataclasses import replace

    from kaspa_tpu.consensus.model.block import Block
    from kaspa_tpu.crypto import merkle as mk

    fresh = Consensus(res.params)
    for b in res.blocks:
        if b.hash == tx_block.hash:
            break
        fresh.validate_and_insert_block(b)
    import copy

    txs = copy.deepcopy(tx_block.transactions)
    txs[1].storage_mass += 7
    txs[1]._id_cache = None
    hdr = replace(tx_block.header, hash_merkle_root=mk.calc_hash_merkle_root(txs))
    hdr._hash_cache = None
    status = fresh.validate_and_insert_block(Block(hdr, txs))
    if status == "utxo_pending":
        assert not fresh._ensure_chain_utxo_valid(hdr.hash)
    else:
        assert status == "disqualified"
