"""Crash-safe persistence: restart-resume of the full consensus state.

The reference's story is typed RocksDB columns + atomic WriteBatches
(database/src/access.rs, consensus/src/consensus/storage.rs); here the
native CRC-framed KV engine backs write-through stores flushed one atomic
batch per block.  These tests cover: clean restart equivalence, replay
continuation across a restart, and kill-mid-replay recovery (a consistent
prefix survives, the remainder re-applies to the identical state).
"""

import os
import signal
import subprocess
import sys
import textwrap

import pytest

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.sim.simulator import SimConfig, simulate
from kaspa_tpu.storage.kv import KvStore


@pytest.fixture(scope="module")
def sim_result():
    cfg = SimConfig(bps=2, delay=1.0, num_miners=3, num_blocks=24, txs_per_block=2, seed=23)
    return simulate(cfg)


def _state_fingerprint(c: Consensus):
    return (
        c.sink(),
        c.get_virtual_daa_score(),
        sorted(c.tips),
        c.virtual_state.parents,
        c.virtual_state.accepted_tx_ids,
        sorted((op.transaction_id, op.index, e.amount) for op, e in c.get_virtual_utxo_view().iter_all()),
        c.multisets[c.sink()].finalize(),
    )


def test_restart_resumes_identical_state(tmp_path, sim_result):
    path = str(tmp_path / "consensus.db")
    db = KvStore(path)
    c1 = Consensus(sim_result.params, db=db)
    for b in sim_result.blocks:
        c1.validate_and_insert_block(b)
    fp1 = _state_fingerprint(c1)
    db.close()

    db2 = KvStore(path)
    c2 = Consensus(sim_result.params, db=db2)
    assert _state_fingerprint(c2) == fp1
    db2.close()


def test_restart_mid_replay_then_continue(tmp_path, sim_result):
    path = str(tmp_path / "consensus.db")
    half = len(sim_result.blocks) // 2
    db = KvStore(path)
    c1 = Consensus(sim_result.params, db=db)
    for b in sim_result.blocks[:half]:
        c1.validate_and_insert_block(b)
    db.close()

    # restart and continue the replay to completion
    db2 = KvStore(path)
    c2 = Consensus(sim_result.params, db=db2)
    for b in sim_result.blocks[half:]:
        c2.validate_and_insert_block(b)
    assert c2.sink() == sim_result.sink
    assert c2.get_virtual_daa_score() == sim_result.virtual_daa_score
    db2.close()

    # a pure-memory replay must agree with the disk-backed one
    c3 = Consensus(sim_result.params)
    for b in sim_result.blocks:
        c3.validate_and_insert_block(b)
    assert c3.sink() == c2.sink()


_KILL_SCRIPT = textwrap.dedent(
    """
    import os, pickle, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from kaspa_tpu.utils import jax_setup; jax_setup.setup()
    from kaspa_tpu.consensus.consensus import Consensus
    from kaspa_tpu.storage.kv import KvStore

    path, blocks_pkl = sys.argv[1], sys.argv[2]
    with open(blocks_pkl, "rb") as f:
        params, blocks = pickle.load(f)
    db = KvStore(path)
    c = Consensus(params, db=db)
    for i, b in enumerate(blocks):
        c.validate_and_insert_block(b)
        print(f"inserted {i}", flush=True)
    """
)


def test_kill9_mid_replay_recovers(tmp_path, sim_result):
    """kill -9 the inserting process; reopen; the survivor is a consistent
    prefix and the remaining blocks replay to the same final state."""
    import pickle

    path = str(tmp_path / "consensus.db")
    blocks_pkl = str(tmp_path / "blocks.pkl")
    with open(blocks_pkl, "wb") as f:
        pickle.dump((sim_result.params, sim_result.blocks), f)
    script = str(tmp_path / "killme.py")
    with open(script, "w") as f:
        f.write(_KILL_SCRIPT)

    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, script, path, blocks_pkl],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
    )
    # wait until at least 6 blocks are in, then kill -9 mid-stride
    inserted = 0
    for line in proc.stdout:
        if line.startswith("inserted"):
            inserted += 1
            if inserted >= 6:
                os.kill(proc.pid, signal.SIGKILL)
                break
    proc.wait()
    assert inserted >= 6, f"inserter died early: {proc.stderr.read()}"

    db = KvStore(path)
    c = Consensus(sim_result.params, db=db)
    # the consensus must have recovered a nonempty prefix of the DAG
    recovered = {b.hash for b in sim_result.blocks if c.storage.statuses.get(b.hash) is not None}
    assert len(recovered) >= 1
    # re-apply every block (duplicates are no-ops) -> identical final state
    for b in sim_result.blocks:
        c.validate_and_insert_block(b)
    assert c.sink() == sim_result.sink
    assert c.get_virtual_daa_score() == sim_result.virtual_daa_score
    db.close()


_FAULT_KILL_SCRIPT = textwrap.dedent(
    """
    import os, pickle, signal, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from kaspa_tpu.utils import jax_setup; jax_setup.setup()
    from kaspa_tpu.consensus.consensus import Consensus
    from kaspa_tpu.resilience.faults import FAULTS, FaultInjected
    from kaspa_tpu.storage.kv import KvStore

    path, blocks_pkl = sys.argv[1], sys.argv[2]
    with open(blocks_pkl, "rb") as f:
        params, blocks = pickle.load(f)
    db = KvStore(path, native=False)
    c = Consensus(params, db=db)
    for i, b in enumerate(blocks):
        if i == 6:
            # arm a one-shot torn-append fault: the next journal flush
            # writes a deterministic prefix of its frame, then "crashes"
            FAULTS.configure({"storage.flush": {"mode": "partial", "after": 1, "max": 1}}, seed=13)
        try:
            c.validate_and_insert_block(b)
        except FaultInjected:
            # power loss at the torn write: die without any cleanup path
            print(f"faulted {i}", flush=True)
            os.kill(os.getpid(), signal.SIGKILL)
        print(f"inserted {i}", flush=True)
    """
)


def test_kill9_on_injected_partial_flush_recovers(tmp_path, sim_result):
    """A mid-batch torn append (injected storage.flush partial fault)
    followed by SIGKILL: the reopened store repairs the torn tail back to
    the last consistent frame and the full replay reconverges — the
    chaos-layer version of the kill-mid-replay test, with the crash point
    placed deterministically inside a journal write."""
    import pickle

    path = str(tmp_path / "consensus-fault.db")
    blocks_pkl = str(tmp_path / "blocks.pkl")
    with open(blocks_pkl, "wb") as f:
        pickle.dump((sim_result.params, sim_result.blocks), f)
    script = str(tmp_path / "killme-faulted.py")
    with open(script, "w") as f:
        f.write(_FAULT_KILL_SCRIPT)

    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, script, path, blocks_pkl],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, timeout=300, env=env,
    )
    assert proc.returncode == -signal.SIGKILL, f"expected SIGKILL exit: {proc.returncode}\n{proc.stderr}"
    lines = proc.stdout.splitlines()
    assert sum(1 for ln in lines if ln.startswith("inserted")) >= 6
    assert any(ln.startswith("faulted") for ln in lines), "fault never fired"
    assert os.path.getsize(path) > 0

    from kaspa_tpu.observability.core import REGISTRY

    repairs_before = REGISTRY.snapshot()["counters"].get("kv_journal_repairs", 0)
    db = KvStore(path, native=False)
    # replay repaired the torn tail left by the killed writer
    assert REGISTRY.snapshot()["counters"].get("kv_journal_repairs", 0) == repairs_before + 1
    c = Consensus(sim_result.params, db=db)
    recovered = {b.hash for b in sim_result.blocks if c.storage.statuses.get(b.hash) is not None}
    assert len(recovered) >= 1
    # re-apply every block (duplicates are no-ops) -> identical final state
    for b in sim_result.blocks:
        c.validate_and_insert_block(b)
    assert c.sink() == sim_result.sink
    assert c.get_virtual_daa_score() == sim_result.virtual_daa_score
    db.close()


def test_reachability_snapshot_fast_restart(tmp_path):
    """Clean shutdown persists the reachability state; restart restores it
    byte-for-byte (verified against a forced full rebuild) and invalidates
    the marker so a subsequent crash falls back to the rebuild path."""
    import random

    from kaspa_tpu.consensus.consensus import Consensus
    from kaspa_tpu.consensus.params import simnet_params
    from kaspa_tpu.consensus.processes.coinbase import MinerData
    from kaspa_tpu.sim.simulator import Miner
    from kaspa_tpu.storage.kv import KvStore

    params = simnet_params(bps=2)
    path = str(tmp_path / "reach.db")
    db = KvStore(path)
    c = Consensus(params, db=db)
    miner = Miner(0, random.Random(21))
    for _ in range(25):
        c.validate_and_insert_block(c.build_block_template(MinerData(miner.spk, b""), []))
    c.save_reachability_snapshot()
    expect = (
        dict(c.reachability._interval), dict(c.reachability._parent),
        dict(c.reachability._children), dict(c.reachability._fcs),
        dict(c.reachability._height), dict(c.reachability._dag_parents),
        dict(c.reachability._dag_children), c.reachability._reindex_root,
    )
    sink = c.sink()
    db.close()

    # snapshot restart restores identical state ...
    db2 = KvStore(path)
    c2 = Consensus(params, db=db2)
    got = (
        dict(c2.reachability._interval), dict(c2.reachability._parent),
        dict(c2.reachability._children), dict(c2.reachability._fcs),
        dict(c2.reachability._height), dict(c2.reachability._dag_parents),
        dict(c2.reachability._dag_children), c2.reachability._reindex_root,
    )
    assert got == expect
    assert c2.sink() == sink
    c2.reachability.validate_intervals()
    # the incrementally-persisted RN column carries the state
    assert any(True for _ in db2.engine.items_prefix(b"RN"))
    # keep processing on the restored index
    c2.validate_and_insert_block(c2.build_block_template(MinerData(miner.spk, b""), []))
    db2.close()

    # crash path (no clean shutdown): the RN column restores the exact
    # state too — crash restarts are O(decode), never a rebuild
    db3 = KvStore(path)
    c3 = Consensus(params, db=db3)
    assert c3.reachability.is_chain_ancestor_of(params.genesis.hash, c3.sink())
    c3.reachability.validate_intervals()
    db3.close()


def test_reachability_crash_image_exact_state(tmp_path):
    """A crash image (file copy at an arbitrary flush boundary, no shutdown
    hook) restores byte-identical reachability state: the per-flush RN
    column is the source of truth, like the reference's always-persistent
    reachability stores (processes/reachability/)."""
    import random
    import shutil

    from kaspa_tpu.consensus.consensus import Consensus
    from kaspa_tpu.consensus.params import simnet_params
    from kaspa_tpu.consensus.processes.coinbase import MinerData
    from kaspa_tpu.sim.simulator import Miner
    from kaspa_tpu.storage.kv import KvStore

    params = simnet_params(bps=2)
    path = str(tmp_path / "reach-crash.db")
    db = KvStore(path)
    c = Consensus(params, db=db)
    miners = [Miner(i, random.Random(31 + i)) for i in range(2)]
    snap_expect = None
    for i in range(30):
        m = miners[i % 2]
        c.validate_and_insert_block(
            c.build_block_template(MinerData(m.spk, b""), [], timestamp=10_000 + 500 * i)
        )
        if i == 19:
            # crash image mid-history: per-block flush already ran.
            # deep-copy: later blocks mutate the live lists in place
            import copy as _copy

            shutil.copy(path, str(tmp_path / "crash-image.db"))
            snap_expect = _copy.deepcopy((
                dict(c.reachability._interval), dict(c.reachability._parent),
                dict(c.reachability._children), dict(c.reachability._fcs),
                dict(c.reachability._height), dict(c.reachability._dag_parents),
                dict(c.reachability._dag_children), c.reachability._reindex_root,
            ))
    db.close()

    db2 = KvStore(str(tmp_path / "crash-image.db"))
    c2 = Consensus(params, db=db2)
    got = (
        dict(c2.reachability._interval), dict(c2.reachability._parent),
        dict(c2.reachability._children), dict(c2.reachability._fcs),
        dict(c2.reachability._height), dict(c2.reachability._dag_parents),
        dict(c2.reachability._dag_children), c2.reachability._reindex_root,
    )
    assert got == snap_expect
    c2.reachability.validate_intervals()
    # the recovered node keeps accepting blocks
    c2.validate_and_insert_block(
        c2.build_block_template(MinerData(miners[0].spk, b""), [], timestamp=60_000)
    )
    db2.close()
