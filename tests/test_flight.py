"""Block flight recorder (observability/flight.py): cross-thread causal
tracing, critical-path attribution, Chrome trace export.

The contracts under test: (1) spans emitted on stage threads, the
coalescing verify-dispatch thread (one device super-batch fanning back
into per-ticket child spans) and the VM-fallback pool all reassemble
into ONE connected span tree per block — no orphans; (2) the
last-finisher critical-path walk attributes wall time to stages by
name; (3) the ring is bounded, begin() is idempotent, late spans attach
to sealed traces until eviction; (4) chrome_trace() emits well-formed
trace-event JSON; (5) tracing on vs off leaves the replayed consensus
end state bit-identical.
"""

import json
import random
import threading

import pytest

from kaspa_tpu.observability import flight, trace
from kaspa_tpu.observability.flight import chrome_trace, critical_path


@pytest.fixture(autouse=True)
def _recorder_reset():
    flight.reset()
    yield
    flight.disable()
    flight.reset()
    trace.enable()


def _span(sid, parent, name, t0, t1, thread="t0", trace_id="aa"):
    return {
        "name": name, "path": name, "trace": trace_id, "span": sid,
        "parent": parent, "start_ns": t0, "end_ns": t1,
        "start_us": t0 // 1000, "dur_us": (t1 - t0) / 1000.0,
        "thread": thread, "depth": 0, "attrs": {},
    }


# --- critical-path analyzer -------------------------------------------------


def test_critical_path_last_finisher_walk():
    # root [0,100], child a [10,60], grandchild g [20,40]: walking back
    # from 100 attributes 100->60 to root, 60->40 to a, [20,40] to g,
    # [10,20] to a (left of g), [0,10] to root.
    spans = [
        _span(1, 0, "root", 0, 100),
        _span(2, 1, "a", 10, 60),
        _span(3, 2, "g", 20, 40),
    ]
    cp = critical_path(spans, 1)
    assert cp["total_ns"] == 100
    assert cp["stages"] == {"root": 50, "a": 30, "g": 20}
    # fraction excludes the root's own self-time (the unexplained part)
    assert cp["fraction"] == pytest.approx(0.5)


def test_critical_path_concurrent_siblings_single_chain():
    # two overlapping children: only the last finisher's interval is
    # charged where they overlap — no double counting, sum == total
    spans = [
        _span(1, 0, "root", 0, 100),
        _span(2, 1, "early", 0, 70),
        _span(3, 1, "late", 30, 100),
    ]
    cp = critical_path(spans, 1)
    assert sum(cp["stages"].values()) == cp["total_ns"]
    assert cp["stages"]["late"] == 70  # [30,100]
    assert cp["stages"]["early"] == 30  # clipped to [0,30]
    assert cp["fraction"] == pytest.approx(1.0)


def test_critical_path_clips_children_to_root_interval():
    # a child ending after the root (late serving span) must not inflate
    # attribution past the root's wall time
    spans = [
        _span(1, 0, "root", 0, 100),
        _span(2, 1, "late", 90, 500),
    ]
    cp = critical_path(spans, 1)
    assert cp["total_ns"] == 100
    assert cp["stages"]["late"] == 10
    assert cp["fraction"] <= 1.0


def test_critical_path_missing_root():
    assert critical_path([], 7) == {
        "stages": {}, "total_ns": 0, "attributed_ns": 0, "fraction": 0.0
    }


# --- one connected tree across super-batch + VM fallback --------------------


def _schnorr_items(n: int):
    from kaspa_tpu.crypto import eclib

    import hashlib

    items = []
    for i in range(n):
        msg = hashlib.sha256(bytes([i, n, 0x5F])).digest()
        sig = eclib.schnorr_sign(msg, i + 1)
        if i % 3 == 2:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])
        items.append((eclib.schnorr_pubkey(i + 1), msg, sig))
    return items


def _p2sh_tx(seed: int):
    """One tx whose single input routes to the VM fallback lane."""
    from kaspa_tpu.consensus.model import (
        SUBNETWORK_ID_NATIVE,
        ComputeCommit,
        Transaction,
        TransactionInput,
        TransactionOutpoint,
        TransactionOutput,
        UtxoEntry,
    )
    from kaspa_tpu.txscript import standard

    OP_1, OP_EQUAL = 0x51, 0x87
    redeem = bytes([OP_1, OP_EQUAL])
    spk = standard.pay_to_script_hash_script(redeem)
    sig_script = bytes([OP_1]) + bytes([len(redeem)]) + redeem
    entry = UtxoEntry(10_000, spk, 5, False)
    tx = Transaction(
        0,
        [TransactionInput(TransactionOutpoint(bytes([seed]) * 32, 0), sig_script, 0, ComputeCommit.sigops(0))],
        [TransactionOutput(9_000, spk)], 0, SUBNETWORK_ID_NATIVE, 0, b"",
    )
    return tx, [entry]


def _parent_chain(span, by_id):
    chain = [span]
    while span["parent"] in by_id:
        span = by_id[span["parent"]]
        chain.append(span)
    return chain


def test_super_batch_and_vm_fallback_one_connected_tree(monkeypatch):
    """Three 'blocks' on three stage threads submit verify chunks that
    coalesce into ONE device super-batch; a fourth block routes a P2SH
    input down the VM-fallback pool.  Every block's spans — including the
    fan-back ``dispatch.device`` children and the ``vm.fallback`` span on
    the pool thread — must form a single connected tree under that
    block's root, at depth 3, with zero orphans."""
    from kaspa_tpu.ops import dispatch as coalesce
    from kaspa_tpu.txscript.batch import BatchScriptChecker
    from kaspa_tpu.txscript.caches import SigCache

    monkeypatch.setenv("KASPA_TPU_COALESCE_AGE_MS", "10000")
    coalesce.configure(16)
    try:
        flight.enable(ring=16)
        items = _schnorr_items(6)
        tickets = {}

        def stage_block(i):
            ctx = flight.begin(bytes([0x10 + i]) * 32)
            with trace.span("pipeline.stage", parent=ctx):
                with trace.span("txscript.verify"):
                    tickets[i] = coalesce.active().submit("schnorr", items[2 * i : 2 * i + 2])

        threads = [
            threading.Thread(target=stage_block, args=(i,), name=f"stage-{i}")
            for i in range(3)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        # first wait() nudges the age-parked queue: all three chunks flush
        # as one super-batch on the verify-dispatch thread
        for i in range(3):
            tickets[i].wait(300.0)

        def _vm(tx, entries, i, reused, pov_daa_score=None, seq_commit_accessor=None):
            from kaspa_tpu.txscript.vm import TxScriptEngine

            TxScriptEngine(tx, entries, i).execute()

        def vm_block():
            ctx = flight.begin(b"\xaa" * 32)
            with trace.span("pipeline.stage", parent=ctx):
                checker = BatchScriptChecker(SigCache(), _vm)
                tx, entries = _p2sh_tx(9)
                checker.collect_tx(0, tx, entries)
                errs = checker.dispatch()
                assert errs.get(0) is None

        th = threading.Thread(target=vm_block, name="stage-vm")
        th.start()
        th.join()

        for i in range(3):
            assert flight.end(bytes([0x10 + i]) * 32) is not None
        assert flight.end(b"\xaa" * 32) is not None

        done = flight.traces()
        assert len(done) == 4
        super_ids = set()
        for t in done:
            spans = t["spans"]
            by_id = {s["span"]: s for s in spans}
            roots = [s for s in spans if s["parent"] not in by_id]
            # exactly one root (the synthetic block span), zero orphans
            assert len(roots) == 1 and roots[0]["name"] == "block", t["label"]
            names = {s["name"] for s in spans}
            if "dispatch.device" in names:
                dev = next(s for s in spans if s["name"] == "dispatch.device")
                # fan-back child sits at depth 3: block <- stage <- verify <- device
                chain = [s["name"] for s in _parent_chain(dev, by_id)]
                assert chain == ["dispatch.device", "txscript.verify", "pipeline.stage", "block"]
                assert dev["attrs"]["super_jobs"] == 6 and dev["attrs"]["chunks"] == 3
                assert dev["thread"] not in {s["thread"] for s in spans if s["name"] == "pipeline.stage"}
                super_ids.add(dev["attrs"]["super_id"])
                assert "wait.dispatch" in names  # queue wait is a first-class span
            if t["label"].startswith("block:aaaa"):
                assert "vm.fallback" in names
                vm = next(s for s in spans if s["name"] == "vm.fallback")
                chain = [s["name"] for s in _parent_chain(vm, by_id)]
                assert chain[-1] == "block" and "pipeline.stage" in chain
        # the three dispatcher blocks shared one super-batch
        assert len(super_ids) == 1
    finally:
        coalesce.configure(0)


# --- recorder lifecycle -----------------------------------------------------


def test_begin_idempotent_and_disabled_noop():
    assert flight.begin(b"\x01" * 32) is None  # disabled: zero work
    flight.enable(ring=4)
    a = flight.begin(b"\x01" * 32)
    b = flight.begin(b"\x01" * 32)
    assert a.span_id == b.span_id and a.trace_id == b.trace_id
    flight.end(b"\x01" * 32)
    assert flight.end(b"\x01" * 32) is None  # double end: no-op


def test_ring_bounded_and_late_spans_attach_until_eviction():
    flight.enable(ring=2)
    ctxs = {}
    for i in range(3):
        h = bytes([i]) * 32
        ctxs[i] = flight.begin(h)
        flight.end(h)
    done = flight.traces()
    assert len(done) == 2  # bounded: oldest evicted
    assert done[0]["trace"] == (b"\x01" * 32).hex()
    # a late span (serving fanout after seal) still lands in its tree
    import time

    t0 = time.perf_counter_ns()
    trace.record_span("serving.fanout", ctxs[2], t0, t0 + 1000)
    latest = flight.traces()[-1]
    assert any(s["name"] == "serving.fanout" for s in latest["spans"])
    # but an evicted trace drops it (and counts the drop)
    before = flight.SPANS_DROPPED.value
    trace.record_span("serving.fanout", ctxs[0], t0, t0 + 1000)
    assert flight.SPANS_DROPPED.value == before + 1


def test_end_records_critical_path_and_histogram():
    flight.enable(ring=4)
    h = b"\x77" * 32
    ctx = flight.begin(h)
    with trace.span("pipeline.stage", parent=ctx):
        pass
    t = flight.end(h)
    cp = t["critical_path"]
    assert 0.0 <= cp["fraction"] <= 1.0
    assert "pipeline.stage" in cp["stages_ms"]
    fam = flight.CRIT_HIST.snapshot()
    assert fam["pipeline.stage"]["count"] >= 1
    assert "block" not in fam  # root self-time is the residual, not a stage


def test_breaker_open_dump(tmp_path):
    flight.enable(ring=4, dump_dir=str(tmp_path))
    h = b"\x42" * 32
    flight.begin(h)
    flight.end(h)
    path = flight.on_breaker_open("secp")
    assert path is not None
    doc = json.load(open(path))
    assert doc["format"] == "kaspa-flight" and doc["reason"] == "breaker-open:secp"
    assert len(doc["traces"]) == 1
    # no dump dir -> breaker dumps are suppressed (tests trip breakers)
    flight.RECORDER.dump_dir = None
    assert flight.on_breaker_open("secp") is None


# --- chrome trace-event export ----------------------------------------------


def test_chrome_trace_export_schema():
    t = {
        "trace": "ab" * 16,
        "label": "block:abababab",
        "spans": [
            _span(1, 0, "block", 0, 100_000, thread="block"),
            _span(2, 1, "pipeline.stage", 10_000, 60_000, thread="stage-0"),
            _span(3, 2, "dispatch.device", 20_000, 40_000, thread="verify-dispatch"),
        ],
    }
    doc = chrome_trace([t])
    ev = doc["traceEvents"]
    meta = [e for e in ev if e["ph"] == "M"]
    assert {"process_name", "thread_name"} <= {e["name"] for e in meta}
    assert any(e["args"]["name"] == "block block:abababab" for e in meta if e["name"] == "process_name")
    xs = [e for e in ev if e["ph"] == "X"]
    assert len(xs) == 3
    for e in xs:
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert e["dur"] > 0 and "ts" in e
    # both cross-thread edges got a flow arrow (s/f pairs share ids)
    souts = [e for e in ev if e["ph"] == "s"]
    fins = [e for e in ev if e["ph"] == "f"]
    assert len(souts) == len(fins) == 2
    assert {e["id"] for e in souts} == {e["id"] for e in fins}
    json.dumps(doc)  # serializable end to end


# --- tracing on/off bit-identity (sim sink) ---------------------------------


def test_tracing_on_off_bit_identical_sim_sink():
    """The recorder observes, never participates: a pipelined replay with
    the flight recorder on and a replay with tracing disabled entirely
    must land on the byte-identical sink + utxo commitment."""
    from kaspa_tpu.sim.simulator import SimConfig, replay_pipelined, simulate

    res = simulate(SimConfig(bps=2, num_blocks=12, txs_per_block=2, seed=11))

    flight.enable(ring=64)
    _, traced = replay_pipelined(res)
    assert len(flight.traces()) == 12
    flight.disable()

    trace.disable()
    try:
        _, plain = replay_pipelined(res)
    finally:
        trace.enable()

    assert traced.sink() == plain.sink() == res.sink
    sink = res.sink
    assert (
        traced.multisets[sink].finalize() == plain.multisets[sink].finalize()
    )


# --- getTraces RPC surface --------------------------------------------------


def test_get_traces_rpc_surface():
    from kaspa_tpu.consensus.consensus import Consensus
    from kaspa_tpu.consensus.params import simnet_params
    from kaspa_tpu.p2p import Node
    from kaspa_tpu.rpc import RpcCoreService
    from kaspa_tpu.sim.simulator import Miner

    node = Node(Consensus(simnet_params(bps=2)), "flight-test")
    service = RpcCoreService(node.consensus, node.mining, address_prefix="kaspasim")
    try:
        flight.enable(ring=16)
        miner = Miner(0, random.Random(5))
        for _ in range(4):
            node.submit_block(node.consensus.build_block_template(miner.miner_data, []))
        out = service.get_traces(limit=8)
        assert out["enabled"] is True
        assert len(out["traces"]) == 4
        s = out["traces"][-1]
        assert s["status"] == "ok" and s["spans"] >= 2 and s["threads"] >= 2
        assert 0.0 <= s["critical_path"]["fraction"] <= 1.0
        full = service.get_traces(limit=2, verbose=True)
        assert len(full["full"]) == 2 and full["full"][-1]["spans"]
        json.dumps(out)  # wire-safe for the daemon's JSON-RPC layer
    finally:
        node.pipeline.shutdown()
