"""Serving tier: backpressured fanout, persistent utxoindex, dual-encoding
streams (reference: notify/src/broadcaster.rs + indexes/utxoindex +
rpc/wrpc/server)."""

from __future__ import annotations

import io
import os
import pickle
import queue
import signal
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.model import ScriptPublicKey, TransactionOutpoint, UtxoEntry
from kaspa_tpu.index.utxoindex import _META_DIRTY, _META_VERSION, UtxoIndex
from kaspa_tpu.notify.notifier import Notification, Notifier
from kaspa_tpu.serving import POLICY_DISCONNECT, POLICY_DROP_OLDEST, Broadcaster, Subscriber
from kaspa_tpu.sim.simulator import Miner, SimConfig, simulate
from kaspa_tpu.storage.kv import KvStore


def _wait_until(cond, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


# ---------------------------------------------------------------------------
# broadcaster: backpressure policies + scope pushdown
# ---------------------------------------------------------------------------


class _BlockedSink:
    """A connection queue that is wedged until released — the slow consumer."""

    def __init__(self):
        self.released = threading.Event()
        self.items: queue.Queue = queue.Queue()

    def put(self, item, timeout=None):
        if not self.released.is_set():
            if timeout:
                time.sleep(min(timeout, 0.02))
            raise queue.Full
        self.items.put(item)


def test_slow_subscriber_drop_oldest_never_stalls_fast():
    root = Notifier("rpc")
    bc = Broadcaster(root)
    fast_sink: queue.Queue = queue.Queue()
    slow_sink = _BlockedSink()
    enc = lambda n: str(n.data["n"]).encode()  # noqa: E731
    fast = Subscriber("fast", enc, fast_sink)
    slow = Subscriber("slow", enc, slow_sink, maxlen=4, policy=POLICY_DROP_OLDEST)
    try:
        bc.register(fast)
        bc.register(slow)
        bc.subscribe(fast, "block-added")
        bc.subscribe(slow, "block-added")
        total = 50
        for i in range(total):
            root.notify(Notification("block-added", {"n": i}))
        # the fast subscriber sees every event, in order, despite the wedge
        got = [fast_sink.get(timeout=10) for _ in range(total)]
        assert got == [str(i).encode() for i in range(total)]
        # the slow one shed load at its bounded queue instead of blocking
        assert _wait_until(lambda: slow.dropped > 0)
        assert slow.dropped >= total - slow.maxlen - 2
        # unwedge: only the retained tail drains, ending at the newest event
        slow_sink.released.set()
        assert _wait_until(lambda: slow_sink.items.qsize() > 0 and slow.queue_depth() == 0)
        time.sleep(0.1)
        drained = []
        while not slow_sink.items.empty():
            drained.append(slow_sink.items.get_nowait())
        assert len(drained) <= slow.maxlen + 2  # queue + at most the in-flight event
        assert drained[-1] == str(total - 1).encode()
    finally:
        bc.close()


def test_slow_subscriber_disconnect_policy_tears_down():
    root = Notifier("rpc")
    bc = Broadcaster(root)
    fast_sink: queue.Queue = queue.Queue()
    disconnected = threading.Event()
    enc = lambda n: str(n.data["n"]).encode()  # noqa: E731
    fast = Subscriber("fast", enc, fast_sink)
    slow = Subscriber(
        "slow", enc, _BlockedSink(), maxlen=2, policy=POLICY_DISCONNECT, on_disconnect=disconnected.set
    )
    try:
        bc.register(fast)
        bc.register(slow)
        bc.subscribe(fast, "block-added")
        bc.subscribe(slow, "block-added")
        for i in range(10):
            root.notify(Notification("block-added", {"n": i}))
        # overflow fires the disconnect callback; the fast stream is untouched
        assert disconnected.wait(timeout=10)
        got = [fast_sink.get(timeout=10) for _ in range(10)]
        assert got == [str(i).encode() for i in range(10)]
        assert slow._stopped
    finally:
        bc.close()


class _Spk:
    def __init__(self, script):
        self.version = 0
        self.script = script


class _Entry:
    def __init__(self, script, amount=1):
        self.script_public_key = _Spk(script)
        self.amount = amount


def _diff_notification(pairs_by_script):
    added = [(f"op-{s.hex()}-{i}", _Entry(s)) for s, n in pairs_by_script.items() for i in range(n)]
    return Notification(
        "utxos-changed",
        {"added": added, "removed": [], "spk_set": set(pairs_by_script)},
    )


def test_scope_filter_pushdown_and_determinism():
    sa, sb, sc = b"\x01" * 4, b"\x02" * 4, b"\x03" * 4
    n = _diff_notification({sc: 1, sa: 2, sb: 1})
    by_script = Broadcaster._index_diff(n)
    assert {s: (len(a), len(r)) for s, (a, r) in by_script.items()} == {sa: (2, 0), sb: (1, 0), sc: (1, 0)}

    # scoped filter keeps only matching scripts, in sorted-script order
    f = Broadcaster._filter_utxos_changed(n, frozenset({sb, sa}), by_script)
    assert [e.script_public_key.script for _, e in f.data["added"]] == [sa, sa, sb]
    assert f.data["spk_set"] == {sa, sb}
    # no overlap -> the event is suppressed before it ever reaches the queue
    assert Broadcaster._filter_utxos_changed(n, frozenset({b"\x09"}), by_script) is None

    # end to end: same scope -> identical payloads on both subscribers;
    # a wildcard subscriber sees the whole diff
    root = Notifier("rpc")
    bc = Broadcaster(root)
    sinks = [queue.Queue() for _ in range(3)]
    subs = [Subscriber(f"s{i}", lambda x: x, sinks[i]) for i in range(3)]
    try:
        for s in subs:
            bc.register(s)
        bc.subscribe(subs[0], "utxos-changed", {sa, sb})
        bc.subscribe(subs[1], "utxos-changed", {sa, sb})
        bc.subscribe(subs[2], "utxos-changed")  # wildcard
        root.notify(n)
        got0 = sinks[0].get(timeout=10)
        got1 = sinks[1].get(timeout=10)
        wild = sinks[2].get(timeout=10)
        assert got0.data["added"] == got1.data["added"]
        assert [e.script_public_key.script for _, e in got0.data["added"]] == [sa, sa, sb]
        assert len(wild.data["added"]) == 4
    finally:
        bc.close()


def test_broadcaster_refcounts_upstream_subscription():
    root = Notifier("rpc")
    bc = Broadcaster(root)
    s1 = Subscriber("s1", lambda x: x, queue.Queue())
    s2 = Subscriber("s2", lambda x: x, queue.Queue())
    try:
        bc.register(s1)
        bc.register(s2)
        bc.subscribe(s1, "block-added")
        bc.subscribe(s2, "block-added")
        assert root.has_subscribers("block-added")
        bc.unsubscribe(s1, "block-added")
        assert root.has_subscribers("block-added")  # s2 still holds the event
        bc.unregister(s2)
        assert not root.has_subscribers("block-added")
    finally:
        bc.close()
        s1.close()
        s2.close()
    # close detached the broadcaster's own listener from the notifier
    assert not root._listeners


# ---------------------------------------------------------------------------
# persistent utxoindex: open modes, journal rewind, resync triggers
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def chain():
    cfg = SimConfig(bps=2, delay=0.5, num_miners=2, num_blocks=20, txs_per_block=2, seed=19)
    return simulate(cfg)


def test_persistent_index_lifecycle(tmp_path, chain):
    import random

    c = Consensus(chain.params)
    mem = UtxoIndex(c)
    p1 = str(tmp_path / "idx.db")
    idx = UtxoIndex(c, db_path=p1)
    assert idx.open_mode == "fresh"
    for b in chain.blocks:
        c.validate_and_insert_block(b)

    # diff-fed persistent state == in-memory state == virtual set
    assert idx.get_circulating_supply() == sum(e.amount for _, e in c.utxo_set.items())
    assert idx.entry_count() == mem.entry_count() > 0
    for script, bucket in mem._by_script.items():
        assert idx.get_utxos_by_script(script) == dict(bucket)
    miner0 = Miner(0, random.Random(19))
    assert idx.get_balance_by_script(miner0.spk.script) == mem.get_balance_by_script(miner0.spk.script) > 0

    # the diff-fed index is byte-identical to a fresh resync
    fresh = UtxoIndex(c, db_path=str(tmp_path / "fresh.db"))
    want = fresh.content_snapshot()
    fresh.close()
    assert idx.content_snapshot() == want

    # reopen: no resync, no rewinds (position may lag sink by net-empty diffs)
    idx.close()
    idx = UtxoIndex(c, db_path=p1)
    assert idx.open_mode in ("clean", "catchup")
    assert idx.journal_rewinds == 0
    assert idx.content_snapshot() == want

    # a journaled diff to a position consensus never heard of (the
    # notify-before-flush crash window) is rewound on reopen, not resynced
    ghost = b"\xab" * 32
    entry = UtxoEntry(777, ScriptPublicKey(0, b"\xaa" * 34), 1, False)
    idx._apply_diff([(TransactionOutpoint(b"\xcd" * 32, 7), entry)], [], ghost)
    assert idx.position == ghost
    idx.close()
    idx = UtxoIndex(c, db_path=p1)
    assert idx.open_mode in ("clean", "catchup")
    assert idx.journal_rewinds >= 1
    assert idx.content_snapshot() == want

    # version bump -> full resync
    idx.close()
    db = KvStore(p1)
    db.engine.put(_META_VERSION, b"999")
    db.close()
    idx = UtxoIndex(c, db_path=p1)
    assert idx.open_mode == "resync"
    assert idx.content_snapshot() == want

    # dirty marker (crash mid-resync) -> full resync
    idx.close()
    db = KvStore(p1)
    db.engine.put(_META_DIRTY, b"1")
    db.close()
    idx = UtxoIndex(c, db_path=p1)
    assert idx.open_mode == "resync"
    assert idx.content_snapshot() == want

    idx.close()
    mem.close()
    # closed index no longer receives notifications
    assert not c.notification_root._listeners


_KILL_SCRIPT = textwrap.dedent(
    """
    import pickle, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from kaspa_tpu.utils import jax_setup; jax_setup.setup()
    from kaspa_tpu.consensus.consensus import Consensus
    from kaspa_tpu.index.utxoindex import UtxoIndex
    from kaspa_tpu.storage.kv import KvStore

    cons_path, index_path, blocks_pkl = sys.argv[1], sys.argv[2], sys.argv[3]
    with open(blocks_pkl, "rb") as f:
        params, blocks = pickle.load(f)
    db = KvStore(cons_path)
    c = Consensus(params, db=db)
    index = UtxoIndex(c, db_path=index_path)
    for i, b in enumerate(blocks):
        c.validate_and_insert_block(b)
        print(f"inserted {i}", flush=True)
    """
)


def test_kill9_during_diff_burst_rewinds_not_resyncs(tmp_path, chain):
    """kill -9 the node mid-burst; the reopened index reconciles through the
    journal + chain-diff walk — byte-identical to a fresh resync, with NO
    full rebuild triggered."""
    cons_path = str(tmp_path / "consensus.db")
    index_path = str(tmp_path / "utxoindex.db")
    blocks_pkl = str(tmp_path / "blocks.pkl")
    with open(blocks_pkl, "wb") as f:
        pickle.dump((chain.params, chain.blocks), f)
    script = str(tmp_path / "killme.py")
    with open(script, "w") as f:
        f.write(_KILL_SCRIPT)

    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, script, cons_path, index_path, blocks_pkl],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
    )
    inserted = 0
    for line in proc.stdout:
        if line.startswith("inserted"):
            inserted += 1
            if inserted >= 8:
                os.kill(proc.pid, signal.SIGKILL)
                break
    proc.wait()
    assert inserted >= 8, f"inserter died early: {proc.stderr.read()}"

    db = KvStore(cons_path)
    c = Consensus(chain.params, db=db)
    idx = UtxoIndex(c, db_path=index_path)
    # the whole point: reconciliation, never the full-rebuild fallback
    assert idx.open_mode in ("clean", "catchup")
    fresh = UtxoIndex(c, db_path=str(tmp_path / "fresh.db"))
    assert idx.content_snapshot() == fresh.content_snapshot()
    assert idx.get_circulating_supply() == sum(e.amount for _, e in c.utxo_set.items())
    fresh.close()
    idx.close()
    db.close()


# ---------------------------------------------------------------------------
# dual-encoding daemon streams: one node, JSON + Borsh subscribers
# ---------------------------------------------------------------------------


@pytest.fixture()
def daemon(tmp_path):
    from kaspa_tpu.node.daemon import Daemon, parse_args

    args = parse_args(
        ["--appdir", str(tmp_path), "--rpclisten", "127.0.0.1:0",
         "--rpclisten-wrpc", "127.0.0.1:0", "--bps", "2"]
    )
    d = Daemon(args)
    d.start()
    yield d, d.wrpc_server.address
    d.stop()


def _json_stream_key(data):
    return [
        (p["outpoint"]["transaction_id"], p["outpoint"]["index"], p["utxo_entry"]["amount"],
         p["utxo_entry"]["script_public_key"]["script"])
        for p in data
    ]


def _borsh_stream_key(entries):
    return [
        (outpoint.transaction_id.hex(), outpoint.index, entry.amount, entry.script_public_key.script.hex())
        for _addr, outpoint, entry in entries
    ]


def test_two_clients_identical_filtered_streams(daemon):
    import random

    from kaspa_tpu.crypto.addresses import extract_script_pub_key_address
    from kaspa_tpu.rpc import borsh_codec as bc
    from kaspa_tpu.rpc.wrpc import WrpcClient

    d, addr = daemon
    miner = Miner(0, random.Random(2))
    pay = extract_script_pub_key_address(miner.spk, "kaspasim").to_string()

    client_json = WrpcClient(addr)
    client_borsh = WrpcClient(addr, encoding="borsh")
    try:
        assert client_borsh.encoding == "borsh"
        assert client_json.subscribe("utxos-changed", [pay]) == "ok"
        client_borsh.subscribe_borsh(bc.OP_UTXOS_CHANGED_NOTIFICATION, [pay])

        for _ in range(8):
            t = client_json.call("getBlockTemplate", {"payAddress": pay})
            client_json.call("submitBlockByTemplateHash", {"hash": t["block_hash"]})
            d.mining.template_cache.clear()

        want_events = 2
        json_events = []
        deadline = time.monotonic() + 60
        while len(json_events) < want_events and time.monotonic() < deadline:
            event, data = client_json.next_notification(timeout=30)
            if event == "utxos-changed":
                json_events.append(data)
        assert len(json_events) >= want_events

        borsh_events = []
        while len(borsh_events) < len(json_events):
            op, payload = client_borsh.borsh_notifications.get(timeout=30)
            if op == bc.OP_UTXOS_CHANGED_NOTIFICATION:
                borsh_events.append(bc.decode_utxos_changed_notification(io.BytesIO(payload)))

        # both encodings observed the SAME filtered stream, event for event
        for jd, bd in zip(json_events, borsh_events):
            assert _json_stream_key(jd["added"]) == _borsh_stream_key(bd["added"])
            assert _json_stream_key(jd["removed"]) == _borsh_stream_key(bd["removed"])
            # scope pushdown: only the subscribed script ever appears
            for p in jd["added"] + jd["removed"]:
                assert p["utxo_entry"]["script_public_key"]["script"] == miner.spk.script.hex()
            for _a, _op, entry in bd["added"] + bd["removed"]:
                assert entry.script_public_key.script == miner.spk.script
            # Borsh recovers the bech32 address from the script
            assert all(a == pay for a, _op, _e in bd["added"])

        # the Borsh query surface serves from the same index
        raw = client_borsh.call_borsh(bc.OP_GET_COIN_SUPPLY, _coin_supply_req())
        supply = bc.decode_get_coin_supply_response(io.BytesIO(raw))
        assert supply["circulating_sompi"] == d.utxoindex.get_circulating_supply()
        assert supply["max_sompi"] == bc.MAX_SOMPI

        w = io.BytesIO()
        bc.encode_get_balance_by_address_request(w, pay)
        raw = client_borsh.call_borsh(bc.OP_GET_BALANCE_BY_ADDRESS, w.getvalue())
        balance = bc.decode_get_balance_by_address_response(io.BytesIO(raw))
        assert balance == d.utxoindex.get_balance_by_script(miner.spk.script)

        w = io.BytesIO()
        bc.encode_get_utxos_by_addresses_request(w, [pay])
        raw = client_borsh.call_borsh(bc.OP_GET_UTXOS_BY_ADDRESSES, w.getvalue())
        entries = bc.decode_get_utxos_by_addresses_response(io.BytesIO(raw))
        assert sum(e.amount for _a, _op, e in entries) == balance
        assert all(a == pay for a, _op, _e in entries)
        # response ordering is pinned: (txid, index) ascending
        keys = [(op_.transaction_id, op_.index) for _a, op_, _e in entries]
        assert keys == sorted(keys)
    finally:
        client_json.close()
        client_borsh.close()


def _coin_supply_req() -> bytes:
    from kaspa_tpu.rpc import borsh_codec as bc

    w = io.BytesIO()
    bc.encode_get_coin_supply_request(w)
    return w.getvalue()


def test_borsh_encoding_negotiation_rejected_for_unknown_proto(daemon):
    from kaspa_tpu.rpc.wrpc import WrpcClient

    _d, addr = daemon
    with pytest.raises(ConnectionError):
        WrpcClient(addr, encoding="msgpack")
