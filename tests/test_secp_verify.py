"""Batched TPU Schnorr/ECDSA kernels vs the pure-python oracle.

Mirrors the signature-check semantics of the reference
(crypto/txscript/src/lib.rs:885-935): BIP340 x-only Schnorr and compact
ECDSA with high-S rejection.  Adversarial cases included — wrong message,
corrupted sigs, invalid pubkeys, out-of-range r/s.
"""

import random

import numpy as np
import pytest

from kaspa_tpu.crypto import eclib, secp

pytestmark = pytest.mark.slow


def _schnorr_cases(n=16, seed=11):
    rng = random.Random(seed)
    items, expect = [], []
    for i in range(n):
        sk = rng.randrange(1, eclib.N)
        msg = rng.randbytes(32)
        pub = eclib.schnorr_pubkey(sk)
        sig = eclib.schnorr_sign(msg, sk, rng.randbytes(32))
        kind = i % 8
        if kind == 1:
            sig = sig[:40] + bytes([sig[40] ^ 1]) + sig[41:]  # corrupt s
        elif kind == 2:
            msg = rng.randbytes(32)  # wrong message
        elif kind == 3:
            sig = bytes([sig[0] ^ 1]) + sig[1:]  # corrupt r
        elif kind == 4:
            pub = rng.randbytes(32)  # likely not a valid x (or wrong key)
        elif kind == 5:
            sig = sig[:32] + (eclib.N + 5).to_bytes(32, "big")  # s >= n
        elif kind == 6:
            sig = (eclib.P + 1).to_bytes(32, "big") + sig[32:]  # r >= p
        items.append((pub, msg, sig))
        expect.append(eclib.schnorr_verify(pub, msg, sig))
    return items, expect


def test_schnorr_batch_matches_oracle():
    items, expect = _schnorr_cases()
    mask = secp.schnorr_verify_batch(items)
    assert list(mask) == expect
    assert any(expect) and not all(expect)  # mix of valid/invalid exercised


def _ecdsa_cases(n=16, seed=12):
    rng = random.Random(seed)
    items, expect = [], []
    for i in range(n):
        sk = rng.randrange(1, eclib.N)
        msg = rng.randbytes(32)
        pub = eclib.ecdsa_pubkey(sk)
        sig = eclib.ecdsa_sign(msg, sk, rng.randrange(1, eclib.N))
        kind = i % 8
        if kind == 1:
            sig = sig[:40] + bytes([sig[40] ^ 1]) + sig[41:]
        elif kind == 2:
            msg = rng.randbytes(32)
        elif kind == 3:  # high-S: must be rejected (libsecp256k1 semantics)
            s = int.from_bytes(sig[32:], "big")
            sig = sig[:32] + (eclib.N - s).to_bytes(32, "big")
        elif kind == 4:
            pub = bytes([9]) + pub[1:]  # bad prefix byte
        elif kind == 5:
            sig = sig[:32] + b"\x00" * 32  # s == 0
        elif kind == 6:
            pub = bytes([pub[0] ^ 1]) + pub[1:]  # flipped parity (2 <-> 3): valid encoding, wrong key
        items.append((pub, msg, sig))
        expect.append(eclib.ecdsa_verify(pub, msg, sig))
    return items, expect


def test_ecdsa_batch_matches_oracle():
    items, expect = _ecdsa_cases()
    mask = secp.ecdsa_verify_batch(items)
    assert list(mask) == expect
    assert any(expect) and not all(expect)


def test_point_ladder_vs_oracle():
    """dual_scalar_mul against python scalar multiplication, incl. edge scalars."""
    import jax.numpy as jnp

    from kaspa_tpu.ops import bigint as bi
    from kaspa_tpu.ops.secp256k1 import points as pt

    rng = random.Random(13)
    sk = rng.randrange(1, eclib.N)
    P = eclib.point_mul(eclib.G, sk)
    cases = [
        (0, 0),
        (1, 0),
        (0, 1),
        (5, 7),
        (eclib.N - 1, 1),
        (rng.randrange(eclib.N), rng.randrange(eclib.N)),
        (rng.randrange(eclib.N), rng.randrange(eclib.N)),
        (1, eclib.N - 1),
    ]
    b = len(cases)
    px = np.tile(bi.int_to_limbs(P[0], 16), (b, 1)).astype(np.int32)
    py = np.tile(bi.int_to_limbs(P[1], 16), (b, 1)).astype(np.int32)
    gd = np.stack([pt.scalar_digits_msb(a) for a, _ in cases])
    pd = np.stack([pt.scalar_digits_msb(c) for _, c in cases])
    import jax

    ladder = jax.jit(lambda *a: pt.to_affine(pt.dual_scalar_mul_base(*a)))
    xa, ya, inf = ladder(jnp.asarray(px), jnp.asarray(py), jnp.asarray(gd), jnp.asarray(pd))
    for i, (a, c) in enumerate(cases):
        exp = eclib.point_add(eclib.point_mul(eclib.G, a), eclib.point_mul(P, c))
        if exp is None:
            assert bool(inf[i])
        else:
            assert not bool(inf[i])
            assert bi.limbs_to_int(np.asarray(xa)[i]) == exp[0]
            assert bi.limbs_to_int(np.asarray(ya)[i]) == exp[1]


def test_cold_bucket_split(monkeypatch):
    """A batch whose padded bucket was never compiled must split into
    sub-dispatches at the largest warm bucket instead of paying the cold
    jit inline; masks reassemble in order, and the cold shape is never
    recorded as compiled."""
    import numpy as np

    from kaspa_tpu.crypto import secp

    calls = []

    def fake_kernel(px, py, rc, d1, d2, ok):
        calls.append(len(ok))
        out = np.asarray(ok, dtype=bool).copy()
        return out

    fake_kernel.__name__ = "fake_kernel"
    monkeypatch.setattr(secp, "_seen_shapes", {("fake_kernel", 8)})
    monkeypatch.delenv("KASPA_TPU_COLD_BUCKET_SPLIT", raising=False)

    batch = secp._Batch()
    for i in range(10):
        if i == 3:
            batch.push_invalid()
        else:
            batch.push(1, 2, 3, 4, 5)
    mask = batch.run(fake_kernel)
    # two warm bucket-8 dispatches, bucket 16 never compiled
    assert calls == [8, 8]
    assert ("fake_kernel", 16) not in secp._seen_shapes
    assert mask.tolist() == [True] * 3 + [False] + [True] * 6

    # disabled: pad up into the cold bucket as before
    calls.clear()
    monkeypatch.setenv("KASPA_TPU_COLD_BUCKET_SPLIT", "0")
    batch2 = secp._Batch()
    for _ in range(10):
        batch2.push(1, 2, 3, 4, 5)
    mask2 = batch2.run(fake_kernel)
    assert calls == [16]
    assert ("fake_kernel", 16) in secp._seen_shapes
    assert mask2.tolist() == [True] * 10
