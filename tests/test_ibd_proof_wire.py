"""Two-OS-process pruning-proof IBD over the binary wire.

The donor daemon mines past its (scaled-down) pruning depth so deep
history is actually deleted; a fresh joiner daemon then dials it and must
converge via proof + trusted data + PP-UTXO chunks + block sync, across
real sockets — the full trustless-join path end to end.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

import pytest

from kaspa_tpu.node.daemon import rpc_call

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

OVERRIDES = [
    "--override-pruning-depth", "60",
    "--override-finality-depth", "30",
    "--override-merge-depth", "15",
    "--override-proof-m", "10",
    "--override-window-scale", "12",
]


def _spawn(tmp_path, name, rpc_port, p2p_port, connect=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["KASPA_TPU_PLATFORM"] = "cpu"
    argv = [
        sys.executable, "-m", "kaspa_tpu.node",
        "--appdir", str(tmp_path / name),
        "--rpclisten", f"127.0.0.1:{rpc_port}",
        "--listen", f"127.0.0.1:{p2p_port}",
        "--bps", "2",
        *OVERRIDES,
    ]
    if connect:
        argv += ["--connect", connect]
    return subprocess.Popen(argv, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)


def _wait_rpc(addr, timeout=90.0):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            return rpc_call(addr, "getServerInfo")
        except Exception as e:  # noqa: BLE001
            last = e
            time.sleep(0.3)
    raise TimeoutError(f"rpc at {addr} not up: {last}")


def _free_ports(n):
    import socket

    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def test_two_process_proof_ibd(tmp_path):
    from kaspa_tpu.crypto.addresses import Address

    addr = Address("kaspasim", 0, bytes(32)).to_string()
    r1, p1, r2, p2 = _free_ports(4)
    donor = _spawn(tmp_path, "donor", r1, p1)
    joiner = None
    try:
        _wait_rpc(f"127.0.0.1:{r1}")
        for _ in range(160):
            t = rpc_call(f"127.0.0.1:{r1}", "getBlockTemplate", {"payAddress": addr})
            rpc_call(f"127.0.0.1:{r1}", "submitBlockByTemplateHash", {"hash": t["block_hash"]})
        dag = rpc_call(f"127.0.0.1:{r1}", "getBlockDagInfo")
        donor_sink = rpc_call(f"127.0.0.1:{r1}", "getSink")
        # pruning actually happened donor-side
        counts = rpc_call(f"127.0.0.1:{r1}", "getBlockCount")
        assert counts["block_count"] < 160, counts

        joiner = _spawn(tmp_path, "joiner", r2, p2, connect=f"127.0.0.1:{p1}")
        _wait_rpc(f"127.0.0.1:{r2}")
        deadline = time.monotonic() + 120
        sink2 = None
        while time.monotonic() < deadline:
            try:
                sink2 = rpc_call(f"127.0.0.1:{r2}", "getSink")
                if sink2 == donor_sink:
                    break
            except Exception:  # noqa: BLE001
                pass
            time.sleep(0.5)
        assert sink2 == donor_sink, f"joiner never converged: {sink2} vs {donor_sink}"
        # mine on the joiner; block must relay back to the donor
        t = rpc_call(f"127.0.0.1:{r2}", "getBlockTemplate", {"payAddress": addr})
        rpc_call(f"127.0.0.1:{r2}", "submitBlockByTemplateHash", {"hash": t["block_hash"]})
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if rpc_call(f"127.0.0.1:{r1}", "getSink") == rpc_call(f"127.0.0.1:{r2}", "getSink"):
                break
            time.sleep(0.5)
        assert rpc_call(f"127.0.0.1:{r1}", "getSink") == rpc_call(f"127.0.0.1:{r2}", "getSink")
    finally:
        for proc, name in ((donor, "donor"), (joiner, "joiner")):
            if proc is None:
                continue
            proc.terminate()
            try:
                out = proc.communicate(timeout=10)[0]
            except subprocess.TimeoutExpired:
                proc.kill()
                out = proc.communicate()[0]
            if out and ("Traceback" in out or "Error" in out):
                print(f"--- {name} output tail ---\n{out[-1500:]}")
