"""PSKT multisig flow: create -> sign (two parties) -> combine -> extract,
with the extracted tx mined into a valid block (2-of-3 P2SH multisig)."""

import random

import pytest

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.model import TransactionOutpoint, TransactionOutput
from kaspa_tpu.consensus.params import simnet_params
from kaspa_tpu.crypto import eclib
from kaspa_tpu.mempool import MiningManager
from kaspa_tpu.sim.simulator import Miner
from kaspa_tpu.txscript import standard
from kaspa_tpu.wallet.pskt import Pskt, PsktError, multisig_redeem_script


def test_pskt_2of3_multisig_roundtrip():
    rng = random.Random(77)
    params = simnet_params(bps=2)
    c = Consensus(params)
    mgr = MiningManager(c)
    miner = Miner(0, rng)

    # fund a 2-of-3 multisig P2SH address
    keys = [rng.randrange(1, eclib.N) for _ in range(3)]
    pubs = [eclib.schnorr_pubkey(k) for k in keys]
    redeem = multisig_redeem_script(2, pubs)
    p2sh = standard.pay_to_script_hash_script(redeem)

    for _ in range(12):
        blk = mgr.get_block_template(miner.miner_data)
        c.validate_and_insert_block(blk)
        mgr.handle_new_block_transactions(blk.transactions, c.get_virtual_daa_score())
        mgr.template_cache.clear()

    # miner sends funds into the multisig
    from kaspa_tpu.consensus import hashing as chash
    from kaspa_tpu.consensus.model import Transaction, TransactionInput
    from kaspa_tpu.consensus.model.tx import SUBNETWORK_ID_NATIVE, ComputeCommit

    view = c.get_virtual_utxo_view()
    pov = c.get_virtual_daa_score()
    op, e = next(
        (op, e) for op, e in c.utxo_set.items()
        if view.get(op) is not None and e.script_public_key == miner.spk
        and not (e.is_coinbase and e.block_daa_score + params.coinbase_maturity > pov)
    )
    fund = Transaction(0, [TransactionInput(op, b"", 0, ComputeCommit.sigops(1))],
                       [TransactionOutput(e.amount - 1000, p2sh)], 0, SUBNETWORK_ID_NATIVE, 0, b"")
    from kaspa_tpu.consensus.mass import MassCalculator

    fund.storage_mass = MassCalculator().calc_contextual_masses(fund, [e])
    msg = chash.calc_schnorr_signature_hash(fund, [e], 0, chash.SIG_HASH_ALL, chash.SigHashReusedValues())
    sig = eclib.schnorr_sign(msg, miner.seckey, rng.randbytes(32))
    fund.inputs[0].signature_script = standard.schnorr_signature_script(sig, chash.SIG_HASH_ALL)
    mgr.validate_and_insert_transaction(fund)
    blk = mgr.get_block_template(miner.miner_data)
    c.validate_and_insert_block(blk)
    mgr.handle_new_block_transactions(blk.transactions, c.get_virtual_daa_score())
    c.validate_and_insert_block(mgr.get_block_template(miner.miner_data))  # merge it

    ms_op = TransactionOutpoint(fund.id(), 0)
    ms_entry = c.get_virtual_utxo_view().get(ms_op)
    assert ms_entry is not None

    # PSKT: construct -> two signers independently -> combine -> extract.
    # Commit 3 sig ops: the runtime counter (lib.rs:898 via the multisig
    # loop) charges one per ATTEMPTED key check, and a 2-of-3 where the
    # second signer holds key[2] attempts keys 0,1,2.
    base = Pskt().add_input(ms_op, ms_entry, redeem, 3).add_output(
        TransactionOutput(ms_entry.amount - 2000, miner.spk)
    )
    wire = base.to_json()
    signer_a = Pskt.from_json(wire).sign(keys[0], rng.randbytes(32))
    signer_c = Pskt.from_json(wire).sign(keys[2], rng.randbytes(32))

    # insufficient sigs -> extraction fails
    with pytest.raises(PsktError, match="1 of 2"):
        Pskt.from_json(signer_a.to_json()).extract_tx()

    # tampered-output PSKT must not combine
    tampered = Pskt.from_json(signer_c.to_json())
    tampered.outputs[0].value -= 1
    with pytest.raises(PsktError, match="incompatible"):
        Pskt.from_json(signer_a.to_json()).combine(tampered)

    combined = Pskt.from_json(signer_a.to_json()).combine(Pskt.from_json(signer_c.to_json()))
    tx = combined.extract_tx()

    # the extracted multisig spend mines into a valid block
    mgr.validate_and_insert_transaction(tx)
    blk2 = mgr.get_block_template(miner.miner_data)
    assert any(t.id() == tx.id() for t in blk2.transactions[1:])
    assert c.validate_and_insert_block(blk2) in ("utxo_valid", "utxo_pending")
