"""graftlint tests: the checkers on seeded fixtures, pragma semantics,
the v2 whole-program fixpoint engine (transitive chains, recursion,
cross-module dispatch), lifecycle/exception-path/env-knob protocols, the
CI ratchet, and the full-repo self-run.

Fixtures are written to tmp_path and linted with run_project — the lint
is AST-only, so fixture code is never imported or executed (a fixture may
freely reference names that don't resolve).
"""

from __future__ import annotations

import json
import os
import textwrap

import kaspa_tpu.analysis.checkers  # noqa: F401 - registers the checkers
from kaspa_tpu.analysis import run_project
from kaspa_tpu.analysis.__main__ import main as lint_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(tmp_path, files: dict[str, str]) -> dict:
    for rel, body in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(body))
    return run_project([str(tmp_path)], root=str(tmp_path))


def _ids(report: dict) -> set[str]:
    return {f["checker"] for f in report["findings"]}


# --- blocking-under-lock --------------------------------------------------


def test_blocking_under_lock_direct(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import time

        def bad(self):
            with self._lock:
                time.sleep(0.1)
                self.fut.result()

        def fine(self):
            with self._lock:
                x = 1
            self.fut.result()
    """})
    lines = [(f["line"], f["checker"]) for f in report["findings"]]
    assert (6, "blocking-under-lock") in lines  # sleep under lock
    assert (7, "blocking-under-lock") in lines  # .result() under lock
    assert not any(line > 9 for line, _ in lines)
    assert report["ok"] is False


def test_blocking_under_lock_condvar_wait_exempt(tmp_path):
    # a condition-variable wait RELEASES the lock — exempt by receiver
    # naming convention; an Event.wait parks while still holding it
    report = _lint(tmp_path, {"mod.py": """
        def ok(self):
            with self._mu:
                self._cv.wait(0.5)

        def bad(self):
            with self._mu:
                self._event.wait(0.5)
    """})
    lines = [f["line"] for f in report["findings"] if f["checker"] == "blocking-under-lock"]
    assert lines == [8]


def test_blocking_under_lock_one_hop_expansion(tmp_path):
    report = _lint(tmp_path, {"a.py": """
        import time

        def helper():
            time.sleep(1.0)

        def caller(self):
            with self._lock:
                helper()
    """})
    msgs = [f for f in report["findings"] if f["checker"] == "blocking-under-lock"]
    assert len(msgs) == 1 and msgs[0]["line"] == 9
    assert "blocks transitively" in msgs[0]["message"]
    assert "a.py:5" in msgs[0]["message"]


def test_one_hop_skips_ambiguous_names(tmp_path):
    # two project-wide definitions of the same bare name: not expanded
    report = _lint(tmp_path, {
        "a.py": """
            import time

            def helper():
                time.sleep(1.0)
        """,
        "b.py": """
            def helper():
                return 1

            def caller(self):
                with self._lock:
                    helper()
        """,
    })
    assert not [f for f in report["findings"] if f["checker"] == "blocking-under-lock"]


# --- raw-lock -------------------------------------------------------------


def test_raw_lock_flags_constructions(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import threading

        a = threading.Lock()
        b = threading.RLock()
        c = threading.Condition()
        d = threading.Condition(a)
        e = threading.Event()
    """})
    lines = sorted(f["line"] for f in report["findings"] if f["checker"] == "raw-lock")
    assert lines == [4, 5, 6]  # bound Condition(a) and Event are fine


def test_raw_lock_exempts_sync_module(tmp_path):
    report = _lint(tmp_path, {"utils/sync.py": """
        import threading

        a = threading.Lock()
    """})
    assert not report["findings"]


# --- tracer-hazard --------------------------------------------------------


def test_tracer_hazard_in_jit_bodies(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import functools
        import jax
        import numpy as np

        _CACHE = {}

        @functools.lru_cache(maxsize=None)
        def cached_helper(x):
            return x

        @jax.jit
        def traced(x):
            _CACHE[1] = x
            y = int(x)
            z = np.add(x, x)
            w = cached_helper(x)
            for i in range(100):
                y = y + i
            return y + z + w
    """})
    msgs = [f["message"] for f in report["findings"] if f["checker"] == "tracer-hazard"]
    assert any("module-level dict" in m for m in msgs)
    assert any("coerces with int()" in m for m in msgs)
    assert any("np.add" in m for m in msgs)
    assert any("lru_cache'd" in m for m in msgs)
    assert any("100-iteration" in m for m in msgs)


def test_tracer_hazard_ignores_host_code_and_factories(tmp_path):
    # the mesh.py idiom: an lru_cache'd FACTORY that builds a jit callable
    # is consulted outside the trace; hazards only count inside jit bodies
    report = _lint(tmp_path, {"mod.py": """
        import functools
        import jax
        import numpy as np

        _CACHE = {}

        @functools.lru_cache(maxsize=None)
        def kernel_factory(n):
            def inner(x):
                return x + n
            return jax.jit(inner)

        def host_only(x):
            _CACHE[1] = int(x)
            return np.add(x, x)
    """})
    hits = [f for f in report["findings"] if f["checker"] == "tracer-hazard"]
    assert not hits


def test_tracer_hazard_catches_shard_map_reference(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import numpy as np
        from jax.experimental.shard_map import shard_map

        def kernel(x):
            return np.square(x)

        sharded = shard_map(kernel, mesh=None, in_specs=None, out_specs=None)
    """})
    hits = [f for f in report["findings"] if f["checker"] == "tracer-hazard"]
    assert len(hits) == 1 and "np.square" in hits[0]["message"]


# --- trace-ctx-handoff ----------------------------------------------------


def test_trace_ctx_handoff(tmp_path):
    report = _lint(tmp_path, {
        "pipeline/stage.py": """
            def bad(self, q, item):
                q.put((item, 1))

            def good(self, q, item, ctx):
                q.put((item, ctx))

            def object_payload(self, q, task):
                q.put(task)
        """,
        "other/stage.py": """
            def uninstrumented(self, q, item):
                q.put((item, 1))
        """,
    })
    hits = [(f["path"], f["line"]) for f in report["findings"] if f["checker"] == "trace-ctx-handoff"]
    assert hits == [("pipeline/stage.py", 3)]


# --- registry-hygiene -----------------------------------------------------


def test_registry_hygiene_fault_points_both_directions(tmp_path):
    report = _lint(tmp_path, {
        "resilience/faults.py": """
            FAULT_POINTS = {
                "a.live": "used below",
                "b.dead": "nothing fires this",
            }
        """,
        "mod.py": """
            from resilience.faults import FAULTS

            def f():
                FAULTS.fire("a.live")
                FAULTS.fire("c.uncataloged")
        """,
    })
    msgs = [f["message"] for f in report["findings"] if f["checker"] == "registry-hygiene"]
    assert any("'b.dead'" in m and "dead point" in m for m in msgs)
    assert any("'c.uncataloged'" in m and "missing from" in m for m in msgs)
    assert not any("'a.live'" in m for m in msgs)


def test_registry_hygiene_metric_names(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        from observability.core import REGISTRY

        _A = REGISTRY.counter("good_name", help="x")
        _B = REGISTRY.counter("Bad-Name", help="x")
        _C = REGISTRY.histogram("good_name", (1, 2), help="dup of _A")
    """})
    msgs = [f["message"] for f in report["findings"] if f["checker"] == "registry-hygiene"]
    assert any("'Bad-Name'" in m and "convention" in m for m in msgs)
    assert any("duplicate registration of 'good_name'" in m for m in msgs)


# --- unbounded-queue ------------------------------------------------------


def test_unbounded_queue_flags_missing_bounds(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import queue
        from collections import deque

        a = deque()
        b = deque([], None)
        c = deque([], 32)
        d = deque(maxlen=8)
        e = queue.Queue()
        f = queue.Queue(0)
        g = queue.Queue(maxsize=128)
        h = queue.SimpleQueue()
    """})
    lines = sorted(f["line"] for f in report["findings"] if f["checker"] == "unbounded-queue")
    # deque()/deque([], None), Queue()/Queue(0), SimpleQueue(); the bounded
    # constructions on lines 7, 8, 11 are the stated overflow policy
    assert lines == [5, 6, 9, 10, 12]


def test_unbounded_queue_exempts_utils_layer(tmp_path):
    # the primitives layer (utils/sync.py waiter deques etc.) owns its
    # buffers as leaf internals — the policy applies to subsystem queues
    report = _lint(tmp_path, {"utils/sync.py": """
        from collections import deque

        waiters = deque()
    """})
    assert not [f for f in report["findings"] if f["checker"] == "unbounded-queue"]


def test_unbounded_queue_pragma_suppression(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import queue

        q = queue.SimpleQueue()  # graftlint: allow(unbounded-queue) -- drained same-call, bounded by caller batch
    """})
    assert report["ok"] is True
    assert not report["findings"]
    assert [s["checker"] for s in report["suppressed"]] == ["unbounded-queue"]


# --- pragmas --------------------------------------------------------------


def test_pragma_suppresses_with_justification(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import threading

        a = threading.Lock()  # graftlint: allow(raw-lock) -- fixture leaf lock
    """})
    assert report["ok"] is True
    assert not report["findings"]
    assert len(report["suppressed"]) == 1
    assert report["suppressed"][0]["justification"] == "fixture leaf lock"


def test_pragma_on_preceding_comment_line(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import threading

        # graftlint: allow(raw-lock) -- covers the next line
        a = threading.Lock()
    """})
    assert report["ok"] is True and len(report["suppressed"]) == 1


def test_pragma_without_justification_is_an_error(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import threading

        a = threading.Lock()  # graftlint: allow(raw-lock)
    """})
    assert report["ok"] is False
    checkers = {f["checker"] for f in report["findings"]}
    # the raw-lock finding stays active AND the naked pragma is flagged
    assert checkers == {"raw-lock", "pragma"}


def test_pragma_only_matching_checker(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import time

        def f(self):
            with self._lock:
                time.sleep(1)  # graftlint: allow(raw-lock) -- wrong id, must not suppress
    """})
    assert any(f["checker"] == "blocking-under-lock" for f in report["findings"])


# --- CLI + self-run -------------------------------------------------------


def test_cli_exit_codes_and_json(tmp_path):
    bad = tmp_path / "seeded"
    bad.mkdir()
    (bad / "mod.py").write_text("import threading\nx = threading.Lock()\n")
    out = tmp_path / "LINT.json"
    rc = lint_main([str(bad), "--root", str(tmp_path), "--json", str(out), "-q"])
    assert rc == 1
    doc = json.loads(out.read_text())
    assert doc["ok"] is False and doc["counts"] == {"raw-lock": 1}

    good = tmp_path / "clean"
    good.mkdir()
    (good / "mod.py").write_text("x = 1\n")
    assert lint_main([str(good), "--root", str(tmp_path), "-q"]) == 0


def test_full_repo_self_run_is_clean():
    """The acceptance gate: the repo lints clean, and every suppression
    carries a justification."""
    report = run_project([os.path.join(REPO, "kaspa_tpu")], root=REPO)
    assert report["findings"] == [], [f["path"] + ":" + str(f["line"]) for f in report["findings"]]
    assert report["ok"] is True
    assert all(s["justification"] for s in report["suppressed"])
    # the migration actually happened: suppressions are the documented
    # exceptions, not the hot subsystems
    hot = [s for s in report["suppressed"]
           if s["checker"] == "raw-lock" and any(
               part in s["path"] for part in ("pipeline/", "ingest/", "serving/", "ops/dispatch"))]
    assert hot == []


# --- v2 engine: transitive chains, recursion, cross-module dispatch -------


def test_transitive_chain_depth_three(tmp_path):
    """A depth-3 chain the v1 one-hop expansion could not see."""
    report = _lint(tmp_path, {"mod.py": """
        import time

        def leaf():
            time.sleep(1.0)

        def mid():
            leaf()

        def top():
            mid()

        def caller(self):
            with self._lock:
                top()
    """})
    msgs = [f for f in report["findings"] if f["checker"] == "blocking-under-lock"]
    assert len(msgs) == 1 and msgs[0]["line"] == 15
    assert "depth 3" in msgs[0]["message"]
    # the rendered chain names every hop down to the primitive sleep
    assert "mid" in msgs[0]["message"] and "leaf" in msgs[0]["message"]


def test_transitive_chain_across_modules(tmp_path):
    report = _lint(tmp_path, {
        "dev.py": """
            import time

            def wait_device():
                time.sleep(1.0)
        """,
        "svc.py": """
            from dev import wait_device

            def run():
                wait_device()

            def caller(self):
                with self._lock:
                    run()
        """,
    })
    msgs = [f for f in report["findings"] if f["checker"] == "blocking-under-lock"]
    assert [f["line"] for f in msgs] == [9]
    assert "dev.py" in msgs[0]["message"]


def test_recursion_cycle_terminates_and_propagates(tmp_path):
    # self-recursion must not hang the fixpoint; the blocking fact still
    # propagates out of the cycle
    report = _lint(tmp_path, {"mod.py": """
        import time

        def walk(n):
            if n:
                walk(n - 1)
            time.sleep(0.1)

        def caller(self):
            with self._lock:
                walk(3)
    """})
    msgs = [f for f in report["findings"] if f["checker"] == "blocking-under-lock"]
    assert [f["line"] for f in msgs] == [11]


def test_mutual_recursion_terminates(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import time

        def ping(n):
            if n:
                pong(n - 1)

        def pong(n):
            time.sleep(0.1)
            ping(n)

        def caller(self):
            with self._lock:
                ping(2)
    """})
    msgs = [f for f in report["findings"] if f["checker"] == "blocking-under-lock"]
    assert [f["line"] for f in msgs] == [14]


def test_cross_module_method_dispatch_by_receiver_name(tmp_path):
    # self.engine.submit() resolves to Engine.submit by the receiver-name
    # heuristic even though the class lives in another module
    report = _lint(tmp_path, {
        "engine.py": """
            import time

            class Engine:
                def submit(self, job):
                    time.sleep(1.0)
        """,
        "node.py": """
            def caller(self):
                with self._lock:
                    self.engine.submit(None)
        """,
    })
    msgs = [f for f in report["findings"] if f["checker"] == "blocking-under-lock"]
    assert [f["line"] for f in msgs] == [4]
    assert "engine.py" in msgs[0]["message"]


def test_pragma_covers_decorated_multiline_statement(tmp_path):
    # the pragma sits on the decorator line; the offending call is three
    # lines into the statement span
    report = _lint(tmp_path, {"mod.py": """
        import time

        def caller(self):
            with self._lock:
                # graftlint: allow(blocking-under-lock) -- fixture: spans cover the whole statement
                x = time.sleep(
                    1.0,
                )
        return x
    """})
    assert not [f for f in report["findings"] if f["checker"] == "blocking-under-lock"]
    assert any(s["checker"] == "blocking-under-lock" for s in report["suppressed"])


# --- exception-path -------------------------------------------------------


def test_exception_path_leaks_lock_on_raise(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        def risky():
            raise ValueError("boom")

        def bad(self):
            self._mu.acquire()
            risky()
            self._mu.release()

        def good(self):
            self._mu.acquire()
            try:
                risky()
            finally:
                self._mu.release()
    """})
    msgs = [f for f in report["findings"] if f["checker"] == "exception-path"]
    assert [f["line"] for f in msgs] == [6]


# --- resource-lifecycle ---------------------------------------------------


def test_lifecycle_ticket_dropped_on_early_return(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        def bad(self, job):
            t = self.pool.submit(job)
            if self.closed:
                return None
            t.resolve(1)
            return t
    """})
    msgs = [f for f in report["findings"] if f["checker"] == "resource-lifecycle"]
    assert len(msgs) == 1
    assert "t" in msgs[0]["message"]


def test_lifecycle_double_resolve(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        def bad(self, job):
            t = self.pool.submit(job)
            t.resolve(1)
            t.resolve(2)
    """})
    msgs = [f for f in report["findings"] if f["checker"] == "resource-lifecycle"]
    assert len(msgs) == 1 and msgs[0]["line"] == 5


def test_lifecycle_clean_paths_are_clean(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        def both_branches(self, job):
            t = self.pool.submit(job)
            if self.ok:
                t.resolve(1)
            else:
                t.cancel()

        def raise_exit_needs_no_resolution(self, job):
            t = self.pool.submit(job)
            if self.closed:
                raise RuntimeError("shutting down")
            t.resolve(1)

        def escapes_to_caller(self, job):
            t = self.pool.submit(job)
            return t

        def consumer_side(self, t):
            t.wait(1.0)
            t.raise_for_status()
    """})
    assert not [f for f in report["findings"] if f["checker"] == "resource-lifecycle"]


def test_lifecycle_span_and_suppress_must_be_context_managers(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        from kaspa_tpu.observability import trace
        from kaspa_tpu.resilience import faults

        def bad(self):
            trace.span("validate")
            faults.suppress()

        def good(self):
            with trace.span("validate"):
                with faults.suppress():
                    pass
    """})
    msgs = [f for f in report["findings"] if f["checker"] == "resource-lifecycle"]
    assert [f["line"] for f in msgs] == [6, 7]


# --- env-knob -------------------------------------------------------------


def test_env_knob_reconciles_both_directions(tmp_path):
    (tmp_path / "KNOBS.md").write_text(
        "| Knob | Default | Owner | Doc |\n"
        "|------|---------|-------|-----|\n"
        "| `KASPA_TPU_ALPHA` | `'1'` | `mod.py` | documented knob |\n"
        "| `KASPA_TPU_GONE` | `'9'` | `mod.py` | reads nothing anymore |\n"
        "| `KASPA_TPU_BARE` | `'2'` | `mod.py` |  |\n"
    )
    report = _lint(tmp_path, {"mod.py": """
        import os

        A = os.environ.get("KASPA_TPU_ALPHA", "1")
        B = os.environ.get("KASPA_TPU_MISSING", "0")
        C = os.environ.get("KASPA_TPU_ALPHA", "7")
        D = os.environ.get("KASPA_TPU_BARE", "2")
    """})
    msgs = sorted(
        (f["path"], f["line"], f["message"]) for f in report["findings"] if f["checker"] == "env-knob"
    )
    texts = [m[2] for m in msgs]
    assert any("KASPA_TPU_MISSING" in t and "missing from KNOBS.md" in t for t in texts)
    assert any("KASPA_TPU_GONE" in t and "no longer read" in t for t in texts)
    assert any("KASPA_TPU_ALPHA" in t and "'7'" in t for t in texts)
    assert any("KASPA_TPU_BARE" in t and "Doc" in t for t in texts)


def test_knobs_md_regen_preserves_docs(tmp_path):
    from kaspa_tpu.analysis.core import Project, collect_files
    from kaspa_tpu.analysis.envknobs import render_knobs_md, scan_knob_sites

    (tmp_path / "mod.py").write_text(
        'import os\nX = os.environ.get("KASPA_TPU_ALPHA", "1")\n'
    )
    files = collect_files([str(tmp_path)], str(tmp_path))
    census = scan_knob_sites(Project(str(tmp_path), files))
    first = render_knobs_md(census, None)
    edited = first.replace(
        "| `KASPA_TPU_ALPHA` | `'1'` | `mod.py` |  |",
        "| `KASPA_TPU_ALPHA` | `'1'` | `mod.py` | hand-written doc |",
    )
    assert "hand-written doc" in edited
    again = render_knobs_md(census, edited)
    assert "hand-written doc" in again


# --- kernel catalog -------------------------------------------------------


def test_kernel_catalog_enumeration():
    from kaspa_tpu.ops import kernel_catalog as cat

    rows = cat.enumerate_signatures()
    fams = {r["family"] for r in rows}
    assert fams == {"ladder", "aggregate", "muhash", "ecdsa"}
    for r in rows:
        assert r["bucket"] % r["mesh"] == 0
        assert r["shard"] >= 8
        assert cat.covered(r["family"], r["bucket"]), r
    assert all(r["mesh"] == 1 for r in rows if r["family"] == "muhash")
    # every coverage rule is live
    reach = {(r["family"], r["bucket"]) for r in rows}
    for fam, lo, hi in cat.WARM_COVERAGE:
        assert any(f == fam and lo <= b <= hi for f, b in reach), (fam, lo, hi)


# --- ratchet --------------------------------------------------------------


def test_ratchet_blocks_growth_allows_shrink():
    from kaspa_tpu.analysis.__main__ import check_ratchet

    base = {"suppressed": [{}] * 3, "counts": {"raw-lock": 1}}
    same = {"suppressed": [{}] * 3, "counts": {"raw-lock": 1}}
    assert check_ratchet(base, same) == []
    shrunk = {"suppressed": [{}] * 2, "counts": {"raw-lock": 0}}
    assert check_ratchet(base, shrunk) == []
    more_supp = {"suppressed": [{}] * 4, "counts": {}}
    assert any("suppression count grew" in f for f in check_ratchet(base, more_supp))
    more_findings = {"suppressed": [{}] * 3, "counts": {"raw-lock": 2}}
    assert any("raw-lock" in f for f in check_ratchet(base, more_findings))
    new_checker = {"suppressed": [], "counts": {"env-knob": 1}}
    assert any("env-knob" in f for f in check_ratchet(base, new_checker))
    assert check_ratchet(None, same) != []
