"""graftlint tests: the six checkers on seeded fixtures, pragma
semantics, one-hop call-graph expansion, and the full-repo self-run.

Fixtures are written to tmp_path and linted with run_project — the lint
is AST-only, so fixture code is never imported or executed (a fixture may
freely reference names that don't resolve).
"""

from __future__ import annotations

import json
import os
import textwrap

import kaspa_tpu.analysis.checkers  # noqa: F401 - registers the checkers
from kaspa_tpu.analysis import run_project
from kaspa_tpu.analysis.__main__ import main as lint_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(tmp_path, files: dict[str, str]) -> dict:
    for rel, body in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(body))
    return run_project([str(tmp_path)], root=str(tmp_path))


def _ids(report: dict) -> set[str]:
    return {f["checker"] for f in report["findings"]}


# --- blocking-under-lock --------------------------------------------------


def test_blocking_under_lock_direct(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import time

        def bad(self):
            with self._lock:
                time.sleep(0.1)
                self.fut.result()

        def fine(self):
            with self._lock:
                x = 1
            self.fut.result()
    """})
    lines = [(f["line"], f["checker"]) for f in report["findings"]]
    assert (6, "blocking-under-lock") in lines  # sleep under lock
    assert (7, "blocking-under-lock") in lines  # .result() under lock
    assert not any(line > 9 for line, _ in lines)
    assert report["ok"] is False


def test_blocking_under_lock_condvar_wait_exempt(tmp_path):
    # a condition-variable wait RELEASES the lock — exempt by receiver
    # naming convention; an Event.wait parks while still holding it
    report = _lint(tmp_path, {"mod.py": """
        def ok(self):
            with self._mu:
                self._cv.wait(0.5)

        def bad(self):
            with self._mu:
                self._event.wait(0.5)
    """})
    lines = [f["line"] for f in report["findings"] if f["checker"] == "blocking-under-lock"]
    assert lines == [8]


def test_blocking_under_lock_one_hop_expansion(tmp_path):
    report = _lint(tmp_path, {"a.py": """
        import time

        def helper():
            time.sleep(1.0)

        def caller(self):
            with self._lock:
                helper()
    """})
    msgs = [f for f in report["findings"] if f["checker"] == "blocking-under-lock"]
    assert len(msgs) == 1 and msgs[0]["line"] == 9
    assert "blocks indirectly" in msgs[0]["message"]
    assert "a.py:5" in msgs[0]["message"]


def test_one_hop_skips_ambiguous_names(tmp_path):
    # two project-wide definitions of the same bare name: not expanded
    report = _lint(tmp_path, {
        "a.py": """
            import time

            def helper():
                time.sleep(1.0)
        """,
        "b.py": """
            def helper():
                return 1

            def caller(self):
                with self._lock:
                    helper()
        """,
    })
    assert not [f for f in report["findings"] if f["checker"] == "blocking-under-lock"]


# --- raw-lock -------------------------------------------------------------


def test_raw_lock_flags_constructions(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import threading

        a = threading.Lock()
        b = threading.RLock()
        c = threading.Condition()
        d = threading.Condition(a)
        e = threading.Event()
    """})
    lines = sorted(f["line"] for f in report["findings"] if f["checker"] == "raw-lock")
    assert lines == [4, 5, 6]  # bound Condition(a) and Event are fine


def test_raw_lock_exempts_sync_module(tmp_path):
    report = _lint(tmp_path, {"utils/sync.py": """
        import threading

        a = threading.Lock()
    """})
    assert not report["findings"]


# --- tracer-hazard --------------------------------------------------------


def test_tracer_hazard_in_jit_bodies(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import functools
        import jax
        import numpy as np

        _CACHE = {}

        @functools.lru_cache(maxsize=None)
        def cached_helper(x):
            return x

        @jax.jit
        def traced(x):
            _CACHE[1] = x
            y = int(x)
            z = np.add(x, x)
            w = cached_helper(x)
            for i in range(100):
                y = y + i
            return y + z + w
    """})
    msgs = [f["message"] for f in report["findings"] if f["checker"] == "tracer-hazard"]
    assert any("module-level dict" in m for m in msgs)
    assert any("coerces with int()" in m for m in msgs)
    assert any("np.add" in m for m in msgs)
    assert any("lru_cache'd" in m for m in msgs)
    assert any("100-iteration" in m for m in msgs)


def test_tracer_hazard_ignores_host_code_and_factories(tmp_path):
    # the mesh.py idiom: an lru_cache'd FACTORY that builds a jit callable
    # is consulted outside the trace; hazards only count inside jit bodies
    report = _lint(tmp_path, {"mod.py": """
        import functools
        import jax
        import numpy as np

        _CACHE = {}

        @functools.lru_cache(maxsize=None)
        def kernel_factory(n):
            def inner(x):
                return x + n
            return jax.jit(inner)

        def host_only(x):
            _CACHE[1] = int(x)
            return np.add(x, x)
    """})
    hits = [f for f in report["findings"] if f["checker"] == "tracer-hazard"]
    assert not hits


def test_tracer_hazard_catches_shard_map_reference(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import numpy as np
        from jax.experimental.shard_map import shard_map

        def kernel(x):
            return np.square(x)

        sharded = shard_map(kernel, mesh=None, in_specs=None, out_specs=None)
    """})
    hits = [f for f in report["findings"] if f["checker"] == "tracer-hazard"]
    assert len(hits) == 1 and "np.square" in hits[0]["message"]


# --- trace-ctx-handoff ----------------------------------------------------


def test_trace_ctx_handoff(tmp_path):
    report = _lint(tmp_path, {
        "pipeline/stage.py": """
            def bad(self, q, item):
                q.put((item, 1))

            def good(self, q, item, ctx):
                q.put((item, ctx))

            def object_payload(self, q, task):
                q.put(task)
        """,
        "other/stage.py": """
            def uninstrumented(self, q, item):
                q.put((item, 1))
        """,
    })
    hits = [(f["path"], f["line"]) for f in report["findings"] if f["checker"] == "trace-ctx-handoff"]
    assert hits == [("pipeline/stage.py", 3)]


# --- registry-hygiene -----------------------------------------------------


def test_registry_hygiene_fault_points_both_directions(tmp_path):
    report = _lint(tmp_path, {
        "resilience/faults.py": """
            FAULT_POINTS = {
                "a.live": "used below",
                "b.dead": "nothing fires this",
            }
        """,
        "mod.py": """
            from resilience.faults import FAULTS

            def f():
                FAULTS.fire("a.live")
                FAULTS.fire("c.uncataloged")
        """,
    })
    msgs = [f["message"] for f in report["findings"] if f["checker"] == "registry-hygiene"]
    assert any("'b.dead'" in m and "dead point" in m for m in msgs)
    assert any("'c.uncataloged'" in m and "missing from" in m for m in msgs)
    assert not any("'a.live'" in m for m in msgs)


def test_registry_hygiene_metric_names(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        from observability.core import REGISTRY

        _A = REGISTRY.counter("good_name", help="x")
        _B = REGISTRY.counter("Bad-Name", help="x")
        _C = REGISTRY.histogram("good_name", (1, 2), help="dup of _A")
    """})
    msgs = [f["message"] for f in report["findings"] if f["checker"] == "registry-hygiene"]
    assert any("'Bad-Name'" in m and "convention" in m for m in msgs)
    assert any("duplicate registration of 'good_name'" in m for m in msgs)


# --- unbounded-queue ------------------------------------------------------


def test_unbounded_queue_flags_missing_bounds(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import queue
        from collections import deque

        a = deque()
        b = deque([], None)
        c = deque([], 32)
        d = deque(maxlen=8)
        e = queue.Queue()
        f = queue.Queue(0)
        g = queue.Queue(maxsize=128)
        h = queue.SimpleQueue()
    """})
    lines = sorted(f["line"] for f in report["findings"] if f["checker"] == "unbounded-queue")
    # deque()/deque([], None), Queue()/Queue(0), SimpleQueue(); the bounded
    # constructions on lines 7, 8, 11 are the stated overflow policy
    assert lines == [5, 6, 9, 10, 12]


def test_unbounded_queue_exempts_utils_layer(tmp_path):
    # the primitives layer (utils/sync.py waiter deques etc.) owns its
    # buffers as leaf internals — the policy applies to subsystem queues
    report = _lint(tmp_path, {"utils/sync.py": """
        from collections import deque

        waiters = deque()
    """})
    assert not [f for f in report["findings"] if f["checker"] == "unbounded-queue"]


def test_unbounded_queue_pragma_suppression(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import queue

        q = queue.SimpleQueue()  # graftlint: allow(unbounded-queue) -- drained same-call, bounded by caller batch
    """})
    assert report["ok"] is True
    assert not report["findings"]
    assert [s["checker"] for s in report["suppressed"]] == ["unbounded-queue"]


# --- pragmas --------------------------------------------------------------


def test_pragma_suppresses_with_justification(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import threading

        a = threading.Lock()  # graftlint: allow(raw-lock) -- fixture leaf lock
    """})
    assert report["ok"] is True
    assert not report["findings"]
    assert len(report["suppressed"]) == 1
    assert report["suppressed"][0]["justification"] == "fixture leaf lock"


def test_pragma_on_preceding_comment_line(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import threading

        # graftlint: allow(raw-lock) -- covers the next line
        a = threading.Lock()
    """})
    assert report["ok"] is True and len(report["suppressed"]) == 1


def test_pragma_without_justification_is_an_error(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import threading

        a = threading.Lock()  # graftlint: allow(raw-lock)
    """})
    assert report["ok"] is False
    checkers = {f["checker"] for f in report["findings"]}
    # the raw-lock finding stays active AND the naked pragma is flagged
    assert checkers == {"raw-lock", "pragma"}


def test_pragma_only_matching_checker(tmp_path):
    report = _lint(tmp_path, {"mod.py": """
        import time

        def f(self):
            with self._lock:
                time.sleep(1)  # graftlint: allow(raw-lock) -- wrong id, must not suppress
    """})
    assert any(f["checker"] == "blocking-under-lock" for f in report["findings"])


# --- CLI + self-run -------------------------------------------------------


def test_cli_exit_codes_and_json(tmp_path):
    bad = tmp_path / "seeded"
    bad.mkdir()
    (bad / "mod.py").write_text("import threading\nx = threading.Lock()\n")
    out = tmp_path / "LINT.json"
    rc = lint_main([str(bad), "--root", str(tmp_path), "--json", str(out), "-q"])
    assert rc == 1
    doc = json.loads(out.read_text())
    assert doc["ok"] is False and doc["counts"] == {"raw-lock": 1}

    good = tmp_path / "clean"
    good.mkdir()
    (good / "mod.py").write_text("x = 1\n")
    assert lint_main([str(good), "--root", str(tmp_path), "-q"]) == 0


def test_full_repo_self_run_is_clean():
    """The acceptance gate: the repo lints clean, and every suppression
    carries a justification."""
    report = run_project([os.path.join(REPO, "kaspa_tpu")], root=REPO)
    assert report["findings"] == [], [f["path"] + ":" + str(f["line"]) for f in report["findings"]]
    assert report["ok"] is True
    assert all(s["justification"] for s in report["suppressed"])
    # the migration actually happened: suppressions are the documented
    # exceptions, not the hot subsystems
    hot = [s for s in report["suppressed"]
           if s["checker"] == "raw-lock" and any(
               part in s["path"] for part in ("pipeline/", "ingest/", "serving/", "ops/dispatch"))]
    assert hot == []
