"""MuHash golden vectors + device tree-product equivalence.

Vectors from crypto/muhash/src/lib.rs tests (EMPTY_MUHASH, the three
UTXO-style vectors with cumulative combination, pre-computed set hash) —
validates the Blake2b element hash, the rand_chacha-compatible ChaCha20
expansion, and the GF(2**3072 - 1103717) arithmetic end to end.
"""

import random

import numpy as np

from kaspa_tpu.crypto.muhash import EMPTY_MUHASH, PRIME, MuHash, data_to_element

V1 = bytes(
    [152, 32, 81, 253, 30, 75, 167, 68, 187, 190, 104, 14, 31, 238, 20, 103, 123, 161, 163, 195, 84, 11, 247, 177, 205,
     182, 6, 232, 87, 35, 62, 14, 0, 0, 0, 0, 1, 0, 0, 0, 1, 0, 242, 5, 42, 1, 0, 0, 0, 67, 65, 4, 150, 181, 56, 232, 83,
     81, 156, 114, 106, 44, 145, 230, 30, 193, 22, 0, 174, 19, 144, 129, 58, 98, 124, 102, 251, 139, 231, 148, 123, 230,
     60, 82, 218, 117, 137, 55, 149, 21, 212, 224, 166, 4, 248, 20, 23, 129, 230, 34, 148, 114, 17, 102, 191, 98, 30, 115,
     168, 44, 191, 35, 66, 200, 88, 238, 172]
)
V2 = bytes(
    [213, 253, 204, 84, 30, 37, 222, 28, 122, 90, 221, 237, 242, 72, 88, 184, 187, 102, 92, 159, 54, 239, 116, 78, 228, 44,
     49, 96, 34, 201, 15, 155, 0, 0, 0, 0, 2, 0, 0, 0, 1, 0, 242, 5, 42, 1, 0, 0, 0, 67, 65, 4, 114, 17, 168, 36, 245, 91,
     80, 82, 40, 228, 195, 213, 25, 76, 31, 207, 170, 21, 164, 86, 171, 223, 55, 249, 185, 217, 122, 64, 64, 175, 192, 115,
     222, 230, 200, 144, 100, 152, 79, 3, 56, 82, 55, 217, 33, 103, 193, 62, 35, 100, 70, 180, 23, 171, 121, 160, 252, 174,
     65, 42, 227, 49, 107, 119, 172]
)
V3 = bytes(
    [68, 246, 114, 34, 96, 144, 216, 93, 185, 169, 242, 251, 254, 95, 15, 150, 9, 179, 135, 175, 123, 229, 183, 251, 183,
     161, 118, 124, 131, 28, 158, 153, 0, 0, 0, 0, 3, 0, 0, 0, 1, 0, 242, 5, 42, 1, 0, 0, 0, 67, 65, 4, 148, 185, 211, 231,
     108, 91, 22, 41, 236, 249, 127, 255, 149, 215, 164, 187, 218, 200, 124, 194, 96, 153, 173, 162, 128, 102, 198, 255,
     30, 185, 25, 18, 35, 205, 137, 113, 148, 160, 141, 12, 39, 38, 197, 116, 127, 29, 180, 158, 140, 249, 14, 117, 220,
     62, 53, 80, 174, 155, 48, 8, 111, 60, 213, 170, 172]
)

MULTISET = [
    "2c379620fdf4ec0ac253cbe4ba82c2bbdc0fedac7fe0e452957d93757bbff5c1",
    "668bb292ef152c54db0f5714bf45ff8da7b1d41c0c5026ad655b2f9e1be67e21",
    "f40b20bdc43ef2f01a173b767cb9c6b8db5602eb535fcb9827385f9b0e3afaf4",
]
CUMULATIVE = [
    "2c379620fdf4ec0ac253cbe4ba82c2bbdc0fedac7fe0e452957d93757bbff5c1",
    "b15bd1124a6b52e64eda3c3023c587e455a79e748c8c954dd7411d0dbd973863",
    "e69c6e050410761648ce6276a81c8044b9efb1715ea6f6fb9f8cf7a8c1e80396",
]


def test_empty_muhash():
    assert EMPTY_MUHASH.hex() == "544eb3142c000f0ad2c76ac41f4222abbababed830eeafee4b6dc56b52d5cac0"


def test_golden_vectors():
    acc = MuHash()
    for i, data in enumerate([V1, V2, V3]):
        single = MuHash()
        single.add_element(data)
        assert single.finalize().hex() == MULTISET[i]
        acc.add_element(data)
        assert acc.finalize().hex() == CUMULATIVE[i]


def test_add_remove_commutes():
    rng = random.Random(4)
    datas = [rng.randbytes(40) for _ in range(6)]
    m = MuHash()
    for d in datas:
        m.add_element(d)
    for d in reversed(datas):
        m.remove_element(d)
    assert m.finalize() == EMPTY_MUHASH
    # order independence
    a = MuHash()
    b = MuHash()
    for d in datas:
        a.add_element(d)
    for d in reversed(datas):
        b.add_element(d)
    assert a.finalize() == b.finalize()


def test_combine_and_serialize_roundtrip():
    a = MuHash()
    a.add_element(V1)
    b = MuHash()
    b.add_element(V2)
    b.remove_element(V3)
    a.combine(b)
    ser = a.serialize()
    back = MuHash.deserialize(ser)
    assert back.finalize() == a.finalize()


def test_device_tree_product_matches_host():
    from kaspa_tpu.ops.muhash_ops import batch_product_ints

    rng = random.Random(5)
    # sizes straddle one bucket boundary but reuse the single 64-wide compile
    for n in (3, 64, 70):
        vals = [rng.randrange(PRIME) for _ in range(n)]
        exp = 1
        for v in vals:
            exp = exp * v % PRIME
        assert batch_product_ints(vals) == exp, n


def test_utxo_element_serialization():
    from kaspa_tpu.consensus.model import ScriptPublicKey, TransactionOutpoint, UtxoEntry
    from kaspa_tpu.crypto.muhash import serialize_utxo

    op = TransactionOutpoint(bytes(range(32)), 7)
    entry = UtxoEntry(1234, ScriptPublicKey(0, b"\xaa\xbb"), 999, True)
    data = serialize_utxo(op, entry)
    # outpoint(32+4) + daa(8) + amount(8) + coinbase(1) + spk ver(2) + len(8) + script(2)
    assert len(data) == 32 + 4 + 8 + 8 + 1 + 2 + 8 + 2
    m = MuHash()
    m.add_element(data)
    m.remove_utxo(op, entry)
    assert m.finalize() == EMPTY_MUHASH
