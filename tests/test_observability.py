"""Observability layer: span tracer, histograms, registry sinks, and the
four hardening fixes that rode along (wire truncation, reject flush,
store miss-sentinel guard, sub-1-BPS activation gate)."""

import random
import socket
import threading
import time

import pytest

from kaspa_tpu.observability import prom, trace
from kaspa_tpu.observability.core import (
    REGISTRY,
    Counter,
    CounterFamily,
    Histogram,
    Registry,
    _derive_rates,
    _merge_numeric,
)

# --- span tracer ----------------------------------------------------------


def test_span_nesting_paths():
    trace.set_capture(256)
    try:
        with trace.span("outer"):
            assert trace.current_path() == "outer"
            with trace.span("inner", key=1):
                assert trace.current_path() == "outer/inner"
            assert trace.current_path() == "outer"
        assert trace.current_path() == ""
        got = trace.drain()
        assert [s["path"] for s in got] == ["outer/inner", "outer"]
        assert got[0]["attrs"] == {"key": 1}
        assert got[0]["dur_us"] >= 0
        assert got[1]["name"] == "outer"
    finally:
        trace.set_capture(0)


def test_span_exception_safety():
    trace.set_capture(256)
    try:
        with pytest.raises(ValueError):
            with trace.span("bad"):
                raise ValueError("boom")
        # stack unwound: a fresh span is a root again
        with trace.span("after"):
            assert trace.current_path() == "after"
        got = trace.drain()
        assert got[0]["name"] == "bad"
        assert got[0]["attrs"]["error"] == "ValueError"
        assert got[1]["path"] == "after"
    finally:
        trace.set_capture(0)


def test_span_disabled_is_noop():
    trace.disable()
    try:
        s = trace.span("anything", a=1)
        assert s is trace.span("other")  # the shared no-op singleton
        with s:
            assert trace.current_path() == ""
    finally:
        trace.enable()


def test_span_overhead_budget():
    """Loose ceilings (CI machines vary): disabled ~0.2µs, enabled ~2µs
    measured locally; budgets 2µs / 10µs."""

    def per_use_us(n=20_000, trials=5):
        best = float("inf")
        for _ in range(trials):
            t0 = time.perf_counter()
            for _ in range(n):
                with trace.span("bench"):
                    pass
            best = min(best, time.perf_counter() - t0)
        return best / n * 1e6

    trace.set_capture(0)
    trace.disable()
    try:
        disabled = per_use_us()
    finally:
        trace.enable()
    enabled = per_use_us()
    assert disabled < 2.0, f"disabled span costs {disabled:.2f}µs"
    assert enabled < 10.0, f"enabled span costs {enabled:.2f}µs"


# --- histograms / counters ------------------------------------------------


def test_histogram_bucket_edges():
    h = Histogram("h", buckets=(1.0, 2.0, 5.0))
    for v in (0.5, 1.0, 1.5, 2.0, 5.0, 7.0):
        h.observe(v)
    snap = h.snapshot()
    # le semantics: value lands in the first bucket whose edge >= value
    assert snap["buckets"] == [[1.0, 2], [2.0, 2], [5.0, 1], ["+Inf", 1]]
    assert snap["count"] == 6
    assert snap["sum"] == pytest.approx(17.0)
    assert snap["min"] == 0.5 and snap["max"] == 7.0
    assert snap["p50"] == 2.0  # 3rd of 6 observations sits in the le=2 bucket


def test_histogram_quantile_edge_cases():
    # empty histogram: 0.0, explicitly — not NaN, not a stale max
    h = Histogram("h", buckets=(1.0, 2.0))
    assert h.quantile(0.5) == 0.0
    assert h.snapshot()["count"] == 0 and "p50" not in h.snapshot()
    # every observation in the +Inf overflow bucket: the edges carry no
    # upper bound, so the estimate is inf — the observed max would
    # understate the tail the caller asked about
    for v in (10.0, 20.0):
        h.observe(v)
    assert h.quantile(0.5) == float("inf")
    assert h.quantile(0.99) == float("inf")
    assert h.snapshot()["p99"] == float("inf")
    # mixed: quantiles below the overflow mass still resolve to edges
    for _ in range(6):
        h.observe(0.5)
    assert h.quantile(0.5) == 1.0
    assert h.quantile(0.99) == float("inf")
    # single observation on an exact edge
    g = Histogram("g", buckets=(1.0, 2.0))
    g.observe(1.0)
    assert g.quantile(0.5) == 1.0 and g.quantile(1.0) == 1.0


def test_counter_snapshot_deterministic():
    r = Registry()
    fam = r.counter_family("jobs", "kind")
    fam.inc("zeta", 3)
    fam.inc("alpha")
    r.counter("plain").inc(7)
    s1, s2 = r.snapshot(), r.snapshot()
    assert s1 == s2  # no mutation between snapshots -> identical trees
    assert list(s1["counters"]["jobs"].keys()) == ["alpha", "zeta"]  # sorted
    assert s1["counters"]["plain"] == 7
    import json

    json.dumps(s1)  # JSON-serializable end to end


def test_registry_collector_merge_and_rates():
    r = Registry()

    class Owner:
        def stats(self):
            return {"store": {"hits": 8, "misses": 2}}

    a, b = Owner(), Owner()
    r.register_collector("caches", a.stats)
    r.register_collector("caches", b.stats)
    snap = r.snapshot()
    assert snap["caches"]["store"]["hits"] == 16  # merged by sum
    assert snap["caches"]["store"]["hit_rate"] == pytest.approx(0.8)
    # dead owners are pruned, not crashed on
    del a, b
    import gc

    gc.collect()
    assert r.snapshot()["caches"] == {}


def test_merge_and_rates_helpers():
    d = _merge_numeric({"a": {"x": 1}}, {"a": {"x": 2, "y": 3}})
    assert d == {"a": {"x": 3, "y": 3}}
    t = {"c": {"hits": 0, "misses": 0}}
    _derive_rates(t)
    assert t["c"]["hit_rate"] == 0.0


# --- prometheus exporter --------------------------------------------------


def test_prom_render_cumulative_buckets():
    r = Registry()
    h = r.histogram("lat", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 2.0):
        h.observe(v)
    r.counter("reqs", help="requests").inc(4)
    fam = r.counter_family("bykind", "kind")
    fam.inc('we"ird\\', 2)  # label escaping
    text = prom.render(r)
    lines = text.splitlines()
    assert '# TYPE kaspa_lat histogram' in lines
    assert 'kaspa_lat_bucket{le="0.1"} 1' in lines
    assert 'kaspa_lat_bucket{le="1.0"} 2' in lines  # cumulative
    assert 'kaspa_lat_bucket{le="+Inf"} 3' in lines  # == _count
    assert 'kaspa_lat_count 3' in lines
    assert 'kaspa_reqs_total 4' in lines
    assert 'kaspa_bykind_total{kind="we\\"ird\\\\"} 2' in lines
    # every sample line is "name{labels} value" with a float-parseable value
    for ln in lines:
        if ln and not ln.startswith("#"):
            float(ln.rsplit(" ", 1)[1])


def test_prom_renders_global_registry_collectors():
    # the global registry always carries span_duration_seconds; rendering
    # must produce valid text even with collector gauge trees attached
    with trace.span("prom.check"):
        pass
    text = prom.render()
    assert "kaspa_span_duration_seconds" in text


def test_prom_family_headers_exactly_once():
    r = Registry()
    # a family with several cells must emit one header block, not one per
    # cell; distinct raw names folding to the same exposition name
    # ("a.b" and "a:b" both sanitize their dots) must not duplicate either
    fam = r.counter_family("jobs", "kind", help="job counts")
    fam.inc("alpha")
    fam.inc("beta")
    hfam = r.histogram_family("lat", "stage", (0.1, 1.0), help="latency")
    hfam.observe("x", 0.05)
    hfam.observe("y", 0.5)
    r.counter("dup.name", help="first").inc(1)
    r.counter("dup name", help="second").inc(2)  # same sanitized name
    lines = prom.render(r).splitlines()
    for needle in ("# TYPE kaspa_jobs counter", "# TYPE kaspa_lat histogram"):
        assert lines.count(needle) == 1
    type_names = [ln.split()[2] for ln in lines if ln.startswith("# TYPE ")]
    assert len(type_names) == len(set(type_names)), "duplicate # TYPE family"
    help_names = [ln.split()[2] for ln in lines if ln.startswith("# HELP ")]
    assert len(help_names) == len(set(help_names)), "duplicate # HELP family"
    # both dup counters still contribute their samples
    assert lines.count("kaspa_dup_name_total 1") == 1
    assert lines.count("kaspa_dup_name_total 2") == 1


def test_prom_help_text_escaped():
    r = Registry()
    r.counter("tricky", help="line one\nline two \\ backslash").inc(3)
    text = prom.render(r)
    # exposition 0.0.4: HELP escapes newline and backslash; the rendered
    # output must stay one physical line per comment
    assert "# HELP kaspa_tricky line one\\nline two \\\\ backslash" in text.splitlines()


def test_prom_full_live_registry_parses():
    """Parse-validate the ENTIRE live global registry (flight recorder,
    dispatch, serving, pipeline families all registered by import time):
    every non-comment line is ``name[{labels}] value`` with a
    float-parseable value, every # TYPE appears exactly once per family,
    and every typed sample's name resolves to its family via the
    histogram/counter suffix rules."""
    import re as _re

    from kaspa_tpu.observability import flight  # noqa: F401 - registers families

    with trace.span("prom.live"):
        pass
    lines = prom.render().splitlines()
    assert lines, "empty exposition"
    sample_re = _re.compile(r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (\S+)$')
    types: dict[str, str] = {}
    for ln in lines:
        if not ln:
            continue
        if ln.startswith("# TYPE "):
            _, _, name, mtype = ln.split(" ", 3)
            assert name not in types, f"duplicate # TYPE for {name}"
            assert mtype in ("counter", "histogram")
            types[name] = mtype
            continue
        if ln.startswith("# HELP "):
            assert "\n" not in ln  # escaped, single physical line
            continue
        m = sample_re.match(ln)
        assert m, f"unparseable sample line: {ln!r}"
        float(m.group(3))  # value must parse
    assert types, "no typed families rendered"
    # suffix rules: histogram samples are _bucket/_sum/_count, counter
    # samples are _total; every sample that wears a typed family's name
    # must agree with that family's declared type
    for ln in lines:
        if not ln or ln.startswith("#"):
            continue
        name = sample_re.match(ln).group(1)
        for fam, mtype in types.items():
            if name.startswith(fam + "_") or name == fam:
                suffix = name[len(fam):]
                allowed = ("_bucket", "_sum", "_count") if mtype == "histogram" else ("_total",)
                assert suffix in allowed, f"{name} disagrees with # TYPE {fam} {mtype}"
    # the always-present span family renders real samples on the page
    assert types.get("kaspa_span_duration_seconds") == "histogram"
    assert any(ln.startswith("kaspa_span_duration_seconds_bucket{") for ln in lines)
    # the flight recorder's histogram family is part of the live page
    assert types.get("kaspa_block_critical_path_ms") == "histogram"


# --- get_metrics sink -----------------------------------------------------


def test_get_metrics_observability_section():
    from kaspa_tpu.consensus.consensus import Consensus
    from kaspa_tpu.consensus.params import simnet_params
    from kaspa_tpu.p2p import Node
    from kaspa_tpu.rpc import RpcCoreService
    from kaspa_tpu.sim.simulator import Miner

    node = Node(Consensus(simnet_params(bps=2)), "obs-test")
    service = RpcCoreService(node.consensus, node.mining, address_prefix="kaspasim")
    miner = Miner(0, random.Random(5))
    for _ in range(6):
        node.submit_block(node.consensus.build_block_template(miner.miner_data, []))
    obs = service.get_metrics()["observability"]
    # per-stage span latencies: block intake runs through the pipeline
    spans = obs["histograms"]["span_duration_seconds"]
    assert "pipeline.stage" in spans and spans["pipeline.stage"]["count"] >= 6
    assert obs["histograms"]["pipeline_queue_wait_seconds"]["stage"]["count"] >= 6
    assert obs["counters"]["pipeline_tasks_submitted"] >= 6
    # store cache hit rates from the ConsensusStorage collector
    headers = obs["store_cache"]["headers"]
    assert headers["hits"] > 0 and "hit_rate" in headers
    # prometheus endpoint renders the same registry
    text = service.get_metrics_prometheus()
    assert 'kaspa_span_duration_seconds_bucket{stage="pipeline.stage"' in text
    node.pipeline.shutdown()


# --- trace_report CLI -----------------------------------------------------


def test_trace_report_aggregation(tmp_path):
    trace.set_capture(1024)
    try:
        with trace.span("root"):
            with trace.span("child"):
                pass
            with trace.span("child"):
                pass
        log = tmp_path / "spans.jsonl"
        n = trace.dump(str(log))
        assert n == 3
    finally:
        trace.set_capture(0)
    import sys

    sys.path.insert(0, "tools")
    try:
        import trace_report
    finally:
        sys.path.pop(0)
    spans = trace_report.load_spans(str(log))
    agg = trace_report.aggregate(spans)
    assert agg["root/child"]["count"] == 2
    assert agg["root"]["count"] == 1
    # self time excludes direct children
    assert agg["root"]["self_us"] <= agg["root"]["total_us"]
    report = trace_report.render_report(spans)
    assert "child" in report and "slowest" in report


# --- satellite: wire truncation hardening ---------------------------------


def test_wire_truncated_frames_raise():
    from kaspa_tpu.p2p import wire

    with pytest.raises(wire.WireError):
        wire._dec_smt_request(b"\x00" * 16)  # pp hash cut short
    # a valid smt chunk, then truncated at every prefix length
    full = wire._enc_smt_chunk(
        {
            "active": True,
            "meta": {
                "lanes_root": b"\x01" * 32, "pcd": b"\x02" * 32,
                "parent_seq_commit": b"\x03" * 32, "shortcut_block": b"\x04" * 32,
                "inactivity_shortcut": b"\x05" * 32,
            },
            "offset": 1,
            "lanes": [(b"\x06" * 32, b"\x07" * 32, 9)],
            "segment": [],
            "done": True,
        }
    )
    assert wire._dec_smt_chunk(full)["lanes"][0][2] == 9
    for cut in (0, 1, 40, 170, len(full) - 1):
        with pytest.raises(wire.WireError):
            wire._dec_smt_chunk(full[:cut])
    # bodies: hash cut short must not silently yield a 20-byte "hash"
    bodies = wire._enc_bodies([(b"\x08" * 32, [])])
    with pytest.raises(wire.WireError):
        wire._dec_bodies(bodies[:-12])


# --- satellite: reject frame flushed before close -------------------------


def _tcp_pair():
    """Loopback TCP pair (WirePeer wants a real getpeername address)."""
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)
    client = socket.create_connection(lsock.getsockname())
    server, _ = lsock.accept()
    lsock.close()
    return server, client


def test_reject_frame_delivered_before_close():
    from kaspa_tpu.p2p import wire
    from kaspa_tpu.p2p.node import MSG_REJECT, ProtocolError
    from kaspa_tpu.p2p.transport import WirePeer

    class StubNode:
        def __init__(self):
            self.lock = threading.Lock()
            self.peers = []

        def _handle(self, peer, msg_type, payload):
            raise ProtocolError("you are misbehaving")

    server_sock, client_sock = _tcp_pair()
    node = StubNode()
    peer = WirePeer(node, server_sock, outbound=False)
    node.peers.append(peer)
    peer.start()
    client_sock.sendall(wire.encode_frame(wire.MSG_PING, 1))

    def read_exactly(n):
        buf = b""
        while len(buf) < n:
            chunk = client_sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("closed before reject arrived")
            buf += chunk
        return buf

    client_sock.settimeout(5.0)
    msg_type, payload = wire.read_message(read_exactly)
    assert msg_type == MSG_REJECT
    assert "misbehaving" in payload
    client_sock.close()


def test_transport_flush_returns_false_when_dead():
    from kaspa_tpu.p2p.transport import WirePeer

    class StubNode:
        lock = threading.Lock()
        peers = []

    a, b = _tcp_pair()
    peer = WirePeer(StubNode(), a, outbound=False)
    peer.close()
    assert peer.flush(timeout=0.1) is False
    b.close()


# --- satellite: store cache miss-sentinel guard ---------------------------


def test_store_cache_rejects_none_values(tmp_path):
    from kaspa_tpu.consensus.stores import CachedDbAccess, ConsensusStorage
    from kaspa_tpu.storage.kv import KvStore

    storage = ConsensusStorage(db=KvStore(str(tmp_path / "t.db")))
    with pytest.raises(AssertionError):
        storage.ghostdag._access.write(b"\x01" * 32, None)
    # a decoder returning None must fail loudly, not loop as eternal misses
    acc = CachedDbAccess(storage, b"ZZ", lambda v: v, lambda b: None, budget=4)
    acc.write(b"\x02" * 32, b"payload")
    storage.flush()
    acc.clear_cache()
    with pytest.raises(AssertionError):
        acc.try_get(b"\x02" * 32)


def test_store_cache_stats_counts(tmp_path):
    from kaspa_tpu.consensus.stores import CachedDbAccess, ConsensusStorage
    from kaspa_tpu.storage.kv import KvStore

    storage = ConsensusStorage(db=KvStore(str(tmp_path / "t.db")))
    acc = CachedDbAccess(storage, b"ZZ", lambda v: v, lambda b: b, budget=2)
    for i in range(4):
        acc.write(bytes([i]) * 32, b"v%d" % i)
    storage.flush()  # unpins; evictions bring the cache back to budget
    assert acc._evictions >= 2
    acc.try_get(b"\x03" * 32)
    base_hits = acc._hits
    acc.try_get(b"\x03" * 32)
    assert acc._hits == base_hits + 1
    acc.try_get(b"\xee" * 32)  # absent everywhere
    assert acc._misses >= 1
    stats = storage.cache_stats()["ZZ"]
    assert stats["hits"] == acc._hits and stats["evictions"] == acc._evictions


# --- satellite: sub-1-BPS activation gate ---------------------------------


def test_activation_gate_blocks_sub_one_bps():
    from kaspa_tpu.p2p.node import _activation_gate_blocks

    assert _activation_gate_blocks(1000) == 86_400  # 1 BPS: one day of blocks
    assert _activation_gate_blocks(100) == 864_000  # 10 BPS
    # sub-1-BPS: the old round(1000/target) factor collapsed to 1 here,
    # inflating the one-day gate to ten days
    assert _activation_gate_blocks(10_000) == 8_640
    assert _activation_gate_blocks(500) == 172_800
