"""Device-runtime supervision: watchdog, hang requeue, canary re-probe,
warm-kernel manifest, and bounded dispatcher shutdown.

The invariants under test: a hung device call costs one watchdog deadline
of latency, never a lost or double-resolved batch (the host degraded lane
answers bit-identically and any late device result is discarded); breaker
HALF_OPEN probes come only from the background canary, never from a live
super-batch; and a wedged dispatcher thread cannot block daemon shutdown.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from kaspa_tpu.crypto import eclib, secp
from kaspa_tpu.ops import dispatch
from kaspa_tpu.resilience import breaker as breaker_mod
from kaspa_tpu.resilience import supervisor
from kaspa_tpu.resilience.breaker import CLOSED, HALF_OPEN, HUNG, OPEN, CircuitBreaker
from kaspa_tpu.resilience.faults import FAULTS


@pytest.fixture(autouse=True)
def _clean_supervision():
    """Every test starts and ends disarmed, unmanaged, breaker CLOSED."""
    FAULTS.clear()
    breaker_mod.device_breaker().reset()
    yield
    FAULTS.clear()
    breaker_mod.device_breaker().reset()
    breaker_mod.device_breaker().set_managed(False)


def _poll(pred, timeout_s: float = 5.0) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


# --- watchdog worker pool -------------------------------------------------


def test_supervised_passthrough_result_and_exception():
    assert supervisor.run_supervised(lambda: 41 + 1) == 42

    def boom():
        raise ValueError("device said no")

    with pytest.raises(ValueError, match="device said no"):
        supervisor.run_supervised(boom)


def test_watchdog_timeout_abandons_and_discards_late_result():
    pool = supervisor.WorkerPool()
    release = threading.Event()

    def slow():
        release.wait(5.0)
        return "late"

    with pytest.raises(supervisor.DeviceHangError) as ei:
        pool.run(slow, 0.1, "dispatch", kernel="k", jobs=3)
    assert ei.value.tier == "dispatch" and ei.value.jobs == 3
    snap = pool.snapshot()
    assert snap["timeouts"] == {"dispatch": 1} and snap["abandoned_threads"] == 1

    # the abandoned worker finishes later: its result is discarded (late),
    # and a fresh worker serves the next call untouched
    release.set()
    assert _poll(lambda: pool.snapshot()["late_results"] == 1, 2.0)
    assert pool.run(lambda: "ok", 1.0, "dispatch") == "ok"
    assert pool.snapshot()["completed"] == 1
    pool.shutdown()


def test_deadline_overrides_scoped_and_restored():
    base = supervisor.deadline_s("dispatch")
    with supervisor.deadline_overrides(dispatch_s=0.5):
        assert supervisor.deadline_s("dispatch") == 0.5
        with supervisor.deadline_overrides(compile_s=1.5):
            assert supervisor.deadline_s("dispatch") == 0.5
            assert supervisor.deadline_s("compile") == 1.5
        assert supervisor.deadline_s("dispatch") == 0.5
    assert supervisor.deadline_s("dispatch") == base


# --- hung dispatch -> host requeue, bit-identical -------------------------


def _signed_items(n: int, seed: int = 11) -> list:
    sk = (seed * 2 + 1) % eclib.N or 1
    pub = eclib.schnorr_pubkey(sk)
    items = []
    for i in range(n):
        msg = bytes([i]) * 32
        items.append((pub, msg, eclib.schnorr_sign(msg, sk)))
    return items


def test_hung_dispatch_requeues_bit_identical_and_trips_hung():
    items = _signed_items(3)
    # corrupt one signature: the mask must stay the exact eclib oracle
    pub, msg, sig = items[1]
    items[1] = (pub, msg, sig[:40] + bytes([sig[40] ^ 1]) + sig[41:])
    oracle = [eclib.schnorr_verify(p, m, s) for p, m, s in items]
    secp.schnorr_verify_batch(items)  # warm the bucket: tier stays "dispatch"

    br = breaker_mod.device_breaker()
    before = supervisor.verdict()["requeued"]["batches"]
    late_before = supervisor._POOL.snapshot()["late_results"]
    FAULTS.configure({"device.hang": {"mode": "wedge", "delay": 0.8, "hits": [1]}}, seed=0)
    with supervisor.deadline_overrides(dispatch_s=0.2):
        t0 = time.monotonic()
        mask = np.asarray(secp.schnorr_verify_batch(items))
        waited = time.monotonic() - t0

    assert mask.tolist() == oracle  # host lane answered, bit-identical
    assert waited < 0.8  # one deadline of stall, not the full hang
    assert br.state == OPEN and br.last_trip_cause == HUNG  # immediate trip
    assert supervisor.verdict()["requeued"]["batches"] == before + 1
    # the wedged worker unblocks at 0.8s; its outcome must be discarded
    assert _poll(lambda: supervisor._POOL.snapshot()["late_results"] > late_before, 3.0)


def test_compile_stall_requeues_and_leaves_shape_cold():
    from kaspa_tpu.resilience.sustain import _compile_stall_drill

    res = _compile_stall_drill(seed=3, stall_delay_s=0.6, compile_deadline_s=0.15)
    assert res["injected"] == 1
    assert res["all_valid"]  # host lane verified every triple correctly
    # the abandoned compile must not leave the shape marked warm
    assert res["shape_left_cold"]
    assert breaker_mod.device_breaker().last_trip_cause == HUNG


# --- canary prober --------------------------------------------------------


def test_hung_trip_recovers_via_injected_canary():
    br = breaker_mod.device_breaker()
    probes = []
    supervisor.install(pretrace=False, probe_fn=lambda: probes.append(1) or True)
    try:
        assert supervisor.installed()
        br.record_failure(cause=HUNG)
        assert br.state == OPEN
        # managed: live dispatches stay degraded even after the backoff
        assert br.allow() is False
        assert _poll(lambda: br.state == CLOSED, 10.0), br.snapshot()
        assert probes and br.recoveries >= 1
    finally:
        supervisor.shutdown()
    assert not supervisor.installed()


def test_canary_probe_cannot_race_live_dispatch():
    br = CircuitBreaker("race-test", failure_threshold=1, backoff_base=0.01)
    br.set_managed(True)
    br.record_failure(cause=HUNG)
    time.sleep(0.05)  # backoff elapsed: legacy allow() would go HALF_OPEN
    assert br.reopen_due()

    denied = []

    def hammer():
        denied.extend(br.allow() for _ in range(50))

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not any(denied)  # no live dispatch ever claimed the probe slot
    assert br.state == OPEN

    assert br.allow(probe=True) is True  # the canary's slot, exactly one
    assert br.state == HALF_OPEN
    assert br.allow(probe=True) is False  # second probe: already in flight
    br.record_success()
    assert br.state == CLOSED
    assert br.allow(probe=True) is False  # nothing to probe when CLOSED
    tos = [t["to"] for t in br.snapshot()["transitions"]]
    assert tos == [OPEN, HALF_OPEN, CLOSED]  # observable via the collector


# --- dispatcher shutdown under a hung device thread -----------------------


def _blocking_verify(monkeypatch):
    entered, release = threading.Event(), threading.Event()

    def fake(items):
        entered.set()
        release.wait(10.0)
        return np.ones(len(items), dtype=bool)

    monkeypatch.setattr(secp, "schnorr_verify_batch", fake)
    return entered, release


def test_close_abandons_hung_device_thread(monkeypatch):
    entered, release = _blocking_verify(monkeypatch)
    eng = dispatch.CoalescingDispatcher(64, 0.01)
    ticket = eng.submit("schnorr", _signed_items(2))
    assert entered.wait(5.0)  # dispatcher thread is wedged inside the call

    assert eng.close(timeout=0.2) is False  # bounded: did not join the hang
    stats = eng.stats()
    assert stats["abandoned"] and stats["unresolved_chunks"] == 0
    with pytest.raises(dispatch.DispatchAbandoned):
        ticket.wait(1.0)

    # the wedged thread finishes later: first resolution wins, the late
    # mask is discarded and the verdict does not flip
    release.set()
    time.sleep(0.1)
    with pytest.raises(dispatch.DispatchAbandoned):
        ticket.wait(0.1)


def test_dispatch_timeout_names_super_batch_and_verdict(monkeypatch):
    entered, release = _blocking_verify(monkeypatch)
    eng = dispatch.CoalescingDispatcher(64, 0.01)
    ticket = eng.submit("schnorr", _signed_items(2))
    assert entered.wait(5.0)
    with pytest.raises(dispatch.DispatchTimeout) as ei:
        ticket.wait(0.3)
    e = ei.value
    assert isinstance(e, TimeoutError)  # infrastructure, not consensus
    assert e.kind == "schnorr" and e.jobs == 2
    assert e.super_id is not None  # the super-batch had formed
    assert e.verdict["watchdog"] in ("on", "off")
    release.set()
    assert np.asarray(ticket.wait(5.0)).tolist() == [True, True]
    assert eng.close(timeout=5.0) is True


# --- warm-kernel manifest -------------------------------------------------


def test_warm_manifest_roundtrip(monkeypatch, tmp_path):
    path = tmp_path / "warm_manifest.json"
    monkeypatch.setenv("KASPA_TPU_WARM_MANIFEST", str(path))
    supervisor.note_shape("schnorr_verify", 8)
    supervisor.note_shape("schnorr_verify", 8)  # dedup
    supervisor.note_shape("ecdsa_verify", 16)
    assert len(supervisor.load_warm_entries()) == 2

    # an entry compiled under another backend must not be pretraced here
    import json

    doc = json.loads(path.read_text())
    doc["entries"].append({"kernel": "schnorr_verify", "bucket": 32, "mesh": 1,
                           "backend": "tpu-v6", "jax_version": "0.0.0"})
    path.write_text(json.dumps(doc))
    rep = supervisor.cache_report()
    assert rep["manifest_path"] == str(path)
    assert rep["entries_total"] == 3 and len(rep["entries"]) == 2

    traced = []
    monkeypatch.setattr(secp, "pretrace_bucket", lambda k, b: traced.append((k, b)) or "traced")
    rows = supervisor.pretrace_warm()
    assert traced == [("schnorr_verify", 8), ("ecdsa_verify", 16)]  # smallest first
    assert [r["status"] for r in rows] == ["traced", "traced"]
    assert all(r["seconds"] >= 0 for r in rows)

    rows = supervisor.pretrace_warm(budget_s=-1.0)  # exhausted budget
    assert [r["status"] for r in rows] == ["skipped:budget"] * 2


def test_pretrace_bucket_rejects_unknown():
    assert supervisor.run_supervised(lambda: None) is None  # smoke: pool alive
    assert secp.pretrace_bucket("no_such_kernel", 8).startswith("error:")
    assert secp.pretrace_bucket("schnorr_verify", 4).startswith("error:")


# --- the wedge drill, tier-1-fast variant ---------------------------------


def test_mini_wedge_drill_bit_identical(tmp_path):
    """End-to-end drill on a tiny hostile DAG: compile stall injected
    mid-run, canary-driven recovery, bit-identity against the fault-free
    replay, and exact requeue/ticket accounting.  (The 24-block variant
    with live dispatch hangs is tools/roundcheck.py's supervision lane.)"""
    from kaspa_tpu.resilience.sustain import run_wedge_drill
    from kaspa_tpu.sim.simulator import SimConfig

    cfg = SimConfig(bps=2, delay=2.0, num_miners=2, num_blocks=6,
                    txs_per_block=2, seed=5, hostile=True)
    report = run_wedge_drill(
        cfg, seed=5, out=str(tmp_path / "SUSTAIN_WEDGE.json"),
        hang_delay_s=1.5, dispatch_deadline_s=2.0,
        stall_delay_s=1.0, compile_deadline_s=0.3,
        hang_hits=(1,), recovery_timeout_s=15.0,
    )
    det, sup = report["deterministic"], report["supervisor"]
    assert det["matches_fault_free"], det
    assert sup["requeue_matches_injected"], sup
    assert sup["recovered"], sup
    assert report["compile_stall"]["all_valid"]
    assert report["compile_stall"]["shape_left_cold"]
    assert report["tickets"]["ok"], report["tickets"]
    assert report["breaker"]["managed"] is True
    assert (tmp_path / "SUSTAIN_WEDGE.json").exists()
