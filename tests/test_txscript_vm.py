"""TxScript VM tests: opcode semantics, limits, P2SH, sig checks.

Covers the engine rules of crypto/txscript/src/{lib.rs,opcodes/mod.rs,
data_stack.rs}: minimal pushes/numbers, conditionals, stack ops, the
201-op and 244-stack limits, P2SH redeem flow, multisig matching, CLTV/CSV,
and fast-path <-> VM consensus equivalence for standard P2PK spends.
"""

import random

import pytest

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.consensus.model import (
    SUBNETWORK_ID_NATIVE,
    ComputeCommit,
    ScriptPublicKey,
    Transaction,
    TransactionInput,
    TransactionOutpoint,
    TransactionOutput,
    UtxoEntry,
)
from kaspa_tpu.crypto import eclib
from kaspa_tpu.txscript import standard
from kaspa_tpu.txscript.vm import (
    TxScriptEngine,
    TxScriptError,
    as_bool,
    check_minimal_data_encoding,
    deserialize_i64,
    serialize_i64,
)

OP_1 = 0x51
OP_ADD = 0x93
OP_EQUAL = 0x87
OP_VERIFY = 0x69
OP_DUP = 0x76
OP_IF, OP_ELSE, OP_ENDIF = 0x63, 0x67, 0x68


def run_ok(script: bytes):
    TxScriptEngine().execute_standalone(script)


def run_err(script: bytes, match: str = ""):
    with pytest.raises(TxScriptError, match=match):
        TxScriptEngine().execute_standalone(script)


def test_number_codec_roundtrip():
    for v in (0, 1, -1, 127, -127, 128, -128, 255, -255, 2**31, -(2**31), 2**63 - 1, -(2**63) + 1):
        enc = serialize_i64(v)
        assert deserialize_i64(enc, True) == v, v
    # non-minimal encodings rejected
    for bad in (b"\x00", b"\x80", b"\x01\x00"):
        with pytest.raises(TxScriptError):
            check_minimal_data_encoding(bad)
    # 0xff00 would wrongly trip without the sign-conflict exception: 255 = [0xff, 0x00]
    check_minimal_data_encoding(bytes([0xFF, 0x00]))


def test_bool_semantics():
    assert not as_bool(b"")
    assert not as_bool(b"\x80")  # negative zero
    assert not as_bool(b"\x00\x00")
    assert as_bool(b"\x01")
    assert as_bool(b"\x00\x01")


def test_simple_arithmetic_script():
    # 1 + 2 == 3
    run_ok(bytes([OP_1, 0x52, OP_ADD, 0x53, OP_EQUAL]))
    run_err(bytes([OP_1, 0x52, OP_ADD, 0x54, OP_EQUAL]), "false stack")


def test_conditionals():
    # IF 2 ELSE 3 ENDIF == 2 (condition true)
    run_ok(bytes([OP_1, OP_IF, 0x52, OP_ELSE, 0x53, OP_ENDIF, 0x52, OP_EQUAL]))
    # condition false branch
    run_ok(bytes([0x00, OP_IF, 0x52, OP_ELSE, 0x53, OP_ENDIF, 0x53, OP_EQUAL]))
    # unbalanced conditional
    run_err(bytes([OP_1, OP_IF, 0x52]), "conditional")
    # non-minimal boolean condition
    run_err(bytes([0x52, OP_IF, OP_ENDIF]), "expected boolean")


def test_minimal_push_enforced():
    # pushing [1] via OpData1 must use OP_1
    run_err(bytes([0x01, 0x01, 0x75, OP_1]), "must use OP_1")
    # pushdata1 for 3 bytes must use direct push
    run_err(bytes([0x4C, 0x03, 1, 2, 3, 0x75, OP_1]), "direct push")


def test_op_limit():
    # 202 non-push ops (NOPs) exceed the 201 limit
    script = bytes([0x61] * 202) + bytes([OP_1])
    run_err(script, "operation limit")
    run_ok(bytes([0x61] * 200) + bytes([OP_1]))


def test_stack_size_limit():
    script = bytes([OP_1] * 245)
    run_err(script, "stack size")


def test_early_return_and_reserved():
    run_err(bytes([0x6A]), "early return")
    run_err(bytes([0x50]), "reserved")
    # reserved opcode inside a non-executed branch is fine
    run_ok(bytes([0x00, OP_IF, 0x50, OP_ENDIF, OP_1]))
    # disabled opcodes fail even in non-executed branches
    run_err(bytes([0x00, OP_IF, 0x8D, OP_ENDIF, OP_1]), "disabled")


def _p2pk_tx(seed=1):
    rng = random.Random(seed)
    sk = rng.randrange(1, eclib.N)
    pub = eclib.schnorr_pubkey(sk)
    spk = standard.pay_to_pub_key(pub)
    entry = UtxoEntry(10_000, spk, 5, False)
    tx = Transaction(
        0,
        [TransactionInput(TransactionOutpoint(b"\x03" * 32, 0), b"", 0, ComputeCommit.sigops(1))],
        [TransactionOutput(9_000, spk)],
        0,
        SUBNETWORK_ID_NATIVE,
        0,
        b"",
    )
    reused = chash.SigHashReusedValues()
    msg = chash.calc_schnorr_signature_hash(tx, [entry], 0, chash.SIG_HASH_ALL, reused)
    sig = eclib.schnorr_sign(msg, sk, rng.randbytes(32))
    tx.inputs[0].signature_script = standard.schnorr_signature_script(sig, chash.SIG_HASH_ALL)
    return tx, [entry], sig


def test_vm_executes_standard_p2pk():
    tx, entries, _sig = _p2pk_tx()
    TxScriptEngine(tx, entries, 0).execute()
    # corrupt signature -> false stack result
    bad = bytearray(tx.inputs[0].signature_script)
    bad[5] ^= 1
    tx.inputs[0].signature_script = bytes(bad)
    with pytest.raises(TxScriptError, match="false stack"):
        TxScriptEngine(tx, entries, 0).execute()


def test_vm_matches_fast_path_decision():
    """Fast-path batch checker and VM must agree on standard P2PK spends."""
    from kaspa_tpu.txscript.batch import BatchScriptChecker

    for seed, corrupt in ((3, False), (4, True)):
        tx, entries, _ = _p2pk_tx(seed)
        if corrupt:
            b = bytearray(tx.inputs[0].signature_script)
            b[8] ^= 1
            tx.inputs[0].signature_script = bytes(b)
        checker = BatchScriptChecker()
        checker.collect_tx(0, tx, entries)
        fast_result = checker.dispatch()[0]
        vm_failed = False
        try:
            TxScriptEngine(tx, entries, 0).execute()
        except TxScriptError:
            vm_failed = True
        assert (fast_result is not None) == vm_failed


def test_p2sh_redeem():
    # redeem script: OP_1 OP_EQUAL ; signature script pushes [1] then redeem
    redeem = bytes([OP_1, OP_EQUAL])
    spk = standard.pay_to_script_hash_script(redeem)
    entry = UtxoEntry(5_000, spk, 5, False)
    tx = Transaction(
        0,
        [TransactionInput(TransactionOutpoint(b"\x04" * 32, 0), b"", 0, ComputeCommit.sigops(0))],
        [],
        0,
        SUBNETWORK_ID_NATIVE,
        0,
        b"",
    )
    tx.inputs[0].signature_script = bytes([OP_1, len(redeem)]) + redeem
    TxScriptEngine(tx, [entry], 0).execute()
    # wrong redeem value fails
    tx.inputs[0].signature_script = bytes([0x52, len(redeem)]) + redeem
    with pytest.raises(TxScriptError):
        TxScriptEngine(tx, [entry], 0).execute()


def test_multisig_2_of_3():
    rng = random.Random(9)
    keys = [rng.randrange(1, eclib.N) for _ in range(3)]
    pubs = [eclib.schnorr_pubkey(k) for k in keys]
    # spk: OP_2 <pk1> <pk2> <pk3> OP_3 OP_CHECKMULTISIG
    spk_script = bytes([0x52]) + b"".join(bytes([32]) + p for p in pubs) + bytes([0x53, 0xAE])
    spk = ScriptPublicKey(0, spk_script)
    entry = UtxoEntry(10_000, spk, 5, False)
    tx = Transaction(
        0,
        [TransactionInput(TransactionOutpoint(b"\x05" * 32, 1), b"", 0, ComputeCommit.sigops(3))],
        [],
        0,
        SUBNETWORK_ID_NATIVE,
        0,
        b"",
    )
    reused = chash.SigHashReusedValues()
    msg = chash.calc_schnorr_signature_hash(tx, [entry], 0, chash.SIG_HASH_ALL, reused)
    sigs = [eclib.schnorr_sign(msg, k, rng.randbytes(32)) + bytes([chash.SIG_HASH_ALL]) for k in keys]
    # sign with keys 0 and 2 (order must match key order)
    tx.inputs[0].signature_script = bytes([len(sigs[0])]) + sigs[0] + bytes([len(sigs[2])]) + sigs[2]
    TxScriptEngine(tx, [entry], 0).execute()
    # wrong order (sig2 then sig0) fails with NullFail-style error
    tx.inputs[0].signature_script = bytes([len(sigs[2])]) + sigs[2] + bytes([len(sigs[0])]) + sigs[0]
    with pytest.raises(TxScriptError):
        TxScriptEngine(tx, [entry], 0).execute()


def test_cltv_and_csv():
    tx, entries, _ = _p2pk_tx(7)
    tx.lock_time = 100
    tx.inputs[0].sequence = 5
    # kaspa CLTV/CSV consume their operand (opcodes/mod.rs pop_raw), so no
    # OP_DROP is needed: <50> OP_CHECKLOCKTIMEVERIFY OP_1
    spk = ScriptPublicKey(0, bytes([0x01, 50, 0xB0, OP_1]))
    entries = [UtxoEntry(10, spk, 0, False)]
    tx.inputs[0].signature_script = b""
    TxScriptEngine(tx, entries, 0).execute()
    # stack locktime above tx locktime fails
    spk2 = ScriptPublicKey(0, bytes([0x01, 101, 0xB0, OP_1]))
    with pytest.raises(TxScriptError, match="locktime"):
        TxScriptEngine(tx, [UtxoEntry(10, spk2, 0, False)], 0).execute()
    # CSV: stack sequence 4 <= input sequence 5 passes (OP_4: minimal push)
    spk3 = ScriptPublicKey(0, bytes([0x54, 0xB1, OP_1]))
    TxScriptEngine(tx, [UtxoEntry(10, spk3, 0, False)], 0).execute()
    spk4 = ScriptPublicKey(0, bytes([0x56, 0xB1, OP_1]))
    with pytest.raises(TxScriptError, match="sequence"):
        TxScriptEngine(tx, [UtxoEntry(10, spk4, 0, False)], 0).execute()


def test_unknown_spk_version_accepted():
    tx, entries, _ = _p2pk_tx(8)
    entry = entries[0]
    from dataclasses import replace

    entries = [replace(entry, script_public_key=ScriptPublicKey(1, b"\xff\xff"))]
    tx.inputs[0].signature_script = b""
    TxScriptEngine(tx, entries, 0).execute()  # accepted without execution
