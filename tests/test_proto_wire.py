"""Protobuf wire engine, gRPC framing, and golden-vector round-trips.

The golden fixtures under tests/fixtures/proto/ pin the exact bytes the
vendored KaspadMessage schema produces for every message type — a schema
or codec change that moves wire bytes fails here first (regenerate with
tools/gen_proto_fixtures.py and commit the diff when intentional).
"""

import io
import json
import os

import pytest

from kaspa_tpu.p2p.proto import framing, schema, wire_format
from kaspa_tpu.p2p.proto.codec import (
    _CONVERTERS,
    ProtoError,
    decode_kaspad_message,
    encode_kaspad_message,
    tier_to_wire_version,
    wire_version_to_tier,
)
from kaspa_tpu.p2p.proto.vectors import sample_payloads
from kaspa_tpu.p2p.proto.wire_format import (
    ProtoWireError,
    decode_message,
    decode_varint,
    encode_message,
    encode_varint,
    zigzag_decode,
    zigzag_encode,
)

FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "fixtures", "proto")


# -- varint / zigzag -------------------------------------------------------


@pytest.mark.parametrize(
    "value,encoded",
    [
        (0, b"\x00"),
        (1, b"\x01"),
        (127, b"\x7f"),
        (128, b"\x80\x01"),
        (300, b"\xac\x02"),
        (1 << 32, b"\x80\x80\x80\x80\x10"),
        ((1 << 64) - 1, b"\xff\xff\xff\xff\xff\xff\xff\xff\xff\x01"),
    ],
)
def test_varint_known_vectors(value, encoded):
    assert encode_varint(value) == encoded
    assert decode_varint(encoded, 0) == (value, len(encoded))


def test_varint_negative_sign_extends_to_ten_bytes():
    enc = encode_varint(-1)
    assert len(enc) == 10  # proto3 int64 -1 is the canonical worst case
    assert decode_varint(enc, 0)[0] == (1 << 64) - 1


def test_varint_truncated_and_overlong_raise():
    with pytest.raises(ProtoWireError):
        decode_varint(b"\x80\x80", 0)  # continuation bit with no terminator
    with pytest.raises(ProtoWireError):
        decode_varint(b"\x80" * 10 + b"\x01", 0)  # 11 bytes


@pytest.mark.parametrize("v", [0, 1, -1, 2, -2, 0x7FFFFFFF, -0x80000000, (1 << 62), -(1 << 62)])
def test_zigzag_roundtrip(v):
    z = zigzag_encode(v)
    assert z >= 0
    assert zigzag_decode(z) == v


# -- unknown-field skip ----------------------------------------------------


def test_unknown_fields_are_skipped_and_counted():
    # a message with extra fields a vendored decoder has never heard of:
    # varint(900), bytes(901), fixed64(902), fixed32(903)
    desc = schema.PING  # {nonce=1 uint64}
    extra = (
        wire_format.encode_tag(900, wire_format.WT_VARINT)
        + encode_varint(7)
        + wire_format.encode_tag(901, wire_format.WT_LEN)
        + encode_varint(3)
        + b"abc"
        + wire_format.encode_tag(902, wire_format.WT_I64)
        + b"\x01" * 8
        + wire_format.encode_tag(903, wire_format.WT_I32)
        + b"\x02" * 4
    )
    data = encode_message(desc, {"nonce": 42}) + extra
    from kaspa_tpu.observability.core import REGISTRY

    skipped = REGISTRY.counter("p2p_proto_unknown_fields_skipped")
    before = skipped.value
    msg = decode_message(desc, data)
    assert msg["nonce"] == 42
    assert skipped.value == before + 4


def test_extension_fields_skip_cleanly_through_base_schema():
    # encode with our extension fields (>=1000), decode against a schema
    # copy WITHOUT them — the reference-decoder view.  The base payload
    # must survive unchanged.
    full = schema.BLOCK_HEADERS
    base = {"name": full["name"], "fields": {n: f for n, f in full["fields"].items() if n < 1000}}
    from kaspa_tpu.p2p.proto.vectors import sample_header

    hdrs = {"headers": [sample_header(1)], "done": True, "continuation": b"\x07" * 32}
    enc = encode_kaspad_message("blockheaders", hdrs)
    # peel the oneof envelope down to the chunk submessage
    outer = decode_message(schema.KASPAD_MESSAGE, enc)
    chunk_bytes = encode_message(full, outer["blockHeaders"])
    seen = decode_message(base, chunk_bytes)
    assert len(seen["blockHeaders"]) == 1
    assert "done" not in seen  # extension invisible to the base schema


# -- proto3 default skipping / deterministic bytes -------------------------


def test_defaults_not_emitted_and_deterministic():
    enc1 = encode_message(schema.PING, {"nonce": 0})
    assert enc1 == b""  # scalar default omitted
    v = {"protocolVersion": 10, "network": "kaspa-simnet", "id": b"\x01" * 16, "userAgent": "x"}
    assert encode_message(schema.VERSION, v) == encode_message(schema.VERSION, dict(reversed(v.items())))


# -- gRPC framing ----------------------------------------------------------


def test_grpc_frame_roundtrip():
    msg = b"\x12\x34\x56" * 100
    frame = framing.encode_grpc_frame(msg)
    assert frame[0] == 0
    assert len(frame) == framing.GRPC_FRAME_OVERHEAD + len(msg)
    r = io.BytesIO(frame)
    assert framing.read_grpc_frame(lambda n: r.read(n)) == msg


def test_grpc_frame_refuses_compression_and_reserved_bits():
    with pytest.raises(ProtoWireError):
        framing.decode_grpc_prefix(b"\x01\x00\x00\x00\x00")
    with pytest.raises(ProtoWireError):
        framing.decode_grpc_prefix(b"\x80\x00\x00\x00\x00")


def test_grpc_frame_bounds_length():
    import struct

    with pytest.raises(ProtoWireError):
        framing.decode_grpc_prefix(b"\x00" + struct.pack(">I", framing.MAX_GRPC_MESSAGE + 1))


# -- version negotiation mapping -------------------------------------------


def test_tier_version_mapping():
    from kaspa_tpu.p2p.node import MIN_PROTOCOL_VERSION, PROTOCOL_VERSION

    for tier in range(MIN_PROTOCOL_VERSION, PROTOCOL_VERSION + 1):
        assert tier_to_wire_version(tier) == tier
        assert wire_version_to_tier(tier) == tier
    # a future reference version clamps to our ceiling; the handshake then
    # negotiates min(local, peer) exactly like the custom wire
    assert wire_version_to_tier(PROTOCOL_VERSION + 5) == PROTOCOL_VERSION
    assert tier_to_wire_version(1) == MIN_PROTOCOL_VERSION


# -- golden vectors --------------------------------------------------------


def _fixture_types():
    with open(os.path.join(FIXTURE_DIR, "manifest.json")) as f:
        return sorted(json.load(f))


def test_fixture_set_covers_every_message_type():
    assert set(_fixture_types()) == set(_CONVERTERS)


@pytest.mark.parametrize("msg_type", _fixture_types())
def test_golden_vector_roundtrip(msg_type):
    with open(os.path.join(FIXTURE_DIR, f"{msg_type}.bin"), "rb") as f:
        pinned = f.read()
    payload = sample_payloads()[msg_type]
    # encode is byte-exact against the pinned fixture...
    assert encode_kaspad_message(msg_type, payload) == pinned
    # ...and the pinned bytes decode back to an equal payload
    got_type, got_payload = decode_kaspad_message(pinned)
    assert got_type == msg_type
    assert got_payload == payload
    # re-encode of the decoded payload is stable (no drift through decode)
    assert encode_kaspad_message(got_type, got_payload) == pinned


def test_unknown_message_type_raises():
    with pytest.raises(ProtoError):
        encode_kaspad_message("no-such-flow-message", {})


def test_empty_kaspad_message_raises():
    with pytest.raises(ProtoError):
        decode_kaspad_message(b"")
