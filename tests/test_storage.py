"""Native KV store tests: persistence, atomic batches, torn-tail recovery.

Reference behavior model: database/src/ (WriteBatch atomicity is the
crash-consistency foundation, SURVEY.md §5).
"""

import os

import pytest

from kaspa_tpu.storage.kv import KvStore, _NativeEngine, open_store


def test_native_build_and_roundtrip(tmp_path):
    path = str(tmp_path / "db.log")
    store = open_store(path)
    assert isinstance(store, _NativeEngine), "native engine should build on this image"
    store.put(b"a", b"1")
    store.put(b"bb", b"22")
    store.delete(b"a")
    assert store.get(b"a") is None
    assert store.get(b"bb") == b"22"
    assert len(store) == 1
    store.close()
    # reopen: state replayed from the log
    store2 = open_store(path)
    assert store2.get(b"bb") == b"22"
    assert store2.get(b"a") is None
    store2.close()


def test_atomic_batch_and_reopen(tmp_path):
    path = str(tmp_path / "db.log")
    kv = KvStore(path)
    with kv.batch() as b:
        for i in range(100):
            b.put(f"k{i}".encode(), f"v{i}".encode())
    kv.close()
    kv2 = KvStore(path)
    assert len(kv2.engine) == 100
    assert kv2.engine.get(b"k42") == b"v42"
    kv2.close()


def test_batch_abort_leaves_no_trace(tmp_path):
    path = str(tmp_path / "db.log")
    kv = KvStore(path)
    kv.engine.put(b"pre", b"existing")
    with pytest.raises(ValueError):
        with kv.batch() as b:
            b.put(b"doomed", b"1")
            raise ValueError("abort")
    assert kv.engine.get(b"doomed") is None
    # engine not stuck in a batch: subsequent writes work and persist
    kv.engine.put(b"post", b"2")
    kv.close()
    kv2 = KvStore(path)
    assert kv2.engine.get(b"doomed") is None
    assert kv2.engine.get(b"pre") == b"existing" and kv2.engine.get(b"post") == b"2"
    kv2.close()


def test_torn_tail_recovery(tmp_path):
    path = str(tmp_path / "db.log")
    store = open_store(path)
    store.put(b"good", b"data")
    store.close()
    # simulate a crash mid-batch: append garbage / truncated frame
    with open(path, "ab") as f:
        f.write(b"KBAT" + (1000).to_bytes(4, "little") + b"partial-batch-without-crc")
    store2 = open_store(path)
    assert store2.get(b"good") == b"data"  # valid prefix survives
    assert len(store2) == 1
    # store remains writable after recovery truncation
    store2.put(b"after", b"crash")
    store2.close()
    store3 = open_store(path)
    assert store3.get(b"after") == b"crash"
    store3.close()


def test_prefixed_stores(tmp_path):
    kv = KvStore(str(tmp_path / "db.log"))
    headers = kv.prefixed(b"\x01")
    ghostdag = kv.prefixed(b"\x02")
    headers.put(b"h1", b"header-bytes")
    ghostdag.put(b"h1", b"gd-bytes")
    assert headers.get(b"h1") == b"header-bytes"
    assert ghostdag.get(b"h1") == b"gd-bytes"
    assert headers.items() == [(b"h1", b"header-bytes")]
    kv.close()


def test_compaction(tmp_path):
    path = str(tmp_path / "db.log")
    store = open_store(path)
    for i in range(50):
        store.put(b"key", f"v{i}".encode())  # 50 versions of one key
    size_before = os.path.getsize(path)
    store.compact()
    size_after = os.path.getsize(path)
    assert size_after < size_before
    assert store.get(b"key") == b"v49"
    store.put(b"post", b"compact")  # still writable
    store.close()
    store2 = open_store(path)
    assert store2.get(b"key") == b"v49" and store2.get(b"post") == b"compact"
    store2.close()


def test_python_fallback_parity(tmp_path):
    path = str(tmp_path / "py.log")
    store = open_store(path, native=False)
    store.put(b"x", b"y")
    store.close()
    # the python engine writes the same frame format the native engine reads
    native = open_store(path, native=True)
    assert native.get(b"x") == b"y"
    native.close()


def test_engine_parity_prefix_ordering_and_batches(tmp_path):
    """Both engines must agree on the serving index's access patterns:
    prefix-stripped suffixes in ascending key order, exact prefix counts,
    and batch atomicity (buffered until commit, dropped on abandon)."""
    keys = [
        b"U\x00\x03abc" + bytes([i]) for i in (9, 1, 5, 3)
    ] + [b"U\x00\x03abd\x01", b"J\x00\x00\x00\x01", b"Mversion"]
    engines = []
    for native in (True, False):
        eng = open_store(str(tmp_path / f"parity-{int(native)}.log"), native=native)
        for i, k in enumerate(keys):
            eng.put(k, f"v{i}".encode())
        eng.delete(keys[1])
        engines.append(eng)
    native_eng, py_eng = engines
    for prefix in (b"U\x00\x03abc", b"U", b"J", b"M", b"nope"):
        assert native_eng.items_prefix(prefix) == py_eng.items_prefix(prefix)
        assert native_eng.keys_prefix(prefix) == py_eng.keys_prefix(prefix)
        assert native_eng.count_prefix(prefix) == py_eng.count_prefix(prefix)
    # ordering contract: suffixes come back sorted ascending
    suffixes = native_eng.keys_prefix(b"U\x00\x03abc")
    assert suffixes == sorted(suffixes) == [bytes([3]), bytes([5]), bytes([9])]

    # batch semantics: writes invisible pre-commit on BOTH engines is not a
    # requirement (the python engine buffers KvStore-side), but commit must
    # apply everything and an abandoned KvStore batch must apply nothing
    for native in (True, False):
        kv = KvStore(str(tmp_path / f"batch-{int(native)}.log"), native=native)
        with kv.batch() as b:
            b.put(b"k1", b"v1")
            b.put(b"k2", b"v2")
            b.delete(b"k1")
        assert kv.engine.get(b"k1") is None
        assert kv.engine.get(b"k2") == b"v2"
        with pytest.raises(RuntimeError):
            with kv.batch() as b:
                b.put(b"k3", b"v3")
                raise RuntimeError("abandon")
        assert kv.engine.get(b"k3") is None, "abandoned batch must not land"
        kv.close()
    for eng in engines:
        eng.close()
