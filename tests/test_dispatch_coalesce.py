"""Cross-block coalescing verify dispatch (ops/dispatch.py).

The contract under test: routing signature jobs through the coalescing
queue is invisible in results — masks and BatchScriptChecker decisions
are bit-identical to per-block blocking dispatch (verify masks are
per-lane functions of each triple; batch composition cannot change
them) — while jobs from multiple submitters merge into one super-batch.

Shape discipline: every device call here lands in the same padded
bucket-8 shape the other verify tests use (each new bucket costs a
fresh XLA compile on CPU, minutes of tier-1 budget).
"""

import hashlib
import json
import random
import threading
import time

import numpy as np
import pytest

from kaspa_tpu.observability.core import REGISTRY
from kaspa_tpu.ops import dispatch as coalesce


@pytest.fixture(autouse=True)
def _coalesce_off_after():
    yield
    coalesce.configure(0)


def _schnorr_items(n: int, corrupt_every: int = 4):
    from kaspa_tpu.crypto import eclib

    items = []
    for i in range(n):
        sk = i + 1
        msg = hashlib.sha256(bytes([i, n])).digest()
        sig = eclib.schnorr_sign(msg, sk)
        if corrupt_every and i % corrupt_every == corrupt_every - 1:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])
        items.append((eclib.schnorr_pubkey(sk), msg, sig))
    return items


# --- configuration ----------------------------------------------------------


def test_configure_modes():
    assert coalesce.configure(0) == 0
    assert coalesce.active() is None
    assert coalesce.drain() is True  # no-op when disabled
    assert coalesce.configure("off") == 0
    assert coalesce.configure(16) == 16
    assert coalesce.active() is not None and coalesce.active().target == 16
    assert coalesce.configure(2) == 8  # clamps up to the min bucket
    assert coalesce.configure(1 << 20) == 16384  # clamps down to the max
    state = REGISTRY.snapshot()["dispatch"]
    assert state["enabled"] and state["target"] == 16384
    assert coalesce.configure(None) == 0  # env default: off
    assert REGISTRY.snapshot()["dispatch"]["enabled"] is False


def test_configure_auto_seeds_target_from_sweep(tmp_path, monkeypatch):
    sweep = tmp_path / "BENCH_SWEEP.json"
    sweep.write_text(json.dumps({"best": {"schnorr/mesh1": {"batch": 512, "value": 1.0}}}))
    monkeypatch.setenv("KASPA_TPU_BENCH_SWEEP_PATH", str(sweep))
    assert coalesce.configure("auto") == 512
    # no sweep file -> documented default
    monkeypatch.setenv("KASPA_TPU_BENCH_SWEEP_PATH", str(tmp_path / "missing.json"))
    assert coalesce.configure("auto") == coalesce.DEFAULT_TARGET


# --- engine mechanics -------------------------------------------------------


def test_empty_submit_resolves_immediately():
    coalesce.configure(16)
    t = coalesce.active().submit("schnorr", [])
    assert t.done() and list(t.wait(1.0)) == []


def test_chunks_coalesce_into_one_super_batch(monkeypatch):
    """Three chunks from one submitter, age parked high: nothing flushes
    until the first wait() nudges — then all three go out as ONE
    super-batch, sliced back per-ticket bit-identically to a direct
    batched call over the same items."""
    from kaspa_tpu.crypto import secp

    monkeypatch.setenv("KASPA_TPU_COALESCE_AGE_MS", "10000")
    coalesce.configure(16)
    eng = coalesce.active()

    items = _schnorr_items(7)
    direct = np.asarray(secp.schnorr_verify_batch(items)).tolist()
    before = REGISTRY.snapshot()["counters"].get("dispatch_flushes", {})

    t1 = eng.submit("schnorr", items[:2])
    t2 = eng.submit("schnorr", items[2:4])
    t3 = eng.submit("schnorr", items[4:])
    got = [bool(v) for t in (t1, t2, t3) for v in t.wait(300.0)]
    assert got == direct
    assert not all(got) and any(got)  # mixed validity actually exercised

    snap = REGISTRY.snapshot()
    flushes = snap["counters"]["dispatch_flushes"]
    assert flushes.get("nudge", 0) == before.get("nudge", 0) + 1
    assert sum(flushes.values()) == sum(before.values()) + 1  # exactly one flush
    assert snap["counters"]["dispatch_coalesced_jobs"]["schnorr"] >= 7
    assert snap["histograms"]["dispatch_coalesce_depth"]["count"] >= 1


def test_drain_resolves_everything(monkeypatch):
    monkeypatch.setenv("KASPA_TPU_COALESCE_AGE_MS", "10000")
    coalesce.configure(16)
    eng = coalesce.active()
    items = _schnorr_items(7)
    tickets = [eng.submit("schnorr", items[:3]), eng.submit("schnorr", items[3:])]
    assert coalesce.drain(timeout=300.0) is True
    assert all(t.done() for t in tickets)
    assert eng.stats()["unresolved_chunks"] == 0


def test_kernel_error_surfaces_on_ticket():
    coalesce.configure(16)
    t = coalesce.active().submit("schnorr", [(None, None, None)])
    with pytest.raises(TypeError):
        t.wait(300.0)


# --- close() racing an in-flight job ----------------------------------------


def _hold_kernel(monkeypatch):
    """Replace the schnorr kernel with one that parks inside the device
    call until released, so a super-batch can be held in flight while the
    test races close() against it."""
    from kaspa_tpu.crypto import secp

    entered, release = threading.Event(), threading.Event()
    real = secp.schnorr_verify_batch

    def slow(items):
        entered.set()
        release.wait(30.0)
        return real(items)

    monkeypatch.setattr(secp, "schnorr_verify_batch", slow)
    return entered, release, real


def _count_resolves(monkeypatch):
    counts: dict[int, int] = {}
    orig = coalesce.Ticket._resolve

    def counting(self, mask, error):
        counts[id(self)] = counts.get(id(self), 0) + 1
        return orig(self, mask, error)

    monkeypatch.setattr(coalesce.Ticket, "_resolve", counting)
    return counts


def test_close_waits_out_in_flight_job(monkeypatch):
    """close() while a super-batch is mid-device-call and the call
    finishes inside the drain window: the ticket resolves exactly once,
    with its real mask — close never clobbers a job that is about to
    complete."""
    entered, release, real = _hold_kernel(monkeypatch)
    counts = _count_resolves(monkeypatch)
    coalesce.configure(16)
    eng = coalesce.active()

    items = _schnorr_items(7)
    direct = np.asarray(real(items)).tolist()
    t = eng.submit("schnorr", items)
    eng.nudge()
    assert entered.wait(30.0)  # the chunk is now inside the kernel

    threading.Timer(0.3, release.set).start()
    assert eng.close(timeout=30.0) is True
    assert [bool(v) for v in t.wait(1.0)] == direct
    assert counts[id(t)] == 1


def test_close_timeout_abandons_in_flight_job_exactly_once(monkeypatch):
    """close() whose drain window expires while the job is still wedged
    in the device call: the ticket fails with DispatchAbandoned, and the
    late result the hung thread eventually produces is discarded at the
    chunk layer — the ticket resolves exactly once, never a second time."""
    entered, release, _ = _hold_kernel(monkeypatch)
    counts = _count_resolves(monkeypatch)
    coalesce.configure(16)
    eng = coalesce.active()

    finishes: list[bool] = []
    orig_finish = eng._finish

    def recording_finish(chunk, mask, error):
        r = orig_finish(chunk, mask, error)
        finishes.append(r)
        return r

    monkeypatch.setattr(eng, "_finish", recording_finish)

    t = eng.submit("schnorr", _schnorr_items(7))
    eng.nudge()
    assert entered.wait(30.0)

    assert eng.close(timeout=0.2) is False  # drain expires, job still wedged
    assert t.done()
    with pytest.raises(coalesce.DispatchAbandoned):
        t.wait(1.0)
    assert eng.stats()["abandoned"] is True
    assert finishes == [True]  # the abandon resolution

    # let the wedged kernel call complete; its late result must be
    # discarded (finish returns False), not resolved into the ticket
    release.set()
    deadline = time.monotonic() + 30.0
    while len(finishes) < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert finishes == [True, False]
    assert counts[id(t)] == 1
    with pytest.raises(coalesce.DispatchAbandoned):
        t.wait(1.0)  # still the abandonment, not the late mask


# --- the production path ----------------------------------------------------


def _p2pk_tx(seed: int, corrupt: bool):
    from kaspa_tpu.consensus import hashing as chash
    from kaspa_tpu.consensus.model import (
        SUBNETWORK_ID_NATIVE,
        ComputeCommit,
        Transaction,
        TransactionInput,
        TransactionOutpoint,
        TransactionOutput,
        UtxoEntry,
    )
    from kaspa_tpu.crypto import eclib
    from kaspa_tpu.txscript import standard

    rng = random.Random(seed)
    sk = rng.randrange(1, eclib.N)
    pub = eclib.schnorr_pubkey(sk)
    spk = standard.pay_to_pub_key(pub)
    entry = UtxoEntry(10_000, spk, 5, False)
    tx = Transaction(
        0,
        [TransactionInput(TransactionOutpoint(bytes([seed]) * 32, 0), b"", 0, ComputeCommit.sigops(1))],
        [TransactionOutput(9_000, spk)], 0, SUBNETWORK_ID_NATIVE, 0, b"",
    )
    reused = chash.SigHashReusedValues()
    msg = chash.calc_schnorr_signature_hash(tx, [entry], 0, chash.SIG_HASH_ALL, reused)
    sig = eclib.schnorr_sign(msg, sk, rng.randbytes(32))
    if corrupt:
        sig = sig[:9] + bytes([sig[9] ^ 1]) + sig[10:]
    tx.inputs[0].signature_script = standard.schnorr_signature_script(sig, chash.SIG_HASH_ALL)
    return tx, [entry]


def _run_checker(txs):
    from kaspa_tpu.txscript.batch import BatchScriptChecker
    from kaspa_tpu.txscript.caches import SigCache

    checker = BatchScriptChecker(SigCache())  # fresh cache: no cross-run skips
    for token, (tx, entries) in enumerate(txs):
        checker.collect_tx(token, tx, entries)
    return {
        t: None if e is None else (getattr(e, "input_index", None), str(e))
        for t, e in checker.dispatch().items()
    }


def test_checker_decisions_identical_coalesced_vs_legacy():
    """BatchScriptChecker fast-path decisions must be bit-identical with
    the coalescing queue on vs off (the acceptance criterion's unit-level
    form; the sim replay covers the full-block form)."""
    txs = [_p2pk_tx(seed, corrupt=(seed % 3 == 0)) for seed in range(40, 47)]
    coalesce.configure(0)
    legacy = _run_checker(txs)
    coalesce.configure(16)
    coalesced = _run_checker(txs)
    assert legacy == coalesced
    assert any(v is not None for v in legacy.values()) and any(v is None for v in legacy.values())


def test_dispatch_async_detaches_the_handle():
    """dispatch_async() snapshots the collected jobs: jobs collected
    afterwards belong to the NEXT dispatch, and result() is idempotent."""
    from kaspa_tpu.txscript.batch import BatchScriptChecker
    from kaspa_tpu.txscript.caches import SigCache

    coalesce.configure(16)
    txs = [_p2pk_tx(seed, corrupt=(seed == 51)) for seed in range(50, 53)]
    checker = BatchScriptChecker(SigCache())
    checker.collect_tx(0, *txs[0])
    checker.collect_tx(1, *txs[1])
    handle = checker.dispatch_async()
    checker.collect_tx(2, *txs[2])  # lands in the next dispatch, not this one

    first = handle.result()
    assert set(first) == {0, 1}
    assert first[0] is None and first[1] is not None
    assert handle.result() is first  # idempotent

    second = checker.dispatch()
    assert set(second) == {2} and second[2] is None


def test_dispatch_async_works_with_coalescing_off():
    coalesce.configure(0)
    txs = [_p2pk_tx(seed, corrupt=(seed == 61)) for seed in range(60, 63)]
    from kaspa_tpu.txscript.batch import BatchScriptChecker
    from kaspa_tpu.txscript.caches import SigCache

    checker = BatchScriptChecker(SigCache())
    for token, (tx, entries) in enumerate(txs):
        checker.collect_tx(token, tx, entries)
    res = checker.dispatch_async().result()
    assert res[0] is None and res[1] is not None and res[2] is None


# --- full-replay bit-identity (slow lane; roundcheck's dispatch section
# carries the fast per-round evidence) ---------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("mesh_n", [1, 8])
def test_sim_replay_identical_coalesced_vs_legacy(mesh_n):
    """Same simulated DAG, coalescing off vs on: sink + utxo_commitment
    must be byte-identical, on single-device and 8-way mesh dispatch."""
    from kaspa_tpu.ops import mesh
    from kaspa_tpu.sim.simulator import SimConfig, replay, simulate

    res = simulate(SimConfig(bps=2, delay=2.0, num_miners=4, num_blocks=64, txs_per_block=4, seed=42))
    assert res.total_txs > 0  # real signature batches actually flow

    mesh.configure(mesh_n)
    try:
        coalesce.configure(0)
        _, legacy = replay(res)
        sink_l = legacy.sink()
        commit_l = legacy.multisets[sink_l].finalize().hex()

        coalesce.configure(64)
        _, co = replay(res)
        sink_c = co.sink()
        commit_c = co.multisets[sink_c].finalize().hex()
    finally:
        mesh.configure(1)

    assert sink_l == sink_c
    assert commit_l == commit_c
