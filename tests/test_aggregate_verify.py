"""Aggregated RLC Schnorr verification: falsification, bisection, weights.

The tentpole property under test: ONE multi-scalar check over the whole
batch accepts iff every signature is individually valid — and when it
rejects, bisection resolves the exact per-signature mask.  The adversarial
cases pin the two ways a batch check can be fooled:

- a single corrupted signature must fail the combined check and bisect to
  exactly its index;
- two bad signatures whose errors CANCEL under equal fixed weights (the
  classic RLC-batching pitfall — demonstrated against the pure-python
  oracle below) must still be rejected under the transcript-seeded random
  weights.

Device-kernel tests are slow-marked like the other secp device suites;
the host-only weight/digit/mode tests run in tier-1.
"""

import hashlib
import json

import pytest

from kaspa_tpu.crypto import eclib, secp
from kaspa_tpu.ops import dispatch
from kaspa_tpu.ops.secp256k1 import aggregate as agg
from kaspa_tpu.ops.secp256k1.verify import _scalars_to_digits


def _gen(n, seed=0, corrupt=()):
    """n valid (pub, msg, sig) triples; indexes in `corrupt` get s += 1."""
    items = []
    for i in range(n):
        sk = int.from_bytes(hashlib.sha256(b"agg-sk-%d-%d" % (seed, i)).digest(), "big") % eclib.N or 1
        msg = hashlib.sha256(b"agg-msg-%d-%d" % (seed, i)).digest()
        sig = eclib.schnorr_sign(msg, sk)
        if i in corrupt:
            s_bad = (int.from_bytes(sig[32:], "big") + 1) % eclib.N
            sig = sig[:32] + s_bad.to_bytes(32, "big")
        items.append((eclib.schnorr_pubkey(sk), msg, sig))
    return items


# --- host-only: weights, digits, mode resolution (tier-1 fast) ---------------


def test_weights_deterministic_and_transcript_bound():
    items = _gen(6)
    w1 = secp._aggregate_weights(items)
    w2 = secp._aggregate_weights(items)
    assert w1 == w2  # same transcript -> same weights (replayable bisection)
    assert all(0 < w < (1 << 128) for w in w1)
    # flipping one transcript byte reseeds every weight
    pub, msg, sig = items[3]
    items[3] = (pub, msg, sig[:-1] + bytes([sig[-1] ^ 1]))
    w3 = secp._aggregate_weights(items)
    assert w3 != w1
    # distinct per-signature weights (the cancellation defence needs them)
    assert len(set(w1)) == len(w1)


def test_weight_digits_live_in_upper_windows():
    # 128-bit weights: MSB-first 4-bit window columns 0..31 are statically
    # zero, which is exactly what A_WINDOWS == 32 assumes
    ws = [(1 << 128) - 1, 1, 0xDEADBEEF]
    d = _scalars_to_digits(ws, 4)
    assert not d[:, : agg.A_WINDOWS].any()
    assert d[0, agg.A_WINDOWS :].tolist() == [15] * 32


def test_scalars_to_digits_bytes_match_ints():
    ks = [0, 1, eclib.N - 1, 0x1234567890ABCDEF]
    as_int = _scalars_to_digits(ks, 6)
    as_bytes = _scalars_to_digits([k.to_bytes(32, "big") for k in ks], 6)
    assert (as_int == as_bytes).all()


def test_resolve_verify_mode(monkeypatch, tmp_path):
    monkeypatch.delenv("KASPA_TPU_VERIFY_MODE", raising=False)
    dispatch.set_verify_mode(None)
    assert dispatch.verify_mode() == "ladder"
    assert dispatch.resolve_verify_mode("schnorr", 4096) == "ladder"

    dispatch.set_verify_mode("aggregate")
    assert dispatch.resolve_verify_mode("schnorr", 2) == "aggregate"
    assert dispatch.resolve_verify_mode("ecdsa", 4096) == "ladder"  # schnorr-only

    sweep = tmp_path / "BENCH_SWEEP.json"
    sweep.write_text(json.dumps({"aggregate": {"crossover_batch": 128}}))
    monkeypatch.setenv("KASPA_TPU_BENCH_SWEEP_PATH", str(sweep))
    dispatch.set_verify_mode("auto")
    assert dispatch.resolve_verify_mode("schnorr", 127) == "ladder"
    assert dispatch.resolve_verify_mode("schnorr", 128) == "aggregate"

    dispatch.set_verify_mode(None)  # restore env-default for later tests


# --- device kernel: falsification + bisection (slow) -------------------------


@pytest.mark.slow
def test_aggregate_matches_ladder_and_oracle():
    items = _gen(8, seed=1, corrupt={2, 5})
    items[6] = (items[6][0], items[6][1], b"\x00" * 63)  # malformed length
    expect = [eclib.schnorr_verify(*it) for it in items]
    got = list(secp.schnorr_verify_batch_aggregate(items))
    assert got == expect
    assert got == list(secp.schnorr_verify_batch(items))
    assert expect.count(False) == 3 and expect.count(True) == 5


@pytest.mark.slow
def test_single_bad_signature_bisects_to_exact_index():
    secp.schnorr_verify_batch_aggregate(_gen(8, seed=2))  # warm bucket 8
    checks0 = secp._AGG_CHECKS.value
    bisect0 = secp._AGG_BISECT_STEPS.value

    items = _gen(64, seed=3, corrupt={37})
    mask = list(secp.schnorr_verify_batch_aggregate(items))
    assert [i for i, ok in enumerate(mask) if not ok] == [37]
    # the combined check ran (several sub-aggregate dispatches) and the
    # failing subset was bisected, not brute-forced per-signature
    assert secp._AGG_CHECKS.value > checks0
    assert secp._AGG_BISECT_STEPS.value > bisect0


@pytest.mark.slow
def test_cancelling_errors_rejected_under_random_weights():
    """Two tampered signatures whose errors cancel under equal weights.

    s1 += d and s2 -= d leaves s1 + s2 unchanged, so the UNWEIGHTED
    combined equation sum(s_i)*G == sum(R_i) + sum(e_i * P_i) still holds
    — verified against the pure-python oracle below.  A fixed-weight
    batcher accepts both forgeries; the transcript-seeded random weights
    must reject them.
    """
    (pub1, msg1, sig1), (pub2, msg2, sig2) = _gen(2, seed=4)
    d = 0x1D2C3B4A
    s1 = (int.from_bytes(sig1[32:], "big") + d) % eclib.N
    s2 = (int.from_bytes(sig2[32:], "big") - d) % eclib.N
    t1 = (pub1, msg1, sig1[:32] + s1.to_bytes(32, "big"))
    t2 = (pub2, msg2, sig2[:32] + s2.to_bytes(32, "big"))

    # both individually invalid...
    assert not eclib.schnorr_verify(*t1)
    assert not eclib.schnorr_verify(*t2)

    # ...yet the equal-weight aggregate equation holds (oracle arithmetic):
    lhs = eclib.point_mul(eclib.G, (s1 + s2) % eclib.N)
    rhs = None
    for pub, msg, sig in (t1, t2):
        p_i = eclib.lift_x(int.from_bytes(pub, "big"))
        r_i = eclib.lift_x(int.from_bytes(sig[:32], "big"))
        e_i = secp.schnorr_challenge(sig[:32], pub, msg)
        rhs = eclib.point_add(rhs, eclib.point_add(r_i, eclib.point_mul(p_i, e_i)))
    assert lhs == rhs  # the fixed-weight blind spot is real

    # random weights break the cancellation: both lanes rejected
    w = secp._aggregate_weights([t1, t2])
    assert w[0] != w[1]
    assert list(secp.schnorr_verify_batch_aggregate([t1, t2])) == [False, False]
