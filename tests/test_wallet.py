"""Wallet tests: BIP32 golden vectors + account send round-trip."""

import pytest

from kaspa_tpu.wallet import Account, ExtendedKey


def test_bip32_vector1():
    """BIP32 test vector 1 (seed 000102...0f): checked via public keys,
    which pin down the full (key, chain code) derivation state."""
    seed = bytes.fromhex("000102030405060708090a0b0c0d0e0f")
    m = ExtendedKey.from_seed(seed)
    assert m.public_key().hex() == "0339a36013301597daef41fbe593a02cc513d0b55527ec2df1050e2e8ff49c85c2"
    # m/0'
    m0h = m.derive_path("m/0'")
    assert m0h.public_key().hex() == "035a784662a4a20a65bf6aab9ae98a6c068a81c52e4b032c0fb5400c706cfccc56"
    # m/0'/1
    m0h1 = m0h.derive_child(1)
    assert m0h1.public_key().hex() == "03501e454bf00751f24b1b489aa925215d66af2234e3891c3b21a52bedb3cd711c"
    # m/0'/1/2'/2/1000000000
    deep = m.derive_path("m/0'/1/2'/2/1000000000")
    assert deep.public_key().hex() == "022a471424da5e657499d1ff51cb43c47481a03b1e77f951fe64cec9f5a48f7011"


def test_bip32_vector2_deep():
    seed = bytes.fromhex(
        "fffcf9f6f3f0edeae7e4e1dedbd8d5d2cfccc9c6c3c0bdbab7b4b1aeaba8a5a29f9c999693908d8a8784817e7b7875726f6c696663605d5a5754514e4b484542"
    )
    m = ExtendedKey.from_seed(seed)
    assert m.public_key().hex() == "03cbcaa9c98c877a26977d00825c956a238e8dddfbd322cce4f74b0b5bd6ace4a7"
    node = m.derive_path("m/0/2147483647'/1/2147483646'/2")
    assert node.public_key().hex() == "024d902e1a2fc7a8755ab5b694c575fce742c48d9ff192e63df5193e4c7afe1f9c"


def test_account_send_roundtrip():
    """Mine to a wallet address, then send with change and confirm balances."""
    import random

    from kaspa_tpu.consensus.consensus import Consensus
    from kaspa_tpu.consensus.params import simnet_params
    from kaspa_tpu.consensus.processes.coinbase import MinerData
    from kaspa_tpu.index import UtxoIndex
    from kaspa_tpu.mempool import MiningManager

    params = simnet_params(bps=2)
    c = Consensus(params)
    index = UtxoIndex(c)
    mgr = MiningManager(c)

    wallet = Account.from_seed(b"test seed for round trip", prefix="kaspasim")
    recv = wallet.receive_keys[0]
    miner_data = MinerData(recv.spk, b"wallet-miner")
    for _ in range(12):  # mature some rewards (simnet maturity = 8)
        blk = mgr.get_block_template(miner_data)
        c.validate_and_insert_block(blk)
        mgr.handle_new_block_transactions(blk.transactions, c.get_virtual_daa_score())
        mgr.template_cache.clear()
    index.resync()
    balance = wallet.balance(index)
    assert balance > 0

    # send to a freshly derived second address
    dest = wallet.derive_receive_address()
    send_amount = balance // 4
    tx = wallet.build_send(
        index, dest.address.to_string(), send_amount, fee=2000,
        virtual_daa_score=c.get_virtual_daa_score(), coinbase_maturity=params.coinbase_maturity,
    )
    mgr.validate_and_insert_transaction(tx)
    blk = mgr.get_block_template(miner_data)
    assert any(t.id() == tx.id() for t in blk.transactions[1:])
    c.validate_and_insert_block(blk)
    mgr.handle_new_block_transactions(blk.transactions, c.get_virtual_daa_score())
    # a block's txs enter the chain UTXO state when a descendant merges it
    nxt = mgr.get_block_template(miner_data)
    c.validate_and_insert_block(nxt)
    index.resync()
    assert index.get_balance_by_script(dest.spk.script) == send_amount
    # insufficient funds raises
    from kaspa_tpu.wallet.account import WalletError

    with pytest.raises(WalletError):
        wallet.build_send(index, dest.address.to_string(), 10**18, fee=0,
                          virtual_daa_score=c.get_virtual_daa_score(), coinbase_maturity=params.coinbase_maturity)


def test_wallet_interactive_terminal(tmp_path):
    """The interactive terminal (reference cli/): a scripted session over a
    live daemon — help, addresses, node info, balance, live monitor of a
    mined coinbase, derived address, clean exit."""
    import random
    import subprocess
    import sys
    import threading
    import time

    from kaspa_tpu.node.daemon import Daemon, parse_args, rpc_call

    seed = tmp_path / "seed.bin"
    seed.write_bytes(b"\x5a" * 32)
    from kaspa_tpu.wallet import Account

    acct = Account.from_seed(b"\x5a" * 32, prefix="kaspasim")
    pay = acct.addresses()[0]

    args = parse_args(["--appdir", str(tmp_path / "node"), "--rpclisten", "127.0.0.1:0", "--bps", "2"])
    d = Daemon(args)
    addr = d.start()
    try:
        import os

        env = dict(os.environ)
        env["KASPA_TPU_PLATFORM"] = "cpu"
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.Popen(
            [sys.executable, "-m", "kaspa_tpu.wallet", "--rpc", addr, "--seed-file", str(seed), "repl"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )

        def mine_soon():
            time.sleep(3)
            for _ in range(2):
                t = rpc_call(addr, "getBlockTemplate", {"payAddress": pay})
                rpc_call(addr, "submitBlockByTemplateHash", {"hash": t["block_hash"]})
                d.mining.template_cache.clear()

        miner = threading.Thread(target=mine_soon, daemon=True)
        miner.start()
        script = (
            f"help\naddress\nnode\ndag\nbalance\nutxos\nfee-rates\n"
            f"estimate {pay} 1\nmonitor 12\nnew-address\nbadcmd\nexit\n"
        )
        out, _ = proc.communicate(script, timeout=120)
        assert proc.returncode == 0
        assert "commands:" in out
        assert pay in out
        assert "network simnet" in out
        assert "sompi" in out
        assert "monitor done" in out and "pending=" in out
        assert "blocks " in out and "pruning-point" in out  # dag
        assert "spendable utxos" in out  # utxos listing
        assert "sompi/g" in out  # fee-rates buckets
        # estimate prints mass/fee pricing (or a clean insufficient-funds
        # message before any coinbase matured)
        assert ("relay fee floor" in out) or ("insufficient funds" in out)
        # the monitored coinbase arrived as a live pending event
        assert "[pending]" in out or "mature=" in out
        assert "unknown command 'badcmd'" in out
    finally:
        d.stop()
