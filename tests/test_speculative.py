"""Speculative chain-state precompute: the cache-hit commit path must be
bit-identical to the synchronous path, hits must actually happen on chain
extension, and the entry cache must stay consistent across forks/reorgs
(misses fall back, never diverge)."""

import random

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.params import simnet_params
from kaspa_tpu.consensus.processes.coinbase import MinerData
from kaspa_tpu.consensus.model import ScriptPublicKey
from kaspa_tpu.pipeline import ConsensusPipeline
from kaspa_tpu.pipeline.speculative import SpeculativeVerifier

MINER = MinerData(ScriptPublicKey(0, b"\x20" + b"\x07" * 32 + b"\xac"))


def _build_chain(n):
    params = simnet_params()
    scratch = Consensus(params)
    blocks = []
    for _ in range(n):
        blk = scratch.build_block_template(MINER, [])
        scratch.validate_and_insert_block(blk)
        blocks.append(blk)
    return params, blocks, scratch


def _build_forked_dag(total, seed=7):
    """Poisson sibling waves: forks, merges, reorg-ish shapes — the DAG
    where speculative entries go stale and misses must fall back cleanly."""
    rng = random.Random(seed)
    params = simnet_params()
    scratch = Consensus(params)
    tips = [params.genesis.hash]
    blocks = []
    while total > 0:
        v = min(params.max_block_parents, max(1, int(rng.gauss(2.5, 1.5))), total)
        total -= v
        new_tips = []
        for _ in range(v):
            blk = scratch.build_block_with_parents(list(tips), MINER)
            blk.header.nonce = rng.getrandbits(48)
            blk.header.invalidate_cache()
            scratch.validate_and_insert_block(blk)
            new_tips.append(blk.hash)
            blocks.append(blk)
        tips = new_tips
    return params, blocks, scratch


def _replay(params, blocks, speculative):
    consensus = Consensus(params)
    pipe = ConsensusPipeline(consensus, workers=3, speculative=speculative)
    try:
        futures = [pipe.submit(b) for b in blocks]
        for f in futures:
            assert f.result(timeout=120) in ("utxo_valid", "utxo_pending")
    finally:
        pipe.shutdown()
    return consensus


def test_speculative_hits_on_chain_extension():
    """A linear chain is the common case the precompute targets: every
    chain block should be served from the cache, none recomputed."""
    params, blocks, scratch = _build_chain(14)
    before = SpeculativeVerifier.snapshot()
    consensus = _replay(params, blocks, speculative=True)
    after = SpeculativeVerifier.snapshot()
    assert after["hits"] > before["hits"], "no speculative hits on a linear chain"
    assert consensus.sink() == scratch.sink()
    sink = consensus.sink()
    assert consensus.multisets[sink].finalize() == scratch.multisets[sink].finalize()
    # pipeline detached the verifier at shutdown: serial callers after the
    # pipeline must not consume stale entries
    assert consensus.speculative is None


def test_speculative_bit_identity_on_forked_dag():
    """Speculation on vs off over a forky DAG: sink, utxo commitment and
    per-block consensus data must be bit-identical — hits, misses and
    fallbacks all converge to the same state."""
    params, blocks, scratch = _build_forked_dag(40)
    c_on = _replay(params, blocks, speculative=True)
    c_off = _replay(params, blocks, speculative=False)
    assert c_on.sink() == c_off.sink() == scratch.sink()
    sink = c_on.sink()
    assert (
        c_on.multisets[sink].finalize()
        == c_off.multisets[sink].finalize()
        == scratch.multisets[sink].finalize()
    )
    assert c_on.get_virtual_daa_score() == c_off.get_virtual_daa_score()
    for blk in blocks:
        assert c_on.storage.ghostdag.get_blue_work(blk.hash) == c_off.storage.ghostdag.get_blue_work(blk.hash)
        # every chain-committed block must carry identical acceptance state
        if c_on.storage.statuses.get(blk.hash) == "utxo_valid" and c_off.storage.statuses.get(blk.hash) == "utxo_valid":
            assert c_on.multisets[blk.hash].finalize() == c_off.multisets[blk.hash].finalize()
            assert c_on.acceptance_data.get(blk.hash) == c_off.acceptance_data.get(blk.hash)


def test_in_cycle_chain_precompute():
    """When a resolve finds a pending chain with no stage-time entries
    (the lock-starvation case), `precompute_chain` must batch the whole
    segment into one dispatch, publish entries, and the verify loop must
    commit every block from the cache — bit-identical to the serial
    build."""
    from kaspa_tpu.utils.sync import LockCtx

    params, blocks, scratch = _build_chain(8)
    c = Consensus(params)
    # the virtual worker's pre-resolve state: headers + bodies committed,
    # tips updated, no virtual resolution yet — every block pending
    for b in blocks:
        c._process_header(b.header)
        c._process_body(b)
    for b in blocks:
        c._update_tips(b.hash)
    c.speculative = SpeculativeVerifier(c, LockCtx("consensus-commit", rank=10))
    before = SpeculativeVerifier.snapshot()
    c._resolve_virtual()
    after = SpeculativeVerifier.snapshot()
    assert after["precomputes"] - before["precomputes"] >= len(blocks) - 1
    assert after["hits"] - before["hits"] >= len(blocks) - 1
    assert after["misses"] == before["misses"]
    assert c.sink() == scratch.sink()
    sink = c.sink()
    assert c.multisets[sink].finalize() == scratch.multisets[sink].finalize()
    for b in blocks:
        assert c.storage.statuses.get(b.hash) == "utxo_valid"
        assert c.acceptance_data.get(b.hash) == scratch.acceptance_data.get(b.hash)


def test_speculative_disabled_env(monkeypatch):
    """KASPA_TPU_SPECULATIVE=0 disables the verifier at construction."""
    monkeypatch.setenv("KASPA_TPU_SPECULATIVE", "0")
    params, blocks, _ = _build_chain(3)
    consensus = Consensus(params)
    pipe = ConsensusPipeline(consensus, workers=2)
    try:
        assert pipe.speculative is None
        assert consensus.speculative is None
        for b in blocks:
            assert pipe.submit(b).result(timeout=60) in ("utxo_valid", "utxo_pending")
    finally:
        pipe.shutdown()


def test_speculative_cache_bounded():
    """The entry cache must never grow past MAX_ENTRIES and take() must
    pop (a consumed entry is gone)."""
    params, blocks, _ = _build_chain(6)
    consensus = Consensus(params)
    pipe = ConsensusPipeline(consensus, workers=2, speculative=True)
    try:
        for b in blocks:
            assert pipe.submit(b).result(timeout=60) in ("utxo_valid", "utxo_pending")
        spec = pipe.speculative
        assert len(spec._entries) <= spec.MAX_ENTRIES
        # chain blocks were all consumed on commit
        for b in blocks:
            gd = consensus.storage.ghostdag.get(b.hash)
            assert (b.hash, gd.selected_parent) not in spec._entries
    finally:
        pipe.shutdown()
