"""Notification streaming over the RPC wire.

Reference: notify/src/notifier.rs + rpc/grpc/server notification streaming —
a remote client subscribes on the same TCP connection it issues requests on,
the node mines, and the client observes BlockAdded / UtxosChanged /
VirtualDaaScoreChanged WITHOUT polling; a wallet UtxoProcessor consumes the
stream and tracks balance remotely.
"""

from __future__ import annotations

import random

import pytest

from kaspa_tpu.node.daemon import Daemon, NotificationClient, parse_args
from kaspa_tpu.sim.simulator import Miner


@pytest.fixture()
def daemon(tmp_path):
    args = parse_args(["--appdir", str(tmp_path), "--rpclisten", "127.0.0.1:0", "--bps", "2"])
    d = Daemon(args)
    addr = d.start()
    yield d, addr
    d.stop()


def _miner_address(miner, prefix="kaspasim"):
    from kaspa_tpu.crypto.addresses import extract_script_pub_key_address

    return extract_script_pub_key_address(miner.spk, prefix).to_string()


def test_subscription_streams_without_polling(daemon):
    d, addr = daemon
    miner = Miner(0, random.Random(4))
    addr_str = _miner_address(miner)

    client = NotificationClient(addr)
    try:
        assert client.subscribe("block-added") == "ok"
        assert client.subscribe("utxos-changed", [addr_str]) == "ok"
        assert client.subscribe("virtual-daa-score-changed") == "ok"

        # remote wallet: UtxoProcessor fed purely by the stream
        from kaspa_tpu.wallet.utxo_processor import UtxoProcessor

        class _Key:
            spk = miner.spk

        class _Account:
            receive_keys = [_Key()]

        uproc = UtxoProcessor(_Account(), d.params.coinbase_maturity)

        for _ in range(3):
            t = client.call("getBlockTemplate", {"payAddress": addr_str})
            res = client.call("submitBlockByTemplateHash", {"hash": t["block_hash"]})
            assert res["status"] in ("utxo_valid", "utxo_pending")
            d.mining.template_cache.clear()

        events = {}
        # 3 blocks produce >= 3 block-added + >= 2 utxos-changed (coinbases
        # become spendable when their block is chain-verified) + daa ticks
        for _ in range(8):
            event, data = client.next_notification(timeout=30)
            events.setdefault(event, []).append(data)
            uproc.feed_wire_notification(event, data)
            if len(events.get("block-added", [])) >= 3 and events.get("utxos-changed"):
                break
        assert len(events["block-added"]) >= 3
        assert events["utxos-changed"], "no UtxosChanged crossed the wire"
        assert events.get("virtual-daa-score-changed"), "no daa-score stream"
        added = [u for n in events["utxos-changed"] for u in n["added"]]
        assert added and all("script_public_key" in u["utxo_entry"] for u in added)
        # the remote wallet saw its coinbase balance (immature => pending)
        assert uproc.balance().total > 0

        # unsubscribe stops the flow for that event
        assert client.unsubscribe("block-added") == "ok"
        t = client.call("getBlockTemplate", {"payAddress": addr_str})
        client.call("submitBlockByTemplateHash", {"hash": t["block_hash"]})
        d.mining.template_cache.clear()
        import queue

        saw_block_added = False
        try:
            while True:
                event, _ = client.next_notification(timeout=2)
                if event == "block-added":
                    saw_block_added = True
        except queue.Empty:
            pass
        assert not saw_block_added
    finally:
        client.close()


def test_address_filtered_utxos_changed(daemon):
    """A listener filtered to an unrelated address sees no UtxosChanged."""
    d, addr = daemon
    miner = Miner(0, random.Random(4))
    other = Miner(1, random.Random(5))
    client = NotificationClient(addr)
    try:
        client.subscribe("utxos-changed", [_miner_address(other)])
        for _ in range(2):
            t = client.call("getBlockTemplate", {"payAddress": _miner_address(miner)})
            client.call("submitBlockByTemplateHash", {"hash": t["block_hash"]})
            d.mining.template_cache.clear()
        import queue

        with pytest.raises(queue.Empty):
            while True:
                event, data = client.next_notification(timeout=2)
                assert not (event == "utxos-changed" and data["added"]), "filter leaked"
    finally:
        client.close()


def test_chain_changed_and_template_events(daemon):
    """VirtualChainChanged carries the added selected-chain path with
    acceptance data; NewBlockTemplate fires when a block invalidates the
    cached template (notify/events.rs parity)."""
    d, addr = daemon
    miner = Miner(0, random.Random(7))
    pay = _miner_address(miner)
    client = NotificationClient(addr)
    try:
        client.subscribe("virtual-chain-changed")
        client.subscribe("new-block-template")
        mined = []
        for _ in range(2):
            t = client.call("getBlockTemplate", {"payAddress": pay})
            client.call("submitBlockByTemplateHash", {"hash": t["block_hash"]})
            mined.append(t["block_hash"])
            d.mining.template_cache.clear()
        events = {"virtual-chain-changed": [], "new-block-template": []}
        for _ in range(8):
            try:
                event, data = client.next_notification(timeout=10)
            except Exception:  # noqa: BLE001
                break
            if event in events:
                events[event].append(data)
            if events["virtual-chain-changed"] and events["new-block-template"]:
                break
        assert events["new-block-template"], "no NewBlockTemplate event"
        chains = events["virtual-chain-changed"]
        assert chains, "no VirtualChainChanged event"
        added = [h for n in chains for h in n["added_chain_block_hashes"]]
        assert any(h in mined for h in added)
        assert all("accepted_transaction_ids" in n for n in chains)
    finally:
        client.close()
