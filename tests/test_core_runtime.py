"""Core/Service runtime, tick, logging, metrics core, sysinfo, DB tool.

Reference shapes: core/src/{core,service}.rs (ordered lifecycle),
core/src/task/tick.rs, metrics/core/src/data.rs (snapshot rates),
database/rocknroll (DB admin tooling).
"""

import threading
import time

import pytest

from kaspa_tpu.core import Core, Service, TickService
from kaspa_tpu.core.log import get_logger, init_logger
from kaspa_tpu.metrics.core import METRIC_GROUPS, MetricsData, MetricsSnapshot


class _Recorder(Service):
    def __init__(self, name, events):
        self._name = name
        self.events = events

    def ident(self):
        return self._name

    def start(self, core):
        self.events.append(("start", self._name))
        t = threading.Thread(target=lambda: None, daemon=True)
        t.start()
        return [t]

    def stop(self):
        self.events.append(("stop", self._name))


def test_core_lifecycle_ordering():
    events = []
    core = Core()
    for name in ("db", "consensus", "rpc"):
        core.bind(_Recorder(name, events))
    workers = core.start()
    assert [e for e in events if e[0] == "start"] == [("start", "db"), ("start", "consensus"), ("start", "rpc")]
    core.join(workers, timeout=5)
    core.shutdown()
    # reverse bind order: dependents stop before their dependencies
    assert [e for e in events if e[0] == "stop"] == [("stop", "rpc"), ("stop", "consensus"), ("stop", "db")]
    # idempotent
    core.shutdown()
    assert len([e for e in events if e[0] == "stop"]) == 3
    assert core.find("consensus") is not None and core.find("nope") is None


def test_core_stop_failure_does_not_strand_others():
    events = []
    core = Core()

    class Bad(Service):
        def stop(self):
            raise RuntimeError("boom")

    core.bind(_Recorder("a", events))
    core.bind(Bad())
    core.bind(_Recorder("b", events))
    core.start()
    core.shutdown()
    assert ("stop", "a") in events and ("stop", "b") in events


def test_tick_service_fires_and_stops_fast():
    ticks = []
    svc = TickService()
    svc.register(0.02, lambda: ticks.append(time.monotonic()))
    core = Core()
    core.bind(svc)
    core.start()
    time.sleep(0.15)
    t0 = time.monotonic()
    core.shutdown()
    assert time.monotonic() - t0 < 1.0  # shutdown doesn't wait out intervals
    assert len(ticks) >= 3


def test_logger_filter_spec():
    init_logger("warn,consensus=trace")
    import logging

    assert logging.getLogger("kaspa").level == logging.WARNING
    assert logging.getLogger("kaspa.consensus").level == 5  # trace
    log = get_logger("consensus")
    log.trace("trace message works")  # must not raise
    init_logger("info")  # restore


def test_metrics_rates_from_snapshot_deltas():
    data = MetricsData()
    s1 = MetricsSnapshot(unixtime_millis=1_000, values={"node_total_bytes_tx": 0, "node_total_bytes_rx": 100})
    s2 = MetricsSnapshot(unixtime_millis=3_000, values={"node_total_bytes_tx": 4000, "node_total_bytes_rx": 300})
    data.push(s1)
    assert s1.values["node_total_bytes_tx_per_second"] == 0.0  # no prior sample
    data.push(s2)
    assert s2.values["node_total_bytes_tx_per_second"] == 2000.0
    assert s2.values["node_total_bytes_rx_per_second"] == 100.0
    # groups index into the same value space
    assert "node_cpu_usage" in METRIC_GROUPS["system"]
    assert set(s2.group("bandwidth")) == set(METRIC_GROUPS["bandwidth"])


def test_sysinfo_and_build_info():
    from kaspa_tpu.utils.sysinfo import build_info, system_info

    info = system_info()
    assert info["cpu_physical_cores"] >= 1
    assert info["total_memory"] > 0
    assert info["fd_limit"] > 0
    assert len(info["system_id"]) == 32
    assert build_info()["version"]
    assert info["git_hash"]  # live repo


def test_db_tool_stats_verify_compact(tmp_path):
    from kaspa_tpu.consensus.consensus import Consensus
    from kaspa_tpu.consensus.params import simnet_params
    from kaspa_tpu.consensus.processes.coinbase import MinerData
    from kaspa_tpu.consensus.model import ScriptPublicKey
    from kaspa_tpu.storage import __main__ as dbtool
    from kaspa_tpu.storage.kv import KvStore

    db_path = tmp_path / "consensus.db"
    db = KvStore(str(db_path))
    c = Consensus(simnet_params(), db=db)
    miner = MinerData(ScriptPublicKey(0, b"\x20" + b"\x07" * 32 + b"\xac"))
    for i in range(4):
        b = c.build_block_with_parents(list(c.tips), miner)
        b.header.nonce = i + 1
        b.header.invalidate_cache()
        c.validate_and_insert_block(b)
    db.close()

    assert dbtool.resolve_active_db(str(tmp_path)) == str(db_path)
    store = KvStore(str(db_path))
    try:
        assert dbtool.cmd_stats(store) == 0
        assert dbtool.cmd_verify(store) == 0
        assert dbtool.cmd_compact(store) == 0
    finally:
        store.close()
    # post-compact: the DB still replays into a working consensus
    db2 = KvStore(str(db_path))
    c2 = Consensus(simnet_params(), db=db2)
    assert c2.get_virtual_daa_score() == c.get_virtual_daa_score()
    assert c2.sink() == c.sink()
    db2.close()


def test_daemon_metrics_snapshot_over_wire(tmp_path):
    from kaspa_tpu.node.daemon import Daemon, parse_args, rpc_call

    args = parse_args(["--appdir", str(tmp_path), "--rpclisten", "127.0.0.1:0", "--no-persist"])
    daemon = Daemon(args)
    try:
        addr = daemon.start()
        # force one sample through the tick body
        daemon.metrics_data.push(
            __import__("kaspa_tpu.metrics.core", fromlist=["collect_snapshot"]).collect_snapshot(
                daemon.consensus, daemon.mining, daemon.perf_monitor, p2p_node=daemon.node
            )
        )
        m = rpc_call(addr, "getMetrics")
        assert m["snapshot"] is not None
        assert m["snapshot"]["node_database_headers_count"] >= 1
        si = rpc_call(addr, "getSystemInfo")
        assert si["cpu_physical_cores"] >= 1 and si["version"]
    finally:
        daemon.stop()
