"""Fast-lane smoke slices of the slow suites (kernel bit-exactness + golden
replay prefixes).

The exhaustive sweeps stay slow-marked (test_pallas_ladder, test_secp_verify,
test_goref_replay); this module keeps the default `-m "not slow"` lane
executing at least one assertion from each risk area so a kernel regression
— e.g. in the addition-chain inverse or the symmetric squaring convolution —
can never ship invisibly.
"""

from __future__ import annotations

import os
import random

import numpy as np

from kaspa_tpu.ops.secp256k1 import ladder_pallas as lp

W8 = lp.W8
P = lp.SECP_P


def _pack(vals):
    return np.stack([lp.int_to_limbs8(v) for v in vals], axis=1).astype(np.int32)


def _unpack(arr):
    out = []
    a = np.asarray(arr)
    for j in range(a.shape[1]):
        out.append(sum(int(a[i, j]) << (8 * i) for i in range(a.shape[0])))
    return out


def test_field_mul_and_sqr_match_oracle():
    """_conv/_conv_sqr + fold/canon against python bigints (8 lanes)."""
    rng = random.Random(42)
    xs = [rng.randrange(P) for _ in range(8)]
    ys = [rng.randrange(P) for _ in range(8)]
    xs[0], ys[0] = P - 1, P - 1  # boundary
    xa, ya = _pack(xs), _pack(ys)
    m8 = lp._m_limbs8(P)
    mul = lambda a, b: lp._canon(lp._mul(a, b), m8)
    sqr = lambda a: lp._canon(lp._sqr(a), m8)
    assert _unpack(mul(xa, ya)) == [(x * y) % P for x, y in zip(xs, ys)]
    assert _unpack(sqr(xa)) == [(x * x) % P for x in xs]
    # _conv_sqr must agree with the generic convolution it replaces
    cs = lambda a: lp._canon(lp._fold(lp._C8_P, lp._carry2(lp._conv_sqr(a))), m8)
    cc = lambda a: lp._canon(lp._fold(lp._C8_P, lp._carry2(lp._conv(a, a))), m8)
    assert _unpack(cs(xa)) == _unpack(cc(xa))


def test_field_inverse_addition_chain_matches_oracle():
    """The 255S+15M Fermat chain (`_inv`) bit-for-bit vs pow(x, p-2, p)."""
    rng = random.Random(7)
    xs = [rng.randrange(1, P) for _ in range(8)]
    xs[0] = 1
    xs[1] = P - 1
    xa = _pack(xs)
    m8 = lp._m_limbs8(P)
    inv = lambda a: lp._canon(lp._inv(a), m8)
    got = _unpack(inv(xa))
    assert got == [pow(x, P - 2, P) for x in xs]
    for x, g in zip(xs, got):
        assert (x * g) % P == 1


def test_point_ops_match_oracle():
    """Projective double/add (Renes-Costello-Batina) vs eclib on 4 lanes."""
    from kaspa_tpu.crypto import eclib

    rng = random.Random(13)
    pts = [eclib.point_mul(eclib.G, rng.randrange(1, eclib.N)) for _ in range(4)]
    qts = [eclib.point_mul(eclib.G, rng.randrange(1, eclib.N)) for _ in range(4)]
    m8 = lp._m_limbs8(P)

    def aff(p3):
        x, y, z = p3
        zi = lp._inv(z)
        return lp._canon(lp._mul(x, zi), m8), lp._canon(lp._mul(y, zi), m8)

    px, py = _pack([p[0] for p in pts]), _pack([p[1] for p in pts])
    qx, qy = _pack([q[0] for q in qts]), _pack([q[1] for q in qts])
    one = _pack([1] * 4)

    dbl = lambda x, y, z: aff(lp._pt_double((x, y, z)))
    add = lambda x, y, z, qxx, qyy: aff(lp._pt_add_mixed((x, y, z), (qxx, qyy)))

    gx, gy = dbl(px, py, one)
    expect = [eclib.point_add(p, p) for p in pts]
    assert _unpack(gx) == [e[0] for e in expect]
    assert _unpack(gy) == [e[1] for e in expect]

    gx, gy = add(px, py, one, qx, qy)
    expect = [eclib.point_add(p, q) for p, q in zip(pts, qts)]
    assert _unpack(gx) == [e[0] for e in expect]
    assert _unpack(gy) == [e[1] for e in expect]


DATA = "/root/reference/testing/integration/testdata/dags_for_json_tests"
TX_DAG = os.path.join(DATA, "goref-1060-tx-265-blocks", "blocks.json.gz")


def test_goref_prefix_replay_smoke():
    """40-block golden prefix with real transactions: header hashes, GHOSTDAG,
    difficulty, merkle, muhash, signature checks all bit-exact (the full 265
    replay stays in the slow lane)."""
    import pytest

    if not os.path.exists(TX_DAG):
        pytest.skip("reference testdata not mounted")
    from kaspa_tpu.sim.goref import replay_goref

    consensus = replay_goref(TX_DAG, limit=40)
    assert consensus.get_virtual_daa_score() == 40
    assert consensus.storage.statuses.get(consensus.sink()) == "utxo_valid"


def test_goref_replay_bounded_caches_and_resume(tmp_path):
    """Memory-bounded replay: a DB-backed golden replay whose history far
    exceeds the cache budgets keeps every decode cache at/under budget, and
    a restart resumes from the DB with O(tips) loading to the same sink
    (access.rs/cache_policy_builder.rs discipline)."""
    import pytest

    if not os.path.exists(TX_DAG):
        pytest.skip("reference testdata not mounted")
    from kaspa_tpu.consensus.consensus import Consensus
    from kaspa_tpu.consensus.stores import CachePolicy
    from kaspa_tpu.sim.goref import load_goref, replay_goref
    from kaspa_tpu.storage.kv import KvStore

    # budgets far below the 120-block replay: every store must evict
    policy = CachePolicy().scaled(0)  # floor of 16 entries per store
    db = KvStore(str(tmp_path / "goref.db"))
    consensus = replay_goref(TX_DAG, limit=120, db=db, cache_policy=policy)
    sink = consensus.sink()
    assert consensus.get_virtual_daa_score() == 120
    for access in consensus.storage._registered:
        assert access._budget is not None
        # dirty entries are pinned only between flushes; after the final
        # flush the cache must sit at/under its budget
        assert len(access._cache) <= access._budget, access._prefix
    db.close()

    # restart: read-through resume, same sink, still fully operational
    db2 = KvStore(str(tmp_path / "goref.db"))
    params, blocks = load_goref(TX_DAG)
    resumed = Consensus(params, db=db2, cache_policy=policy)
    assert resumed.sink() == sink
    assert resumed.get_virtual_daa_score() == 120
    status = resumed.validate_and_insert_block(blocks[121])
    assert status in ("utxo_valid", "utxo_pending")
    db2.close()
