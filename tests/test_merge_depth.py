"""Bounded merge depth (post_pow_validation.rs check_bounded_merge_depth).

A merged block is only *red* once its blue anticone exceeds k, and the
bounded-merge rule only constrains reds: merging a fork that stayed stale
for more than merge_depth blocks (and >k, so it is red) without a
kosherizing blue must be rejected at the header stage.
"""

import pytest

from kaspa_tpu.consensus.consensus import Consensus, RuleError
from kaspa_tpu.consensus.params import simnet_params
from kaspa_tpu.consensus.processes.coinbase import MinerData
from kaspa_tpu.txscript import standard


def _miner_data(tag: bytes):
    return MinerData(standard.pay_to_pub_key(bytes(31) + tag), extra_data=tag)


def _grow(c, tip, n, md, t0=10_000):
    for i in range(n):
        blk = c.build_block_with_parents([tip], md, [], timestamp=t0 + i)
        assert c.validate_and_insert_block(blk) in ("utxo_valid", "utxo_pending")
        tip = blk.hash
    return tip


def test_deep_stale_fork_merge_rejected():
    params = simnet_params(bps=2)
    params.merge_depth = 5
    c = Consensus(params)
    md = _miner_data(b"\x01")

    # stale fork block directly on genesis
    stale = c.build_block_with_parents([params.genesis.hash], _miner_data(b"\x09"), [], timestamp=5_000)
    assert c.validate_and_insert_block(stale) in ("utxo_valid", "utxo_pending")

    # grow the main chain beyond both k (so the stale block becomes red when
    # merged) and merge_depth, never merging the fork
    tip = _grow(c, params.genesis.hash, params.ghostdag_k + 9, md)

    # merging both now puts a red beyond the merge-depth root with no
    # kosherizing blue -> bounded merge violation
    bad = c.build_block_with_parents([tip, stale.hash], md, [], timestamp=99_000)
    gd = c.ghostdag_manager.ghostdag([tip, stale.hash])
    assert stale.hash in gd.mergeset_reds, "test setup: stale fork must be red"
    with pytest.raises(RuleError, match="merge depth"):
        c.validate_and_insert_block(bad)


def test_recent_fork_merge_allowed():
    params = simnet_params(bps=2)
    params.merge_depth = 5
    c = Consensus(params)
    md = _miner_data(b"\x02")

    tip = _grow(c, params.genesis.hash, 6, md)
    # a shallow fork (within depth; blue anyway) merges fine
    fork = c.build_block_with_parents(
        [c.storage.ghostdag.get_selected_parent(tip)], _miner_data(b"\x03"), [], timestamp=50_000
    )
    assert c.validate_and_insert_block(fork) in ("utxo_valid", "utxo_pending")
    merged = c.build_block_with_parents([tip, fork.hash], md, [], timestamp=60_000)
    assert c.validate_and_insert_block(merged) in ("utxo_valid", "utxo_pending")
