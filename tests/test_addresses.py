"""Address codec golden tests (vectors from crypto/addresses/src/lib.rs tests)."""

import pytest

from kaspa_tpu.crypto.addresses import (
    PREFIX_MAINNET,
    PREFIX_TESTNET,
    VERSION_PUBKEY,
    VERSION_PUBKEY_ECDSA,
    Address,
    AddressError,
    extract_script_pub_key_address,
    pay_to_address_script,
)

VECTORS = [
    (PREFIX_TESTNET, VERSION_PUBKEY, b"\x00" * 32, "kaspatest:qqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqhqrxplya"),
    (PREFIX_TESTNET, VERSION_PUBKEY_ECDSA, b"\x00" * 33, "kaspatest:qyqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqhe837j2d"),
    (
        PREFIX_TESTNET,
        VERSION_PUBKEY_ECDSA,
        bytes.fromhex("ba01fc5f4e9d9879599c69a3dafdb835a7255e5f2e934e9322ecd3af190ab0f60e"),
        "kaspatest:qxaqrlzlf6wes72en3568khahq66wf27tuhfxn5nytkd8tcep2c0vrse6gdmpks",
    ),
    (PREFIX_MAINNET, VERSION_PUBKEY, b"\x00" * 32, "kaspa:qqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqkx9awp4e"),
    (
        PREFIX_MAINNET,
        VERSION_PUBKEY,
        bytes.fromhex("5fff3c4da18f45adcdd499e44611e9fff148ba69db3c4ea2ddd955fc46a59522"),
        "kaspa:qp0l70zd5x85ttwd6jv7g3s3a8llzj96d8dncn4zmhv4tlzx5k2jyqh70xmfj",
    ),
]


def test_address_encode_golden():
    for prefix, version, payload, expected in VECTORS:
        assert Address(prefix, version, payload).to_string() == expected


def test_address_decode_roundtrip():
    for prefix, version, payload, expected in VECTORS:
        a = Address.from_string(expected)
        assert (a.prefix, a.version, a.payload) == (prefix, version, payload)


def test_bad_checksum_rejected():
    s = "kaspa:qqqqqqqqqqqqq1qqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqqkx9awp4e"
    with pytest.raises(AddressError):
        Address.from_string(s)


def test_script_address_roundtrip():
    a = Address.from_string(VECTORS[4][3])
    spk = pay_to_address_script(a)
    assert extract_script_pub_key_address(spk, PREFIX_MAINNET) == a
