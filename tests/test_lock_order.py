"""Lock-order discipline + hold tracing (SURVEY §5 race/deadlock strategy).

LockCtx is the framework's deadlock-detection story: under debug every
guarded acquisition asserts the global rank order and records hold-time
aggregates (the reference's ranked-lock discipline + semaphore trace,
utils/src/sync/semaphore.rs).  These tests run the REAL node flow under
debug and prove both the clean path and the loud failure on inversion.
"""

from __future__ import annotations

import random

import pytest

from kaspa_tpu.utils import sync as usync
from kaspa_tpu.utils.sync import LockCtx


@pytest.fixture()
def lock_debug():
    usync.set_lock_debug(True)
    with usync._trace_mu:
        usync._trace.clear()
    yield
    usync.set_lock_debug(False)


def test_ordering_violation_raises(lock_debug):
    low = LockCtx("inner", rank=5)
    high = LockCtx("outer", rank=10)
    # correct order: lower rank first
    with low, high:
        pass
    # inversion fails loudly instead of deadlocking at runtime
    with pytest.raises(AssertionError, match="lock-order violation"), high:
        with low:
            pass
    # reentrancy on the SAME lock is not a violation (RLock semantics)
    with low, low:
        pass


def test_node_flow_clean_under_debug_and_traced(lock_debug):
    """Relay + RPC dispatch through the real node/pipeline lock hierarchy
    runs without ordering violations, and the trace accumulates."""
    from kaspa_tpu.consensus.consensus import Consensus
    from kaspa_tpu.consensus.params import simnet_params
    from kaspa_tpu.consensus.processes.coinbase import MinerData
    from kaspa_tpu.p2p.node import Node, connect
    from kaspa_tpu.sim.simulator import Miner

    params = simnet_params(bps=2)
    a = Node(Consensus(params), "a")
    b = Node(Consensus(params), "b")
    connect(a, b)
    miner = Miner(0, random.Random(5))
    for i in range(6):
        t = a.consensus.build_block_template(MinerData(miner.spk, b""), [], timestamp=10_000 + 600 * i)
        # the daemon's dispatch discipline: node lock (rank 5) held around
        # the submit, pipeline commit lock (rank 10) taken inside
        with a.lock:
            a.submit_block(t)
    assert b.consensus.sink() == a.consensus.sink()
    trace = usync.lock_trace_snapshot()
    assert trace.get("node", {}).get("acquisitions", 0) > 0
    assert trace.get("consensus-commit", {}).get("acquisitions", 0) > 0
    assert all(v["total_hold_s"] >= 0 for v in trace.values())


def test_sim_replay_and_ingest_flood_clean_under_debug(lock_debug):
    """The PR 13 adoption gate: a 24-block sim replay through the full
    pipeline plus an ingest flood wave, all with lock debug on.  Any rank
    inversion on the migrated locks (RANKS table, utils/sync.py) raises
    AssertionError here; on success the trace must carry hold-time
    aggregates for the newly ranked subsystems."""
    import threading

    from kaspa_tpu.consensus.consensus import Consensus
    from kaspa_tpu.ingest.tier import ACCEPTED, IngestTier
    from kaspa_tpu.mempool import MiningManager
    from kaspa_tpu.sim.simulator import SimConfig, simulate
    from tests.test_ingest import _spends

    from kaspa_tpu.ops import dispatch as coalesce
    from kaspa_tpu.pipeline.pipeline import ConsensusPipeline

    cfg = SimConfig(bps=2, delay=0.5, num_miners=2, num_blocks=24, txs_per_block=2, seed=23)
    res = simulate(cfg)
    c = Consensus(res.params)
    coalesce.configure(64)  # engage the coalescing queue (dispatch.queue rank)
    try:
        pipe = ConsensusPipeline(c, workers=3)
        futs = [pipe.submit(b) for b in res.blocks]
        for f in futs:
            f.result(timeout=120)
        pipe.wait_for_idle()
        pipe.shutdown()
    finally:
        coalesce.configure(0)

    # flood wave: concurrent submitters race the queue locks, one pump
    # drains through mempool admission on the verify plane
    tier = IngestTier(MiningManager(c))
    # simulate() draws miner keys from Random(seed) at construction, in
    # order — reseeding reproduces the keypairs that own the sim's UTXOs
    from kaspa_tpu.sim.simulator import Miner

    sim_rng = random.Random(cfg.seed)
    miners = [Miner(i, sim_rng) for i in range(cfg.num_miners)]
    txs = _spends(c, miners[0], random.Random(31), 6)
    tickets = []
    t_mu = threading.Lock()

    def _submit(tx, src):
        tk = tier.submit(tx, src)
        with t_mu:
            tickets.append(tk)

    threads = [
        threading.Thread(target=_submit, args=(tx, "rpc" if i % 2 else "p2p"))
        for i, tx in enumerate(txs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert tier.pump() == len(txs)
    assert all(t.status == ACCEPTED for t in tickets)
    assert tier.stats()["lost"] == 0

    trace = usync.lock_trace_snapshot()
    # the replay exercises the pipeline ranks, the flood the ingest ranks
    for name in ("consensus-commit", "pipeline.deps", "pipeline.idle",
                 "dispatch.queue", "ingest.queue", "ingest.state", "ingest.stats"):
        assert trace.get(name, {}).get("acquisitions", 0) > 0, f"no hold trace for {name}"
        assert trace[name]["total_hold_s"] >= trace[name]["max_hold_s"] >= 0


def test_ranked_lock_table_is_consistent():
    """Every RANKS name builds, ranks are unique enough to order the
    documented nestings, and ranked_lock rejects undeclared names."""
    from kaspa_tpu.utils.sync import RANKS, ranked_lock

    assert RANKS["node"] < RANKS["consensus-commit"] < RANKS["dispatch.queue"]
    assert RANKS["fabric.config"] < RANKS["fabric.balancer"] < RANKS["fabric.wire"]
    assert RANKS["serving.broadcaster"] < RANKS["serving.subscriber"]
    lk = ranked_lock("pipeline.idle", reentrant=False)
    assert lk.rank == RANKS["pipeline.idle"]
    cv = lk.condition()
    with lk:
        cv.notify_all()  # bound to the same underlying lock: must not raise
    with pytest.raises(KeyError):
        ranked_lock("no-such-lock")


def test_metrics_exposes_lock_trace(lock_debug):
    from kaspa_tpu.consensus.consensus import Consensus
    from kaspa_tpu.consensus.params import simnet_params
    from kaspa_tpu.mempool import MiningManager
    from kaspa_tpu.rpc.service import RpcCoreService

    c = Consensus(simnet_params(bps=2))
    svc = RpcCoreService(c, MiningManager(c))
    with LockCtx("probe", rank=99):
        pass
    m = svc.get_metrics()
    assert m["lock_trace"].get("probe", {}).get("acquisitions") == 1
