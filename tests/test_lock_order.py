"""Lock-order discipline + hold tracing (SURVEY §5 race/deadlock strategy).

LockCtx is the framework's deadlock-detection story: under debug every
guarded acquisition asserts the global rank order and records hold-time
aggregates (the reference's ranked-lock discipline + semaphore trace,
utils/src/sync/semaphore.rs).  These tests run the REAL node flow under
debug and prove both the clean path and the loud failure on inversion.
"""

from __future__ import annotations

import random

import pytest

from kaspa_tpu.utils import sync as usync
from kaspa_tpu.utils.sync import LockCtx


@pytest.fixture()
def lock_debug():
    usync.set_lock_debug(True)
    with usync._trace_mu:
        usync._trace.clear()
    yield
    usync.set_lock_debug(False)


def test_ordering_violation_raises(lock_debug):
    low = LockCtx("inner", rank=5)
    high = LockCtx("outer", rank=10)
    # correct order: lower rank first
    with low, high:
        pass
    # inversion fails loudly instead of deadlocking at runtime
    with pytest.raises(AssertionError, match="lock-order violation"), high:
        with low:
            pass
    # reentrancy on the SAME lock is not a violation (RLock semantics)
    with low, low:
        pass


def test_node_flow_clean_under_debug_and_traced(lock_debug):
    """Relay + RPC dispatch through the real node/pipeline lock hierarchy
    runs without ordering violations, and the trace accumulates."""
    from kaspa_tpu.consensus.consensus import Consensus
    from kaspa_tpu.consensus.params import simnet_params
    from kaspa_tpu.consensus.processes.coinbase import MinerData
    from kaspa_tpu.p2p.node import Node, connect
    from kaspa_tpu.sim.simulator import Miner

    params = simnet_params(bps=2)
    a = Node(Consensus(params), "a")
    b = Node(Consensus(params), "b")
    connect(a, b)
    miner = Miner(0, random.Random(5))
    for i in range(6):
        t = a.consensus.build_block_template(MinerData(miner.spk, b""), [], timestamp=10_000 + 600 * i)
        # the daemon's dispatch discipline: node lock (rank 5) held around
        # the submit, pipeline commit lock (rank 10) taken inside
        with a.lock:
            a.submit_block(t)
    assert b.consensus.sink() == a.consensus.sink()
    trace = usync.lock_trace_snapshot()
    assert trace.get("node", {}).get("acquisitions", 0) > 0
    assert trace.get("consensus-commit", {}).get("acquisitions", 0) > 0
    assert all(v["total_hold_s"] >= 0 for v in trace.values())


def test_metrics_exposes_lock_trace(lock_debug):
    from kaspa_tpu.consensus.consensus import Consensus
    from kaspa_tpu.consensus.params import simnet_params
    from kaspa_tpu.mempool import MiningManager
    from kaspa_tpu.rpc.service import RpcCoreService

    c = Consensus(simnet_params(bps=2))
    svc = RpcCoreService(c, MiningManager(c))
    with LockCtx("probe", rank=99):
        pass
    m = svc.get_metrics()
    assert m["lock_trace"].get("probe", {}).get("acquisitions") == 1
