"""Ingest tier tests: queue fairness, backpressure, wave batching, worker
path, and batched-vs-per-tx admission identity.

Uses the same small simulated chain as the mempool tests for mature
spendable UTXOs, then drives admission through ``IngestTier`` instead of
``MiningManager.validate_and_insert_transaction`` directly.
"""

import random

import pytest

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.consensus.model import (
    Transaction,
    TransactionInput,
    TransactionOutpoint,
    TransactionOutput,
)
from kaspa_tpu.consensus.model.tx import SUBNETWORK_ID_NATIVE, ComputeCommit
from kaspa_tpu.crypto import eclib
from kaspa_tpu.ingest.queue import SOURCE_P2P, SOURCE_RPC, IngestQueue
from kaspa_tpu.ingest.tier import (
    ACCEPTED,
    ORPHANED,
    REJECTED,
    IngestConfig,
    IngestTier,
)
from kaspa_tpu.mempool import MiningManager
from kaspa_tpu.mempool.mempool import MempoolError
from kaspa_tpu.sim.simulator import Miner, SimConfig, simulate
from kaspa_tpu.txscript import standard


@pytest.fixture(scope="module")
def chain():
    cfg = SimConfig(bps=2, delay=0.5, num_miners=2, num_blocks=26, txs_per_block=0, seed=17)
    res = simulate(cfg)
    from kaspa_tpu.consensus.consensus import Consensus

    c = Consensus(res.params)
    for b in res.blocks:
        c.validate_and_insert_block(b)
    sim_rng = random.Random(17)
    miners = [Miner(i, sim_rng) for i in range(2)]
    return c, miners


def _spends(consensus, miner, rng, n, fee=1000):
    """n signed single-input spends of distinct mature UTXOs of `miner`."""
    view = consensus.get_virtual_utxo_view()
    pov = consensus.get_virtual_daa_score()
    maturity = consensus.params.coinbase_maturity
    txs = []
    for outpoint, entry in list(consensus.utxo_set.items()):
        if len(txs) == n:
            break
        if view.get(outpoint) is None:
            continue
        if entry.script_public_key != miner.spk:
            continue
        if entry.is_coinbase and entry.block_daa_score + maturity > pov:
            continue
        tx = Transaction(
            0,
            [TransactionInput(outpoint, b"", 0, ComputeCommit.sigops(1))],
            [TransactionOutput(entry.amount - fee, miner.spk)],
            0,
            SUBNETWORK_ID_NATIVE,
            0,
            b"",
        )
        reused = chash.SigHashReusedValues()
        msg = chash.calc_schnorr_signature_hash(tx, [entry], 0, chash.SIG_HASH_ALL, reused)
        sig = eclib.schnorr_sign(msg, miner.seckey, rng.randbytes(32))
        tx.inputs[0].signature_script = standard.schnorr_signature_script(sig, chash.SIG_HASH_ALL)
        txs.append(tx)
    assert len(txs) == n, f"only {len(txs)} mature utxos available"
    return txs


# --- queue ---------------------------------------------------------------


def test_queue_round_robin_fairness():
    q = IngestQueue(capacity=8)
    for x in (1, 2, 3):
        assert q.put(SOURCE_RPC, x)
    for x in ("a", "b"):
        assert q.put(SOURCE_P2P, x)
    # the wave alternates lanes (rpc first: cursor starts there) while
    # preserving each lane's FIFO order
    assert q.pop_wave(10) == [1, "a", 2, "b", 3]
    assert q.depth() == 0


def test_queue_sheds_only_the_full_lane():
    q = IngestQueue(capacity=2)
    assert q.put(SOURCE_P2P, "a")
    assert q.put(SOURCE_P2P, "b")
    assert not q.put(SOURCE_P2P, "c")  # p2p lane full: shed
    assert q.put(SOURCE_RPC, 1)  # rpc lane unaffected
    assert q.depth(SOURCE_P2P) == 2
    assert q.depth(SOURCE_RPC) == 1


# --- tier: sync (pump) path ---------------------------------------------


def test_wave_batches_concurrent_entrants(chain):
    consensus, miners = chain
    tier = IngestTier(MiningManager(consensus))
    txs = _spends(consensus, miners[0], random.Random(11), 4)
    tickets = [tier.submit(tx, SOURCE_RPC if i % 2 == 0 else SOURCE_P2P) for i, tx in enumerate(txs)]
    assert tier.pump() == 4
    assert all(t.status == ACCEPTED for t in tickets)
    stats = tier.stats()
    assert stats["waves"] == 1  # all four entrants rode one wave
    assert stats["lost"] == 0
    assert all(t.raise_for_status() == [] for t in tickets)


def test_backpressure_resolves_ticket_immediately(chain):
    consensus, miners = chain
    tier = IngestTier(MiningManager(consensus), config=IngestConfig(queue_capacity=1))
    txs = _spends(consensus, miners[0], random.Random(13), 2)
    t1 = tier.submit(txs[0], SOURCE_P2P)
    t2 = tier.submit(txs[1], SOURCE_P2P)  # lane full: shed, not queued
    assert t2.status == REJECTED
    with pytest.raises(MempoolError, match="queue full"):
        t2.raise_for_status()
    assert t2.error.code == "ingest-backpressure"
    tier.pump()
    assert t1.status == ACCEPTED
    assert tier.stats()["lost"] == 0


def test_orphan_parks_and_duplicate_rejects(chain):
    consensus, miners = chain
    mgr = MiningManager(consensus)
    tier = IngestTier(mgr)
    orphan = Transaction(
        0,
        [TransactionInput(TransactionOutpoint(b"\x77" * 32, 0), b"\x01\x01", 0, ComputeCommit.sigops(1))],
        [TransactionOutput(100, miners[0].spk)],
        0,
        SUBNETWORK_ID_NATIVE,
        0,
        b"",
    )
    ticket = tier.admit(orphan)
    assert ticket.status == ORPHANED
    assert orphan.id() in mgr.mempool.orphans
    # resubmitting the same parked tx is a duplicate rejection
    dup = tier.admit(orphan)
    assert dup.status == REJECTED
    with pytest.raises(MempoolError, match="already"):
        dup.raise_for_status()


# --- tier: worker-thread path --------------------------------------------


def test_worker_thread_admits_and_drains(chain):
    consensus, miners = chain
    tier = IngestTier(MiningManager(consensus))
    txs = _spends(consensus, miners[1], random.Random(19), 4)
    tier.start()
    try:
        tickets = [tier.submit(tx, SOURCE_RPC if i % 2 == 0 else SOURCE_P2P) for i, tx in enumerate(txs)]
        for t in tickets:
            assert t.wait(30.0), "ticket not resolved by the worker"
        assert all(t.status == ACCEPTED for t in tickets)
    finally:
        tier.stop()
    stats = tier.stats()
    assert stats["lost"] == 0
    assert stats["submitted"] == stats["resolved"] == 4


# --- batched vs per-tx identity ------------------------------------------


def test_batched_admission_matches_per_tx(chain):
    """One wave through the shared-checker split intake must leave the
    mempool exactly as N per-tx validate_and_insert calls in the same
    order (the roundcheck ``ingest`` gate, unit-sized)."""
    consensus, miners = chain
    batched_mgr = MiningManager(consensus, seed=5)
    pertx_mgr = MiningManager(consensus, seed=5)
    txs = _spends(consensus, miners[0], random.Random(23), 3)
    # a conflicting higher-fee respend of the first target rides the same
    # wave, so the RBF path is part of the identity too
    rbf = _spends(consensus, miners[0], random.Random(29), 1, fee=5000)

    tier = IngestTier(batched_mgr)
    tickets = [tier.submit(tx) for tx in [*txs, *rbf]]
    tier.pump()
    assert tier.stats()["lost"] == 0
    assert tickets[-1].status == ACCEPTED  # RBF won (strictly higher feerate)

    for tx in [*txs, *rbf]:
        try:
            pertx_mgr.validate_and_insert_transaction(tx)
        except MempoolError:
            pass

    pool_a = {t: e.fee for t, e in batched_mgr.mempool.pool.items()}
    pool_b = {t: e.fee for t, e in pertx_mgr.mempool.pool.items()}
    assert pool_a == pool_b
    assert set(batched_mgr.mempool.orphans) == set(pertx_mgr.mempool.orphans)
