"""Notification pipeline + utxoindex tests (reference: notify/, indexes/utxoindex)."""

import random

import pytest

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.index import UtxoIndex
from kaspa_tpu.notify.notifier import Notification, Notifier
from kaspa_tpu.sim.simulator import Miner, SimConfig, simulate


def test_notifier_subscription_filtering():
    root = Notifier("root")
    got = []
    lid = root.register(got.append)
    root.start_notify(lid, "block-added")
    root.notify(Notification("block-added", {"n": 1}))
    root.notify(Notification("virtual-daa-score-changed", {"n": 2}))  # not subscribed
    assert len(got) == 1 and got[0].data["n"] == 1
    root.stop_notify(lid, "block-added")
    root.notify(Notification("block-added", {"n": 3}))
    assert len(got) == 1


def test_notifier_chaining():
    root = Notifier("root")
    child = Notifier("child", parent=root)
    got = []
    lid = child.register(got.append)
    child.start_notify(lid, "block-added")
    root.notify(Notification("block-added", {"n": 7}))  # flows root -> child -> listener
    assert len(got) == 1 and got[0].data["n"] == 7


def test_utxos_changed_address_filter():
    root = Notifier("root")
    got = []
    lid = root.register(got.append)
    root.start_notify(lid, "utxos-changed", addresses={b"spk-a"})

    class _SPK:
        def __init__(self, s):
            self.script = s

    class _E:
        def __init__(self, s):
            self.script_public_key = _SPK(s)
            self.amount = 5

    n = Notification(
        "utxos-changed",
        {"added": [("op1", _E(b"spk-a")), ("op2", _E(b"spk-b"))], "removed": [], "spk_set": {b"spk-a", b"spk-b"}},
    )
    root.notify(n)
    assert len(got) == 1
    assert [x[0] for x in got[0].data["added"]] == ["op1"]
    # notification touching only other addresses is dropped
    root.notify(Notification("utxos-changed", {"added": [], "removed": [], "spk_set": {b"spk-b"}}))
    assert len(got) == 1


def test_utxoindex_tracks_chain(tmp_path):
    cfg = SimConfig(bps=2, delay=0.5, num_miners=2, num_blocks=20, txs_per_block=2, seed=19)
    res = simulate(cfg)
    c = Consensus(res.params)
    index = UtxoIndex(c)
    for b in res.blocks:
        c.validate_and_insert_block(b)
    # index must match a fresh resync of the virtual set
    live = {s: dict(u) for s, u in index._by_script.items()}
    index.resync()
    assert {s: dict(u) for s, u in index._by_script.items()} == live
    # balances: sum of index == circulating supply == sum of virtual set view
    supply = index.get_circulating_supply()
    assert supply > 0
    sim_rng = random.Random(19)
    miners = [Miner(i, sim_rng) for i in range(2)]
    assert index.get_balance_by_script(miners[0].spk.script) > 0
