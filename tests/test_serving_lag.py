"""Serving latency observatory: per-stage lag instrumentation on the
broadcaster delivery path (serving_lag_ms histograms, conflation lag
honesty, Prometheus exposition of the new families, tracing-off payload
bit-identity, and the overload pressure signal fed by queue-wait lag)."""

from __future__ import annotations

import queue
import re
import threading
import time
from time import perf_counter_ns

import pytest

from kaspa_tpu.notify.notifier import Notification, Notifier
from kaspa_tpu.observability import core as obs_core
from kaspa_tpu.observability import prom
from kaspa_tpu.observability.core import MS_LATENCY_BUCKETS
from kaspa_tpu.serving import Broadcaster, Subscriber
from kaspa_tpu.serving import broadcaster as broadcaster_mod


@pytest.fixture(autouse=True)
def _restore_stage_tracing():
    prev = broadcaster_mod.stage_tracing_enabled()
    yield
    broadcaster_mod.set_stage_tracing(prev)


def _wait_until(cond, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def _stage_counts() -> dict:
    return {s: broadcaster_mod._LAG_MS.cell(s).count for s in broadcaster_mod.LAG_STAGES}


# ---------------------------------------------------------------------------
# accept stamps + per-stage feed
# ---------------------------------------------------------------------------


def test_notification_carries_accept_stamp():
    t0 = perf_counter_ns()
    n = Notification("block-added", {"n": 1})
    assert t0 <= n.t_accept_ns <= perf_counter_ns()
    assert n.merged == 0
    # an explicit stamp (conflation, filtering) is preserved verbatim
    m = Notification("block-added", {"n": 2}, t_accept_ns=123, merged=3)
    assert (m.t_accept_ns, m.merged) == (123, 3)


def test_scope_filter_propagates_stamp_and_merge_count():
    class _Spk:
        def __init__(self, s):
            self.script = s

    class _Entry:
        def __init__(self, s):
            self.script_public_key = _Spk(s)

    s = b"\x01" * 4
    n = Notification(
        "utxos-changed",
        {"added": [("op", _Entry(s))], "removed": [], "spk_set": {s}},
        t_accept_ns=777, merged=2,
    )
    f = Broadcaster._filter_utxos_changed(n, frozenset({s}), Broadcaster._index_diff(n))
    assert (f.t_accept_ns, f.merged) == (777, 2)


def test_per_stage_lag_feed_through_delivery():
    broadcaster_mod.set_stage_tracing(True)
    before = _stage_counts()
    root = Notifier("rpc")
    bc = Broadcaster(root)
    sink: queue.Queue = queue.Queue()
    sub = Subscriber("lagged", lambda n: str(n.data["n"]).encode(), sink)
    total = 5
    try:
        bc.register(sub)
        bc.subscribe(sub, "block-added")
        for i in range(total):
            root.notify(Notification("block-added", {"n": i}))
        got = [sink.get(timeout=10) for _ in range(total)]
        assert got == [str(i).encode() for i in range(total)]
        assert _wait_until(lambda: sub.delivered == total)
    finally:
        bc.close()
    after = _stage_counts()
    # every stage of the observatory saw every delivery (one fanout pickup
    # + one delivery per event: a single subscriber)
    for stage in broadcaster_mod.LAG_STAGES:
        assert after[stage] - before[stage] == total, stage


def test_stage_tracing_off_skips_lag_observes_but_not_delivery():
    before = _stage_counts()
    root = Notifier("rpc")
    bc = Broadcaster(root)
    sink: queue.Queue = queue.Queue()
    sub = Subscriber("untraced", lambda n: str(n.data["n"]).encode(), sink)
    try:
        bc.register(sub)
        bc.subscribe(sub, "block-added")
        broadcaster_mod.set_stage_tracing(False)
        assert not broadcaster_mod.stage_tracing_enabled()
        for i in range(3):
            root.notify(Notification("block-added", {"n": i}))
        assert [sink.get(timeout=10) for _ in range(3)] == [b"0", b"1", b"2"]
        assert _wait_until(lambda: sub.delivered == 3)
        # the legacy per-encoding lag family still feeds (serving_check
        # scrapes it) ...
        assert broadcaster_mod._LAG.cell("json").count > 0
    finally:
        bc.close()
    # ... but none of the per-stage families moved
    assert _stage_counts() == before


# ---------------------------------------------------------------------------
# conflation: lag honesty under brownout
# ---------------------------------------------------------------------------


def _diff(n_added: int, t_accept_ns: int, merged: int = 0) -> Notification:
    class _Spk:
        def __init__(self, s):
            self.script = s

    class _Entry:
        def __init__(self, s):
            self.script_public_key = _Spk(s)

    s = b"\x07" * 4
    return Notification(
        "utxos-changed",
        {"added": [(f"op{i}", _Entry(s)) for i in range(n_added)], "removed": [], "spk_set": {s}},
        t_accept_ns=t_accept_ns, merged=merged,
    )


def test_conflation_keeps_oldest_accept_stamp_and_merge_count():
    old = _diff(2, t_accept_ns=1_000)
    new = _diff(3, t_accept_ns=9_000)
    merged = broadcaster_mod._conflate_utxos_changed(old, new)
    assert merged.t_accept_ns == 1_000  # the OLDEST constituent's stamp
    assert merged.merged == 1
    assert len(merged.data["added"]) == 5
    # merging again accumulates (and min() is order-independent)
    newer = _diff(1, t_accept_ns=500)
    again = broadcaster_mod._conflate_utxos_changed(merged, newer)
    assert again.t_accept_ns == 500
    assert again.merged == 2


def test_conflated_delivery_reports_lag_from_oldest_diff():
    """The delivered merged diff's end_to_end lag must cover the OLDEST
    merged constituent's age — conflation cannot hide staleness."""
    broadcaster_mod.set_stage_tracing(True)
    age_ns = 5_000_000_000  # 5s: far above anything this suite produces
    old = _diff(1, t_accept_ns=perf_counter_ns() - age_ns)
    new = _diff(1, t_accept_ns=perf_counter_ns())
    merged = broadcaster_mod._conflate_utxos_changed(old, new)

    e2e = broadcaster_mod._LAG_END_TO_END
    conf = broadcaster_mod._CONFLATE_MERGED
    sum_before, merged_count_before = e2e.sum, conf.count
    sub = Subscriber("conflated", lambda n: b"x", queue.Queue())
    try:
        assert sub._deliver(merged, perf_counter_ns())
    finally:
        sub.close()
    # one delivery, whose end_to_end observation is >= the old diff's age
    assert e2e.sum - sum_before >= age_ns * 1e-6 * 0.99
    assert conf.count - merged_count_before == 1  # 2 diffs folded into 1


def test_offer_path_conflation_merges_with_oldest_stamp():
    """Through the real offer() brownout path: a wedged subscriber at the
    conflate floor folds queued diffs, keeping the oldest accept stamp."""
    released = threading.Event()

    class _WedgedSink:
        def put(self, item, timeout=None):
            if not released.is_set():
                time.sleep(min(timeout or 0.02, 0.02))
                raise queue.Full
            self.got = item

    sub = Subscriber("brownout", lambda n: b"x", _WedgedSink(), maxlen=8)
    sub.conflate_floor = 1
    try:
        t_old = perf_counter_ns() - 1_000_000
        # first event is popped by the sender (wedged in put); the next two
        # meet at the floor and conflate in-queue
        sub.offer(_diff(1, t_accept_ns=perf_counter_ns()), perf_counter_ns())
        assert _wait_until(lambda: sub.queue_depth() == 0)
        sub.offer(_diff(1, t_accept_ns=t_old), perf_counter_ns())
        sub.offer(_diff(1, t_accept_ns=perf_counter_ns()), perf_counter_ns())
        assert _wait_until(lambda: sub.conflated == 1)
        assert sub.queue_depth() == 1
        queued, _t = sub._dq[-1]
        assert queued.merged == 1
        assert queued.t_accept_ns == t_old
        released.set()
        assert _wait_until(lambda: sub.delivered == 2)
    finally:
        sub.close()


# ---------------------------------------------------------------------------
# quantile edges + Prometheus exposition of the new families
# ---------------------------------------------------------------------------


def test_ms_lag_histogram_quantile_edges():
    h = obs_core.Histogram("t", MS_LATENCY_BUCKETS)
    assert h.quantile(0.99) == 0.0  # empty -> 0.0, not NaN
    h.observe(0.3)
    assert h.quantile(0.5) == 0.5  # upper edge of the holding bucket
    h.observe(50_000.0)  # above the 10_000ms top edge
    assert h.quantile(0.999) == float("inf")  # overflow bucket -> inf


_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r' (-?\d+(\.\d+)?([eE][+-]?\d+)?|[+-]Inf|NaN)$'
)


def test_prom_render_of_serving_lag_parses_and_sums():
    """Render serving_lag_ms (with an overflow observation, so an inf
    quantile gauge exists) on an isolated registry and validate the text
    against the exposition grammar: every sample line parses, bucket
    counts are cumulative, +Inf closes each series, and non-finite values
    use the spec spellings (never Python's 'inf')."""
    reg = obs_core.Registry()
    fam = reg.histogram_family("serving_lag_ms", "stage", MS_LATENCY_BUCKETS)
    for stage, values in {
        "queue_wait": (0.05, 0.4, 3.0),
        "end_to_end": (1.0, 250.0, 50_000.0),  # one in the +Inf overflow
    }.items():
        for v in values:
            fam.observe(stage, v)
    quantiles = {
        stage: {"p50": h.quantile(0.5), "p999": h.quantile(0.999)}
        for stage, h in fam._cells.items()
    }
    assert quantiles["end_to_end"]["p999"] == float("inf")
    # the collector key deliberately differs from the histogram family
    # name: gauge samples may not wear a TYPEd family's name with a
    # non-histogram suffix (_p50 under "# TYPE ... histogram" is invalid)
    reg.register_collector("serving", lambda: {"lag_quantiles_ms": quantiles})

    text = prom.render(registry=reg)
    assert "inf" not in text  # the spelling is +Inf, capital I
    assert re.search(r'kaspa_serving_lag_quantiles_ms_p999\{key="end_to_end"\} \+Inf', text)
    series: dict[str, list[int]] = {}
    counts: dict[str, int] = {}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        assert _SAMPLE_RE.match(line), f"unparseable exposition line: {line!r}"
        m = re.match(r'^kaspa_serving_lag_ms_bucket\{stage="(\w+)",le="([^"]+)"\} (\d+)$', line)
        if m:
            series.setdefault(m.group(1), []).append(int(m.group(3)))
        m = re.match(r'^kaspa_serving_lag_ms_count\{stage="(\w+)"\} (\d+)$', line)
        if m:
            counts[m.group(1)] = int(m.group(2))
    assert set(series) == {"queue_wait", "end_to_end"}
    for stage, cum in series.items():
        assert cum == sorted(cum), f"{stage}: bucket counts not cumulative"
        assert len(cum) == len(MS_LATENCY_BUCKETS) + 1  # edges + le="+Inf"
        assert cum[-1] == counts[stage] == 3


def test_prom_fmt_nonfinite_spellings():
    assert prom._fmt(float("inf")) == "+Inf"
    assert prom._fmt(float("-inf")) == "-Inf"
    assert prom._fmt(float("nan")) == "NaN"
    assert prom._fmt(1.5) == "1.5"


# ---------------------------------------------------------------------------
# collector block + tracing-off bit-identity + overload signal
# ---------------------------------------------------------------------------


def test_serving_collector_reports_lag_quantiles_and_fanout():
    broadcaster_mod.set_stage_tracing(True)
    root = Notifier("rpc")
    bc = Broadcaster(root)
    sink: queue.Queue = queue.Queue()
    sub = Subscriber("snap", lambda n: b"x", sink)
    try:
        bc.register(sub)
        bc.subscribe(sub, "block-added")
        root.notify(Notification("block-added", {"n": 0}))
        sink.get(timeout=10)
        assert _wait_until(lambda: bc.fanout_events >= 1)
        snap = obs_core.REGISTRY.snapshot()["serving"]
    finally:
        bc.close()
    assert snap["subscribers"] == 1
    assert snap["stage_tracing"] == 1
    assert snap["fanout"]["events"] >= 1
    assert snap["fanout"]["busy_ns"] > 0
    for stage in ("queue_wait", "encode", "socket_write", "end_to_end"):
        q = snap["lag_quantiles_ms"][stage]
        assert q["count"] > 0
        assert 0.0 <= q["p50"] <= q["p99"] <= q["p999"]


def _collect_payload_stream(tracing_on: bool, events: list[Notification]) -> list[bytes]:
    broadcaster_mod.set_stage_tracing(tracing_on)
    root = Notifier("rpc")
    bc = Broadcaster(root)
    sink: queue.Queue = queue.Queue()
    scope = {b"\x01" * 4}
    sub = Subscriber(
        "stream", lambda n: repr([(op, e.script_public_key.script) for op, e in n.data["added"]]).encode(), sink
    )
    try:
        bc.register(sub)
        bc.subscribe(sub, "utxos-changed", scope)
        for n in events:
            root.notify(n)
        out = [sink.get(timeout=10) for _ in range(len(events))]
        assert _wait_until(lambda: sub.delivered == len(events))
        return out
    finally:
        bc.close()


def test_tracing_off_payload_stream_bit_identical():
    """KASPA_TPU_SERVING_TRACE only toggles telemetry: the encoded byte
    stream a subscriber receives (through the full fanout + scope-filter +
    delivery path) is identical with stage tracing on and off — accept
    stamps ride the Notification object, never the payload."""

    class _Spk:
        def __init__(self, s):
            self.script = s

    class _Entry:
        def __init__(self, s):
            self.script_public_key = _Spk(s)

    def mk_events():
        s, other = b"\x01" * 4, b"\x02" * 4
        return [
            Notification(
                "utxos-changed",
                {"added": [(f"op{i}-{j}", _Entry(s)) for j in range(i + 1)]
                 + [(f"alien{i}", _Entry(other))],
                 "removed": [], "spk_set": {s, other}},
            )
            for i in range(6)
        ]

    stream_on = _collect_payload_stream(True, mk_events())
    stream_off = _collect_payload_stream(False, mk_events())
    assert stream_on == stream_off


def test_overload_default_signals_include_fanout_lag():
    from kaspa_tpu.resilience.overload import DEFAULT_THRESHOLDS, default_signals

    root = Notifier("rpc")
    bc = Broadcaster(root)
    try:
        signals = {s.name: s for s in default_signals(broadcaster=bc)}
    finally:
        bc.close()
    assert "fanout_lag_ms" in signals
    assert signals["fanout_lag_ms"].enter == DEFAULT_THRESHOLDS["fanout_lag_ms"]
    # windowed mean: reads 0.0 when nothing new was observed since last read
    sig = signals["fanout_lag_ms"]
    sig.read()
    assert sig.read() == 0.0
    broadcaster_mod._LAG_QUEUE_WAIT.observe(40.0)
    assert sig.read() == pytest.approx(40.0)
