"""Pruning proof build/validate + trusted bootstrap tests.

Strategy mirrors the reference's pruning-import integration tests
(consensus/src/processes/pruning_proof/, testing/integration): a donor DAG
long enough for the pruning point to move, a proof + trusted snapshot +
pruning UTXO set exported, a fresh consensus bootstrapped from them, and
the remaining post-pp history replayed to convergence.  Negative cases
corrupt the UTXO set and the proof.
"""

from __future__ import annotations

import random

import pytest

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.model.block import Block
from kaspa_tpu.consensus.params import GenesisBlock, Params
from kaspa_tpu.consensus.processes.pruning_proof import ProofError
from kaspa_tpu.consensus.utxo import UtxoCollection
from kaspa_tpu.sim.simulator import Miner


def _prune_params() -> Params:
    genesis = GenesisBlock(hash=b"\x01" + b"\x00" * 31, bits=0x207FFFFF, timestamp=0)
    # windows must be shallower than the pruning depth (the reference's
    # params enforce this invariant; here the test scales both down)
    return Params.from_bps(
        "simnet-prunetest",
        2,
        genesis,
        skip_proof_of_work=True,
        coinbase_maturity=8,
        merge_depth=15,
        finality_depth=30,
        pruning_depth=60,
        pruning_proof_m=10,
        difficulty_window_size=15,
        min_difficulty_window_size=5,
        difficulty_sample_rate=2,
        past_median_time_window_size=10,
        past_median_time_sample_rate=2,
    )


@pytest.fixture(scope="module")
def donor():
    params = _prune_params()
    cons = Consensus(params)
    miner = Miner(0, random.Random(9))
    blocks = []
    for _ in range(160):
        t = cons.build_block_template(miner.miner_data, [])
        cons.validate_and_insert_block(t)
        blocks.append(t)
    assert cons.pruning_processor.pruning_point != params.genesis.hash, "pp never moved"
    return params, cons, blocks


def _export(cons):
    ppm = cons.pruning_proof_manager
    return ppm.build_proof(), ppm.get_trusted_data(), ppm.get_pruning_utxo_set()


def test_proof_builds_and_validates(donor):
    params, cons, _ = donor
    proof, trusted, _utxo = _export(cons)
    assert proof and proof[0]
    pp_header = max(proof[0], key=lambda h: (h.blue_work, h.hash))
    assert pp_header.hash == cons.pruning_processor.pruning_point
    # validation against a fresh node's (genesis-only) proof accepts
    fresh = Consensus(params)
    hdr = fresh.pruning_proof_manager.validate_proof(
        proof, fresh.pruning_proof_manager.build_proof()
    )
    assert hdr.hash == trusted.pruning_point
    # validation against an equal proof (the donor's own) rejects: ties
    # favor the defender (compare_proofs_inner discipline)
    with pytest.raises(ProofError):
        cons.pruning_proof_manager.validate_proof(proof, proof)


def test_trusted_bootstrap_and_catchup(donor):
    params, cons, _ = donor
    proof, trusted, utxo = _export(cons)
    imp = Consensus(params)
    imp.pruning_proof_manager.import_pruning_data(proof, trusted, utxo)
    pp = trusted.pruning_point
    assert imp.sink() == pp
    assert imp.pruning_processor.pruning_point == pp
    assert imp.pruning_processor.check_pruning_utxo_commitment()

    # replay the donor's post-pp history in topological order
    reach = cons.reachability
    post = [
        h
        for h in cons.storage.headers.keys()
        if h != pp and reach.has(h) and reach.is_dag_ancestor_of(pp, h)
    ]
    post.sort(key=lambda h: (cons.storage.ghostdag.get_blue_work(h), h))
    for h in post:
        blk = Block(cons.storage.headers.get(h), cons.storage.block_transactions.get(h))
        status = imp.validate_and_insert_block(blk)
        assert status in ("utxo_valid", "utxo_pending_verification"), (status, h.hex())
    assert imp.sink() == cons.sink()
    assert imp.get_virtual_daa_score() == cons.get_virtual_daa_score()
    assert dict(imp.utxo_set) == dict(cons.utxo_set)
    # the importer can now mine further blocks itself
    miner = Miner(1, random.Random(77))
    t = imp.build_block_template(miner.miner_data, [])
    assert imp.validate_and_insert_block(t) in ("utxo_valid", "utxo_pending_verification")


def test_corrupt_utxo_set_rejected(donor):
    params, cons, _ = donor
    proof, trusted, utxo = _export(cons)
    bad = UtxoCollection(dict(utxo))
    op = next(iter(bad))
    del bad[op]
    imp = Consensus(params)
    with pytest.raises(ProofError, match="commitment"):
        imp.pruning_proof_manager.import_pruning_data(proof, trusted, bad)


def test_shallow_proof_rejected(donor):
    params, cons, _ = donor
    proof, trusted, utxo = _export(cons)
    # strip level 0 below m without reaching genesis
    shallow = [proof[0][-3:]] + proof[1:]
    imp = Consensus(params)
    with pytest.raises(ProofError):
        imp.pruning_proof_manager.import_pruning_data(shallow, trusted, utxo)


def test_forged_blue_fields_rejected(donor):
    """Self-consistent structure but forged blue fields: inflating a
    non-tip header's claimed blue_work above the tip re-sorts it to the
    end of the level list, yet the RECOMPUTED per-level GHOSTDAG still
    selects the true tip — the proof is rejected (validate.rs recompute
    discipline; claimed fields cannot buy the tip)."""
    import dataclasses

    params, cons, _ = donor
    proof, _trusted, _utxo = _export(cons)
    forged_levels = [list(level) for level in proof]
    level0 = forged_levels[0]
    assert len(level0) >= 3
    victim = level0[len(level0) // 2]
    forged = dataclasses.replace(victim)
    forged.blue_work = level0[-1].blue_work + 1_000_000
    if hasattr(forged, "_hash_cache"):
        forged._hash_cache = None  # the forgery must re-hash
    level0[len(level0) // 2] = forged
    forged_levels[0] = sorted(level0, key=lambda h: (h.blue_work, h.hash))
    fresh = Consensus(params)
    with pytest.raises(ProofError):
        fresh.pruning_proof_manager.validate_proof(
            forged_levels, fresh.pruning_proof_manager.build_proof()
        )


def test_shallower_real_proof_loses():
    """A genuinely valid but shorter-history proof must not displace a
    deeper defender: recomputed blue-work beyond the common ancestor
    decides (compare_proofs_inner).  m is sized so the two proofs' level
    slices overlap across one finality-sample pruning-point gap, as real
    mainnet m windows do."""
    genesis = GenesisBlock(hash=b"\x01" + b"\x00" * 31, bits=0x207FFFFF, timestamp=0)
    params = Params.from_bps(
        "simnet-prooffight", 2, genesis, skip_proof_of_work=True, coinbase_maturity=8,
        merge_depth=15, finality_depth=30, pruning_depth=60, pruning_proof_m=20,
        difficulty_window_size=15, min_difficulty_window_size=5, difficulty_sample_rate=2,
        past_median_time_window_size=10, past_median_time_sample_rate=2,
    )

    def build(n):
        c = Consensus(params)
        m = Miner(0, random.Random(9))
        for _ in range(n):
            t = c.build_block_template(m.miner_data, [])
            c.validate_and_insert_block(t)
        return c

    deep, short = build(220), build(190)
    assert deep.pruning_processor.pruning_point != short.pruning_processor.pruning_point
    deep_proof = deep.pruning_proof_manager.build_proof()
    short_proof = short.pruning_proof_manager.build_proof()

    # the deep node rejects the shallow proof ...
    with pytest.raises(ProofError):
        deep.pruning_proof_manager.validate_proof(short_proof, deep_proof)
    # ... while the shallow node adopts the deep one
    hdr = short.pruning_proof_manager.validate_proof(deep_proof, short_proof)
    assert hdr.hash == deep.pruning_processor.pruning_point


def test_imported_node_serves_acceptable_proof(donor):
    """apply.rs parity: a proof-bootstrapped node can itself act as a proof
    donor without a cold rebuild — the proof it builds from retained proof
    headers is accepted by a third (fresh) node."""
    params, cons, _ = donor
    proof, trusted, utxo = _export(cons)
    imp = Consensus(params)
    imp.pruning_proof_manager.import_pruning_data(proof, trusted, utxo)

    served = imp.pruning_proof_manager.build_proof()
    assert served and served[0]
    third = Consensus(params)
    hdr = third.pruning_proof_manager.validate_proof(
        served, third.pruning_proof_manager.build_proof()
    )
    assert hdr.hash == trusted.pruning_point
