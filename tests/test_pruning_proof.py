"""Pruning proof build/validate + trusted bootstrap tests.

Strategy mirrors the reference's pruning-import integration tests
(consensus/src/processes/pruning_proof/, testing/integration): a donor DAG
long enough for the pruning point to move, a proof + trusted snapshot +
pruning UTXO set exported, a fresh consensus bootstrapped from them, and
the remaining post-pp history replayed to convergence.  Negative cases
corrupt the UTXO set and the proof.
"""

from __future__ import annotations

import random

import pytest

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.model.block import Block
from kaspa_tpu.consensus.params import GenesisBlock, Params
from kaspa_tpu.consensus.processes.pruning_proof import ProofError
from kaspa_tpu.consensus.utxo import UtxoCollection
from kaspa_tpu.sim.simulator import Miner


def _prune_params() -> Params:
    genesis = GenesisBlock(hash=b"\x01" + b"\x00" * 31, bits=0x207FFFFF, timestamp=0)
    # windows must be shallower than the pruning depth (the reference's
    # params enforce this invariant; here the test scales both down)
    return Params.from_bps(
        "simnet-prunetest",
        2,
        genesis,
        skip_proof_of_work=True,
        coinbase_maturity=8,
        merge_depth=15,
        finality_depth=30,
        pruning_depth=60,
        pruning_proof_m=10,
        difficulty_window_size=15,
        min_difficulty_window_size=5,
        difficulty_sample_rate=2,
        past_median_time_window_size=10,
        past_median_time_sample_rate=2,
    )


@pytest.fixture(scope="module")
def donor():
    params = _prune_params()
    cons = Consensus(params)
    miner = Miner(0, random.Random(9))
    blocks = []
    for _ in range(160):
        t = cons.build_block_template(miner.miner_data, [])
        cons.validate_and_insert_block(t)
        blocks.append(t)
    assert cons.pruning_processor.pruning_point != params.genesis.hash, "pp never moved"
    return params, cons, blocks


def _export(cons):
    ppm = cons.pruning_proof_manager
    return ppm.build_proof(), ppm.get_trusted_data(), ppm.get_pruning_utxo_set()


def test_proof_builds_and_validates(donor):
    params, cons, _ = donor
    proof, trusted, _utxo = _export(cons)
    assert proof and proof[0]
    pp_header = max(proof[0], key=lambda h: (h.blue_work, h.hash))
    assert pp_header.hash == cons.pruning_processor.pruning_point
    # validation against a fresh node's (genesis-only) proof accepts
    fresh = Consensus(params)
    fresh_works = fresh.pruning_proof_manager.proof_level_works(
        fresh.pruning_proof_manager.build_proof()
    )
    hdr = fresh.pruning_proof_manager.validate_proof(proof, fresh_works)
    assert hdr.hash == trusted.pruning_point
    # validation against an equal proof (the donor's own) rejects: derived
    # work exceeds at no level
    own_works = cons.pruning_proof_manager.proof_level_works(proof)
    with pytest.raises(ProofError):
        cons.pruning_proof_manager.validate_proof(proof, own_works)


def test_trusted_bootstrap_and_catchup(donor):
    params, cons, _ = donor
    proof, trusted, utxo = _export(cons)
    imp = Consensus(params)
    imp.pruning_proof_manager.import_pruning_data(proof, trusted, utxo)
    pp = trusted.pruning_point
    assert imp.sink() == pp
    assert imp.pruning_processor.pruning_point == pp
    assert imp.pruning_processor.check_pruning_utxo_commitment()

    # replay the donor's post-pp history in topological order
    reach = cons.reachability
    post = [
        h
        for h in cons.storage.headers.keys()
        if h != pp and reach.has(h) and reach.is_dag_ancestor_of(pp, h)
    ]
    post.sort(key=lambda h: (cons.storage.ghostdag.get_blue_work(h), h))
    for h in post:
        blk = Block(cons.storage.headers.get(h), cons.storage.block_transactions.get(h))
        status = imp.validate_and_insert_block(blk)
        assert status in ("utxo_valid", "utxo_pending_verification"), (status, h.hex())
    assert imp.sink() == cons.sink()
    assert imp.get_virtual_daa_score() == cons.get_virtual_daa_score()
    assert dict(imp.utxo_set) == dict(cons.utxo_set)
    # the importer can now mine further blocks itself
    miner = Miner(1, random.Random(77))
    t = imp.build_block_template(miner.miner_data, [])
    assert imp.validate_and_insert_block(t) in ("utxo_valid", "utxo_pending_verification")


def test_corrupt_utxo_set_rejected(donor):
    params, cons, _ = donor
    proof, trusted, utxo = _export(cons)
    bad = UtxoCollection(dict(utxo))
    op = next(iter(bad))
    del bad[op]
    imp = Consensus(params)
    with pytest.raises(ProofError, match="commitment"):
        imp.pruning_proof_manager.import_pruning_data(proof, trusted, bad)


def test_shallow_proof_rejected(donor):
    params, cons, _ = donor
    proof, trusted, utxo = _export(cons)
    # strip level 0 below m without reaching genesis
    shallow = [proof[0][-3:]] + proof[1:]
    imp = Consensus(params)
    with pytest.raises(ProofError):
        imp.pruning_proof_manager.import_pruning_data(shallow, trusted, utxo)
