"""Parallel VM fallback lane (txscript/batch.py).

Non-fast-path inputs are queued at collect time and executed at dispatch
on a bounded thread pool, overlapped with the device batches.  These tests
pin the serial-equivalence contract: identical results dict (including
which input index a failure maps to), identical first-error precedence,
and the `txscript_vm_fallbacks` counter still counting every routed input.
"""

import random

import pytest

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.consensus.model import (
    SUBNETWORK_ID_NATIVE,
    ComputeCommit,
    ScriptPublicKey,
    Transaction,
    TransactionInput,
    TransactionOutpoint,
    TransactionOutput,
    UtxoEntry,
)
from kaspa_tpu.crypto import eclib
from kaspa_tpu.observability.core import REGISTRY
from kaspa_tpu.txscript import standard
from kaspa_tpu.txscript.batch import BatchScriptChecker, ScriptCheckError
from kaspa_tpu.txscript.caches import SigCache
from kaspa_tpu.txscript.vm import TxScriptEngine

OP_1, OP_EQUAL = 0x51, 0x87


def _vm_fallback(tx, entries, i, reused, pov_daa_score=None, seq_commit_accessor=None):
    TxScriptEngine(tx, entries, i).execute()


def _p2sh_input(value: bytes, ok: bool):
    """(signature_script, spk) for a trivial P2SH redeem: <v> {OP_1 OP_EQUAL}."""
    redeem = bytes([OP_1, OP_EQUAL])
    spk = standard.pay_to_script_hash_script(redeem)
    push = value if ok else bytes([0x52])  # 2 != 1 -> false stack
    return push + bytes([len(redeem)]) + redeem, spk


def _multisig_input(rng, tx_builder):
    """2-of-3 schnorr multisig spk + a deferred signer (needs the final tx)."""
    keys = [rng.randrange(1, eclib.N) for _ in range(3)]
    pubs = [eclib.schnorr_pubkey(k) for k in keys]
    spk_script = bytes([0x52]) + b"".join(bytes([32]) + p for p in pubs) + bytes([0x53, 0xAE])
    return ScriptPublicKey(0, spk_script), keys


def _fallback_heavy_tx(seed: int, bad_input: int | None = None):
    """One tx whose every input routes to the VM lane: P2SH redeems plus a
    2-of-3 multisig; ``bad_input`` (if set) fails script execution."""
    rng = random.Random(seed)
    entries, inputs = [], []
    for i in range(3):
        sig_script, spk = _p2sh_input(bytes([OP_1]), ok=(i != bad_input))
        entries.append(UtxoEntry(10_000, spk, 5, False))
        inputs.append(
            TransactionInput(TransactionOutpoint(bytes([seed]) * 32, i), sig_script, 0, ComputeCommit.sigops(0))
        )
    ms_spk, ms_keys = _multisig_input(rng, None)
    entries.append(UtxoEntry(10_000, ms_spk, 5, False))
    inputs.append(
        TransactionInput(TransactionOutpoint(bytes([seed]) * 32, 3), b"", 0, ComputeCommit.sigops(3))
    )
    tx = Transaction(0, inputs, [TransactionOutput(9_000, entries[0].script_public_key)],
                     0, SUBNETWORK_ID_NATIVE, 0, b"")
    reused = chash.SigHashReusedValues()
    msg = chash.calc_schnorr_signature_hash(tx, entries, 3, chash.SIG_HASH_ALL, reused)
    sigs = [eclib.schnorr_sign(msg, k, rng.randbytes(32)) + bytes([chash.SIG_HASH_ALL]) for k in ms_keys]
    ms_script = bytes([len(sigs[0])]) + sigs[0] + bytes([len(sigs[2])]) + sigs[2]
    if bad_input == 3:
        ms_script = bytes([len(sigs[2])]) + sigs[2] + bytes([len(sigs[0])]) + sigs[0]  # wrong order
    tx.inputs[3].signature_script = ms_script
    return tx, entries


def _p2pk_tx(seed: int, corrupt: bool = False):
    rng = random.Random(seed)
    sk = rng.randrange(1, eclib.N)
    pub = eclib.schnorr_pubkey(sk)
    spk = standard.pay_to_pub_key(pub)
    entry = UtxoEntry(10_000, spk, 5, False)
    tx = Transaction(
        0,
        [TransactionInput(TransactionOutpoint(bytes([seed, 1]) * 16, 0), b"", 0, ComputeCommit.sigops(1))],
        [TransactionOutput(9_000, spk)], 0, SUBNETWORK_ID_NATIVE, 0, b"",
    )
    reused = chash.SigHashReusedValues()
    msg = chash.calc_schnorr_signature_hash(tx, [entry], 0, chash.SIG_HASH_ALL, reused)
    sig = eclib.schnorr_sign(msg, sk, rng.randbytes(32))
    if corrupt:
        sig = sig[:10] + bytes([sig[10] ^ 1]) + sig[11:]
    tx.inputs[0].signature_script = standard.schnorr_signature_script(sig, chash.SIG_HASH_ALL)
    return tx, [entry]


def _run_block(workers: int | None, bad_p2sh_token=2, bad_ms_token=4):
    """Collect a fallback-heavy 'block' and dispatch with the given lane
    width; returns {token: error | None}."""
    checker = BatchScriptChecker(SigCache(), _vm_fallback, fallback_workers=workers)
    blueprint = [
        (0, _fallback_heavy_tx(10)),
        (1, _p2pk_tx(11)),
        (2, _fallback_heavy_tx(12, bad_input=1)),
        (3, _p2pk_tx(13, corrupt=True)),
        (4, _fallback_heavy_tx(14, bad_input=3)),
        (5, _fallback_heavy_tx(15)),
    ]
    for token, (tx, entries) in blueprint:
        checker.collect_tx(token, tx, entries)
    return checker.dispatch()


def _summarize(results):
    return {
        t: None if e is None else (type(e).__name__, getattr(e, "input_index", None), str(e))
        for t, e in results.items()
    }


def test_parallel_matches_serial():
    serial = _run_block(workers=0)
    parallel = _run_block(workers=4)
    assert _summarize(serial) == _summarize(parallel)
    # the mix actually exercised both lanes
    assert serial[0] is None and serial[1] is None and serial[5] is None
    assert serial[2] is not None and serial[3] is not None and serial[4] is not None


@pytest.mark.parametrize("workers", [0, 4])
def test_failure_maps_to_input_index(workers):
    results = _run_block(workers=workers)
    assert isinstance(results[2], ScriptCheckError)
    assert results[2].input_index == 1  # the corrupted P2SH redeem
    assert isinstance(results[4], ScriptCheckError)
    assert results[4].input_index == 3  # the wrong-order multisig
    # fast-path failure still maps too
    assert isinstance(results[3], ScriptCheckError)
    assert results[3].input_index == 0


def test_fallback_counter_increments():
    before = REGISTRY.snapshot()["counters"]["txscript_vm_fallbacks"]
    _run_block(workers=4)
    after = REGISTRY.snapshot()["counters"]["txscript_vm_fallbacks"]
    # 4 fallback-heavy txs x 4 VM-routed inputs each
    assert after - before == 16


def test_vm_error_precedence_over_batch_error():
    """A token with both a VM failure and a batch failure must surface the
    VM error exactly like the serial path did (VM ran at collect time,
    so it owned the first-error slot)."""
    rng = random.Random(20)
    sig_script, spk_bad = _p2sh_input(bytes([OP_1]), ok=False)
    tx, entries = _p2pk_tx(21, corrupt=True)
    tx.inputs.append(TransactionInput(TransactionOutpoint(b"\x22" * 32, 1), sig_script, 0, ComputeCommit.sigops(0)))
    entries.append(UtxoEntry(10_000, spk_bad, 5, False))
    for workers in (0, 4):
        checker = BatchScriptChecker(SigCache(), _vm_fallback, fallback_workers=workers)
        checker.collect_tx(7, tx, entries)
        err = checker.dispatch()[7]
        assert isinstance(err, ScriptCheckError)
        # serial parity: the VM failure (input 1) wins over the batch
        # signature failure (input 0)
        assert err.input_index == 1, (workers, err.input_index, str(err))


def test_fallback_without_vm_raises_at_collect():
    tx, entries = _fallback_heavy_tx(30)
    checker = BatchScriptChecker(SigCache(), vm_fallback=None)
    checker.collect_tx(0, tx, entries)
    err = checker.dispatch()[0]
    assert isinstance(err, ScriptCheckError)
    assert "VM fallback not wired" in str(err)
