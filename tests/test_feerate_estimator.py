"""Feerate estimator goldens + frontier sampling determinism.

Golden values freeze the closed-form M/D/1 estimator curve
(mining/src/feerate/mod.rs port): bucket feerates for a known
(total_weight, inclusion_interval) pair, the outlier-removal prefix
search in ``build_feerate_estimator`` (a whale at the frontier top must
be excluded from the estimator weight exactly once), and the
feerate<->time inversions.  The sampling tests pin the weighted
in-place sampler to its seed: same seed, same frontier => the same
template candidate sequence, which is what makes template selection
reproducible across the batched and per-tx admission paths.
"""

import random

import pytest

from kaspa_tpu.mempool.feerate import (
    ALPHA,
    FeerateEstimator,
    FeerateEstimatorArgs,
)
from kaspa_tpu.mempool.frontier import COLLISION_FACTOR, FeerateKey, Frontier


def _estimator() -> FeerateEstimator:
    return FeerateEstimator(
        total_weight=1000.0, inclusion_interval=0.004, target_time_per_block_seconds=1.0
    )


def test_golden_buckets():
    """Frozen bucket curve for c2=1000, c1=0.004, 1s target."""
    est = _estimator()
    buckets = est.calc_estimations(minimum_standard_feerate=1.0).ordered_buckets()
    golden = [
        (1.5895232484149204, 1.0),  # priority: next-block inclusion
        (1.3485658414484367, 1.6349608127301345),  # normal: sub-minute / 0.66 quantile
        (1.1970292315732947, 2.336092236412274),  # mid interpolation point
        (1.0853242347775465, 3.1328265571175917),  # low: sub-hour / 0.25 quantile
    ]
    assert len(buckets) == len(golden)
    for bucket, (feerate, seconds) in zip(buckets, golden):
        assert bucket.feerate == pytest.approx(feerate, rel=1e-12)
        assert bucket.estimated_seconds == pytest.approx(seconds, rel=1e-12)
    # the curve is monotone: paying more never waits longer
    feerates = [b.feerate for b in buckets]
    times = [b.estimated_seconds for b in buckets]
    assert feerates == sorted(feerates, reverse=True)
    assert times == sorted(times)


def test_feerate_time_inversions():
    est = _estimator()
    assert est.feerate_to_time(2.0) == pytest.approx(0.504, rel=1e-12)
    assert est.time_to_feerate(1.0) == pytest.approx(1.5895232484149204, rel=1e-12)
    # round trip through both directions of the curve
    for f in (1.1, 2.0, 7.5):
        assert est.time_to_feerate(est.feerate_to_time(f)) == pytest.approx(f, rel=1e-9)
    # quantile interior point + degenerate interval
    assert est.quantile(1.0, 4.0, 0.5) == pytest.approx(1.3719886811400708, rel=1e-12)
    assert est.quantile(2.5, 2.5, 0.7) == 2.5
    empty = FeerateEstimator(0.0, 0.004, 1.0)
    assert empty.quantile(1.0, 4.0, 0.5) == 1.0


def test_frontier_estimator_removes_whale_outlier():
    """build_feerate_estimator's prefix search must settle on the frontier
    minus the single whale: its weight (500**ALPHA) dominates the flat tail,
    and removing any tail tx after it makes the estimate worse (break)."""
    fr = Frontier(target_time_per_block_seconds=1.0)
    fr.insert(FeerateKey(fee=1_000_000, mass=2000, txid=b"\xff" * 32))
    for i in range(64):
        fr.insert(FeerateKey(fee=1000, mass=2000, txid=bytes([i]) * 32))
    assert fr.tree.total_weight() == pytest.approx(500.0**ALPHA + 64 * 0.5**ALPHA)

    args = FeerateEstimatorArgs(network_blocks_per_second=2, maximum_mass_per_block=100_000)
    est = fr.build_feerate_estimator(args)
    # the whale (and only the whale) is outside the estimator weight
    assert est.total_weight == pytest.approx(64 * 0.5**ALPHA, rel=1e-12)
    # one 2000-mass slot consumed out of the 100k block, avg mass decayed
    # from INITIAL_AVG_MASS over 65 inserts of mass 2000
    assert est.inclusion_interval == pytest.approx(0.010387635752481802, rel=1e-12)


def _filled_frontier(n: int, mass: int = 2000) -> Frontier:
    fr = Frontier(target_time_per_block_seconds=1.0)
    rng = random.Random(0xFEE)
    for i in range(n):
        fee = rng.randrange(1_000, 1_000_000)
        fr.insert(FeerateKey(fee=fee, mass=mass, txid=i.to_bytes(4, "big") * 8))
    return fr


def test_sampling_deterministic_under_fixed_seed():
    """Same frontier + same RNG seed => the identical candidate sequence,
    on the weighted-sampling path (total mass past the collision factor)."""
    max_block_mass = 100_000
    fr = _filled_frontier(400)
    assert fr.total_mass > COLLISION_FACTOR * max_block_mass  # sampling, not greedy
    first = fr.select(random.Random(42), max_block_mass)
    second = fr.select(random.Random(42), max_block_mass)
    assert first == second
    assert len(first) > 0
    assert len({k.txid for k in first}) == len(first)  # no duplicates sampled
    # a different seed draws a different sequence
    other = fr.select(random.Random(43), max_block_mass)
    assert other != first
    # and the same seed on an independently built identical frontier agrees
    again = _filled_frontier(400).select(random.Random(42), max_block_mass)
    assert again == first


def test_small_frontier_selection_is_exact_greedy():
    """Below the collision factor, selection is the full descending-feerate
    walk — deterministic regardless of the RNG."""
    max_block_mass = 100_000
    fr = _filled_frontier(16)
    assert fr.total_mass <= COLLISION_FACTOR * max_block_mass
    sel_a = fr.select(random.Random(1), max_block_mass)
    sel_b = fr.select(random.Random(999), max_block_mass)
    assert sel_a == sel_b
    assert len(sel_a) == 16
    feerates = [k.feerate for k in sel_a]
    assert feerates == sorted(feerates, reverse=True)
