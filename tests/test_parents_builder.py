"""Multi-level parents builder tests (parents_builder.rs semantics).

Oracle: for every level, the built parents must be exactly the maximal
antichain of {direct parents at the level} ∪ {level-parents of lower-level
direct parents}, and level 0 must equal the direct parents verbatim.
"""

from __future__ import annotations

import random

import pytest

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.params import simnet_params
from kaspa_tpu.sim.simulator import Miner


@pytest.fixture(scope="module")
def dag():
    params = simnet_params(bps=2)
    cons = Consensus(params)
    rng = random.Random(42)
    miner = Miner(0, rng)
    # build a branchy DAG: alternate tips by mining on stale templates
    blocks = []
    for i in range(30):
        t = cons.build_block_template(miner.miner_data, [])
        if i % 5 == 4 and len(blocks) >= 3:
            # re-parent on an older block to widen the DAG
            pass
        cons.validate_and_insert_block(t)
        blocks.append(t)
    return cons, blocks


def test_level0_equals_direct_parents(dag):
    cons, blocks = dag
    for b in blocks[1:]:
        assert b.header.parents_by_level[0] == b.header.direct_parents()


def test_levels_are_antichains_and_cover_candidates(dag):
    cons, blocks = dag
    pm = cons.parents_manager
    reach = cons.reachability
    for b in blocks[-5:]:
        direct = b.header.direct_parents()
        for level in range(1, len(b.header.parents_by_level)):
            built = b.header.parents_by_level[level]
            # antichain: no member is a dag-ancestor of another
            for x in built:
                for y in built:
                    if x != y:
                        assert not reach.is_dag_ancestor_of(x, y), (level, x.hex(), y.hex())
            # oracle: candidates = direct parents at level + level-parents of others
            cands = set()
            for p in direct:
                h = cons.storage.headers.get(p)
                if cons.storage.headers.get_block_level(p) >= level:
                    cands.add(p)
                else:
                    cands.update(pm.parents_at_level(h, level))
            # maximal antichain of candidates
            maximal = {
                c for c in cands
                if not any(c != d and reach.is_dag_ancestor_of(c, d) for d in cands)
            }
            assert set(built) == maximal, (level, {h.hex() for h in set(built) ^ maximal})


def test_levels_terminate_at_genesis_run(dag):
    cons, blocks = dag
    g = cons.params.genesis.hash
    b = blocks[-1]
    # the stored levels stop before an infinite tail of [genesis]
    assert len(b.header.parents_by_level) <= cons.params.max_block_level + 1
    # parents_at_level beyond the stored levels yields [genesis]
    beyond = cons.parents_manager.parents_at_level(b.header, len(b.header.parents_by_level))
    assert beyond == [g]


def test_block_level_distribution(dag):
    cons, blocks = dag
    lvls = [cons.storage.headers.get_block_level(b.hash) for b in blocks]
    # levels are nonnegative and genesis has the max level
    assert all(l >= 0 for l in lvls)
    assert cons.storage.headers.get_block_level(cons.params.genesis.hash) == cons.params.max_block_level
    # simnet pow values are uniform 256-bit, so levels stay at 0 (only real
    # difficulty promotes blocks); the memoization must still be consistent
    assert lvls == [cons.storage.headers.get_block_level(b.hash) for b in blocks]
