"""Mempool + MiningManager tests: insert/validate, RBF, orphans, templates.

Reference behavior model: mining/src/mempool/ and manager.rs.  Uses a small
simulated chain to provide mature spendable UTXOs, then drives the mining
round-trip: submit tx -> template -> insert block -> mempool update.
"""

import random

import pytest

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.consensus.model import Transaction, TransactionInput, TransactionOutput
from kaspa_tpu.consensus.model.tx import SUBNETWORK_ID_NATIVE, ComputeCommit
from kaspa_tpu.consensus.processes.transaction_validator import TxRuleError
from kaspa_tpu.crypto import eclib
from kaspa_tpu.mempool import MiningManager
from kaspa_tpu.mempool.mempool import MempoolError
from kaspa_tpu.sim.simulator import Miner, SimConfig, simulate
from kaspa_tpu.txscript import standard


@pytest.fixture(scope="module")
def chain():
    cfg = SimConfig(bps=2, delay=0.5, num_miners=2, num_blocks=26, txs_per_block=0, seed=17)
    res = simulate(cfg)
    from kaspa_tpu.consensus.consensus import Consensus

    c = Consensus(res.params)
    for b in res.blocks:
        c.validate_and_insert_block(b)
    return c, res


def _signed_spend(consensus, miner: Miner, rng, fee=1000, seq=0):
    view = consensus.get_virtual_utxo_view()
    pov = consensus.get_virtual_daa_score()
    # find a mature utxo of this miner
    maturity = consensus.params.coinbase_maturity
    for outpoint, entry in list(consensus.utxo_set.items()):
        if view.get(outpoint) is None:
            continue
        if entry.script_public_key != miner.spk:
            continue
        if entry.is_coinbase and entry.block_daa_score + maturity > pov:
            continue
        tx = Transaction(
            0,
            [TransactionInput(outpoint, b"", seq, ComputeCommit.sigops(1))],
            [TransactionOutput(entry.amount - fee, miner.spk)],
            0,
            SUBNETWORK_ID_NATIVE,
            0,
            b"",
        )
        reused = chash.SigHashReusedValues()
        msg = chash.calc_schnorr_signature_hash(tx, [entry], 0, chash.SIG_HASH_ALL, reused)
        sig = eclib.schnorr_sign(msg, miner.seckey, rng.randbytes(32))
        tx.inputs[0].signature_script = standard.schnorr_signature_script(sig, chash.SIG_HASH_ALL)
        return tx, outpoint, entry
    raise AssertionError("no mature utxo found")


def test_mempool_roundtrip(chain):
    consensus, res = chain
    rng = random.Random(3)
    # reconstruct a miner from the sim (same seed ordering as simulate())
    sim_rng = random.Random(17)
    miners = [Miner(i, sim_rng) for i in range(2)]
    mgr = MiningManager(consensus)

    tx, outpoint, entry = _signed_spend(consensus, miners[0], rng)
    assert mgr.validate_and_insert_transaction(tx) == []
    assert mgr.mempool.has(tx.id())

    # duplicate rejected
    with pytest.raises(MempoolError, match="already"):
        mgr.validate_and_insert_transaction(tx)

    # RBF: same outpoint, higher fee wins; lower fee loses
    tx_low, _, _ = _signed_spend(consensus, miners[0], rng, fee=500)
    if tx_low.inputs[0].previous_outpoint == outpoint:
        with pytest.raises(MempoolError, match="feerate"):
            mgr.validate_and_insert_transaction(tx_low)
    tx_high, _, _ = _signed_spend(consensus, miners[0], rng, fee=5000)
    if tx_high.inputs[0].previous_outpoint == outpoint:
        evicted = mgr.validate_and_insert_transaction(tx_high)
        assert evicted == [tx.id()]

    # template includes the best tx and mines validly
    template = mgr.get_block_template(miners[0].miner_data)
    assert len(template.transactions) >= 2
    status = consensus.validate_and_insert_block(template)
    assert status in ("utxo_valid", "utxo_pending")

    # mempool drained after the block
    mgr.handle_new_block_transactions(template.transactions, consensus.get_virtual_daa_score())
    assert all(not mgr.mempool.has(t.id()) for t in template.transactions[1:])


def test_invalid_signature_rejected(chain):
    consensus, res = chain
    rng = random.Random(5)
    sim_rng = random.Random(17)
    miners = [Miner(i, sim_rng) for i in range(2)]
    mgr = MiningManager(consensus)
    tx, _, _ = _signed_spend(consensus, miners[1], rng)
    sig = bytearray(tx.inputs[0].signature_script)
    sig[9] ^= 1
    tx.inputs[0].signature_script = bytes(sig)
    tx._id_cache = None
    with pytest.raises(TxRuleError):
        mgr.validate_and_insert_transaction(tx)
    assert not mgr.mempool.has(tx.id())


def test_orphan_pool(chain):
    consensus, res = chain
    rng = random.Random(7)
    sim_rng = random.Random(17)
    miners = [Miner(i, sim_rng) for i in range(2)]
    mgr = MiningManager(consensus)
    # a tx spending a nonexistent outpoint goes to the orphan pool
    from kaspa_tpu.consensus.model import TransactionOutpoint

    orphan = Transaction(
        0,
        [TransactionInput(TransactionOutpoint(b"\x99" * 32, 0), b"\x01\x01", 0, ComputeCommit.sigops(1))],
        [TransactionOutput(100, miners[0].spk)],
        0,
        SUBNETWORK_ID_NATIVE,
        0,
        b"",
    )
    mgr.validate_and_insert_transaction(orphan)
    assert orphan.id() in mgr.mempool.orphans
    assert not mgr.mempool.get(orphan.id())


def test_intake_rejects_gas_above_lane_cap(chain):
    """check_transaction_limits.rs:19 RejectGas: a tx whose own gas exceeds
    gas_per_lane can never be mined and must be refused at mempool intake."""
    c, res = chain
    mm = MiningManager(c)
    rng = random.Random(77)
    sim_rng = random.Random(17)
    miners = [Miner(i, sim_rng) for i in range(2)]
    tx, _, entry = _signed_spend(c, miners[0], rng)
    # ride a non-native lane (native txs with gas are already rejected in
    # isolation); the cap check fires before any signature validation
    from kaspa_tpu.consensus.model.tx import subnetwork_from_byte

    tx.subnetwork_id = subnetwork_from_byte(9)
    tx.gas = c.params.gas_per_lane + 1
    with pytest.raises(MempoolError, match="per-lane cap"):
        mm.validate_and_insert_transaction(tx)
    assert len(mm.mempool) == 0
