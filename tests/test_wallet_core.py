"""Wallet core: encrypted storage, tx generator with chaining, UTXO
processor events.

Reference shapes: wallet/core/src/storage/local (encrypted document),
tx/generator (mass-aware aggregation + batch chaining + summary),
utxo/processor.rs (event stream with maturity tracking).
"""

import random

import pytest

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.model import ScriptPublicKey, TransactionOutpoint, UtxoEntry
from kaspa_tpu.consensus.params import simnet_params
from kaspa_tpu.consensus.processes.coinbase import MinerData
from kaspa_tpu.index import UtxoIndex
from kaspa_tpu.wallet.account import Account
from kaspa_tpu.wallet.generator import Generator, GeneratorError, estimate
from kaspa_tpu.wallet.storage import WalletStorage, WalletStorageError, decrypt_payload, encrypt_payload
from kaspa_tpu.wallet.utxo_processor import Balance, UtxoProcessor, WalletEventType


# ----------------------------------------------------------------------
# encrypted storage
# ----------------------------------------------------------------------


def test_storage_roundtrip_and_wrong_password(tmp_path):
    path = str(tmp_path / "wallet.kaspa")
    seed = bytes(range(32))
    ws = WalletStorage.create(path, "hunter2", seed, account_name="main")
    ws2 = WalletStorage.open(path, "hunter2")
    assert ws2.document == ws.document
    assert ws2.seed_for(ws2.accounts()[0]) == seed
    with pytest.raises(WalletStorageError, match="wrong password|corrupted"):
        WalletStorage.open(path, "hunter3")
    with pytest.raises(WalletStorageError, match="already exists"):
        WalletStorage.create(path, "x", seed)


def test_storage_tamper_detection(tmp_path):
    blob = encrypt_payload("pw", b'{"keydata": []}')
    assert decrypt_payload("pw", blob) == b'{"keydata": []}'
    for pos in (5, 10, 40, len(blob) - 1):  # version, salt, ciphertext, tag
        bad = bytearray(blob)
        bad[pos] ^= 0x01
        with pytest.raises(WalletStorageError):
            decrypt_payload("pw", bytes(bad))


def test_storage_account_watermark_restores_addresses(tmp_path):
    path = str(tmp_path / "wallet.kaspa")
    seed = bytes(range(32, 64))
    ws = WalletStorage.create(path, "pw", seed)
    a1 = ws.load_account()
    a1_addrs = a1.addresses()
    # derive one more, persist the watermark
    ws.load_account()  # no-op sanity
    acct = ws.load_account()
    acct.derive_receive_address()
    ws.bump_receive_index(0, "pw")
    reopened = WalletStorage.open(path, "pw").load_account()
    assert reopened.addresses()[: len(a1_addrs)] == a1_addrs
    assert len(reopened.addresses()) == len(a1_addrs) + 1


# ----------------------------------------------------------------------
# generator
# ----------------------------------------------------------------------


def _funded_chain(n_blocks=14):
    params = simnet_params()
    c = Consensus(params)
    index = UtxoIndex(c)
    acct = Account.from_seed(b"\x11" * 32)
    miner = MinerData(acct.receive_keys[0].spk)
    for i in range(n_blocks):
        b = c.build_block_with_parents(list(c.tips), miner)
        b.header.nonce = i + 1
        b.header.invalidate_cache()
        c.validate_and_insert_block(b)
    return params, c, index, acct, miner


def test_generator_single_stage_spend_accepted_by_consensus():
    params, c, index, acct, miner = _funded_chain()
    spendables = acct.spendable_utxos(index, c.get_virtual_daa_score(), params.coinbase_maturity)
    assert spendables
    dest = ScriptPublicKey(0, b"\x20" + b"\x99" * 32 + b"\xac")
    from kaspa_tpu.consensus.mass import MassCalculator

    gen = Generator(
        spendables,
        acct.receive_keys[0].spk,
        [(dest, 10_000_000)],
        mass_calculator=MassCalculator.from_params(params),
    )
    txs = [p.sign() for p in gen.generate()]
    assert len(txs) == 1
    s = gen.summary
    assert s.number_of_generated_transactions == 1
    assert s.final_transaction_amount == 10_000_000
    assert s.aggregated_fees > 0
    # the block pipeline accepts the generated tx
    blk = c.build_block_with_parents(list(c.tips), miner, txs=txs)
    blk.header.nonce = 777
    blk.header.invalidate_cache()
    assert c.validate_and_insert_block(blk) == "utxo_valid"
    assert c.get_virtual_utxo_view().get(TransactionOutpoint(txs[0].id(), 0)) is not None


def test_generator_chains_batches_over_input_limit():
    params, c, index, acct, miner = _funded_chain(18)
    spendables = acct.spendable_utxos(index, c.get_virtual_daa_score(), params.coinbase_maturity)
    assert len(spendables) >= 6
    total = sum(e.amount for _, e, _ in spendables)
    dest = ScriptPublicKey(0, b"\x20" + b"\x99" * 32 + b"\xac")
    from kaspa_tpu.consensus.mass import MassCalculator

    gen = Generator(
        spendables,
        acct.receive_keys[0].spk,
        [(dest, total - 100_000_000)],  # nearly a full sweep: needs all inputs
        mass_calculator=MassCalculator.from_params(params),
    )
    gen.MAX_INPUTS_PER_STAGE = 4  # force chaining
    pendings = list(gen.generate())
    assert len(pendings) >= 2, "expected batch stage(s) + final"
    assert all(not p.is_final for p in pendings[:-1]) and pendings[-1].is_final
    # chained stages spend the prior stage's swept output
    batch_txid = pendings[0].tx.id()
    later_inputs = {inp.previous_outpoint for p in pendings[1:] for inp in p.tx.inputs}
    assert TransactionOutpoint(batch_txid, 0) in later_inputs
    # sign everything and replay the whole chain through consensus in order
    txs = [p.sign() for p in pendings]
    blk = c.build_block_with_parents(list(c.tips), miner, txs=txs[:1])
    blk.header.nonce = 801
    blk.header.invalidate_cache()
    assert c.validate_and_insert_block(blk) == "utxo_valid"
    for j, tx in enumerate(txs[1:], start=1):
        blk = c.build_block_with_parents(list(c.tips), miner, txs=[tx])
        blk.header.nonce = 801 + j
        blk.header.invalidate_cache()
        assert c.validate_and_insert_block(blk) == "utxo_valid"
    assert gen.summary.number_of_generated_transactions == len(txs)


def test_generator_insufficient_funds():
    params, c, index, acct, miner = _funded_chain()
    spendables = acct.spendable_utxos(index, c.get_virtual_daa_score(), params.coinbase_maturity)
    total = sum(e.amount for _, e, _ in spendables)
    dest = ScriptPublicKey(0, b"\x20" + b"\x99" * 32 + b"\xac")
    gen = Generator(spendables, acct.receive_keys[0].spk, [(dest, total * 2)])
    with pytest.raises(GeneratorError, match="insufficient funds"):
        list(gen.generate())


def test_estimate_matches_generation():
    params, c, index, acct, miner = _funded_chain()
    spendables = acct.spendable_utxos(index, c.get_virtual_daa_score(), params.coinbase_maturity)
    dest = ScriptPublicKey(0, b"\x20" + b"\x99" * 32 + b"\xac")
    s = estimate(spendables, acct.receive_keys[0].spk, [(dest, 5_000_000)])
    assert s.number_of_generated_transactions >= 1
    assert s.final_transaction_amount == 5_000_000
    assert s.aggregated_fees > 0 and s.aggregated_mass > 0


# ----------------------------------------------------------------------
# utxo processor events
# ----------------------------------------------------------------------


def test_utxo_processor_maturity_and_balance_events():
    acct = Account.from_seed(b"\x22" * 32)
    spk = acct.receive_keys[0].spk
    up = UtxoProcessor(acct, coinbase_maturity=10)
    events = []
    up.add_listener(events.append)

    op1 = TransactionOutpoint(b"\x01" * 32, 0)
    op2 = TransactionOutpoint(b"\x02" * 32, 0)
    foreign = TransactionOutpoint(b"\x03" * 32, 0)
    up.on_utxos_changed(
        added=[
            (op1, UtxoEntry(500, spk, 100, True)),  # immature coinbase
            (op2, UtxoEntry(300, spk, 0, False)),  # plain mature
            (foreign, UtxoEntry(900, ScriptPublicKey(0, b"\xff"), 0, False)),  # not ours
        ],
        removed=[],
        virtual_daa_score=105,
    )
    assert up.balance() == Balance(mature=300, pending=500)
    kinds = [e.type for e in events]
    assert WalletEventType.PENDING in kinds and WalletEventType.DISCOVERY in kinds
    assert WalletEventType.BALANCE in kinds

    # maturity crossing emits MATURITY + BALANCE
    events.clear()
    up.on_virtual_daa_score_changed(110)
    assert [e.type for e in events] == [WalletEventType.MATURITY, WalletEventType.BALANCE]
    assert up.balance() == Balance(mature=800, pending=0)

    # spend removes
    events.clear()
    up.on_utxos_changed(added=[], removed=[(op2, None)], virtual_daa_score=111)
    assert up.balance() == Balance(mature=500, pending=0)
    assert [e.type for e in events] == [WalletEventType.BALANCE]


def test_multisig_account_round_trip():
    """2-of-3 schnorr multisig (wallet/core multisig variant): fund the
    P2SH address, spend with 2 cosigners through full consensus validation,
    and prove 1 signature is insufficient."""
    import random

    import pytest as _pytest

    from kaspa_tpu.consensus.consensus import Consensus
    from kaspa_tpu.consensus.params import simnet_params
    from kaspa_tpu.index import UtxoIndex
    from kaspa_tpu.sim.simulator import Miner
    from kaspa_tpu.wallet.account import Account, MultisigAccount

    params = simnet_params(bps=2)
    c = Consensus(params)
    index = UtxoIndex(c)
    miner = Miner(0, random.Random(8))

    funder = Account.from_seed(b"\x11" * 32)
    ms = MultisigAccount.from_seeds([b"\x21" * 32, b"\x22" * 32, b"\x23" * 32], required=2)
    ms_addr = ms.addresses()[0]

    def mine(txs=None):
        blk = c.build_block_template(miner.miner_data, txs or [])
        assert c.validate_and_insert_block(blk) in ("utxo_valid", "utxo_pending")
        return blk

    # mature some miner coinbases, then fund the multisig address
    fund_pay = funder.addresses()[0]
    for _ in range(params.coinbase_maturity + 2):
        blk = c.build_block_template(
            __import__("kaspa_tpu.consensus.processes.coinbase", fromlist=["MinerData"]).MinerData(
                funder.receive_keys[0].spk, b""
            ),
            [],
        )
        assert c.validate_and_insert_block(blk) in ("utxo_valid", "utxo_pending")
    daa = c.get_virtual_daa_score()
    fund_tx = funder.build_send(index, ms_addr, 5_000_000_000, 10_000, daa, params.coinbase_maturity)
    mine([fund_tx])
    mine()  # a block's txs are accepted by the NEXT chain block merging it
    assert ms.balance(index) == 5_000_000_000

    # 2-of-3 spend back to the funder validates through consensus
    daa = c.get_virtual_daa_score()
    spend = ms.build_send(index, fund_pay, 1_000_000_000, 10_000, daa, params.coinbase_maturity,
                          signer_indices=[0, 2])
    mine([spend])
    mine()
    assert ms.balance(index) == 5_000_000_000 - 1_000_000_000 - 10_000

    # requesting fewer signers than m is refused at build time ...
    daa = c.get_virtual_daa_score()
    from kaspa_tpu.wallet.account import WalletError

    with _pytest.raises(WalletError):
        ms.build_send(index, fund_pay, 1_000, 1_000, daa, params.coinbase_maturity, signer_indices=[1])
    # ... and an under-signed script (1 sig grafted into a 2-of-3 redeem)
    # fails consensus validation: the block template drops the tx
    under = ms.build_send(index, fund_pay, 1_000_000_000, 10_000, daa, params.coinbase_maturity)
    from kaspa_tpu.txscript.script_builder import ScriptBuilder
    from kaspa_tpu.consensus import hashing as chash2

    for i, inp in enumerate(under.inputs):
        # strip to a single signature: re-parse pushes and keep sig1+redeem
        script = inp.signature_script
        # first push: 65-byte sig blob (0x41 <sig+type>); last push: redeem
        sig1 = script[1:66]
        redeem = ms.receive_keys[0].redeem_script
        b = ScriptBuilder()
        b.add_data(sig1)
        b.add_data(redeem)
        inp.signature_script = b.drain()
    # explicit test-harness txs bypass template filtering; consensus chain
    # verification must disqualify the block carrying the under-signed tx
    blk = c.build_block_template(miner.miner_data, [under])
    assert len(blk.transactions) == 2
    assert c.validate_and_insert_block(blk) == "disqualified" 
