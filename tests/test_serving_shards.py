"""Sharded fanout tier: identity, routing, the unsubscribe-during-fanout
race, per-shard brownout conflation and the overload plane's
max-across-shards lag signal."""

import time
from time import perf_counter_ns

import pytest

from kaspa_tpu.notify.notifier import Notification, Notifier
from kaspa_tpu.serving.broadcaster import _SHARD_QUEUE_WAIT, Subscriber
from kaspa_tpu.serving.check import run_check
from kaspa_tpu.serving.shards import ShardedBroadcaster, shard_of


class _Spk:
    __slots__ = ("script",)

    def __init__(self, script):
        self.script = script


class _Entry:
    __slots__ = ("script_public_key", "amount")

    def __init__(self, script, amount):
        self.script_public_key = _Spk(script)
        self.amount = amount


class ListSink:
    def __init__(self):
        self.items = []

    def put(self, payload, timeout=None):
        self.items.append(payload)


class SlowSink(ListSink):
    """Sink that takes a while per write and stamps each completion."""

    def __init__(self, delay_s=0.02):
        super().__init__()
        self.delay_s = delay_s
        self.done_ns = []

    def put(self, payload, timeout=None):
        time.sleep(self.delay_s)
        self.items.append(payload)
        self.done_ns.append(perf_counter_ns())


def _encode(n):
    return repr(
        (n.event_type, sorted(n.data.get("spk_set") or ()), n.t_accept_ns, n.merged)
    ).encode()


def _diff(scripts, stamp):
    added = [(i, _Entry(s, 1000 + i)) for i, s in enumerate(scripts)]
    return Notification(
        "utxos-changed",
        {"added": added, "removed": [], "spk_set": set(scripts)},
        None,
        t_accept_ns=stamp,
    )


def _mk(name, sink=None, maxlen=256):
    return Subscriber(name, _encode, sink or ListSink(), encoding="t", maxlen=maxlen)


def _settle(bc, subs, timeout=5.0):
    deadline = time.monotonic() + timeout
    last = -1
    while time.monotonic() < deadline:
        total = sum(s.delivered for s in subs)
        if bc.pending() == 0 and not any(s.queue_depth() for s in subs) and total == last:
            return True
        last = total
        time.sleep(0.01)
    return False


def test_identity_small_run():
    """shards=3 vs single fanout: bit-identical per-subscriber streams on
    a short recorded sequence with mid-run churn."""
    report = run_check(shards=3, blocks=12, subs=60, seed=5)
    assert report["serving_identity_ok"], report
    assert report["deliveries_single"] == report["deliveries_sharded"] > 0


def test_partition_is_stable_and_total():
    names = [f"conn-{i}" for i in range(200)]
    assert [shard_of(n, 4) for n in names] == [shard_of(n, 4) for n in names]
    assert {shard_of(n, 4) for n in names} == {0, 1, 2, 3}
    for n in names:
        assert shard_of(n, 1) == 0


def test_scoped_routing_and_wildcard():
    notifier = Notifier()
    bc = ShardedBroadcaster(notifier, shards=2)
    try:
        scoped = _mk("scoped")
        wild = _mk("wild")
        miss = _mk("miss")
        for s in (scoped, wild, miss):
            bc.register(s)
        bc.subscribe(scoped, "utxos-changed", {b"S1"})
        bc.subscribe(wild, "utxos-changed")
        bc.subscribe(miss, "utxos-changed", {b"S9"})
        notifier.notify(_diff([b"S1", b"S2"], 7))
        assert _settle(bc, [scoped, wild, miss])
        assert scoped.sink.items == [_encode(_diff([b"S1"], 7))]
        # wildcard gets the raw notification (spk_set as published)
        assert wild.sink.items == [_encode(_diff([b"S1", b"S2"], 7))]
        assert miss.sink.items == []
    finally:
        bc.close()


def test_unsubscribe_during_fanout_race():
    """After unsubscribe() returns, the subscriber's sink must never see
    another delivery of that event — queued entries are purged and the
    in-flight one is waited out, even with routing snapshots in flight."""
    notifier = Notifier()
    bc = ShardedBroadcaster(notifier, shards=2, shard_maxsize=64)
    try:
        sink = SlowSink(delay_s=0.02)
        victim = _mk("victim", sink=sink)
        bc.register(victim)
        bc.subscribe(victim, "utxos-changed", {b"S1"})
        for i in range(12):
            notifier.notify(_diff([b"S1"], i + 1))
        # let the first slow delivery start
        deadline = time.monotonic() + 5.0
        while not sink.items and time.monotonic() < deadline:
            time.sleep(0.002)
        assert sink.items, "first delivery never started"
        bc.unsubscribe(victim, "utxos-changed")
        t_unsub = perf_counter_ns()
        seen = len(sink.items)
        # anything still routed afterwards must be bounced by the
        # subscriber's active-event set
        notifier_still_live = _diff([b"S1"], 99)
        bc.publish(notifier_still_live)
        time.sleep(0.3)
        assert len(sink.items) == seen, "delivery completed after unsubscribe returned"
        assert all(t <= t_unsub for t in sink.done_ns)
        assert "utxos-changed" not in victim.subscriptions
    finally:
        bc.close()


def test_conflation_engages_per_shard():
    notifier = Notifier()
    bc = ShardedBroadcaster(notifier, shards=2)
    try:
        # names landing on each shard
        names0 = [f"c{i}" for i in range(40) if shard_of(f"c{i}", 2) == 0][:2]
        names1 = [f"c{i}" for i in range(40) if shard_of(f"c{i}", 2) == 1][:2]
        subs = {n: bc.register(_mk(n)) for n in names0 + names1}
        bc.set_conflation(2, shard=0)
        assert all(subs[n].conflate_floor == 2 for n in names0)
        assert all(subs[n].conflate_floor is None for n in names1)
        bc.set_conflation(3)  # all shards
        assert all(s.conflate_floor == 3 for s in subs.values())
        bc.set_conflation(None)
        assert all(s.conflate_floor is None for s in subs.values())
        # the facade floor applies to late registrations too
        bc.set_conflation(5)
        late_name = "late-sub"
        late = bc.register(_mk(late_name))
        assert late.conflate_floor == 5
    finally:
        bc.close()


def test_overload_lag_signal_is_max_across_shards():
    """One wedged shard's queue_wait must drive the fanout_lag_ms signal
    even when the other shards are fast (a global mean would dilute it)."""
    from kaspa_tpu.resilience.overload import default_signals

    notifier = Notifier()
    bc = ShardedBroadcaster(notifier, shards=3)
    try:
        sig = next(
            s for s in default_signals(broadcaster=bc) if s.name == "fanout_lag_ms"
        )
        sig.read()  # anchor the windows
        # shard 1 wedged (500 ms waits), shards 0/2 fast (0.1 ms)
        _SHARD_QUEUE_WAIT.cell("1").observe(500.0)
        for _ in range(50):
            _SHARD_QUEUE_WAIT.cell("0").observe(0.1)
            _SHARD_QUEUE_WAIT.cell("2").observe(0.1)
        value = sig.read()
        assert value == pytest.approx(500.0), value
        # ELEVATED enter threshold (25.0) would be missed by the global
        # mean of the same observations (~5 ms) — the max trips it
        assert value >= sig.enter[0]
    finally:
        bc.close()


def test_collector_reports_per_shard_blocks():
    notifier = Notifier()
    bc = ShardedBroadcaster(notifier, shards=2)
    try:
        sub = bc.register(_mk("m1"))
        bc.subscribe(sub, "utxos-changed", {b"S1"})
        notifier.notify(_diff([b"S1"], 3))
        assert _settle(bc, [sub])
        snap = bc._collect()
        assert snap["fanout"]["shards"] == 2
        assert len(snap["shards"]) == 2
        assert {b["shard"] for b in snap["shards"]} == {0, 1}
        assert snap["delivered"] == 1
        assert snap["subscribers"] == 1
        assert snap["fanout"]["events"] == 1
        assert snap["fanout"]["busy_ns"] > 0
    finally:
        bc.close()


def test_register_rejects_wrong_shard_hint():
    notifier = Notifier()
    bc = ShardedBroadcaster(notifier, shards=4)
    try:
        name = "conn-x"
        wrong = (shard_of(name, 4) + 1) % 4
        sub = Subscriber(name, _encode, ListSink(), encoding="t", shard=wrong)
        with pytest.raises(ValueError):
            bc.register(sub)
        sub.stop()
    finally:
        bc.close()


def test_daemon_fanout_shards_flag(monkeypatch, tmp_path):
    from kaspa_tpu.node.daemon import parse_args

    args = parse_args(["--appdir", str(tmp_path)])
    assert args.fanout_shards == 1
    args = parse_args(["--appdir", str(tmp_path), "--fanout-shards", "4"])
    assert args.fanout_shards == 4
    monkeypatch.setenv("KASPA_TPU_FANOUT_SHARDS", "3")
    args = parse_args(["--appdir", str(tmp_path)])
    assert args.fanout_shards == 3


def test_event_refs_are_shared_across_shards():
    """One upstream wildcard listener per event type, refcounted across
    every shard — the notifier must see start/stop exactly once."""
    notifier = Notifier()
    bc = ShardedBroadcaster(notifier, shards=3)
    try:
        starts, stops = [], []
        orig_start, orig_stop = notifier.start_notify, notifier.stop_notify
        notifier.start_notify = lambda lid, ev, *a, **k: (
            starts.append(ev), orig_start(lid, ev, *a, **k))[-1]
        notifier.stop_notify = lambda lid, ev: (stops.append(ev), orig_stop(lid, ev))[-1]
        subs = [bc.register(_mk(f"r{i}")) for i in range(6)]
        for s in subs:
            bc.subscribe(s, "utxos-changed", {b"S1"})
        assert starts == ["utxos-changed"]
        for s in subs[:-1]:
            bc.unsubscribe(s, "utxos-changed")
        assert stops == []
        bc.unsubscribe(subs[-1], "utxos-changed")
        assert stops == ["utxos-changed"]
    finally:
        bc.close()


def test_tune_gil_switch_interval_is_raise_only(monkeypatch):
    """The serving-tier GIL tuning never shrinks an interval the embedder
    already set, honors the env knob, and 0 disables it entirely."""
    import sys as _sys

    from kaspa_tpu.serving.broadcaster import tune_gil_switch_interval

    prev = _sys.getswitchinterval()
    try:
        _sys.setswitchinterval(0.005)
        monkeypatch.setenv("KASPA_TPU_GIL_SWITCH_MS", "25")
        assert tune_gil_switch_interval() == pytest.approx(0.025)
        # raise-only: a larger ambient interval is kept
        _sys.setswitchinterval(0.1)
        assert tune_gil_switch_interval() == pytest.approx(0.1)
        # 0 (and garbage) disable the tuning
        _sys.setswitchinterval(0.005)
        monkeypatch.setenv("KASPA_TPU_GIL_SWITCH_MS", "0")
        assert tune_gil_switch_interval() == pytest.approx(0.005)
        monkeypatch.setenv("KASPA_TPU_GIL_SWITCH_MS", "bogus")
        assert tune_gil_switch_interval() == pytest.approx(0.005)
    finally:
        _sys.setswitchinterval(prev)
