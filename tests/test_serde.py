"""Round-trip exactness of the canonical binary codec (consensus/serde.py)."""

import random

import pytest

from kaspa_tpu.consensus import serde
from kaspa_tpu.consensus.model import (
    ComputeCommit,
    Covenant,
    Header,
    ScriptPublicKey,
    Transaction,
    TransactionInput,
    TransactionOutpoint,
    TransactionOutput,
    UtxoEntry,
)
from kaspa_tpu.consensus.stores import GhostdagData
from kaspa_tpu.consensus.utxo import UtxoDiff
from kaspa_tpu.crypto.muhash import MuHash


def _rand_hash(rng):
    return rng.randbytes(32)


def _rand_tx(rng, version=0):
    inputs = [
        TransactionInput(
            TransactionOutpoint(_rand_hash(rng), rng.randrange(2**32)),
            rng.randbytes(rng.randrange(0, 120)),
            rng.randrange(2**64),
            ComputeCommit.sigops(rng.randrange(256)) if version == 0 else ComputeCommit.budget(rng.randrange(2**16)),
        )
        for _ in range(rng.randrange(0, 5))
    ]
    outputs = [
        TransactionOutput(
            rng.randrange(2**63),
            ScriptPublicKey(rng.randrange(2**16), rng.randbytes(rng.randrange(0, 40))),
            Covenant(rng.randrange(2**16), _rand_hash(rng)) if rng.random() < 0.3 else None,
        )
        for _ in range(rng.randrange(0, 5))
    ]
    return Transaction(
        version, inputs, outputs, rng.randrange(2**64), rng.randbytes(20),
        rng.randrange(2**32), rng.randbytes(rng.randrange(0, 60)), rng.randrange(2**32),
    )


def _rand_header(rng):
    h = Header(
        version=rng.randrange(2**16),
        parents_by_level=[[_rand_hash(rng) for _ in range(rng.randrange(1, 4))] for _ in range(rng.randrange(1, 4))],
        hash_merkle_root=_rand_hash(rng),
        accepted_id_merkle_root=_rand_hash(rng),
        utxo_commitment=_rand_hash(rng),
        timestamp=rng.randrange(2**48),
        bits=rng.randrange(2**32),
        nonce=rng.randrange(2**64),
        daa_score=rng.randrange(2**48),
        blue_work=rng.randrange(2**192),
        blue_score=rng.randrange(2**48),
        pruning_point=_rand_hash(rng),
    )
    if rng.random() < 0.5:
        h._hash_cache = _rand_hash(rng)
    return h


def test_tx_roundtrip():
    rng = random.Random(1)
    for i in range(50):
        tx = _rand_tx(rng, version=i % 2)
        assert serde.decode_tx(serde.encode_tx(tx)) == tx
    txs = [_rand_tx(rng) for _ in range(7)]
    assert serde.decode_txs(serde.encode_txs(txs)) == txs


def test_header_roundtrip():
    rng = random.Random(2)
    for _ in range(30):
        h = _rand_header(rng)
        h2 = serde.decode_header(serde.encode_header(h))
        assert h2 == h
        assert h2._hash_cache == h._hash_cache


def test_ghostdag_roundtrip():
    rng = random.Random(3)
    for _ in range(20):
        gd = GhostdagData(
            rng.randrange(2**48),
            rng.randrange(2**192),
            _rand_hash(rng),
            [_rand_hash(rng) for _ in range(rng.randrange(1, 5))],
            [_rand_hash(rng) for _ in range(rng.randrange(0, 3))],
            {_rand_hash(rng): rng.randrange(40) for _ in range(rng.randrange(0, 4))},
        )
        assert serde.decode_ghostdag(serde.encode_ghostdag(gd)) == gd


def test_utxo_entry_and_diff_roundtrip():
    rng = random.Random(4)
    for _ in range(20):
        e = UtxoEntry(
            rng.randrange(2**63),
            ScriptPublicKey(0, rng.randbytes(34)),
            rng.randrange(2**48),
            rng.random() < 0.5,
            _rand_hash(rng) if rng.random() < 0.3 else None,
        )
        assert serde.decode_utxo_entry(serde.encode_utxo_entry(e)) == e
    diff = UtxoDiff()
    for _ in range(9):
        op = TransactionOutpoint(_rand_hash(rng), rng.randrange(10))
        e = UtxoEntry(5, ScriptPublicKey(0, b"\x51"), 3, False, None)
        (diff.add if rng.random() < 0.5 else diff.remove)[op] = e
    d2 = serde.decode_utxo_diff(serde.encode_utxo_diff(diff))
    assert d2.add == diff.add and d2.remove == diff.remove


def test_outpoint_muhash_roundtrip():
    rng = random.Random(5)
    op = TransactionOutpoint(_rand_hash(rng), 7)
    assert serde.decode_outpoint(serde.encode_outpoint(op)) == op
    mh = MuHash()
    mh.add_element(b"x")
    mh.remove_element(b"y")
    mh2 = serde.decode_muhash(serde.encode_muhash(mh))
    assert mh2.numerator == mh.numerator and mh2.denominator == mh.denominator
    assert mh2.finalize() == mh.finalize()


def test_truncation_raises_eof():
    rng = random.Random(6)
    tx = _rand_tx(rng)
    data = serde.encode_tx(tx)
    for cut in range(len(data)):
        with pytest.raises(EOFError):
            serde.decode_tx(data[:cut])
    h = _rand_header(rng)
    hdata = serde.encode_header(h)
    for cut in range(0, len(hdata), 7):
        with pytest.raises(EOFError):
            serde.decode_header(hdata[:cut])


def test_bad_subnetwork_length_rejected_at_encode():
    rng = random.Random(7)
    tx = _rand_tx(rng)
    tx.subnetwork_id = b"\x00" * 19
    with pytest.raises(AssertionError):
        serde.encode_tx(tx)


def test_varint_bounds():
    import io

    w = io.BytesIO()
    serde.write_varint(w, 2**200)
    assert serde.read_varint(io.BytesIO(w.getvalue())) == 2**200
    with pytest.raises(ValueError):
        serde.write_varint(io.BytesIO(), -1)
