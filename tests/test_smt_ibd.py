"""KIP-21 lane-state sync over proof IBD.

A post-Toccata pruning point commits to an SMT over active lanes; a fresh
node bootstrapping from a pruning proof cannot recompute that state from
pruned history, so the donor serves it and the receiver verifies it against
the proven PP header's sequencing commitment before installing it
(flows/src/ibd/flow.rs:145-150 sync_new_smt_state,
kaspa-seq-commit verify.rs verify_smt_metadata).
"""

from __future__ import annotations

import random

import pytest

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.consensus import seq_commit as sc
from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.model.tx import (
    SUBNETWORK_ID_NATIVE,
    ComputeCommit,
    Transaction,
    TransactionInput,
    TransactionOutput,
)
from kaspa_tpu.consensus.params import GenesisBlock, Params
from kaspa_tpu.crypto import eclib
from kaspa_tpu.p2p.node import Node, ProtocolError, connect
from kaspa_tpu.sim.simulator import Miner
from kaspa_tpu.txscript import standard


def _toccata_prune_params() -> Params:
    genesis = GenesisBlock(hash=b"\x01" + b"\x00" * 31, bits=0x207FFFFF, timestamp=0)
    return Params.from_bps(
        "simnet-smtibd",
        2,
        genesis,
        skip_proof_of_work=True,
        coinbase_maturity=8,
        merge_depth=15,
        finality_depth=30,
        pruning_depth=60,
        pruning_proof_m=10,
        difficulty_window_size=15,
        min_difficulty_window_size=5,
        difficulty_sample_rate=2,
        past_median_time_window_size=10,
        past_median_time_sample_rate=2,
        toccata_activation=0,
    )


def _signed_spend(consensus, miner, rng, fee=100_000):
    view = consensus.get_virtual_utxo_view()
    pov = consensus.get_virtual_daa_score()
    maturity = consensus.params.coinbase_maturity
    for outpoint, entry in sorted(
        consensus.utxo_set.items(), key=lambda kv: (kv[0].transaction_id, kv[0].index)
    ):
        if view.get(outpoint) is None or entry.script_public_key != miner.spk:
            continue
        if entry.is_coinbase and entry.block_daa_score + maturity > pov:
            continue
        tx = Transaction(
            0,
            [TransactionInput(outpoint, b"", 0, ComputeCommit.sigops(1))],
            [TransactionOutput(entry.amount - fee, miner.spk)],
            0,
            SUBNETWORK_ID_NATIVE,
            0,
            b"",
        )
        reused = chash.SigHashReusedValues()
        msg = chash.calc_schnorr_signature_hash(tx, [entry], 0, chash.SIG_HASH_ALL, reused)
        sig = eclib.schnorr_sign(msg, miner.seckey, rng.randbytes(32))
        tx.inputs[0].signature_script = standard.schnorr_signature_script(sig, chash.SIG_HASH_ALL)
        return tx
    return None


@pytest.fixture(scope="module")
def toccata_donor():
    """A toccata-active donor whose pruning point moved past genesis, with
    periodic native-lane touches so the PP lane state is non-trivial."""
    params = _toccata_prune_params()
    donor = Node(Consensus(params), "donor")
    miner = Miner(0, random.Random(31))
    rng = random.Random(7)
    for i in range(160):
        txs = []
        if i % 8 == 5:
            tx = _signed_spend(donor.consensus, miner, rng)
            if tx is not None:
                txs = [tx]
        t = donor.consensus.build_block_template(miner.miner_data, txs)
        donor.submit_block(t)
    assert donor.consensus.pruning_processor.pruning_point != params.genesis.hash
    return params, donor


def test_donor_export_roundtrips_verification(toccata_donor):
    """The donor's exported PP lane state passes the receiver-side
    verification against the PP header, and the PP build metadata matches."""
    from kaspa_tpu.consensus.smt_processor import verify_lane_state

    params, donor = toccata_donor
    cons = donor.consensus
    pp = cons.pruning_processor.pruning_point
    state = cons.export_pp_lane_state()
    assert state is not None
    meta, lanes, segment = state
    pp_header = cons.storage.headers.get(pp)
    verify_lane_state(pp_header, meta, lanes)  # must not raise
    build = cons.lane_tracker.builds.try_get(pp)
    assert meta["lanes_root"] == build.lanes_root
    # segment is a hash-bound header chain: shortcut .. pp
    assert segment[-1].hash == pp and segment[0].hash == build.shortcut_block
    for a, b in zip(segment, segment[1:]):
        assert a.hash in b.direct_parents()
    # the coinbase lane is touched by every chain block: always present
    assert any(lk == sc.COINBASE_LANE_KEY for lk, _, _ in lanes)


def test_proof_ibd_transfers_lane_state(toccata_donor):
    """End-to-end: a fresh node proof-bootstraps from a post-Toccata donor,
    its PP lane root equals the donor's recorded one, the full post-PP chain
    re-verifies (seq commits recomputed over the imported state), and new
    post-bootstrap tx-bearing blocks flow both ways."""
    params, donor = toccata_donor
    joiner = Node(Consensus(params), "joiner")
    original = joiner.consensus
    pj, pd = connect(joiner, donor)
    joiner.ibd_from(pj)
    assert joiner.consensus is not original  # staging swapped in

    pp = donor.consensus.pruning_processor.pruning_point
    assert joiner.consensus.pruning_processor.pruning_point == pp
    jb = joiner.consensus.lane_tracker.builds.try_get(pp)
    db = donor.consensus.lane_tracker.builds.try_get(pp)
    assert jb is not None and jb.lanes_root == db.lanes_root
    # materialized state converged with the donor's at the shared position
    assert joiner.consensus.sink() == donor.consensus.sink()
    assert joiner.consensus.lane_tracker.tree.root() == donor.consensus.lane_tracker.tree.root()
    assert joiner.consensus.lane_tracker.lane_tips == donor.consensus.lane_tracker.lane_tips

    # post-bootstrap blocks with lane touches validate on both sides
    miner = Miner(1, random.Random(5))
    rng = random.Random(23)
    dminer = Miner(0, random.Random(31))
    for i in range(6):
        tx = _signed_spend(donor.consensus, dminer, rng)
        t = donor.consensus.build_block_template(dminer.miner_data, [tx] if tx else [])
        donor.submit_block(t)
        assert joiner.consensus.sink() == donor.consensus.sink()
    t = joiner.consensus.build_block_template(miner.miner_data, [])
    joiner.submit_block(t)
    assert donor.consensus.sink() == joiner.consensus.sink()


def test_tampered_lane_state_rejected(toccata_donor):
    """A peer serving a lane set that does not hash to the committed root is
    detected and the staging bootstrap is cancelled."""
    params, donor = toccata_donor
    cons = donor.consensus
    state = cons.export_pp_lane_state()
    meta, lanes, segment = state
    # tamper one lane tip
    bad_lanes = list(lanes)
    lk, tip, bs = bad_lanes[0]
    bad_lanes[0] = (lk, bytes(32), bs)
    # prime the donor's serving snapshot with the tampered state
    pp = cons.pruning_processor.pruning_point
    donor._pp_smt_snapshot = (pp, (meta, bad_lanes, segment))
    try:
        joiner = Node(Consensus(params), "joiner2")
        pj, pd = connect(joiner, donor)
        with pytest.raises(ProtocolError, match="SMT state"):
            joiner.ibd_from(pj)
    finally:
        donor._pp_smt_snapshot = None  # restore clean serving


def test_bootstrap_lane_state_survives_restart(toccata_donor, tmp_path):
    """A proof-bootstrapped node restarted from disk resumes the imported
    lane state and anchors, and keeps accepting post-Toccata chain blocks."""
    from kaspa_tpu.storage.kv import KvStore

    params, donor = toccata_donor
    # proof IBD populates a staging consensus; persistence rides the staging
    # DB exactly as the daemon rotates it (node/daemon.py _staging_factory)
    path = str(tmp_path / "joiner-staging.db")
    joiner = Node(Consensus(params), "joiner3")
    joiner.cmgr._factory = lambda: Consensus(params, KvStore(path))
    pj, pd = connect(joiner, donor)
    joiner.ibd_from(pj)
    root = joiner.consensus.lane_tracker.tree.root()
    tips = dict(joiner.consensus.lane_tracker.lane_tips)
    chain_base = joiner.consensus.selected_chain[0]
    sink = joiner.consensus.sink()
    joiner.consensus.storage.flush()
    joiner.consensus.storage.db.close()
    joiner.consensus.storage.db = None

    db2 = KvStore(path)
    c2 = Consensus(params, db2)
    assert c2.sink() == sink
    assert c2.lane_tracker.tree.root() == root
    assert c2.lane_tracker.lane_tips == tips
    # the below-PP anchor coverage (incl. headers) survived the restart:
    # the rebuilt chain index reaches at least as deep as the imported
    # segment base (ghostdag-dense test networks rebuild even deeper)
    assert c2.selected_chain[0][0] <= chain_base[0]
    assert chain_base in c2.selected_chain
    assert c2.storage.headers.has(chain_base[1])
    # still validates new donor blocks after restart
    miner = Miner(0, random.Random(31))
    t = donor.consensus.build_block_template(miner.miner_data, [])
    donor.submit_block(t)
    assert c2.validate_and_insert_block(t) == "utxo_valid"
    db2.close()


def test_bootstrap_from_pre_toccata_pp_crossing_activation():
    """Bootstrap from a PRE-Toccata pruning point on a network whose
    activation falls between the PP and the tips: no lane state is
    transferred (there is none), and post-activation chain blocks resolve
    their inactivity shortcut to the pre-Toccata chain base, folding to
    ZERO exactly like the reference's backward walk
    (processor.rs:890-905) — so the bootstrapped node stays in consensus."""
    params = _toccata_prune_params()
    params.toccata_activation = 130
    donor = Node(Consensus(params), "donor-x")
    miner = Miner(0, random.Random(31))
    rng = random.Random(7)
    for i in range(160):
        txs = []
        if i % 8 == 5:
            tx = _signed_spend(donor.consensus, miner, rng)
            if tx is not None:
                txs = [tx]
        donor.submit_block(donor.consensus.build_block_template(miner.miner_data, txs))
    pp = donor.consensus.pruning_processor.pruning_point
    assert pp != params.genesis.hash
    pp_hdr = donor.consensus.storage.headers.get(pp)
    assert not params.toccata_active(pp_hdr.daa_score)  # PP is pre-fork
    tip_hdr = donor.consensus.storage.headers.get(donor.consensus.sink())
    assert params.toccata_active(tip_hdr.daa_score)  # tips are post-fork

    joiner = Node(Consensus(params), "joiner-x")
    original = joiner.consensus
    pj, pd = connect(joiner, donor)
    joiner.ibd_from(pj)
    assert joiner.consensus is not original
    assert joiner.consensus.sink() == donor.consensus.sink()

    # both directions keep accepting post-activation blocks
    for _ in range(4):
        tx = _signed_spend(donor.consensus, miner, rng)
        donor.submit_block(donor.consensus.build_block_template(miner.miner_data, [tx] if tx else []))
        assert joiner.consensus.sink() == donor.consensus.sink()
    m2 = Miner(1, random.Random(5))
    joiner.submit_block(joiner.consensus.build_block_template(m2.miner_data, []))
    assert donor.consensus.sink() == joiner.consensus.sink()


def test_smt_snapshot_bounded_by_ttl_and_anchor(toccata_donor):
    """The serve-side SMT snapshot is invalidated by prune_caches: after the
    idle TTL while its anchor is live, after the shorter stale grace once
    the local pruning point moved past it — and a chunk request re-arms the
    clock (an active receiver keeps its snapshot alive)."""
    from kaspa_tpu.p2p.node import (
        MSG_REQUEST_PP_SMT,
        SMT_SNAPSHOT_STALE_GRACE_SECONDS,
        SMT_SNAPSHOT_TTL_SECONDS,
    )

    params, donor = toccata_donor
    pp = donor.consensus.pruning_processor.pruning_point
    state = donor.consensus.export_pp_lane_state()
    t0 = 1000.0

    # live anchor: survives until idle past the TTL
    donor._pp_smt_snapshot = (pp, state, t0)
    donor.prune_caches(t0 + SMT_SNAPSHOT_TTL_SECONDS - 1)
    assert donor._pp_smt_snapshot is not None
    donor.prune_caches(t0 + SMT_SNAPSHOT_TTL_SECONDS + 1)
    assert donor._pp_smt_snapshot is None

    # stale anchor (pruning point moved on): only the shorter grace
    donor._pp_smt_snapshot = (b"\x99" * 32, state, t0)
    donor.prune_caches(t0 + SMT_SNAPSHOT_STALE_GRACE_SECONDS - 1)
    assert donor._pp_smt_snapshot is not None
    donor.prune_caches(t0 + SMT_SNAPSHOT_STALE_GRACE_SECONDS + 1)
    assert donor._pp_smt_snapshot is None

    # a stale UTXO snapshot drops as soon as the anchor moves
    donor._pp_utxo_snapshot = (b"\x98" * 32, [])
    donor.prune_caches(t0)
    assert donor._pp_utxo_snapshot is None

    # serving a chunk request (re)creates the snapshot with a fresh clock
    joiner = Node(Consensus(params), "joiner-prune")
    pj, _pd = connect(joiner, donor)
    pj.send(MSG_REQUEST_PP_SMT, {"pp": pp, "offset": 0})
    snap = donor._pp_smt_snapshot
    assert snap is not None and snap[0] == pp and len(snap) == 3
    first_ref = snap[2]
    pj.send(MSG_REQUEST_PP_SMT, {"pp": pp, "offset": 1})
    assert donor._pp_smt_snapshot[2] >= first_ref  # last-use refreshed
    donor._pp_smt_snapshot = None  # restore clean serving for other tests
