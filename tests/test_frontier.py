"""Frontier search tree, weighted sampling, and fee estimator tests.

Mirrors the reference's test strategy (frontier.rs tests: tree vs brute
force, sampling distribution, estimator bucket monotonicity —
mining/src/feerate/mod.rs tests).
"""

from __future__ import annotations

import random

import pytest

from kaspa_tpu.mempool.feerate import FeerateEstimator, FeerateEstimatorArgs
from kaspa_tpu.mempool.frontier import Frontier, FeerateKey, SearchTree


def _key(i: int, fee: int, mass: int) -> FeerateKey:
    return FeerateKey(fee, mass, i.to_bytes(8, "big"))


def test_search_tree_vs_bruteforce():
    rng = random.Random(1)
    tree = SearchTree()
    keys: dict[bytes, FeerateKey] = {}
    for i in range(500):
        k = _key(i, rng.randrange(1000, 5_000_000), rng.randrange(1000, 100_000))
        assert tree.insert(k)
        keys[k.txid] = k
    # random removals
    for txid in rng.sample(sorted(keys), 200):
        assert tree.remove(keys.pop(txid))
    assert len(tree) == len(keys)
    ordered = sorted(keys.values(), key=lambda k: k.sort_key())
    assert [k.txid for k in tree.ascending()] == [k.txid for k in ordered]
    assert [k.txid for k in tree.descending()] == [k.txid for k in reversed(ordered)]
    total = sum(k.weight for k in keys.values())
    assert tree.total_weight() == pytest.approx(total, rel=1e-9)
    # prefix weights
    for k in rng.sample(ordered, 25):
        brute = sum(q.weight for q in ordered if q.sort_key() <= k.sort_key())
        assert tree.prefix_weight(k) == pytest.approx(brute, rel=1e-9)
    # weighted search: cumulative ascending-order weight query lands on key
    acc = 0.0
    for k in ordered[:50]:
        assert tree.search(acc + k.weight * 0.5).txid == k.txid
        acc += k.weight


def test_weighted_sampling_prefers_high_feerate():
    rng = random.Random(7)
    fr = Frontier()
    # congested frontier: total mass >> 4x block mass
    for i in range(4000):
        fee = 2000 * (1 + (i % 10))  # feerates 1..10 per mass unit
        fr.insert(_key(i, fee * 1000, 2000))
    assert fr.total_mass == 4000 * 2000
    counts = [0] * 11
    for trial in range(50):
        sample = fr.sample_inplace(rng, max_block_mass=50_000)
        for k in sample:
            counts[k.fee // 2_000_000] += 1
    # weight ∝ feerate^3: feerate-10 txs should be sampled far more than feerate-1
    assert counts[10] > 20 * max(counts[1], 1)
    # sampled mass approximately the 1.2x target
    assert 40_000 <= sum(k.mass for k in sample) <= 80_000


def test_sampling_converges_on_biased_weights():
    """A single huge-weight tx must not stall sampling (top-narrowing)."""
    rng = random.Random(3)
    fr = Frontier()
    fr.insert(_key(0, 10**12, 2000))  # enormous feerate outlier
    for i in range(1, 2000):
        fr.insert(_key(i, 2000, 2000))
    sample = fr.sample_inplace(rng, max_block_mass=500_000)
    ids = {k.txid for k in sample}
    assert _key(0, 10**12, 2000).txid in ids
    assert len(ids) > 100  # narrowing let it escape the outlier


def test_small_frontier_greedy_descending():
    rng = random.Random(5)
    fr = Frontier()
    for i in range(10):
        fr.insert(_key(i, (i + 1) * 1000, 1000))
    sel = fr.select(rng, max_block_mass=500_000)
    rates = [k.feerate for k in sel]
    assert rates == sorted(rates, reverse=True)
    assert len(sel) == 10


def test_estimator_bucket_monotonicity():
    for total_weight, interval in [(1002283.659, 0.004), (0.00659, 0.004), (0.0, 0.0), (0.0, 0.1), (0.1, 0.0)]:
        est = FeerateEstimator(total_weight, interval, 1.0)
        for min_feerate in (0.755, 1.0, 3.0):
            b = est.calc_estimations(min_feerate).ordered_buckets()
            assert b[-1].feerate >= min_feerate
            for hi, lo in zip(b, b[1:]):
                assert hi.feerate >= lo.feerate
                assert hi.estimated_seconds <= lo.estimated_seconds


def test_frontier_estimator_outlier_removal():
    fr = Frontier()
    for i in range(500):
        fr.insert(_key(i, 2000, 2000))  # constant feerate 1.0
    fr.insert(_key(999, 10**13, 2000))  # absurd outlier
    args = FeerateEstimatorArgs(network_blocks_per_second=1, maximum_mass_per_block=500_000)
    est = fr.build_feerate_estimator(args)
    # outlier must be excluded from weight, else feerate-1 time estimate explodes
    t = est.feerate_to_time(1.0)
    assert t < 60.0, t
    ests = est.calc_estimations(minimum_standard_feerate=0.01)
    assert ests.priority_bucket.feerate < 100.0


def test_mempool_frontier_integration():
    from kaspa_tpu.consensus.model import (
        Transaction, TransactionInput, TransactionOutpoint, TransactionOutput, ScriptPublicKey,
    )
    from kaspa_tpu.mempool.mempool import Mempool, MempoolTx

    def mk_tx(seed: int, prev: bytes):
        spk = ScriptPublicKey(0, b"\x20" + bytes(32) + b"\xac")
        return Transaction(
            version=0,
            inputs=[TransactionInput(TransactionOutpoint(prev, 0), b"", 0, 1)],
            outputs=[TransactionOutput(1000, spk)],
            lock_time=0,
            subnetwork_id=bytes(20),
            gas=0,
            payload=b"",
        )

    mp = Mempool()
    parent = mk_tx(1, b"\x01" * 32)
    pid = parent.id()
    mp.insert(MempoolTx(parent, fee=5000, mass=2000, added_daa_score=0))
    child = mk_tx(2, pid)
    mp.insert(MempoolTx(child, fee=9000, mass=2000, added_daa_score=0))
    # child chains on in-pool parent: not in frontier
    assert len(mp.frontier) == 1
    sel = mp.select_transactions()
    assert [e.tx.id() for e in sel] == [pid]
    # parent accepted -> child becomes ready
    mp.handle_accepted_transactions([pid], daa_score=1)
    assert len(mp.frontier) == 1
    assert [e.tx.id() for e in mp.select_transactions()] == [child.id()]
    # child expired -> frontier drains
    mp.expire(current_daa_score=10**9)
    assert len(mp.frontier) == 0 and len(mp.pool) == 0


# ---------------------------------------------------------------------------
# KIP-21 lane-aware selection (frontier.rs:60-61,166-185, selectors.rs:28-66)
# ---------------------------------------------------------------------------

from kaspa_tpu.mempool.frontier import LaneSelectionState


def _lane_key(i: int, fee: int, mass: int, lane: int, gas: int = 0) -> FeerateKey:
    return FeerateKey(fee, mass, i.to_bytes(8, "big"), lane=bytes([lane]) + b"\x00" * 19, gas=gas)


def test_lane_selection_state_caps():
    s = LaneSelectionState(lanes_per_block=2, gas_per_lane=100)
    a, b, c = (bytes([i]) + b"\x00" * 19 for i in (3, 4, 5))
    assert s.try_select(a, 60)
    assert s.try_select(a, 40)           # fills lane a's gas exactly
    assert not s.try_select(a, 1)        # gas cap
    assert s.try_select(b, 101) is False  # single tx over cap never enters
    assert s.try_select(b, 0)
    assert not s.try_select(c, 0)        # LPB cap: third lane refused


def test_sample_inplace_freezes_lane_set():
    """Once the weighted sample occupies LPB lanes, spill attempts freeze the
    lane set and the remainder comes from those lanes only (best-first)."""
    rng = random.Random(11)
    fr = Frontier()
    n_lanes, per_lane = 40, 120
    for lane in range(n_lanes):
        for j in range(per_lane):
            i = lane * per_lane + j
            fr.insert(_lane_key(i, fee=2000 * (1 + (i % 7)), mass=2000, lane=3 + lane))
    assert fr.total_mass > 4 * 50_000  # congested: sampling path
    lpb = 5
    sample = fr.sample_inplace(rng, max_block_mass=50_000, lanes_per_block=lpb)
    lanes_used = {k.lane for k in sample}
    assert 0 < len(lanes_used) <= lpb
    assert sum(k.mass for k in sample) >= 50_000  # freeze still fills the block


def test_mempool_select_respects_lane_limits():
    """End-to-end: select_transactions never exceeds the lane count or
    per-lane gas caps even when the frontier spans many lanes."""
    from types import SimpleNamespace

    from kaspa_tpu.mempool.mempool import Mempool, MempoolTx
    from kaspa_tpu.consensus.model import Transaction, TransactionInput, TransactionOutput
    from kaspa_tpu.consensus.model.tx import (
        ComputeCommit,
        ScriptPublicKey,
        TransactionOutpoint,
        subnetwork_from_byte,
    )

    mp = Mempool()
    rng = random.Random(3)
    for i in range(200):
        lane = subnetwork_from_byte(3 + i % 20)  # 20 distinct lanes
        tx = Transaction(
            1,
            [TransactionInput(TransactionOutpoint(i.to_bytes(32, "big"), 0), b"", 0, ComputeCommit.budget(0))],
            [TransactionOutput(1, ScriptPublicKey(0, b"\x51"))],
            0,
            lane,
            40,  # per-tx gas
            b"",
        )
        mp.insert(MempoolTx(tx, fee=rng.randrange(1000, 100_000), mass=2000, added_daa_score=0))
    lane_limits = SimpleNamespace(lanes_per_block=4, gas_per_lane=100)
    mass_limits = SimpleNamespace(compute=500_000, transient=500_000, storage=500_000)
    chosen = mp.select_transactions(mass_limits=mass_limits, lane_limits=lane_limits)
    assert chosen
    per_lane_gas: dict[bytes, int] = {}
    for e in chosen:
        per_lane_gas[e.tx.subnetwork_id] = per_lane_gas.get(e.tx.subnetwork_id, 0) + e.tx.gas
    assert len(per_lane_gas) <= 4
    assert all(g <= 100 for g in per_lane_gas.values())  # => ≤2 txs/lane at gas 40
