"""Hostile-load sustain run: determinism + convergence acceptance.

Slow lane (three full replays of a hostile workload); the per-round
fast-path evidence for the same properties is the roundcheck ``chaos``
section, which shells out to ``python -m kaspa_tpu.sim --hostile``.
"""

from __future__ import annotations

import json

import pytest

from kaspa_tpu.resilience import breaker as breaker_mod
from kaspa_tpu.resilience.faults import FAULTS
from kaspa_tpu.resilience.sustain import build_workload, default_schedule, run_sustain
from kaspa_tpu.sim.simulator import SimConfig

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def workload():
    cfg = SimConfig(num_blocks=24, txs_per_block=4, seed=7, hostile=True)
    return cfg, build_workload(cfg)


@pytest.fixture(autouse=True)
def _clean():
    FAULTS.clear()
    breaker_mod.device_breaker().reset()
    yield
    FAULTS.clear()
    breaker_mod.device_breaker().reset()


def test_sustain_converges_and_is_deterministic(tmp_path, workload):
    cfg, wl = workload
    out1 = tmp_path / "S1.json"
    out2 = tmp_path / "S2.json"
    r1 = run_sustain(cfg, seed=7, workload=wl, out=str(out1))
    r2 = run_sustain(cfg, seed=7, workload=wl, out=str(out2))

    # the acceptance bit: post-recovery end state == fault-free replay
    assert r1["deterministic"]["matches_fault_free"] is True
    # byte-identical deterministic sections across two runs
    assert json.dumps(r1["deterministic"], sort_keys=True) == json.dumps(r2["deterministic"], sort_keys=True)
    # both SUSTAIN.json artifacts carry identical deterministic sections too
    d1 = json.loads(out1.read_text())["deterministic"]
    d2 = json.loads(out2.read_text())["deterministic"]
    assert d1 == d2

    # the stock schedule demonstrably exercised the breaker and both lanes
    assert r1["breaker"]["trips"] >= 1 and r1["breaker"]["recoveries"] >= 1
    assert r1["metrics"]["secp_degraded_dispatches"] >= 1
    assert r1["metrics"]["txscript_vm_fault_retries"] >= 1
    assert r1["deterministic"]["events"], "no faults fired"
    # non-deterministic observability sections live under run_meta.wall,
    # so artifact diffing over the stable view stays churn-free
    assert "lock_traces" in r1["run_meta"]["wall"]
    assert r1["metrics"]["replay_seconds"] > 0
    from kaspa_tpu.resilience.sustain import stable_view

    assert "run_meta" not in stable_view(r1)


def test_hostile_workload_exercises_vm_fallback_scripts(workload):
    """The hostile script mix must actually put multisig/P2SH spends on the
    DAG — otherwise the sustain run isn't stressing the fallback lane."""
    cfg, wl = workload
    kinds = set()
    for block in wl["main"].blocks:
        for tx in block.transactions[1:]:
            for out in tx.outputs:
                kinds.add(bytes(out.script_public_key.script[:1]))
    # multisig redeem scripts start OP_2 (0x52) / P2SH starts OP_BLAKE2B (0xaa)
    assert len(kinds) > 1, "hostile workload produced a single script kind"


def test_empty_schedule_matches_and_fires_nothing(workload):
    cfg, wl = workload
    r = run_sustain(cfg, schedule={}, seed=7, workload=wl)
    assert r["deterministic"]["events"] == []
    assert r["deterministic"]["matches_fault_free"] is True
    assert r["breaker"]["trips"] == 0


def test_default_schedule_shape():
    sched = default_schedule()
    assert "device.verify" in sched and "vm.fallback.exec" in sched
