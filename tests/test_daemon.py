"""Daemon integration test: full node over a real TCP JSON-RPC socket.

Reference strategy: testing/integration/src/common/daemon.rs — spawn full
service stacks in-process on OS-assigned localhost ports, connect real RPC
clients, and drive mining + queries end to end.
"""

import random

import pytest

from kaspa_tpu.node.daemon import Daemon, parse_args, rpc_call
from kaspa_tpu.sim.simulator import Miner


@pytest.fixture()
def daemon(tmp_path):
    args = parse_args(["--appdir", str(tmp_path), "--rpclisten", "127.0.0.1:0", "--bps", "2"])
    d = Daemon(args)
    addr = d.start()
    yield d, addr
    d.stop()


def test_daemon_rpc_roundtrip(daemon):
    d, addr = daemon
    info = rpc_call(addr, "getServerInfo")
    assert info["server_version"].startswith("kaspa-tpu")
    assert rpc_call(addr, "getBlockDagInfo")["block_count"] == 0

    # mine via the RPC template flow
    rng = random.Random(2)
    miner = Miner(0, rng)
    from kaspa_tpu.crypto.addresses import extract_script_pub_key_address

    addr_str = extract_script_pub_key_address(miner.spk, "kaspasim").to_string()
    for _ in range(5):
        t = rpc_call(addr, "getBlockTemplate", {"payAddress": addr_str})
        res = rpc_call(addr, "submitBlockByTemplateHash", {"hash": t["block_hash"]})
        assert res["status"] in ("utxo_valid", "utxo_pending")
        d.mining.template_cache.clear()

    dag = rpc_call(addr, "getBlockDagInfo")
    assert dag["block_count"] == 5
    blk = rpc_call(addr, "getBlock", {"hash": dag["sink"]})
    assert blk["verbose"]["is_chain_block"]
    chain = rpc_call(addr, "getVirtualChainFromBlock", {"startHash": d.params.genesis.hash.hex()})
    assert len(chain["added_chain_blocks"]) == 5
    metrics = rpc_call(addr, "getMetrics")
    assert metrics["block_count"] == 5
    assert metrics["process_counters"]["header_counts"] == 5
    supply = rpc_call(addr, "getCoinSupply")
    assert supply["circulating_sompi"] >= 0

    # unknown method errors cleanly over the wire
    with pytest.raises(RuntimeError, match="unknown method"):
        rpc_call(addr, "noSuchMethod")
