"""ScriptBuilder minimal-push canonicality + perf monitor sampling."""

import pytest

from kaspa_tpu.metrics import PerfMonitor
from kaspa_tpu.txscript.script_builder import ScriptBuilder, ScriptBuilderError
from kaspa_tpu.txscript.vm import TxScriptEngine


def test_builder_pushes_are_engine_minimal():
    """Everything the builder emits must pass the engine's minimal-push rule."""
    b = ScriptBuilder()
    b.add_i64(0).add_i64(5).add_i64(16).add_i64(-1).add_i64(17).add_i64(-255)
    b.add_data(b"").add_data(b"\x07").add_data(b"\x81").add_data(bytes(75)).add_data(bytes(76)).add_data(bytes(300))
    b.add_op(0x75)  # drop something so the stack isn't huge; irrelevant here
    script = b.script()
    engine = TxScriptEngine()
    # executes without minimal-push violations (final stack check not relevant)
    engine.execute_script(script, verify_only_push=False)
    assert len(engine.dstack) >= 10


def test_builder_numeric_encodings():
    assert ScriptBuilder().add_i64(0).script() == b"\x00"
    assert ScriptBuilder().add_i64(7).script() == bytes([0x51 + 6])
    assert ScriptBuilder().add_i64(-1).script() == b"\x4f"
    assert ScriptBuilder().add_i64(127).script() == bytes([0x01, 127])
    assert ScriptBuilder().add_i64(128).script() == bytes([0x02, 128, 0])
    assert ScriptBuilder().add_lock_time(50).script() == bytes([8]) + (50).to_bytes(8, "little")


def test_builder_size_limits():
    with pytest.raises(ScriptBuilderError):
        ScriptBuilder().add_data(bytes(521))


def test_cltv_script_via_builder_executes():
    from kaspa_tpu.consensus.model import (
        ComputeCommit,
        ScriptPublicKey,
        Transaction,
        TransactionInput,
        TransactionOutpoint,
        UtxoEntry,
    )
    from kaspa_tpu.consensus.model.tx import SUBNETWORK_ID_NATIVE

    script = ScriptBuilder().add_lock_time(50).add_op(0xB0).add_op(0x51).script()
    tx = Transaction(
        0,
        [TransactionInput(TransactionOutpoint(b"\x01" * 32, 0), b"", 5, ComputeCommit.sigops(0))],
        [],
        100,
        SUBNETWORK_ID_NATIVE,
        0,
        b"",
    )
    entry = UtxoEntry(10, ScriptPublicKey(0, script), 0, False)
    TxScriptEngine(tx, [entry], 0).execute()


def test_vm_execution_counters():
    """Engine runs tick the observability counters (success and error)."""
    from kaspa_tpu.txscript import vm as vm_mod

    execs, errors = vm_mod._VM_EXECUTIONS.value, vm_mod._VM_ERRORS.value
    base_time = vm_mod._VM_EXEC_TIME.count
    script = ScriptBuilder().add_op(0x51).script()  # OP_TRUE
    TxScriptEngine().execute_script(script, verify_only_push=False)
    # execute_script is the low-level path; the counters wrap execute()
    from kaspa_tpu.consensus.model import (
        ComputeCommit,
        ScriptPublicKey,
        Transaction,
        TransactionInput,
        TransactionOutpoint,
        UtxoEntry,
    )
    from kaspa_tpu.consensus.model.tx import SUBNETWORK_ID_NATIVE

    tx = Transaction(
        0,
        [TransactionInput(TransactionOutpoint(b"\x01" * 32, 0), b"", 0, ComputeCommit.sigops(0))],
        [],
        0,
        SUBNETWORK_ID_NATIVE,
        0,
        b"",
    )
    entry = UtxoEntry(10, ScriptPublicKey(0, script), 0, False)
    TxScriptEngine(tx, [entry], 0).execute()
    assert vm_mod._VM_EXECUTIONS.value == execs + 1
    assert vm_mod._VM_EXEC_TIME.count == base_time + 1
    assert vm_mod._VM_ERRORS.value == errors
    bad = ScriptBuilder().add_op(0x00).script()  # OP_FALSE -> final stack false
    entry_bad = UtxoEntry(10, ScriptPublicKey(0, bad), 0, False)
    with pytest.raises(Exception):
        TxScriptEngine(tx, [entry_bad], 0).execute()
    assert vm_mod._VM_ERRORS.value == errors + 1
    assert vm_mod._VM_EXECUTIONS.value == execs + 2


def test_perf_monitor_samples():
    mon = PerfMonitor()
    m = mon.sample()
    assert m.resident_set_size > 0
    assert m.core_num > 0
    assert m.fd_num > 0
    # burn cpu and confirm usage registers as strictly positive
    x = 0
    for i in range(3_000_000):
        x += i * i
    m2 = mon.sample()
    assert m2.cpu_usage > 0.0
