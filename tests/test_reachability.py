"""Interval reachability vs an exact bitset oracle on randomized DAGs.

Mirrors the reference's randomized DAG test strategy
(consensus/src/processes/reachability/tests/gen.rs): generate DAGs with a
GHOSTDAG-like selected-parent rule, insert with small reindex_depth/slack to
force both reindex paths (subtree propagation and earlier-than-root slack
reclamation) plus reindex-root advancement, then compare every pairwise
chain/DAG query against the O(n^2/64) bitset oracle.
"""

import random

import pytest

from kaspa_tpu.consensus.reachability import ORIGIN, ReachabilityService


class BitsetOracle:
    """The exact (round-1) backend: past/chain bitmasks over dense indices."""

    def __init__(self):
        self._idx = {}
        self._past = []
        self._chain = []
        self._add(ORIGIN, [], None)

    def _add(self, block, parents, selected_parent):
        i = len(self._past)
        self._idx[block] = i
        past = 0
        for p in parents:
            pi = self._idx[p]
            past |= self._past[pi] | (1 << pi)
        self._past.append(past)
        if selected_parent is None:
            self._chain.append(1 << i)
        else:
            self._chain.append(self._chain[self._idx[selected_parent]] | (1 << i))

    def add_block(self, block, parents, selected_parent):
        self._add(block, parents, selected_parent)

    def is_dag_ancestor_of(self, a, b):
        if a == b:
            return True
        return bool(self._past[self._idx[b]] & (1 << self._idx[a]))

    def is_chain_ancestor_of(self, a, b):
        return bool(self._chain[self._idx[b]] & (1 << self._idx[a]))


def _mergeset(oracle: BitsetOracle, parents, selected_parent):
    """The ghostdag mergeset WITHOUT the selected parent: blocks reachable
    from parents but not in past(sp) ∪ {sp} (what add_block registers)."""
    ia = oracle._idx
    sp_i = ia[selected_parent]
    past_sp = oracle._past[sp_i] | (1 << sp_i)
    merged_mask = 0
    for p in parents:
        merged_mask |= oracle._past[ia[p]] | (1 << ia[p])
    merged_mask &= ~past_sp
    merged_mask &= ~(oracle._past[ia[ORIGIN]] | (1 << ia[ORIGIN]))
    out = []
    for blk, i in ia.items():
        if merged_mask & (1 << i):
            out.append(blk)
    return out


def _gen_dag(rng, n_blocks, max_parents=4, window=12):
    """Random DAG: parents picked from a recent-tip window (gen.rs shape)."""
    genesis = b"\xaa" * 32
    blocks = [genesis]
    parents_of = {genesis: []}
    tips = [genesis]
    for i in range(1, n_blocks):
        h = i.to_bytes(32, "big")
        k = min(len(tips), rng.randint(1, max_parents))
        parents = rng.sample(tips, k)
        parents_of[h] = parents
        tips = [t for t in tips if t not in parents] + [h]
        if len(tips) > window:
            tips = tips[-window:]
        blocks.append(h)
    return blocks, parents_of


@pytest.mark.parametrize("seed,n", [(1, 200), (2, 350), (3, 150)])
def test_randomized_dag_matches_oracle(seed, n):
    rng = random.Random(seed)
    # tiny capacity parameters force frequent reindexing incl. the
    # earlier-than-root path and root advancement
    svc = ReachabilityService(reindex_depth=10, reindex_slack=8)
    oracle = BitsetOracle()
    blocks, parents_of = _gen_dag(rng, n)
    genesis = blocks[0]
    svc.add_block(genesis, ORIGIN, [], [ORIGIN])
    oracle.add_block(genesis, [ORIGIN], None)

    sink = genesis
    for h in blocks[1:]:
        parents = parents_of[h]
        # selected parent: max "blue work" proxy = max chain length, by hash
        sp = max(parents, key=lambda p: (oracle._past[oracle._idx[p]].bit_count(), p))
        ms = _mergeset(oracle, parents, sp)
        svc.add_block(h, sp, ms, parents)
        oracle.add_block(h, parents, sp)
        # advance the root with the heaviest tip (sink proxy)
        if oracle._past[oracle._idx[h]].bit_count() >= oracle._past[oracle._idx[sink]].bit_count():
            sink = h
        svc.hint_virtual_selected_parent(sink)

    # exhaustive pairwise equivalence
    sample = blocks if len(blocks) <= 200 else rng.sample(blocks, 200)
    for a in sample:
        for b in sample:
            assert svc.is_dag_ancestor_of(a, b) == oracle.is_dag_ancestor_of(a, b), (a.hex(), b.hex())
            assert svc.is_chain_ancestor_of(a, b) == oracle.is_chain_ancestor_of(a, b), (a.hex(), b.hex())


def test_chain_only_dag_deep():
    """A 3000-long pure chain with tiny reindex params: interval memory must
    stay O(n) and queries exact (the bitset backend was O(n^2) here)."""
    svc = ReachabilityService(reindex_depth=25, reindex_slack=16)
    prev = ORIGIN
    chain = []
    for i in range(1, 3000):
        h = i.to_bytes(32, "little")
        svc.add_block(h, prev, [], [prev])
        svc.hint_virtual_selected_parent(h)
        chain.append(h)
        prev = h
    assert svc.is_chain_ancestor_of(chain[0], chain[-1])
    assert svc.is_chain_ancestor_of(chain[1500], chain[2500])
    assert not svc.is_chain_ancestor_of(chain[-1], chain[0])
    assert svc.is_dag_ancestor_of(chain[7], chain[2998])
    # memory: every node stores one interval + empty-ish FCS
    assert len(svc._interval) == 3000  # 2999 + ORIGIN


def test_delete_block_preserves_queries():
    rng = random.Random(9)
    svc = ReachabilityService(reindex_depth=10, reindex_slack=8)
    oracle = BitsetOracle()
    blocks, parents_of = _gen_dag(rng, 120)
    genesis = blocks[0]
    svc.add_block(genesis, ORIGIN, [], [ORIGIN])
    oracle.add_block(genesis, [ORIGIN], None)
    for h in blocks[1:]:
        parents = parents_of[h]
        sp = max(parents, key=lambda p: (oracle._past[oracle._idx[p]].bit_count(), p))
        ms = _mergeset(oracle, parents, sp)
        svc.add_block(h, sp, ms, parents)
        oracle.add_block(h, parents, sp)

    # delete a prefix of early blocks (pruning deletes old history in
    # ascending topological order); all queries among survivors must hold,
    # including DAG queries that previously routed through deleted blocks
    victims = sorted(blocks[1:25], key=lambda h: oracle._past[oracle._idx[h]].bit_count())
    for victim in victims:
        svc.delete_block(victim)
    survivors = [b for b in blocks if b not in set(victims)]
    for a in survivors:
        for b in survivors:
            assert svc.is_dag_ancestor_of(a, b) == oracle.is_dag_ancestor_of(a, b), (a.hex(), b.hex())
            assert svc.is_chain_ancestor_of(a, b) == oracle.is_chain_ancestor_of(a, b), (a.hex(), b.hex())
