"""Interpret-mode validation of the fused Pallas verification ladder.

The Mosaic kernel (ops/secp256k1/ladder_pallas.py) is the TPU fast path for
batched Schnorr/ECDSA; on the CPU test mesh we run it through the Pallas
interpreter and check the validity mask bit-for-bit against the pure-python
oracle (eclib) — same strategy as the XLA kernel's oracle tests.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from kaspa_tpu.crypto import eclib
from kaspa_tpu.crypto.secp import schnorr_challenge
from kaspa_tpu.ops import bigint as bi
from kaspa_tpu.ops.secp256k1 import points as pt
from kaspa_tpu.ops.secp256k1.ladder_pallas import verify_batch_pallas

pytestmark = pytest.mark.slow

B = 8


@pytest.fixture(scope="module")
def keys():
    random.seed(7)
    sk = [random.randrange(1, eclib.N) for _ in range(B)]
    return sk


def _limbs(vals):
    return np.stack([bi.int_to_limbs(v, 16) for v in vals]).astype(np.int32)


@pytest.mark.parametrize("glv", [False, True])
def test_schnorr_pallas_interpret(keys, glv):
    sk = keys
    pubs = [eclib.schnorr_pubkey(k) for k in sk]
    pks = [eclib.lift_x(int.from_bytes(p, "big")) for p in pubs]
    msgs = [random.randbytes(32) for _ in range(B)]
    sigs = [eclib.schnorr_sign(m, k, b"\x07" * 32) for m, k in zip(msgs, sk)]
    expect = [True] * B
    for i in (1, 5):  # corrupt s
        sigs[i] = sigs[i][:40] + bytes([sigs[i][40] ^ 1]) + sigs[i][41:]
        expect[i] = False
    # corrupt r on one more lane
    sigs[6] = bytes([sigs[6][0] ^ 1]) + sigs[6][1:]
    expect[6] = False

    px = _limbs([p[0] for p in pks])
    py = _limbs([p[1] for p in pks])
    rc = _limbs([int.from_bytes(s[:32], "big") for s in sigs])
    sd = [int.from_bytes(s[32:], "big") for s in sigs]
    ed = [schnorr_challenge(s[:32], pubs[i], msgs[i]) for i, s in enumerate(sigs)]
    ok = np.ones(B, dtype=bool)
    ok[3] = False  # host-side encoding rejection must mask through
    expect[3] = False

    mask = verify_batch_pallas(px, py, rc, sd, ed, ok, ecdsa=False, interpret=True, glv=glv)
    assert mask.tolist() == expect

    # oracle cross-check on the uncorrupted lanes
    for i in (0, 2, 4, 7):
        assert eclib.schnorr_verify(pubs[i], msgs[i], sigs[i])


def test_ecdsa_pallas_interpret(keys):
    sk = keys
    pks = [eclib.point_mul(eclib.G, k) for k in sk]
    msgs = [random.randbytes(32) for _ in range(B)]
    sigs_b = [eclib.ecdsa_sign(m, k, 10_007 + i) for i, (m, k) in enumerate(zip(msgs, sk))]
    rs = [(int.from_bytes(s[:32], "big"), int.from_bytes(s[32:], "big")) for s in sigs_b]
    expect = [True] * B
    rs[2] = (rs[2][0], rs[2][1] ^ 2)  # corrupt s
    expect[2] = False

    u1, u2 = [], []
    for m, (r, s) in zip(msgs, rs):
        z = int.from_bytes(m, "big") % eclib.N
        si = pow(s, -1, eclib.N)
        u1.append(z * si % eclib.N)
        u2.append(r * si % eclib.N)

    px = _limbs([p[0] for p in pks])
    py = _limbs([p[1] for p in pks])
    rn = _limbs([r % eclib.N for r, _ in rs])
    ok = np.ones(B, dtype=bool)

    mask = verify_batch_pallas(px, py, rn, u1, u2, ok, ecdsa=True, interpret=True)
    assert mask.tolist() == expect


def test_glv_split_identity():
    from kaspa_tpu.ops.secp256k1.ladder_pallas import GLV_LAMBDA, glv_split

    random.seed(11)
    for _ in range(500):
        k = random.randrange(eclib.N)
        k1, k2 = glv_split(k)
        assert (k1 + k2 * GLV_LAMBDA) % eclib.N == k
        assert abs(k1).bit_length() <= 132 and abs(k2).bit_length() <= 132
