"""ScopeIndex property tests: index routing vs the brute-force
per-subscriber filter oracle (the single-fanout Broadcaster's own scope
scan) under random subscribe/unsubscribe/scope-mutation interleavings."""

import random

import pytest

from kaspa_tpu.notify.notifier import Notification
from kaspa_tpu.serving.broadcaster import Broadcaster
from kaspa_tpu.serving.scope_index import ScopeIndex
from kaspa_tpu.serving.shards import filter_payload


class _Spk:
    __slots__ = ("script",)

    def __init__(self, script):
        self.script = script


class _Entry:
    __slots__ = ("script_public_key", "amount")

    def __init__(self, script, amount):
        self.script_public_key = _Spk(script)
        self.amount = amount


SCRIPTS = [b"spk-%03d" % i for i in range(40)]


def _diff(rnd, seq0=0):
    """A random utxos-changed diff over the universe (added + removed)."""
    seq = seq0
    added, removed, spk_set = [], [], set()
    for _ in range(rnd.randint(1, 10)):
        s = rnd.choice(SCRIPTS)
        added.append((seq, _Entry(s, 1000 + seq)))
        spk_set.add(s)
        seq += 1
    for _ in range(rnd.randint(0, 4)):
        s = rnd.choice(SCRIPTS)
        removed.append((seq, _Entry(s, 1000 + seq)))
        spk_set.add(s)
        seq += 1
    return Notification(
        "utxos-changed",
        {"added": added, "removed": removed, "spk_set": spk_set},
        None,
        t_accept_ns=seq0 + 1,
    )


def _canon(n):
    return (
        [(k, e.script_public_key.script, e.amount) for k, e in n.data["added"]],
        [(k, e.script_public_key.script, e.amount) for k, e in n.data["removed"]],
        sorted(n.data["spk_set"]),
        n.t_accept_ns,
        n.merged,
    )


@pytest.mark.parametrize("seed", [1, 7, 23, 101])
def test_scope_index_matches_brute_force_oracle(seed):
    """Random op interleavings; after every diff the index's affected set
    and per-subscriber payloads must equal the oracle's (a plain dict of
    sub -> scope run through _filter_utxos_changed)."""
    rnd = random.Random(seed)
    index = ScopeIndex()
    oracle: dict[str, frozenset | None] = {}  # name -> scope (None = wildcard)

    for step in range(300):
        op = rnd.random()
        name = f"sub-{rnd.randrange(25)}"
        if op < 0.35:
            # (re)subscribe with a fresh scope (None = wildcard 1 in 6)
            new = (
                None
                if rnd.randrange(6) == 0
                else frozenset(rnd.sample(SCRIPTS, rnd.randint(1, 6)))
            )
            if name in oracle:
                index.update(name, oracle[name], new)
            else:
                index.add(name, new)
            oracle[name] = new
        elif op < 0.55 and oracle:
            # scope mutation: grow or shrink an existing subscriber
            name = rnd.choice(sorted(oracle))
            old = oracle[name]
            if old is None:
                new = frozenset(rnd.sample(SCRIPTS, rnd.randint(1, 4)))
            elif rnd.random() < 0.5:
                new = old | frozenset(rnd.sample(SCRIPTS, rnd.randint(1, 3)))
            else:
                keep = rnd.randint(0, len(old))
                new = frozenset(rnd.sample(sorted(old), keep)) or None
            index.update(name, old, new)
            oracle[name] = new
        elif op < 0.7 and oracle:
            # unsubscribe
            name = rnd.choice(sorted(oracle))
            index.discard(name, oracle.pop(name))
        else:
            # route a diff and compare against the oracle
            n = _diff(rnd, seq0=step * 100)
            by_script = Broadcaster._index_diff(n)
            hits = index.route(by_script)
            routed = set(hits) | set(index.wildcard)
            expected_payloads = {}
            affected = set()
            for sub, scope in oracle.items():
                if scope is None:
                    affected.add(sub)  # wildcard: gets the raw notification
                    continue
                filtered = Broadcaster._filter_utxos_changed(n, scope, by_script)
                if filtered is not None:
                    affected.add(sub)
                    expected_payloads[sub] = _canon(filtered)
            assert routed == affected, f"step {step}: affected-set divergence"
            for sub, matched in hits.items():
                got = filter_payload(n, matched, by_script)
                assert _canon(got) == expected_payloads[sub], (
                    f"step {step}: payload divergence for {sub}"
                )

    # structural sanity after the churn
    assert index.entry_count() == sum(
        len(s) for s in oracle.values() if s is not None
    )
    assert index.wildcard == {s for s, sc in oracle.items() if sc is None}


def test_scope_index_update_delta_only():
    """update() must touch only the symmetric difference."""
    index = ScopeIndex()
    old = frozenset(SCRIPTS[:10])
    index.add("a", old)
    new = frozenset(SCRIPTS[5:15])
    index.update("a", old, new)
    for s in SCRIPTS[5:15]:
        assert "a" in index.watchers(s)
    for s in SCRIPTS[:5]:
        assert "a" not in index.watchers(s)
    # scripts with no watchers are pruned (no unbounded key growth)
    assert index.script_count() == 10


def test_scope_index_wildcard_transitions():
    index = ScopeIndex()
    index.add("w", None)
    assert index.wildcard == {"w"}
    index.update("w", None, frozenset(SCRIPTS[:3]))
    assert index.wildcard == set()
    assert index.entry_count() == 3
    index.update("w", frozenset(SCRIPTS[:3]), None)
    assert index.wildcard == {"w"}
    assert index.entry_count() == 0
    index.discard("w", None)
    assert index.wildcard == set()


def test_route_ignores_unwatched_scripts():
    index = ScopeIndex()
    index.add("a", frozenset({SCRIPTS[0]}))
    hits = index.route([SCRIPTS[0], SCRIPTS[1], SCRIPTS[2]])
    assert hits == {"a": [SCRIPTS[0]]}
    assert index.route([SCRIPTS[5]]) == {}
