"""Concurrent pipeline tests: the port of the reference's
consensus_pipeline_tests.rs (test_concurrent_pipeline /
test_concurrent_pipeline_random) plus deps-manager unit coverage.

Blocks are real (built against a scratch consensus), then submitted to a
fresh pipelined consensus concurrently / out of order / in duplicate —
results must match a sequential replay bit-for-bit and the reachability
intervals must stay valid.
"""

import random
import threading

import pytest

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.params import simnet_params
from kaspa_tpu.consensus.processes.coinbase import MinerData
from kaspa_tpu.consensus.model import ScriptPublicKey
from kaspa_tpu.pipeline import BlockTaskDependencyManager, ConsensusPipeline

MINER = MinerData(ScriptPublicKey(0, b"\x20" + b"\x07" * 32 + b"\xac"))


def _build_dag(topology):
    """topology: list of (name, [parent names]); returns (params, blocks in
    topology order) built/validated on a scratch consensus."""
    params = simnet_params()
    scratch = Consensus(params)
    by_name = {"G": params.genesis.hash}
    blocks = []
    for i, (name, parent_names) in enumerate(topology):
        parents = [by_name[p] for p in parent_names]
        blk = scratch.build_block_with_parents(parents, MINER)
        blk.header.nonce = i + 1  # disambiguate same-parent siblings
        blk.header.invalidate_cache()
        scratch.validate_and_insert_block(blk)
        by_name[name] = blk.hash
        blocks.append(blk)
    return params, blocks, by_name


TOPOLOGY = [
    ("2", ["G"]),
    ("3", ["G"]),
    ("4", ["2", "3"]),
    ("5", ["4"]),
    ("6", ["G"]),
    ("7", ["5", "6"]),
    ("8", ["G"]),
    ("9", ["G"]),
    ("10", ["7", "8", "9"]),
    ("11", ["G"]),
    ("12", ["11", "10"]),
]


def test_concurrent_pipeline():
    """Reference: consensus_pipeline_tests.rs test_concurrent_pipeline —
    every block submitted twice concurrently; reachability relations and
    intervals must come out exact."""
    params, blocks, names = _build_dag(TOPOLOGY)
    consensus = Consensus(params)
    pipe = ConsensusPipeline(consensus, workers=3)
    try:
        for blk in blocks:
            f1 = pipe.submit(blk)
            f2 = pipe.submit(blk)  # duplicate: absorbed by the task group
            assert f1.result(timeout=60) in ("utxo_valid", "utxo_pending")
            assert f2.result(timeout=60) in ("utxo_valid", "utxo_pending")
    finally:
        pipe.shutdown()

    reach = consensus.reachability
    reach.validate_intervals()
    g = params.genesis.hash
    for name in [t[0] for t in TOPOLOGY]:
        assert reach.is_dag_ancestor_of(g, names[name])

    in_past = lambda a, b: reach.is_dag_ancestor_of(names[a], names[b]) and names[a] != names[b]
    anticone = lambda a, b: not reach.is_dag_ancestor_of(names[a], names[b]) and not reach.is_dag_ancestor_of(
        names[b], names[a]
    )
    assert in_past("2", "4") and in_past("2", "5") and in_past("2", "7")
    assert in_past("5", "10") and in_past("6", "10")
    assert in_past("10", "12") and in_past("11", "12")
    assert anticone("2", "3") and anticone("2", "6") and anticone("3", "6")
    assert anticone("5", "6") and anticone("3", "8")
    assert anticone("11", "2") and anticone("11", "4") and anticone("11", "6") and anticone("11", "9")


def test_concurrent_pipeline_random_waves():
    """Reference: test_concurrent_pipeline_random — Poisson waves of
    sibling blocks submitted concurrently without awaiting; the pipelined
    result must equal a sequential replay."""
    rng = random.Random(42)
    params = simnet_params()
    scratch = Consensus(params)
    tips = [params.genesis.hash]
    all_blocks = []
    total = 120
    while total > 0:
        v = min(params.max_block_parents, max(1, int(rng.gauss(3, 1.5))))
        v = min(v, total)
        total -= v
        new_tips = []
        for _ in range(v):
            blk = scratch.build_block_with_parents(list(tips), MINER)
            blk.header.nonce = rng.getrandbits(48)
            blk.header.invalidate_cache()
            scratch.validate_and_insert_block(blk)
            new_tips.append(blk.hash)
            all_blocks.append(blk)
        tips = new_tips

    consensus = Consensus(params)
    pipe = ConsensusPipeline(consensus, workers=3)
    try:
        futures = [pipe.submit(b) for b in all_blocks]  # whole DAG in flight
        for f in futures:
            f.result(timeout=120)
    finally:
        pipe.shutdown()

    consensus.reachability.validate_intervals()
    assert consensus.sink() == scratch.sink()
    assert consensus.get_virtual_daa_score() == scratch.get_virtual_daa_score()
    for blk in all_blocks:
        # consensus data must be bit-identical; statuses may differ only in
        # that drained-batch resolution leaves side blocks utxo_pending
        # (the reference's virtual processor batches the same way)
        assert consensus.storage.ghostdag.get_blue_work(blk.hash) == scratch.storage.ghostdag.get_blue_work(blk.hash)
        assert consensus.storage.ghostdag.get(blk.hash).mergeset_blues == scratch.storage.ghostdag.get(blk.hash).mergeset_blues
        status = consensus.storage.statuses.get(blk.hash)
        ref_status = scratch.storage.statuses.get(blk.hash)
        assert status == ref_status or (status == "utxo_pending" and ref_status == "utxo_valid")
    # every selected-chain ancestor of the sink is fully UTXO-verified
    cur = consensus.sink()
    while cur != params.genesis.hash:
        assert consensus.storage.statuses.get(cur) == "utxo_valid"
        cur = consensus.storage.ghostdag.get_selected_parent(cur)


def test_pipeline_out_of_order_chain():
    """A linear chain submitted all at once: children park on pending
    parents in the deps manager and complete once released."""
    topo = [(str(i), [str(i - 1)] if i > 2 else ["G"]) for i in range(2, 22)]
    params, blocks, _ = _build_dag(topo)
    consensus = Consensus(params)
    pipe = ConsensusPipeline(consensus, workers=2)
    try:
        futures = [pipe.submit(b) for b in blocks]
        statuses = [f.result(timeout=120) for f in futures]
    finally:
        pipe.shutdown()
    assert statuses[-1] == "utxo_valid"
    assert consensus.sink() == blocks[-1].hash


def test_pipeline_missing_parent_errors():
    params, blocks, _ = _build_dag([("2", ["G"]), ("3", ["2"])])
    consensus = Consensus(params)
    pipe = ConsensusPipeline(consensus)
    try:
        fut = pipe.submit(blocks[1])  # parent never submitted nor known
        with pytest.raises(Exception, match="missing parent"):
            fut.result(timeout=30)
    finally:
        pipe.shutdown()


def test_deps_manager_parking_and_groups():
    dm = BlockTaskDependencyManager()

    class T:
        def __init__(self, h, parents):
            self.h, self.parents = h, parents

    a, b = b"\xaa" * 32, b"\xbb" * 32
    ta, tb = T(a, []), T(b, [a])
    assert dm.register(a, ta) is True
    assert dm.register(b, tb) is True
    assert dm.register(b, tb) is False  # duplicate absorbed

    parents_of = lambda t: t.parents
    # b parks under pending a
    assert dm.try_begin(b, parents_of) is None
    assert dm.try_begin(a, parents_of) is ta
    released = dm.end(a)
    assert released == [b]
    assert dm.try_begin(b, parents_of) is tb
    # first b ends -> same hash requeued for the duplicate
    assert dm.end(b) == [b]
    assert dm.try_begin(b, parents_of) is tb
    assert dm.end(b) == []
    assert dm.wait_for_idle(1.0)


def test_pipeline_wait_for_idle_and_counters():
    topo = [(str(i), [str(i - 1)] if i > 2 else ["G"]) for i in range(2, 8)]
    params, blocks, _ = _build_dag(topo)
    consensus = Consensus(params)
    pipe = ConsensusPipeline(consensus)
    try:
        for b in blocks:
            pipe.submit(b)
        pipe.wait_for_idle()
        snap = consensus.counters.snapshot()
        assert snap.body_counts == len(blocks)
    finally:
        pipe.shutdown()


def test_channel_drain_cap():
    """Channel.drain(max_items) takes at most that many, FIFO, leaving the
    rest queued — the primitive under the virtual worker's batch bound."""
    from kaspa_tpu.utils.sync import Channel

    ch = Channel()
    for i in range(10):
        ch.send(i)
    assert ch.drain(3) == [0, 1, 2]
    assert ch.drain(0) == []
    assert ch.drain(None) == [3, 4, 5, 6, 7, 8, 9]
    assert ch.drain(5) == []


def test_virtual_batch_cap(monkeypatch):
    """KASPA_TPU_VIRTUAL_BATCH_MAX bounds blocks absorbed per virtual
    cycle; a capped pipeline must still absorb every block (the feed stays
    honest, the batches just get smaller)."""
    from kaspa_tpu.pipeline.pipeline import _VIRT_BATCH

    monkeypatch.setenv("KASPA_TPU_VIRTUAL_BATCH_MAX", "2")
    topo = [(str(i), [str(i - 1)] if i > 2 else ["G"]) for i in range(2, 18)]
    params, blocks, _ = _build_dag(topo)
    consensus = Consensus(params)
    count0, max0 = _VIRT_BATCH.count, _VIRT_BATCH.max
    pipe = ConsensusPipeline(consensus, workers=2)
    assert pipe._virtual_batch_max == 2
    try:
        futures = [pipe.submit(b) for b in blocks]
        statuses = [f.result(timeout=120) for f in futures]
    finally:
        pipe.shutdown()
    assert statuses[-1] == "utxo_valid"
    assert consensus.sink() == blocks[-1].hash
    # the histogram recorded this run's cycles, none above the cap
    assert _VIRT_BATCH.count > count0
    if _VIRT_BATCH.max > max0:
        assert _VIRT_BATCH.max <= 2


def test_relay_out_of_order_parks_on_inflight_parent():
    """VERDICT r3 #3 'done' criterion: a relayed child whose parent is
    still IN FLIGHT inside the pipeline must park in the deps manager (not
    orphan out), and both must land — overlapped header/body/virtual
    processing across relay arrivals."""
    import random
    import threading
    import time

    from kaspa_tpu.p2p.node import Node, connect

    params = simnet_params(bps=2)
    scratch = Consensus(params)
    node = Node(Consensus(params), "ooo-relay")

    # build parent + child on a scratch consensus
    parent = scratch.build_block_template(MINER, [])
    scratch.validate_and_insert_block(parent)
    child = scratch.build_block_template(MINER, [])

    # hold the pipeline's commit lock so the parent stays in flight while
    # the child arrives over relay
    gate = node.pipeline._lock
    release = threading.Event()

    def hold():
        with gate:
            release.wait(10)

    holder = threading.Thread(target=hold, daemon=True)
    holder.start()
    time.sleep(0.1)

    parent_fut = node.pipeline.submit(parent)
    time.sleep(0.2)  # stage worker now blocked on the held lock
    assert node.pipeline.deps.is_pending(parent.hash)

    peer_node = Node(Consensus(params), "ooo-peer")
    pa, pb = connect(node, peer_node)

    done = []

    def relay_child():
        # _on_relay_block must treat the in-flight parent as present
        with node.lock:
            node._on_relay_block(pb.remote, child)
        done.append(True)

    relayer = threading.Thread(target=relay_child, daemon=True)
    relayer.start()
    time.sleep(0.2)
    assert child.hash not in node.orphan_blocks, "child wrongly orphaned"
    release.set()
    relayer.join(30)
    assert done, "relay did not complete"
    assert parent_fut.result(30) in ("utxo_valid", "utxo_pending")
    assert node.consensus.storage.statuses.get(child.hash) == "utxo_valid"
    assert node.consensus.sink() == child.hash
