"""Finality conflict detection: a heavier chain excluding the finality point
must never be adopted — it is surfaced as a FinalityConflict notification
and requires manual resolution (virtual_processor finality filtering +
flow_context.rs on_finality_conflict)."""

from __future__ import annotations

import random

import pytest

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.params import GenesisBlock, Params
from kaspa_tpu.sim.simulator import Miner


def _params() -> Params:
    genesis = GenesisBlock(hash=b"\x01" + b"\x00" * 31, bits=0x207FFFFF, timestamp=0)
    return Params.from_bps(
        "simnet-finality", 2, genesis, skip_proof_of_work=True, coinbase_maturity=8,
        merge_depth=10, finality_depth=20, pruning_depth=60, pruning_proof_m=10,
        difficulty_window_size=15, min_difficulty_window_size=5, difficulty_sample_rate=2,
        past_median_time_window_size=10, past_median_time_sample_rate=2,
    )


def test_finality_violating_chain_not_adopted():
    params = _params()
    c = Consensus(params)
    miner = Miner(0, random.Random(12))
    events = []
    lid = c.notification_root.register(lambda n: events.append(n))
    c.notification_root.start_notify(lid, "finality-conflict")
    c.notification_root.start_notify(lid, "finality-conflict-resolved")

    # main chain: 40 blocks (well past finality_depth=20)
    for i in range(40):
        t = c.build_block_template(miner.miner_data, [], timestamp=1_000 + 600 * i)
        assert c.validate_and_insert_block(t) in ("utxo_valid", "utxo_pending")
    main_sink = c.sink()

    # heavier side chain from genesis: 50 blocks, never merging main
    fork_tip = params.genesis.hash
    for i in range(50):
        blk = c.build_block_with_parents([fork_tip], miner.miner_data, [], timestamp=2_000 + 600 * i)
        status = c.validate_and_insert_block(blk)
        assert status in ("utxo_valid", "utxo_pending"), status
        fork_tip = blk.hash

    # the fork is heavier ...
    assert c.storage.ghostdag.get_blue_work(fork_tip) > c.storage.ghostdag.get_blue_work(main_sink)
    # ... but the sink must stay on the finality-anchored chain
    assert c.sink() == main_sink
    assert c.reachability.is_chain_ancestor_of(main_sink, c.sink())
    conflicts = [n for n in events if n.event_type == "finality-conflict"]
    assert conflicts, "no FinalityConflict notification emitted"
    assert any(n.data["violating_tip"] == fork_tip.hex() for n in conflicts)
    # mining continues on the honest chain
    t = c.build_block_template(miner.miner_data, [], timestamp=60_000)
    assert c.validate_and_insert_block(t) in ("utxo_valid", "utxo_pending")
    assert c.reachability.is_chain_ancestor_of(main_sink, c.sink())

    # operator resolution clears the conflict and emits the resolved event
    from kaspa_tpu.p2p import Node
    from kaspa_tpu.rpc import RpcCoreService

    node = Node(c, "finality-test")
    svc = RpcCoreService(c, node.mining, p2p_node=node)
    assert "active" in c._finality_conflicts.values()
    svc.resolve_finality_conflict(main_sink)
    assert all(st == "resolved" for st in c._finality_conflicts.values())
    resolved = [n for n in events if n.event_type == "finality-conflict-resolved"]
    assert resolved and resolved[0].data["finality_block_hash"] == main_sink.hex()

    from kaspa_tpu.rpc.service import RpcError

    with pytest.raises(RpcError):
        svc.resolve_finality_conflict(main_sink)


def test_finality_conflict_emitted_once_no_sink_search_wedge():
    """An active conflict tip must notify exactly once, stay unadopted
    across every subsequent virtual resolve, and never wedge sink search
    (each later insert's resolve must still terminate with a valid sink)."""
    params = _params()
    c = Consensus(params)
    miner = Miner(0, random.Random(99))
    events = []
    lid = c.notification_root.register(lambda n: events.append(n))
    c.notification_root.start_notify(lid, "finality-conflict")

    for i in range(40):
        t = c.build_block_template(miner.miner_data, [], timestamp=1_000 + 600 * i)
        assert c.validate_and_insert_block(t) in ("utxo_valid", "utxo_pending")
    main_sink = c.sink()

    # heavier fork from genesis that excludes the finality point
    fork_tip = params.genesis.hash
    for i in range(50):
        blk = c.build_block_with_parents([fork_tip], miner.miner_data, [], timestamp=2_000 + 600 * i)
        assert c.validate_and_insert_block(blk) in ("utxo_valid", "utxo_pending")
        fork_tip = blk.hash
    assert c.storage.ghostdag.get_blue_work(fork_tip) > c.storage.ghostdag.get_blue_work(main_sink)

    def conflicts_for(tip):
        return [
            n for n in events
            if n.event_type == "finality-conflict" and n.data["violating_tip"] == tip.hex()
        ]

    assert len(conflicts_for(fork_tip)) == 1
    assert c.sink() == main_sink

    # every further honest insert re-runs _resolve_virtual over the same tip
    # set; the standing conflict must neither re-notify nor block the search
    for i in range(6):
        t = c.build_block_template(miner.miner_data, [], timestamp=40_000 + 600 * i)
        assert c.validate_and_insert_block(t) in ("utxo_valid", "utxo_pending")
        sink = c.sink()
        assert sink != fork_tip
        assert c.reachability.is_chain_ancestor_of(main_sink, sink)
    assert len(conflicts_for(fork_tip)) == 1

    # the violating tip must not appear among virtual parents either
    assert fork_tip not in c.virtual_state.parents
