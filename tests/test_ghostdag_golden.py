"""GHOSTDAG coloring vs the reference's golden DAG vectors.

Replays testdata/dags/dag0-5.json (the go-kaspad-derived vectors used by the
reference's ghostdag_test, testing/integration/src/consensus_integration_tests.rs:273)
through our GhostdagManager and asserts selected parent, blues, reds, and
blue score per block.
"""

import json
import os

import pytest

from kaspa_tpu.consensus.model import Header
from kaspa_tpu.consensus.processes.ghostdag import GhostdagManager
from kaspa_tpu.consensus.reachability import ORIGIN, ReachabilityService
from kaspa_tpu.consensus.stores import ConsensusStorage

DAG_DIR = "/root/reference/testing/integration/testdata/dags"
UNIFORM_BITS = 0x207FFFFF

# the golden vectors live in the reference checkout; without it the
# parametrization is empty and pytest reports a clean skip, not a
# collection error
_DAG_FILES = sorted(os.listdir(DAG_DIR)) if os.path.isdir(DAG_DIR) else []


def string_to_hash(s: str) -> bytes:
    return s.encode().ljust(32, b"\x00")


def _mk_header(block_hash: bytes, parents: list[bytes]) -> Header:
    hd = Header(
        version=1,
        parents_by_level=[parents],
        hash_merkle_root=b"\x00" * 32,
        accepted_id_merkle_root=b"\x00" * 32,
        utxo_commitment=b"\x00" * 32,
        timestamp=0,
        bits=UNIFORM_BITS,
        nonce=0,
        daa_score=0,
        blue_work=0,
        blue_score=0,
        pruning_point=b"\x00" * 32,
    )
    hd._hash_cache = block_hash  # test blocks use synthetic ids (skip_proof_of_work style)
    return hd


@pytest.mark.skipif(not _DAG_FILES, reason=f"golden DAG vectors not present at {DAG_DIR}")
@pytest.mark.parametrize("dag_file", _DAG_FILES or ["<missing>"])
def test_ghostdag_golden(dag_file):
    with open(os.path.join(DAG_DIR, dag_file)) as f:
        test = json.load(f)

    genesis = string_to_hash(test["GenesisID"])
    storage = ConsensusStorage()
    reach = ReachabilityService()
    mgr = GhostdagManager(genesis, test["K"], storage.ghostdag, storage.relations, storage.headers, reach)

    storage.relations.insert(genesis, [ORIGIN])
    storage.headers.insert(_mk_header(genesis, [ORIGIN]))
    storage.ghostdag.insert(genesis, mgr.genesis_ghostdag_data())
    reach.add_block(genesis, ORIGIN, [], [ORIGIN])

    for block in test["Blocks"]:
        block_id = string_to_hash(block["ID"])
        parents = [string_to_hash(p) for p in block["Parents"]]
        data = mgr.ghostdag(parents)
        storage.relations.insert(block_id, parents)
        storage.headers.insert(_mk_header(block_id, parents))
        storage.ghostdag.insert(block_id, data)
        reach.add_block(block_id, data.selected_parent, data.unordered_mergeset_without_selected_parent(), parents)

        ctx = f"{dag_file}:{block['ID']}"
        assert data.selected_parent == string_to_hash(block["ExpectedSelectedParent"]), ctx
        assert data.mergeset_reds == [string_to_hash(h) for h in block["ExpectedReds"]], ctx
        assert data.mergeset_blues == [string_to_hash(h) for h in block["ExpectedBlues"]], ctx
        assert data.blue_score == block["ExpectedScore"], ctx
