"""Wallet-side mass/fee estimator vectors + event-driven balance updates.

The vectors are hand-derived from the reference formulas in
wallet/core/src/tx/mass.rs (sizes, gram costs, relay fee, dust) so a
change to any constant or term breaks a byte-precise expectation, and the
two-process test drives wallet balance purely from the notification
stream — no balance polling.
"""

from __future__ import annotations

import random

import pytest

from kaspa_tpu.consensus.model import (
    ComputeCommit,
    ScriptPublicKey,
    Transaction,
    TransactionInput,
    TransactionOutpoint,
    TransactionOutput,
)
from kaspa_tpu.consensus.model.tx import SUBNETWORK_ID_NATIVE, UtxoEntry
from kaspa_tpu.consensus.params import simnet_params
from kaspa_tpu.wallet import mass as wm


def _p2pk_spk() -> ScriptPublicKey:
    return ScriptPublicKey(0, bytes([32]) + bytes(32) + bytes([0xAC]))  # 34 bytes


def _unsigned_tx(n_inputs: int, n_outputs: int) -> Transaction:
    inputs = [
        TransactionInput(TransactionOutpoint(bytes([i]) * 32, 0), b"", 0, ComputeCommit.sigops(1))
        for i in range(n_inputs)
    ]
    outputs = [TransactionOutput(10_000_000, _p2pk_spk()) for _ in range(n_outputs)]
    return Transaction(0, inputs, outputs, 0, SUBNETWORK_ID_NATIVE, 0, b"")


def test_serialized_size_vectors():
    """mass.rs size formulas, term by term."""
    # blank tx: 2 + 8 + 8 + 8 + 20 + 8 + 32 + 8 = 94 (mass.rs:154-171)
    assert wm.blank_transaction_serialized_byte_size() == 94
    # outpoint 36; unsigned input 36+8+0+8 = 52 (mass.rs:173-187)
    tx = _unsigned_tx(1, 2)
    assert wm.transaction_input_serialized_byte_size(tx.inputs[0]) == 52
    # p2pk output: 8 + 2 + 8 + 34 = 52 (mass.rs:190-196)
    assert wm.transaction_output_serialized_byte_size(tx.outputs[0]) == 52
    # standard output uses the max script vector: 8+2+8+36 = 54 (mass.rs:198)
    assert wm.transaction_standard_output_serialized_byte_size() == 54
    # whole tx: 94 + 52 + 2*52 = 250
    assert wm.transaction_serialized_byte_size(tx) == 250


def test_compute_mass_vectors():
    """Unsigned 1-in-2-out p2pk at mainnet gram costs (mass_per_tx_byte=1,
    per_spk_byte=10, per_sig_op=1000), mass.rs:236-291."""
    params = simnet_params(bps=2)
    mc = wm.WalletMassCalculator(params)
    tx = _unsigned_tx(1, 2)
    # blank 94*1; payload 0; outputs 2*(10*(2+34) + 52*1) = 2*412 = 824;
    # input 1*1000 + 52*1 = 1052
    assert mc.blank_transaction_compute_mass() == 94
    assert mc.calc_compute_mass_for_output(tx.outputs[0]) == 412
    assert mc.calc_compute_mass_for_input(tx.inputs[0]) == 1052
    assert mc.calc_compute_mass_for_signed_transaction(tx) == 94 + 824 + 1052
    # + signature mass 66*1*1 per input (mass.rs:275-281)
    assert mc.calc_signature_compute_mass_for_inputs(1, 1) == 66
    assert mc.calc_compute_mass_for_unsigned_transaction(tx, 1) == 94 + 824 + 1052 + 66
    # payload hardening: bytes priced at max(mass_per_tx_byte, 2)
    assert mc.calc_compute_mass_for_payload(100) == 200


def test_relay_fee_and_dust_vectors():
    """mass.rs:29-45 relay fee scaling and :227-233 dust threshold."""
    assert wm.calc_minimum_required_transaction_relay_fee(1000) == 100_000
    assert wm.calc_minimum_required_transaction_relay_fee(2036) == 203_600
    assert wm.calc_minimum_required_transaction_relay_fee(0) == 100_000  # floor
    params = simnet_params(bps=2)
    mc = wm.WalletMassCalculator(params)
    # threshold: value*1000/606 < 100_000 => dust below 60_600 sompi
    assert wm.STANDARD_OUTPUT_SIZE_PLUS_INPUT_SIZE_3X == 606
    assert mc.is_dust(60_599)
    assert not mc.is_dust(60_601)


def test_overall_mass_matches_consensus():
    """The wallet's overall unsigned mass must dominate what consensus
    charges the signed tx (signature bytes are the only estimate slack)."""
    from kaspa_tpu.consensus.mass import MassCalculator

    params = simnet_params(bps=2)
    wmc = wm.WalletMassCalculator(params)
    cmc = MassCalculator.from_params(params)
    tx = _unsigned_tx(2, 2)
    entries = [
        UtxoEntry(50_000_000, _p2pk_spk(), 1, False),
        UtxoEntry(50_000_000, _p2pk_spk(), 1, False),
    ]
    overall = wmc.calc_overall_mass_for_unsigned_transaction(tx, entries, 1)
    signed = Transaction(
        0,
        [
            TransactionInput(i.previous_outpoint, bytes(66), 0, i.compute_commit)
            for i in tx.inputs
        ],
        list(tx.outputs),
        0,
        SUBNETWORK_ID_NATIVE,
        0,
        b"",
    )
    consensus_compute = cmc.calc_non_contextual_masses(signed).compute_mass
    compute_est = wmc.calc_compute_mass_for_unsigned_transaction(tx, 1)
    storage = wmc.calc_storage_mass(tx, entries)
    assert overall == max(compute_est, storage)  # mass.rs combine_mass
    # the compute estimate dominates consensus with only varint-width slack
    assert compute_est >= consensus_compute
    assert compute_est - consensus_compute <= 2 * 16


def test_balance_from_notification_stream_two_process(tmp_path):
    """Event-driven discovery: a remote wallet learns its balance purely
    from streamed utxos-changed notifications over the wire — it never
    calls a balance RPC (wallet/core UtxoProcessor discipline)."""
    from kaspa_tpu.node.daemon import Daemon, parse_args
    from kaspa_tpu.rpc.wrpc import WrpcClient
    from kaspa_tpu.wallet.account import Account
    from kaspa_tpu.wallet.utxo_processor import UtxoProcessor, WalletEventType

    args = parse_args(
        ["--appdir", str(tmp_path), "--rpclisten", "127.0.0.1:0",
         "--rpclisten-wrpc", "127.0.0.1:0", "--bps", "2"]
    )
    d = Daemon(args)
    d.start()
    client = None
    try:
        account = Account.from_seed(b"\x07" * 32)
        derived = account.derive_receive_address()
        addr_str = derived.address.to_string() if hasattr(derived.address, "to_string") else str(derived.address)
        up = UtxoProcessor(account, coinbase_maturity=d.consensus.params.coinbase_maturity)
        up.track_new_address(derived)
        events = []
        up.add_listener(events.append)

        client = WrpcClient(d.wrpc_server.address)
        client.subscribe("utxos-changed", [addr_str])
        client.subscribe("virtual-daa-score-changed")

        # mine TO the wallet address via RPC; the wallet consumes only the
        # notification stream from here on.  A block's coinbase reaches the
        # UTXO set when a LATER chain block accepts it and the final event
        # can still be in flight when the stream drains, so mine 5 and
        # require at least 3 streamed coinbases.
        for _ in range(5):
            t = client.call("getBlockTemplate", {"payAddress": addr_str})
            client.call("submitBlockByTemplateHash", {"hash": t["block_hash"]})
            d.mining.template_cache.clear()

        subsidy = d.consensus.coinbase_manager.calc_block_subsidy(1)
        deadline_events = 40
        while deadline_events and up.balance().total < 3 * subsidy:
            try:
                event, data = client.next_notification(timeout=10)
            except Exception:
                break
            up.feed_wire_notification(event, data)
            deadline_events -= 1
        bal = up.balance()
        assert bal.total >= 3 * subsidy  # at least three coinbases
        assert any(e.type == WalletEventType.BALANCE for e in events)
        # zero balance RPCs were needed; the index can only be AHEAD of the
        # stream (a final event may still be in flight)
        assert d.utxoindex.get_balance_by_script(derived.spk.script) >= bal.total
    finally:
        if client is not None:
            client.close()
        d.stop()


def test_budget_commit_input_mass_matches_consensus():
    """v1 inputs carry compute budgets; the wallet charges them exactly as
    consensus does (GRAMS_PER_COMPUTE_BUDGET_UNIT per unit) instead of the
    reference's unpriced TODO — a wallet must never under-price a spend."""
    from kaspa_tpu.consensus.mass import GRAMS_PER_COMPUTE_BUDGET_UNIT

    params = simnet_params(bps=2)
    mc = wm.WalletMassCalculator(params)
    inp = TransactionInput(
        TransactionOutpoint(bytes(32), 0), b"", 0, ComputeCommit.budget(100)
    )
    got = mc.calc_compute_mass_for_input(inp, tx_version=1)
    assert got == 100 * GRAMS_PER_COMPUTE_BUDGET_UNIT + 52  # + serialized size
    # budget(0) still prices to the serialized-size term only, not sigops
    inp0 = TransactionInput(
        TransactionOutpoint(bytes(32), 0), b"", 0, ComputeCommit.budget(0)
    )
    assert mc.calc_compute_mass_for_input(inp0, tx_version=1) == 52
