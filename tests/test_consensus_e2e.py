"""End-to-end consensus slice: simulate a DAG, replay it, verify state.

The python equivalent of the reference's simpa-based integration testing
(simpa/src/main.rs:327-345): build a multi-miner DAG with real signed
transactions, then replay into a fresh consensus and require identical
sink/DAA/UTXO outcomes.  Also covers adversarial block rejection.
"""

import pytest

from kaspa_tpu.consensus.consensus import Consensus, RuleError
from kaspa_tpu.consensus.params import simnet_params
from kaspa_tpu.consensus.processes.coinbase import MinerData
from kaspa_tpu.sim.simulator import SimConfig, replay, simulate
from kaspa_tpu.txscript import standard

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def sim_result():
    cfg = SimConfig(bps=2, delay=1.0, num_miners=3, num_blocks=30, txs_per_block=2, seed=11)
    return simulate(cfg)


def test_simulation_produces_merging_dag(sim_result):
    # with delay ~2x block interval, some blocks must have multi-parent merges
    multi_parent = sum(1 for b in sim_result.blocks if len(b.header.direct_parents()) > 1)
    assert multi_parent > 0
    assert sim_result.total_txs > 0


def test_replay_reaches_identical_state(sim_result):
    elapsed, fresh = replay(sim_result)
    assert fresh.sink() == sim_result.sink
    assert fresh.get_virtual_daa_score() == sim_result.virtual_daa_score


def test_tampered_block_rejected(sim_result):
    fresh = Consensus(sim_result.params)
    blocks = sim_result.blocks
    for block in blocks[:-1]:
        fresh.validate_and_insert_block(block)
    bad = blocks[-1]
    # tamper: flip a byte in the utxo commitment
    from dataclasses import replace

    hdr = bad.header
    tampered = replace(hdr, utxo_commitment=bytes([hdr.utxo_commitment[0] ^ 1]) + hdr.utxo_commitment[1:])
    tampered._hash_cache = None
    from kaspa_tpu.consensus.model.block import Block

    tb = Block(tampered, bad.transactions)
    # merkle still ok, header checks ok; chain verification must disqualify it
    status = fresh.validate_and_insert_block(tb)
    assert status in ("disqualified", "utxo_pending")
    if status == "utxo_pending":
        # it wasn't on the selected chain; force qualification attempt
        ok = fresh._ensure_chain_utxo_valid(tb.hash)
        assert not ok


def test_wrong_difficulty_bits_rejected(sim_result):
    fresh = Consensus(sim_result.params)
    for block in sim_result.blocks[:5]:
        fresh.validate_and_insert_block(block)
    nxt = sim_result.blocks[5]
    from dataclasses import replace

    hdr = replace(nxt.header, bits=nxt.header.bits + 1)
    hdr._hash_cache = None
    from kaspa_tpu.consensus.model.block import Block

    with pytest.raises(RuleError, match="difficulty bits"):
        fresh.validate_and_insert_block(Block(hdr, nxt.transactions))


def test_corrupt_signature_rejected():
    """A block containing a tx with a corrupted signature must be disqualified."""
    cfg = SimConfig(bps=2, delay=1.0, num_miners=2, num_blocks=24, txs_per_block=2, seed=13)
    res = simulate(cfg)
    tx_block_idx = next(i for i, b in enumerate(res.blocks) if len(b.transactions) > 1)
    fresh = Consensus(res.params)
    for block in res.blocks[:tx_block_idx]:
        fresh.validate_and_insert_block(block)
    victim = res.blocks[tx_block_idx]
    tx = victim.transactions[1]
    sig = bytearray(tx.inputs[0].signature_script)
    sig[10] ^= 1
    tx.inputs[0].signature_script = bytes(sig)
    tx._id_cache = None
    # merkle root no longer matches -> body rejection
    with pytest.raises(RuleError, match="merkle"):
        fresh.validate_and_insert_block(victim)
    # rebuild merkle to sneak it past the body stage: then chain must disqualify
    from dataclasses import replace

    from kaspa_tpu.consensus.model.block import Block
    from kaspa_tpu.crypto import merkle as mk

    hdr = replace(victim.header, hash_merkle_root=mk.calc_hash_merkle_root(victim.transactions))
    hdr._hash_cache = None
    fixed = Block(hdr, victim.transactions)
    status = fresh.validate_and_insert_block(fixed)
    if status == "utxo_pending":
        assert not fresh._ensure_chain_utxo_valid(fixed.hash)
    else:
        assert status == "disqualified"
