// kaspa-tpu native allocator: size-classed slab arena for the KV index.
//
// The reference ships kaspa-alloc (mimalloc as the global allocator +
// activation hooks) because the node's hot allocation path — millions of
// small key/node allocations in the store layer — dominates allocator
// behavior.  Here the same role is played where it matters in THIS
// runtime: the native engine's resident structures (map nodes + key
// bytes) allocate from size-class freelists carved out of 64 KiB slabs,
// mimalloc's small-object strategy in miniature:
//
// - size classes in 16-byte steps up to 512 bytes (beyond that, malloc);
// - per-class freelists, O(1) alloc/free, no per-object headers;
// - slabs are never returned to the OS while the store lives (freed
//   objects recycle within their class), eliminating heap churn and
//   fragmentation for the long-running node process;
// - stats (slab count, bytes reserved/in-use) surface through the C ABI
//   into the python metrics snapshot (kaspa-alloc's visibility story).

#pragma once

#include <cstddef>
#include <cstdint>
#include <cstdlib>
#include <vector>

namespace kvarena {

constexpr size_t kSlabBytes = 64 * 1024;
constexpr size_t kStep = 16;
constexpr size_t kMaxSmall = 512;
constexpr size_t kNumClasses = kMaxSmall / kStep;  // 32 classes

struct Stats {
  uint64_t slabs = 0;
  uint64_t reserved_bytes = 0;
  uint64_t in_use_bytes = 0;
  uint64_t large_allocs = 0;  // fell through to malloc
};

class SlabArena {
 public:
  SlabArena() : free_lists_(kNumClasses, nullptr), bump_(nullptr), bump_left_(0) {}

  ~SlabArena() {
    for (void* s : slabs_) std::free(s);
  }

  SlabArena(const SlabArena&) = delete;
  SlabArena& operator=(const SlabArena&) = delete;

  void* alloc(size_t n) {
    if (n == 0) n = 1;
    if (n > kMaxSmall) {
      stats_.large_allocs++;
      return std::malloc(n);
    }
    size_t cls = (n + kStep - 1) / kStep - 1;
    size_t sz = (cls + 1) * kStep;
    stats_.in_use_bytes += sz;
    if (free_lists_[cls]) {
      void* p = free_lists_[cls];
      free_lists_[cls] = *reinterpret_cast<void**>(p);
      return p;
    }
    if (bump_left_ < sz) {
      void* slab = std::malloc(kSlabBytes);
      slabs_.push_back(slab);
      stats_.slabs++;
      stats_.reserved_bytes += kSlabBytes;
      bump_ = static_cast<char*>(slab);
      bump_left_ = kSlabBytes;
    }
    void* p = bump_;
    bump_ += sz;
    bump_left_ -= sz;
    return p;
  }

  void free(void* p, size_t n) {
    if (p == nullptr) return;
    if (n == 0) n = 1;
    if (n > kMaxSmall) {
      std::free(p);
      return;
    }
    size_t cls = (n + kStep - 1) / kStep - 1;
    stats_.in_use_bytes -= (cls + 1) * kStep;
    *reinterpret_cast<void**>(p) = free_lists_[cls];
    free_lists_[cls] = p;
  }

  const Stats& stats() const { return stats_; }

 private:
  std::vector<void*> slabs_;
  std::vector<void*> free_lists_;
  char* bump_;
  size_t bump_left_;
  Stats stats_;
};

// std-compatible allocator adapter binding a container to one SlabArena.
template <typename T>
struct ArenaAllocator {
  using value_type = T;

  SlabArena* arena;

  explicit ArenaAllocator(SlabArena* a) : arena(a) {}
  template <typename U>
  ArenaAllocator(const ArenaAllocator<U>& other) : arena(other.arena) {}

  T* allocate(size_t n) { return static_cast<T*>(arena->alloc(n * sizeof(T))); }
  void deallocate(T* p, size_t n) { arena->free(p, n * sizeof(T)); }

  template <typename U>
  bool operator==(const ArenaAllocator<U>& o) const {
    return arena == o.arena;
  }
  template <typename U>
  bool operator!=(const ArenaAllocator<U>& o) const {
    return arena != o.arena;
  }
};

}  // namespace kvarena
