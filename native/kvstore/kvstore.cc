// kaspa-tpu native storage engine: persistent KV store with atomic batches.
//
// The TPU-native counterpart of the reference's RocksDB-backed store layer
// (database/src/: ConnBuilder/DB/CachedDbAccess/BatchDbWriter).  Design:
// a crash-consistent append-only log with CRC-framed record batches plus an
// in-memory hash index, compacted on demand.  Write batches are atomic: a
// batch frame is only honored on recovery if its trailer CRC matches —
// mirroring the WriteBatch atomicity the reference's crash-consistency
// story depends on (SURVEY.md §5 failure detection/recovery).
//
// C ABI for ctypes; all functions return 0 on success, negative on error.

#include <cstdint>
#include <unistd.h>
#include <cstdio>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

uint32_t crc32(const uint8_t* data, size_t len) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = c & 1 ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++) c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Slice {
  std::string data;
};

// log record: u8 op (0=put, 1=del), u32 klen, u32 vlen, key, value
// batch frame: magic "KBAT", u32 payload_len, payload, u32 crc(payload)
constexpr char kMagic[4] = {'K', 'B', 'A', 'T'};

struct Store {
  std::string path;
  FILE* log = nullptr;
  std::unordered_map<std::string, std::string> index;
  std::string pending;  // current batch payload under construction
  bool in_batch = false;

  int replay() {
    FILE* f = fopen(path.c_str(), "rb");
    if (!f) return 0;  // fresh store
    std::vector<uint8_t> buf;
    char magic[4];
    long valid_end = 0;
    while (fread(magic, 1, 4, f) == 4) {
      if (memcmp(magic, kMagic, 4) != 0) break;
      uint32_t plen;
      if (fread(&plen, 4, 1, f) != 1) break;
      buf.resize(plen);
      if (plen && fread(buf.data(), 1, plen, f) != plen) break;
      uint32_t crc_stored;
      if (fread(&crc_stored, 4, 1, f) != 1) break;
      if (crc32(buf.data(), plen) != crc_stored) break;  // torn batch: stop
      // apply payload
      size_t off = 0;
      bool ok = true;
      while (off < plen) {
        if (off + 9 > plen) { ok = false; break; }
        uint8_t op = buf[off];
        uint32_t klen, vlen;
        memcpy(&klen, &buf[off + 1], 4);
        memcpy(&vlen, &buf[off + 5], 4);
        off += 9;
        if (off + klen + vlen > plen) { ok = false; break; }
        std::string key(reinterpret_cast<char*>(&buf[off]), klen);
        off += klen;
        if (op == 0) {
          index[key] = std::string(reinterpret_cast<char*>(&buf[off]), vlen);
        } else {
          index.erase(key);
        }
        off += vlen;
      }
      if (!ok) break;
      valid_end = ftell(f);
    }
    fclose(f);
    // truncate any torn tail so the next append starts clean
    if (valid_end >= 0) {
      FILE* t = fopen(path.c_str(), "rb+");
      if (t) {
#if defined(_WIN32)
        (void)t;
#else
        if (ftruncate(fileno(t), valid_end) != 0) { /* best effort */ }
#endif
        fclose(t);
      }
    }
    return 0;
  }

  void append_record(uint8_t op, const char* key, uint32_t klen, const char* val, uint32_t vlen) {
    size_t base = pending.size();
    pending.resize(base + 9 + klen + vlen);
    char* p = &pending[base];
    p[0] = static_cast<char>(op);
    memcpy(p + 1, &klen, 4);
    memcpy(p + 5, &vlen, 4);
    memcpy(p + 9, key, klen);
    if (vlen) memcpy(p + 9 + klen, val, vlen);
  }

  int flush_batch() {
    if (pending.empty()) return 0;
    uint32_t plen = static_cast<uint32_t>(pending.size());
    uint32_t crc = crc32(reinterpret_cast<const uint8_t*>(pending.data()), plen);
    if (fwrite(kMagic, 1, 4, log) != 4) return -10;
    if (fwrite(&plen, 4, 1, log) != 1) return -10;
    if (fwrite(pending.data(), 1, plen, log) != plen) return -10;
    if (fwrite(&crc, 4, 1, log) != 1) return -10;
    if (fflush(log) != 0) return -10;
    pending.clear();
    return 0;
  }
};

}  // namespace

extern "C" {

void* kv_open(const char* path) {
  Store* s = new Store();
  s->path = path;
  if (s->replay() != 0) {
    delete s;
    return nullptr;
  }
  s->log = fopen(path, "ab");
  if (!s->log) {
    delete s;
    return nullptr;
  }
  return s;
}

void kv_close(void* h) {
  Store* s = static_cast<Store*>(h);
  if (s->log) fclose(s->log);
  delete s;
}

int kv_put(void* h, const char* key, uint32_t klen, const char* val, uint32_t vlen) {
  Store* s = static_cast<Store*>(h);
  s->append_record(0, key, klen, val, vlen);
  s->index[std::string(key, klen)] = std::string(val, vlen);
  if (!s->in_batch) return s->flush_batch();
  return 0;
}

int kv_delete(void* h, const char* key, uint32_t klen) {
  Store* s = static_cast<Store*>(h);
  s->append_record(1, key, klen, nullptr, 0);
  s->index.erase(std::string(key, klen));
  if (!s->in_batch) return s->flush_batch();
  return 0;
}

// returns value length, or -1 if missing; copies up to cap bytes into out
int64_t kv_get(void* h, const char* key, uint32_t klen, char* out, uint32_t cap) {
  Store* s = static_cast<Store*>(h);
  auto it = s->index.find(std::string(key, klen));
  if (it == s->index.end()) return -1;
  uint32_t n = static_cast<uint32_t>(it->second.size());
  if (out && cap) memcpy(out, it->second.data(), n < cap ? n : cap);
  return n;
}

int kv_batch_begin(void* h) {
  Store* s = static_cast<Store*>(h);
  if (s->in_batch) return -20;
  s->in_batch = true;
  return 0;
}

int kv_batch_commit(void* h) {
  Store* s = static_cast<Store*>(h);
  if (!s->in_batch) return -21;
  s->in_batch = false;
  return s->flush_batch();
}

uint64_t kv_len(void* h) { return static_cast<Store*>(h)->index.size(); }

// iteration: caller provides a callback
typedef void (*kv_iter_cb)(const char* key, uint32_t klen, const char* val, uint32_t vlen, void* ctx);

void kv_iterate(void* h, kv_iter_cb cb, void* ctx) {
  Store* s = static_cast<Store*>(h);
  for (const auto& kv : s->index) {
    cb(kv.first.data(), static_cast<uint32_t>(kv.first.size()), kv.second.data(),
       static_cast<uint32_t>(kv.second.size()), ctx);
  }
}

// compaction: rewrite the log with only live records (one atomic batch)
int kv_compact(void* h) {
  Store* s = static_cast<Store*>(h);
  if (s->in_batch) return -22;
  std::string tmp = s->path + ".compact";
  FILE* old = s->log;
  FILE* nf = fopen(tmp.c_str(), "wb");
  if (!nf) return -30;
  Store out;
  out.log = nf;
  for (const auto& kv : s->index) {
    out.append_record(0, kv.first.data(), static_cast<uint32_t>(kv.first.size()), kv.second.data(),
                      static_cast<uint32_t>(kv.second.size()));
  }
  if (out.flush_batch() != 0) {
    fclose(nf);
    remove(tmp.c_str());
    return -31;
  }
  fclose(nf);
  fclose(old);
  if (rename(tmp.c_str(), s->path.c_str()) != 0) return -32;
  s->log = fopen(s->path.c_str(), "ab");
  return s->log ? 0 : -33;
}

}  // extern "C"
