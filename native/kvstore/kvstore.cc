// kaspa-tpu native storage engine: persistent KV store with atomic batches.
//
// The TPU-native counterpart of the reference's RocksDB-backed store layer
// (database/src/: ConnBuilder/DB/CachedDbAccess/BatchDbWriter).  Design:
// a crash-consistent append-only log with CRC-framed record batches plus an
// in-memory ORDERED index of key -> (file offset, length); values live on
// disk and are pread() on demand, so resident memory is O(keys), not
// O(history bytes) — the engine-level half of the reference's
// memory-bounded storage story (database/src/access.rs CachedDbAccess
// caches bounded decodes over a disk-resident column).  The ordered index
// additionally serves prefix scans (RocksDB prefix-iterator equivalent,
// database/src/registry.rs prefixed columns).
//
// Write batches are atomic: a batch frame is only honored on recovery if
// its trailer CRC matches — mirroring the WriteBatch atomicity the
// reference's crash-consistency story depends on (SURVEY.md §5 failure
// detection/recovery).
//
// C ABI for ctypes; all functions return 0 on success, negative on error.

#include <cstdint>
#include <unistd.h>
#include <fcntl.h>
#include <cstdio>
#include <cstring>
#include <memory>
#include <string>
#include <string_view>
#include <map>
#include <vector>

#include "arena.h"

namespace {

uint32_t crc32(const uint8_t* data, size_t len) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = c & 1 ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++) c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// log record: u8 op (0=put, 1=del), u32 klen, u32 vlen, key, value
// batch frame: magic "KBAT", u32 payload_len, payload, u32 crc(payload)
constexpr char kMagic[4] = {'K', 'B', 'A', 'T'};

struct ValueRef {
  uint64_t off;   // file offset of the value bytes (or offset into pending)
  uint32_t len;
  bool pending;   // true: value not yet flushed, read from Store::pending
};

struct Store {
  std::string path;
  FILE* log = nullptr;
  int read_fd = -1;            // separate fd for pread (no seek races with appends)
  uint64_t file_end = 0;       // durable end of log (next batch frame starts here)
  // resident index: keys interned in the slab arena, rb-tree nodes
  // allocated from it too (the kaspa-alloc role for this runtime)
  std::unique_ptr<kvarena::SlabArena> arena = std::make_unique<kvarena::SlabArena>();
  using IndexAlloc = kvarena::ArenaAllocator<std::pair<const std::string_view, ValueRef>>;
  using Index = std::map<std::string_view, ValueRef, std::less<>, IndexAlloc>;
  Index index{std::less<>(), IndexAlloc(arena.get())};
  std::string pending;         // current batch payload under construction
  bool in_batch = false;

  std::string_view intern_key(const char* k, uint32_t klen) {
    char* p = static_cast<char*>(arena->alloc(klen));
    memcpy(p, k, klen);
    return std::string_view(p, klen);
  }

  void upsert(const char* k, uint32_t klen, ValueRef ref) {
    std::string_view key(k, klen);
    auto it = index.find(key);
    if (it != index.end()) {
      it->second = ref;
    } else {
      index.emplace(intern_key(k, klen), ref);
    }
  }

  void erase_key(const char* k, uint32_t klen) {
    std::string_view key(k, klen);
    auto it = index.find(key);
    if (it != index.end()) {
      std::string_view stored = it->first;
      index.erase(it);
      arena->free(const_cast<char*>(stored.data()), stored.size());
    }
  }

  int replay() {
    FILE* f = fopen(path.c_str(), "rb");
    if (!f) return 0;  // fresh store
    std::vector<uint8_t> buf;
    char magic[4];
    long frame_start = 0;
    long valid_end = 0;
    while (fread(magic, 1, 4, f) == 4) {
      if (memcmp(magic, kMagic, 4) != 0) break;
      uint32_t plen;
      if (fread(&plen, 4, 1, f) != 1) break;
      buf.resize(plen);
      if (plen && fread(buf.data(), 1, plen, f) != plen) break;
      uint32_t crc_stored;
      if (fread(&crc_stored, 4, 1, f) != 1) break;
      if (crc32(buf.data(), plen) != crc_stored) break;  // torn batch: stop
      // apply payload; record value offsets relative to the frame payload
      uint64_t payload_base = static_cast<uint64_t>(frame_start) + 8;
      size_t off = 0;
      bool ok = true;
      while (off < plen) {
        if (off + 9 > plen) { ok = false; break; }
        uint8_t op = buf[off];
        uint32_t klen, vlen;
        memcpy(&klen, &buf[off + 1], 4);
        memcpy(&vlen, &buf[off + 5], 4);
        off += 9;
        if (off + klen + vlen > plen) { ok = false; break; }
        const char* kptr = reinterpret_cast<char*>(&buf[off]);
        off += klen;
        if (op == 0) {
          upsert(kptr, klen, ValueRef{payload_base + off, vlen, false});
        } else {
          erase_key(kptr, klen);
        }
        off += vlen;
      }
      if (!ok) break;
      valid_end = ftell(f);
      frame_start = valid_end;
    }
    fclose(f);
    // truncate any torn tail so the next append starts clean
    if (valid_end >= 0) {
      FILE* t = fopen(path.c_str(), "rb+");
      if (t) {
#if defined(_WIN32)
        (void)t;
#else
        if (ftruncate(fileno(t), valid_end) != 0) { /* best effort */ }
#endif
        fclose(t);
      }
    }
    file_end = static_cast<uint64_t>(valid_end);
    return 0;
  }

  // appends one record to the pending payload and indexes its value as
  // pending (readable from the buffer until flush converts it to a file ref)
  void append_record(uint8_t op, const char* key, uint32_t klen, const char* val, uint32_t vlen) {
    size_t base = pending.size();
    pending.resize(base + 9 + klen + vlen);
    char* p = &pending[base];
    p[0] = static_cast<char>(op);
    memcpy(p + 1, &klen, 4);
    memcpy(p + 5, &vlen, 4);
    memcpy(p + 9, key, klen);
    if (vlen) memcpy(p + 9 + klen, val, vlen);
    if (op == 0) {
      upsert(key, klen, ValueRef{base + 9 + klen, vlen, true});
    } else {
      erase_key(key, klen);
    }
  }

  int flush_batch() {
    if (pending.empty()) return 0;
    uint32_t plen = static_cast<uint32_t>(pending.size());
    uint32_t crc = crc32(reinterpret_cast<const uint8_t*>(pending.data()), plen);
    if (fwrite(kMagic, 1, 4, log) != 4) return -10;
    if (fwrite(&plen, 4, 1, log) != 1) return -10;
    if (fwrite(pending.data(), 1, plen, log) != plen) return -10;
    if (fwrite(&crc, 4, 1, log) != 1) return -10;
    if (fflush(log) != 0) return -10;
    // pending value refs become file refs: payload starts at file_end + 8
    uint64_t payload_base = file_end + 8;
    size_t off = 0;
    while (off < plen) {
      uint8_t op = static_cast<uint8_t>(pending[off]);
      uint32_t klen, vlen;
      memcpy(&klen, &pending[off + 1], 4);
      memcpy(&vlen, &pending[off + 5], 4);
      off += 9;
      std::string_view key(&pending[off], klen);
      off += klen;
      if (op == 0) {
        auto it = index.find(key);
        // only rebind if this record is the one the index points at
        // (a later record in the same batch wins; deletes already erased)
        if (it != index.end() && it->second.pending && it->second.off == off) {
          it->second = ValueRef{payload_base + off, vlen, false};
        }
      }
      off += vlen;
    }
    file_end += 8ull + plen + 4ull;
    pending.clear();
    return 0;
  }

  // reads a value (flushed: pread from log; pending: from the buffer)
  bool read_value(const ValueRef& ref, char* out, uint32_t cap) const {
    uint32_t n = ref.len < cap ? ref.len : cap;
    if (!n) return true;
    if (ref.pending) {
      memcpy(out, pending.data() + ref.off, n);
      return true;
    }
    ssize_t got = pread(read_fd, out, n, static_cast<off_t>(ref.off));
    return got == static_cast<ssize_t>(n);
  }
};

}  // namespace

extern "C" {

void* kv_open(const char* path) {
  Store* s = new Store();
  s->path = path;
  if (s->replay() != 0) {
    delete s;
    return nullptr;
  }
  s->log = fopen(path, "ab");
  if (!s->log) {
    delete s;
    return nullptr;
  }
  s->read_fd = open(path, O_RDONLY);
  if (s->read_fd < 0) {
    fclose(s->log);
    delete s;
    return nullptr;
  }
  return s;
}

void kv_close(void* h) {
  Store* s = static_cast<Store*>(h);
  if (s->log) fclose(s->log);
  if (s->read_fd >= 0) close(s->read_fd);
  delete s;
}

int kv_put(void* h, const char* key, uint32_t klen, const char* val, uint32_t vlen) {
  Store* s = static_cast<Store*>(h);
  s->append_record(0, key, klen, val, vlen);
  if (!s->in_batch) return s->flush_batch();
  return 0;
}

int kv_delete(void* h, const char* key, uint32_t klen) {
  Store* s = static_cast<Store*>(h);
  s->append_record(1, key, klen, nullptr, 0);
  if (!s->in_batch) return s->flush_batch();
  return 0;
}

// returns value length, or -1 if missing; copies up to cap bytes into out
int64_t kv_get(void* h, const char* key, uint32_t klen, char* out, uint32_t cap) {
  Store* s = static_cast<Store*>(h);
  auto it = s->index.find(std::string_view(key, klen));
  if (it == s->index.end()) return -1;
  if (out && cap) {
    if (!s->read_value(it->second, out, cap)) return -2;
  }
  return it->second.len;
}

int kv_batch_begin(void* h) {
  Store* s = static_cast<Store*>(h);
  if (s->in_batch) return -20;
  s->in_batch = true;
  return 0;
}

int kv_batch_commit(void* h) {
  Store* s = static_cast<Store*>(h);
  if (!s->in_batch) return -21;
  s->in_batch = false;
  return s->flush_batch();
}

uint64_t kv_len(void* h) { return static_cast<Store*>(h)->index.size(); }

// iteration: caller provides a callback; values are read from disk per entry
typedef void (*kv_iter_cb)(const char* key, uint32_t klen, const char* val, uint32_t vlen, void* ctx);

void kv_iterate(void* h, kv_iter_cb cb, void* ctx) {
  Store* s = static_cast<Store*>(h);
  std::string buf;
  for (const auto& kv : s->index) {
    buf.resize(kv.second.len);
    if (kv.second.len && !s->read_value(kv.second, &buf[0], kv.second.len)) continue;
    cb(kv.first.data(), static_cast<uint32_t>(kv.first.size()), buf.data(), kv.second.len, ctx);
  }
}

// ordered prefix scan over [prefix, prefix+1): the engine-side primitive
// behind prefixed-store iteration.  want_values=0 passes vlen but a null
// value pointer — a keys-only scan touches no disk at all.
void kv_iterate_prefix(void* h, const char* prefix, uint32_t plen, int want_values, kv_iter_cb cb,
                       void* ctx) {
  Store* s = static_cast<Store*>(h);
  std::string_view pfx(prefix, plen);
  std::string buf;
  for (auto it = s->index.lower_bound(pfx); it != s->index.end(); ++it) {
    if (it->first.substr(0, plen) != pfx) break;
    if (want_values) {
      buf.resize(it->second.len);
      if (it->second.len && !s->read_value(it->second, &buf[0], it->second.len)) continue;
      cb(it->first.data(), static_cast<uint32_t>(it->first.size()), buf.data(), it->second.len, ctx);
    } else {
      cb(it->first.data(), static_cast<uint32_t>(it->first.size()), nullptr, it->second.len, ctx);
    }
  }
}

uint64_t kv_count_prefix(void* h, const char* prefix, uint32_t plen) {
  Store* s = static_cast<Store*>(h);
  std::string_view pfx(prefix, plen);
  uint64_t n = 0;
  for (auto it = s->index.lower_bound(pfx); it != s->index.end(); ++it) {
    if (it->first.substr(0, plen) != pfx) break;
    n++;
  }
  return n;
}

// arena stats: [slabs, reserved_bytes, in_use_bytes, large_allocs]
void kv_mem_stats(void* h, uint64_t* out4) {
  const kvarena::Stats& st = static_cast<Store*>(h)->arena->stats();
  out4[0] = st.slabs;
  out4[1] = st.reserved_bytes;
  out4[2] = st.in_use_bytes;
  out4[3] = st.large_allocs;
}

// compaction: rewrite the log with only live records (one atomic batch)
int kv_compact(void* h) {
  Store* s = static_cast<Store*>(h);
  if (s->in_batch) return -22;
  std::string tmp = s->path + ".compact";
  FILE* nf = fopen(tmp.c_str(), "wb");
  if (!nf) return -30;
  // one frame holding every live record; new value offsets recorded in
  // index order so a second pass can rebind without touching keys/arena
  std::string payload;
  std::vector<uint64_t> new_offsets;
  new_offsets.reserve(s->index.size());
  std::string buf;
  for (const auto& kv : s->index) {
    buf.resize(kv.second.len);
    if (kv.second.len && !s->read_value(kv.second, &buf[0], kv.second.len)) {
      fclose(nf);
      remove(tmp.c_str());
      return -34;
    }
    uint32_t klen = static_cast<uint32_t>(kv.first.size());
    uint32_t vlen = kv.second.len;
    size_t base = payload.size();
    payload.resize(base + 9 + klen + vlen);
    char* p = &payload[base];
    p[0] = 0;
    memcpy(p + 1, &klen, 4);
    memcpy(p + 5, &vlen, 4);
    memcpy(p + 9, kv.first.data(), klen);
    if (vlen) memcpy(p + 9 + klen, buf.data(), vlen);
    new_offsets.push_back(8ull + base + 9 + klen);  // frame header is 8 bytes
  }
  uint32_t plen = static_cast<uint32_t>(payload.size());
  uint32_t crc = crc32(reinterpret_cast<const uint8_t*>(payload.data()), plen);
  bool wrote = fwrite(kMagic, 1, 4, nf) == 4 && fwrite(&plen, 4, 1, nf) == 1 &&
               (plen == 0 || fwrite(payload.data(), 1, plen, nf) == plen) &&
               fwrite(&crc, 4, 1, nf) == 1 && fflush(nf) == 0;
  if (!wrote) {
    fclose(nf);
    remove(tmp.c_str());
    return -31;
  }
  fclose(nf);
  // open the compacted file's handles FIRST: the store's live handles are
  // only swapped once every step succeeded, so any failure leaves the store
  // fully usable on the old log
  FILE* new_log = fopen(tmp.c_str(), "ab");
  int new_fd = open(tmp.c_str(), O_RDONLY);
  if (!new_log || new_fd < 0) {
    if (new_log) fclose(new_log);
    if (new_fd >= 0) close(new_fd);
    remove(tmp.c_str());
    return -33;
  }
  if (rename(tmp.c_str(), s->path.c_str()) != 0) {
    fclose(new_log);
    close(new_fd);
    remove(tmp.c_str());
    return -32;
  }
  fclose(s->log);
  close(s->read_fd);
  s->log = new_log;
  s->read_fd = new_fd;
  // rebind the live index's value refs to the compacted file's offsets
  size_t i = 0;
  for (auto& kv : s->index) {
    kv.second = ValueRef{new_offsets[i++], kv.second.len, false};
  }
  s->file_end = 8ull + plen + 4ull;
  return 0;
}

}  // extern "C"
