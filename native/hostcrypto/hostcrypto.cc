// Host crypto hot loops: ChaCha20 keystream expansion for muhash elements.
//
// The reference expands each muhash element with rand_chacha
// (crypto/muhash/src/lib.rs:152-168) in native Rust; this provides the
// equivalent native path for the framework's host side (djb variant:
// 64-bit counter from 0, nonce 0), batched over N keys.
//
// C ABI for ctypes.

#include <cstdint>
#include <cstring>

namespace {

inline uint32_t rotl(uint32_t x, int n) { return (x << n) | (x >> (32 - n)); }

#define QR(a, b, c, d)                                                                                   \
  a += b; d ^= a; d = rotl(d, 16);                                                                       \
  c += d; b ^= c; b = rotl(b, 12);                                                                       \
  a += b; d ^= a; d = rotl(d, 8);                                                                        \
  c += d; b ^= c; b = rotl(b, 7);

void chacha_block(const uint32_t key[8], uint64_t counter, uint8_t out[64]) {
  uint32_t init[16] = {0x61707865u, 0x3320646eu, 0x79622d32u, 0x6b206574u,
                       key[0], key[1], key[2], key[3], key[4], key[5], key[6], key[7],
                       static_cast<uint32_t>(counter), static_cast<uint32_t>(counter >> 32), 0u, 0u};
  uint32_t x[16];
  memcpy(x, init, sizeof(x));
  for (int i = 0; i < 10; i++) {
    QR(x[0], x[4], x[8], x[12])
    QR(x[1], x[5], x[9], x[13])
    QR(x[2], x[6], x[10], x[14])
    QR(x[3], x[7], x[11], x[15])
    QR(x[0], x[5], x[10], x[15])
    QR(x[1], x[6], x[11], x[12])
    QR(x[2], x[7], x[8], x[13])
    QR(x[3], x[4], x[9], x[14])
  }
  for (int i = 0; i < 16; i++) {
    uint32_t v = x[i] + init[i];
    out[4 * i + 0] = static_cast<uint8_t>(v);
    out[4 * i + 1] = static_cast<uint8_t>(v >> 8);
    out[4 * i + 2] = static_cast<uint8_t>(v >> 16);
    out[4 * i + 3] = static_cast<uint8_t>(v >> 24);
  }
}

}  // namespace

extern "C" {

// keys: n x 32 bytes (little-endian words); out: n x out_len bytes
void chacha20_keystream_batch(const uint8_t* keys, uint64_t n, uint8_t* out, uint64_t out_len) {
  uint64_t blocks = (out_len + 63) / 64;
  uint8_t buf[64];
  for (uint64_t i = 0; i < n; i++) {
    uint32_t key[8];
    memcpy(key, keys + i * 32, 32);
    uint8_t* dst = out + i * out_len;
    for (uint64_t b = 0; b < blocks; b++) {
      chacha_block(key, b, buf);
      uint64_t off = b * 64;
      uint64_t take = out_len - off < 64 ? out_len - off : 64;
      memcpy(dst + off, buf, take);
    }
  }
}

}  // extern "C"
