#!/usr/bin/env python
"""One-command round evidence: graftlint + fast-lane tests + sim replay
+ bench probe + multichip dryrun + mesh smoke + flight-recorder trace
+ chaos sustain.

Runs the repo's tier-1 fast lane, a short simulator replay, the bench
session probe, the sharded multichip dryrun (on every visible device,
forced-CPU), a `--mesh 8` sim smoke replay, the flight-recorder lane (a
traced 24-block pipelined replay whose dump must hold one connected
>=4-thread span tree per block with >= 90% critical-path attribution and
a valid Perfetto export, plus a tracing-off-within-2% overhead gate),
the hostile-load chaos sustain run (seeded fault schedule; the faulted
replay must converge to the bit-identical fault-free end state), the
device-supervision wedge drill (injected dispatch hangs + a compile
stall; watchdog requeue accounting + canary recovery, bit-identity
gated), the ingest lane (batched-vs-per-tx mempool-admission
identity plus a short tx-flood sustain; clean acceptance >= 0.99 and
zero lost tickets), and the overload lane (a tx-flood replay with the
adaptive brownout ramp; the controller must reach SATURATED, shed load
with zero lost tickets, hold cadence within 1.5x of nominal, and settle
back to NOMINAL), and the swarm lane (three real in-process nodes over
loopback sockets: partition/heal with a deep attacker reorg and a
late-join IBD, gated on fleet-wide bit-identity, fault-free match, zero
lost tickets and a relay-amplification budget), then writes a single
round-evidence JSON (ROUNDCHECK.json)
summarizing them — the artifact a driver round or a reviewer reads
instead of eight scrollback logs.

    python tools/roundcheck.py                     # everything
    python tools/roundcheck.py --only tier1        # just one section
    python tools/roundcheck.py --only sim --only fabric
    python tools/roundcheck.py --skip-bench        # no device probe
    python tools/roundcheck.py --skip-mesh         # no multichip/mesh lanes
    python tools/roundcheck.py --skip-obs          # no flight-recorder lane
    python tools/roundcheck.py --skip-chaos        # no fault-injection sustain
    python tools/roundcheck.py --skip-supervision  # no wedge drill
    python tools/roundcheck.py --skip-fabric       # no two-process fabric drill
    python tools/roundcheck.py --skip-ingest       # no tx-ingest admission lane
    python tools/roundcheck.py --skip-overload     # no brownout ramp drill
    python tools/roundcheck.py --skip-lint         # no graftlint static-analysis gate
    python tools/roundcheck.py --skip-serving_load # no 50k-subscriber latency observatory run
    python tools/roundcheck.py --skip-swarm        # no multi-node partition/heal swarm drill
    python tools/roundcheck.py --out my.json       # custom artifact path

``--only SECTION`` (repeatable, or comma-separated) runs exactly the
named sections and ignores the skip flags; section names are the keys in
ROUNDCHECK.json (tier1, sim, bench_probe, multichip, mesh_smoke,
dispatch, aggregate, serving, obs, tenbps, chaos, supervision,
fabric, ingest, overload, swarm).  Every section records its own
``wall_seconds`` in the artifact.

Exit code 0 iff every section that ran passed.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the tier1 section shells out to the pre-PR gate script so roundcheck
# and a bare `bash tools/ci_fastlane.sh` can never disagree on what
# "tier-1 green" means (fast-lane pytest + proto/borsh wire-freeze checks)
FASTLANE_CMD = ["bash", os.path.join(REPO_ROOT, "tools", "ci_fastlane.sh")]


def _utc() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def _run(cmd: list[str], timeout_s: float, env_extra: dict | None = None) -> dict:
    """Run one section command, capture tail + rc + wall time."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.update(env_extra or {})
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            cmd, cwd=REPO_ROOT, env=env, timeout=timeout_s,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        rc, out = proc.returncode, proc.stdout or ""
    except subprocess.TimeoutExpired as e:
        rc = -9
        out = (e.stdout.decode() if isinstance(e.stdout, bytes) else e.stdout or "") + "\n[roundcheck] TIMEOUT"
    return {
        "cmd": " ".join(cmd),
        "rc": rc,
        "seconds": round(time.monotonic() - t0, 1),
        "tail": out.strip().splitlines()[-12:],
    }


def _last_json_line(section: dict) -> dict | None:
    for line in reversed(section["tail"]):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _validate_flight(path: str) -> dict:
    """Schema + connectivity validation for a flight-recorder dump: every
    block trace must form a single connected span tree (exactly one root,
    zero orphan spans), cross >= 4 threads, and carry >= 90% critical-path
    attribution.  Returns the verdict + the aggregate top-3 stages."""
    with open(path) as f:
        doc = json.load(f)
    out: dict = {"path": path, "ok": False}
    if doc.get("format") != "kaspa-flight" or "traces" not in doc:
        out["error"] = "not a kaspa-flight dump"
        return out
    traces = doc["traces"]
    if not traces:
        out["error"] = "dump holds zero traces"
        return out
    bad_tree = bad_threads = bad_frac = 0
    thread_counts, fractions = [], []
    stage_ns: dict[str, float] = {}
    for t in traces:
        spans = t["spans"]
        ids = {s["span"] for s in spans}
        roots = [s for s in spans if s["parent"] not in ids]
        if len(roots) != 1 or roots[0]["name"] != "block":
            bad_tree += 1
        threads = {s["thread"] for s in spans}
        thread_counts.append(len(threads))
        if len(threads) < 4:
            bad_threads += 1
        cp = t.get("critical_path", {})
        frac = float(cp.get("fraction", 0.0))
        fractions.append(frac)
        if frac < 0.90:
            bad_frac += 1
        for name, ms in cp.get("stages_ms", {}).items():
            if name != "block":
                stage_ns[name] = stage_ns.get(name, 0.0) + ms
    top3 = sorted(stage_ns.items(), key=lambda kv: -kv[1])[:3]
    out.update(
        traces=len(traces),
        orphan_trees=bad_tree,
        under_4_threads=bad_threads,
        under_90pct_attribution=bad_frac,
        min_threads=min(thread_counts),
        min_fraction=round(min(fractions), 4),
        mean_fraction=round(sum(fractions) / len(fractions), 4),
        top_stages=[{"stage": n, "total_ms": round(ms, 2)} for n, ms in top3],
        ok=bad_tree == 0 and bad_threads == 0 and bad_frac == 0,
    )
    return out


def _validate_chrome(path: str) -> dict:
    """Minimal Chrome trace-event schema check on the exported Perfetto
    JSON: complete events carry ts/dur/pid/tid, flow events pair up."""
    with open(path) as f:
        doc = json.load(f)
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        return {"path": path, "ok": False, "error": "no traceEvents"}
    complete = flows_out = flows_in = malformed = 0
    for e in events:
        if not isinstance(e.get("pid"), int) or not isinstance(e.get("tid"), int):
            malformed += 1
            continue
        ph = e.get("ph")
        if ph == "X":
            complete += 1
            if "ts" not in e or "dur" not in e or "name" not in e:
                malformed += 1
        elif ph == "s":
            flows_out += 1
        elif ph == "f":
            flows_in += 1
    return {
        "path": path,
        "events": len(events),
        "complete_spans": complete,
        "flow_edges": flows_out,
        "malformed": malformed,
        "ok": malformed == 0 and complete > 0 and flows_out == flows_in,
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--skip-tests", action="store_true", help="skip the tier-1 fast lane")
    ap.add_argument("--skip-sim", action="store_true", help="skip the simulator replay")
    ap.add_argument("--skip-bench", action="store_true", help="skip the bench device probe")
    ap.add_argument("--skip-mesh", action="store_true", help="skip the multichip dryrun + mesh smoke replay")
    ap.add_argument("--skip-chaos", action="store_true", help="skip the hostile-load chaos sustain run")
    ap.add_argument("--skip-dispatch", action="store_true", help="skip the coalesced-dispatch throughput lane")
    ap.add_argument("--skip-aggregate", action="store_true", help="skip the aggregated RLC verify lane")
    ap.add_argument("--skip-serving", action="store_true", help="skip the serving-tier dual-encoding + kill -9 lane")
    ap.add_argument("--skip-obs", action="store_true", help="skip the flight-recorder traced-replay lane")
    ap.add_argument("--skip-tenbps", action="store_true", help="skip the 10-BPS speculative-pipeline lane")
    ap.add_argument("--skip-supervision", action="store_true", help="skip the device-supervision wedge drill")
    ap.add_argument("--skip-fabric", action="store_true", help="skip the two-process verify-fabric drill")
    ap.add_argument("--skip-ingest", action="store_true", help="skip the tx-ingest admission lane")
    ap.add_argument("--skip-overload", action="store_true", help="skip the brownout ramp drill")
    ap.add_argument("--skip-swarm", action="store_true", help="skip the multi-node swarm partition/heal drill")
    ap.add_argument("--skip-lint", action="store_true", help="skip the graftlint static-analysis gate")
    ap.add_argument("--skip-serving_load", action="store_true",
                    help="skip the 50k-virtual-subscriber serving latency observatory run")
    ap.add_argument("--serving-load-subscribers", type=int, default=50_000,
                    help="final population for the serving_load section")
    ap.add_argument(
        "--only", action="append", default=None, metavar="SECTION",
        help="run only the named section(s); repeatable or comma-separated, "
        "overrides every --skip-* flag",
    )
    ap.add_argument("--chaos-blocks", type=int, default=24, help="chaos sustain main-DAG length")
    # long enough that coinbase maturity passes and real signature batches
    # flow through the sharded verify path (a 12-block replay carries 0 txs)
    ap.add_argument("--mesh-blocks", type=int, default=48, help="mesh smoke replay length")
    ap.add_argument("--blocks", type=int, default=64, help="sim replay length")
    ap.add_argument("--test-timeout", type=float, default=900.0)
    ap.add_argument("--probe-timeout", type=float, default=180.0)
    ap.add_argument("--out", default=os.path.join(REPO_ROOT, "ROUNDCHECK.json"))
    args = ap.parse_args(argv)

    # forced 8 CPU host devices: the mesh lanes must work on any box the
    # round runs on, with or without a real accelerator
    mesh_env = {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}

    def _sect_lint() -> dict:
        t0 = time.monotonic()
        sect = _run([sys.executable, os.path.join(REPO_ROOT, "tools", "lint.py"), "-q", "--ratchet"], 120.0)
        sect["wall_s"] = round(time.monotonic() - t0, 2)
        report = None
        try:
            with open(os.path.join(REPO_ROOT, "LINT.json")) as f:
                report = json.load(f)
        except (OSError, json.JSONDecodeError):
            pass
        sect["findings"] = len(report["findings"]) if report else None
        sect["suppressed"] = len(report["suppressed"]) if report else None
        sect["files"] = report["files"] if report else None
        sect["engine"] = report.get("engine") if report else None
        sect["callgraph"] = report.get("callgraph") if report else None
        sect["ratchet"] = report.get("ratchet") if report else None
        sect["ok"] = (
            sect["rc"] == 0
            and report is not None
            and report["ok"]
            and report.get("engine") == "v2"
        )
        return sect

    def _sect_tier1() -> dict:
        sect = _run(FASTLANE_CMD, args.test_timeout, {"JAX_PLATFORMS": "cpu"})
        summary = next((ln for ln in reversed(sect["tail"]) if "passed" in ln), "")
        sect["summary"] = summary.strip()
        sect["ok"] = sect["rc"] == 0
        return sect

    def _sect_sim() -> dict:
        sect = _run(
            [sys.executable, "-m", "kaspa_tpu.sim", "--bps", "2", "--blocks", str(args.blocks), "--json"],
            300.0,
            {"JAX_PLATFORMS": "cpu"},
        )
        result = _last_json_line(sect)
        sect["result"] = result
        sect["ok"] = sect["rc"] == 0 and result is not None
        return sect

    def _sect_bench_probe() -> dict:
        sect = _run(
            [sys.executable, os.path.join(REPO_ROOT, "bench.py"), "--probe"],
            args.probe_timeout,
        )
        result = _last_json_line(sect)
        sect["result"] = result
        sect["ok"] = bool(result and result.get("probe_ok"))
        return sect

    def _sect_multichip() -> dict:
        # multichip dryrun: masks + muhash product checked against host
        # oracles on every visible device (round evidence for item 6)
        sect = _run(
            [
                sys.executable, "-c",
                "import json, jax, __graft_entry__ as g; n = len(jax.devices()); "
                "g.dryrun_multichip(n); print(json.dumps({'devices': n, 'dryrun_ok': True}))",
            ],
            600.0,
            mesh_env,
        )
        result = _last_json_line(sect)
        sect["result"] = result
        sect["ok"] = sect["rc"] == 0 and bool(result and result.get("dryrun_ok"))
        return sect

    def _sect_mesh_smoke() -> dict:
        # mesh smoke: the production batch path (BatchScriptChecker +
        # muhash) sharded over 8 host devices for a short replay — the
        # tier-1 fast lane exercises sharded dispatch at least once a round
        sect = _run(
            [
                sys.executable, "-m", "kaspa_tpu.sim",
                "--bps", "2", "--blocks", str(args.mesh_blocks), "--mesh", "8", "--json",
            ],
            # budget covers the one-time shard_map trace of the verify ladder
            # (~3-4 min/process on CPU; the XLA compile itself is served by
            # the persistent cache after the first round)
            900.0,
            mesh_env,
        )
        result = _last_json_line(sect)
        sect["result"] = result
        sect["ok"] = sect["rc"] == 0 and bool(result) and result.get("mesh") == 8
        return sect

    def _sect_dispatch() -> dict:
        # coalesced dispatch lane: cross-block coalescing vs legacy per-block
        # dispatch over the same jobs on the CPU bench path.  Chunk size 4
        # models the sim's per-block signature count (tpb 4; every block
        # pads half its bucket-8 lanes); the coalesced lane packs the same
        # jobs into 64-lane super-batches.  Acceptance: >= 1.3x verifies/sec AND
        # a 24-block sim replay (long enough for coinbase maturity, so real
        # signature batches flow) bit-identical (sink + utxo_commitment)
        # with coalescing on vs off.
        sect = _run(
            [sys.executable, os.path.join(REPO_ROOT, "bench.py")],
            900.0,
            {
                **mesh_env,
                "KASPA_TPU_BENCH_CHILD": "1",
                "KASPA_TPU_BENCH_MODE": "dispatch",
                "KASPA_TPU_BENCH_DISPATCH_B": "120",
                "KASPA_TPU_BENCH_CHUNK": "4",
                "KASPA_TPU_COALESCE": "64",
                "KASPA_TPU_BENCH_DISPATCH_REPLAY": "24",
            },
        )
        result = _last_json_line(sect)
        if result is not None:
            result.pop("observability", None)
        sect["result"] = result
        sect["ok"] = (
            sect["rc"] == 0
            and bool(result)
            and result.get("speedup", 0.0) >= 1.3
            and bool(result.get("replay_identical"))
        )
        return sect

    def _sect_aggregate() -> dict:
        # aggregated RLC verify lane: ONE random-linear-combination
        # multi-scalar pass over the super-batch vs per-signature ladders,
        # on the CPU bench path.  Batch 64 is the production coalesce size
        # and sits past the measured crossover (batch 16).  Acceptance:
        # >= 1.5x verifies/sec AND a 24-block sim replay with
        # --verify-mode aggregate bit-identical (sink + utxo_commitment)
        # with the ladder replay — bisection must make the two lanes
        # indistinguishable, not just agree on all-valid batches.
        sect = _run(
            [sys.executable, os.path.join(REPO_ROOT, "bench.py")],
            900.0,
            {
                "JAX_PLATFORMS": "cpu",
                "KASPA_TPU_BENCH_CHILD": "1",
                "KASPA_TPU_BENCH_MODE": "aggregate",
                "KASPA_TPU_BENCH_AGG_B": "64",
                "KASPA_TPU_COLD_BUCKET_SPLIT": "0",
            },
        )
        result = _last_json_line(sect)
        if result is not None:
            result.pop("observability", None)
        sect["result"] = result
        replay_cmd = [
            sys.executable, "-m", "kaspa_tpu.sim",
            "--bps", "2", "--blocks", "24", "--tpb", "4", "--json",
        ]
        lad = _run(replay_cmd + ["--verify-mode", "ladder"], 600.0, {"JAX_PLATFORMS": "cpu"})
        agg = _run(replay_cmd + ["--verify-mode", "aggregate"], 600.0, {"JAX_PLATFORMS": "cpu"})
        j_lad = _last_json_line(lad)
        j_agg = _last_json_line(agg)
        identical = bool(
            j_lad and j_agg
            and j_lad["sink"] == j_agg["sink"]
            and j_lad["utxo_commitment"] == j_agg["utxo_commitment"]
        )
        sect["replay_ladder"] = j_lad
        sect["replay_aggregate"] = j_agg
        sect["replay_identical"] = identical
        sect["ok"] = (
            sect["rc"] == 0
            and bool(result)
            and result.get("speedup", 0.0) >= 1.5
            and lad["rc"] == 0
            and agg["rc"] == 0
            and identical
        )
        return sect

    def _sect_serving() -> dict:
        # serving tier: one persistent daemon, one JSON + one Borsh client
        # on the same UtxosChanged scope — the streams must be identical —
        # then kill -9 and a reopen that reconciles (journal rewind /
        # chain-diff catch-up), never a full resync.  Subscriber-lag
        # histograms and per-encoding request counters land in the evidence.
        sect = _run(
            [sys.executable, os.path.join(REPO_ROOT, "tools", "serving_check.py"), "--blocks", "10"],
            600.0,
            {"JAX_PLATFORMS": "cpu"},
        )
        result = _last_json_line(sect)
        sect["result"] = result
        sect["ok"] = sect["rc"] == 0 and bool(result and result.get("serving_ok"))
        return sect

    def _sect_serving_load() -> dict:
        # serving latency observatory (tools/serving_load.py): first the
        # sharded-vs-single fanout identity harness (delivered streams
        # must be bit-identical at shards=4), then a ramped >=50k-virtual-
        # subscriber run of BOTH legs — the single-fanout baseline curve
        # and the sharded (--shards 4) curve, the latter gated against the
        # committed PR 16 baseline (saturation >= 1.5x, paced p99 <= 0.5x,
        # zero drops/disconnects) on top of the historical gates (drained,
        # bounded p99, tracing-off overhead).  Evidence: SERVING_LOAD.json.
        ident = _run(
            [sys.executable, "-m", "kaspa_tpu.serving.check", "--shards", "4"],
            300.0,
            {"JAX_PLATFORMS": "cpu"},
        )
        ident["result"] = _last_json_line(ident)
        sect = _run(
            [
                sys.executable, os.path.join(REPO_ROOT, "tools", "serving_load.py"),
                "--subscribers", str(args.serving_load_subscribers),
                "--shards", "4",
                "--out", os.path.join(REPO_ROOT, "SERVING_LOAD.json"),
            ],
            1500.0,
            {"JAX_PLATFORMS": "cpu"},
        )
        result = _last_json_line(sect)
        sect["identity"] = ident
        sect["result"] = result
        identity_ok = ident["rc"] == 0 and bool(
            ident["result"] and ident["result"].get("serving_identity_ok")
        )
        sect["ok"] = (
            identity_ok
            and sect["rc"] == 0
            and bool(result and result.get("serving_load_ok"))
        )
        return sect

    def _sect_obs() -> dict:
        # flight-recorder lane: a traced 24-block pipelined + coalesced
        # replay (the full production thread topology: stage workers,
        # virtual worker, verify-dispatch, serving fanout) must produce a
        # dump where every block is a single connected span tree crossing
        # >= 4 threads with >= 90% critical-path attribution, the Perfetto
        # export must be valid Chrome trace JSON, and the tracing-disabled
        # replay must stay within 2% of the default (PR 5 baseline) replay.
        flight_path = os.path.join(REPO_ROOT, "FLIGHT.json")
        perfetto_path = os.path.join(REPO_ROOT, "FLIGHT.perfetto.json")
        sect = _run(
            [
                sys.executable, "-m", "kaspa_tpu.sim",
                "--bps", "2", "--blocks", "24", "--tpb", "4",
                "--pipeline", "--coalesce", "64", "--trace", flight_path, "--json",
            ],
            600.0,
            {"JAX_PLATFORMS": "cpu"},
        )
        sect["result"] = _last_json_line(sect)
        traced_ok = sect["rc"] == 0 and bool(sect["result"])
        if traced_ok:
            sect["flight"] = _validate_flight(flight_path)
            conv = _run(
                [sys.executable, os.path.join(REPO_ROOT, "tools", "trace_report.py"),
                 flight_path, "--perfetto", perfetto_path],
                120.0,
                {"JAX_PLATFORMS": "cpu"},
            )
            sect["perfetto"] = (
                _validate_chrome(perfetto_path) if conv["rc"] == 0
                else {"ok": False, "error": "trace_report --perfetto failed", "tail": conv["tail"]}
            )
        # overhead gate: serial replay as in PR 5 (default tracing, no
        # flight recorder) vs the same replay with tracing disabled —
        # best-of-2 each to keep run-to-run noise out of the 2% budget
        base_cmd = [
            sys.executable, "-m", "kaspa_tpu.sim",
            "--bps", "2", "--blocks", "24", "--tpb", "4", "--json",
        ]
        def _best_bps(cmd):
            best, tails = 0.0, []
            for _ in range(2):
                r = _run(cmd, 300.0, {"JAX_PLATFORMS": "cpu"})
                tails.append(r["tail"][-1:])
                j = _last_json_line(r)
                if r["rc"] == 0 and j:
                    best = max(best, float(j.get("replay_blocks_per_sec", 0.0)))
            return best, tails
        base_bps, _ = _best_bps(base_cmd)
        off_bps, _ = _best_bps(base_cmd + ["--notrace"])
        sect["overhead"] = {
            "baseline_bps": base_bps,
            "tracing_off_bps": off_bps,
            "ratio": round(off_bps / base_bps, 4) if base_bps else 0.0,
            "ok": base_bps > 0 and off_bps >= 0.98 * base_bps,
        }
        sect["ok"] = (
            traced_ok
            and sect.get("flight", {}).get("ok", False)
            and sect.get("perfetto", {}).get("ok", False)
            and sect["overhead"]["ok"]
        )
        return sect

    def _sect_tenbps() -> dict:
        # 10-BPS lane (ROADMAP item 2): a pipelined replay of a 10-BPS DAG
        # with the chaos schedule off, speculation on — records the
        # realtime_factor and the speculative hit-rate — gated on the
        # speculation-disabled replay of the same DAG reaching the
        # bit-identical sink + utxo_commitment (the hit path must be
        # indistinguishable from the honest path)
        tenbps_cmd = [
            sys.executable, "-m", "kaspa_tpu.sim",
            "--bps", "10", "--blocks", "24", "--tpb", "4", "--pipeline", "--json",
        ]
        sect = _run(tenbps_cmd, 600.0, {"JAX_PLATFORMS": "cpu"})
        spec_on = _last_json_line(sect)
        off = _run(tenbps_cmd + ["--no-spec"], 600.0, {"JAX_PLATFORMS": "cpu"})
        spec_off = _last_json_line(off)
        identical = bool(
            spec_on and spec_off
            and spec_on["sink"] == spec_off["sink"]
            and spec_on["utxo_commitment"] == spec_off["utxo_commitment"]
        )
        sect["result"] = spec_on
        sect["no_spec_result"] = spec_off
        sect["identical_to_no_spec"] = identical
        if spec_on:
            sect["realtime_factor"] = spec_on.get("realtime_factor")
            sect["speculative"] = spec_on.get("speculative")
        sect["ok"] = sect["rc"] == 0 and off["rc"] == 0 and identical
        return sect

    def _sect_chaos() -> dict:
        # chaos sustain: seeded fault schedule under hostile script mix +
        # attacker-fork reorg; the acceptance bit is the faulted run
        # converging to the byte-identical fault-free end state with the
        # breaker demonstrably tripping and recovering (round evidence for
        # ROADMAP item 5)
        sect = _run(
            [
                sys.executable, "-m", "kaspa_tpu.sim",
                "--hostile", "--faults", "default", "--blocks", str(args.chaos_blocks),
                "--tpb", "4", "--seed", "7", "--json",
                "--sustain-out", os.path.join(REPO_ROOT, "SUSTAIN.json"),
            ],
            900.0,
            {"JAX_PLATFORMS": "cpu"},
        )
        result = _last_json_line(sect)
        sect["result"] = result
        sect["ok"] = (
            sect["rc"] == 0
            and bool(result)
            and bool(result.get("matches_fault_free"))
            and result.get("breaker_trips", 0) >= 1
        )
        return sect

    def _sect_supervision() -> dict:
        # supervision wedge drill: dispatch hangs + a compile stall injected
        # mid-replay; the watchdog reroutes every wedged super-batch to the
        # host degraded lane and the canary prober recovers the breaker —
        # gated on bit-identity with the fault-free replay plus exact
        # requeue accounting (no ticket lost, none double-resolved)
        sect = _run(
            [
                sys.executable, "-m", "kaspa_tpu.sim",
                "--hostile", "--wedge-drill", "--blocks", "24",
                "--tpb", "4", "--seed", "7", "--coalesce", "256", "--json",
                "--sustain-out", os.path.join(REPO_ROOT, "SUSTAIN_WEDGE.json"),
            ],
            1200.0,
            {"JAX_PLATFORMS": "cpu"},
        )
        result = _last_json_line(sect)
        sect["result"] = result
        sect["ok"] = (
            sect["rc"] == 0
            and bool(result)
            and bool(result.get("matches_fault_free"))
            and bool(result.get("requeue_matches_injected"))
            and result.get("injected_hangs", 0) > 0
            and bool(result.get("compile_stall_ok"))
            and bool(result.get("tickets_ok"))
            and bool(result.get("recovered"))
        )
        return sect

    def _sect_fabric() -> dict:
        # verify fabric: spawn a real verifyd (second process), replay over
        # the wire and gate on bit-identity with the local-only replay, then
        # SIGKILL the server mid-replay and gate on the degraded-lane
        # failover losing zero tickets (ISSUE acceptance: fabric smoke +
        # slice-kill drill)
        sect = _run(
            [sys.executable, os.path.join(REPO_ROOT, "tools", "fabric_check.py"), "--blocks", "24"],
            900.0,
            {"JAX_PLATFORMS": "cpu"},
        )
        result = _last_json_line(sect)
        sect["result"] = result
        sect["ok"] = sect["rc"] == 0 and bool(result and result.get("fabric_ok"))
        return sect

    def _sect_ingest() -> dict:
        # ingest lane (ISSUE 12): (a) batched waves on the verify plane vs
        # one-at-a-time validate_and_insert over the same hostile flood in
        # the same arrival order must leave the mempool, orphan pool and a
        # fixed-timestamp template bit-identical; (b) a short tx-flood
        # sustain run must keep consensus bit-identical to the fault-free
        # replay with clean acceptance >= 0.99 and zero lost tickets
        sect = _run(
            [sys.executable, "-m", "kaspa_tpu.ingest.check", "--blocks", "24", "--tpb", "4", "--slots", "6"],
            600.0,
            {"JAX_PLATFORMS": "cpu"},
        )
        identity = _last_json_line(sect)
        sect["result"] = identity
        flood = _run(
            [
                sys.executable, "-m", "kaspa_tpu.sim",
                "--txflood", "--no-pace", "--blocks", "24", "--tpb", "4",
                "--seed", "7", "--json",
                "--sustain-out", os.path.join(REPO_ROOT, "SUSTAIN_TXFLOOD.json"),
            ],
            900.0,
            {"JAX_PLATFORMS": "cpu"},
        )
        j_flood = _last_json_line(flood)
        sect["flood_cmd"] = flood["cmd"]
        sect["flood_tail"] = flood["tail"]
        sect["flood_result"] = j_flood
        sect["ok"] = (
            sect["rc"] == 0
            and bool(identity and identity.get("ingest_ok"))
            and flood["rc"] == 0
            and bool(j_flood)
            and bool(j_flood.get("matches_fault_free"))
            and j_flood.get("tx_acceptance_rate", 0.0) >= 0.99
            and j_flood.get("lost_tickets", 1) == 0
        )
        return sect

    def _sect_overload() -> dict:
        # overload lane (ISSUE 14): a tx-flood replay with the adaptive
        # brownout ramp engaged — flood scale climbs past the pressure
        # thresholds, the controller must reach SATURATED, every brownout
        # seam sheds observably (zero lost tickets — every shed tx still
        # resolves its admission ticket), block cadence under SATURATED
        # stays within 1.5x of loaded-nominal, and the controller settles
        # back to NOMINAL once the flood drains.  The late ramp fractions
        # leave the 24-block warm phase long enough for coinbase maturity,
        # so the NOMINAL cadence baseline carries real flood traffic.
        sect = _run(
            [
                sys.executable, "-m", "kaspa_tpu.sim",
                "--txflood", "--overload", "--no-pace", "--blocks", "24",
                "--tpb", "4", "--seed", "7", "--json",
                "--overload-config", '{"warm_frac": 0.5, "ramp_frac": 0.2, "hold_frac": 0.2}',
                "--sustain-out", os.path.join(REPO_ROOT, "SUSTAIN_OVERLOAD.json"),
            ],
            900.0,
            {"JAX_PLATFORMS": "cpu"},
        )
        result = _last_json_line(sect)
        sect["result"] = result
        sect["ok"] = (
            sect["rc"] == 0
            and bool(result)
            and bool(result.get("matches_fault_free"))
            and result.get("lost_tickets", 1) == 0
            and result.get("overload_max_level") in ("SATURATED", "CRITICAL")
            and result.get("overload_shed", 0) > 0
            and bool(result.get("overload_recovered"))
            and bool(result.get("overload_ok"))
        )
        return sect

    def _sect_swarm() -> dict:
        # swarm drill (ISSUE 19): three real in-process nodes over loopback
        # sockets run the seeded default scenario — partition into
        # {attacker} x {honest}, divergent mining on both sides, heal with
        # a deep attacker reorg, post-heal relay round, then a late joiner
        # IBDs the whole DAG.  Gated on every node converging bit-identical
        # (sink + utxo commitment), the run matching the fault-free replay,
        # zero lost admission tickets fleet-wide, and block-relay traffic
        # staying under the O(N * blocks) amplification budget.
        sect = _run(
            [
                sys.executable, "-m", "kaspa_tpu.sim",
                "--swarm", "3", "--blocks", "24", "--seed", "7", "--json",
                "--swarm-out", os.path.join(REPO_ROOT, "SWARM.json"),
            ],
            900.0,
            {"JAX_PLATFORMS": "cpu"},
        )
        result = _last_json_line(sect)
        sect["result"] = result
        sect["ok"] = (
            sect["rc"] == 0
            and bool(result)
            and bool(result.get("converged"))
            and bool(result.get("matches_fault_free"))
            and result.get("lost_tickets", 1) == 0
            and bool(result.get("amp_ok"))
        )
        return sect

    sections: list[tuple[str, bool, object]] = [
        ("lint", not args.skip_lint, _sect_lint),
        ("tier1", not args.skip_tests, _sect_tier1),
        ("sim", not args.skip_sim, _sect_sim),
        ("bench_probe", not args.skip_bench, _sect_bench_probe),
        ("multichip", not args.skip_mesh, _sect_multichip),
        ("mesh_smoke", not args.skip_mesh, _sect_mesh_smoke),
        ("dispatch", not args.skip_dispatch, _sect_dispatch),
        ("aggregate", not args.skip_aggregate, _sect_aggregate),
        ("serving", not args.skip_serving, _sect_serving),
        ("serving_load", not args.skip_serving_load, _sect_serving_load),
        ("obs", not args.skip_obs, _sect_obs),
        ("tenbps", not args.skip_tenbps, _sect_tenbps),
        ("chaos", not args.skip_chaos, _sect_chaos),
        ("supervision", not args.skip_supervision, _sect_supervision),
        ("fabric", not args.skip_fabric, _sect_fabric),
        ("ingest", not args.skip_ingest, _sect_ingest),
        ("overload", not args.skip_overload, _sect_overload),
        ("swarm", not args.skip_swarm, _sect_swarm),
    ]
    only: set[str] | None = None
    if args.only:
        only = {name.strip() for spec in args.only for name in spec.split(",") if name.strip()}
        known = {name for name, _, _ in sections}
        unknown = only - known
        if unknown:
            ap.error(f"unknown --only section(s) {sorted(unknown)}; known: {sorted(known)}")

    evidence: dict = {"created": _utc(), "sections": {}}
    ok = True
    for name, enabled, fn in sections:
        if only is not None:
            if name not in only:
                continue
        elif not enabled:
            continue
        t0 = time.monotonic()
        sect = fn()
        # wall_seconds covers the whole section (some run several commands;
        # each command's own time stays in its "seconds")
        sect["wall_seconds"] = round(time.monotonic() - t0, 1)
        evidence["sections"][name] = sect
        ok &= sect["ok"]

    evidence["ok"] = ok
    with open(args.out, "w") as f:
        json.dump(evidence, f, indent=2)
        f.write("\n")
    print(f"[roundcheck] {'PASS' if ok else 'FAIL'} -> {args.out}")
    for name, sect in evidence["sections"].items():
        print(f"  {name:12s} {'ok' if sect['ok'] else 'FAIL':4s} rc={sect['rc']} {sect['wall_seconds']}s")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
