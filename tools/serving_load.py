#!/usr/bin/env python
"""Serving-plane latency observatory: the >=50k-virtual-subscriber load run.

Drives the PRODUCTION serving stack — Broadcaster ingest queue, per-event
script indexing, zipf-scoped per-subscriber filtering, bounded subscriber
queues, shared sender pool — with a deterministic ramped population of
virtual subscribers (``kaspa_tpu/serving/loadgen.py``: memory sinks plus a
datagram-socketpair wire cohort drained by one selector thread; no
thread-per-subscriber, fd budget preflighted).  Emits ``SERVING_LOAD.json``:

* p50/p99/p999 block-accept -> last-hop notification lag per ramp stage,
  measured at the sinks on the same monotonic clock that stamped the diff
  (cross-checking the broadcaster's own ``serving_lag_ms`` histograms);
* drop / disconnect / conflation rates (gated: zero drops at nominal pace);
* the lag-vs-population curve and the fanout-thread saturation point;
* the tracing-off overhead gate (PR 7 convention, best-of-N per leg:
  disabling ``KASPA_TPU_SERVING_TRACE`` instrumentation must not LOSE
  throughput — ``off >= 0.98 * on``; the raw on/off ratio is reported);
* optionally (``--daemon-probe``) a daemon-child smoke: a real node, a
  real wRPC subscriber, mined blocks, and the ``serving_lag_ms`` families
  visible in its Prometheus export.

Prints one JSON line as the last stdout line (tools/roundcheck.py's
``serving_load`` section consumes it).

    python tools/serving_load.py --subscribers 50000 --out SERVING_LOAD.json
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import tempfile
import textwrap
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from kaspa_tpu.serving import broadcaster as broadcaster_mod  # noqa: E402
from kaspa_tpu.serving.loadgen import LoadGen  # noqa: E402
from kaspa_tpu.utils import fdbudget  # noqa: E402

OVERHEAD_GATE = 0.98
WIRE_AUTO_CAP = 256

# The committed PR 16 single-fanout baseline at 50k subscribers
# (SERVING_LOAD.json on main): the sharded tier's capacity gates are
# ratios against THESE constants, not against the in-run baseline leg, so
# the gate is a fixed bar — a slow machine slows both legs, but the
# committed curve is what the sharded tier must beat where it was set.
PR16_BASELINE_SATURATION_EPS = 2.07   # unpaced fanout events/s of busy time
PR16_BASELINE_P99_MS = 1978.9         # paced p99 last-hop lag at 50k
SHARD_SAT_FACTOR = 1.5                # sharded saturation >= 1.5x baseline
SHARD_P99_FACTOR = 0.5                # sharded paced p99 <= 0.5x baseline

_DAEMON_SCRIPT = textwrap.dedent(
    """
    import sys, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    from kaspa_tpu.utils import jax_setup; jax_setup.setup()
    from kaspa_tpu.node.daemon import Daemon, parse_args

    args = parse_args(["--appdir", sys.argv[1], "--rpclisten", "127.0.0.1:0",
                       "--rpclisten-wrpc", "127.0.0.1:0", "--bps", "2",
                       "--serving-pool", "2"])
    d = Daemon(args)
    d.start()
    print("WRPC " + d.wrpc_server.address, flush=True)
    while True:
        time.sleep(3600)
    """
)


def _stage_plan(n: int) -> list[int]:
    """Ramp milestones up to the full population (the lag-vs-population
    curve's x axis)."""
    plan = sorted({max(1000, n // 25), max(2000, n // 5), n // 2, n})
    return [p for p in plan if p <= n] or [n]


def _run_stage(lg: LoadGen, events: int, pace_hz: float, size: int, hot_frac: float) -> dict:
    marker = lg.reset_window()
    t0 = time.monotonic()
    publish_wall = lg.drive(events, pace_hz=pace_hz, size=size, hot_frac=hot_frac)
    drained = lg.drain(timeout=120.0)
    wall = time.monotonic() - t0
    delivered = lg.delivered() - marker["delivered"]
    busy_ns = lg.fanout_busy_ns() - marker["busy_ns"]
    return {
        "population": len(lg.subscribers),
        "events": events,
        "pace_hz": pace_hz,
        "publish_wall_s": round(publish_wall, 4),
        "wall_s": round(wall, 4),
        "drained": drained,
        "delivered": delivered,
        "deliveries_per_event": round(delivered / events, 1) if events else 0.0,
        "dropped": lg.dropped() - marker["dropped"],
        "disconnects": lg.disconnects - marker["disconnects"],
        "conflated": lg.conflated() - marker["conflated"],
        "fanout_busy_frac": round(busy_ns / (wall * 1e9), 4) if wall > 0 else 0.0,
        "lag_ms": {k: (round(v, 3) if isinstance(v, float) else v)
                   for k, v in lg.recorder.percentiles().items()},
    }


def _overhead_ab(args) -> dict:
    """Best-of-N unpaced delivery throughput, stage tracing on vs off, on
    a dedicated mid-size population (legs interleaved so drift hits both)."""
    lg = LoadGen(
        seed=args.seed ^ 0xAB, addresses=min(args.addresses, 10_000),
        zipf_s=args.zipf_s, pool_workers=args.pool_workers,
    )
    try:
        lg.ramp_to(args.overhead_population)

        def leg(on: bool) -> float:
            broadcaster_mod.set_stage_tracing(on)
            marker = lg.reset_window()
            t0 = time.monotonic()
            lg.drive(args.overhead_events, pace_hz=0.0, size=args.diff_size, hot_frac=args.hot_frac)
            # fine settle: the drain poll quantum must stay well under the
            # leg wall or it becomes the dominant noise term in the ratio
            if not lg.drain(timeout=60.0, settle=0.002):
                return 0.0
            wall = time.monotonic() - t0
            return (lg.delivered() - marker["delivered"]) / wall if wall > 0 else 0.0

        leg(True)  # warmup (jit-free, but caches/allocator settle)
        best_on = best_off = 0.0
        for _ in range(args.overhead_rounds):
            best_off = max(best_off, leg(False))
            best_on = max(best_on, leg(True))
    finally:
        broadcaster_mod.set_stage_tracing(True)
        lg.close()
    return {
        "population": args.overhead_population,
        "events_per_leg": args.overhead_events,
        "rounds": args.overhead_rounds,
        "tracing_on_dps": round(best_on, 1),
        "tracing_off_dps": round(best_off, 1),
        # PR 7 gate direction: the off leg must reach >=0.98x of the
        # default (instrumented) leg — turning telemetry off never loses
        # throughput.  on/off is the honest instrumentation-cost ratio.
        "off_over_on": round(best_off / best_on, 4) if best_on else 0.0,
        "on_over_off": round(best_on / best_off, 4) if best_off else 0.0,
        "gate": OVERHEAD_GATE,
        "ok": best_on > 0 and best_off >= OVERHEAD_GATE * best_on,
    }


def _saturation_probe(lg: LoadGen, events: int, size: int, hot_frac: float) -> dict:
    """Unpaced burst: the fanout thread's indexing+filter+offer capacity
    (events/s of pure busy time) and the end-to-end drain throughput."""
    marker = lg.reset_window()
    t0 = time.monotonic()
    lg.drive(events, pace_hz=0.0, size=size, hot_frac=hot_frac)
    drained = lg.drain(timeout=180.0)
    wall = time.monotonic() - t0
    busy_s = (lg.fanout_busy_ns() - marker["busy_ns"]) * 1e-9
    delivered = lg.delivered() - marker["delivered"]
    return {
        "events": events,
        "wall_s": round(wall, 4),
        "drained": drained,
        "fanout_busy_s": round(busy_s, 4),
        # pace above this and the fanout thread itself becomes the wall
        "fanout_saturation_events_per_s": round(events / busy_s, 2) if busy_s > 0 else 0.0,
        "end_to_end_events_per_s": round(events / wall, 2) if wall > 0 else 0.0,
        "deliveries_per_s": round(delivered / wall, 1) if wall > 0 else 0.0,
        "lag_ms": {k: (round(v, 3) if isinstance(v, float) else v)
                   for k, v in lg.recorder.percentiles().items()},
    }


def _ramp_leg(args, wire: int, shards: int) -> dict:
    """One full observatory leg (ramp curve + saturation probe + rates)
    against a fresh population: ``shards`` = 0/1 drives the single-fanout
    ``Broadcaster``, > 1 the ``ShardedBroadcaster``.  The LoadGen (and its
    wire sockets) is torn down before returning, so two legs in one run
    never hold the fd cohort twice."""
    lg = LoadGen(
        seed=args.seed, addresses=args.addresses, zipf_s=args.zipf_s,
        sub_maxlen=args.sub_maxlen, pool_workers=args.pool_workers,
        shards=shards,
    )
    try:
        stages = []
        wire_left = wire
        for target in _stage_plan(args.subscribers):
            grow = target - len(lg.subscribers)
            take_wire = min(wire_left, grow)
            wire_left -= take_wire
            t_ramp = time.monotonic()
            lg.ramp_to(target, wire=take_wire)
            stage = _run_stage(
                lg, args.events_per_stage, args.pace_hz, args.diff_size, args.hot_frac
            )
            stage["ramp_s"] = round(time.monotonic() - t_ramp - stage["wall_s"], 4)
            stages.append(stage)

        saturation = _saturation_probe(
            lg, args.saturation_events, args.diff_size, args.hot_frac
        )

        delivered = sum(s["delivered"] for s in stages)
        dropped = sum(s["dropped"] for s in stages)
        conflated = sum(s["conflated"] for s in stages)
        disconnects = sum(s["disconnects"] for s in stages)
        rates = {
            "delivered": delivered,
            "drop_rate": round(dropped / delivered, 6) if delivered else 0.0,
            "disconnect_rate": round(disconnects / max(1, len(lg.subscribers)), 6),
            "conflation_rate": round(conflated / delivered, 6) if delivered else 0.0,
        }

        # the broadcaster's own collector view (getMetrics["serving"] /
        # Prometheus gauges), snapshotted while this leg is still live
        from kaspa_tpu.observability.core import REGISTRY

        serving = REGISTRY.snapshot().get("serving", {})
        serving.pop("queue_depths", None)
        serving.pop("dropped_by_subscriber", None)

        return {
            "fanout_shards": shards if shards > 1 else 1,
            "stages": stages,
            "lag_vs_population": [
                {"population": s["population"], "p50_ms": s["lag_ms"]["p50"],
                 "p99_ms": s["lag_ms"]["p99"], "p999_ms": s["lag_ms"]["p999"]}
                for s in stages
            ],
            "saturation": saturation,
            "rates": rates,
            "dropped": dropped,
            "disconnects": disconnects,
            "registry_serving": serving,
        }
    finally:
        lg.close()


def _daemon_probe(timeout_s: float) -> dict:
    """Boot a real daemon child (pooled senders), stream one UtxosChanged
    over wRPC, and assert the serving_lag_ms families show up in its
    Prometheus export and getMetrics serving block."""
    appdir = tempfile.mkdtemp(prefix="serving-load-")
    script = os.path.join(appdir, "daemon-child.py")
    with open(script, "w") as f:
        f.write(_DAEMON_SCRIPT)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, script, appdir],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
    )
    out: dict = {"ok": False}
    client = None
    try:
        addr = None
        deadline = time.monotonic() + timeout_s
        for line in proc.stdout:
            if line.startswith("WRPC "):
                addr = line.split(" ", 1)[1].strip()
                break
            if time.monotonic() > deadline:
                break
        if addr is None:
            out["error"] = "daemon never came up: " + proc.stderr.read()[-400:]
            return out

        import random

        from kaspa_tpu.crypto.addresses import extract_script_pub_key_address
        from kaspa_tpu.rpc.wrpc import WrpcClient
        from kaspa_tpu.sim.simulator import Miner

        miner = Miner(0, random.Random(2))
        pay = extract_script_pub_key_address(miner.spk, "kaspasim").to_string()
        client = WrpcClient(addr)
        client.subscribe("utxos-changed", [pay])
        for _ in range(6):
            t = client.call("getBlockTemplate", {"payAddress": pay})
            client.call("submitBlockByTemplateHash", {"hash": t["block_hash"]})
        events = 0
        while events < 1 and time.monotonic() < deadline:
            try:
                event, _data = client.next_notification(timeout=10)
            except Exception:  # noqa: BLE001 - keep polling to the deadline
                continue
            if event == "utxos-changed":
                events += 1
        prom_text = client.call("getMetricsPrometheus")
        stages = set(
            re.findall(r'kaspa_serving_lag_ms_bucket\{stage="([\w-]+)"', prom_text)
        )
        counts = {
            stage: int(float(v))
            for stage, v in re.findall(
                r'kaspa_serving_lag_ms_count\{stage="([\w-]+)"\} (\S+)', prom_text
            )
        }
        serving = client.call("getMetrics").get("serving", {})
        out.update(
            {
                "events": events,
                "prom_stages": sorted(stages),
                "prom_stage_counts": counts,
                "metrics_serving_block": bool(serving),
                "lag_ms_quantiles": serving.get("lag_quantiles_ms", {}),
                "ok": (
                    events >= 1
                    and {"accept_to_fanout", "queue_wait", "encode", "socket_write", "end_to_end"}
                    <= stages
                    and counts.get("end_to_end", 0) >= 1
                    and bool(serving)
                ),
            }
        )
        return out
    except Exception as e:  # noqa: BLE001 - evidence carries the failure
        out.setdefault("error", str(e))
        return out
    finally:
        if client is not None:
            client.close()
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--subscribers", type=int, default=50_000, help="final virtual-subscriber population")
    ap.add_argument("--wire", default="auto",
                    help="wire-cohort size: socketpair-backed subscribers (2 fds each); "
                    "'auto' fits the fd budget (capped at %d)" % WIRE_AUTO_CAP)
    ap.add_argument("--addresses", type=int, default=50_000, help="synthetic address universe size")
    ap.add_argument("--zipf-s", type=float, default=1.05, help="zipf exponent for address popularity")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--events-per-stage", type=int, default=12, help="diffs published per ramp stage")
    ap.add_argument("--pace-hz", type=float, default=3.0, help="nominal diff pace (0 = unpaced)")
    ap.add_argument("--diff-size", type=int, default=24, help="addresses touched per diff")
    ap.add_argument("--hot-frac", type=float, default=0.125, help="fraction of diff addresses popularity-sampled")
    ap.add_argument("--pool-workers", type=int, default=2, help="shared sender-pool workers")
    ap.add_argument("--shards", type=int, default=0,
                    help="fanout shards for the sharded leg (0 = single-fanout "
                    "run only; N > 1 runs BOTH legs — the single-fanout "
                    "baseline curve and the sharded curve — and gates the "
                    "sharded one against the committed PR 16 baseline)")
    ap.add_argument("--sub-maxlen", type=int, default=1024, help="per-subscriber queue bound")
    ap.add_argument("--overhead-population", type=int, default=2000)
    ap.add_argument("--overhead-events", type=int, default=60)
    ap.add_argument("--overhead-rounds", type=int, default=3)
    ap.add_argument("--saturation-events", type=int, default=12)
    ap.add_argument("--p99-budget-ms", type=float, default=5000.0,
                    help="final-stage p99 lag gate at nominal pace (measured "
                    "1.9-3.4s across runs at 50k subscribers on one CPU core; "
                    "an unhealthy fanout shows tens of seconds)")
    ap.add_argument("--daemon-probe", action=argparse.BooleanOptionalAction, default=False,
                    help="also boot a daemon child and verify serving_lag_ms on the real wire")
    ap.add_argument("--daemon-timeout", type=float, default=180.0)
    ap.add_argument("--out", default=None, help="write SERVING_LOAD.json here")
    args = ap.parse_args(argv)

    result: dict = {
        "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    ok = False
    try:
        # --- fd preflight (satellite: fail fast with the remedy, never
        # EMFILE mid-ramp).  The sharded budget counts per-shard sender
        # crews on top of the wire-cohort sockets. ---
        shards = max(0, args.shards)
        if args.wire == "auto":
            b = fdbudget.budget()
            crews = max(1, shards)
            slack = max(0, b["available"] - crews * args.pool_workers)
            wire = max(0, min(WIRE_AUTO_CAP, slack // 2))
        else:
            wire = int(args.wire)
        fd = fdbudget.serving_preflight(
            shards=shards, pool_workers=args.pool_workers, wire_cohort=wire,
            what="serving load harness",
        )
        # same GIL tuning the daemon applies when it builds its serving
        # tier — the harness measures the production configuration
        switch_s = broadcaster_mod.tune_gil_switch_interval()
        result["run_meta"] = {
            "seed": args.seed,
            "gil_switch_interval_ms": round(switch_s * 1e3, 3),
            "subscribers": args.subscribers,
            "wire_cohort": wire,
            "addresses": args.addresses,
            "zipf_s": args.zipf_s,
            "diff_size": args.diff_size,
            "hot_frac": args.hot_frac,
            "pace_hz": args.pace_hz,
            "pool_workers": args.pool_workers,
            "fanout_shards": shards,
            "sub_maxlen": args.sub_maxlen,
            "fd_budget": fd,
            "cpu_count": os.cpu_count(),
            "stage_tracing": broadcaster_mod.stage_tracing_enabled(),
        }

        # --- tracing-off overhead gate (dedicated mid-size population) ---
        result["overhead"] = _overhead_ab(args)

        # --- the ramp legs: lag vs population at nominal pace.  With
        # --shards N the run produces BOTH curves (baseline single-fanout
        # then sharded) and the top-level stages/saturation/rates describe
        # the SHARDED leg; without it, today's single-leg shape exactly. ---
        if shards > 1:
            result["baseline"] = _ramp_leg(args, wire, 0)
            leg = _ramp_leg(args, wire, shards)
        else:
            leg = _ramp_leg(args, wire, 0)
        stages = leg["stages"]
        result["stages"] = stages
        result["lag_vs_population"] = leg["lag_vs_population"]
        result["saturation"] = leg["saturation"]
        result["rates"] = leg["rates"]
        result["registry_serving"] = leg["registry_serving"]
        dropped = leg["dropped"]

        if args.daemon_probe:
            result["daemon_probe"] = _daemon_probe(args.daemon_timeout)

        final = stages[-1]
        gates = {
            "population": {
                "value": final["population"], "min": args.subscribers,
                "ok": final["population"] >= args.subscribers,
            },
            "drained": {"ok": all(s["drained"] for s in stages)},
            "drop_rate_nominal": {"value": result["rates"]["drop_rate"], "ok": dropped == 0},
            "p99_bounded": {
                "value": final["lag_ms"]["p99"], "budget_ms": args.p99_budget_ms,
                "ok": 0.0 < final["lag_ms"]["p99"] <= args.p99_budget_ms,
            },
            "overhead": {"value": result["overhead"]["off_over_on"], "ok": result["overhead"]["ok"]},
        }
        if shards > 1:
            # capacity gates vs the COMMITTED PR 16 baseline constants.
            # The sharded value is END-TO-END (wall) events/s: capacity
            # of the tier as a whole.  For the single-fanout baseline the
            # busy-based and wall-based figures coincide (one thread,
            # busy == wall when saturated), so the committed constant is
            # directly comparable; the sharded busy figure is a SUM over
            # parallel workers (a serial-equivalent, reported alongside)
            # and structurally cannot express parallel capacity.
            sat = result["saturation"]["end_to_end_events_per_s"]
            sat_min = round(SHARD_SAT_FACTOR * PR16_BASELINE_SATURATION_EPS, 2)
            p99 = final["lag_ms"]["p99"]
            p99_max = round(SHARD_P99_FACTOR * PR16_BASELINE_P99_MS, 1)
            gates["shard_saturation"] = {
                "value": sat, "min": sat_min,
                "baseline": PR16_BASELINE_SATURATION_EPS,
                "ok": sat >= sat_min,
            }
            gates["shard_p99"] = {
                "value": p99, "max_ms": p99_max,
                "baseline_ms": PR16_BASELINE_P99_MS,
                "ok": 0.0 < p99 <= p99_max,
            }
            gates["shard_clean"] = {
                "dropped": leg["dropped"], "disconnects": leg["disconnects"],
                "ok": leg["dropped"] == 0 and leg["disconnects"] == 0,
            }
        if args.daemon_probe:
            gates["daemon_probe"] = {"ok": result["daemon_probe"]["ok"]}
        result["gates"] = gates
        ok = all(g["ok"] for g in gates.values())
    except fdbudget.FdBudgetError as e:
        result["error"] = str(e)
        print(str(e), file=sys.stderr)
    except Exception as e:  # noqa: BLE001 - evidence line carries the failure
        import traceback

        result["error"] = str(e)
        traceback.print_exc()

    result["serving_load_ok"] = ok
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=False)
            f.write("\n")
    summary = {
        "serving_load_ok": ok,
        "fanout_shards": result.get("run_meta", {}).get("fanout_shards", 0),
        "population": result.get("stages", [{}])[-1].get("population", 0),
        "p50_ms": result.get("stages", [{}])[-1].get("lag_ms", {}).get("p50", 0.0),
        "p99_ms": result.get("stages", [{}])[-1].get("lag_ms", {}).get("p99", 0.0),
        "drop_rate": result.get("rates", {}).get("drop_rate", 1.0),
        "overhead_off_over_on": result.get("overhead", {}).get("off_over_on", 0.0),
        "fanout_saturation_events_per_s": result.get("saturation", {}).get(
            "fanout_saturation_events_per_s", 0.0
        ),
        "error": result.get("error"),
    }
    print(json.dumps(summary))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
