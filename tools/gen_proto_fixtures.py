#!/usr/bin/env python
"""Regenerate the golden-vector protobuf fixtures.

Encodes every sample payload from kaspa_tpu.p2p.proto.vectors into
tests/fixtures/proto/<msg_type>.bin plus a manifest with sizes and the
schema oneof key per type.  Run after an intentional schema change and
commit the diff — tests/test_proto_wire.py pins these bytes.

    python tools/gen_proto_fixtures.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from kaspa_tpu.p2p.proto.codec import _CONVERTERS, encode_kaspad_message  # noqa: E402
from kaspa_tpu.p2p.proto.vectors import sample_payloads  # noqa: E402


def main() -> None:
    out_dir = os.path.join(os.path.dirname(__file__), "..", "tests", "fixtures", "proto")
    os.makedirs(out_dir, exist_ok=True)
    manifest = {}
    for msg_type, payload in sorted(sample_payloads().items()):
        data = encode_kaspad_message(msg_type, payload)
        with open(os.path.join(out_dir, f"{msg_type}.bin"), "wb") as f:
            f.write(data)
        manifest[msg_type] = {"oneof": _CONVERTERS[msg_type][0], "bytes": len(data)}
    with open(os.path.join(out_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"wrote {len(manifest)} fixtures to {os.path.relpath(out_dir)}")


if __name__ == "__main__":
    main()
