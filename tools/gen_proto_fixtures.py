#!/usr/bin/env python
"""Regenerate the golden-vector protobuf fixtures.

Encodes every sample payload from kaspa_tpu.p2p.proto.vectors into
tests/fixtures/proto/<msg_type>.bin plus a manifest with sizes and the
schema oneof key per type.  Run after an intentional schema change and
commit the diff — tests/test_proto_wire.py pins these bytes.

    python tools/gen_proto_fixtures.py          # rewrite fixtures
    python tools/gen_proto_fixtures.py --check  # re-encode in memory, diff

``--check`` never touches disk: it re-encodes every sample payload and
fails (exit 1) on any byte drift against the committed fixtures — the
ci_fastlane.sh wire-freeze gate.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from kaspa_tpu.p2p.proto.codec import _CONVERTERS, encode_kaspad_message  # noqa: E402
from kaspa_tpu.p2p.proto.vectors import sample_payloads  # noqa: E402


def _encode_all() -> dict[str, bytes]:
    return {
        msg_type: encode_kaspad_message(msg_type, payload)
        for msg_type, payload in sorted(sample_payloads().items())
    }


def check(out_dir: str) -> int:
    """Diff in-memory re-encodes against the committed fixture bytes."""
    drift = []
    frames = _encode_all()
    for msg_type, data in frames.items():
        path = os.path.join(out_dir, f"{msg_type}.bin")
        try:
            with open(path, "rb") as f:
                pinned = f.read()
        except FileNotFoundError:
            drift.append(f"{msg_type}: fixture missing (run tools/gen_proto_fixtures.py)")
            continue
        if pinned != data:
            drift.append(f"{msg_type}: {len(pinned)} pinned bytes != {len(data)} re-encoded")
    for line in drift:
        print(f"proto fixture drift: {line}", file=sys.stderr)
    if not drift:
        print(f"proto fixtures: {len(frames)} frames byte-identical")
    return 1 if drift else 0


def main(argv: list[str] | None = None) -> int:
    out_dir = os.path.join(os.path.dirname(__file__), "..", "tests", "fixtures", "proto")
    if "--check" in (argv if argv is not None else sys.argv[1:]):
        return check(out_dir)
    os.makedirs(out_dir, exist_ok=True)
    manifest = {}
    for msg_type, data in _encode_all().items():
        with open(os.path.join(out_dir, f"{msg_type}.bin"), "wb") as f:
            f.write(data)
        manifest[msg_type] = {"oneof": _CONVERTERS[msg_type][0], "bytes": len(data)}
    with open(os.path.join(out_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"wrote {len(manifest)} fixtures to {os.path.relpath(out_dir)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
