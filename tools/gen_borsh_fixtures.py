#!/usr/bin/env python
"""Regenerate the golden-vector Borsh wRPC fixtures.

Encodes every sample payload from kaspa_tpu.rpc.borsh_vectors into
tests/fixtures/borsh/<name>.bin plus a manifest with the wire op and
sizes.  Run after an intentional wire change and commit the diff —
tests/test_wrpc.py pins these bytes (and the op numbers: a renumbered op
is a wire break for every deployed client).

    python tools/gen_borsh_fixtures.py          # rewrite fixtures
    python tools/gen_borsh_fixtures.py --check  # re-encode in memory, diff

``--check`` never touches disk: it re-encodes every sample frame and
fails (exit 1) on any byte or op drift against the committed fixtures +
manifest — the ci_fastlane.sh wire-freeze gate.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from kaspa_tpu.rpc.borsh_vectors import sample_frames  # noqa: E402


def check(out_dir: str) -> int:
    """Diff in-memory re-encodes (bytes + ops) against the committed fixtures."""
    try:
        with open(os.path.join(out_dir, "manifest.json")) as f:
            manifest = json.load(f)
    except FileNotFoundError:
        manifest = {}
    drift = []
    frames = sorted(sample_frames().items())
    for name, (op, data) in frames:
        path = os.path.join(out_dir, f"{name}.bin")
        try:
            with open(path, "rb") as f:
                pinned = f.read()
        except FileNotFoundError:
            drift.append(f"{name}: fixture missing (run tools/gen_borsh_fixtures.py)")
            continue
        if pinned != data:
            drift.append(f"{name}: {len(pinned)} pinned bytes != {len(data)} re-encoded")
        if name in manifest and manifest[name]["op"] != op:
            drift.append(f"{name}: op renumbered {manifest[name]['op']} -> {op} (wire break)")
    for line in drift:
        print(f"borsh fixture drift: {line}", file=sys.stderr)
    if not drift:
        print(f"borsh fixtures: {len(frames)} frames byte-identical, ops stable")
    return 1 if drift else 0


def main(argv: list[str] | None = None) -> int:
    out_dir = os.path.join(os.path.dirname(__file__), "..", "tests", "fixtures", "borsh")
    if "--check" in (argv if argv is not None else sys.argv[1:]):
        return check(out_dir)
    os.makedirs(out_dir, exist_ok=True)
    manifest = {}
    for name, (op, data) in sorted(sample_frames().items()):
        with open(os.path.join(out_dir, f"{name}.bin"), "wb") as f:
            f.write(data)
        manifest[name] = {"op": op, "bytes": len(data)}
    with open(os.path.join(out_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"wrote {len(manifest)} fixtures to {os.path.relpath(out_dir)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
