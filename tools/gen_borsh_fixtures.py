#!/usr/bin/env python
"""Regenerate the golden-vector Borsh wRPC fixtures.

Encodes every sample payload from kaspa_tpu.rpc.borsh_vectors into
tests/fixtures/borsh/<name>.bin plus a manifest with the wire op and
sizes.  Run after an intentional wire change and commit the diff —
tests/test_wrpc.py pins these bytes (and the op numbers: a renumbered op
is a wire break for every deployed client).

    python tools/gen_borsh_fixtures.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from kaspa_tpu.rpc.borsh_vectors import sample_frames  # noqa: E402


def main() -> None:
    out_dir = os.path.join(os.path.dirname(__file__), "..", "tests", "fixtures", "borsh")
    os.makedirs(out_dir, exist_ok=True)
    manifest = {}
    for name, (op, data) in sorted(sample_frames().items()):
        with open(os.path.join(out_dir, f"{name}.bin"), "wb") as f:
            f.write(data)
        manifest[name] = {"op": op, "bytes": len(data)}
    with open(os.path.join(out_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"wrote {len(manifest)} fixtures to {os.path.relpath(out_dir)}")


if __name__ == "__main__":
    main()
