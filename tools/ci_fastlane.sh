#!/usr/bin/env bash
# The pre-PR fast lane: static analysis + tier-1 tests + wire-freeze
# fixture checks.
#
# Runs, in order:
#   1. proto golden-fixture check  (tools/gen_proto_fixtures.py --check)
#   2. borsh golden-fixture check  (tools/gen_borsh_fixtures.py --check)
#   3. graftlint static analysis   (tools/lint.py, writes LINT.json)
#   4. the tier-1 pytest fast lane (tests/, -m "not slow")
#
# The fixture checks re-encode every sample payload in memory and diff
# against the committed bytes under tests/fixtures/{proto,borsh} — any
# drift is a wire break and fails before the test suite even starts.
# roundcheck's tier1 section shells out to this script, and it is the
# gate to run locally before opening a PR:
#
#     bash tools/ci_fastlane.sh
#
# Exit 0 iff all four stages pass.

set -u
REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO_ROOT"
export PYTHONPATH="$REPO_ROOT${PYTHONPATH:+:$PYTHONPATH}"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
PY="${PYTHON:-python}"

fail=0

echo "[ci_fastlane] 1/4 proto wire-freeze check"
"$PY" tools/gen_proto_fixtures.py --check || fail=1

echo "[ci_fastlane] 2/4 borsh wire-freeze check"
"$PY" tools/gen_borsh_fixtures.py --check || fail=1

echo "[ci_fastlane] 3/4 graftlint static analysis (ratcheted vs committed LINT.json)"
"$PY" tools/lint.py -q --ratchet || fail=1

echo "[ci_fastlane] 4/4 tier-1 fast lane"
pytest_log="$(mktemp)"
trap 'rm -f "$pytest_log"' EXIT
"$PY" -m pytest tests/ -q -m "not slow" \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee "$pytest_log"
rc=${PIPESTATUS[0]}
[ "$rc" -eq 0 ] || fail=1

if [ "$fail" -eq 0 ]; then
    echo "[ci_fastlane] PASS"
else
    echo "[ci_fastlane] FAIL"
fi
exit "$fail"
