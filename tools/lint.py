#!/usr/bin/env python3
"""Repo-root graftlint wrapper: ``python tools/lint.py [paths...]``.

Pins --root to the repo root (so findings are repo-relative regardless
of cwd), defaults --json to LINT.json next to this script's parent, and
turns on the gated kernel-shape audit (``--no-shapes`` opts out — e.g.
in environments without a importable jax).  Everything else is
``python -m kaspa_tpu.analysis``; in particular ``--ratchet`` compares
against the *committed* LINT.json before overwriting it, and ``--knobs``
regenerates KNOBS.md.
"""

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

from kaspa_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    argv = sys.argv[1:]
    if "--root" not in argv:
        argv = ["--root", _ROOT, *argv]
    if "--json" not in argv:
        argv = ["--json", os.path.join(_ROOT, "LINT.json"), *argv]
    if "--no-shapes" in argv:
        argv = [a for a in argv if a != "--no-shapes"]
    elif "--shapes" not in argv and "--knobs" not in argv:
        argv = ["--shapes", *argv]
    sys.exit(main(argv))
