#!/usr/bin/env python3
"""Repo-root graftlint wrapper: ``python tools/lint.py [paths...]``.

Pins --root to the repo root (so findings are repo-relative regardless
of cwd) and defaults --json to LINT.json next to this script's parent.
Everything else is ``python -m kaspa_tpu.analysis``.
"""

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

from kaspa_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    argv = sys.argv[1:]
    if "--root" not in argv:
        argv = ["--root", _ROOT, *argv]
    if "--json" not in argv:
        argv = ["--json", os.path.join(_ROOT, "LINT.json"), *argv]
    sys.exit(main(argv))
