"""Ad-hoc kernel-variant bench: times the plain vs GLV Pallas ladder on
the live device (run from the repo root).  Not part of the driver bench."""
import random, time
import numpy as np
from kaspa_tpu.utils import jax_setup
jax_setup.setup()
from kaspa_tpu.crypto import eclib
from kaspa_tpu.crypto.secp import schnorr_challenge
from kaspa_tpu.ops import bigint as bi
from kaspa_tpu.ops.secp256k1.ladder_pallas import verify_batch_pallas

B = 16384
UNIQUE = 32
random.seed(2026)
sk = random.randrange(1, eclib.N)
pub = eclib.schnorr_pubkey(sk)
pk = eclib.lift_x(int.from_bytes(pub, "big"))
msgs = [random.randbytes(32) for _ in range(UNIQUE)]
sigs = [eclib.schnorr_sign(m, sk, b"\x05" * 32) for m in msgs]
expect = [True] * UNIQUE
for i in range(0, UNIQUE, 4):
    sigs[i] = sigs[i][:40] + bytes([sigs[i][40] ^ 1]) + sigs[i][41:]
    expect[i] = False
reps = B // UNIQUE
px = np.tile(bi.int_to_limbs(pk[0], 16), (B, 1)).astype(np.int32)
py = np.tile(bi.int_to_limbs(pk[1], 16), (B, 1)).astype(np.int32)
rc = np.tile(np.stack([bi.int_to_limbs(int.from_bytes(s[:32], "big"), 16) for s in sigs]), (reps, 1))
s_ints = [int.from_bytes(s[32:], "big") % eclib.N for s in sigs] * reps
e_ints = [schnorr_challenge(s[:32], pub, msgs[i]) for i, s in enumerate(sigs)] * reps
ok = np.ones(B, dtype=bool)
for glv in (False,):
    mask = np.asarray(verify_batch_pallas(px, py, rc, s_ints, e_ints, ok, ecdsa=False, glv=glv))
    assert mask.tolist() == expect * reps, "MISMATCH glv=%s" % glv
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        out = np.asarray(verify_batch_pallas(px, py, rc, s_ints, e_ints, ok, ecdsa=False, glv=glv))
        best = min(best, time.perf_counter() - t0)
    print("glv=%s: %.1f verifies/sec" % (glv, B / best))
