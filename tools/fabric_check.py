#!/usr/bin/env python
"""Two-process verify-fabric drill: bit-identity + slice-kill failover.

Spawns a standalone verifyd slice server (`python -m
kaspa_tpu.fabric.service`), replays the same simulated DAG three ways in
this process — local-only, over the fabric, and over the fabric with the
server SIGKILLed mid-replay — and gates on:

- the fabric replay reaching the byte-identical sink + utxo_commitment
  of the local-only replay, with remote chunks actually served and the
  balancer's zero-lost-tickets invariant holding (``lost == 0``);
- the kill drill converging to the same fingerprints
  (``matches_fault_free``) with every post-kill chunk absorbed by the
  bit-identical host degraded lane — failover loses nothing.

Prints one JSON line (the roundcheck ``fabric`` section consumes it);
exit 0 iff every gate holds.

    python tools/fabric_check.py --blocks 24
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from kaspa_tpu.utils import jax_setup  # noqa: E402

jax_setup.setup()

from kaspa_tpu.fabric import balancer as fabric_balancer  # noqa: E402
from kaspa_tpu.sim.simulator import SimConfig, replay, simulate  # noqa: E402


def _log(msg: str) -> None:
    print(f"[fabric_check] {msg}", file=sys.stderr, flush=True)


def _spawn_server(slices: int) -> tuple[subprocess.Popen, str]:
    """Start a verifyd on an ephemeral port; returns (proc, host:port)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "kaspa_tpu.fabric.service",
         "--listen", "127.0.0.1:0", "--slices", str(slices)],
        cwd=REPO_ROOT, env=env, stdout=subprocess.PIPE, text=True,
    )
    line = proc.stdout.readline()
    try:
        info = json.loads(line)
    except (json.JSONDecodeError, TypeError):
        proc.kill()
        raise SystemExit(f"verifyd failed to start (got {line!r})")
    return proc, info["fabric_listen"]


def _stop_server(proc: subprocess.Popen) -> None:
    if proc.poll() is None:
        proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=10)
    proc.stdout.close()


def _warm_server(addr: str) -> None:
    """One verify round-trip with a generous deadline before the drill
    arms its short one: a fresh verifyd pays the first-dispatch kernel
    trace/compile on its first request, and the drill must measure
    failover behaviour, not cold-start compile latency."""
    import hashlib

    from kaspa_tpu.crypto import eclib

    msg = hashlib.sha256(b"fabric-warmup").digest()
    items = [(eclib.schnorr_pubkey(7), msg, eclib.schnorr_sign(msg, 7))]
    warm = fabric_balancer.FabricBalancer([addr], deadline_s=300.0)  # not installed
    try:
        if not warm.submit("schnorr", items).wait(timeout=300.0).all():
            raise SystemExit("fabric warmup verified a valid signature as invalid")
    finally:
        warm.close(timeout=10.0)


def _fingerprints(fresh) -> dict:
    sink = fresh.sink()
    return {"sink": sink.hex(), "utxo_commitment": fresh.multisets[sink].finalize().hex()}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--blocks", type=int, default=24,
                    help="replay length (>= ~24 so coinbase maturity passes and real "
                    "signature batches flow over the wire)")
    ap.add_argument("--tpb", type=int, default=4)
    ap.add_argument("--slices", type=int, default=2)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--deadline", type=float, default=3.0,
                    help="kill-drill fabric deadline: how long a chunk may hang on the "
                    "dead server before the per-slice breaker trips and it fails over")
    args = ap.parse_args(argv)

    t_start = time.monotonic()
    cfg = SimConfig(bps=2, num_blocks=args.blocks, txs_per_block=args.tpb, seed=args.seed)
    res = simulate(cfg)
    _log(f"built {len(res.blocks)} blocks / {res.total_txs} txs")

    _, fresh = replay(res)
    base = _fingerprints(fresh)
    _log(f"local-only replay: sink {base['sink'][:16]}…")

    # --- fabric replay: identical fingerprints, chunks actually remote ---
    proc, addr = _spawn_server(args.slices)
    try:
        bal = fabric_balancer.configure(addr)
        _, fresh2 = replay(res)
        bal.drain(timeout=30.0)
        fab = _fingerprints(fresh2)
        stats = bal.stats()
    finally:
        fabric_balancer.shutdown(timeout=10.0)
        _stop_server(proc)
    identity = {
        "matches_local": fab == base,
        "remote_chunks": stats["remote"],
        "degraded_chunks": stats["degraded"],
        "lost": stats["lost"],
        "slices": stats["slices"],
    }
    _log(f"fabric replay: matches={identity['matches_local']} remote={stats['remote']} lost={stats['lost']}")

    # --- slice-kill drill: SIGKILL the server mid-replay, lose nothing ---
    proc2, addr2 = _spawn_server(args.slices)
    _warm_server(addr2)
    killed = threading.Event()
    stop_watch = threading.Event()

    def _killer(bal2):
        # wait for the first remotely-served chunk so the kill provably
        # lands mid-replay, then let a little more traffic through
        while not stop_watch.is_set():
            if bal2.stats()["remote"] >= 1:
                time.sleep(0.3)
                if proc2.poll() is None:
                    proc2.send_signal(signal.SIGKILL)
                killed.set()
                return
            time.sleep(0.05)

    try:
        bal2 = fabric_balancer.configure(addr2, deadline_s=args.deadline)
        watcher = threading.Thread(target=_killer, args=(bal2,), daemon=True)
        watcher.start()
        _, fresh3 = replay(res)
        bal2.drain(timeout=30.0)
        fab3 = _fingerprints(fresh3)
        st3 = bal2.stats()
    finally:
        stop_watch.set()
        fabric_balancer.shutdown(timeout=10.0)
        _stop_server(proc2)
    drill = {
        "killed_mid_replay": killed.is_set(),
        "matches_fault_free": fab3 == base,
        "remote_chunks": st3["remote"],
        "degraded_chunks": st3["degraded"],
        "failovers": st3["failovers"],
        "breaker_trips": sum(s["trips"] for s in st3["slices"]),
        "lost": st3["lost"],
    }
    _log(
        f"kill drill: killed={drill['killed_mid_replay']} matches={drill['matches_fault_free']} "
        f"degraded={drill['degraded_chunks']} lost={drill['lost']}"
    )

    ok = (
        identity["matches_local"]
        and identity["remote_chunks"] >= 1
        and identity["lost"] == 0
        and drill["killed_mid_replay"]
        and drill["matches_fault_free"]
        and drill["degraded_chunks"] >= 1
        and drill["lost"] == 0
    )
    print(json.dumps({
        "fabric_ok": ok,
        "blocks": len(res.blocks),
        "txs": res.total_txs,
        "identity": identity,
        "kill_drill": drill,
        "seconds": round(time.monotonic() - t_start, 1),
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
