#!/usr/bin/env python
"""Replay a captured span log into a per-stage flame summary.

Input: a JSONL span log written by ``kaspa_tpu.observability.trace.dump``
(one span dict per line: name/path/start_us/dur_us/thread/depth/attrs), or
a JSON document embedding such a list under an ``observability`` /
``spans`` key — e.g. a bench.py result line or a BENCH_*.json entry whose
``tail`` carries the snapshot.

Output: a path-aggregated flame table (total vs self time, counts,
mean/max) plus the slowest individual spans — enough to answer "which
stage stalled" when a bench reports 0.0 verifies/sec:

    python tools/trace_report.py /tmp/spans.jsonl
    python tools/trace_report.py BENCH_r06.json --top 15
"""

from __future__ import annotations

import argparse
import json
import sys


def _find_spans(obj) -> list | None:
    """Depth-first hunt for a list of span dicts inside a JSON document."""
    if isinstance(obj, list):
        if obj and isinstance(obj[0], dict) and "dur_us" in obj[0] and ("path" in obj[0] or "name" in obj[0]):
            return obj
        for item in obj:
            found = _find_spans(item)
            if found is not None:
                return found
        return None
    if isinstance(obj, dict):
        for key in ("spans", "observability", "tail"):
            if key in obj:
                found = _find_spans(obj[key])
                if found is not None:
                    return found
        for v in obj.values():
            found = _find_spans(v)
            if found is not None:
                return found
    return None


def load_spans(path: str) -> list[dict]:
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if doc is not None:
        spans = _find_spans(doc)
        if spans is None:
            raise SystemExit(f"{path}: JSON document contains no span list")
        return spans
    spans = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        spans.append(json.loads(line))
    return spans


def aggregate(spans: list[dict]) -> dict[str, dict]:
    """Per-path totals; self time = total minus direct children's total."""
    agg: dict[str, dict] = {}
    for s in spans:
        path = s.get("path") or s.get("name", "?")
        a = agg.setdefault(path, {"count": 0, "total_us": 0.0, "max_us": 0.0})
        dur = float(s.get("dur_us", 0.0))
        a["count"] += 1
        a["total_us"] += dur
        if dur > a["max_us"]:
            a["max_us"] = dur
    for path, a in agg.items():
        child_total = sum(
            other["total_us"]
            for opath, other in agg.items()
            if opath.startswith(path + "/") and "/" not in opath[len(path) + 1 :]
        )
        a["self_us"] = max(0.0, a["total_us"] - child_total)
    return agg


def _ms(us: float) -> str:
    return f"{us / 1000.0:10.3f}"


def render_report(spans: list[dict], top: int = 10) -> str:
    if not spans:
        return "no spans in input\n"
    agg = aggregate(spans)
    lines = [f"{len(spans)} spans over {len(agg)} stages", ""]
    lines.append(f"{'stage (path)':<52} {'count':>7} {'total ms':>10} {'self ms':>10} {'mean ms':>9} {'max ms':>10}")
    lines.append("-" * 102)
    # flame ordering: depth-first by path so children sit under parents,
    # roots sorted by total time descending
    roots = sorted(
        (p for p in agg if "/" not in p), key=lambda p: -agg[p]["total_us"]
    )

    def emit(path: str, indent: int) -> None:
        a = agg[path]
        label = ("  " * indent) + path.rsplit("/", 1)[-1]
        mean = a["total_us"] / a["count"]
        lines.append(
            f"{label:<52} {a['count']:>7} {_ms(a['total_us'])} {_ms(a['self_us'])} "
            f"{mean / 1000.0:>9.3f} {_ms(a['max_us'])}"
        )
        children = sorted(
            (
                p
                for p in agg
                if p.startswith(path + "/") and "/" not in p[len(path) + 1 :]
            ),
            key=lambda p: -agg[p]["total_us"],
        )
        for c in children:
            emit(c, indent + 1)

    for r in roots:
        emit(r, 0)
    lines.append("")
    lines.append(f"slowest {top} spans:")
    slowest = sorted(spans, key=lambda s: -float(s.get("dur_us", 0.0)))[:top]
    for s in slowest:
        attrs = s.get("attrs") or {}
        attr_txt = " ".join(f"{k}={v}" for k, v in attrs.items())
        lines.append(
            f"  {float(s.get('dur_us', 0.0)) / 1000.0:10.3f} ms  {s.get('path', s.get('name', '?')):<40}"
            f" [{s.get('thread', '?')}] {attr_txt}"
        )
    return "\n".join(lines) + "\n"


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="per-stage flame summary from a span log")
    ap.add_argument("log", help="span JSONL file or JSON document embedding a span list")
    ap.add_argument("--top", type=int, default=10, help="slowest individual spans to list")
    args = ap.parse_args(argv)
    sys.stdout.write(render_report(load_spans(args.log), top=args.top))


if __name__ == "__main__":
    main()
