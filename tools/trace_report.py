#!/usr/bin/env python
"""Replay a captured span log into a per-stage flame summary.

Input: a JSONL span log written by ``kaspa_tpu.observability.trace.dump``
(one span dict per line: name/path/start_us/dur_us/thread/depth/attrs), or
a JSON document embedding such a list under an ``observability`` /
``spans`` key — e.g. a bench.py result line or a BENCH_*.json entry whose
``tail`` carries the snapshot — or a flight-recorder dump
(``kaspa_tpu.observability.flight.dump``: per-block span trees with
critical-path attribution).

Output: a path-aggregated flame table (total vs self time, counts,
mean/max) plus the slowest individual spans — enough to answer "which
stage stalled" when a bench reports 0.0 verifies/sec.  Flight dumps
additionally get a per-block critical-path table, and export to the
Chrome trace-event format that ui.perfetto.dev / chrome://tracing load:

    python tools/trace_report.py /tmp/spans.jsonl
    python tools/trace_report.py BENCH_r06.json --top 15
    python tools/trace_report.py FLIGHT.json --critical-path
    python tools/trace_report.py FLIGHT.json --perfetto trace.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _flight_module():
    """Import kaspa_tpu.observability.flight, tolerating a bare checkout
    (tools/ run from anywhere without the package installed)."""
    try:
        from kaspa_tpu.observability import flight
    except ImportError:
        sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        from kaspa_tpu.observability import flight
    return flight


def load_flight(path: str) -> dict | None:
    """Return the parsed flight dump if ``path`` holds one, else None."""
    with open(path) as f:
        head = f.read(256)
    if '"kaspa-flight"' not in head:
        return None
    with open(path) as f:
        doc = json.load(f)
    if doc.get("format") != "kaspa-flight":
        return None
    return doc


def _find_spans(obj) -> list | None:
    """Depth-first hunt for a list of span dicts inside a JSON document."""
    if isinstance(obj, list):
        if obj and isinstance(obj[0], dict) and "dur_us" in obj[0] and ("path" in obj[0] or "name" in obj[0]):
            return obj
        for item in obj:
            found = _find_spans(item)
            if found is not None:
                return found
        return None
    if isinstance(obj, dict):
        for key in ("spans", "observability", "tail"):
            if key in obj:
                found = _find_spans(obj[key])
                if found is not None:
                    return found
        for v in obj.values():
            found = _find_spans(v)
            if found is not None:
                return found
    return None


def load_spans(path: str) -> list[dict]:
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if doc is not None:
        spans = _find_spans(doc)
        if spans is None:
            raise SystemExit(f"{path}: JSON document contains no span list")
        return spans
    spans = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        spans.append(json.loads(line))
    return spans


def aggregate(spans: list[dict]) -> dict[str, dict]:
    """Per-path totals; self time = total minus direct children's total."""
    agg: dict[str, dict] = {}
    for s in spans:
        path = s.get("path") or s.get("name", "?")
        a = agg.setdefault(path, {"count": 0, "total_us": 0.0, "max_us": 0.0})
        dur = float(s.get("dur_us", 0.0))
        a["count"] += 1
        a["total_us"] += dur
        if dur > a["max_us"]:
            a["max_us"] = dur
    for path, a in agg.items():
        child_total = sum(
            other["total_us"]
            for opath, other in agg.items()
            if opath.startswith(path + "/") and "/" not in opath[len(path) + 1 :]
        )
        a["self_us"] = max(0.0, a["total_us"] - child_total)
    return agg


def _ms(us: float) -> str:
    return f"{us / 1000.0:10.3f}"


def render_report(spans: list[dict], top: int = 10) -> str:
    if not spans:
        return "no spans in input\n"
    agg = aggregate(spans)
    lines = [f"{len(spans)} spans over {len(agg)} stages", ""]
    lines.append(f"{'stage (path)':<52} {'count':>7} {'total ms':>10} {'self ms':>10} {'mean ms':>9} {'max ms':>10}")
    lines.append("-" * 102)
    # flame ordering: depth-first by path so children sit under parents,
    # roots sorted by total time descending
    roots = sorted(
        (p for p in agg if "/" not in p), key=lambda p: -agg[p]["total_us"]
    )

    def emit(path: str, indent: int) -> None:
        a = agg[path]
        label = ("  " * indent) + path.rsplit("/", 1)[-1]
        mean = a["total_us"] / a["count"]
        lines.append(
            f"{label:<52} {a['count']:>7} {_ms(a['total_us'])} {_ms(a['self_us'])} "
            f"{mean / 1000.0:>9.3f} {_ms(a['max_us'])}"
        )
        children = sorted(
            (
                p
                for p in agg
                if p.startswith(path + "/") and "/" not in p[len(path) + 1 :]
            ),
            key=lambda p: -agg[p]["total_us"],
        )
        for c in children:
            emit(c, indent + 1)

    for r in roots:
        emit(r, 0)
    lines.append("")
    lines.append(f"slowest {top} spans:")
    slowest = sorted(spans, key=lambda s: -float(s.get("dur_us", 0.0)))[:top]
    for s in slowest:
        attrs = s.get("attrs") or {}
        attr_txt = " ".join(f"{k}={v}" for k, v in attrs.items())
        lines.append(
            f"  {float(s.get('dur_us', 0.0)) / 1000.0:10.3f} ms  {s.get('path', s.get('name', '?')):<40}"
            f" [{s.get('thread', '?')}] {attr_txt}"
        )
    return "\n".join(lines) + "\n"


def render_by_shard(spans: list[dict], top: int = 10) -> str:
    """Flame table grouped by the ``shard`` attr serving spans carry
    (``serving.fanout`` / ``serving.deliver`` / ``wait.serving_queue``):
    per-shard totals first, then each shard's stage breakdown.  Spans
    without a shard tag (the single-fanout path, consensus stages) group
    under ``unsharded``."""
    if not spans:
        return "no spans in input\n"
    groups: dict[str, list[dict]] = {}
    for s in spans:
        shard = (s.get("attrs") or {}).get("shard")
        key = f"shard {shard}" if shard is not None else "unsharded"
        groups.setdefault(key, []).append(s)
    lines = [f"{len(spans)} spans over {len(groups)} shard groups", ""]
    lines.append(f"{'group':<14} {'spans':>7} {'total ms':>10} {'max ms':>10}")
    lines.append("-" * 44)
    order = sorted(
        groups, key=lambda g: -sum(float(s.get("dur_us", 0.0)) for s in groups[g])
    )
    for g in order:
        durs = [float(s.get("dur_us", 0.0)) for s in groups[g]]
        lines.append(
            f"{g:<14} {len(durs):>7} {_ms(sum(durs))} {_ms(max(durs))}"
        )
    for g in order:
        lines.append("")
        lines.append(f"== {g} ==")
        lines.append(render_report(groups[g], top=top).rstrip())
    return "\n".join(lines) + "\n"


def render_critical_path(doc: dict, top: int = 10) -> str:
    """Per-block critical-path table + aggregate stage attribution for a
    flight dump (recomputed from the span trees, so dumps predating the
    embedded summary still work)."""
    flight = _flight_module()
    traces = doc.get("traces", [])
    if not traces:
        return "no traces in flight dump\n"
    lines = [f"{len(traces)} block traces (dump reason: {doc.get('reason', '?')})", ""]
    lines.append(f"{'block':<18} {'spans':>6} {'threads':>8} {'wall ms':>9} {'attrib %':>9}  top stages")
    lines.append("-" * 100)
    agg: dict[str, float] = {}
    fractions = []
    for t in traces:
        spans = t["spans"]
        root = next((s for s in spans if s["name"] == "block"), spans[0])
        cp = flight.critical_path(spans, root["span"])
        fractions.append(cp["fraction"])
        stages = sorted(cp["stages"].items(), key=lambda kv: -kv[1])
        for name, ns in stages:
            agg[name] = agg.get(name, 0.0) + ns
        top3 = " ".join(f"{n}={ns / 1e6:.1f}ms" for n, ns in stages[:3] if n != "block")
        lines.append(
            f"{t['label'][:16]:<18} {len(spans):>6} {len({s['thread'] for s in spans}):>8} "
            f"{cp['total_ns'] / 1e6:>9.2f} {cp['fraction'] * 100:>8.1f}%  {top3}"
        )
    lines.append("")
    lines.append(f"min/mean attribution: {min(fractions) * 100:.1f}% / {sum(fractions) / len(fractions) * 100:.1f}%")
    lines.append("")
    lines.append("aggregate critical-path time by stage:")
    total = sum(agg.values()) or 1.0
    for name, ns in sorted(agg.items(), key=lambda kv: -kv[1])[:top]:
        lines.append(f"  {name:<28} {ns / 1e6:>10.2f} ms  {ns / total * 100:>5.1f}%")
    return "\n".join(lines) + "\n"


def export_perfetto(doc: dict, out_path: str) -> str:
    """Write the Chrome trace-event JSON for a flight dump; load the file
    at ui.perfetto.dev or chrome://tracing."""
    flight = _flight_module()
    chrome = flight.chrome_trace(doc.get("traces", []))
    with open(out_path, "w") as f:
        json.dump(chrome, f)
    return out_path


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="per-stage flame summary from a span log")
    ap.add_argument("log", help="span JSONL file, JSON document embedding a span list, or flight dump")
    ap.add_argument("--top", type=int, default=10, help="slowest individual spans to list")
    ap.add_argument(
        "--perfetto", default=None, metavar="OUT",
        help="convert a flight dump to Chrome trace-event JSON at OUT (open in ui.perfetto.dev)",
    )
    ap.add_argument(
        "--critical-path", action="store_true",
        help="per-block critical-path attribution table (flight dumps only)",
    )
    ap.add_argument(
        "--by-shard", action="store_true",
        help="group the flame table by the serving tier's shard span tag "
        "(untagged spans group under 'unsharded')",
    )
    args = ap.parse_args(argv)
    doc = load_flight(args.log)
    if args.by_shard:
        spans = (
            [s for t in doc.get("traces", []) for s in t["spans"]]
            if doc is not None
            else load_spans(args.log)
        )
        sys.stdout.write(render_by_shard(spans, top=args.top))
        return
    if args.perfetto or args.critical_path:
        if doc is None:
            raise SystemExit(f"{args.log}: not a flight-recorder dump (need format=kaspa-flight)")
        if args.perfetto:
            path = export_perfetto(doc, args.perfetto)
            n = sum(len(t["spans"]) for t in doc.get("traces", []))
            sys.stdout.write(f"wrote {path}: {len(doc.get('traces', []))} block traces, {n} spans\n")
        if args.critical_path:
            sys.stdout.write(render_critical_path(doc, top=args.top))
        return
    if doc is not None:
        spans = [s for t in doc.get("traces", []) for s in t["spans"]]
        sys.stdout.write(render_report(spans, top=args.top))
        sys.stdout.write("\n")
        sys.stdout.write(render_critical_path(doc, top=args.top))
        return
    sys.stdout.write(render_report(load_spans(args.log), top=args.top))


if __name__ == "__main__":
    main()
