#!/usr/bin/env python
"""Serving-tier round evidence: dual-encoding streams + kill -9 reopen.

Boots a persistent daemon child, attaches one JSON and one Borsh wRPC
client to the same node, subscribes both to UtxosChanged scoped to the
miner address, and mines a short chain over RPC.  Asserts the two
encodings observed the IDENTICAL filtered stream, scrapes the serving
metrics (subscriber-lag histograms, per-encoding request counters, drop
counters), then kill -9s the daemon and reopens the on-disk utxoindex:
the acceptance bit is ``open_mode != "resync"`` with content
byte-identical to a fresh resync.  Prints one JSON line as the last
stdout line (consumed by tools/roundcheck.py).

    python tools/serving_check.py --blocks 10
"""

from __future__ import annotations

import argparse
import io
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import textwrap
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from kaspa_tpu.utils import jax_setup  # noqa: E402

jax_setup.setup()

_DAEMON_SCRIPT = textwrap.dedent(
    """
    import sys, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    from kaspa_tpu.utils import jax_setup; jax_setup.setup()
    from kaspa_tpu.node.daemon import Daemon, parse_args

    args = parse_args(["--appdir", sys.argv[1], "--rpclisten", "127.0.0.1:0",
                       "--rpclisten-wrpc", "127.0.0.1:0", "--bps", "2", "--persist"])
    d = Daemon(args)
    d.start()
    print("WRPC " + d.wrpc_server.address, flush=True)
    while True:
        time.sleep(3600)
    """
)


def _json_key(pairs) -> list[tuple]:
    return [
        (p["outpoint"]["transaction_id"], p["outpoint"]["index"], p["utxo_entry"]["amount"],
         p["utxo_entry"]["script_public_key"]["script"])
        for p in pairs
    ]


def _borsh_key(entries) -> list[tuple]:
    return [
        (op.transaction_id.hex(), op.index, e.amount, e.script_public_key.script.hex())
        for _addr, op, e in entries
    ]


def _scrape_serving_metrics(prom_text: str) -> dict:
    lag: dict = {}
    for kind, enc, val in re.findall(
        r'kaspa_serving_subscriber_lag_seconds_(count|sum)\{encoding="([\w-]+)"\} (\S+)', prom_text
    ):
        lag.setdefault(enc, {})[kind] = float(val)
    requests = {
        enc: int(float(v))
        for enc, v in re.findall(r'kaspa_rpc_requests_by_encoding_total\{encoding="([\w-]+)"\} (\S+)', prom_text)
    }
    m = re.search(r"kaspa_serving_subscriber_dropped_total (\S+)", prom_text)
    return {
        "subscriber_lag_seconds": lag,
        "rpc_requests_by_encoding": requests,
        "subscriber_dropped": int(float(m.group(1))) if m else 0,
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--blocks", type=int, default=10, help="blocks mined over RPC before the kill")
    ap.add_argument("--events", type=int, default=2, help="UtxosChanged events required on each stream")
    ap.add_argument("--appdir", default=None, help="daemon appdir (default: a fresh temp dir)")
    ap.add_argument("--timeout", type=float, default=120.0, help="daemon boot + stream deadline (s)")
    args = ap.parse_args(argv)

    appdir = args.appdir or tempfile.mkdtemp(prefix="serving-check-")
    script = os.path.join(appdir, "daemon-child.py")
    with open(script, "w") as f:
        f.write(_DAEMON_SCRIPT)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, script, appdir],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
    )

    result: dict = {"appdir": appdir, "blocks": args.blocks}
    ok = False
    client_json = client_borsh = None
    try:
        addr = None
        deadline = time.monotonic() + args.timeout
        for line in proc.stdout:
            if line.startswith("WRPC "):
                addr = line.split(" ", 1)[1].strip()
                break
            if time.monotonic() > deadline:
                break
        if addr is None:
            result["error"] = "daemon never came up: " + proc.stderr.read()[-400:]
            raise RuntimeError(result["error"])

        import random

        from kaspa_tpu.crypto.addresses import extract_script_pub_key_address
        from kaspa_tpu.rpc import borsh_codec as bc
        from kaspa_tpu.rpc.wrpc import WrpcClient
        from kaspa_tpu.sim.simulator import Miner

        miner = Miner(0, random.Random(2))
        pay = extract_script_pub_key_address(miner.spk, "kaspasim").to_string()

        client_json = WrpcClient(addr)
        client_borsh = WrpcClient(addr, encoding="borsh")
        client_json.subscribe("utxos-changed", [pay])
        client_borsh.subscribe_borsh(bc.OP_UTXOS_CHANGED_NOTIFICATION, [pay])

        for _ in range(args.blocks):
            t = client_json.call("getBlockTemplate", {"payAddress": pay})
            client_json.call("submitBlockByTemplateHash", {"hash": t["block_hash"]})

        json_events = []
        deadline = time.monotonic() + args.timeout
        while len(json_events) < args.events and time.monotonic() < deadline:
            try:
                event, data = client_json.next_notification(timeout=10)
            except Exception:  # noqa: BLE001 - keep polling to the deadline
                continue
            if event == "utxos-changed":
                json_events.append(data)
        borsh_events = []
        while len(borsh_events) < len(json_events) and time.monotonic() < deadline:
            try:
                op, payload = client_borsh.borsh_notifications.get(timeout=10)
            except Exception:  # noqa: BLE001
                continue
            if op == bc.OP_UTXOS_CHANGED_NOTIFICATION:
                borsh_events.append(bc.decode_utxos_changed_notification(io.BytesIO(payload)))

        result["events_json"] = len(json_events)
        result["events_borsh"] = len(borsh_events)
        result["streams_identical"] = (
            len(json_events) >= args.events
            and len(json_events) == len(borsh_events)
            and all(
                _json_key(j["added"]) == _borsh_key(b["added"])
                and _json_key(j["removed"]) == _borsh_key(b["removed"])
                for j, b in zip(json_events, borsh_events)
            )
        )

        raw = client_borsh.call_borsh(bc.OP_GET_COIN_SUPPLY, _supply_req(bc))
        result["circulating_sompi"] = bc.decode_get_coin_supply_response(io.BytesIO(raw))["circulating_sompi"]
        result["metrics"] = _scrape_serving_metrics(client_json.call("getMetricsPrometheus"))

        # --- kill -9, then the reopened index must reconcile, not rebuild ---
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait()
        for c in (client_json, client_borsh):
            c.close()
        client_json = client_borsh = None

        from kaspa_tpu.consensus.consensus import Consensus
        from kaspa_tpu.consensus.params import simnet_params
        from kaspa_tpu.index.utxoindex import UtxoIndex
        from kaspa_tpu.storage.kv import KvStore

        active = "consensus.db"
        active_path = os.path.join(appdir, "ACTIVE")
        if os.path.exists(active_path):
            with open(active_path) as f:
                name = f.read().strip()
            if name and os.path.exists(os.path.join(appdir, name)):
                active = name
        db = KvStore(os.path.join(appdir, active))
        c = Consensus(simnet_params(bps=2), db=db)
        idx = UtxoIndex(c, db_path=os.path.join(appdir, "utxoindex.db"))
        fresh = UtxoIndex(c, db_path=os.path.join(appdir, "utxoindex-fresh.db"))
        result["reopen_mode"] = idx.open_mode
        result["journal_rewinds"] = idx.journal_rewinds
        result["catchup_blocks"] = idx.catchup_blocks
        result["reopen_identical"] = idx.content_snapshot() == fresh.content_snapshot()
        result["reopen_supply"] = idx.get_circulating_supply()
        idx.close()
        fresh.close()
        db.close()

        ok = (
            result["streams_identical"]
            and result["reopen_mode"] in ("clean", "catchup")
            and result["reopen_identical"]
        )
    except Exception as e:  # noqa: BLE001 - evidence line carries the failure
        result.setdefault("error", str(e))
    finally:
        for c in (client_json, client_borsh):
            if c is not None:
                c.close()
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    result["serving_ok"] = ok
    print(json.dumps(result))
    return 0 if ok else 1


def _supply_req(bc) -> bytes:
    w = io.BytesIO()
    bc.encode_get_coin_supply_request(w)
    return w.getvalue()


if __name__ == "__main__":
    sys.exit(main())
