#!/usr/bin/env python
"""Probe: can the limb-product convolution ride the MXU?

Times candidate formulations of the 256x256-bit schoolbook product at
batch B to pick the mul engine for the secp kernel.
"""
from __future__ import annotations

import sys
import time

sys.path.insert(0, "/root/repo")

import numpy as np

from kaspa_tpu.utils import jax_setup

jax_setup.setup()

import jax
import jax.numpy as jnp

B = 16384


def bench(f, args, iters=20, warmup=3):
    g = jax.jit(f)
    for _ in range(warmup):
        out = g(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = g(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def onehot(k):
    m = np.zeros((k * k, 2 * k), np.float32)
    for i in range(k):
        for j in range(k):
            m[i * k + j, i + j] = 1
    return m


def main():
    rng = np.random.default_rng(0)
    # current path: int32 8-bit split outer + onehot dot
    a8 = jnp.asarray(rng.integers(0, 256, (B, 32), dtype=np.int32))
    b8 = jnp.asarray(rng.integers(0, 256, (B, 32), dtype=np.int32))
    m32 = jnp.asarray(onehot(32).astype(np.int32))

    def cur(a, b, m):
        p = (a[:, :, None] * b[:, None, :]).reshape(B, 32 * 32)
        return jax.lax.dot_general(p, m, (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32)

    print(f"int32 8bit outer+dot [B,1024]@[1024,64] : {bench(cur,(a8,b8,m32))*1e3:7.3f} ms")

    # bf16 4-bit digits
    a4 = jnp.asarray(rng.integers(0, 16, (B, 64)).astype(np.float32), dtype=jnp.bfloat16)
    b4 = jnp.asarray(rng.integers(0, 16, (B, 64)).astype(np.float32), dtype=jnp.bfloat16)
    m64 = jnp.asarray(onehot(64), dtype=jnp.bfloat16)

    def v_bf16(a, b, m):
        p = (a[:, :, None] * b[:, None, :]).reshape(B, 64 * 64)
        return jax.lax.dot_general(p, m, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    print(f"bf16 4bit outer+dot [B,4096]@[4096,128] : {bench(v_bf16,(a4,b4,m64))*1e3:7.3f} ms")

    # int8 4-bit signed digits
    a4i = jnp.asarray(rng.integers(-8, 8, (B, 64), dtype=np.int8))
    b4i = jnp.asarray(rng.integers(-8, 8, (B, 64), dtype=np.int8))
    m64i = jnp.asarray(onehot(64).astype(np.int8))

    def v_int8(a, b, m):
        p = (a[:, :, None] * b[:, None, :]).reshape(B, 64 * 64)
        return jax.lax.dot_general(p, m, (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32)

    print(f"int8 4bit outer+dot [B,4096]@[4096,128] : {bench(v_int8,(a4i,b4i,m64i))*1e3:7.3f} ms")

    # blocked int8: 4 blocks of 16 digits -> pair products through 16-wide onehot
    m16 = jnp.asarray(onehot(16).astype(np.int8))

    def v_int8_blk(a, b, m):
        ab = a.reshape(B, 4, 16)
        bb = b.reshape(B, 4, 16)
        p = (ab[:, :, None, :, None] * bb[:, None, :, None, :]).reshape(B, 16, 256)
        c = jax.lax.dot_general(p, m, (((2,), (0,)), ((), ())), preferred_element_type=jnp.int32)
        return c  # collection matmul omitted (cheap int32 [B,16,32]@... later)

    print(f"int8 blocked [B,16,256]@[256,32]        : {bench(v_int8_blk,(a4i,b4i,m16))*1e3:7.3f} ms")

    # pure outer product cost (bf16), no dot
    def outer_only(a, b):
        return (a[:, :, None] * b[:, None, :]).reshape(B, 64 * 64)

    print(f"bf16 outer only [B,4096]                : {bench(outer_only,(a4,b4))*1e3:7.3f} ms")

    # pure matmul cost (bf16) on pre-materialized p
    p = jnp.asarray(rng.integers(0, 225, (B, 4096)).astype(np.float32), dtype=jnp.bfloat16)

    def dot_only(p, m):
        return jax.lax.dot_general(p, m, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    print(f"bf16 dot only [B,4096]@[4096,128]       : {bench(dot_only,(p,m64))*1e3:7.3f} ms")

    # f32 variant of current (int32 values exact < 2^24 in f32): 8-bit digits
    a8f = a8.astype(jnp.float32)
    b8f = b8.astype(jnp.float32)
    m32f = jnp.asarray(onehot(32))

    def v_f32(a, b, m):
        p = (a[:, :, None] * b[:, None, :]).reshape(B, 32 * 32)
        return jax.lax.dot_general(p, m, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    print(f"f32 8bit outer+dot [B,1024]@[1024,64]   : {bench(v_f32,(a8f,b8f,m32f))*1e3:7.3f} ms")


if __name__ == "__main__":
    main()
