#!/usr/bin/env python
"""Microbenchmarks for the secp kernel primitives on the real device.

Usage: python tools/microbench.py [mul|double|add|ladder|full|int8]
"""
from __future__ import annotations

import sys
import time

import numpy as np

from kaspa_tpu.utils import jax_setup

jax_setup.setup()

import jax
import jax.numpy as jnp

from kaspa_tpu.ops import bigint as bi
from kaspa_tpu.ops.secp256k1 import points as pt

FP = bi.FP
B = 16384


def bench(fn, args, iters=10, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
        jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    return dt


def rand_limbs(rng, b=B):
    # random 256-bit values (canonical-ish limbs)
    return jnp.asarray(rng.integers(0, 1 << 16, size=(b, 16), dtype=np.int32))


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    rng = np.random.default_rng(0)
    a = rand_limbs(rng)
    b = rand_limbs(rng)
    print(f"devices: {jax.devices()}", file=sys.stderr)

    if which in ("mul", "all"):
        f = jax.jit(lambda x, y: bi.mul(FP, x, y))
        dt = bench(f, (a, b))
        print(f"bi.mul        B={B}: {dt*1e3:8.3f} ms  ({B/dt/1e6:.1f} M muls/s)")

    if which in ("double", "all"):
        one = jnp.broadcast_to(jnp.asarray(FP.one), a.shape).astype(jnp.int32)
        f = jax.jit(lambda x, y, z: pt.point_double((x, y, z)))
        dt = bench(f, (a, b, one))
        print(f"point_double  B={B}: {dt*1e3:8.3f} ms")

    if which in ("add", "all"):
        one = jnp.broadcast_to(jnp.asarray(FP.one), a.shape).astype(jnp.int32)
        f = jax.jit(lambda x, y, z: pt.point_add((x, y, z), (y, x, one)))
        dt = bench(f, (a, b, one))
        print(f"point_add     B={B}: {dt*1e3:8.3f} ms")

    if which in ("ladder", "all"):
        dg = jnp.asarray(rng.integers(0, 16, size=(B, 64), dtype=np.int32))
        f = jax.jit(pt.dual_scalar_mul_base)
        t0 = time.perf_counter()
        out = f(a, b, dg, dg)
        jax.block_until_ready(out)
        print(f"ladder compile+run: {time.perf_counter()-t0:.1f} s", file=sys.stderr)
        dt = bench(f, (a, b, dg, dg), iters=3, warmup=1)
        print(f"ladder        B={B}: {dt*1e3:8.3f} ms  ({B/dt:.0f}/s)")


if __name__ == "__main__":
    main()
