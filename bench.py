#!/usr/bin/env python
"""Headline benchmark: batched Schnorr-secp256k1 verification throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: 50_000 verifies/sec on a single TPU v5e chip (BASELINE.json
north star; the reference does this on CPU via libsecp256k1 + rayon,
consensus/src/processes/transaction_validator/tx_validation_in_utxo_context.rs:206-223).

Every lane verifies a DISTINCT (pubkey, message, signature) triple —
no tiling — and the batch mixes valid and invalid signatures: the device
mask must match the pure-python oracle expectation exactly.

Host-side generation uses incremental points (P_i = P_{i-1} + G,
R_i = R_{i-1} + G) so building 16384 unique signatures costs two
point_adds per lane instead of two full scalar ladders; the signatures
are standard BIP340 (verified by eclib on a sample).
"""

from __future__ import annotations

import json
import random
import time

import numpy as np

from kaspa_tpu.utils import jax_setup

jax_setup.setup()


def _device_watchdog(timeout_s: float = 240.0) -> bool:
    """True if the device answers a trivial jit within the timeout.

    The tunneled TPU backend can wedge on compile RPCs; a hung bench is
    worse than an honest failure line, so probe before the real workload.
    """
    import threading

    ok = []

    def probe():
        import jax
        import jax.numpy as jnp

        y = jax.jit(lambda v: v + 1)(jnp.ones((8,), jnp.int32))
        y.block_until_ready()
        ok.append(True)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    return bool(ok)


from kaspa_tpu.crypto import eclib
from kaspa_tpu.crypto.secp import schnorr_challenge
from kaspa_tpu.ops import bigint as bi
from kaspa_tpu.ops.secp256k1.verify import schnorr_verify

BASELINE = 50_000.0  # verifies/sec/chip target
B = 16384


def _gen_unique_batch(b: int):
    """b distinct BIP340 (pubkey, msg, sig) triples via incremental points."""
    rng = random.Random(2026)
    sk0 = rng.randrange(1, eclib.N - b)
    k0 = rng.randrange(1, eclib.N - b)
    P = eclib.point_mul(eclib.G, sk0)
    R = eclib.point_mul(eclib.G, k0)
    triples = []
    for i in range(b):
        sk, k = sk0 + i, k0 + i
        # BIP340 key/nonce negation for even-y points
        d = sk if P[1] % 2 == 0 else eclib.N - sk
        pub = P[0].to_bytes(32, "big")
        kk = k if R[1] % 2 == 0 else eclib.N - k
        r = R[0].to_bytes(32, "big")
        msg = rng.getrandbits(256).to_bytes(32, "big")
        e = schnorr_challenge(r, pub, msg)
        s = (kk + e * d) % eclib.N
        triples.append((P, pub, msg, r + s.to_bytes(32, "big")))
        P = eclib.point_add(P, eclib.G)
        R = eclib.point_add(R, eclib.G)
    return triples


def main() -> None:
    if not _device_watchdog():
        # device backend unresponsive: report an explicit zero, never hang.
        # os._exit skips jax's atexit teardown, which would block on the
        # same wedged PJRT client after the JSON is out.
        import os
        import sys

        print(
            json.dumps(
                {
                    "metric": "schnorr_secp256k1_batch_verify_throughput",
                    "value": 0.0,
                    "unit": "verifies/sec/chip",
                    "vs_baseline": 0.0,
                    "error": "device backend unresponsive (jit watchdog timeout)",
                }
            )
        )
        sys.stdout.flush()
        os._exit(0)

    triples = _gen_unique_batch(B)
    # spot-check the generator against the reference verifier
    for i in (0, 1, B // 2, B - 1):
        P, pub, msg, sig = triples[i]
        assert eclib.schnorr_verify(pub, msg, sig), "generator produced bad sig"

    expect = [True] * B
    rng = random.Random(7)
    sigs = [t[3] for t in triples]
    for i in range(0, B, 4):  # corrupt a quarter of the batch
        j = rng.randrange(64)
        sigs[i] = sigs[i][:j] + bytes([sigs[i][j] ^ (1 + rng.randrange(255))]) + sigs[i][j + 1 :]
        expect[i] = False

    px = np.stack([bi.int_to_limbs(t[0][0], 16) for t in triples]).astype(np.int32)
    # lifted pubkey (even y): negate odd-y points host-side like secp.py does
    py = np.stack(
        [
            bi.int_to_limbs(t[0][1] if t[0][1] % 2 == 0 else eclib.P - t[0][1], 16)
            for t in triples
        ]
    ).astype(np.int32)
    rc = np.stack([bi.int_to_limbs(int.from_bytes(s[:32], "big"), 16) for s in sigs]).astype(np.int32)
    # scalars stay python ints: the backend (pallas or XLA) derives its own
    # window-digit layout — the e2e path includes that host marshalling
    s_ints = [int.from_bytes(s[32:], "big") % eclib.N for s in sigs]
    e_ints = [
        schnorr_challenge(s[:32], t[1], t[2]) for s, t in zip(sigs, triples)
    ]
    # host-side encoding validity: r must be a canonical field element and
    # on-curve (lift_x); corrupted r bytes can make lanes invalid-by-encoding
    ok = np.ones(B, dtype=bool)
    for i in range(0, B, 4):
        r_int = int.from_bytes(sigs[i][:32], "big")
        if r_int >= eclib.P or eclib.lift_x(r_int) is None:
            ok[i] = False
        if int.from_bytes(sigs[i][32:], "big") >= eclib.N:
            ok[i] = False

    mask = np.asarray(schnorr_verify(px, py, rc, s_ints, e_ints, ok))  # compile + warmup
    assert mask.tolist() == expect, "BENCH CORRECTNESS FAILURE: mask != oracle"

    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        out = np.asarray(schnorr_verify(px, py, rc, s_ints, e_ints, ok))
        best = min(best, time.perf_counter() - t0)
    assert out.tolist() == expect

    value = B / best
    print(
        json.dumps(
            {
                "metric": "schnorr_secp256k1_batch_verify_throughput",
                "value": round(value, 1),
                "unit": "verifies/sec/chip",
                "vs_baseline": round(value / BASELINE, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
