#!/usr/bin/env python
"""Headline benchmark: batched Schnorr-secp256k1 verification throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: 50_000 verifies/sec on a single TPU v5e chip (BASELINE.json
north star; the reference does this on CPU via libsecp256k1 + rayon,
consensus/src/processes/transaction_validator/tx_validation_in_utxo_context.rs:206-223).

Correctness is asserted inside the run: the batch mixes valid and invalid
signatures and the mask must match the pure-python oracle exactly.
"""

from __future__ import annotations

import json
import random
import time

import numpy as np

from kaspa_tpu.utils import jax_setup

jax_setup.setup()


def _device_watchdog(timeout_s: float = 240.0) -> bool:
    """True if the device answers a trivial jit within the timeout.

    The tunneled TPU backend can wedge on compile RPCs; a hung bench is
    worse than an honest failure line, so probe before the real workload.
    """
    import threading

    ok = []

    def probe():
        import jax
        import jax.numpy as jnp

        y = jax.jit(lambda v: v + 1)(jnp.ones((8,), jnp.int32))
        y.block_until_ready()
        ok.append(True)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    return bool(ok)


from kaspa_tpu.crypto import eclib
from kaspa_tpu.crypto.secp import schnorr_challenge
from kaspa_tpu.ops import bigint as bi
from kaspa_tpu.ops.secp256k1.verify import schnorr_verify

BASELINE = 50_000.0  # verifies/sec/chip target
B = 16384
UNIQUE = 32  # distinct real signatures, tiled (host-side sig generation is slow)


def main() -> None:
    if not _device_watchdog():
        # device backend unresponsive: report an explicit zero, never hang.
        # os._exit skips jax's atexit teardown, which would block on the
        # same wedged PJRT client after the JSON is out.
        import os
        import sys

        print(
            json.dumps(
                {
                    "metric": "schnorr_secp256k1_batch_verify_throughput",
                    "value": 0.0,
                    "unit": "verifies/sec/chip",
                    "vs_baseline": 0.0,
                    "error": "device backend unresponsive (jit watchdog timeout)",
                }
            )
        )
        sys.stdout.flush()
        os._exit(0)
    random.seed(2026)
    sk = random.randrange(1, eclib.N)
    pub = eclib.schnorr_pubkey(sk)
    pk = eclib.lift_x(int.from_bytes(pub, "big"))
    msgs = [random.randbytes(32) for _ in range(UNIQUE)]
    sigs = [eclib.schnorr_sign(m, sk, b"\x05" * 32) for m in msgs]
    expect = [True] * UNIQUE
    # corrupt a quarter of them
    for i in range(0, UNIQUE, 4):
        sigs[i] = sigs[i][:40] + bytes([sigs[i][40] ^ 1]) + sigs[i][41:]
        expect[i] = False

    reps = B // UNIQUE
    px = np.tile(bi.int_to_limbs(pk[0], 16), (B, 1)).astype(np.int32)
    py = np.tile(bi.int_to_limbs(pk[1], 16), (B, 1)).astype(np.int32)
    rc = np.tile(np.stack([bi.int_to_limbs(int.from_bytes(s[:32], "big"), 16) for s in sigs]), (reps, 1))
    # scalars stay python ints: the backend (pallas or XLA) derives its own
    # window-digit layout — the e2e path includes that host marshalling
    s_ints = [int.from_bytes(s[32:], "big") % eclib.N for s in sigs] * reps
    e_ints = [schnorr_challenge(s[:32], pub, msgs[i]) for i, s in enumerate(sigs)] * reps
    ok = np.ones(B, dtype=bool)

    mask = np.asarray(schnorr_verify(px, py, rc, s_ints, e_ints, ok))  # compile + warmup
    assert mask.tolist() == expect * reps, "BENCH CORRECTNESS FAILURE: mask != oracle"

    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        out = np.asarray(schnorr_verify(px, py, rc, s_ints, e_ints, ok))
        best = min(best, time.perf_counter() - t0)
    assert out.tolist() == expect * reps

    value = B / best
    print(
        json.dumps(
            {
                "metric": "schnorr_secp256k1_batch_verify_throughput",
                "value": round(value, 1),
                "unit": "verifies/sec/chip",
                "vs_baseline": round(value / BASELINE, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
