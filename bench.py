#!/usr/bin/env python
"""Headline benchmark: batched Schnorr-secp256k1 verification throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: 50_000 verifies/sec on a single TPU v5e chip (BASELINE.json
north star; the reference does this on CPU via libsecp256k1 + rayon,
consensus/src/processes/transaction_validator/tx_validation_in_utxo_context.rs:206-223).

Resilience: the tunneled TPU backend has wedged mid-compile in past driver
runs, and a wedged PJRT client poisons its whole process — no in-process
watchdog can recover it.  So this script is a jax-free PARENT that runs the
real workload in FRESH SUBPROCESSES: each attempt gets a staged in-child
device probe (fail fast on a dead backend) and a hard parent-side timeout
(kill on a hung one), with retries over a multi-attempt horizon.  Only
after every attempt fails does it report an explicit zero.

Every lane verifies a DISTINCT (pubkey, message, signature) triple —
no tiling — and the batch mixes valid and invalid signatures: the device
mask must match the pure-python oracle expectation exactly.

``--sweep`` runs the kernel x batch-size x mesh-size grid instead of the
headline number (one fresh child per cell, mesh via KASPA_TPU_MESH) and
writes best-per-config to BENCH_SWEEP.json; ``--probe`` just reports
backend liveness + device count.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

BASELINE = 50_000.0  # verifies/sec/chip target
B = int(os.environ.get("KASPA_TPU_BENCH_B", "16384"))

METRIC = "schnorr_secp256k1_batch_verify_throughput"
UNIT = "verifies/sec/chip"

# -- parent-side tunables (env-overridable for local experiments) ----------
TOTAL_BUDGET_S = float(os.environ.get("KASPA_TPU_BENCH_BUDGET_S", "1500"))
ATTEMPT_TIMEOUT_S = float(os.environ.get("KASPA_TPU_BENCH_ATTEMPT_S", "420"))
PROBE_TIMEOUT_S = float(os.environ.get("KASPA_TPU_BENCH_PROBE_S", "90"))
MAX_ATTEMPTS = int(os.environ.get("KASPA_TPU_BENCH_ATTEMPTS", "5"))
RETRY_BACKOFF_S = float(os.environ.get("KASPA_TPU_BENCH_BACKOFF_S", "15"))


# ==========================================================================
# child: the actual device workload (runs in a fresh interpreter per try)
# ==========================================================================


def _compile_events(spans: list) -> list:
    """Filter a drained span list down to jit/compile events (the
    ``bench.jit_compile`` probe span, secp's per-shape ``secp.jit_compile``,
    mesh shard_map traces) — the part of a trace a wedge dossier needs."""
    out = []
    for s in spans or []:
        name = str(s.get("path") or s.get("name") or "")
        if "jit" in name or "compile" in name:
            out.append(s)
    return out


def _child_probe(timeout_s: float) -> bool:
    """True if the device answers a trivial jit within the timeout.

    Runs in a daemon thread so a wedged compile RPC can't hang the child
    past the deadline — the child reports and exits, and the parent
    retries in another fresh process (fresh PJRT client).
    """
    import threading

    ok = []

    def probe():
        import jax
        import jax.numpy as jnp

        from kaspa_tpu.observability import trace

        # span the first-call compile so the wedge dossier can show how far
        # the backend got (span present+closed = compile finished; capture
        # empty = it never came back)
        with trace.span("bench.jit_compile", kernel="probe_add1", batch=8):
            y = jax.jit(lambda v: v + 1)(jnp.ones((8,), jnp.int32))
            y.block_until_ready()
        ok.append(True)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    return bool(ok)


def _child_probe_main() -> None:
    """Probe-only child (KASPA_TPU_BENCH_MODE=probe): one trivial jit,
    one JSON line, exit 0/3.  The parent's session-start probe and
    tools/roundcheck.py both run this in a fresh interpreter so a wedged
    PJRT client dies with the child, never with the caller."""
    from kaspa_tpu.utils import jax_setup

    jax_setup.setup()

    from kaspa_tpu.observability import trace

    trace.set_capture(64)
    t0 = time.perf_counter()
    ok = _child_probe(PROBE_TIMEOUT_S)
    devices = 0
    if ok:
        import jax

        devices = len(jax.devices())  # the sweep's mesh column source
    # persistent-kernel-cache status: a warm manifest means the heavy secp
    # shapes need no re-trace — the probe reuses (and reports) that cache
    # instead of proving compilation from scratch
    cache: dict = {}
    if ok:
        try:
            from kaspa_tpu.resilience import supervisor

            rep = supervisor.cache_report()
            entries = rep.get("entries") or []
            cache = {
                "manifest_path": rep.get("manifest_path"),
                "xla_cache_dir": rep.get("xla_cache_dir"),
                "warm_entries": len(entries),
                # aggregate-RLC kernels warm in this env (family column in
                # the manifest schema): 0 means the first --verify-mode
                # aggregate dispatch pays a cold compile
                "aggregate_warm_entries": sum(1 for e in entries if e.get("family") == "aggregate"),
                "entries_total": rep.get("entries_total", 0),
            }
        except Exception:  # noqa: BLE001 - cache evidence is best-effort
            pass
    print(
        json.dumps(
            {
                "probe_ok": ok,
                "elapsed_s": round(time.perf_counter() - t0, 3),
                "platform": os.environ.get("JAX_PLATFORMS", ""),
                "devices": devices,
                # jit/compile span evidence for the wedge dossier
                "jit_compile_events": _compile_events(trace.drain()),
                "kernel_cache": cache,
            }
        )
    )
    sys.stdout.flush()
    os._exit(0 if ok else 3)


def _child_warmstart_main() -> None:
    """Warm-start child (KASPA_TPU_BENCH_MODE=warmstart): fresh interpreter,
    re-trace every shape in the warm-kernel manifest, report per-bucket jit
    time.  This is the measured "restart after a wedge" cost the dossier
    records — with a hot persistent cache the rows come back in dispatch
    time, not compile time."""
    from kaspa_tpu.utils import jax_setup

    jax_setup.setup()

    from kaspa_tpu.resilience import supervisor

    budget = float(os.environ.get("KASPA_TPU_BENCH_PRETRACE_BUDGET_S", "120"))
    t0 = time.perf_counter()
    rows = supervisor.pretrace_warm(budget_s=budget)
    print(
        json.dumps(
            {
                "warm_start": rows,
                "total_seconds": round(time.perf_counter() - t0, 3),
                "budget_s": budget,
                "kernel_cache": supervisor.cache_report(),
            }
        )
    )
    sys.stdout.flush()
    os._exit(0)


def _gen_unique_batch(b: int):
    """b distinct BIP340 (pubkey, msg, sig) triples via incremental points.

    P_i = P_{i-1} + G, R_i = R_{i-1} + G: two point_adds per lane instead
    of two full scalar ladders; signatures are standard BIP340.
    """
    import random

    from kaspa_tpu.crypto import eclib
    from kaspa_tpu.crypto.secp import schnorr_challenge

    rng = random.Random(2026)
    sk0 = rng.randrange(1, eclib.N - b)
    k0 = rng.randrange(1, eclib.N - b)
    P = eclib.point_mul(eclib.G, sk0)
    R = eclib.point_mul(eclib.G, k0)
    triples = []
    for i in range(b):
        sk, k = sk0 + i, k0 + i
        # BIP340 key/nonce negation for even-y points
        d = sk if P[1] % 2 == 0 else eclib.N - sk
        pub = P[0].to_bytes(32, "big")
        kk = k if R[1] % 2 == 0 else eclib.N - k
        r = R[0].to_bytes(32, "big")
        msg = rng.getrandbits(256).to_bytes(32, "big")
        e = schnorr_challenge(r, pub, msg)
        s = (kk + e * d) % eclib.N
        triples.append((P, pub, msg, r + s.to_bytes(32, "big")))
        P = eclib.point_add(P, eclib.G)
        R = eclib.point_add(R, eclib.G)
    return triples


def _gen_unique_ecdsa_batch(b: int):
    """b distinct ECDSA (pubkey_point, msg, low-S sig) with known nonces.

    Same incremental-point trick as the Schnorr generator: P_i = P_{i-1}+G
    and R_i = R_{i-1}+G replace two full scalar ladders per lane; s comes
    from the known nonce k_i = k0+i (one cheap modular inverse per lane).
    """
    import random

    from kaspa_tpu.crypto import eclib

    rng = random.Random(2027)
    sk0 = rng.randrange(1, eclib.N - b)
    k0 = rng.randrange(1, eclib.N - b)
    P = eclib.point_mul(eclib.G, sk0)
    R = eclib.point_mul(eclib.G, k0)
    triples = []
    for i in range(b):
        sk, k = sk0 + i, k0 + i
        r = R[0] % eclib.N
        msg = rng.getrandbits(256).to_bytes(32, "big")
        z = int.from_bytes(msg, "big") % eclib.N
        s = pow(k, -1, eclib.N) * (z + r * sk) % eclib.N
        if s > eclib.N // 2:
            s = eclib.N - s  # low-S, like the signing front-end
        triples.append((P, msg, r.to_bytes(32, "big") + s.to_bytes(32, "big")))
        P = eclib.point_add(P, eclib.G)
        R = eclib.point_add(R, eclib.G)
    return triples


def _child_ecdsa_main(obs_fn) -> None:
    """ECDSA sweep lane: mirrors the Schnorr child (distinct triples, a
    corrupted quarter, host-side validity checks matching secp.py's
    front-end, device mask asserted against the oracle expectation)."""
    import random

    import numpy as np

    from kaspa_tpu.crypto import eclib
    from kaspa_tpu.ops import bigint as bi
    from kaspa_tpu.ops import mesh
    from kaspa_tpu.ops.secp256k1.verify import ecdsa_verify

    triples = _gen_unique_ecdsa_batch(B)
    for i in (0, 1, B // 2, B - 1):
        Pt, msg, sig = triples[i]
        pub33 = bytes([2 + (Pt[1] & 1)]) + Pt[0].to_bytes(32, "big")
        assert eclib.ecdsa_verify(pub33, msg, sig), "generator produced bad ecdsa sig"

    expect = [True] * B
    rng = random.Random(11)
    sigs = [t[2] for t in triples]
    for i in range(0, B, 4):  # corrupt a quarter of the batch
        j = rng.randrange(64)
        sigs[i] = sigs[i][:j] + bytes([sigs[i][j] ^ (1 + rng.randrange(255))]) + sigs[i][j + 1 :]
        expect[i] = False

    half_n = eclib.N // 2
    px = np.zeros((B, 16), np.int32)
    py = np.zeros((B, 16), np.int32)
    rc = np.zeros((B, 16), np.int32)
    u1 = [0] * B
    u2 = [0] * B
    ok = np.zeros(B, dtype=bool)
    for i, ((x, y), msg, _orig) in enumerate(triples):
        r = int.from_bytes(sigs[i][:32], "big")
        s = int.from_bytes(sigs[i][32:], "big")
        # same validity gate as secp.ecdsa_verify_batch (corrupt r/s can
        # fail by encoding before ever reaching the device)
        if not (1 <= r < eclib.N) or not (1 <= s < eclib.N) or s > half_n:
            continue
        z = int.from_bytes(msg, "big") % eclib.N
        si = pow(s, -1, eclib.N)
        px[i] = bi.int_to_limbs(x, 16)
        py[i] = bi.int_to_limbs(y, 16)
        rc[i] = bi.int_to_limbs(r, 16)
        u1[i] = z * si % eclib.N
        u2[i] = r * si % eclib.N
        ok[i] = True

    mask = np.asarray(ecdsa_verify(px, py, rc, u1, u2, ok))  # compile + warmup
    assert mask.tolist() == expect, "BENCH CORRECTNESS FAILURE: ecdsa mask != oracle"

    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        out = np.asarray(ecdsa_verify(px, py, rc, u1, u2, ok))
        best = min(best, time.perf_counter() - t0)
    assert out.tolist() == expect

    value = B / best
    print(
        json.dumps(
            {
                "metric": "ecdsa_secp256k1_batch_verify_throughput",
                "value": round(value, 1),
                "unit": UNIT,
                "vs_baseline": round(value / BASELINE, 4),
                "batch": B,
                "mesh": mesh.active_size(),
                "observability": obs_fn(),
            }
        )
    )
    sys.stdout.flush()
    os._exit(0)


def _child_dispatch_main(obs_fn) -> None:
    """Dispatch-layer lane (KASPA_TPU_BENCH_MODE=dispatch): coalesced
    cross-block dispatch vs legacy per-block dispatch over the SAME jobs
    and the SAME device kernel, so the delta isolates the dispatch layer.

    Legacy = one blocking device call per chunk (what per-block
    ``BatchScriptChecker.dispatch`` does); coalesced = every chunk
    submitted to the CoalescingDispatcher up front, masks collected from
    tickets.  Both lanes are oracle-checked before timing.
    """
    import random

    from kaspa_tpu.crypto import secp
    from kaspa_tpu.ops import dispatch as coalesce
    from kaspa_tpu.ops import mesh

    total = int(os.environ.get("KASPA_TPU_BENCH_DISPATCH_B", "512"))
    chunk = int(os.environ.get("KASPA_TPU_BENCH_CHUNK", "16"))
    passes = int(os.environ.get("KASPA_TPU_BENCH_DISPATCH_PASSES", "2"))
    kind = os.environ.get("KASPA_TPU_BENCH_KERNEL", "schnorr")
    # deterministic flush behavior while timing: size-triggered flushes plus
    # one final nudge, with the age timer parked out of the way
    os.environ.setdefault("KASPA_TPU_COALESCE_AGE_MS", "500")
    target = coalesce.configure(os.environ.get("KASPA_TPU_COALESCE") or min(total, 256))

    if kind == "ecdsa":
        raw = _gen_unique_ecdsa_batch(total)
        items = [(bytes([2 + (P[1] & 1)]) + P[0].to_bytes(32, "big"), msg, sig) for P, msg, sig in raw]
        batch_fn = secp.ecdsa_verify_batch
    else:
        raw = _gen_unique_batch(total)
        items = [(pub, msg, sig) for _P, pub, msg, sig in raw]
        batch_fn = secp.schnorr_verify_batch
    expect = [True] * total
    rng = random.Random(13)
    for i in range(0, total, 4):  # corrupt a quarter of the jobs
        pub, msg, sig = items[i]
        j = rng.randrange(64)
        items[i] = (pub, msg, sig[:j] + bytes([sig[j] ^ (1 + rng.randrange(255))]) + sig[j + 1 :])
        expect[i] = False
    chunks = [items[i : i + chunk] for i in range(0, total, chunk)]

    engine = coalesce.active()
    assert engine is not None, "coalescing engine failed to configure"

    def run_legacy() -> list:
        out = []
        for ch in chunks:
            out.extend(bool(v) for v in batch_fn(ch))
        return out

    def run_coalesced() -> list:
        tickets = [engine.submit(kind, list(ch)) for ch in chunks]
        out = []
        for t in tickets:
            out.extend(bool(v) for v in t.wait())
        return out

    # compile + warmup both shapes, oracle-checked
    assert run_legacy() == expect, "BENCH CORRECTNESS FAILURE: legacy mask != oracle"
    assert run_coalesced() == expect, "BENCH CORRECTNESS FAILURE: coalesced mask != oracle"

    legacy_best = coalesced_best = float("inf")
    for _ in range(passes):
        t0 = time.perf_counter()
        out = run_legacy()
        legacy_best = min(legacy_best, time.perf_counter() - t0)
        assert out == expect
        t0 = time.perf_counter()
        out = run_coalesced()
        coalesced_best = min(coalesced_best, time.perf_counter() - t0)
        assert out == expect

    legacy_vps = total / legacy_best
    coalesced_vps = total / coalesced_best
    result = {
        "metric": "verify_dispatch_coalescing",
        "value": round(coalesced_vps, 1),
        "unit": UNIT,
        "legacy_vps": round(legacy_vps, 1),
        "coalesced_vps": round(coalesced_vps, 1),
        "speedup": round(coalesced_vps / legacy_vps, 3),
        "batch": total,
        "chunk": chunk,
        "coalesce_target": target,
        "passes": passes,
        "kernel": kind,
        "mesh": mesh.active_size(),
    }

    # optional end-to-end identity check: replay the same simulated DAG with
    # coalescing off and on; sink + utxo_commitment must be bit-identical
    replay_blocks = int(os.environ.get("KASPA_TPU_BENCH_DISPATCH_REPLAY", "0"))
    if replay_blocks:
        from kaspa_tpu.sim.simulator import SimConfig, replay, simulate

        cfg = SimConfig(
            bps=2, delay=2.0, num_miners=4, num_blocks=replay_blocks, txs_per_block=4, seed=42
        )
        res = simulate(cfg)
        coalesce.configure(0)
        _, fresh_legacy = replay(res)
        sink_l = fresh_legacy.sink()
        commit_l = fresh_legacy.multisets[sink_l].finalize().hex()
        coalesce.configure(target)
        _, fresh_co = replay(res)
        sink_c = fresh_co.sink()
        commit_c = fresh_co.multisets[sink_c].finalize().hex()
        result.update(
            replay_blocks=replay_blocks,
            replay_txs=res.total_txs,  # must be > 0 for the check to mean anything
            replay_identical=bool(sink_l == sink_c and commit_l == commit_c),
            sink=sink_c.hex(),
            utxo_commitment=commit_c,
        )

    coalesce.drain(timeout=10.0)
    print(json.dumps({**result, "observability": obs_fn()}))
    sys.stdout.flush()
    os._exit(0)


def _child_aggregate_main(obs_fn) -> None:
    """Aggregate-RLC lane (KASPA_TPU_BENCH_MODE=aggregate): ONE combined
    multi-scalar check vs per-signature dual ladders over the SAME items on
    the SAME backend — the delta is the tentpole speedup (the shared
    doubling chain amortized over the batch instead of paid per lane).

    Correctness before timing: an all-valid batch must come back all-True
    on both lanes, and a small corrupted batch must bisect to the oracle
    mask through the aggregate lane (the falsification path the tests pin).
    """
    from kaspa_tpu.crypto import eclib, secp
    from kaspa_tpu.ops import mesh

    total = int(os.environ.get("KASPA_TPU_BENCH_AGG_B", "512"))
    passes = int(os.environ.get("KASPA_TPU_BENCH_AGG_PASSES", "2"))
    check_b = int(os.environ.get("KASPA_TPU_BENCH_AGG_CHECK_B", "8"))
    raw = _gen_unique_batch(total + check_b)
    items = [(pub, msg, sig) for _P, pub, msg, sig in raw[:total]]

    # bisection correctness on a small corrupted batch (small on purpose:
    # each recursion bucket is a fresh ~1min XLA compile on a cold CPU
    # backend, so the falsification check must not walk a deep bucket chain)
    bad = [(pub, msg, sig) for _P, pub, msg, sig in raw[total:]]
    k = len(bad) // 2
    bad[k] = (bad[k][0], bad[k][1], bad[k][2][:32] + ((int.from_bytes(bad[k][2][32:], "big") + 1) % eclib.N).to_bytes(32, "big"))
    expect_bad = [eclib.schnorr_verify(*it) for it in bad]
    assert expect_bad.count(False) == 1
    got_bad = [bool(v) for v in secp.schnorr_verify_batch_aggregate(bad)]
    assert got_bad == expect_bad, "BENCH CORRECTNESS FAILURE: aggregate bisect mask != oracle"

    # warm both lanes on the timing shape, all-valid masks oracle-checked
    assert all(bool(v) for v in secp.schnorr_verify_batch_aggregate(items)), (
        "BENCH CORRECTNESS FAILURE: aggregate rejected a valid batch"
    )
    assert all(bool(v) for v in secp.schnorr_verify_batch(items))

    agg_best = ladder_best = float("inf")
    for _ in range(passes):
        t0 = time.perf_counter()
        out = secp.schnorr_verify_batch_aggregate(items)
        agg_best = min(agg_best, time.perf_counter() - t0)
        assert all(bool(v) for v in out)
        t0 = time.perf_counter()
        out = secp.schnorr_verify_batch(items)
        ladder_best = min(ladder_best, time.perf_counter() - t0)
        assert all(bool(v) for v in out)

    agg_vps = total / agg_best
    ladder_vps = total / ladder_best
    print(
        json.dumps(
            {
                "metric": "schnorr_aggregate_verify_throughput",
                "value": round(agg_vps, 1),
                "unit": UNIT,
                "aggregate_vps": round(agg_vps, 1),
                "ladder_vps": round(ladder_vps, 1),
                "speedup": round(agg_vps / ladder_vps, 3),
                "batch": total,
                "passes": passes,
                "mesh": mesh.active_size(),
                "observability": obs_fn(),
            }
        )
    )
    sys.stdout.flush()
    os._exit(0)


def _child_main() -> None:
    """Generate the batch, verify on device, print the JSON result line.

    Exits via os._exit so jax's atexit teardown can't block on a sick
    PJRT client after the result is already out.
    """
    import random

    import numpy as np

    from kaspa_tpu.utils import jax_setup

    jax_setup.setup()

    # span capture + metric registry ride the result line (success AND
    # failure): when the backend wedges, the tail shows exactly which spans
    # ever completed (host marshal? device dispatch?) and what compiled
    from kaspa_tpu.observability import snapshot as obs_snapshot
    from kaspa_tpu.observability import trace

    trace.set_capture(512)

    def _obs() -> dict:
        # the supervisor verdict rides every result line (success AND
        # failure): watchdog escalations + host-lane requeue counts are the
        # first evidence a wedge dossier hoists
        from kaspa_tpu.resilience import supervisor

        return {
            "metrics": obs_snapshot(),
            "spans": trace.drain(),
            "supervisor": supervisor.verdict(),
        }

    if not _child_probe(PROBE_TIMEOUT_S):
        print(json.dumps({"child_error": "probe_timeout", "observability": _obs()}))
        sys.stdout.flush()
        os._exit(3)

    if os.environ.get("KASPA_TPU_BENCH_MODE") == "dispatch":
        _child_dispatch_main(_obs)
        return  # unreachable (child exits)

    if os.environ.get("KASPA_TPU_BENCH_MODE") == "aggregate":
        _child_aggregate_main(_obs)
        return  # unreachable (child exits)

    if os.environ.get("KASPA_TPU_BENCH_KERNEL", "schnorr") == "ecdsa":
        _child_ecdsa_main(_obs)
        return  # unreachable (child exits)

    from kaspa_tpu.crypto import eclib
    from kaspa_tpu.crypto.secp import schnorr_challenge
    from kaspa_tpu.ops import bigint as bi
    from kaspa_tpu.ops.secp256k1.verify import schnorr_verify

    triples = _gen_unique_batch(B)
    # spot-check the generator against the reference verifier
    for i in (0, 1, B // 2, B - 1):
        P, pub, msg, sig = triples[i]
        assert eclib.schnorr_verify(pub, msg, sig), "generator produced bad sig"

    expect = [True] * B
    rng = random.Random(7)
    sigs = [t[3] for t in triples]
    for i in range(0, B, 4):  # corrupt a quarter of the batch
        j = rng.randrange(64)
        sigs[i] = sigs[i][:j] + bytes([sigs[i][j] ^ (1 + rng.randrange(255))]) + sigs[i][j + 1 :]
        expect[i] = False

    px = np.stack([bi.int_to_limbs(t[0][0], 16) for t in triples]).astype(np.int32)
    # lifted pubkey (even y): negate odd-y points host-side like secp.py does
    py = np.stack(
        [
            bi.int_to_limbs(t[0][1] if t[0][1] % 2 == 0 else eclib.P - t[0][1], 16)
            for t in triples
        ]
    ).astype(np.int32)
    rc = np.stack([bi.int_to_limbs(int.from_bytes(s[:32], "big"), 16) for s in sigs]).astype(np.int32)
    # scalars stay python ints: the backend (pallas or XLA) derives its own
    # window-digit layout — the e2e path includes that host marshalling
    s_ints = [int.from_bytes(s[32:], "big") % eclib.N for s in sigs]
    e_ints = [schnorr_challenge(s[:32], t[1], t[2]) for s, t in zip(sigs, triples)]
    # host-side encoding validity: r must be a canonical field element and
    # on-curve (lift_x); corrupted r bytes can make lanes invalid-by-encoding
    ok = np.ones(B, dtype=bool)
    for i in range(0, B, 4):
        r_int = int.from_bytes(sigs[i][:32], "big")
        if r_int >= eclib.P or eclib.lift_x(r_int) is None:
            ok[i] = False
        if int.from_bytes(sigs[i][32:], "big") >= eclib.N:
            ok[i] = False

    mask = np.asarray(schnorr_verify(px, py, rc, s_ints, e_ints, ok))  # compile + warmup
    assert mask.tolist() == expect, "BENCH CORRECTNESS FAILURE: mask != oracle"

    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        out = np.asarray(schnorr_verify(px, py, rc, s_ints, e_ints, ok))
        best = min(best, time.perf_counter() - t0)
    assert out.tolist() == expect

    from kaspa_tpu.ops import mesh

    value = B / best
    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": round(value, 1),
                "unit": UNIT,
                "vs_baseline": round(value / BASELINE, 4),
                "batch": B,
                "mesh": mesh.active_size(),
                "observability": _obs(),
            }
        )
    )
    sys.stdout.flush()
    os._exit(0)


# ==========================================================================
# parent: jax-free orchestration — fresh subprocess per attempt
# ==========================================================================


def _run_attempt(timeout_s: float) -> tuple[dict | None, str, dict | None]:
    """One fresh-subprocess attempt.
    Returns (result_json | None, note, observability | None) — the obs tail
    comes back even from failed children so the final error line can carry
    the last evidence of what the device did before wedging."""
    env = dict(os.environ)
    env["KASPA_TPU_BENCH_CHILD"] = "1"
    # the headline measures one fixed kernel shape; warm-bucket splitting
    # would silently substitute smaller dispatches for it
    env.setdefault("KASPA_TPU_COLD_BUCKET_SPLIT", "0")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
        env=env,
    )
    try:
        out, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            proc.communicate(timeout=10)
        except Exception:
            pass
        return None, f"attempt timeout after {timeout_s:.0f}s (killed)", None
    for line in reversed((out or "").strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if obj.get("metric") == METRIC and obj.get("value", 0) > 0:
            return obj, "ok", obj.get("observability")
        if "child_error" in obj:
            return None, f"child: {obj['child_error']}", obj.get("observability")
    return None, f"child exited rc={proc.returncode} without a result line", None


def _utc_stamp(compact: bool = True) -> str:
    fmt = "%Y%m%dT%H%M%SZ" if compact else "%Y-%m-%dT%H:%M:%SZ"
    return time.strftime(fmt, time.gmtime())


def _run_json_child(env_extra: dict, timeout_s: float) -> tuple[dict | None, str]:
    """Fresh subprocess -> last JSON line on stdout (None on hang/garbage)."""
    env = dict(os.environ)
    env.update(env_extra)
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
        env=env,
    )
    try:
        out, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            proc.communicate(timeout=10)
        except Exception:
            pass
        return None, f"killed after {timeout_s:.0f}s"
    for line in reversed((out or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), f"rc={proc.returncode}"
            except json.JSONDecodeError:
                continue
    return None, f"rc={proc.returncode}, no JSON line"


def _run_sim_json(sim_args: list, env_extra: dict, timeout_s: float) -> tuple[dict | None, str]:
    """Fresh `python -m kaspa_tpu.sim` subprocess -> last JSON line."""
    env = dict(os.environ)
    env.update(env_extra)
    proc = subprocess.Popen(
        [sys.executable, "-m", "kaspa_tpu.sim", *sim_args, "--json"],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
        env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    try:
        out, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            proc.communicate(timeout=10)
        except Exception:
            pass
        return None, f"killed after {timeout_s:.0f}s"
    for line in reversed((out or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), f"rc={proc.returncode}"
            except json.JSONDecodeError:
                continue
    return None, f"rc={proc.returncode}, no JSON line"


def _flight_virtual_fraction(path: str) -> dict | None:
    """Aggregate a flight dump's critical-path attribution: the virtual.*
    (+ pipeline.virtual) share of total block wall time, and the top-3
    stages — the number ROADMAP item 2 tracks per round."""
    from kaspa_tpu.observability import flight

    try:
        with open(path) as f:
            doc = json.load(f)
        stage_ns: dict[str, float] = {}
        total = 0.0
        for t in doc["traces"]:
            cp = flight.critical_path(t["spans"], t["root"])
            total += cp["total_ns"]
            for stage, ns in cp["stages"].items():
                stage_ns[stage] = stage_ns.get(stage, 0.0) + ns
    except Exception:
        return None
    if not total:
        return None
    virt = sum(ns for s, ns in stage_ns.items() if s.startswith("virtual.") or s == "pipeline.virtual")
    top3 = sorted(((s, ns) for s, ns in stage_ns.items() if s != "block"), key=lambda kv: -kv[1])[:3]
    return {
        "virtual_fraction": round(virt / total, 4),
        "top_stages": [
            {"stage": s, "total_ms": round(ns / 1e6, 2), "fraction": round(ns / total, 4)} for s, ns in top3
        ],
    }


def _virtual_critical_path(timeout_s: float = 300.0) -> dict | None:
    """Before/after evidence for the speculative precompute: two traced
    24-block pipelined CPU replays — speculation off ("before", the serial
    virtual path) and on ("after") — each reduced to its virtual.*
    critical-path fraction + top-3 stages.  Embedded into the headline
    JSON so BENCH_r* documents the shift even while the device wedge keeps
    hardware numbers CPU-only.  KASPA_TPU_BENCH_VCP=0 disables."""
    if os.environ.get("KASPA_TPU_BENCH_VCP", "1") in ("0", "off"):
        return None
    out: dict = {}
    # tpb 6 matters: the build phase then carries real signature batches,
    # so the XLA verify-kernel compile happens before t0 and the replay
    # measures pipeline shape, not a one-time jit wall absorbed into the
    # first virtual cycle's shared span
    base_args = ["--bps", "4", "--blocks", "24", "--tpb", "6", "--pipeline"]
    # the per-block fraction charges a cycle's shared span to every block
    # it absorbed, so an uncapped fast replay (one cycle swallowing most
    # of the 24 blocks) reads ~95% even at hit rate 1.0 — bound the cycle
    # so before/after attribution stays comparable across runs
    env = {"JAX_PLATFORMS": "cpu", "KASPA_TPU_VIRTUAL_BATCH_MAX": "8"}
    for label, extra in (("before_no_spec", ["--no-spec"]), ("after_speculative", [])):
        dump = os.path.join(tempfile.gettempdir(), f"bench_vcp_{label}.json")
        obj, note = _run_sim_json(
            base_args + extra + ["--trace", dump], env, timeout_s
        )
        frac = _flight_virtual_fraction(dump) if obj is not None else None
        if frac is None:
            out[label] = {"error": note}
            continue
        frac["replay_blocks_per_sec"] = obj.get("replay_blocks_per_sec")
        if obj.get("speculative"):
            frac["speculative_hit_rate"] = obj["speculative"].get("hit_rate")
        out[label] = frac
    return out


def _session_probe(log: list) -> bool:
    """Session-start device probe: trivial jit in a fresh child, hard
    parent-side timeout.  Every step lands in ``log`` with a UTC stamp so a
    wedge leaves a trail instead of a silent death."""
    timeout_s = PROBE_TIMEOUT_S + 30  # child gets PROBE_TIMEOUT_S; +30 for interpreter spin-up
    log.append({"t": _utc_stamp(), "event": "session_probe_start", "timeout_s": timeout_s})
    obj, note = _run_json_child(
        {"KASPA_TPU_BENCH_CHILD": "1", "KASPA_TPU_BENCH_MODE": "probe"}, timeout_s
    )
    ok = bool(obj and obj.get("probe_ok"))
    log.append({"t": _utc_stamp(), "event": "session_probe_result", "ok": ok, "note": note, "child": obj})
    return ok


def _cpu_fallback(log: list) -> dict | None:
    """Wedge path: rerun the workload on the CPU XLA backend (reduced batch)
    so the dossier carries real throughput numbers, not just a corpse."""
    b = int(os.environ.get("KASPA_TPU_BENCH_FALLBACK_B", "1024"))
    log.append({"t": _utc_stamp(), "event": "cpu_fallback_start", "batch": b})
    obj, note = _run_json_child(
        {"KASPA_TPU_BENCH_CHILD": "1", "JAX_PLATFORMS": "cpu", "KASPA_TPU_BENCH_B": str(b)},
        ATTEMPT_TIMEOUT_S,
    )
    if obj is not None:
        # the dossier wants numbers, not full span dumps — but keep the
        # jit/compile events (did the CPU backend compile?) and the
        # supervisor verdict (watchdog escalations / requeue counts)
        obs = obj.pop("observability", None)
        if obs:
            obj["jit_compile_events"] = _compile_events(obs.get("spans"))
            if obs.get("supervisor"):
                obj["supervisor"] = obs["supervisor"]
    log.append({"t": _utc_stamp(), "event": "cpu_fallback_result", "note": note, "result": obj})
    return obj


def _warm_start_child(log: list) -> dict | None:
    """Wedge-path evidence: measured warm-start jit time in a fresh child.

    Runs the warm-kernel manifest re-trace on the CPU backend (the wedged
    device would hang it) so the dossier records how fast a daemon restart
    re-arms the heavy secp shapes from the persistent compilation cache."""
    budget = float(os.environ.get("KASPA_TPU_BENCH_PRETRACE_BUDGET_S", "120"))
    log.append({"t": _utc_stamp(), "event": "warm_start_probe", "budget_s": budget})
    obj, note = _run_json_child(
        {"KASPA_TPU_BENCH_CHILD": "1", "KASPA_TPU_BENCH_MODE": "warmstart", "JAX_PLATFORMS": "cpu"},
        budget + 60,
    )
    log.append({"t": _utc_stamp(), "event": "warm_start_result", "note": note, "result": obj})
    return obj


def _write_wedge_dossier(
    probe_log: list,
    fallback: dict | None,
    reason: str = "device probe wedge at session start",
    warm_start: dict | None = None,
) -> str:
    """Timestamped evidence file for a wedged device session."""
    out_dir = os.environ.get("KASPA_TPU_BENCH_DOSSIER_DIR", ".")
    path = os.path.join(out_dir, f"bench_wedge_{_utc_stamp()}.json")
    # hoist every child's jit/compile spans to one top-level list: "how far
    # did each compile get" is the first question a wedge post-mortem asks;
    # the supervisor verdict (watchdog escalations, requeue counts) is the
    # second — pull the latest one any child reported
    compile_events: list = []
    supervisor_verdict: dict | None = None
    kernel_cache: dict | None = None
    for entry in probe_log:
        child = entry.get("child") if isinstance(entry, dict) else None
        if isinstance(child, dict):
            compile_events += child.get("jit_compile_events") or []
            obs = child.get("observability") or {}
            compile_events += _compile_events(obs.get("spans"))
            supervisor_verdict = obs.get("supervisor") or supervisor_verdict
            kernel_cache = child.get("kernel_cache") or kernel_cache
    if isinstance(fallback, dict):
        compile_events += fallback.get("jit_compile_events") or []
        fb_obs = fallback.get("observability") or {}
        supervisor_verdict = fb_obs.get("supervisor") or fallback.get("supervisor") or supervisor_verdict
    if isinstance(warm_start, dict):
        kernel_cache = warm_start.get("kernel_cache") or kernel_cache
    with open(path, "w") as f:
        json.dump(
            {
                "created": _utc_stamp(compact=False),
                "reason": reason,
                "metric": METRIC,
                "batch": B,
                "jit_compile_events": compile_events,
                "supervisor": supervisor_verdict,
                "kernel_cache": kernel_cache,
                # measured warm-start jit time: how fast a restart re-arms
                # the secp shapes from the persistent compilation cache
                "warm_start": warm_start,
                "probe_log": probe_log,
                "cpu_fallback": fallback,
            },
            f,
            indent=2,
        )
    return path


WEDGE_TTL_S = float(os.environ.get("KASPA_TPU_BENCH_WEDGE_TTL_S", "3600"))


def _cached_wedge(log: list) -> tuple[str, dict] | None:
    """Fast-fail on a recent wedge verdict.

    A wedged backend costs the full probe + retry spiral to re-diagnose
    (minutes of subprocess timeouts), and the verdict rarely changes
    within the hour.  If a ``bench_wedge_*.json`` dossier younger than
    KASPA_TPU_BENCH_WEDGE_TTL_S exists, reuse it instead of re-proving
    the same timeout.  KASPA_TPU_BENCH_FORCE_PROBE=1 bypasses the cache
    (the daemon's recurring BenchCapture sets it so device *recovery* is
    still noticed within one tick interval).
    """
    if os.environ.get("KASPA_TPU_BENCH_FORCE_PROBE"):
        return None
    out_dir = os.environ.get("KASPA_TPU_BENCH_DOSSIER_DIR", ".")
    try:
        names = os.listdir(out_dir)
    except OSError:
        return None
    now = time.time()
    newest, newest_mtime = None, 0.0
    for fn in names:
        if not (fn.startswith("bench_wedge_") and fn.endswith(".json")):
            continue
        path = os.path.join(out_dir, fn)
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            continue
        if now - mtime <= WEDGE_TTL_S and mtime > newest_mtime:
            newest, newest_mtime = path, mtime
    if newest is None:
        return None
    try:
        with open(newest) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    log.append(
        {
            "t": _utc_stamp(),
            "event": "cached_wedge_verdict",
            "dossier": newest,
            "age_s": round(now - newest_mtime, 1),
            "ttl_s": WEDGE_TTL_S,
        }
    )
    return newest, doc


def _sweep(probe_log: list, devices: int) -> None:
    """ROADMAP item-1 sweep: kernel x batch-size x mesh-size grid, one
    fresh child per cell, best-per-(kernel, mesh) config into the sweep
    JSON.  Reuses the headline machinery: each cell still probes in-child
    and dies alone on a wedged backend; the parent just records the hole.
    """
    batches = [
        int(b) for b in os.environ.get("KASPA_TPU_BENCH_SWEEP_BATCHES", "1024,4096,16384").split(",") if b.strip()
    ]
    meshes = [1] + ([devices] if devices > 1 else [])
    deadline = time.monotonic() + TOTAL_BUDGET_S
    cells = []
    for kernel in ("schnorr", "ecdsa"):
        for mesh_n in meshes:
            for b in batches:
                cell = {"kernel": kernel, "batch": b, "mesh": mesh_n}
                remaining = deadline - time.monotonic()
                if remaining <= 30:
                    cell.update(value=0.0, note="sweep budget exhausted")
                    cells.append(cell)
                    continue
                obj, note = _run_json_child(
                    {
                        "KASPA_TPU_BENCH_CHILD": "1",
                        "KASPA_TPU_BENCH_B": str(b),
                        "KASPA_TPU_BENCH_KERNEL": kernel,
                        "KASPA_TPU_MESH": str(mesh_n),
                        # cells measure this exact bucket shape: no
                        # warm-bucket substitution
                        "KASPA_TPU_COLD_BUCKET_SPLIT": "0",
                    },
                    min(ATTEMPT_TIMEOUT_S, remaining),
                )
                if obj is not None and obj.get("value", 0) > 0:
                    cell.update(value=obj["value"], unit=obj.get("unit", UNIT), note="ok")
                else:
                    err = (obj or {}).get("child_error", note)
                    cell.update(value=0.0, note=f"failed: {err}")
                cells.append(cell)
    # coalesce-depth column: dispatch-layer throughput (cross-block
    # coalescing vs per-block dispatch over the same chunked jobs), one
    # dispatch-mode child per depth — measures the layer the kernel cells
    # can't see
    depths = [
        int(d) for d in os.environ.get("KASPA_TPU_BENCH_SWEEP_DEPTHS", "4,16").split(",") if d.strip()
    ]
    chunk = int(os.environ.get("KASPA_TPU_BENCH_CHUNK", "16"))
    for kernel in ("schnorr", "ecdsa"):
        for mesh_n in meshes:
            for depth in depths:
                target = depth * chunk
                cell = {"kernel": kernel, "batch": target, "mesh": mesh_n, "coalesce_depth": depth}
                remaining = deadline - time.monotonic()
                if remaining <= 30:
                    cell.update(value=0.0, note="sweep budget exhausted")
                    cells.append(cell)
                    continue
                obj, note = _run_json_child(
                    {
                        "KASPA_TPU_BENCH_CHILD": "1",
                        "KASPA_TPU_BENCH_MODE": "dispatch",
                        "KASPA_TPU_BENCH_KERNEL": kernel,
                        "KASPA_TPU_BENCH_DISPATCH_B": str(target * 2),
                        "KASPA_TPU_BENCH_CHUNK": str(chunk),
                        "KASPA_TPU_COALESCE": str(target),
                        "KASPA_TPU_MESH": str(mesh_n),
                    },
                    min(ATTEMPT_TIMEOUT_S, remaining),
                )
                if obj is not None and obj.get("coalesced_vps", 0) > 0:
                    cell.update(
                        value=obj["coalesced_vps"],
                        speedup=obj.get("speedup"),
                        legacy_vps=obj.get("legacy_vps"),
                        unit=obj.get("unit", UNIT),
                        note="ok",
                    )
                else:
                    err = (obj or {}).get("child_error", note)
                    cell.update(value=0.0, note=f"failed: {err}")
                cells.append(cell)
    # aggregate-RLC column: combined multi-scalar check vs per-signature
    # ladders at each batch size; the smallest batch where the aggregate
    # lane wins becomes the recorded crossover that --verify-mode auto
    # reads back from this file (ops/dispatch._aggregate_crossover)
    agg_batches = [
        int(b) for b in os.environ.get("KASPA_TPU_BENCH_AGG_BATCHES", "64,256,1024").split(",") if b.strip()
    ]
    agg_cells: list = []
    for b in agg_batches:
        cell = {"lane": "aggregate", "kernel": "schnorr", "batch": b, "mesh": 1}
        remaining = deadline - time.monotonic()
        if remaining <= 30:
            cell.update(value=0.0, note="sweep budget exhausted")
            agg_cells.append(cell)
            continue
        obj, note = _run_json_child(
            {
                "KASPA_TPU_BENCH_CHILD": "1",
                "KASPA_TPU_BENCH_MODE": "aggregate",
                "KASPA_TPU_BENCH_AGG_B": str(b),
                # cells measure this exact bucket shape, like the kernel grid
                "KASPA_TPU_COLD_BUCKET_SPLIT": "0",
            },
            min(ATTEMPT_TIMEOUT_S, remaining),
        )
        if obj is not None and obj.get("aggregate_vps", 0) > 0:
            cell.update(
                value=obj["aggregate_vps"],
                ladder_vps=obj.get("ladder_vps"),
                aggregate_speedup=obj.get("speedup"),
                unit=obj.get("unit", UNIT),
                note="ok",
            )
        else:
            err = (obj or {}).get("child_error", note)
            cell.update(value=0.0, note=f"failed: {err}")
        agg_cells.append(cell)
    cells.extend(agg_cells)
    agg_crossover = None
    for c in sorted(agg_cells, key=lambda c: c["batch"]):
        if (c.get("aggregate_speedup") or 0) >= 1.0:
            agg_crossover = c["batch"]
            break
    # per-mesh replay cells: end-to-end sim replay blocks/sec at each mesh
    # width, the lane where ROUNDCHECK first exposed the mesh-8 regression
    # (1.13 vs 2.7 blocks/s).  The dominant cost at mesh > 1 is the
    # per-subprocess shard_map re-trace of the verify ladder (~3-4 min of
    # one-time tracing each fresh process pays before the first batch),
    # not genuine shard overhead — the cells record replay_seconds next to
    # blocks/sec so the two are distinguishable per round.
    replay_blocks = int(os.environ.get("KASPA_TPU_BENCH_SWEEP_REPLAY", "24"))
    for mesh_n in meshes:
        cell = {"lane": "replay", "mesh": mesh_n, "blocks": replay_blocks}
        remaining = deadline - time.monotonic()
        if remaining <= 30:
            cell.update(value=0.0, note="sweep budget exhausted")
            cells.append(cell)
            continue
        env_extra = {"JAX_PLATFORMS": "cpu"}
        if mesh_n > 1:
            env_extra["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={mesh_n}"
            ).strip()
        obj, note = _run_sim_json(
            ["--bps", "2", "--blocks", str(replay_blocks), "--mesh", str(mesh_n)],
            env_extra,
            min(900.0, remaining),
        )
        if obj is not None and obj.get("replay_blocks_per_sec", 0) > 0:
            cell.update(
                value=obj["replay_blocks_per_sec"],
                unit="replay_blocks_per_sec",
                replay_seconds=obj.get("replay_seconds"),
                sink=obj.get("sink"),
                note="ok",
            )
        else:
            cell.update(value=0.0, note=f"failed: {note}")
        cells.append(cell)
    best: dict = {}
    for c in cells:
        if c.get("lane") == "replay":
            key = f"replay/mesh{c['mesh']}"
            if c["value"] > best.get(key, {}).get("value", 0.0):
                best[key] = {"value": c["value"], "replay_seconds": c.get("replay_seconds")}
            continue
        if c.get("lane") == "aggregate":
            key = f"{c['kernel']}/mesh{c['mesh']}/aggregate"
            if c["value"] > best.get(key, {}).get("value", 0.0):
                best[key] = {
                    "batch": c["batch"], "value": c["value"], "speedup": c.get("aggregate_speedup"),
                }
            continue
        if "coalesce_depth" in c:
            key = f"{c['kernel']}/mesh{c['mesh']}/coalesce"
            if c["value"] > best.get(key, {}).get("value", 0.0):
                best[key] = {"batch": c["batch"], "depth": c["coalesce_depth"], "value": c["value"]}
            continue
        key = f"{c['kernel']}/mesh{c['mesh']}"
        if c["value"] > best.get(key, {}).get("value", 0.0):
            best[key] = {"batch": c["batch"], "value": c["value"]}
    out_path = os.environ.get("KASPA_TPU_BENCH_SWEEP_PATH", "BENCH_SWEEP.json")
    doc = {
        "created": _utc_stamp(compact=False),
        "devices": devices,
        "batches": batches,
        "meshes": meshes,
        "cells": cells,
        "best": best,
        # --verify-mode auto reads crossover_batch from here; cells above
        # carry the full aggregate_speedup column
        "aggregate": {"crossover_batch": agg_crossover, "batches": agg_batches},
        "probe_log": probe_log,
    }
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
    print(json.dumps({"sweep": out_path, "devices": devices, "best": best}))


def main() -> None:
    if os.environ.get("KASPA_TPU_BENCH_CHILD"):
        mode = os.environ.get("KASPA_TPU_BENCH_MODE")
        if mode == "probe":
            _child_probe_main()
        elif mode == "warmstart":
            _child_warmstart_main()
        else:
            _child_main()
        return  # unreachable (child exits)

    # fast-fail: a wedge dossier younger than the TTL is a standing verdict —
    # skip the probe + fresh-subprocess retry spiral entirely
    probe_log: list = []
    cached = _cached_wedge(probe_log)
    if cached is not None:
        dossier, doc = cached
        if "--probe" in sys.argv[1:]:
            print(json.dumps({"probe_ok": False, "cached_wedge": dossier, "log": probe_log}))
            sys.exit(1)
        fb = doc.get("cpu_fallback") or {}
        print(
            json.dumps(
                {
                    "metric": METRIC,
                    "value": 0.0,
                    "unit": UNIT,
                    "vs_baseline": 0.0,
                    "error": "cached wedge verdict within TTL "
                    "(KASPA_TPU_BENCH_FORCE_PROBE=1 to re-probe)",
                    "wedge_dossier": dossier,
                    "cached": True,
                    "cpu_fallback_value": float(fb.get("value") or 0.0),
                }
            )
        )
        return

    # session-start probe: a dead backend is diagnosed in ~2 min with a
    # dossier on disk, instead of burning the whole attempt budget first
    probe_ok = _session_probe(probe_log)
    if "--probe" in sys.argv[1:]:
        print(json.dumps({"probe_ok": probe_ok, "log": probe_log}))
        sys.exit(0 if probe_ok else 1)
    if not probe_ok:
        fallback = _cpu_fallback(probe_log)
        warm = _warm_start_child(probe_log)
        dossier = _write_wedge_dossier(probe_log, fallback, warm_start=warm)
        fb_value = float(fallback.get("value", 0.0)) if fallback else 0.0
        print(
            json.dumps(
                {
                    "metric": METRIC,
                    "value": 0.0,
                    "unit": UNIT,
                    "vs_baseline": 0.0,
                    "error": "device probe wedged at session start (see wedge dossier)",
                    "wedge_dossier": dossier,
                    "cpu_fallback_value": fb_value,
                    # the pipeline-shape evidence is CPU-path and survives
                    # the wedge: the round artifact still documents the
                    # virtual critical-path shift
                    "virtual_critical_path": _virtual_critical_path(),
                }
            )
        )
        return

    if "--sweep" in sys.argv[1:]:
        devices = 0
        for entry in probe_log:
            child = entry.get("child") or {}
            devices = max(devices, int(child.get("devices", 0) or 0))
        _sweep(probe_log, devices)
        return

    deadline = time.monotonic() + TOTAL_BUDGET_S
    notes: list[str] = []
    last_obs: dict | None = None
    for attempt in range(MAX_ATTEMPTS):
        remaining = deadline - time.monotonic()
        if attempt > 0 and remaining <= RETRY_BACKOFF_S + 60:
            notes.append("budget exhausted")
            break
        # always give the first attempt its full window; later ones get
        # whatever budget remains (a wedged backend burns probe-time only)
        timeout_s = ATTEMPT_TIMEOUT_S if attempt == 0 else min(ATTEMPT_TIMEOUT_S, remaining - 10)
        result, note, obs = _run_attempt(timeout_s)
        notes.append(f"attempt {attempt + 1}: {note}")
        if obs is not None:
            last_obs = obs
        if result is not None:
            result["virtual_critical_path"] = _virtual_critical_path()
            print(json.dumps(result))
            return
        time.sleep(RETRY_BACKOFF_S)

    # the retry spiral exhausting IS a wedge verdict: record it as a dossier
    # so the next invocation within the TTL fast-fails instead of burning
    # another full attempt budget on the same sick backend
    probe_log.append({"t": _utc_stamp(), "event": "attempt_spiral_exhausted", "notes": notes})
    warm = _warm_start_child(probe_log)
    dossier = _write_wedge_dossier(
        probe_log,
        None,
        reason="attempt spiral exhausted (probe answered, workload never finished)",
        warm_start=warm,
    )
    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": 0.0,
                "unit": UNIT,
                "vs_baseline": 0.0,
                "error": "device backend unresponsive after fresh-subprocess retries: "
                + "; ".join(notes),
                "wedge_dossier": dossier,
                "observability": last_obs,
            }
        )
    )


if __name__ == "__main__":
    main()
