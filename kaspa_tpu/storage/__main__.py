"""DB admin tooling: inspect / verify / compact a node's KV stores.

Reference: database/rocknroll — offline RocksDB tooling over a kaspad
datadir (open the active consensus DB, scan/prune/report).  Here the
store is our CRC-framed append-only engine (native/kvstore); the tool
resolves the ACTIVE pointer like the daemon does and speaks the same
prefix registry as consensus/stores.py.

    python -m kaspa_tpu.storage stats   --appdir ~/.kaspa-tpu
    python -m kaspa_tpu.storage verify  --appdir ~/.kaspa-tpu
    python -m kaspa_tpu.storage compact --appdir ~/.kaspa-tpu
    python -m kaspa_tpu.storage get     --appdir ... --prefix HD --key <hex>
"""

from __future__ import annotations

import argparse
import os
import sys

from kaspa_tpu.consensus import stores as st
from kaspa_tpu.storage.kv import KvStore

PREFIX_NAMES = {
    st.PREFIX_HEADERS: "headers",
    st.PREFIX_RELATIONS: "relations",
    st.PREFIX_GHOSTDAG: "ghostdag",
    st.PREFIX_STATUSES: "statuses",
    st.PREFIX_BLOCK_TXS: "block-transactions",
    st.PREFIX_UTXO_DIFFS: "utxo-diffs",
    st.PREFIX_MULTISETS: "multisets",
    st.PREFIX_ACCEPTANCE: "acceptance-data",
    st.PREFIX_DAA_EXCLUDED: "daa-excluded",
    st.PREFIX_UTXO_SET: "utxo-set",
    st.PREFIX_PRUNING_UTXO: "pruning-utxo-set",
    st.PREFIX_DEPTH: "merge-depth",
    st.PREFIX_PRUNING_SAMPLES: "pruning-samples",
    st.PREFIX_REACH_MERGESET: "reachability-mergesets",
    st.PREFIX_CHILDREN: "relations-children",
    st.PREFIX_BLOCK_LEVELS: "block-levels",
    st.PREFIX_META: "metadata",
    st.PREFIX_REACH_NODE: "reachability-nodes",
    b"SM": "smt-builds",
    b"SL": "smt-lane-tips",
}


def resolve_active_db(appdir: str) -> str:
    """Same ACTIVE-pointer discipline as the daemon (node/daemon.py)."""
    active = "consensus.db"
    pointer = os.path.join(appdir, "ACTIVE")
    if os.path.exists(pointer):
        with open(pointer) as f:
            name = f.read().strip()
        if name and os.path.exists(os.path.join(appdir, name)):
            active = name
    path = os.path.join(appdir, active)
    if not os.path.exists(path):
        raise SystemExit(f"no consensus DB at {path}")
    return path


def cmd_stats(store: KvStore) -> int:
    per_prefix: dict[bytes, list] = {}
    total_keys = 0
    total_bytes = 0
    for k, v in store.engine.items():
        total_keys += 1
        total_bytes += len(k) + len(v)
        bucket = per_prefix.setdefault(k[:2], [0, 0])
        bucket[0] += 1
        bucket[1] += len(k) + len(v)
    print(f"{'store':<24}{'keys':>10}{'bytes':>14}")
    for prefix, (n, size) in sorted(per_prefix.items(), key=lambda kv: -kv[1][1]):
        name = PREFIX_NAMES.get(prefix, f"?{prefix!r}")
        print(f"{name:<24}{n:>10}{size:>14}")
    print(f"{'TOTAL':<24}{total_keys:>10}{total_bytes:>14}")
    print(f"log size on disk: {store.size_on_disk()} bytes")
    ms = store.mem_stats()
    print(
        f"arena: {ms['arena_slabs']} slabs, {ms['arena_reserved_bytes']} reserved, "
        f"{ms['arena_in_use_bytes']} in use, {ms['arena_large_allocs']} large allocs"
    )
    return 0


def cmd_verify(store: KvStore) -> int:
    """The open itself replays the CRC-framed log; surviving it means every
    frame checksummed clean.  Cross-check the live index for shape."""
    n = 0
    bad = 0
    for k, _v in store.engine.items():
        n += 1
        if k[:2] not in PREFIX_NAMES:
            bad += 1
    print(f"replayed clean: {n} live keys, {bad} outside the prefix registry")
    return 1 if bad else 0


def cmd_compact(store: KvStore) -> int:
    before = store.size_on_disk()
    store.engine.compact()
    after = store.size_on_disk()
    print(f"compacted: {before} -> {after} bytes ({before - after} reclaimed)")
    return 0


def cmd_get(store: KvStore, prefix: str, key_hex: str) -> int:
    value = store.engine.get(prefix.encode() + bytes.fromhex(key_hex))
    if value is None:
        print("not found", file=sys.stderr)
        return 1
    print(value.hex())
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kaspa-tpu-db", description="KV store admin tooling")
    p.add_argument("command", choices=["stats", "verify", "compact", "get"])
    p.add_argument("--appdir", default=os.path.expanduser("~/.kaspa-tpu"))
    p.add_argument("--db", default=None, help="explicit db path (bypasses the ACTIVE pointer)")
    p.add_argument("--prefix", default=None, help="2-char store prefix for `get`")
    p.add_argument("--key", default=None, help="hex key for `get`")
    args = p.parse_args(argv)
    path = args.db if args.db else resolve_active_db(args.appdir)
    store = KvStore(path)
    try:
        if args.command == "stats":
            return cmd_stats(store)
        if args.command == "verify":
            return cmd_verify(store)
        if args.command == "compact":
            return cmd_compact(store)
        if args.command == "get":
            if not args.prefix or not args.key:
                p.error("get requires --prefix and --key")
            return cmd_get(store, args.prefix, args.key)
        return 2
    finally:
        store.close()


if __name__ == "__main__":
    raise SystemExit(main())
