"""Persistent KV store: ctypes bindings over the native C++ engine.

The storage layer counterpart of the reference's kaspa-database
(database/src/: DB + DbWriter/BatchDbWriter + prefixed stores).  The C++
engine (native/kvstore/kvstore.cc) provides crash-consistent CRC-framed
atomic write batches over an append log with in-memory index; this module
adds the typed prefixed-store access layer (registry.rs/access.rs shape).

Builds the shared library on first use (g++, cached beside the source);
a pure-python fallback engine keeps tests running without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading

from kaspa_tpu.utils.sync import ranked_lock

from kaspa_tpu.observability.core import REGISTRY
from kaspa_tpu.resilience.faults import FAULTS, FaultInjected

_JOURNAL_REPAIRS = REGISTRY.counter(
    "kv_journal_repairs", help="torn log tails truncated back to the last valid frame on replay"
)
_TORN_BYTES = REGISTRY.counter("kv_journal_torn_bytes", help="garbage bytes discarded by journal repair")

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native", "kvstore")
_SRC = os.path.join(_NATIVE_DIR, "kvstore.cc")
_HEADERS = (os.path.join(_NATIVE_DIR, "arena.h"),)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libkvstore.so")
_BUILD_LOCK = ranked_lock("storage.build")


def _src_mtime() -> float:
    return max(os.path.getmtime(f) for f in (_SRC, *_HEADERS) if os.path.exists(f))


def _build_native():
    if os.path.exists(_LIB_PATH) and os.path.getmtime(_LIB_PATH) >= _src_mtime():
        return _LIB_PATH
    with _BUILD_LOCK:
        if os.path.exists(_LIB_PATH) and os.path.getmtime(_LIB_PATH) >= _src_mtime():
            return _LIB_PATH
        tmp = _LIB_PATH + f".tmp{os.getpid()}"
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC],
            check=True,
            capture_output=True,
        )
        os.replace(tmp, _LIB_PATH)
    return _LIB_PATH


# keys/values are raw binary (embedded NULs are the norm for hashes), so the
# callback must take void* — c_char_p would NUL-truncate before string_at
_ITER_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p)


class _NativeEngine:
    def __init__(self, path: str):
        self.path = path
        lib = ctypes.CDLL(_build_native())
        lib.kv_open.restype = ctypes.c_void_p
        lib.kv_open.argtypes = [ctypes.c_char_p]
        lib.kv_close.argtypes = [ctypes.c_void_p]
        lib.kv_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint32]
        lib.kv_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
        lib.kv_get.restype = ctypes.c_int64
        lib.kv_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint32]
        lib.kv_batch_begin.argtypes = [ctypes.c_void_p]
        lib.kv_batch_commit.argtypes = [ctypes.c_void_p]
        lib.kv_len.restype = ctypes.c_uint64
        lib.kv_len.argtypes = [ctypes.c_void_p]
        lib.kv_iterate.argtypes = [ctypes.c_void_p, _ITER_CB, ctypes.c_void_p]
        lib.kv_iterate_prefix.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_int, _ITER_CB, ctypes.c_void_p,
        ]
        lib.kv_count_prefix.restype = ctypes.c_uint64
        lib.kv_count_prefix.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
        lib.kv_mem_stats.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
        lib.kv_compact.argtypes = [ctypes.c_void_p]
        self._lib = lib
        self._h = lib.kv_open(path.encode())
        if not self._h:
            raise IOError(f"failed to open kv store at {path}")

    def put(self, key: bytes, value: bytes):
        rc = self._lib.kv_put(self._h, key, len(key), value, len(value))
        if rc != 0:
            raise IOError(f"kv_put failed: {rc}")

    def get(self, key: bytes):
        n = self._lib.kv_get(self._h, key, len(key), None, 0)
        if n < 0:
            return None
        buf = ctypes.create_string_buffer(n)
        rc = self._lib.kv_get(self._h, key, len(key), buf, n)
        if rc < 0:
            # values live on disk now: a failed pread must raise, never
            # hand zero-filled bytes to a consensus decoder
            raise IOError(f"kv_get read failed: {rc}")
        return buf.raw

    def has(self, key: bytes) -> bool:
        # length-probe only: no disk read
        return self._lib.kv_get(self._h, key, len(key), None, 0) >= 0

    def delete(self, key: bytes):
        self._lib.kv_delete(self._h, key, len(key))

    def batch_begin(self):
        rc = self._lib.kv_batch_begin(self._h)
        if rc != 0:
            raise IOError(f"kv_batch_begin failed: {rc}")

    def batch_commit(self):
        # fires BEFORE the native commit: the engine's own crash-safety
        # (CRC-framed atomic batch) must absorb the abandoned batch
        FAULTS.fire("storage.commit")
        rc = self._lib.kv_batch_commit(self._h)
        if rc != 0:
            raise IOError(f"kv_batch_commit failed: {rc}")

    def __len__(self):
        return self._lib.kv_len(self._h)

    def items(self):
        out = []

        def cb(k, klen, v, vlen, _ctx):
            out.append((ctypes.string_at(k, klen), ctypes.string_at(v, vlen)))

        self._lib.kv_iterate(self._h, _ITER_CB(cb), None)
        return out

    def items_prefix(self, prefix: bytes):
        """Ordered (key-without-prefix, value) pairs under ``prefix``."""
        n = len(prefix)
        out = []

        def cb(k, klen, v, vlen, _ctx):
            out.append((ctypes.string_at(k, klen)[n:], ctypes.string_at(v, vlen) if vlen else b""))

        self._lib.kv_iterate_prefix(self._h, prefix, n, 1, _ITER_CB(cb), None)
        return out

    def keys_prefix(self, prefix: bytes):
        """Ordered keys (without the prefix) under ``prefix`` — no disk reads."""
        n = len(prefix)
        out = []

        def cb(k, klen, _v, _vlen, _ctx):
            out.append(ctypes.string_at(k, klen)[n:])

        self._lib.kv_iterate_prefix(self._h, prefix, n, 0, _ITER_CB(cb), None)
        return out

    def count_prefix(self, prefix: bytes) -> int:
        return self._lib.kv_count_prefix(self._h, prefix, len(prefix))

    def mem_stats(self) -> dict:
        """Slab-arena stats of the resident index (the kaspa-alloc
        visibility story: allocator behavior is observable)."""
        out = (ctypes.c_uint64 * 4)()
        self._lib.kv_mem_stats(self._h, out)
        return {
            "arena_slabs": out[0],
            "arena_reserved_bytes": out[1],
            "arena_in_use_bytes": out[2],
            "arena_large_allocs": out[3],
        }

    def compact(self):
        rc = self._lib.kv_compact(self._h)
        if rc != 0:
            raise IOError(f"kv_compact failed: {rc}")

    def close(self):
        if self._h:
            self._lib.kv_close(self._h)
            self._h = None


class _PythonEngine:
    """Fallback with the same log format semantics (non-durable simplification:
    full-file rewrite on close/compact, in-memory otherwise)."""

    def __init__(self, path: str):
        self.path = path
        self.index: dict[bytes, bytes] = {}
        self._batch = False
        if os.path.exists(path):
            self._replay()
        self._log = open(path, "ab")
        self._pending = bytearray()

    def _replay(self):
        import zlib

        with open(self.path, "rb") as f:
            data = f.read()
        off = 0
        while off + 12 <= len(data):
            if data[off : off + 4] != b"KBAT":
                break
            (plen,) = struct.unpack_from("<I", data, off + 4)
            end = off + 8 + plen
            if end + 4 > len(data):
                break
            payload = data[off + 8 : end]
            (crc,) = struct.unpack_from("<I", data, end)
            if zlib.crc32(payload) != crc:
                break
            p = 0
            while p < plen:
                op = payload[p]
                klen, vlen = struct.unpack_from("<II", payload, p + 1)
                p += 9
                key = payload[p : p + klen]
                p += klen
                if op == 0:
                    self.index[key] = payload[p : p + vlen]
                else:
                    self.index.pop(key, None)
                p += vlen
            off = end + 4
        if off < len(data):
            # torn tail (crash mid-frame): truncate back to the last valid
            # frame so the append handle extends the *valid* prefix —
            # without this, later frames land after garbage and are
            # silently orphaned on the next replay
            _JOURNAL_REPAIRS.inc()
            _TORN_BYTES.inc(len(data) - off)
            with open(self.path, "r+b") as f:
                f.truncate(off)

    def put(self, key, value):
        self._pending += bytes([0]) + struct.pack("<II", len(key), len(value)) + key + value
        self.index[key] = value
        if not self._batch:
            self._flush()

    def delete(self, key):
        self._pending += bytes([1]) + struct.pack("<II", len(key), 0) + key
        self.index.pop(key, None)
        if not self._batch:
            self._flush()

    def _flush(self):
        import zlib

        if not self._pending:
            return
        payload = bytes(self._pending)
        frame = b"KBAT" + struct.pack("<I", len(payload)) + payload + struct.pack("<I", zlib.crc32(payload))
        act = FAULTS.fire("storage.flush")
        if act is not None and act.mode == "partial":
            # simulated crash mid-write: a deterministic prefix of the frame
            # hits the disk, the rest never does.  _pending is retained and
            # the torn tail is left behind — replay truncates it on reopen.
            cut = act.rng.randrange(1, len(frame))
            self._log.write(frame[:cut])
            self._log.flush()
            raise FaultInjected("storage.flush", act.hit, act.mode)
        start = self._log.tell()
        try:
            self._log.write(frame)
            self._log.flush()
        except Exception:
            # atomic append: a failed/short write must not leave a torn
            # frame for the *next* flush to bury — roll the file back to
            # the pre-write offset and keep _pending for a retry
            try:
                self._log.seek(start)
                self._log.truncate(start)
            except OSError:
                pass
            raise
        self._pending = bytearray()

    def get(self, key):
        return self.index.get(key)

    def has(self, key: bytes) -> bool:
        return key in self.index

    def batch_begin(self):
        self._batch = True

    def batch_commit(self):
        # same placement as the native engine: the abandoned batch must be
        # absorbed by the CRC frame discipline, not half-applied
        FAULTS.fire("storage.commit")
        self._batch = False
        self._flush()

    def __len__(self):
        return len(self.index)

    def items(self):
        return list(self.index.items())

    def items_prefix(self, prefix: bytes):
        n = len(prefix)
        return sorted((k[n:], v) for k, v in self.index.items() if k.startswith(prefix))

    def keys_prefix(self, prefix: bytes):
        n = len(prefix)
        return sorted(k[n:] for k in self.index if k.startswith(prefix))

    def count_prefix(self, prefix: bytes) -> int:
        return sum(1 for k in self.index if k.startswith(prefix))

    def mem_stats(self) -> dict:
        return {"arena_slabs": 0, "arena_reserved_bytes": 0, "arena_in_use_bytes": 0, "arena_large_allocs": 0}

    def compact(self):
        pass

    def close(self):
        self._flush()
        self._log.close()


def open_store(path: str, native: bool = True):
    if native:
        try:
            return _NativeEngine(path)
        except Exception:
            pass
    return _PythonEngine(path)


class KvStore:
    """Typed prefixed access (database/src/registry.rs + access.rs shape)."""

    def __init__(self, path: str, native: bool = True):
        self.path = path
        self.engine = open_store(path, native)

    def prefixed(self, prefix: bytes) -> "PrefixedStore":
        return PrefixedStore(self.engine, prefix)

    def batch(self):
        return _Batch(self.engine)

    def mem_stats(self) -> dict:
        return self.engine.mem_stats()

    def size_on_disk(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def close(self):
        self.engine.close()


class PrefixedStore:
    def __init__(self, engine, prefix: bytes):
        self.engine = engine
        self.prefix = prefix

    def put(self, key: bytes, value: bytes):
        self.engine.put(self.prefix + key, value)

    def get(self, key: bytes):
        return self.engine.get(self.prefix + key)

    def delete(self, key: bytes):
        self.engine.delete(self.prefix + key)

    def items(self):
        return self.engine.items_prefix(self.prefix)

    def keys(self):
        return self.engine.keys_prefix(self.prefix)

    def count(self) -> int:
        return self.engine.count_prefix(self.prefix)


class _Batch:
    """Atomic write batch with a real abort path.

    Mutations are buffered python-side and only touch the engine inside a
    begin/commit frame on successful exit — an exception inside the `with`
    leaves both the engine index and the log completely untouched
    (BatchDbWriter semantics, database/src/writer.rs)."""

    def __init__(self, engine):
        self.engine = engine
        self._ops: list[tuple] = []

    def put(self, key: bytes, value: bytes):
        self._ops.append(("put", key, value))

    def delete(self, key: bytes):
        self._ops.append(("del", key, None))

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None and self._ops:
            self.engine.batch_begin()
            try:
                for op, key, value in self._ops:
                    if op == "put":
                        self.engine.put(key, value)
                    else:
                        self.engine.delete(key)
            finally:
                self.engine.batch_commit()
        self._ops.clear()
        return False
