from kaspa_tpu.storage.kv import KvStore, open_store  # noqa: F401
