"""Batched-vs-per-tx admission identity check (roundcheck ``ingest`` section).

Builds a short DAG, replays it into one consensus, then drives the SAME
deterministic flood stream (clean spends, double-spend chains, RBF churn,
orphan storms — txflood.FloodStream) through two mempools over that one
consensus:

- **batched**: ``IngestTier.submit`` + ``pump`` waves (one shared checker
  dispatch per wave on the ``standalone_tx`` traffic class), recording the
  true arrival order (source-lane round-robin) as the waves prepare;
- **per-tx**: ``validate_and_insert_transaction`` replayed one call at a
  time in exactly that recorded order.

Gates (all must hold):

- pool state identity: same txids with the same fees, same orphan-pool
  txids, and a fixed-timestamp block template selecting the same tx ids
  in the same order (both managers share one sampling seed);
- clean-fraction acceptance >= 0.99 on the batched path;
- zero lost tickets (every submission resolved exactly once).

Emits one JSON line; exit 0 iff ``ingest_ok``.

    python -m kaspa_tpu.ingest.check --blocks 24 --tpb 4 --slots 6
"""

from __future__ import annotations

import argparse
import json
import random

from kaspa_tpu.utils import jax_setup

jax_setup.setup()

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.processes.transaction_validator import TxRuleError
from kaspa_tpu.ingest.tier import IngestTier
from kaspa_tpu.mempool.mempool import MempoolError
from kaspa_tpu.mempool.mining_manager import MiningManager
from kaspa_tpu.resilience.txflood import FloodStream, TxFloodConfig
from kaspa_tpu.sim.simulator import SimConfig, simulate


def run_check(
    blocks: int = 24, tpb: int = 4, slots: int = 6, seed: int = 7, bps: int = 2
) -> dict:
    cfg = SimConfig(bps=bps, num_blocks=blocks, txs_per_block=tpb, seed=seed)
    sim = simulate(cfg)
    consensus = Consensus(sim.params)
    for b in sim.blocks:
        status = consensus.validate_and_insert_block(b)
        assert status in ("utxo_valid", "utxo_pending"), status

    # batched path, recording the true in-wave arrival order
    batched = MiningManager(consensus, seed=seed)
    tier = IngestTier(batched)
    flood = FloodStream(consensus, cfg, TxFloodConfig(), random.Random(seed ^ 0xF100D))
    arrival: list = []
    orig_prepare = batched.prepare_transaction

    def recording_prepare(tx, checker, token):
        arrival.append(tx)
        return orig_prepare(tx, checker, token)

    batched.prepare_transaction = recording_prepare
    for _ in range(slots):
        flood.step(tier)
    tier_stats = tier.stats()

    # per-tx path: the same transactions, the same arrival order
    pertx = MiningManager(consensus, seed=seed)
    for tx in arrival:
        try:
            pertx.validate_and_insert_transaction(tx)
        except (MempoolError, TxRuleError):
            pass

    pool_a = {t.hex(): e.fee for t, e in sorted(batched.mempool.pool.items())}
    pool_b = {t.hex(): e.fee for t, e in sorted(pertx.mempool.pool.items())}
    orphans_a = sorted(t.hex() for t in batched.mempool.orphans)
    orphans_b = sorted(t.hex() for t in pertx.mempool.orphans)
    ts = consensus.virtual_state.past_median_time + 1
    template_a = [t.id().hex() for t in batched.get_block_template(flood.miner_data, timestamp=ts).transactions]
    template_b = [t.id().hex() for t in pertx.get_block_template(flood.miner_data, timestamp=ts).transactions]

    fl = flood.counters
    clean_rate = fl["clean_accepted"] / fl["clean_submitted"] if fl["clean_submitted"] else 0.0
    identical = pool_a == pool_b and orphans_a == orphans_b and template_a == template_b
    return {
        "blocks": blocks,
        "slots": slots,
        "flood": dict(sorted(fl.items())),
        "pool_size": len(pool_a),
        "orphan_size": len(orphans_a),
        "template_txs": len(template_a),
        "pool_identical": pool_a == pool_b,
        "orphans_identical": orphans_a == orphans_b,
        "template_identical": template_a == template_b,
        "tx_acceptance_rate": round(clean_rate, 4),
        "lost_tickets": tier_stats["lost"],
        "waves": tier_stats["waves"],
        "ingest_ok": identical and clean_rate >= 0.99 and tier_stats["lost"] == 0,
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--blocks", type=int, default=24)
    ap.add_argument("--tpb", type=int, default=4)
    ap.add_argument("--slots", type=int, default=6, help="flood slots to drive after the replay")
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args(argv)
    report = run_check(blocks=args.blocks, tpb=args.tpb, slots=args.slots, seed=args.seed)
    print(json.dumps(report))
    return 0 if report["ingest_ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
