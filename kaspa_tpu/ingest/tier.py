"""Micro-batched transaction admission on the verify plane.

Concurrent entrants from the ingest queue are grouped into *waves*: the
worker pops the first entrant, lingers ``max_wait_ms`` for company, and
admits the whole wave through the split mempool intake
(``MiningManager.prepare_transaction`` / ``finish_transaction``):

- **phase 1 (on the mempool lock, arrival order)**: contextual
  pre-checks — isolation, gas cap, header context, the virtual-UTXO view
  lookup (missing inputs park the tx as an orphan right here), fee/mass
  population — with every entrant's signature/script jobs collected into
  ONE shared ``BatchScriptChecker``;
- **phase 2 (off the lock)**: a single ``dispatch_async`` rides the
  verify plane under the ``standalone_tx`` traffic class, so a wave of N
  transactions pays one coalesced device dispatch instead of N;
- **phase 3 (on the lock, arrival order)**: per-entrant verdicts feed
  ``finish_transaction`` — duplicate/double-spend/RBF/fee-floor/full
  resolve at insert exactly as the per-tx path would have resolved them.

Because every state-dependent step runs in arrival order under the same
lock, batched admission is state-identical to calling
``validate_and_insert_transaction`` per entrant (the roundcheck
``ingest`` section asserts this bit-for-bit).  Each entrant gets an
``AdmissionTicket`` resolved when its wave completes; no ticket is ever
lost — every accepted submission resolves exactly once, even on
``stop()``.
"""

from __future__ import annotations

import threading

from kaspa_tpu.utils.sync import ranked_lock
import time
from dataclasses import dataclass

from kaspa_tpu.ingest.queue import SOURCE_RPC, IngestQueue
from kaspa_tpu.mempool.mempool import MempoolError
from kaspa_tpu.observability import trace
from kaspa_tpu.observability.core import REGISTRY, SIZE_BUCKETS
from kaspa_tpu.ops.dispatch import TX_CLASS

_WAVE_SIZE = REGISTRY.histogram(
    "ingest_wave_size", SIZE_BUCKETS, help="transactions admitted per ingest wave"
)
_WAVE_MS = REGISTRY.histogram(
    "ingest_wave_ms",
    (0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 1000.0),
    help="wall time per ingest wave (prepare + verify + finish), milliseconds",
)
_OUTCOMES = REGISTRY.counter_family(
    "ingest_outcomes", "outcome", help="admission verdicts (accepted/orphaned/rejected)"
)
from kaspa_tpu.observability.shed import SHED as _SHED  # noqa: E402  (family declared once there)

ACCEPTED = "accepted"
ORPHANED = "orphaned"
REJECTED = "rejected"


@dataclass
class IngestConfig:
    queue_capacity: int = 10_000  # per-source lane bound
    batch_max: int = 256  # wave ceiling (matches the standalone_tx coalesce default)
    max_wait_ms: float = 2.0  # linger after the first entrant before admitting


class AdmissionTicket:
    """One entrant's admission future.

    Resolves exactly once with status accepted / orphaned / rejected;
    ``raise_for_status`` replays the per-tx call's contract (raise the
    stored MempoolError/TxRuleError, else return the RBF-evicted txids).
    """

    __slots__ = ("tx", "source", "status", "evicted", "error", "_done")

    def __init__(self, tx, source: str):
        self.tx = tx
        self.source = source
        self.status: str | None = None
        self.evicted: list[bytes] = []
        self.error: Exception | None = None
        self._done = threading.Event()

    def _resolve(self, status: str, evicted=None, error=None) -> None:
        self.status = status
        if evicted:
            self.evicted = evicted
        self.error = error
        _OUTCOMES.inc(status)
        self._done.set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def raise_for_status(self) -> list[bytes]:
        assert self._done.is_set(), "ticket not yet resolved"
        if self.error is not None:
            raise self.error
        return self.evicted


class IngestTier:
    """The admission front door: queue + worker + wave batcher.

    ``lock`` serializes mempool/consensus access; the daemon passes the
    node lock so admission interleaves safely with block processing.
    Standalone use (sim, tests) defaults to a private RLock.
    """

    def __init__(self, mining, lock=None, config: IngestConfig | None = None):
        self.mining = mining
        self.lock = lock if lock is not None else ranked_lock("ingest.state")
        self.config = config or IngestConfig()
        self.queue = IngestQueue(self.config.queue_capacity)
        self._worker: threading.Thread | None = None
        self._stop = threading.Event()
        # lost = submitted - resolved must be 0 after drain (roundcheck gate)
        self._submitted = 0
        self._resolved = 0
        self._waves = 0
        self._mu = ranked_lock("ingest.stats", reentrant=False)
        # overload brownout state (set by resilience/overload.py): when
        # active, new submissions are rejected up-front with the stable
        # node-overloaded code + a retry-after hint.  Already-queued
        # tickets still admit normally — shed new work, never accepted work.
        self._overload_active = False
        self._overload_retry_ms = 0

    def set_overload(self, active: bool, retry_after_ms: int = 0) -> None:
        """Brownout seam: reject new submissions with ``node-overloaded``
        (+ retry hint) while active.  Every rejected tx still resolves its
        AdmissionTicket — the lost==0 invariant is untouched."""
        with self._mu:
            self._overload_active = bool(active)
            self._overload_retry_ms = int(retry_after_ms)

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        if self._worker is not None:
            return
        self._stop.clear()
        self._worker = threading.Thread(target=self._run, name="tx-ingest", daemon=True)
        self._worker.start()

    def stop(self, timeout: float = 10.0) -> None:
        """Drain the queue, resolve every outstanding ticket, stop the worker."""
        self._stop.set()
        worker = self._worker
        if worker is not None:
            worker.join(timeout)
            self._worker = None
        # the worker exits only after draining, but a stop() without start()
        # (sync mode) may still hold queued tickets
        self.pump()

    # -- submission -----------------------------------------------------

    def submit(self, tx, source: str = SOURCE_RPC) -> AdmissionTicket:
        """Enqueue one transaction; returns its ticket immediately.

        A full lane resolves the ticket rejected right away (bounded
        memory under floods) instead of blocking the submitter.
        """
        ticket = AdmissionTicket(tx, source)
        with self._mu:
            self._submitted += 1
            overloaded, retry_ms = self._overload_active, self._overload_retry_ms
        if overloaded:
            _SHED.inc("ingest_shed")
            self._finish_ticket(
                ticket,
                REJECTED,
                error=MempoolError(
                    "node overloaded, retry later",
                    code="node-overloaded",
                    retry_after_ms=retry_ms or None,
                ),
            )
            return ticket
        if not self.queue.put(source, ticket):
            self._finish_ticket(
                ticket,
                REJECTED,
                error=MempoolError(
                    f"ingest queue full for source {source!r}", code="ingest-backpressure"
                ),
            )
        return ticket

    def pump(self) -> int:
        """Synchronously drain the queue in waves; returns txs admitted.

        The deterministic path for sim/roundcheck: no worker thread, no
        timing dependence — every queued entrant is admitted now.
        """
        total = 0
        while True:
            wave = self.queue.pop_wave(self.config.batch_max)
            if not wave:
                return total
            self._admit_wave(wave)
            total += len(wave)

    def admit(self, tx, source: str = SOURCE_RPC) -> AdmissionTicket:
        """Submit + combining pump: the caller-thread batching front door.

        Without a worker, the submitter drains the queue itself — and since
        the queue is shared, it admits every *concurrent* entrant queued
        behind the lock in the same wave (the combining-lock pattern:
        batching emerges exactly when submissions contend).  Our own ticket
        may have been popped by a concurrent pumper whose wave is still in
        flight, so wait for resolution either way.  With a worker running,
        this just blocks on the ticket — do not call it while holding
        ``self.lock`` in that mode (the worker needs the lock to resolve).
        """
        ticket = self.submit(tx, source)
        if self._worker is None:
            self.pump()
        ticket.wait(600.0)
        return ticket

    # -- worker ---------------------------------------------------------

    def _run(self) -> None:
        linger = self.config.max_wait_ms / 1000.0
        while True:
            wave = self.queue.pop_wave(1, wait_s=0.25)
            if wave:
                if linger > 0 and len(wave) < self.config.batch_max:
                    time.sleep(linger)  # let concurrent entrants join the wave
                wave.extend(self.queue.pop_wave(self.config.batch_max - len(wave)))
                try:
                    self._admit_wave(wave)
                except Exception:  # noqa: BLE001 - tickets already resolved rejected
                    pass
            elif self._stop.is_set():
                if self.queue.depth() == 0:
                    return
            # else: idle poll; loop back to the blocking pop

    # -- wave admission -------------------------------------------------

    def _admit_wave(self, tickets: list[AdmissionTicket]) -> None:
        t0 = time.perf_counter()
        try:
            with trace.span("ingest.wave", size=len(tickets)):
                checker = self.mining.consensus.transaction_validator.new_checker(
                    traffic_class=TX_CLASS
                )
                prepared: dict[int, object] = {}
                # phase 1: contextual pre-checks in arrival order, on the lock
                with self.lock:
                    for i, t in enumerate(tickets):
                        try:
                            prepared[i] = self.mining.prepare_transaction(t.tx, checker, token=i)
                        except Exception as e:  # noqa: BLE001 - verdict, not crash
                            self._finish_ticket(t, REJECTED, error=e)
                # phase 2: one batched verify for the whole wave, off the lock
                errs = checker.dispatch_async().result() if prepared else {}
                # phase 3: verdicts + inserts in arrival order, on the lock
                with self.lock:
                    for i, t in enumerate(tickets):
                        p = prepared.get(i)
                        if p is None:
                            continue  # rejected in phase 1
                        try:
                            evicted = self.mining.finish_transaction(p, errs.get(i))
                        except Exception as e:  # noqa: BLE001
                            self._finish_ticket(t, REJECTED, error=e)
                            continue
                        self._finish_ticket(t, ORPHANED if p.orphan else ACCEPTED, evicted=evicted)
        finally:
            # no ticket ever leaks unresolved: a wave-level failure (device
            # dispatch error, unexpected crash between phases) rejects every
            # still-pending entrant instead of stranding its waiter
            for t in tickets:
                if not t._done.is_set():
                    self._finish_ticket(
                        t, REJECTED, error=MempoolError("ingest wave failed", code="ingest-internal")
                    )
        with self._mu:
            self._waves += 1
        _WAVE_SIZE.observe(len(tickets))
        _WAVE_MS.observe((time.perf_counter() - t0) * 1000.0)

    def _finish_ticket(self, ticket: AdmissionTicket, status: str, evicted=None, error=None) -> None:
        ticket._resolve(status, evicted=evicted, error=error)
        with self._mu:
            self._resolved += 1

    # -- telemetry ------------------------------------------------------

    def stats(self) -> dict:
        with self._mu:
            submitted, resolved, waves = self._submitted, self._resolved, self._waves
            overloaded = self._overload_active
        out = _OUTCOMES.snapshot()
        return {
            "overload_active": overloaded,
            "submitted": submitted,
            "resolved": resolved,
            "lost": submitted - resolved - self.queue.depth(),
            "waves": waves,
            "accepted": out.get(ACCEPTED, 0),
            "orphaned": out.get(ORPHANED, 0),
            "rejected": out.get(REJECTED, 0),
            "queue": self.queue.stats(),
        }
