"""Transaction-ingest tier: backpressured, batched mempool admission.

The production front door — concurrent ``submitTransaction`` RPC callers
and P2P tx relay — feeds a bounded per-source-fair queue (queue.py) whose
waves are admitted by a micro-batcher (tier.py): contextual pre-checks
stay on the mempool lock in arrival order, while signature+script
verification for the whole wave rides the verify plane off-lock as the
``standalone_tx`` coalescing traffic class.  Admission outcomes are
state-identical to the per-tx ``validate_and_insert_transaction`` path.
"""

from kaspa_tpu.ingest.queue import SOURCE_P2P, SOURCE_RPC, IngestQueue
from kaspa_tpu.ingest.tier import AdmissionTicket, IngestConfig, IngestTier

__all__ = [
    "SOURCE_P2P",
    "SOURCE_RPC",
    "AdmissionTicket",
    "IngestConfig",
    "IngestQueue",
    "IngestTier",
]
