"""Bounded ingest queue with per-source fairness.

Two front doors feed transaction admission — RPC ``submitTransaction``
callers and P2P tx relay — and a flood on one must not starve the other.
Each source gets its own FIFO lane with its own capacity; a wave pop
round-robins across lanes (preserving per-source arrival order) so a P2P
orphan storm and a legitimate RPC submitter share the batcher fairly.
``put`` never blocks: a full lane sheds load immediately (the caller
turns that into an ``ingest-backpressure`` rejection), which keeps the
admission path's worst-case memory bounded under hostile floods.
"""

from __future__ import annotations

import threading

from kaspa_tpu.utils.sync import ranked_lock
from collections import deque

from kaspa_tpu.observability.core import REGISTRY

SOURCE_RPC = "rpc"
SOURCE_P2P = "p2p"

_SUBMITTED = REGISTRY.counter_family(
    "ingest_submitted", "source", help="transactions offered to the ingest queue, by source"
)
_BACKPRESSURE = REGISTRY.counter_family(
    "ingest_backpressure", "source", help="transactions shed by a full ingest lane, by source"
)


class IngestQueue:
    """Per-source bounded FIFO lanes under one lock + condition.

    ``capacity`` bounds each lane independently (a hostile source fills
    only its own lane).  ``pop_wave`` blocks up to ``wait_s`` for the
    first item, then drains up to ``max_items`` alternating lanes from a
    persistent round-robin cursor.
    """

    def __init__(self, capacity: int = 10_000, sources: tuple[str, ...] = (SOURCE_RPC, SOURCE_P2P)):
        self.capacity = capacity
        self._limit: int | None = None  # overload clamp (see set_capacity_limit)
        self._lanes: dict[str, deque] = {s: deque() for s in sources}  # graftlint: allow(unbounded-queue) -- lanes are capacity-bounded by the put() check below
        self._order: tuple[str, ...] = tuple(sources)
        self._next = 0  # round-robin cursor into _order
        self._mu = ranked_lock("ingest.queue", reentrant=False)
        self._nonempty = self._mu.condition()

    def set_capacity_limit(self, limit: int | None) -> None:
        """Overload clamp: shrink the effective per-lane bound below the
        configured capacity (None restores it).  Items already queued
        above a new lower limit stay queued — the clamp sheds new
        arrivals, it never drops accepted work."""
        with self._mu:
            self._limit = max(1, int(limit)) if limit is not None else None

    def effective_capacity(self) -> int:
        limit = self._limit
        return min(self.capacity, limit) if limit is not None else self.capacity

    def put(self, source: str, item) -> bool:
        """Enqueue on the source's lane; False (shed) when that lane is full."""
        _SUBMITTED.inc(source)
        with self._mu:
            lane = self._lanes.get(source)
            if lane is None:
                lane = self._lanes[source] = deque()  # graftlint: allow(unbounded-queue) -- bounded by the effective-capacity check below
                self._order = self._order + (source,)
            if len(lane) >= self.effective_capacity():
                _BACKPRESSURE.inc(source)
                return False
            lane.append(item)
            self._nonempty.notify()
            return True

    def pop_wave(self, max_items: int, wait_s: float = 0.0) -> list:
        """Dequeue up to ``max_items`` round-robin across lanes.

        Blocks up to ``wait_s`` for the first item; returns [] on timeout.
        Within one source the FIFO order is preserved; across sources the
        cursor alternates so neither can monopolize a wave.
        """
        with self._mu:
            if wait_s > 0 and not any(self._lanes.values()):
                self._nonempty.wait_for(lambda: any(self._lanes.values()), timeout=wait_s)
            out: list = []
            order = self._order
            n = len(order)
            misses = 0
            while len(out) < max_items and misses < n:
                lane = self._lanes[order[self._next % n]]
                self._next = (self._next + 1) % n
                if lane:
                    out.append(lane.popleft())
                    misses = 0
                else:
                    misses += 1
            return out

    def depth(self, source: str | None = None) -> int:
        with self._mu:
            if source is not None:
                lane = self._lanes.get(source)
                return len(lane) if lane is not None else 0
            return sum(len(lane) for lane in self._lanes.values())

    def stats(self) -> dict:
        with self._mu:
            return {
                "capacity": self.capacity,
                "effective_capacity": self.effective_capacity(),
                "depth": {s: len(lane) for s, lane in self._lanes.items()},
            }
