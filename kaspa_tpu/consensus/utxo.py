"""UTXO collections, diffs and views.

Reference: consensus/core/src/utxo/{utxo_collection,utxo_diff,utxo_view}.rs.
A UtxoDiff is (add, remove) entry maps with reconciliation rules;
views compose a base UTXO source with stacked diffs for O(1) lookups during
mergeset replay.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kaspa_tpu.consensus.model import Transaction, TransactionOutpoint, UtxoEntry


class UtxoAlgebraError(Exception):
    pass


class UtxoCollection(dict):
    """outpoint -> UtxoEntry"""


@dataclass
class UtxoDiff:
    add: UtxoCollection = field(default_factory=UtxoCollection)
    remove: UtxoCollection = field(default_factory=UtxoCollection)

    def add_entry(self, outpoint: TransactionOutpoint, entry: UtxoEntry) -> None:
        # mirror utxo_diff.rs add_entry: cancel with remove set when daa scores match
        if outpoint in self.remove and self.remove[outpoint].block_daa_score == entry.block_daa_score:
            del self.remove[outpoint]
        elif outpoint not in self.add:
            self.add[outpoint] = entry
        else:
            raise UtxoAlgebraError(f"double add call for {outpoint}")

    def remove_entry(self, outpoint: TransactionOutpoint, entry: UtxoEntry) -> None:
        if outpoint in self.add and self.add[outpoint].block_daa_score == entry.block_daa_score:
            del self.add[outpoint]
        elif outpoint not in self.remove:
            self.remove[outpoint] = entry
        else:
            raise UtxoAlgebraError(f"double remove call for {outpoint}")

    def add_transaction(self, tx: Transaction, utxo_entries, block_daa_score: int) -> None:
        """Spend the tx inputs and add its outputs (utxo_diff.rs:224-244)."""
        for inp, entry in zip(tx.inputs, utxo_entries):
            self.remove_entry(inp.previous_outpoint, entry)
        is_coinbase = tx.is_coinbase()
        tx_id = tx.id()
        for i, output in enumerate(tx.outputs):
            entry = UtxoEntry(
                output.value,
                output.script_public_key,
                block_daa_score,
                is_coinbase,
                output.covenant.covenant_id if output.covenant is not None else None,
            )
            self.add_entry(TransactionOutpoint(tx_id, i), entry)

    def clone(self) -> "UtxoDiff":
        return UtxoDiff(UtxoCollection(self.add), UtxoCollection(self.remove))


class UtxoView:
    """Layered view: base mapping composed with a diff (utxo_view.rs)."""

    def __init__(self, base, diff: UtxoDiff):
        self.base = base
        self.diff = diff

    def get(self, outpoint: TransactionOutpoint):
        if outpoint in self.diff.add:
            return self.diff.add[outpoint]
        if outpoint in self.diff.remove:
            return None
        if isinstance(self.base, UtxoView):
            return self.base.get(outpoint)
        return self.base.get(outpoint)

    def compose(self, diff: UtxoDiff) -> "UtxoView":
        return UtxoView(self, diff)

    def iter_all(self):
        """Yield every (outpoint, entry) visible through the view."""
        base_items = self.base.iter_all() if isinstance(self.base, UtxoView) else self.base.items()
        for op, entry in base_items:
            if op not in self.diff.add and op not in self.diff.remove:
                yield op, entry
        yield from self.diff.add.items()


def compose(base, diff: UtxoDiff) -> UtxoView:
    return UtxoView(base, diff)


def apply_diff(utxo_set: UtxoCollection, diff: UtxoDiff) -> None:
    """In-place application of a diff to a full UTXO set."""
    for outpoint in diff.remove:
        del utxo_set[outpoint]
    for outpoint, entry in diff.add.items():
        utxo_set[outpoint] = entry


def unapply_diff(utxo_set: UtxoCollection, diff: UtxoDiff) -> None:
    for outpoint in diff.add:
        del utxo_set[outpoint]
    for outpoint, entry in diff.remove.items():
        utxo_set[outpoint] = entry
