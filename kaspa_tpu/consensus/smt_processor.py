"""KIP-21 lane-state processor: the consensus-side SMT over active lanes.

Plays the combined role of the reference's `kaspa-smt-store` crate and the
virtual processor's seq-commit helpers
(consensus/smt-store/src/processor.rs, consensus/src/pipeline/
virtual_processor/utxo_validation.rs:497-684, processor.rs:790-906):

- materialized lane tips + SMT for the current UTXO position, moved in
  lock-step with the consensus engine's materialized UTXO set (advance on
  chain extension, retreat on reorg) — where the reference filters stale DB
  versions via `is_smt_canonical`, we keep the materialized state canonical
  by construction and version it with per-chain-block undo records
  (lane_version_store.rs semantics);
- the inactivity window: lanes untouched for `finality_depth` blue scores
  expire from the active set (SeqCommitBounds, bounds.rs);
- the inactivity shortcut block: highest chain block at
  ``bs <= current_bs - F - 1`` (processor.rs:790-853);
- per-chain-block metadata (lanes root, active-lane count, shortcut,
  payload digest) for parent lookups and IBD export (smt_metadata.rs).

Persistence piggybacks on the consensus storage batch: per-block build
records under ``SM``, materialized lane tips as deltas under ``SL`` — a
restart reloads the tip snapshot and rebuilds the tree (O(active lanes)).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from kaspa_tpu.consensus import seq_commit as sc
from kaspa_tpu.crypto.smt import SEQ_COMMIT_ACTIVE, SparseMerkleTree

ZERO_HASH = b"\x00" * 32

PREFIX_SMT_BUILD = b"SM"
PREFIX_SMT_LANE = b"SL"


@dataclass
class SmtBuild:
    """Result of computing one chain block's sequencing state (SmtBuild of
    smt-store/src/processor.rs plus the undo data our versioning needs)."""

    seq_commit: bytes
    lanes_root: bytes
    payload_ctx_digest: bytes
    active_lanes_count: int
    shortcut_block: bytes
    updates: dict[bytes, tuple[bytes, int]]  # lane_key -> (tip, blue_score)
    expired: tuple[bytes, ...]  # lane keys removed by the inactivity window
    undo: dict[bytes, tuple[bytes, int] | None] = field(default_factory=dict)


def _encode_build(b: SmtBuild) -> bytes:
    out = [b.seq_commit, b.lanes_root, b.payload_ctx_digest,
           struct.pack("<QI I I", b.active_lanes_count, len(b.updates), len(b.expired), len(b.undo)),
           b.shortcut_block]
    for lk, (tip, bs) in sorted(b.updates.items()):
        out.append(lk + tip + struct.pack("<Q", bs))
    for lk in sorted(b.expired):
        out.append(lk)
    for lk, prev in sorted(b.undo.items()):
        if prev is None:
            out.append(lk + b"\x00")
        else:
            out.append(lk + b"\x01" + prev[0] + struct.pack("<Q", prev[1]))
    return b"".join(out)


def _decode_build(raw: bytes) -> SmtBuild:
    seq, lanes_root, pcd = raw[:32], raw[32:64], raw[64:96]
    count, n_up, n_exp, n_undo = struct.unpack_from("<QI I I", raw, 96)
    off = 96 + 20
    shortcut = raw[116:148]
    off = 148
    updates = {}
    for _ in range(n_up):
        lk = raw[off : off + 32]
        tip = raw[off + 32 : off + 64]
        (bs,) = struct.unpack_from("<Q", raw, off + 64)
        updates[lk] = (tip, bs)
        off += 72
    expired = []
    for _ in range(n_exp):
        expired.append(raw[off : off + 32])
        off += 32
    undo: dict[bytes, tuple[bytes, int] | None] = {}
    for _ in range(n_undo):
        lk = raw[off : off + 32]
        off += 32
        if raw[off] == 0:
            undo[lk] = None
            off += 1
        else:
            tip = raw[off + 1 : off + 33]
            (bs,) = struct.unpack_from("<Q", raw, off + 33)
            undo[lk] = (tip, bs)
            off += 41
    return SmtBuild(seq, lanes_root, pcd, count, shortcut, updates, tuple(expired), undo)


@dataclass
class MergesetSeqData:
    lane_activities: list  # [(lane_id20, [activity_leaf, ...])] sorted by lane_id
    miner_payload_leaves: list


def collect_mergeset_seq_data(mergeset_acceptance, headers_store) -> MergesetSeqData:
    """utxo_validation.rs:497 — per-lane activity leaves + miner payload
    leaves from the mergeset acceptance data (selected parent first).

    ``mergeset_acceptance``: [(merged_block, coinbase_payload, [accepted tx])]
    in mergeset order; accepted txs include the selected parent's coinbase.
    """
    lane_activities: dict[bytes, list[bytes]] = {}
    miner_payload_leaves = []
    global_merge_idx = 0
    for merged_block, coinbase_payload, accepted_txs in mergeset_acceptance:
        blue_work = headers_store.get(merged_block).blue_work
        miner_payload_leaves.append(sc.miner_payload_leaf(merged_block, blue_work, coinbase_payload))
        for tx in accepted_txs:
            lane_id = bytes(tx.subnetwork_id)
            al = sc.activity_leaf(tx.id(), tx.version, global_merge_idx)
            lane_activities.setdefault(lane_id, []).append(al)
            global_merge_idx += 1
    return MergesetSeqData(sorted(lane_activities.items()), miner_payload_leaves)


class LaneStateError(Exception):
    """Imported lane state fails verification against the PP header."""


def verify_lane_state(pp_header, meta: dict, lanes: list) -> None:
    """Verify a transferred pruning-point lane state against the PP header
    (kaspa-seq-commit verify.rs verify_smt_metadata + the lanes-root check
    performed by the streaming importer, flows/src/ibd/flow.rs:742-752).

    ``meta``: {lanes_root, pcd, parent_seq_commit, shortcut_block,
    inactivity_shortcut}; ``lanes``: [(lane_key32, tip32, blue_score)].

    Soundness: the PP header is proof-validated, and its
    accepted_id_merkle_root binds (parent_seq_commit, inactivity_shortcut,
    lanes_root, pcd) jointly through the seq-commit hash chain — a peer
    cannot shift wrongness between fields without a hash break.  The lanes
    themselves are bound by lanes_root via the SMT rebuild below.
    """
    tree = SparseMerkleTree(SEQ_COMMIT_ACTIVE)
    for lk, tip, bs in lanes:
        tree.insert(lk, sc.smt_leaf_hash(tip, bs))
    if tree.root() != meta["lanes_root"]:
        raise LaneStateError("transferred lanes do not hash to the claimed lanes root")
    activity_root = sc.activity_root_hash(meta["inactivity_shortcut"], meta["lanes_root"])
    state_root = sc.seq_state_root(activity_root, meta["pcd"])
    if sc.seq_commit(meta["parent_seq_commit"], state_root) != pp_header.accepted_id_merkle_root:
        raise LaneStateError(
            "lane-state metadata does not reproduce the pruning point's sequencing commitment"
        )


class ConsensusSeqCommitAccessor:
    """Live SeqCommitAccessor over consensus state (model/services/
    seq_commit_accessor.rs): what OpChainblockSeqCommit (0xd4) queries."""

    def __init__(self, selected_parent, reachability, headers_store, toccata_active_fn, threshold: int):
        self.selected_parent = selected_parent
        self.reachability = reachability
        self.headers = headers_store
        self.toccata_active = toccata_active_fn
        self.threshold = threshold

    def is_chain_ancestor_from_pov(self, block: bytes):
        if not self.headers.has(block):
            return None
        try:
            return bool(self.reachability.is_chain_ancestor_of(block, self.selected_parent))
        except KeyError:
            return None  # reachability pruned: outside the retention future

    def seq_commitment_within_depth(self, block: bytes):
        if not self.headers.has(block):
            return None
        header = self.headers.get(block)
        if not self.toccata_active(header.daa_score):
            return None
        sp_bs = self.headers.get_blue_score(self.selected_parent)
        # seq_commit_within_threshold: low + threshold > high
        if header.blue_score + self.threshold > sp_bs:
            return header.accepted_id_merkle_root
        return None


class LaneTracker:
    """Materialized KIP-21 lane state at the consensus UTXO position."""

    def __init__(self, storage, finality_depth: int, genesis_hash: bytes):
        from kaspa_tpu.consensus.stores import CachedDbAccess

        self.storage = storage
        self.finality_depth = finality_depth
        self.genesis_hash = genesis_hash
        self.tree = SparseMerkleTree(SEQ_COMMIT_ACTIVE)
        # lane_tips/score_index/tree are the active-lane working set —
        # bounded by the inactivity window, kept resident by design
        self.lane_tips: dict[bytes, tuple[bytes, int]] = {}
        self.score_index: dict[int, set[bytes]] = {}
        # per-chain-block build records: bounded read-through column (the
        # reference's smt_metadata store) — NOT a whole-history RAM map
        self.builds = CachedDbAccess(
            storage, PREFIX_SMT_BUILD, _encode_build, _decode_build, storage.policy.acceptance
        )

    # -- persistence -----------------------------------------------------

    def load(self) -> None:
        """Rebuild the materialized lane state from the SL tip snapshot —
        O(active lanes); build records stay on disk and read through."""
        if self.storage.db is None:
            return
        for lk, raw in self.storage.db.engine.items_prefix(PREFIX_SMT_LANE):
            tip, (bs,) = raw[:32], struct.unpack_from("<Q", raw, 32)
            self._set_tip(lk, (tip, bs))

    def _stage_tip(self, lk: bytes, val: tuple[bytes, int] | None) -> None:
        if self.storage.db is None:
            return
        if val is None:
            self.storage.stage(PREFIX_SMT_LANE + lk, None)
        else:
            self.storage.stage(PREFIX_SMT_LANE + lk, val[0] + struct.pack("<Q", val[1]))

    # -- materialized-state primitives ----------------------------------

    def _set_tip(self, lk: bytes, val: tuple[bytes, int]) -> None:
        prev = self.lane_tips.get(lk)
        if prev is not None:
            s = self.score_index.get(prev[1])
            if s is not None:
                s.discard(lk)
                if not s:
                    del self.score_index[prev[1]]
        self.lane_tips[lk] = val
        self.score_index.setdefault(val[1], set()).add(lk)
        self.tree.insert(lk, sc.smt_leaf_hash(val[0], val[1]))

    def _del_tip(self, lk: bytes) -> None:
        prev = self.lane_tips.pop(lk, None)
        if prev is not None:
            s = self.score_index.get(prev[1])
            if s is not None:
                s.discard(lk)
                if not s:
                    del self.score_index[prev[1]]
            self.tree.delete(lk)

    # -- compute (verification / template path) -------------------------

    def compute(
        self,
        gd,
        header_daa_score: int,
        mergeset_acceptance,
        headers_store,
        toccata_active_fn,
        selected_chain_index,
    ) -> SmtBuild:
        """recompute_seq_commit (utxo_validation.rs:634): compute the
        expected sequencing commitment for a chain block whose selected
        parent is the current materialized position.

        ``selected_chain_index(target_bs) -> bytes`` returns the highest
        selected-chain block (ancestor-or-equal of the selected parent)
        with blue_score <= target_bs, or the genesis hash.  Shortcut
        anchors always have headers locally: live history retains them, and
        proof bootstrap imports the below-PP anchor-segment headers.
        """
        sp = gd.selected_parent
        parent_header = headers_store.get(sp)
        current_bs = gd.blue_score

        # inactivity shortcut (processor.rs:790-865)
        if current_bs < self.finality_depth + 1:
            shortcut_block = self.genesis_hash
        else:
            shortcut_block = selected_chain_index(current_bs - self.finality_depth - 1)
        sc_header = headers_store.get(shortcut_block)
        inactivity_shortcut = (
            sc_header.accepted_id_merkle_root if toccata_active_fn(sc_header.daa_score) else ZERO_HASH
        )

        context_hash = sc.mergeset_context_hash(
            sc.MergesetContext(
                timestamp=parent_header.timestamp,
                daa_score=header_daa_score,
                blue_score=current_bs,
            )
        )
        parent_seq_commit = parent_header.accepted_id_merkle_root
        data = collect_mergeset_seq_data(mergeset_acceptance, headers_store)

        active_min = max(current_bs - self.finality_depth, 0)
        parent_build = self.builds.get(sp)
        parent_active = parent_build.active_lanes_count if parent_build else 0

        # expiry scan: canonical lanes whose latest touch falls below the
        # active window (SeqCommitBounds.newly_expired_range)
        parent_min = max(parent_header.blue_score - self.finality_depth, 0)
        expired = []
        undo: dict[bytes, tuple[bytes, int] | None] = {}
        for bs in [b for b in self.score_index if parent_min <= b < active_min]:
            for lk in list(self.score_index.get(bs, ())):
                expired.append(lk)

        # lane updates (utxo_validation.rs:532): a tip below the active
        # window is invisible — the lane re-activates on parent_seq_commit
        updates: dict[bytes, tuple[bytes, int]] = {}
        new_count = 0
        for lane_id, leaves in data.lane_activities:
            lk = sc.lane_key(lane_id)
            ad = sc.activity_digest_lane(leaves)
            existing = self.lane_tips.get(lk)
            if existing is not None and existing[1] < active_min:
                existing = None
            if existing is None:
                new_count += 1
                parent_ref = parent_seq_commit
            else:
                parent_ref = existing[0]
            updates[lk] = (sc.lane_tip_next(parent_ref, lk, ad, context_hash), current_bs)

        # apply to a scratch view to compute the root without committing.
        # A boundary lane both expires and re-activates in the same block:
        # it stays out of the tree ops (the update overwrites) but both
        # count operations stand and cancel (+1 new, +1 expired), matching
        # processor.rs's BTreeMap-overwrite + count arithmetic.
        expired_count = len(expired)
        touched = set(expired) | set(updates)
        for lk in touched:
            undo[lk] = self.lane_tips.get(lk)
        expired = tuple(lk for lk in expired if lk not in updates)
        # the rollback must run even if a hashing helper raises mid-scratch,
        # else the live tree diverges from lane_tips with no recovery
        try:
            for lk in expired:
                self.tree.delete(lk)
            for lk, (tip, bs) in updates.items():
                self.tree.insert(lk, sc.smt_leaf_hash(tip, bs))
            lanes_root = self.tree.root()
        finally:
            # roll the scratch mutation back; advance() re-applies on commit
            for lk in touched:
                prev = undo[lk]
                if prev is None:
                    self.tree.delete(lk)
                else:
                    self.tree.insert(lk, sc.smt_leaf_hash(prev[0], prev[1]))

        payload_root = sc.miner_payload_root(data.miner_payload_leaves)
        pcd = sc.payload_and_context_digest(context_hash, payload_root)
        activity_root = sc.activity_root_hash(inactivity_shortcut, lanes_root)
        state_root = sc.seq_state_root(activity_root, pcd)
        commit = sc.seq_commit(parent_seq_commit, state_root)

        return SmtBuild(
            seq_commit=commit,
            lanes_root=lanes_root,
            payload_ctx_digest=pcd,
            active_lanes_count=parent_active + new_count - expired_count,
            shortcut_block=shortcut_block,
            updates=updates,
            expired=expired,
            undo=undo,
        )

    # -- position movement ----------------------------------------------

    def commit(self, block: bytes, build: SmtBuild) -> None:
        """Record a verified chain block's build and advance onto it."""
        self.builds[block] = build  # CachedDbAccess stages the write-through
        self._apply(build)

    def advance(self, block: bytes) -> None:
        """Re-apply a previously recorded build (forward chain walk)."""
        build = self.builds.try_get(block)
        if build is not None:
            self._apply(build)

    def retreat(self, block: bytes) -> None:
        """Unwind a recorded build (reorg backward walk)."""
        build = self.builds.try_get(block)
        if build is not None:
            for lk, prev in build.undo.items():
                if prev is None:
                    self._del_tip(lk)
                    self._stage_tip(lk, None)
                else:
                    self._set_tip(lk, prev)
                    self._stage_tip(lk, prev)

    def _apply(self, build: SmtBuild) -> None:
        for lk in build.expired:
            self._del_tip(lk)
            self._stage_tip(lk, None)
        for lk, val in build.updates.items():
            self._set_tip(lk, val)
            self._stage_tip(lk, val)

    def prune(self, block: bytes) -> None:
        """Drop the build record of a pruned chain block."""
        self.builds.delete(block)

    # -- IBD import ------------------------------------------------------

    def import_state(self, pp: bytes, pp_header, meta: dict, lanes: list) -> None:
        """Install a verified pruning-point lane state (the receiving side
        of flows/src/ibd/flow.rs sync_new_smt_state → consensus
        import_pruning_point_smt).  Caller must have run verify_lane_state.
        """
        for lk, tip, bs in lanes:
            self._set_tip(lk, (tip, bs))
            self._stage_tip(lk, (tip, bs))
        # the PP's build record anchors parent lookups for the first
        # post-bootstrap chain block (parent_active, shortcut seeding) —
        # the role of the reference's SmtBlockMetadata row for the PP
        self.builds[pp] = SmtBuild(
            seq_commit=pp_header.accepted_id_merkle_root,
            lanes_root=meta["lanes_root"],
            payload_ctx_digest=meta["pcd"],
            active_lanes_count=len(lanes),
            shortcut_block=meta["shortcut_block"],
            updates={lk: (tip, bs) for lk, tip, bs in lanes},
            expired=(),
            undo={},
        )
