"""DAG reachability: interval labeling with dynamic reindexing.

O(1) chain queries and O(log |FCS|) DAG queries at O(n) total memory — the
algorithmic design of the reference (consensus/src/processes/reachability/:
inquirer.rs, tree.rs, reindex.rs, interval.rs), re-implemented natively over
dict state:

- Every block is a node of the *selected-parent tree* and owns an interval
  ``[start, end]`` strictly inside its parent's.  ``is_chain_ancestor_of``
  is interval containment.
- Every block keeps a *future covering set* (FCS): an interval-ordered list
  of the blocks that merged it (it was in their mergeset).
  ``is_dag_ancestor_of(a, b)`` = chain containment OR binary search of
  ``b`` among a's FCS items.
- Intervals are allocated by halving the parent's remaining capacity; on
  exhaustion a *reindex* reallocates a subtree, splitting capacity
  exponentially by subtree size (GHOSTDAG growth heuristic).  Below the
  *reindex root* (a slowly advancing chain block ``reindex_depth`` behind
  the sink), slack is reclaimed along the chain instead of reindexing the
  whole tree.

``add_block`` takes the block's ghostdag mergeset (not its DAG parents):
FCS registration per merged block is exactly what makes DAG queries
complete.  ``delete_block`` (inquirer.rs delete_block) supports the pruning
executor: child intervals are spliced into the parent so all other queries
are preserved.
"""

from __future__ import annotations

from collections import deque

ORIGIN = b"\xfe" * 32

_U64_MAX = (1 << 64) - 1

DEFAULT_REINDEX_DEPTH = 100
DEFAULT_REINDEX_SLACK = 1 << 12


class _I:
    """Interval helpers over (start, end) tuples; empty iff end == start-1."""

    @staticmethod
    def size(iv):
        return iv[1] + 1 - iv[0]

    @staticmethod
    def contains(a, b):
        return a[0] <= b[0] and b[1] <= a[1]

    @staticmethod
    def strictly_contains(a, b):
        return a[0] <= b[0] and b[1] < a[1]

    @staticmethod
    def split_half_left(iv):
        left = (_I.size(iv) + 1) // 2
        return (iv[0], iv[0] + left - 1)

    @staticmethod
    def split_exact(iv, sizes):
        assert sum(sizes) == _I.size(iv)
        out = []
        start = iv[0]
        for s in sizes:
            out.append((start, start + s - 1))
            start += s
        return out

    @staticmethod
    def split_exponential(iv, sizes):
        """Allocate each part >= sizes[i]; bias the surplus exponentially by
        subtree size (interval.rs split_exponential)."""
        total = _I.size(iv)
        ssum = sum(sizes)
        assert total >= ssum and ssum > 0
        if total == ssum:
            return _I.split_exact(iv, sizes)
        remaining = total - ssum
        total_bias = float(remaining)
        mx = max(sizes)
        fracs = [1.0 / (2.0 ** float(mx - s)) for s in sizes]
        fsum = sum(fracs)
        fracs = [f / fsum for f in fracs]
        biased = []
        for i, f in enumerate(fracs):
            bias = remaining if i == len(fracs) - 1 else min(remaining, round(total_bias * f))
            biased.append(sizes[i] + bias)
            remaining -= bias
        return _I.split_exact(iv, biased)


class ReachabilityService:
    def __init__(self, reindex_depth: int = DEFAULT_REINDEX_DEPTH, reindex_slack: int = DEFAULT_REINDEX_SLACK):
        self.reindex_depth = reindex_depth
        self.reindex_slack = reindex_slack
        self._interval: dict[bytes, tuple[int, int]] = {ORIGIN: (1, _U64_MAX - 1)}
        self._parent: dict[bytes, bytes | None] = {ORIGIN: None}
        self._children: dict[bytes, list[bytes]] = {ORIGIN: []}
        self._fcs: dict[bytes, list[bytes]] = {ORIGIN: []}
        self._height: dict[bytes, int] = {ORIGIN: 0}
        self._reindex_root: bytes = ORIGIN
        # the reachability-relations store (model/stores/relations.rs kept for
        # reachability): DAG edges, rewired on delete so the current mergeset
        # of any remaining block is recomputable (relations.rs:53-78)
        self._dag_parents: dict[bytes, list[bytes]] = {ORIGIN: []}
        self._dag_children: dict[bytes, list[bytes]] = {ORIGIN: []}
        # incremental persistence (the reference's store-backed model:
        # reachability stores are the source of truth and are never rebuilt
        # — processes/reachability/): every mutation marks the touched
        # nodes; the consensus flush stages exactly those records, so a
        # kill -9 restart decodes the column instead of rebuilding
        self._dirty: set[bytes] = {ORIGIN}
        self._deleted: set[bytes] = set()

    def _mark(self, *blocks: bytes) -> None:
        for b in blocks:
            self._dirty.add(b)
            self._deleted.discard(b)

    # ------------------------------------------------------------------
    # queries (inquirer.rs)
    # ------------------------------------------------------------------

    def has(self, block: bytes) -> bool:
        return block in self._interval

    def is_chain_ancestor_of(self, this: bytes, queried: bytes) -> bool:
        """this ∈ selected-parent chain(queried) ∪ {queried}."""
        return _I.contains(self._interval[this], self._interval[queried])

    def is_strict_chain_ancestor_of(self, this: bytes, queried: bytes) -> bool:
        return _I.strictly_contains(self._interval[this], self._interval[queried])

    def is_dag_ancestor_of(self, this: bytes, queried: bytes) -> bool:
        """queried ∈ future(this) ∪ {this}."""
        if self.is_chain_ancestor_of(this, queried):
            return True
        found, _ = self._bsearch(self._fcs[this], queried)
        return found

    def is_dag_ancestor_of_any(self, this: bytes, queried_iter) -> bool:
        return any(self.is_dag_ancestor_of(this, q) for q in queried_iter)

    def is_any_dag_ancestor_of(self, list_iter, queried: bytes) -> bool:
        return any(self.is_dag_ancestor_of(x, queried) for x in list_iter)

    def get_next_chain_ancestor(self, descendant: bytes, ancestor: bytes) -> bytes:
        """The tree child of `ancestor` on the chain of `descendant`."""
        found, i = self._bsearch(self._children[ancestor], descendant)
        assert found, "descendant not in ancestor's subtree"
        return self._children[ancestor][i]

    def forward_chain_iterator(self, from_block: bytes, to_block: bytes):
        """Chain blocks from `from_block` (exclusive) down to `to_block`."""
        cur = from_block
        while cur != to_block:
            cur = self.get_next_chain_ancestor(to_block, cur)
            yield cur

    def _bsearch(self, ordered: list[bytes], descendant: bytes):
        """Binary search an interval-ordered hash list for the item whose
        subtree contains `descendant`; returns (found, index-or-insertion)."""
        point = self._interval[descendant][1]
        lo, hi = 0, len(ordered)
        while lo < hi:
            mid = (lo + hi) // 2
            if self._interval[ordered[mid]][0] <= point:
                lo = mid + 1
            else:
                hi = mid
        # candidate is the last item whose start <= point
        if lo > 0 and self.is_chain_ancestor_of(ordered[lo - 1], descendant):
            return True, lo - 1
        return False, lo

    # ------------------------------------------------------------------
    # insertion (tree.rs add_tree_block + inquirer.rs add_dag_block)
    # ------------------------------------------------------------------

    def add_block(self, block: bytes, selected_parent: bytes, mergeset, parents=None) -> None:
        """Insert `block` under `selected_parent`; register it in the FCS of
        every mergeset block.  `mergeset` must EXCLUDE the selected parent
        (header_processor/processor.rs:393 passes
        unordered_mergeset_without_selected_parent) — tree containment covers
        the chain.  `parents` (DAG parents) feed the reachability-relations
        store that supports deletion; defaults to [selected_parent]."""
        assert block not in self._interval, "block already added"
        self._add_tree_block(block, selected_parent)
        for merged in mergeset:
            self._insert_fcs(merged, block)
        parents = list(parents) if parents is not None else [selected_parent]
        self._dag_parents[block] = parents
        self._dag_children[block] = []
        for p in parents:
            self._dag_children.setdefault(p, []).append(block)
        self._mark(block, *parents)

    def _add_tree_block(self, new: bytes, parent: bytes) -> None:
        remaining = self._remaining_after(parent)
        self._children[parent].append(new)
        self._parent[new] = parent
        self._children[new] = []
        self._fcs[new] = []
        self._height[new] = self._height[parent] + 1
        self._mark(new, parent)
        if _I.size(remaining) <= 0:
            # the empty interval at the exact end of capacity: reindex relies
            # on this position
            self._interval[new] = remaining
            self._reindex_intervals(new)
        else:
            self._interval[new] = _I.split_half_left(remaining)

    def _insert_fcs(self, merged: bytes, new: bytes) -> None:
        found, i = self._bsearch(self._fcs[merged], new)
        assert not found, "FCS inconsistency: chain relation within mergeset"
        self._fcs[merged].insert(i, new)
        self._mark(merged)

    def _children_capacity(self, block: bytes):
        iv = self._interval[block]
        return (iv[0], iv[1] - 1)  # strict containment: keep `end` exclusive

    def _remaining_before(self, block: bytes):
        cap = self._children_capacity(block)
        ch = self._children[block]
        if not ch:
            return cap
        return (cap[0], self._interval[ch[0]][0] - 1)

    def _remaining_after(self, block: bytes):
        cap = self._children_capacity(block)
        ch = self._children[block]
        if not ch:
            return cap
        return (self._interval[ch[-1]][1] + 1, cap[1])

    # ------------------------------------------------------------------
    # reindexing (reindex.rs)
    # ------------------------------------------------------------------

    def _count_subtrees(self, block: bytes, sizes: dict[bytes, int]) -> None:
        """Iterative subtree-size count rooted at `block` (BFS + push-up)."""
        if block in sizes:
            return
        queue = deque([block])  # graftlint: allow(unbounded-queue) -- local BFS work-list over the reindex subtree
        counts: dict[bytes, int] = {}
        while queue:
            current = queue.popleft()
            children = self._children[current]
            if not children:
                sizes[current] = 1
            elif current not in sizes:
                queue.extend(children)
                continue
            while current != block:
                current = self._parent[current]
                counts[current] = counts.get(current, 0) + 1
                children = self._children[current]
                if counts[current] < len(children):
                    break
                sizes[current] = sum(sizes[c] for c in children) + 1

    def _propagate_interval(self, block: bytes, sizes: dict[bytes, int]) -> None:
        self._count_subtrees(block, sizes)
        queue = deque([block])  # graftlint: allow(unbounded-queue) -- local BFS work-list over the reindex subtree
        while queue:
            current = queue.popleft()
            children = self._children[current]
            if children:
                ivs = _I.split_exponential(self._children_capacity(current), [sizes[c] for c in children])
                for c, iv in zip(children, ivs):
                    self._interval[c] = iv
                    self._mark(c)
                queue.extend(children)

    def _reindex_intervals(self, new_child: bytes) -> None:
        sizes: dict[bytes, int] = {}
        current = new_child
        while True:
            self._count_subtrees(current, sizes)
            if _I.size(self._interval[current]) >= sizes[current]:
                break
            parent = self._parent[current]
            assert parent is not None, "over 2^64 blocks?"
            assert current != self._reindex_root, "reindex root out of capacity"
            if self.is_strict_chain_ancestor_of(parent, self._reindex_root):
                # don't reindex above the root's chain: reclaim chain slack
                self._reclaim_earlier_than_root(current, parent, sizes[current], sizes)
                return
            current = parent
        self._propagate_interval(current, sizes)

    def _reclaim_earlier_than_root(
        self, allocation_block: bytes, common_ancestor: bytes, required: int, sizes: dict[bytes, int]
    ) -> None:
        chosen = self.get_next_chain_ancestor(self._reindex_root, common_ancestor)
        before = self._interval[allocation_block][0] < self._interval[chosen][0]
        slack = self.reindex_slack

        if before:
            remaining_fn, grow_alloc, shift_sibling, shrink_chain = (
                self._remaining_before,
                lambda iv, d: (iv[0], iv[1] + d),      # increase_end
                lambda iv, d: (iv[0] + d, iv[1] + d),  # increase
                lambda iv, d: (iv[0] + d, iv[1]),      # increase_start
            )
        else:
            remaining_fn, grow_alloc, shift_sibling, shrink_chain = (
                self._remaining_after,
                lambda iv, d: (iv[0] - d, iv[1]),      # decrease_start
                lambda iv, d: (iv[0] - d, iv[1] - d),  # decrease
                lambda iv, d: (iv[0], iv[1] - d),      # decrease_end
            )

        def offset_siblings(current: bytes, offset: int) -> None:
            parent = self._parent[current]
            children = self._children[parent]
            idx = children.index(current)
            siblings = reversed(children[:idx]) if before else children[idx + 1 :]
            for sib in siblings:
                if sib == allocation_block:
                    self._interval[sib] = grow_alloc(self._interval[sib], offset)
                    self._mark(sib)
                    self._propagate_interval(sib, sizes)
                    break
                self._interval[sib] = shift_sibling(self._interval[sib], offset)
                self._mark(sib)
                self._propagate_interval(sib, sizes)

        slack_sum = 0
        path_len = 0
        path_slack_alloc = 0
        current = chosen
        while True:
            if current == self._reindex_root:
                # the (practically unbounded) root: allocate fresh slack for
                # the whole traversed chain
                offset = required + slack * path_len - slack_sum
                self._interval[current] = shrink_chain(self._interval[current], offset)
                self._mark(current)
                self._propagate_interval(current, sizes)
                offset_siblings(current, offset)
                path_slack_alloc = slack
                break
            avail = _I.size(remaining_fn(current))
            slack_sum += avail
            if slack_sum >= required:
                offset = avail - (slack_sum - required)
                self._interval[current] = shrink_chain(self._interval[current], offset)
                self._mark(current)
                offset_siblings(current, offset)
                break
            current = self.get_next_chain_ancestor(self._reindex_root, current)
            path_len += 1

        # walk back down toward the common ancestor, reserving path slack
        while True:
            current = self._parent[current]
            if current == common_ancestor:
                break
            avail = _I.size(remaining_fn(current))
            offset = avail - path_slack_alloc
            self._interval[current] = shrink_chain(self._interval[current], offset)
            self._mark(current)
            offset_siblings(current, offset)

    # ------------------------------------------------------------------
    # reindex root advancement (tree.rs try_advancing_reindex_root)
    # ------------------------------------------------------------------

    def hint_virtual_selected_parent(self, hint: bytes) -> None:
        current = self._reindex_root
        ancestor, nxt = self._find_next_reindex_root(current, hint)
        if current == nxt:
            return
        while ancestor != nxt:
            child = self.get_next_chain_ancestor(nxt, ancestor)
            self._concentrate_interval(ancestor, child, child == nxt)
            ancestor = child
        self._reindex_root = nxt

    def _find_next_reindex_root(self, current: bytes, hint: bytes):
        if current == hint:
            return current, current
        ancestor = nxt = current
        hint_height = self._height[hint]
        if not self.is_chain_ancestor_of(current, hint):
            # reorg: switch chains only after a reindex_slack height gap
            cur_height = self._height[current]
            if hint_height < cur_height or hint_height - cur_height < self.reindex_slack:
                return current, current
            common = hint
            while not self.is_chain_ancestor_of(common, current):
                common = self._parent[common]
            ancestor = nxt = common
        while True:
            child = self.get_next_chain_ancestor(hint, nxt)
            child_height = self._height[child]
            assert hint_height >= child_height
            if hint_height - child_height < self.reindex_depth:
                break
            nxt = child
        return ancestor, nxt

    def _concentrate_interval(self, parent: bytes, child: bytes, is_final: bool) -> None:
        children = self._children[parent]
        idx = children.index(child)
        before, after = children[:idx], children[idx + 1 :]
        sizes: dict[bytes, int] = {}
        slack = self.reindex_slack
        piv = self._interval[parent]

        sum_before = 0
        if before:
            for c in before:
                self._count_subtrees(c, sizes)
            csizes = [sizes[c] for c in before]
            sum_before = sum(csizes)
            tight = (piv[0] + slack, piv[0] + slack + sum_before - 1)
            for c, iv in zip(before, _I.split_exact(tight, csizes)):
                self._interval[c] = iv
                self._mark(c)
                self._propagate_interval(c, sizes)

        sum_after = 0
        if after:
            for c in after:
                self._count_subtrees(c, sizes)
            csizes = [sizes[c] for c in after]
            sum_after = sum(csizes)
            tight = (piv[1] - slack - sum_after, piv[1] - slack - 1)
            for c, iv in zip(after, _I.split_exact(tight, csizes)):
                self._interval[c] = iv
                self._mark(c)
                self._propagate_interval(c, sizes)

        allocation = (piv[0] + sum_before + slack, piv[1] - sum_after - slack - 1)
        current = self._interval[child]
        if is_final and not _I.contains(allocation, current):
            # keep slack off both sides so the next advance rarely propagates
            self._interval[child] = (allocation[0] + slack, allocation[1] - slack)
            self._propagate_interval(child, sizes)
        self._interval[child] = allocation
        self._mark(child)

    # ------------------------------------------------------------------
    # deletion (inquirer.rs delete_block) — the pruning executor's hook
    # ------------------------------------------------------------------

    def _current_mergeset_wo_sp(self, selected_parent: bytes, parents) -> list[bytes]:
        """Mergeset over the CURRENT (rewired) reachability relations
        (ghostdag/mergeset.rs unordered_mergeset_without_selected_parent)."""
        queue = deque(p for p in parents if p != selected_parent)  # graftlint: allow(unbounded-queue) -- local BFS work-list, bounded by the block's anticone
        mergeset = set(queue)
        past: set[bytes] = set()
        while queue:
            current = queue.popleft()
            for parent in self._dag_parents[current]:
                if parent in mergeset or parent in past:
                    continue
                if self.is_dag_ancestor_of(parent, selected_parent):
                    past.add(parent)
                    continue
                mergeset.add(parent)
                queue.append(parent)
        return list(mergeset)

    def delete_block(self, block: bytes) -> None:
        """Remove `block` while preserving all other pairwise queries
        (inquirer.rs delete_block + relations.rs
        delete_reachability_relations).  Every FCS list currently holding
        `block` — exactly its mergeset over the rewired relations — gets it
        replaced by its tree children; DAG children inherit the needed
        grandparents."""
        interval = self._interval[block]
        parent = self._parent[block]
        children = self._children[block]  # tree children
        dag_parents = self._dag_parents[block]

        # mergeset over current relations BEFORE rewiring anything
        mergeset = self._current_mergeset_wo_sp(parent, dag_parents)

        # rewire DAG relations: each child keeps only grandparents not
        # covered by its other parents (relations.rs:63-75)
        for child in self._dag_children[block]:
            other = [p for p in self._dag_parents[child] if p != block]
            needed = [
                gp for gp in dag_parents
                if gp not in other and not self.is_dag_ancestor_of_any(gp, other)
            ]
            newp = [p for p in self._dag_parents[child] if p != block] + needed
            self._dag_parents[child] = newp
            self._mark(child)
            for gp in needed:
                self._dag_children.setdefault(gp, []).append(child)
                self._mark(gp)
        for p in dag_parents:
            ch = self._dag_children.get(p)
            if ch and block in ch:
                ch.remove(block)
                self._mark(p)

        # tree splice
        siblings = self._children[parent]
        idx = siblings.index(block)
        siblings[idx : idx + 1] = children
        self._mark(parent)
        for c in children:
            self._parent[c] = parent
            self._mark(c)

        # FCS surgery: replace `block` with its tree children
        for merged in mergeset:
            fcs = self._fcs[merged]
            found, i = self._bsearch(fcs, block)
            assert found and fcs[i] == block, "FCS inconsistency during delete"
            fcs[i : i + 1] = children
            self._mark(merged)

        if not children:
            if idx > 0:
                sib = siblings[idx - 1]
                self._interval[sib] = (self._interval[sib][0], interval[1])
                self._mark(sib)
        elif len(children) == 1:
            self._interval[children[0]] = interval
        else:
            first, last = children[0], children[-1]
            self._interval[first] = (interval[0], self._interval[first][1])
            self._interval[last] = (self._interval[last][0], interval[1])

        if self._reindex_root == block:
            self._reindex_root = parent
        del self._interval[block], self._parent[block], self._children[block], self._fcs[block], self._height[block]
        del self._dag_parents[block], self._dag_children[block]
        self._dirty.discard(block)
        self._deleted.add(block)

    def validate_intervals(self, root: bytes = ORIGIN) -> None:
        """Debug invariant check (reachability/tests/mod.rs
        validate_intervals): every tree block's children hold disjoint,
        ascending intervals strictly contained in the parent's allocation,
        and each FCS list is interval-sorted.  Raises AssertionError."""
        stack = [root]
        while stack:
            parent = stack.pop()
            p_iv = self._interval[parent]
            assert p_iv[0] <= p_iv[1] + 1, f"malformed interval {p_iv}"
            prev_end = p_iv[0] - 1
            for child in self._children[parent]:
                c_iv = self._interval[child]
                assert c_iv[0] > prev_end, f"overlap/disorder under {parent.hex()}"
                # strict: the parent's last slot is reserved (_children_capacity
                # keeps `end` exclusive) so parent/child intervals never tie
                assert c_iv[1] < p_iv[1], f"child {child.hex()} escapes parent allocation"
                prev_end = c_iv[1]
                stack.append(child)
            fcs = self._fcs[parent]
            starts = [self._interval[b][0] for b in fcs]
            assert starts == sorted(starts), f"FCS of {parent.hex()} not interval-sorted"
