"""DAG reachability service.

The reference achieves O(1) `is_dag_ancestor_of` through interval labeling
of the selected-parent tree plus future-covering sets with dynamic
reindexing (consensus/src/processes/reachability/, 1.6k LoC).  This module
provides the same service interface with an interned-bitset backend:
each block's past is one python int used as a bitmask over dense block
indices — O(1) amortised queries, O(n/64 words) per insertion, exact for
any DAG topology.  It is the correctness-first backend sized for
simulation/test scale; the interval-tree backend is the planned upgrade for
unbounded chains (tracked for a later round).

Semantics mirror reachability/inquirer.rs:
- is_dag_ancestor_of(a, b): a ∈ past(b) ∪ {b}
- is_chain_ancestor_of(a, b): a on the selected-parent chain of b (incl. b)
"""

from __future__ import annotations

ORIGIN = b"\xfe" * 32


class ReachabilityService:
    def __init__(self):
        self._idx: dict[bytes, int] = {}
        self._past: list[int] = []  # bitmask over indices
        self._chain: list[int] = []  # bitmask over selected-parent chain
        self._bit: list[int] = []
        # ORIGIN is the virtual genesis: every block is in its future
        self._add(ORIGIN, [], ORIGIN)

    def _add(self, block: bytes, parents: list[bytes], selected_parent: bytes | None):
        assert block not in self._idx, "block already added"
        i = len(self._past)
        self._idx[block] = i
        bit = 1 << i
        self._bit.append(bit)
        past = 0
        for p in parents:
            pi = self._idx[p]
            past |= self._past[pi] | self._bit[pi]
        self._past.append(past)
        if selected_parent is None or selected_parent == block:
            self._chain.append(bit)
        else:
            si = self._idx[selected_parent]
            self._chain.append(self._chain[si] | bit)

    def add_block(self, block: bytes, parents: list[bytes], selected_parent: bytes) -> None:
        """Insert a block; parents must already be present."""
        self._add(block, parents, selected_parent)

    def has(self, block: bytes) -> bool:
        return block in self._idx

    def is_dag_ancestor_of(self, this: bytes, queried: bytes) -> bool:
        if this == queried:
            return True
        ti = self._idx[this]
        return bool(self._past[self._idx[queried]] & self._bit[ti])

    def is_dag_ancestor_of_any(self, this: bytes, queried_iter) -> bool:
        return any(self.is_dag_ancestor_of(this, q) for q in queried_iter)

    def is_any_dag_ancestor_of(self, list_iter, queried: bytes) -> bool:
        return any(self.is_dag_ancestor_of(x, queried) for x in list_iter)

    def is_chain_ancestor_of(self, this: bytes, queried: bytes) -> bool:
        """this ∈ selected-parent chain(queried) (inclusive)."""
        ti = self._idx[this]
        return bool(self._chain[self._idx[queried]] & self._bit[ti])
