"""Lane-based sequencing commitments (KIP-21 seq-commit).

Reference: consensus/seq-commit/src/{hashing,types,verify}.rs.  The
commitment tree:

    SeqCommit(B) = H_seq(parent_seq_commit || state_root)
    state_root   = H_seq(activity_root || payload_and_ctx_digest)
    activity_root = H_activity_root(inactivity_shortcut || lanes_root)
    lanes_root   = SMT root over active lanes (crypto/smt.py,
                   SeqCommitActiveNode/CollapsedNode domains)
    payload_and_ctx_digest = H_seq(context_hash || payload_root)

All hashers are keyed BLAKE3 with zero-padded domain keys
(crypto/hashes/src/hashers.rs blake3_hasher! block); golden vectors from
the reference's own unit tests pin the exact bytes.
"""

from __future__ import annotations

from dataclasses import dataclass

import functools

from kaspa_tpu.crypto.blake3 import Blake3Keyed, keyed_hash
from kaspa_tpu.crypto.merkle import calc_merkle_root
from kaspa_tpu.crypto.smt import SEQ_COMMIT_ACTIVE, SmtProof, SparseMerkleTree

ZERO_HASH = b"\x00" * 32

_D = {
    "merkle": b"SeqCommitmentMerkleBranchHash",
    "payload": b"PayloadDigest",
    "lane_key": b"SeqCommitLaneKey",
    "lane_tip": b"SeqCommitLaneTip",
    "activity_leaf": b"SeqCommitActivityLeaf",
    "mergeset_context": b"SeqCommitMergesetContext",
    "miner_payload_leaf": b"SeqCommitMinerPayloadLeaf",
    "activity_root": b"SeqCommitActivityRoot",
    "active_leaf": b"SeqCommitActiveLeaf",
}


def _h(domain: str, data: bytes) -> bytes:
    return keyed_hash(_D[domain], data)


# Blake3 H_seq as a merkle hasher_factory (Blake3Keyed has the same
# incremental update()/digest() interface the merkle builder expects)
_SeqMerkleHasher = functools.partial(Blake3Keyed, _D["merkle"])


def lane_key(lane_id: bytes) -> bytes:
    """H_lane_key(lane_id) — lane_id is the 20-byte subnetwork id."""
    assert len(lane_id) == 20
    return _h("lane_key", lane_id)


def activity_leaf(tx_id: bytes, version: int, merge_idx: int) -> bytes:
    return _h("activity_leaf", tx_id + version.to_bytes(2, "little") + merge_idx.to_bytes(4, "little"))


def activity_digest_lane(leaves: list) -> bytes:
    """Merkle root over activity leaves with H_seq; single leaf = itself."""
    return calc_merkle_root(list(leaves), hasher_factory=_SeqMerkleHasher)


def lane_tip_next(parent_ref: bytes, lane_key_: bytes, activity_digest: bytes, context_hash: bytes) -> bytes:
    return _h("lane_tip", parent_ref + lane_key_ + activity_digest + context_hash)


@dataclass(frozen=True)
class MergesetContext:
    timestamp: int
    daa_score: int
    blue_score: int


def mergeset_context_hash(ctx: MergesetContext) -> bytes:
    return _h(
        "mergeset_context",
        ctx.timestamp.to_bytes(8, "little")
        + ctx.daa_score.to_bytes(8, "little")
        + ctx.blue_score.to_bytes(8, "little"),
    )


def activity_root_hash(inactivity_shortcut: bytes, lanes_root: bytes) -> bytes:
    return _h("activity_root", inactivity_shortcut + lanes_root)


def miner_payload_hash(payload: bytes) -> bytes:
    return _h("payload", payload)


def miner_payload_leaf(block_hash: bytes, blue_work: int, payload: bytes) -> bytes:
    """H_miner_payload_leaf(block_hash || blue_work || H_payload(payload));
    blue_work encoded as write_blue_work: le_u64(len) || stripped BE bytes."""
    stripped = blue_work.to_bytes((blue_work.bit_length() + 7) // 8, "big") if blue_work else b""
    return _h(
        "miner_payload_leaf",
        block_hash + len(stripped).to_bytes(8, "little") + stripped + miner_payload_hash(payload),
    )


def miner_payload_root(leaves: list) -> bytes:
    return calc_merkle_root(list(leaves), hasher_factory=_SeqMerkleHasher)


def smt_leaf_hash(lane_tip: bytes, blue_score: int) -> bytes:
    """H_active_leaf(lane_tip || le_u64(blue_score)) — lane_key is omitted
    because both the SMT key path and the lane tip already commit to it."""
    return _h("active_leaf", lane_tip + blue_score.to_bytes(8, "little"))


def payload_and_context_digest(context_hash: bytes, payload_root: bytes) -> bytes:
    return _h("merkle", context_hash + payload_root)


def seq_state_root(activity_root: bytes, payload_and_ctx_digest: bytes) -> bytes:
    return _h("merkle", activity_root + payload_and_ctx_digest)


def seq_commit(parent_seq_commit: bytes, state_root: bytes) -> bytes:
    return _h("merkle", parent_seq_commit + state_root)


COINBASE_LANE_KEY = lane_key(b"\x01" + b"\x00" * 19)


# ----------------------------------------------------------------------
# lane state tracking + IBD verification (verify.rs + smt-store role)
# ----------------------------------------------------------------------


class SmtVerifyError(Exception):
    pass


@dataclass
class SmtMetadata:
    lanes_root: bytes
    payload_and_ctx_digest: bytes
    parent_seq_commit: bytes


def verify_smt_metadata(
    metadata: SmtMetadata,
    inactivity_shortcut: bytes,
    expected_seq_commit: bytes,
    expected_parent_seq_commit: bytes,
) -> None:
    """verify.rs:38 — check IBD-transferred lane metadata against the
    header's sequencing commitment before accepting any lane entries."""
    if metadata.parent_seq_commit != expected_parent_seq_commit:
        raise SmtVerifyError(
            f"parent_seq_commit mismatch: expected {expected_parent_seq_commit.hex()}, got {metadata.parent_seq_commit.hex()}"
        )
    activity_root = activity_root_hash(inactivity_shortcut, metadata.lanes_root)
    state_root = seq_state_root(activity_root, metadata.payload_and_ctx_digest)
    computed = seq_commit(metadata.parent_seq_commit, state_root)
    if computed != expected_seq_commit:
        raise SmtVerifyError(
            f"seq_commit mismatch: expected {expected_seq_commit.hex()}, computed {computed.hex()}"
        )


class LaneState:
    """Versioned active-lane tracking — the role of consensus/smt-store:
    the current SMT over active lanes plus per-chain-block version history
    so reorgs roll lanes back to the fork point (lane_version_store.rs /
    reverse_blue_score.rs semantics, in-memory)."""

    def __init__(self):
        self.tree = SparseMerkleTree(SEQ_COMMIT_ACTIVE)
        self.lane_tips: dict[bytes, tuple[bytes, int]] = {}  # lane_key -> (tip, blue_score)
        self._versions: list[tuple[bytes, dict]] = []  # (chain block, {lane_key: prev or None})

    def advance(self, chain_block: bytes, updates: dict[bytes, tuple[bytes, int]]) -> bytes:
        """Apply lane-tip updates for one chain block; returns the new
        lanes root.  ``updates``: lane_key -> (lane_tip, blue_score)."""
        undo: dict[bytes, tuple | None] = {}
        for lk, (tip, blue_score) in updates.items():
            undo[lk] = self.lane_tips.get(lk)
            self.lane_tips[lk] = (tip, blue_score)
            self.tree.insert(lk, smt_leaf_hash(tip, blue_score))
        self._versions.append((chain_block, undo))
        return self.tree.root()

    def rollback(self, to_chain_block: bytes | None) -> bytes:
        """Unwind versions until the top of history is `to_chain_block`
        (None = genesis state); returns the restored lanes root.  An
        unknown target raises rather than silently wiping lane state."""
        if to_chain_block is not None and all(b != to_chain_block for b, _ in self._versions):
            raise SmtVerifyError(f"rollback target {to_chain_block.hex()} not in lane version history")
        while self._versions and (to_chain_block is None or self._versions[-1][0] != to_chain_block):
            _, undo = self._versions.pop()
            for lk, prev in undo.items():
                if prev is None:
                    self.lane_tips.pop(lk, None)
                    self.tree.delete(lk)
                else:
                    self.lane_tips[lk] = prev
                    self.tree.insert(lk, smt_leaf_hash(prev[0], prev[1]))
        return self.tree.root()

    def lanes_root(self) -> bytes:
        return self.tree.root()

    def prove_lane(self, lane_key_: bytes) -> SmtProof:
        return self.tree.prove(lane_key_)


class SeqCommitAccessor:
    """What OpChainblockSeqCommit (0xd4) queries (crypto/txscript/src/
    seq_commit_accessor.rs): resolves a chain block's sequencing commitment
    from the PoV of the validating context.  Wired into the engine only
    when KIP-21 is consensus-active; its absence keeps the opcode invalid.

    ``commitments``: chain block -> seq commit; ``chain_blocks``: the
    selected chain from the PoV, most recent last; ``max_depth``: how far
    back commitments may be requested."""

    def __init__(self, commitments: dict, chain_blocks: list, max_depth: int):
        self._commitments = commitments
        self._chain_index = {b: i for i, b in enumerate(chain_blocks)}
        self._tip_index = len(chain_blocks) - 1
        self._max_depth = max_depth

    def is_chain_ancestor_from_pov(self, block: bytes):
        if block not in self._commitments and block not in self._chain_index:
            return None  # unknown/pruned
        return block in self._chain_index

    def seq_commitment_within_depth(self, block: bytes):
        idx = self._chain_index.get(block)
        if idx is None or self._tip_index - idx > self._max_depth:
            return None
        return self._commitments.get(block)
