"""Processing counters + monitor (observability).

Reference: consensus/core/src/api/counters.rs (ProcessingCounters atomics)
and consensus/src/pipeline/monitor.rs (ConsensusMonitor logging rolling
block/header/tx throughput).  Python ints under the GIL stand in for the
atomics.  Snapshots are surfaced through RpcCoreService.get_metrics
(process_counters field); ConsensusMonitor turns snapshot deltas into
rolling rates for operator logging.
"""

from __future__ import annotations

import time
from dataclasses import asdict, dataclass, field


@dataclass
class ProcessingCountersSnapshot:
    blocks_submitted: int = 0
    header_counts: int = 0
    body_counts: int = 0
    txs_counts: int = 0
    chain_block_counts: int = 0
    chain_disqualified_counts: int = 0
    mass_counts: int = 0
    dep_counts: int = 0

    def __sub__(self, other: "ProcessingCountersSnapshot") -> "ProcessingCountersSnapshot":
        return ProcessingCountersSnapshot(
            **{k: v - getattr(other, k) for k, v in asdict(self).items()}
        )


class ProcessingCounters:
    def __init__(self):
        self._s = ProcessingCountersSnapshot()

    def inc_blocks_submitted(self, n=1):
        self._s.blocks_submitted += n

    def inc_headers(self, n=1):
        self._s.header_counts += n

    def inc_bodies(self, n=1):
        self._s.body_counts += n

    def inc_txs(self, n=1):
        self._s.txs_counts += n

    def inc_chain_blocks(self, n=1):
        self._s.chain_block_counts += n

    def inc_chain_disqualified(self, n=1):
        self._s.chain_disqualified_counts += n

    def snapshot(self) -> ProcessingCountersSnapshot:
        return ProcessingCountersSnapshot(**asdict(self._s))


class ConsensusMonitor:
    """Rolling throughput from counter deltas (pipeline/monitor.rs)."""

    def __init__(self, counters: ProcessingCounters):
        self.counters = counters
        self._last = counters.snapshot()
        self._last_time = time.monotonic()

    def tick(self) -> dict:
        now = time.monotonic()
        snapshot = self.counters.snapshot()
        delta = snapshot - self._last
        elapsed = max(now - self._last_time, 1e-9)
        self._last, self._last_time = snapshot, now
        return {
            "blocks_per_sec": delta.blocks_submitted / elapsed,
            "headers_per_sec": delta.header_counts / elapsed,
            "txs_per_sec": delta.txs_counts / elapsed,
            "chain_blocks_per_sec": delta.chain_block_counts / elapsed,
            "disqualified": delta.chain_disqualified_counts,
            "window_secs": elapsed,
        }
