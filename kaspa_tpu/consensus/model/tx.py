"""Transaction model (reference: consensus/core/src/tx.rs, subnets.rs).

Hashes and ids are 32-byte ``bytes``; scripts/payloads are ``bytes``;
amounts/scores are python ints (u64 range).  ``Transaction.storage_mass`` is
the miner-committed storage mass (KIP-9), hashed into tx::hash but never
into tx::id (tx.rs design notes in hashing/tx.rs:70-90).
"""

from __future__ import annotations

from dataclasses import dataclass, field

SUBNETWORK_ID_SIZE = 20


def subnetwork_from_byte(b: int) -> bytes:
    return bytes([b]) + b"\x00" * (SUBNETWORK_ID_SIZE - 1)


SUBNETWORK_ID_NATIVE = subnetwork_from_byte(0)
SUBNETWORK_ID_COINBASE = subnetwork_from_byte(1)
SUBNETWORK_ID_REGISTRY = subnetwork_from_byte(2)


def subnetwork_is_builtin(sid: bytes) -> bool:
    return sid in (SUBNETWORK_ID_COINBASE, SUBNETWORK_ID_REGISTRY)


def subnetwork_is_native(sid: bytes) -> bool:
    return sid == SUBNETWORK_ID_NATIVE


@dataclass(frozen=True)
class TransactionOutpoint:
    transaction_id: bytes  # 32
    index: int  # u32


@dataclass(frozen=True)
class ComputeCommit:
    """v0 carries a sig-op count (u8); v1 carries a compute budget (u16).

    Reference: consensus/core/src/tx.rs:71-97 (ComputeCommit enum).
    """

    kind: str  # "sigops" | "budget"
    value: int

    @staticmethod
    def sigops(n: int) -> "ComputeCommit":
        return ComputeCommit("sigops", n)

    @staticmethod
    def budget(n: int) -> "ComputeCommit":
        return ComputeCommit("budget", n)

    def sig_op_count(self):
        return self.value if self.kind == "sigops" else None

    def compute_budget(self):
        return self.value if self.kind == "budget" else None

    @staticmethod
    def version_expects_compute_budget_field(version: int) -> bool:
        return version >= 1

    @staticmethod
    def version_expects_sig_op_count_field(version: int) -> bool:
        return version < 1


@dataclass
class TransactionInput:
    previous_outpoint: TransactionOutpoint
    signature_script: bytes
    sequence: int  # u64
    compute_commit: ComputeCommit

    @staticmethod
    def new(outpoint: TransactionOutpoint, signature_script: bytes, sequence: int, sig_op_count: int):
        return TransactionInput(outpoint, signature_script, sequence, ComputeCommit.sigops(sig_op_count))


@dataclass(frozen=True)
class ScriptPublicKey:
    version: int  # u16
    script: bytes


@dataclass(frozen=True)
class Covenant:
    authorizing_input: int  # u16
    covenant_id: bytes  # 32


@dataclass
class TransactionOutput:
    value: int  # u64 sompi
    script_public_key: ScriptPublicKey
    covenant: Covenant | None = None


@dataclass
class Transaction:
    version: int  # u16
    inputs: list[TransactionInput]
    outputs: list[TransactionOutput]
    lock_time: int  # u64
    subnetwork_id: bytes  # 20
    gas: int  # u64
    payload: bytes
    storage_mass: int = 0  # committed storage mass (tx.rs:264)
    _id_cache: bytes | None = field(default=None, repr=False, compare=False)

    def id(self) -> bytes:
        if self._id_cache is None:
            from kaspa_tpu.consensus import hashing as chash

            self._id_cache = chash.tx_id(self)
        return self._id_cache

    def is_coinbase(self) -> bool:
        return self.subnetwork_id == SUBNETWORK_ID_COINBASE


@dataclass(frozen=True)
class UtxoEntry:
    """Reference: consensus/core/src/tx.rs UtxoEntry."""

    amount: int  # u64
    script_public_key: ScriptPublicKey
    block_daa_score: int
    is_coinbase: bool
    covenant_id: bytes | None = None
