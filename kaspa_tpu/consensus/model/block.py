"""Block = header + transactions (reference: consensus/core/src/block.rs)."""

from __future__ import annotations

from dataclasses import dataclass, field

from kaspa_tpu.consensus.model.header import Header
from kaspa_tpu.consensus.model.tx import Transaction


@dataclass
class Block:
    header: Header
    transactions: list[Transaction] = field(default_factory=list)

    @property
    def hash(self) -> bytes:
        return self.header.hash

    def is_header_only(self) -> bool:
        return not self.transactions

    @staticmethod
    def from_header(header: Header) -> "Block":
        return Block(header, [])
