"""Consensus data model: transactions, headers, UTXO entries.

TPU-native re-design of the reference's consensus/core data model
(consensus/core/src/tx.rs:50-450, header.rs:137-153).  Host-side objects are
plain python dataclasses (the framework's Array-of-Structs boundary); device
batching converts them into Structure-of-Arrays int32 tensors at the FFI
edge (see kaspa_tpu/crypto/secp.py and ops/).
"""

from kaspa_tpu.consensus.model.tx import (  # noqa: F401
    SUBNETWORK_ID_COINBASE,
    SUBNETWORK_ID_NATIVE,
    SUBNETWORK_ID_REGISTRY,
    SUBNETWORK_ID_SIZE,
    ComputeCommit,
    Covenant,
    ScriptPublicKey,
    Transaction,
    TransactionInput,
    TransactionOutpoint,
    TransactionOutput,
    UtxoEntry,
    subnetwork_from_byte,
)
from kaspa_tpu.consensus.model.header import Header  # noqa: F401
