"""Block header model (reference: consensus/core/src/header.rs:137-153).

``parents_by_level`` is stored expanded (list of levels, each a list of
32-byte hashes); the run-length-compressed wire form (CompressedParents,
header.rs:19) belongs to the P2P codec layer.  ``blue_work`` is an int
(Uint192 range).  The cached ``hash`` is computed lazily.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Header:
    version: int  # u16
    parents_by_level: list[list[bytes]]
    hash_merkle_root: bytes
    accepted_id_merkle_root: bytes
    utxo_commitment: bytes
    timestamp: int  # u64 milliseconds
    bits: int  # u32 compact difficulty target
    nonce: int  # u64
    daa_score: int  # u64
    blue_work: int  # Uint192
    blue_score: int  # u64
    pruning_point: bytes
    _hash_cache: bytes | None = field(default=None, repr=False, compare=False)

    @property
    def hash(self) -> bytes:
        if self._hash_cache is None:
            from kaspa_tpu.consensus import hashing as chash

            self._hash_cache = chash.header_hash(self)
        return self._hash_cache

    def direct_parents(self) -> list[bytes]:
        return self.parents_by_level[0] if self.parents_by_level else []

    def invalidate_cache(self) -> None:
        self._hash_cache = None
