"""Real network presets: mainnet / testnet / simnet / devnet.

Genesis constants mirrored from consensus/core/src/config/genesis.rs
(network data, not code); parameter presets follow config/params.rs with
the Bps<10> generator for post-Crescendo mainnet.  test_networks.py proves
our header/merkle hashing reproduces each network's real genesis hash from
these raw constants.
"""

from kaspa_tpu.consensus.model import SUBNETWORK_ID_COINBASE, Header, Transaction
from kaspa_tpu.consensus.model.block import Block
from kaspa_tpu.consensus.params import GenesisBlock, Params

GENESIS_DATA = {
 "mainnet": {
  "hash": "58c2d4199e21f910d1571d114969cecef48f09f934d42ccb6a281a15868f2999",
  "version": 0,
  "hash_merkle_root": "8ec898568c6801d13df4ee6e2a1b54b7e6236f671f20954f05306410518eeb32",
  "utxo_commitment": "710f27df423e63aa6cdb72b89ea5a06cffa399d66f167704455b5af59def8e20",
  "timestamp": 1637609671037,
  "bits": 486722099,
  "nonce": 211244,
  "daa_score": 1312860,
  "payload": "000000000000000000e1f5050000000000000100d795d79ed79420d793d79920d7a2d79cd799d79a20d795d7a2d79c20d790d797d799d79a20d799d799d798d79120d791d7a9d790d7a820d79bd7a1d7a4d79020d795d793d794d791d79420d79cd79ed7a2d791d79320d79bd7a8d7a2d795d7aa20d790d79cd794d79bd79d20d7aad7a2d791d793d795d79f0000000000000000000b1f8e1c17b0133d439174e52efbb0c41c3583a8aa66b00fca37ca667c2d550a6c4416dad9717e50927128c424fa4edbebc436ab13aeef"
 },
 "testnet": {
  "hash": "f896a3034873be1739fc4359236899fd3d65d2bc94f9780df0d0da3eb1cc4370",
  "version": 0,
  "hash_merkle_root": "17341408a5724556504df4d6cf515cbfbb220430dc451c743c22d5e911720c2a",
  "utxo_commitment": "544eb3142c000f0ad2c76ac41f4222abbababed830eeafee4b6dc56b52d5cac0",
  "timestamp": 1633687894966,
  "bits": 511705087,
  "nonce": 83330,
  "daa_score": 0,
  "payload": "000000000000000000e1f50500000000000001006b617370612d746573746e6574"
 },
 "simnet": {
  "hash": "411f8cd26f3d41aea39e78573927da24d23995705b579f30959b9127e96b79e3",
  "version": 0,
  "hash_merkle_root": "1946d629f7e922a7bced59190521c3771f73d352ddbbb686564ad7fd56857c1b",
  "utxo_commitment": "544eb3142c000f0ad2c76ac41f4222abbababed830eeafee4b6dc56b52d5cac0",
  "timestamp": 1633687894966,
  "bits": 545259519,
  "nonce": 2,
  "daa_score": 0,
  "payload": "000000000000000000e1f50500000000000001006b617370612d73696d6e6574"
 },
 "devnet": {
  "hash": "4cb48d0b2073b802360145a15ad1abdc01d89b5c2fe4722630ab9b5fe9dfc4f2",
  "version": 0,
  "hash_merkle_root": "58abf20321d70716162b6bf8d9f589ca33ae6e32b3b19abb7fa65d1141a3f94d",
  "utxo_commitment": "544eb3142c000f0ad2c76ac41f4222abbababed830eeafee4b6dc56b52d5cac0",
  "timestamp": 1231006505000,
  "bits": 505527324,
  "nonce": 298590,
  "daa_score": 0,
  "payload": "000000000000000000e1f50500000000000001006b617370612d6465766e6574"
 }
}


def _genesis_block(net: str) -> Block:
    g = GENESIS_DATA[net]
    header = Header(
        version=g["version"],
        parents_by_level=[],
        hash_merkle_root=bytes.fromhex(g["hash_merkle_root"]),
        accepted_id_merkle_root=b"\x00" * 32,
        utxo_commitment=bytes.fromhex(g["utxo_commitment"]),
        timestamp=g["timestamp"],
        bits=g["bits"],
        nonce=g["nonce"],
        daa_score=g["daa_score"],
        blue_work=0,
        blue_score=0,
        pruning_point=b"\x00" * 32,
    )
    coinbase = Transaction(0, [], [], 0, SUBNETWORK_ID_COINBASE, 0, bytes.fromhex(g["payload"]))
    return Block(header, [coinbase])


_DEFLATIONARY_PHASE_DAA_SCORE = 15778800 - 259200  # params.rs: ~6 months minus pre-mainnet period


def _network_params(net: str, bps: int, prefix_name: str, **overrides) -> Params:
    block = _genesis_block(net)
    g = GENESIS_DATA[net]
    params = Params.from_bps(
        prefix_name,
        bps,
        GenesisBlock(
            hash=bytes.fromhex(g["hash"]),
            bits=g["bits"],
            timestamp=g["timestamp"],
            version=g["version"],
            daa_score=g["daa_score"],
        ),
        genesis_override=block,
        **overrides,
    )
    return params


def mainnet_params() -> Params:
    """Post-Crescendo mainnet (10 BPS, Bps<10> generated constants)."""
    return _network_params(
        "mainnet", 10, "kaspa-mainnet",
        deflationary_phase_daa_score=_DEFLATIONARY_PHASE_DAA_SCORE,
        pre_deflationary_phase_base_subsidy=50_000_000_000,
        # roughly 2026-06-30 16:15 UTC (params.rs:724)
        toccata_activation=474_165_565,
    )


def testnet_params() -> Params:
    return _network_params(
        "testnet", 10, "kaspa-testnet",
        deflationary_phase_daa_score=_DEFLATIONARY_PHASE_DAA_SCORE,
        toccata_activation=467_579_632,  # params.rs:785
    )


def simnet_network_params() -> Params:
    # simnet activates Toccata from genesis (params.rs:830 ForkActivation::always)
    return _network_params("simnet", 10, "kaspa-simnet", skip_proof_of_work=True, toccata_activation=0)


def devnet_params() -> Params:
    return _network_params("devnet", 10, "kaspa-devnet")
