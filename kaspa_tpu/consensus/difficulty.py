"""Difficulty primitives: compact target codec and work calculation.

Reference: math/src/lib.rs:61-97 (compact bits codec),
consensus/src/processes/difficulty.rs:211-232 (calc_work / level_work).
Python ints stand in for Uint256/Uint192 (exact, unbounded).
"""

from __future__ import annotations

U256 = 1 << 256
MAX_WORK_LEVEL = 192  # difficulty.rs MAX_WORK_LEVEL (Uint192 blue work)


def compact_to_target(bits: int) -> int:
    """Uint256::from_compact_target_bits."""
    unshifted_expt = bits >> 24
    if unshifted_expt <= 3:
        mant = (bits & 0xFFFFFF) >> (8 * (3 - unshifted_expt))
        expt = 0
    else:
        mant = bits & 0xFFFFFF
        expt = 8 * (unshifted_expt - 3)
    if mant > 0x7FFFFF:
        return 0  # "mantissa is signed but may not be negative"
    return (mant << expt) % U256


def target_to_compact(target: int) -> int:
    """Uint256::compact_target_bits."""
    size = (target.bit_length() + 7) // 8
    if size <= 3:
        compact = (target << (8 * (3 - size))) & 0xFFFFFFFF
    else:
        compact = (target >> (8 * (size - 3))) & 0xFFFFFFFF
    if compact & 0x00800000:
        compact >>= 8
        size += 1
    return compact | (size << 24)


def calc_work(bits: int) -> int:
    """Work = 2**256 // (target+1), computed as in chain.cpp / difficulty.rs."""
    target = compact_to_target(bits)
    res = ((U256 - 1 - target) // (target + 1)) + 1
    assert res < (1 << 192), "Work should not exceed 2**192"
    return res


def level_work(level: int, max_block_level: int) -> int:
    """Lower-bound work per block at a given proof level (difficulty.rs:223)."""
    if level == 0:
        return 0
    exp = level + 256 - max_block_level
    return 1 << min(exp, MAX_WORK_LEVEL)
