"""Three-dimensional transaction mass (KIP-9): compute / transient / storage.

Reference: consensus/core/src/mass/{mod.rs,units.rs}.  Storage mass is the
harmonic/arithmetic plurality-generalized formula; compute mass combines
serialized size, script-pubkey bytes and sigop/compute-budget grams;
transient mass scales serialized size.  Block limits normalize all
dimensions to the compute scale via cofactors.
"""

from __future__ import annotations

from dataclasses import dataclass

from kaspa_tpu.consensus.model import Transaction

HASH_SIZE = 32
SUBNETWORK_ID_SIZE = 20
TRANSIENT_BYTE_TO_MASS_FACTOR = 4  # constants.rs:36
SOMPI_PER_KASPA = 100_000_000
STORAGE_MASS_PARAMETER = SOMPI_PER_KASPA * 10_000  # constants.rs:31 (= 10^12)
GRAMS_PER_COMPUTE_BUDGET_UNIT = 100  # units.rs:4
GRAMS_PER_SIGOP_COUNT_UNIT = 1000  # units.rs:12 scale (grams per sigop)

UTXO_CONST_STORAGE = 32 + 4 + 8 + 8 + 1 + 2 + 8  # mass/mod.rs utxo_plurality
UTXO_UNIT_SIZE = 100


def transaction_estimated_serialized_size(tx: Transaction) -> int:
    size = 2 + 8  # version + input count
    for inp in tx.inputs:
        size += HASH_SIZE + 4  # outpoint
        size += 8 + len(inp.signature_script)
        size += 8  # sequence
        if tx.version >= 1:
            size += 2  # compute budget
    size += 8  # output count
    for out in tx.outputs:
        size += 8 + 2 + 8 + len(out.script_public_key.script)
        if out.covenant is not None:
            size += 2 + HASH_SIZE
    size += 8 + SUBNETWORK_ID_SIZE + 8 + HASH_SIZE  # lock time, subnet, gas, payload hash
    size += 8 + len(tx.payload)
    return size


def utxo_plurality(spk, has_covenant: bool) -> int:
    total = UTXO_CONST_STORAGE + len(spk.script) + (HASH_SIZE if has_covenant else 0)
    return -(-total // UTXO_UNIT_SIZE)


def _cell_of_entry(entry):
    return (utxo_plurality(entry.script_public_key, entry.covenant_id is not None), entry.amount)


def _cell_of_output(out):
    return (utxo_plurality(out.script_public_key, out.covenant is not None), out.value)


def calc_storage_mass(is_coinbase: bool, input_cells: list, output_cells: list, storm_param: int) -> int | None:
    """KIP-9: max(0, C·(|O|/H(O) − |I|/A(I))), relaxed harmonic path when
    |O| = 1 or |I| = 1 or |O| = |I| = 2 (plurality-generalized)."""
    if is_coinbase:
        return 0
    outs_plurality = 0
    harmonic_outs = 0
    for plurality, amount in output_cells:
        outs_plurality += plurality
        term = storm_param * plurality * plurality
        if term >= (1 << 64):  # mirrors checked_mul overflow -> incomputable
            return None
        harmonic_outs += term // amount
        if harmonic_outs >= (1 << 64):
            return None

    if outs_plurality == 1:
        relaxed = True
    elif len(input_cells) > 2:
        relaxed = False
    else:
        ins_plurality = sum(p for p, _ in input_cells)
        relaxed = ins_plurality == 1 or (outs_plurality == 2 and ins_plurality == 2)

    if relaxed:
        harmonic_ins = 0
        for plurality, amount in input_cells:
            harmonic_ins = min(harmonic_ins + storm_param * plurality * plurality // amount, (1 << 64) - 1)
        return max(0, harmonic_outs - harmonic_ins)

    ins_plurality = sum(p for p, _ in input_cells)
    sum_ins = sum(a for _, a in input_cells)
    mean_ins = max(sum_ins // ins_plurality, 1)
    arithmetic_ins = min(ins_plurality * (storm_param // mean_ins), (1 << 64) - 1)
    return max(0, harmonic_outs - arithmetic_ins)


@dataclass
class NonContextualMasses:
    compute_mass: int
    transient_mass: int


@dataclass
class BlockMassLimits:
    storage: int
    compute: int
    transient: int

    @staticmethod
    def with_shared_limit(limit: int) -> "BlockMassLimits":
        return BlockMassLimits(limit, limit, limit)

    def would_fit(self, totals: "NonContextualMasses", storage_total: int) -> bool:
        """True if per-dimension totals are within the per-dimension limits."""
        return (
            totals.compute_mass <= self.compute
            and totals.transient_mass <= self.transient
            and storage_total <= self.storage
        )


@dataclass
class BlockLaneLimits:
    """KIP-21 per-block lane limits (consensus/core/src/mass/mod.rs
    BlockLaneLimits, constants.rs:98-101): a block may occupy at most
    `lanes_per_block` distinct subnetwork lanes among its non-coinbase
    transactions, and the summed gas within any lane is capped at
    `gas_per_lane`."""

    lanes_per_block: int
    gas_per_lane: int


class MassCalculator:
    def __init__(
        self,
        mass_per_tx_byte: int = 1,
        mass_per_script_pub_key_byte: int = 10,
        storage_mass_parameter: int = STORAGE_MASS_PARAMETER,
        mass_per_sig_op: int = GRAMS_PER_SIGOP_COUNT_UNIT,
    ):
        self.mass_per_tx_byte = mass_per_tx_byte
        self.mass_per_script_pub_key_byte = mass_per_script_pub_key_byte
        self.storage_mass_parameter = storage_mass_parameter
        self.mass_per_sig_op = mass_per_sig_op

    @staticmethod
    def from_params(params) -> "MassCalculator":
        return MassCalculator(
            params.mass_per_tx_byte,
            params.mass_per_script_pub_key_byte,
            params.storage_mass_parameter,
            params.mass_per_sig_op,
        )

    def calc_non_contextual_masses(self, tx: Transaction) -> NonContextualMasses:
        if tx.is_coinbase():
            return NonContextualMasses(0, 0)
        size = transaction_estimated_serialized_size(tx)
        compute_for_size = size * self.mass_per_tx_byte
        spk_size = sum(2 + len(o.script_public_key.script) for o in tx.outputs)
        spk_mass = spk_size * self.mass_per_script_pub_key_byte
        if tx.version >= 1:
            script_mass = GRAMS_PER_COMPUTE_BUDGET_UNIT * sum(
                (i.compute_commit.compute_budget() or 0) for i in tx.inputs
            )
        else:
            script_mass = self.mass_per_sig_op * sum((i.compute_commit.sig_op_count() or 0) for i in tx.inputs)
        return NonContextualMasses(compute_for_size + spk_mass + script_mass, size * TRANSIENT_BYTE_TO_MASS_FACTOR)

    def calc_contextual_masses(self, tx: Transaction, utxo_entries) -> int | None:
        """Storage mass of a populated tx (None == incomputable/too high)."""
        return calc_storage_mass(
            tx.is_coinbase(),
            [_cell_of_entry(e) for e in utxo_entries],
            [_cell_of_output(o) for o in tx.outputs],
            self.storage_mass_parameter,
        )
