"""ConsensusManager: active-consensus ownership with staging swap.

Reference: components/consensusmanager/src/lib.rs — the manager owns the
current consensus instance, hands out sessions, and supports creating a
*staging* consensus that is populated off to the side (pruning-proof
import) and atomically swapped in on commit.  In this framework the
single-writer node lock plays the session role; the manager supplies the
factory/swap machinery plus listener callbacks so dependents (mining,
RPC, indexes) re-bind on swap.
"""

from __future__ import annotations


class StagingConsensus:
    """A consensus being populated for adoption (staging_consensus.rs)."""

    def __init__(self, manager: "ConsensusManager", consensus):
        self.manager = manager
        self.consensus = consensus
        self._done = False

    def commit(self) -> None:
        assert not self._done
        self._done = True
        self.manager._swap(self.consensus)

    def cancel(self) -> None:
        """Discard: close and delete the staging DB, if any."""
        self._done = True
        db = getattr(self.consensus.storage, "db", None)
        if db is not None:
            self.consensus.storage.db = None
            path = getattr(db, "path", None)
            try:
                db.close()
            except Exception:  # noqa: BLE001
                pass
            if path:
                import contextlib
                import os

                with contextlib.suppress(OSError):
                    os.remove(path)


class ConsensusManager:
    def __init__(self, consensus, factory=None):
        """`factory()` builds a fresh consensus for staging; defaults to a
        memory-backed instance with the active params."""
        self._consensus = consensus
        self._factory = factory
        self._listeners: list = []

    @property
    def consensus(self):
        return self._consensus

    def on_swap(self, fn) -> None:
        """Register fn(new_consensus), called after a staging commit."""
        self._listeners.append(fn)

    def new_staging(self) -> StagingConsensus:
        if self._factory is not None:
            fresh = self._factory()
        else:
            from kaspa_tpu.consensus.consensus import Consensus

            fresh = Consensus(self._consensus.params)
        return StagingConsensus(self, fresh)

    def _swap(self, new_consensus) -> None:
        self._consensus = new_consensus
        for fn in self._listeners:
            fn(new_consensus)
