"""In-memory consensus stores.

Mirrors the reference's store registry (consensus/src/model/stores/, 20
stores aggregated in ConsensusStorage, consensus/src/consensus/storage.rs)
with a pluggable in-memory backend.  The persistent (RocksDB-style C++ KV)
backend slots behind the same interfaces in a later milestone; the store
*interfaces* are the contract the pipeline codes against.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kaspa_tpu.consensus.model import Header, Transaction


@dataclass
class GhostdagData:
    """consensus/src/model/stores/ghostdag.rs GhostdagData."""

    blue_score: int
    blue_work: int
    selected_parent: bytes
    mergeset_blues: list[bytes]
    mergeset_reds: list[bytes]
    blues_anticone_sizes: dict[bytes, int]

    def mergeset_size(self) -> int:
        return len(self.mergeset_blues) + len(self.mergeset_reds)

    def unordered_mergeset(self):
        yield from self.mergeset_blues
        yield from self.mergeset_reds

    def unordered_mergeset_without_selected_parent(self):
        yield from self.mergeset_blues[1:]
        yield from self.mergeset_reds

    def ascending_mergeset_without_selected_parent(self, gd_store):
        """Mergeset (minus selected parent) ascending by (blue_work, hash)."""
        return sorted(
            self.unordered_mergeset_without_selected_parent(),
            key=lambda h: (gd_store.get(h).blue_work, h),
        )

    def consensus_ordered_mergeset(self, gd_store):
        return [self.selected_parent] + self.ascending_mergeset_without_selected_parent(gd_store)


class HeaderStore:
    def __init__(self):
        self._headers: dict[bytes, Header] = {}

    def insert(self, header: Header) -> None:
        self._headers[header.hash] = header

    def get(self, block: bytes) -> Header:
        return self._headers[block]

    def has(self, block: bytes) -> bool:
        return block in self._headers

    def get_bits(self, block: bytes) -> int:
        return self._headers[block].bits

    def get_timestamp(self, block: bytes) -> int:
        return self._headers[block].timestamp

    def get_blue_score(self, block: bytes) -> int:
        return self._headers[block].blue_score

    def get_daa_score(self, block: bytes) -> int:
        return self._headers[block].daa_score


class RelationsStore:
    """Parent/child relations (level 0; higher levels added with pruning proofs)."""

    def __init__(self):
        self._parents: dict[bytes, list[bytes]] = {}
        self._children: dict[bytes, list[bytes]] = {}

    def insert(self, block: bytes, parents: list[bytes]) -> None:
        self._parents[block] = list(parents)
        self._children.setdefault(block, [])
        for p in parents:
            self._children.setdefault(p, []).append(block)

    def get_parents(self, block: bytes) -> list[bytes]:
        return self._parents[block]

    def get_children(self, block: bytes) -> list[bytes]:
        return self._children.get(block, [])

    def has(self, block: bytes) -> bool:
        return block in self._parents


class GhostdagStore:
    def __init__(self):
        self._data: dict[bytes, GhostdagData] = {}

    def insert(self, block: bytes, data: GhostdagData) -> None:
        self._data[block] = data

    def get(self, block: bytes) -> GhostdagData:
        return self._data[block]

    def has(self, block: bytes) -> bool:
        return block in self._data

    def get_blue_work(self, block: bytes) -> int:
        return self._data[block].blue_work

    def get_blue_score(self, block: bytes) -> int:
        return self._data[block].blue_score

    def get_selected_parent(self, block: bytes) -> bytes:
        return self._data[block].selected_parent

    def get_blues_anticone_sizes(self, block: bytes) -> dict[bytes, int]:
        return self._data[block].blues_anticone_sizes


class StatusesStore:
    """Block statuses (consensus/src/model/stores/statuses.rs)."""

    STATUS_INVALID = "invalid"
    STATUS_UTXO_VALID = "utxo_valid"
    STATUS_UTXO_PENDING_VERIFICATION = "utxo_pending"
    STATUS_DISQUALIFIED = "disqualified"
    STATUS_HEADER_ONLY = "header_only"

    def __init__(self):
        self._status: dict[bytes, str] = {}

    def set(self, block: bytes, status: str) -> None:
        self._status[block] = status

    def get(self, block: bytes) -> str | None:
        return self._status.get(block)

    def is_valid(self, block: bytes) -> bool:
        return self._status.get(block) in (self.STATUS_UTXO_VALID, self.STATUS_UTXO_PENDING_VERIFICATION, self.STATUS_HEADER_ONLY)


class BlockTransactionsStore:
    def __init__(self):
        self._txs: dict[bytes, list[Transaction]] = {}

    def insert(self, block: bytes, txs: list[Transaction]) -> None:
        self._txs[block] = txs

    def get(self, block: bytes) -> list[Transaction]:
        return self._txs[block]

    def has(self, block: bytes) -> bool:
        return block in self._txs


@dataclass
class ConsensusStorage:
    """Aggregation of all stores (consensus/src/consensus/storage.rs:38-83)."""

    headers: HeaderStore = field(default_factory=HeaderStore)
    relations: RelationsStore = field(default_factory=RelationsStore)
    ghostdag: GhostdagStore = field(default_factory=GhostdagStore)
    statuses: StatusesStore = field(default_factory=StatusesStore)
    block_transactions: BlockTransactionsStore = field(default_factory=BlockTransactionsStore)
